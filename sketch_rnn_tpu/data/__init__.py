from sketch_rnn_tpu.data.strokes import (
    augment_strokes,
    calculate_normalizing_scale_factor,
    normalize_strokes,
    random_scale,
    strokes_to_lines,
    to_big_strokes,
    to_normal_strokes,
)
from sketch_rnn_tpu.data.loader import (
    DataLoader,
    load_dataset,
    make_synthetic_strokes,
)
from sketch_rnn_tpu.data.quickdraw import convert_ndjson, drawing_to_stroke3

__all__ = [
    "DataLoader",
    "convert_ndjson",
    "drawing_to_stroke3",
    "augment_strokes",
    "calculate_normalizing_scale_factor",
    "load_dataset",
    "make_synthetic_strokes",
    "normalize_strokes",
    "random_scale",
    "strokes_to_lines",
    "to_big_strokes",
    "to_normal_strokes",
]
