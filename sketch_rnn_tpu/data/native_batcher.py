"""ctypes binding for the native C++ batch assembler.

SURVEY.md §2 component 1 — native input-pipeline path. The shared object
is built on demand with g++ (the toolchain is part of the target
environment); any failure — no compiler, missing source, corrupt or
wrong-ABI artifact — silently falls back to the numpy path in
:mod:`sketch_rnn_tpu.data.loader`, so the framework stays
pure-Python-capable. Set ``SKETCH_RNN_TPU_NO_NATIVE=1`` to force the
fallback.

The ABI version is part of the shared-object FILENAME
(``batcher_v<N>.so``): a Python/C++ version skew can therefore never
dlopen a stale mapping — the old artifact is simply never referenced.
Builds write to a per-process temp name and ``os.replace`` into place, so
concurrent builders (multi-process launches, pytest-xdist) cannot corrupt
each other's output.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_ABI_VERSION = 4
_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native", "batcher.cc")
_SO = os.path.join(_HERE, "native", f"batcher_v{_ABI_VERSION}.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    tmp = f"{_SO}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("SKETCH_RNN_TPU_NO_NATIVE") == "1":
            return None
        try:
            needs_build = (not os.path.exists(_SO)
                           or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        except OSError:
            # source missing: use a prebuilt artifact as-is, else fall back
            needs_build = not os.path.exists(_SO)
            if needs_build:
                return None
        if needs_build and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
            if lib.batcher_abi_version() != _ABI_VERSION:
                return None  # foreign artifact under our versioned name
        except (OSError, AttributeError):
            return None
        lib.assemble_batch.restype = ctypes.c_int
        lib.assemble_batch.argtypes = [
            ctypes.POINTER(ctypes.c_float),   # seq_data
            ctypes.POINTER(ctypes.c_int32),   # seq_lens
            ctypes.c_int32,                   # n
            ctypes.c_int32,                   # max_len
            ctypes.POINTER(ctypes.c_float),   # out
        ]
        lib.assemble_batch_aug.restype = ctypes.c_int
        lib.assemble_batch_aug.argtypes = [
            ctypes.POINTER(ctypes.c_float),   # seq_data
            ctypes.POINTER(ctypes.c_int32),   # seq_lens
            ctypes.c_int32,                   # n
            ctypes.c_int32,                   # max_len
            ctypes.c_float,                   # scale_factor
            ctypes.c_float,                   # drop_prob
            ctypes.c_uint64,                  # seed
            ctypes.c_int32,                   # n_threads
            ctypes.POINTER(ctypes.c_float),   # out
            ctypes.POINTER(ctypes.c_int32),   # out_lens
        ]
        lib.assemble_batch_aug_i16.restype = ctypes.c_int
        lib.assemble_batch_aug_i16.argtypes = [
            ctypes.POINTER(ctypes.c_float),   # seq_data
            ctypes.POINTER(ctypes.c_int32),   # seq_lens
            ctypes.c_int32,                   # n
            ctypes.c_int32,                   # max_len
            ctypes.c_float,                   # scale_factor
            ctypes.c_float,                   # drop_prob
            ctypes.c_uint64,                  # seed
            ctypes.c_int32,                   # n_threads
            ctypes.c_float,                   # quant
            ctypes.POINTER(ctypes.c_int16),   # out (int16)
            ctypes.POINTER(ctypes.c_int32),   # out_lens
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _flatten(seqs: List[np.ndarray], max_len: int):
    n = len(seqs)
    lens = np.array([len(s) for s in seqs], dtype=np.int32)
    if (lens > max_len).any():
        return None
    flat = np.ascontiguousarray(
        np.concatenate([np.asarray(s, np.float32) for s in seqs], axis=0))
    return n, lens, flat


def assemble_batch(seqs: List[np.ndarray], max_len: int
                   ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Pad + stroke-5-convert a batch natively (no augmentation).

    ``seqs`` are float32 stroke-3 arrays. Returns ``(strokes, seq_len)``
    — ``strokes [n, max_len + 1, 5]`` with the start token at t=0 — or
    None when the native library is unavailable (caller falls back).
    Bit-exact equal to the numpy path (golden-tested).
    """
    lib = _load()
    if lib is None or not seqs:
        return None
    packed = _flatten(seqs, max_len)
    if packed is None:
        return None
    n, lens, flat = packed
    out = np.empty((n, max_len + 1, 5), dtype=np.float32)
    rc = lib.assemble_batch(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(n), ctypes.c_int32(max_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    if rc != 0:
        return None
    return out, lens


def pad_batch_numpy(seqs: List[np.ndarray], max_len: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """THE stroke-5 batch layout, pure numpy: ``strokes [B, max_len+1,
    5]`` with the start token at t=0, plus ``seq_len [B]``. Bit-exact
    to the native :func:`assemble_batch` (golden-tested) and the ONE
    shared implementation behind ``DataLoader._pad_batch``,
    :func:`stream_batches`' fallback and the serve endpoints'
    ``pad_prefixes`` — the serve-vs-offline bitwise-parity contract
    depends on these never drifting, so the layout lives once."""
    from sketch_rnn_tpu.data import strokes as S

    out = np.zeros((len(seqs), max_len + 1, 5), dtype=np.float32)
    lens = np.empty((len(seqs),), dtype=np.int32)
    for i, s in enumerate(seqs):
        s = np.asarray(s, np.float32)
        out[i, 1:, :] = S.to_big_strokes(s, max_len)
        out[i, 0, :] = [0, 0, 1, 0, 0]
        lens[i] = len(s)
    return out, lens


def stream_batches(seq_iter, batch_size: int, max_len: int,
                   drop_last: bool = False):
    """Assemble stroke-5 batches straight from a stroke-3 stream
    (ISSUE 15 streaming ingestion: ``data.quickdraw.stream_stroke3`` /
    ``stream_categories`` -> the serving fleet, no materialized corpus).

    ``seq_iter`` yields stroke-3 arrays OR ``(label, stroke3)`` pairs;
    sequences longer than ``max_len`` are dropped (the loader's
    ``_purify`` filter contract), counted in the ``records_skipped``
    telemetry counter when a core is enabled. Yields loader-layout
    dicts — ``strokes [B, max_len+1, 5]`` float32 with the start token
    at t=0, ``seq_len [B]``, ``labels [B]`` — assembled through the
    native C++ batcher when available and the bit-exact numpy fallback
    otherwise. The trailing partial batch is yielded at its true size
    unless ``drop_last``.
    """
    if batch_size < 1 or max_len < 1:
        raise ValueError(f"batch_size and max_len must be >= 1, got "
                         f"{batch_size}/{max_len}")

    def flush(buf_seqs, buf_labels):
        native = assemble_batch(buf_seqs, max_len)
        if native is None:
            strokes, lens = pad_batch_numpy(buf_seqs, max_len)
        else:
            strokes, lens = native
        return {"strokes": strokes, "seq_len": lens,
                "labels": np.asarray(buf_labels, np.int32)}

    from sketch_rnn_tpu.utils.telemetry import get_telemetry

    def skip_one():
        # ticked PER drop, not at generator exhaustion: a consumer
        # that takes only the first K batches (islice) must still see
        # its drops counted; zero-length records count too
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("records_skipped", 1.0, cat="data")

    buf_seqs: List[np.ndarray] = []
    buf_labels: List[int] = []
    for item in seq_iter:
        if isinstance(item, tuple):
            label, s3 = item
        else:
            label, s3 = 0, item
        s3 = np.asarray(s3, np.float32)
        if len(s3) > max_len or len(s3) == 0:
            skip_one()
            continue
        buf_seqs.append(s3)
        buf_labels.append(int(label))
        if len(buf_seqs) == batch_size:
            yield flush(buf_seqs, buf_labels)
            buf_seqs, buf_labels = [], []
    if buf_seqs and not drop_last:
        yield flush(buf_seqs, buf_labels)


def assemble_batch_aug(seqs: List[np.ndarray], max_len: int,
                       scale_factor: float, drop_prob: float, seed: int,
                       n_threads: int = 0
                       ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Augment + pad + stroke-5-convert a batch natively (train path).

    Applies per-sequence random scale jitter (``scale_factor``) and
    point-dropout (``drop_prob``) inside the C++ loop — the whole
    train-time batch assembly is one native call. Each sequence draws
    from an independent counter-based RNG stream keyed by ``(seed,
    index)``, so results are deterministic and independent of
    ``n_threads`` (0 = hardware concurrency). Distributionally
    equivalent to the numpy path (strokes.random_scale /
    augment_strokes), not bit-identical. Returns ``(strokes, seq_len)``
    with post-augmentation lengths, or None (caller falls back).
    """
    lib = _load()
    if lib is None or not seqs:
        return None
    packed = _flatten(seqs, max_len)
    if packed is None:
        return None
    n, lens, flat = packed
    out = np.empty((n, max_len + 1, 5), dtype=np.float32)
    out_lens = np.empty((n,), dtype=np.int32)
    rc = lib.assemble_batch_aug(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(n), ctypes.c_int32(max_len),
        ctypes.c_float(scale_factor), ctypes.c_float(drop_prob),
        ctypes.c_uint64(seed & (2 ** 64 - 1)), ctypes.c_int32(n_threads),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        return None
    return out, out_lens


def assemble_batch_aug_i16(seqs: List[np.ndarray], max_len: int,
                           scale_factor: float, drop_prob: float,
                           seed: int, quant: float, n_threads: int = 0
                           ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Augment + pad + QUANTIZE a batch natively to int16 data units.

    The int16 exact-transfer feed path (``hps.transfer_dtype="int16"``):
    offsets are multiplied back by ``quant`` (the corpus normalization
    scale) and rounded half-even — bit-identical to ``np.rint`` so the
    Python fallback matches — in the same native pass as augmentation
    and packing, so quantization adds no host-side Python work.
    ``scale_factor=0`` / ``drop_prob=0`` is the no-augmentation path.
    Returns ``(strokes int16 [n, max_len+1, 5], seq_len)`` or None.
    """
    lib = _load()
    if lib is None or not seqs or quant <= 0:
        return None
    packed = _flatten(seqs, max_len)
    if packed is None:
        return None
    n, lens, flat = packed
    out = np.empty((n, max_len + 1, 5), dtype=np.int16)
    out_lens = np.empty((n,), dtype=np.int32)
    rc = lib.assemble_batch_aug_i16(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(n), ctypes.c_int32(max_len),
        ctypes.c_float(scale_factor), ctypes.c_float(drop_prob),
        ctypes.c_uint64(seed & (2 ** 64 - 1)), ctypes.c_int32(n_threads),
        ctypes.c_float(quant),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        return None
    return out, out_lens
