"""QuickDraw raw ``.ndjson`` -> stroke-3 conversion (dataset creation).

The reference trains on per-category ``.npz`` files of stroke-3 int16
sequences; Google distributes QuickDraw as ``.ndjson`` (one JSON drawing
per line, each stroke ``[[x...], [y...]]`` in 0-255 canvas coordinates).
The canonical sketch-rnn dataset was produced from the raw drawings by
(1) Ramer-Douglas-Peucker simplification at epsilon=2.0 and (2) delta
encoding with pen-lift bits — this module reimplements that pipeline so
users can build training sets for categories (or custom collections)
that have no prebuilt ``.npz`` (SURVEY.md §2 component 1 tooling; the
"Simplified Drawing files" described by the public quickdraw dataset
docs already have step (1) applied — pass ``epsilon=0`` for those).

Everything is pure numpy; no network access is required or attempted
(pair with ``scripts/fetch_quickdraw.py`` for the prebuilt ``.npz``).
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional, Sequence

import numpy as np


def rdp(points: np.ndarray, epsilon: float) -> np.ndarray:
    """Ramer-Douglas-Peucker polyline simplification.

    ``points``: ``[N, 2]`` float array. Returns the simplified ``[M, 2]``
    subsequence (endpoints always kept). Iterative (explicit stack), so
    pathological polylines cannot hit Python's recursion limit.
    """
    pts = np.asarray(points, dtype=np.float64)
    n = len(pts)
    if n <= 2 or epsilon <= 0:
        return np.asarray(points)
    keep = np.zeros(n, dtype=bool)
    keep[0] = keep[-1] = True
    stack = [(0, n - 1)]
    while stack:
        lo, hi = stack.pop()
        if hi <= lo + 1:
            continue
        seg = pts[hi] - pts[lo]
        mid = pts[lo + 1:hi]
        rel = mid - pts[lo]
        seg_len = np.hypot(*seg)
        if seg_len == 0.0:
            # degenerate chord: fall back to distance from the point
            d = np.hypot(rel[:, 0], rel[:, 1])
        else:
            # perpendicular distance to the chord (2-D cross product;
            # np.cross on 2-D vectors is deprecated in numpy 2)
            d = np.abs(seg[0] * rel[:, 1] - seg[1] * rel[:, 0]) / seg_len
        i = int(np.argmax(d))
        if d[i] > epsilon:
            split = lo + 1 + i
            keep[split] = True
            stack.append((lo, split))
            stack.append((split, hi))
    return np.asarray(points)[keep]


def _align_to_box(strokes: List[np.ndarray], box: float = 255.0
                  ) -> List[np.ndarray]:
    """Translate the drawing to the origin and uniformly scale its larger
    dimension to ``box`` — the canonical QuickDraw normalization applied
    BEFORE RDP, which is what makes epsilon=2.0 resolution-independent
    (raw captures come in arbitrary device coordinates)."""
    allpts = np.concatenate(strokes, axis=0)
    lo = allpts.min(axis=0)
    span = float((allpts - lo).max())
    scale = box / span if span > 0 else 1.0
    return [(s - lo) * scale for s in strokes]


def drawing_to_stroke3(drawing: Sequence[Sequence[Sequence[float]]],
                       epsilon: float = 2.0,
                       max_points: Optional[int] = None,
                       quantize: bool = False) -> np.ndarray:
    """One ndjson ``drawing`` (list of ``[[xs], [ys]]`` strokes) ->
    stroke-3 ``[N, 3]`` float32 (dx, dy, pen_lift).

    Matches the canonical preprocessing: align the drawing to the origin
    and uniformly scale it into the 0-255 box, then per-stroke RDP at
    ``epsilon`` (2.0, resolution-independent thanks to the scaling; 0
    skips BOTH steps for pre-simplified files, which are already in the
    0-255 box), delta encoding from the first point, ``pen_lift=1`` on
    each stroke's last point. ``max_points`` truncates (the loader's
    ``max_seq_len`` filter would otherwise drop very long drawings
    entirely). ``quantize=True`` rounds the ABSOLUTE coordinates to
    integers before diffing, so deltas are exact integer differences
    (the canonical int16 layout) with no cumulative rounding drift —
    rounding per-point deltas instead would random-walk the
    reconstructed positions by several pixels over a long sketch.
    """
    raw_strokes: List[np.ndarray] = []
    for stroke in drawing:
        xy = np.stack([np.asarray(stroke[0], np.float64),
                       np.asarray(stroke[1], np.float64)], axis=1)
        if len(xy):
            raw_strokes.append(xy)
    if not raw_strokes:
        return np.zeros((0, 3), np.float32)
    if epsilon > 0:
        raw_strokes = _align_to_box(raw_strokes)
    pts: List[np.ndarray] = []
    pens: List[np.ndarray] = []
    for xy in raw_strokes:
        xy = rdp(xy, epsilon)
        pen = np.zeros(len(xy))
        pen[-1] = 1.0
        pts.append(xy)
        pens.append(pen)
    xy = np.concatenate(pts, axis=0)
    if quantize:
        xy = np.round(xy)
    pen = np.concatenate(pens, axis=0)
    deltas = np.diff(xy, axis=0, prepend=xy[:1])
    out = np.concatenate([deltas, pen[:, None]], axis=1).astype(np.float32)
    # the first row's delta is 0,0 by construction; the canonical data
    # starts at the first real movement, so drop a leading no-op point
    # unless it also lifts the pen
    if len(out) > 1 and out[0, 0] == 0 and out[0, 1] == 0 and out[0, 2] == 0:
        out = out[1:]
    if max_points is not None:
        out = out[:max_points]
        if len(out):
            out[-1, 2] = 1.0
    return out


def iter_ndjson(lines: Iterable[str],
                recognized_only: bool = True,
                source: str = "<ndjson>",
                skip_bad: bool = False):
    """Yield ``(word, stroke3-ready drawing)`` from ndjson lines.

    ``recognized_only`` keeps only drawings the QuickDraw classifier
    recognized (the canonical datasets do the same).

    Hardening (ISSUE 10 satellite): a corrupt line — torn JSON from a
    truncated download, or a record without a ``drawing`` — fails with
    ONE line naming ``source`` and the line number instead of a raw
    ``json.loads`` traceback; ``skip_bad`` skips such lines instead,
    counted in the ``records_skipped`` telemetry counter (cat ``data``).
    """
    from sketch_rnn_tpu.utils.telemetry import get_telemetry

    skipped = 0
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
            drawing = rec["drawing"]
        except (ValueError, KeyError, TypeError) as e:
            if not skip_bad:
                raise ValueError(
                    f"corrupt ndjson record: {source} line {lineno}: "
                    f"{type(e).__name__}: {e}") from None
            skipped += 1
            tel = get_telemetry()
            if tel.enabled:
                tel.counter("records_skipped", 1.0, cat="data")
            continue
        if recognized_only and not rec.get("recognized", True):
            continue
        yield rec.get("word", ""), drawing
    if skipped:
        import sys
        print(f"[data] WARNING: skipped {skipped} corrupt ndjson "
              f"line(s) in {source} (skip_bad)", file=sys.stderr,
              flush=True)


def stream_stroke3(path: str,
                   epsilon: float = 2.0,
                   max_points: Optional[int] = 250,
                   recognized_only: bool = True,
                   skip_bad: bool = False,
                   limit: Optional[int] = None,
                   min_points: int = 2):
    """Stream one category ``.ndjson`` file as stroke-3 arrays.

    The streaming half of :func:`convert_ndjson` (ISSUE 15): yields
    each drawing's canonical-preprocessed stroke-3 ``[N, 3]`` float32
    array (integer-valued deltas — the same ``quantize=True`` pipeline
    the ``.npz`` conversion writes) WITHOUT materializing the corpus,
    so the full 345-category QuickDraw set can feed a serving fleet's
    prefix corpus or the native batcher one drawing at a time.
    Drawings shorter than ``min_points`` after simplification are
    dropped, exactly like the converter.
    """
    count = 0
    with open(path) as f:
        for _, drawing in iter_ndjson(f, recognized_only=recognized_only,
                                      source=path, skip_bad=skip_bad):
            s3 = drawing_to_stroke3(drawing, epsilon=epsilon,
                                    max_points=max_points,
                                    quantize=True)
            if len(s3) < min_points:
                continue
            yield s3
            count += 1
            if limit is not None and count >= limit:
                return


def stream_categories(data_dir: str, categories: Sequence[str],
                      interleave: bool = True, **kw):
    """Stream ``(label, stroke3)`` pairs from per-category ``.ndjson``
    files under ``data_dir`` (ISSUE 15 streaming ingestion).

    ``categories`` name the files (``.ndjson`` appended when missing);
    the label is the category's index, matching ``load_dataset``'s
    file-order labeling. ``interleave=True`` (default) round-robins
    one drawing per category so a downstream batch window mixes
    classes the way a pooled corpus would; ``False`` streams each file
    to exhaustion in order. ``**kw`` passes through to
    :func:`stream_stroke3` (epsilon / max_points / limit / skip_bad).
    """
    import os

    paths = [os.path.join(
        data_dir, c if c.endswith(".ndjson") else c + ".ndjson")
        for c in categories]
    streams = [stream_stroke3(p, **kw) for p in paths]
    if not interleave:
        for label, stream in enumerate(streams):
            for s3 in stream:
                yield label, s3
        return
    live = list(range(len(streams)))
    while live:
        done = []
        for label in live:
            try:
                yield label, next(streams[label])
            except StopIteration:
                done.append(label)
        for label in done:
            live.remove(label)


def convert_ndjson(in_path: str, out_path: str,
                   epsilon: float = 2.0,
                   max_points: int = 250,
                   num_valid: int = 2500,
                   num_test: int = 2500,
                   limit: Optional[int] = None,
                   seed: int = 0,
                   skip_bad: bool = False) -> dict:
    """Convert one category ``.ndjson`` file to a sketch-rnn ``.npz``.

    Writes ``train``/``valid``/``test`` object arrays of int16 stroke-3
    sequences (the exact layout ``data.loader.load_dataset`` reads and
    the reference's prebuilt files use). Returns split sizes.
    ``skip_bad`` skips corrupt lines (counted) instead of failing on
    the first one — see :func:`iter_ndjson`.
    """
    # one pipeline: the converter is the streaming reader (ISSUE 15)
    # materialized — the two paths cannot drift
    seqs: List[np.ndarray] = [
        s3.astype(np.int16)
        for s3 in stream_stroke3(in_path, epsilon=epsilon,
                                 max_points=max_points,
                                 skip_bad=skip_bad, limit=limit)]
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(seqs))
    seqs = [seqs[i] for i in order]
    n_eval = num_valid + num_test
    if len(seqs) <= n_eval:
        raise ValueError(
            f"{in_path}: only {len(seqs)} usable drawings, need more than "
            f"num_valid+num_test={n_eval}")
    splits = {
        "valid": seqs[:num_valid],
        "test": seqs[num_valid:n_eval],
        "train": seqs[n_eval:],
    }
    def obj_array(v):
        # np.array(v, dtype=object) would build a 3-D object array when
        # every sequence happens to share a length (e.g. max_points
        # truncation) — the canonical layout is a 1-D object array of
        # int16 [N, 3] arrays
        out = np.empty(len(v), dtype=object)
        out[:] = v
        return out

    np.savez_compressed(
        out_path, **{k: obj_array(v) for k, v in splits.items()})
    return {k: len(v) for k, v in splits.items()}
