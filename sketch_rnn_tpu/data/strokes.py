"""Stroke-format utilities (host-side numpy).

TPU-native equivalent of the reference's stroke helpers (SURVEY.md §2
component 1: ``to_big_strokes``, ``to_normal_strokes``, ``augment_strokes``,
``calculate_normalizing_scale_factor``; reference unreadable — semantics per
the sketch-rnn paper, arXiv:1704.03477 §3.1).

Formats:

- **stroke-3**: ``[N, 3]`` rows of ``(dx, dy, pen_lifted)`` where
  ``pen_lifted`` is 1 on the last point of each pen-down stroke.
- **stroke-5**: ``[N, 5]`` rows of ``(dx, dy, p1, p2, p3)`` one-hot pen
  state: p1 = pen down, p2 = pen up (end of a stroke), p3 = end of sketch.

These run on the host as plain numpy: the data pipeline stays off the TPU;
only padded stroke-5 batches cross the host→device boundary (SURVEY §3.1
boundary notes).
"""

from __future__ import annotations

import numpy as np


def to_big_strokes(stroke3: np.ndarray, max_len: int) -> np.ndarray:
    """stroke-3 -> stroke-5, padded to ``max_len`` with end-of-sketch rows.

    The output does NOT include the initial zero row; callers prepend the
    start token ``(0, 0, 1, 0, 0)`` when building model inputs.
    """
    n = len(stroke3)
    if n > max_len:
        raise ValueError(f"sequence of length {n} exceeds max_len {max_len}")
    out = np.zeros((max_len, 5), dtype=np.float32)
    out[:n, 0:2] = stroke3[:, 0:2]
    out[:n, 3] = stroke3[:, 2]          # p2 = pen lifted
    out[:n, 2] = 1.0 - stroke3[:, 2]    # p1 = pen down
    out[n:, 4] = 1.0                    # p3 = end of sketch for the padding
    return out


def to_normal_strokes(big: np.ndarray) -> np.ndarray:
    """stroke-5 -> stroke-3, truncated at the first end-of-sketch row."""
    end = len(big)
    for i in range(len(big)):
        if big[i, 4] > 0.5:
            end = i
            break
    out = np.zeros((end, 3), dtype=np.float32)
    out[:, 0:2] = big[:end, 0:2]
    out[:, 2] = big[:end, 3]
    return out


def calculate_normalizing_scale_factor(stroke3_list) -> float:
    """Std of all (dx, dy) offsets pooled over the training split.

    The reference normalizes every split by the *train* split's offset std
    (SURVEY §3.5); this factor is part of the model contract and must be
    checkpointed (SURVEY §5 'Checkpoint / resume').
    """
    data = np.concatenate([s[:, 0:2].reshape(-1) for s in stroke3_list])
    return float(np.std(data))


def normalize_strokes(stroke3_list, scale_factor: float):
    """Divide offsets by ``scale_factor`` (in place on copies)."""
    out = []
    for s in stroke3_list:
        s = np.array(s, dtype=np.float32)
        s[:, 0:2] /= scale_factor
        out.append(s)
    return out


def random_scale(stroke3: np.ndarray, factor: float,
                 rng: np.random.Generator) -> np.ndarray:
    """Per-axis random scale jitter in [1-factor, 1+factor] (train-time)."""
    x = (rng.random() * 2.0 - 1.0) * factor + 1.0
    y = (rng.random() * 2.0 - 1.0) * factor + 1.0
    out = np.array(stroke3, dtype=np.float32)
    out[:, 0] *= x
    out[:, 1] *= y
    return out


def augment_strokes(stroke3: np.ndarray, prob: float,
                    rng: np.random.Generator) -> np.ndarray:
    """Random point-dropout augmentation.

    With probability ``prob`` a pen-down point is merged into its
    predecessor (offsets summed), thinning dense polylines without changing
    the drawing. Points adjacent to pen-lifts are never dropped.
    """
    if prob <= 0.0:
        return np.array(stroke3, dtype=np.float32)
    result = []
    prev = [0.0, 0.0, 0.0]
    count = 0
    for i in range(len(stroke3)):
        candidate = [float(stroke3[i][0]), float(stroke3[i][1]),
                     int(stroke3[i][2])]
        if candidate[2] == 1 or prev[2] == 1:
            count = 0
        else:
            count += 1
        check = candidate[2] == 0 and prev[2] == 0 and count > 2
        if check and rng.random() < prob and result:
            result[-1][0] += candidate[0]
            result[-1][1] += candidate[1]
        else:
            result.append(candidate)
            prev = candidate
    return np.array(result, dtype=np.float32)


def strokes_to_lines(stroke3: np.ndarray):
    """stroke-3 -> list of polylines [[(x, y), ...], ...] in absolute coords."""
    x, y = 0.0, 0.0
    lines = []
    line = []
    for i in range(len(stroke3)):
        x += float(stroke3[i, 0])
        y += float(stroke3[i, 1])
        line.append((x, y))
        if stroke3[i, 2] >= 1:
            lines.append(line)
            line = []
    if line:
        lines.append(line)
    return lines
