// Native batch assembler: the hot host loop of the input pipeline.
//
// TPU-native-framework equivalent of the reference's host-side batch
// assembly (SURVEY.md §2 component 1). The reference leans on external
// native libraries for its performance core; this framework's own native
// surface is this C++ batcher: stroke-3 -> padded stroke-5 conversion and
// batch packing run as one tight loop per batch instead of a Python loop
// of small numpy ops, keeping 8 chips fed at large global batch sizes.
//
// C ABI (used from Python via ctypes, see ../native_batcher.py):
//
//   assemble_batch(seq_data, seq_lens, n, max_len, out)
//
//   seq_data    flattened float32 stroke-3 rows (dx, dy, pen) of all n
//               sequences, concatenated in order
//   seq_lens    int32[n] row counts per sequence
//   n           batch size
//   max_len     padded sequence length (excluding the start token)
//   out         float32[n, max_len + 1, 5], written fully
//
// Output layout per sequence (matches strokes.to_big_strokes + the
// loader's start token exactly; golden-tested for equality in
// tests/test_native_batcher.py):
//   row 0:                  (0, 0, 1, 0, 0)   start token
//   rows 1..len:            (dx, dy, 1-p, p, 0)
//   rows len+1..max_len:    (0, 0, 0, 0, 1)   end-of-sketch padding
//
// Build: g++ -O3 -shared -fPIC (see ../native_batcher.py _ensure_built).

#include <cstdint>
#include <cstring>

extern "C" {

int assemble_batch(const float* seq_data,
                   const int32_t* seq_lens,
                   int32_t n,
                   int32_t max_len,
                   float* out) {
  const int32_t row = 5;
  const int64_t per_seq = static_cast<int64_t>(max_len + 1) * row;
  const float* src = seq_data;
  for (int32_t i = 0; i < n; ++i) {
    const int32_t len = seq_lens[i];
    if (len < 0 || len > max_len) return -1;  // caller filtered; guard anyway
    float* dst = out + i * per_seq;
    // start token
    dst[0] = 0.f; dst[1] = 0.f; dst[2] = 1.f; dst[3] = 0.f; dst[4] = 0.f;
    float* p = dst + row;
    for (int32_t t = 0; t < len; ++t, p += row, src += 3) {
      const float pen = src[2];
      p[0] = src[0];
      p[1] = src[1];
      p[2] = 1.f - pen;
      p[3] = pen;
      p[4] = 0.f;
    }
    for (int32_t t = len; t < max_len; ++t, p += row) {
      p[0] = 0.f; p[1] = 0.f; p[2] = 0.f; p[3] = 0.f; p[4] = 1.f;
    }
  }
  return 0;
}

// Version tag so the Python side can detect a stale shared object.
int batcher_abi_version() { return 2; }

}  // extern "C"
