// Native batch assembler: the hot host loop of the input pipeline.
//
// TPU-native-framework equivalent of the reference's host-side batch
// assembly (SURVEY.md §2 component 1). The reference leans on external
// native libraries for its performance core; this framework's own native
// surface is this C++ batcher: train-time augmentation (random per-axis
// scale jitter + point-dropout), stroke-3 -> padded stroke-5 conversion
// and batch packing run as one tight (optionally multi-threaded) loop
// per batch instead of a Python loop of small numpy ops, keeping 8 chips
// fed at large global batch sizes.
//
// C ABI (used from Python via ctypes, see ../native_batcher.py):
//
//   assemble_batch(seq_data, seq_lens, n, max_len, out)
//       the eval-path entry: no augmentation. Bit-exact equal to
//       strokes.to_big_strokes + the loader's start token (golden-tested
//       in tests/test_native_batcher.py).
//
//   assemble_batch_aug(seq_data, seq_lens, n, max_len, scale_factor,
//                      drop_prob, seed, n_threads, out, out_lens)
//       the train-path entry: per-sequence augmentation THEN packing.
//       - scale_factor > 0: each sequence's dx (dy) is multiplied by an
//         independent uniform draw from [1-f, 1+f] (strokes.random_scale
//         semantics).
//       - drop_prob > 0: pen-down points whose two predecessors are also
//         pen-down are merged into the previous point with probability
//         drop_prob (strokes.augment_strokes semantics — offsets summed,
//         so the drawing is unchanged; pen-lift structure preserved).
//       - seed: batch-level RNG seed. Each sequence uses an independent
//         splitmix64 stream seeded by (seed, index), so results are
//         deterministic in (seed, index) and INDEPENDENT of n_threads.
//         Distributionally equivalent to the numpy path, different bits.
//       - n_threads: sequences are chunked across std::threads (<=1 or
//         n small: serial). Output rows are disjoint per sequence.
//       - out_lens: int32[n], the post-augmentation lengths.
//
//   seq_data    flattened float32 stroke-3 rows (dx, dy, pen) of all n
//               sequences, concatenated in order
//   seq_lens    int32[n] row counts per sequence
//   n           batch size
//   max_len     padded sequence length (excluding the start token)
//   out         float32[n, max_len + 1, 5], written fully
//
// Output layout per sequence (start token at t=0):
//   row 0:                  (0, 0, 1, 0, 0)   start token
//   rows 1..len:            (dx, dy, 1-p, p, 0)
//   rows len+1..max_len:    (0, 0, 0, 0, 1)   end-of-sketch padding
//
// Build: g++ -O3 -shared -fPIC (see ../native_batcher.py _ensure_built).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// splitmix64: tiny, high-quality counter-based PRNG — each (seed, index)
// pair is an independent stream, which is what makes the augmentation
// deterministic under any thread count.
struct SplitMix64 {
  uint64_t state;
  explicit SplitMix64(uint64_t s) : state(s) {}
  uint64_t next() {
    uint64_t z = (state += 0x9e3779b97f4a7c15ull);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  }
  // uniform in [0, 1)
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
};

// Output writers: float passthrough, or int16 quantization back to data
// units (offset * quant, round-half-even like numpy rint so the Python
// fallback is bit-identical; pen/pad values are exact small integers).
template <typename OutT>
inline OutT quantize(float v, float quant);
template <>
inline float quantize<float>(float v, float) { return v; }
template <>
inline int16_t quantize<int16_t>(float v, float quant) {
  float r = nearbyintf(v * quant);
  if (r > 32767.f) r = 32767.f;
  if (r < -32767.f) r = -32767.f;
  return static_cast<int16_t>(r);
}

// Pen columns: float keeps the source arithmetic bit-identical to the
// numpy path (golden-tested); int16 writes exact 0/1.
template <typename OutT>
inline OutT pen_down(float pen);
template <>
inline float pen_down<float>(float pen) { return 1.f - pen; }
template <>
inline int16_t pen_down<int16_t>(float pen) { return pen >= 0.5f ? 0 : 1; }
template <typename OutT>
inline OutT pen_up(float pen);
template <>
inline float pen_up<float>(float pen) { return pen; }
template <>
inline int16_t pen_up<int16_t>(float pen) { return pen >= 0.5f ? 1 : 0; }

// One sequence: augment (optional) then pack into its output rows.
// Returns the post-augmentation length. ``quant`` is only read by the
// int16 instantiation (offsets leave as integer data units).
template <typename OutT>
int32_t process_one(const float* src, int32_t len, int32_t max_len,
                    float scale_factor, float drop_prob, uint64_t seed,
                    int64_t index, OutT* dst, float* scratch,
                    float quant) {
  const int32_t row = 5;
  SplitMix64 rng(seed * 0x2545f4914f6cdd1dull + 0x9e3779b97f4a7c15ull
                 + static_cast<uint64_t>(index));

  float sx = 1.f, sy = 1.f;
  if (scale_factor > 0.f) {
    sx = static_cast<float>(rng.uniform() * 2.0 - 1.0) * scale_factor + 1.f;
    sy = static_cast<float>(rng.uniform() * 2.0 - 1.0) * scale_factor + 1.f;
  }

  // point-dropout into scratch (stroke-3), merging dropped offsets into
  // the previous kept point; mirrors strokes.augment_strokes exactly
  // (candidates need >2 consecutive pen-down predecessors and a kept
  // previous point).
  const float* s3 = src;
  int32_t out_len = len;
  if (drop_prob > 0.f) {
    int32_t kept = 0;
    float prev_pen = 0.f;
    int32_t count = 0;
    bool have_prev = false;
    for (int32_t i = 0; i < len; ++i) {
      const float dx = src[3 * i], dy = src[3 * i + 1], pen = src[3 * i + 2];
      if (pen >= 0.5f || prev_pen >= 0.5f) {
        count = 0;
      } else {
        ++count;
      }
      const bool check = pen < 0.5f && prev_pen < 0.5f && count > 2;
      if (check && have_prev && rng.uniform() < drop_prob) {
        scratch[3 * (kept - 1)] += dx;
        scratch[3 * (kept - 1) + 1] += dy;
      } else {
        scratch[3 * kept] = dx;
        scratch[3 * kept + 1] = dy;
        scratch[3 * kept + 2] = pen;
        ++kept;
        prev_pen = pen;
        have_prev = true;
      }
    }
    s3 = scratch;
    out_len = kept;
  }

  // pack: start token, stroke-5 rows (with the scale jitter applied on
  // the fly), end-of-sketch padding
  dst[0] = OutT(0); dst[1] = OutT(0); dst[2] = OutT(1);
  dst[3] = OutT(0); dst[4] = OutT(0);
  OutT* p = dst + row;
  for (int32_t t = 0; t < out_len; ++t, p += row) {
    const float pen = s3[3 * t + 2];
    p[0] = quantize<OutT>(s3[3 * t] * sx, quant);
    p[1] = quantize<OutT>(s3[3 * t + 1] * sy, quant);
    p[2] = pen_down<OutT>(pen);
    p[3] = pen_up<OutT>(pen);
    p[4] = OutT(0);
  }
  for (int32_t t = out_len; t < max_len; ++t, p += row) {
    p[0] = OutT(0); p[1] = OutT(0); p[2] = OutT(0);
    p[3] = OutT(0); p[4] = OutT(1);
  }
  return out_len;
}

// Shared augment+pack driver (float and int16 instantiations).
template <typename OutT>
int assemble_aug_impl(const float* seq_data, const int32_t* seq_lens,
                      int32_t n, int32_t max_len, float scale_factor,
                      float drop_prob, uint64_t seed, int32_t n_threads,
                      OutT* out, int32_t* out_lens, float quant) {
  const int32_t row = 5;
  const int64_t per_seq = static_cast<int64_t>(max_len + 1) * row;

  // per-sequence source offsets (prefix sum; sequences vary in length)
  std::vector<int64_t> offsets(n + 1, 0);
  for (int32_t i = 0; i < n; ++i) {
    const int32_t len = seq_lens[i];
    if (len < 0 || len > max_len) return -1;
    offsets[i + 1] = offsets[i] + 3 * static_cast<int64_t>(len);
  }

  auto work = [&](int32_t lo, int32_t hi) {
    std::vector<float> scratch(3 * static_cast<size_t>(max_len));
    for (int32_t i = lo; i < hi; ++i) {
      out_lens[i] = process_one<OutT>(
          seq_data + offsets[i], seq_lens[i], max_len, scale_factor,
          drop_prob, seed, i, out + i * per_seq, scratch.data(), quant);
    }
  };

  int32_t threads = n_threads;
  const int32_t hw = static_cast<int32_t>(std::thread::hardware_concurrency());
  if (threads <= 0) threads = hw > 0 ? hw : 1;
  if (threads > n) threads = n;
  // cap by total work so thread create/join (~tens of us each) never
  // rivals the packing itself on many-core hosts: one thread per ~64k
  // source points (~a millisecond of work each)
  const int64_t total_points = offsets[n] / 3;
  const int32_t by_work = static_cast<int32_t>(total_points / 65536) + 1;
  if (threads > by_work) threads = by_work;
  if (threads <= 1 || n < 64) {
    work(0, n);
    return 0;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads);
  const int32_t chunk = (n + threads - 1) / threads;
  for (int32_t t = 0; t < threads; ++t) {
    const int32_t lo = t * chunk;
    const int32_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back(work, lo, hi);
  }
  for (auto& th : pool) th.join();
  return 0;
}

}  // namespace

extern "C" {

int assemble_batch(const float* seq_data,
                   const int32_t* seq_lens,
                   int32_t n,
                   int32_t max_len,
                   float* out) {
  const int32_t row = 5;
  const int64_t per_seq = static_cast<int64_t>(max_len + 1) * row;
  const float* src = seq_data;
  for (int32_t i = 0; i < n; ++i) {
    const int32_t len = seq_lens[i];
    if (len < 0 || len > max_len) return -1;  // caller filtered; guard anyway
    float* dst = out + i * per_seq;
    // start token
    dst[0] = 0.f; dst[1] = 0.f; dst[2] = 1.f; dst[3] = 0.f; dst[4] = 0.f;
    float* p = dst + row;
    for (int32_t t = 0; t < len; ++t, p += row, src += 3) {
      const float pen = src[2];
      p[0] = src[0];
      p[1] = src[1];
      p[2] = 1.f - pen;
      p[3] = pen;
      p[4] = 0.f;
    }
    for (int32_t t = len; t < max_len; ++t, p += row) {
      p[0] = 0.f; p[1] = 0.f; p[2] = 0.f; p[3] = 0.f; p[4] = 1.f;
    }
  }
  return 0;
}

int assemble_batch_aug(const float* seq_data,
                       const int32_t* seq_lens,
                       int32_t n,
                       int32_t max_len,
                       float scale_factor,
                       float drop_prob,
                       uint64_t seed,
                       int32_t n_threads,
                       float* out,
                       int32_t* out_lens) {
  return assemble_aug_impl<float>(seq_data, seq_lens, n, max_len,
                                  scale_factor, drop_prob, seed, n_threads,
                                  out, out_lens, 0.f);
}

// int16 variant (the exact-transfer feed path): same augmentation and
// packing, offsets quantized back to integer data units by ``quant``
// (the corpus normalization scale) in the same native pass — the host
// never touches the batch again, so int16 transfer adds no Python-side
// work. scale_factor=0 / drop_prob=0 gives the no-augmentation path.
int assemble_batch_aug_i16(const float* seq_data,
                           const int32_t* seq_lens,
                           int32_t n,
                           int32_t max_len,
                           float scale_factor,
                           float drop_prob,
                           uint64_t seed,
                           int32_t n_threads,
                           float quant,
                           int16_t* out,
                           int32_t* out_lens) {
  if (!(quant > 0.f)) return -1;
  return assemble_aug_impl<int16_t>(seq_data, seq_lens, n, max_len,
                                    scale_factor, drop_prob, seed,
                                    n_threads, out, out_lens, quant);
}

// Version tag so the Python side can detect a stale shared object.
int batcher_abi_version() { return 4; }

}  // extern "C"
