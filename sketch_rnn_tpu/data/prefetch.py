"""Overlapped input pipeline: background-thread batch prefetch.

The reference feeds its training loop synchronously from host numpy
(SURVEY.md §3.1: ``loader.random_batch()`` then ``sess.run`` each step).
On TPU that serializes host batch assembly + host->device transfer with
device compute; at flagship scale (global batch 2048 x 250 steps) the
host feed would starve the chips (SURVEY §7 "input pipeline that doesn't
starve 8 chips").

``Prefetcher`` runs a single producer thread that assembles the next
``depth`` batches — including the sharded device transfer, so the DMA
overlaps the current step's compute — ahead of the consumer. One producer
thread keeps the loader's RNG sequence identical to a synchronous feed
(tested in tests/test_prefetch.py), so turning prefetch on/off cannot
change training results, only throughput.

JAX note: ``jax.device_put`` / sharded transfers are thread-safe and
asynchronous; dispatching them from the producer thread simply enqueues
the copies earlier. The consumer receives committed device arrays.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional

from sketch_rnn_tpu.utils.telemetry import get_telemetry


class Prefetcher:
    """Bounded look-ahead around a ``producer() -> batch`` callable.

    - ``get()`` returns batches in exactly the order the producer yields
      them (single producer thread).
    - A producer exception is re-raised by the next ``get()`` call.
    - ``close()`` (or exiting the context manager) stops the thread; it is
      idempotent and never blocks on a full queue.
    """

    _SENTINEL = object()

    def __init__(self, producer: Callable[[], Any], depth: int = 2):
        if depth <= 0:
            raise ValueError(f"prefetch depth must be positive, got {depth}")
        self._producer = producer
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="batch-prefetch", daemon=True)
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                self._put(self._producer())
        except BaseException as e:  # noqa: BLE001 — must cross the thread
            self._exc = e
            self._put(self._SENTINEL)

    def _put(self, item: Any) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return
            except queue.Full:
                continue

    # -- consumer side -----------------------------------------------------

    def get(self) -> Any:
        """Next batch; re-raises a producer failure; blocks while healthy."""
        if self._stop.is_set():
            raise RuntimeError("Prefetcher is closed")
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._exc is not None and self._q.empty():
                    raise self._exc
                if not self._thread.is_alive() and self._q.empty():
                    if self._exc is not None:
                        raise self._exc
                    raise RuntimeError("prefetch thread died unexpectedly")
                continue
            if item is self._SENTINEL:
                raise self._exc  # type: ignore[misc]
            tel = get_telemetry()
            if tel.enabled:
                # look-ahead health (ISSUE 8): batches still queued at
                # the moment the consumer takes one — a timeline
                # hugging 0 means the producer can't keep pace (the
                # feeder_wait stalls' cause, visible from /metrics)
                tel.gauge("prefetch_queue_depth", self._q.qsize(),
                          cat="data")
            return item

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


class SyncFeeder:
    """Synchronous drop-in for :class:`Prefetcher` (depth 0): assembles
    and transfers each batch on the calling thread. The strawman the
    overlapped pipeline is benchmarked against, and the fallback when
    prefetching is disabled."""

    def __init__(self, producer: Callable[[], Any]):
        self._producer = producer

    def get(self) -> Any:
        return self._producer()

    def close(self) -> None:
        pass

    def __enter__(self) -> "SyncFeeder":
        return self

    def __exit__(self, *exc_info) -> None:
        pass


def prefetch_batches(loader, mesh=None, depth: int = 2, stack: int = 1,
                     transfer_dtype: Optional[str] = None):
    """Feeder over ``loader.next_batch()`` (``random_batch`` when the
    loader has no bucketed plan / no such method) with the device transfer
    (sharded onto ``mesh`` when given) done on the producer thread;
    ``depth <= 0`` returns a synchronous feeder with the same interface.

    ``stack=K`` (for ``steps_per_call=K`` multi-step training) assembles
    K consecutive batches per ``get()`` and stacks them on a new leading
    axis — one transfer and one dispatch feed K micro-steps. The loader's
    RNG sequence is identical to K single gets, so K-step training sees
    exactly the batches K single steps would have.

    Bucketed loaders (``loader.bucket_edges`` set) compose with
    ``stack=K`` through the bucket-run scheduler (ISSUE 5): each
    ``get()`` returns ``loader.next_stack(K)`` — up to K consecutive
    batches of ONE ``(B, Tb)`` geometry run stacked ``[k, B, Tb+1, 5]``
    with ``k <= K`` (run remainders come back short; the training loop
    replays them as single micro-steps). The micro-batch stream is
    exactly the ``next_batch`` stream, so stacking never changes what
    is trained on.

    ``transfer_dtype="bfloat16"`` casts the strokes array host-side so
    the transfer moves half the bytes (``hps.transfer_dtype``; the model
    upcasts on entry — see config.py for the rounding trade).
    ``transfer_dtype="int16"`` quantizes the offset columns back to
    integer data units (``round(x * scale_factor)``) and ships pen bits
    as int16 0/1: the same 2 bytes/element as bfloat16, but for
    integer-origin corpora (QuickDraw deltas) EXACT — the on-device
    dequant ``int / scale`` reproduces the host normalization
    bit-for-bit, so unlike bfloat16 there is no rounding trade (the
    recommended mode for real data; measured throughput parity with
    bfloat16). Exactness caveat (ADVICE r4): bit-for-bit holds for
    UNAUGMENTED feeds (eval loaders; train with augment off). Train
    loaders default to random-scale jitter, which makes offsets
    non-integer before quantization — the int16 train feed then
    differs from an f32 feed by at most 0.5 raw data units per offset
    (the same magnitude as the corpus's own integer quantization), a
    rounding of the AUGMENTATION noise, not of the data. The
    per-example scale rides as a ``"transfer_scale"`` [B] batch
    leaf. Because the quantization step is ONE raw data
    unit, the mode refuses corpora whose normalization scale would
    make that coarse relative to the (unit-variance) normalized data —
    silently training on rounded-to-nothing strokes is the failure
    this guard exists to prevent.
    """
    if stack < 1:
        raise ValueError(f"stack must be >= 1, got {stack}")
    if transfer_dtype not in (None, "float32", "bfloat16", "int16"):
        # mirror HParams' validation for direct callers: an arbitrary
        # dtype (e.g. int8) would silently truncate the stroke deltas
        raise ValueError(f"transfer_dtype must be 'float32', 'bfloat16' "
                         f"or 'int16', got {transfer_dtype!r}")
    cast = None
    if transfer_dtype == "bfloat16":
        import jax.numpy as jnp

        cast = jnp.dtype(transfer_dtype)
    quant_scale = None
    if transfer_dtype == "int16":
        # quantization happens INSIDE the loader's native batch assembly
        # (data/native/batcher.cc) — zero extra host-side Python work; a
        # numpy fallback lives in DataLoader._assemble
        quant_scale = getattr(loader, "scale_factor", None)
        # max quantization error is 0.5/scale in normalized (unit-
        # variance) units; refuse when that exceeds 10% of the data std
        # — int16 is for integer-origin corpora (QuickDraw scale ~30-60),
        # not float-natured ones, where it silently destroys the strokes
        if quant_scale is None or quant_scale < 5.0:
            raise ValueError(
                f"transfer_dtype='int16' needs an integer-origin corpus: "
                f"loader scale_factor is {quant_scale!r}, so quantizing "
                f"to integer data units would round away the strokes "
                f"(max error 0.5/scale normalized units). Use 'bfloat16' "
                f"or 'float32' for float-natured corpora.")
        quant_scale = float(quant_scale)

    # bucketed loaders feed from their epoch plan via next_batch; with
    # bucket_edges unset next_batch IS random_batch (bit-for-bit the same
    # feed), and plain producers without the method keep working
    next_fn = getattr(loader, "next_batch", None) or loader.random_batch
    bucketed_stack = stack > 1 and bool(getattr(loader, "bucket_edges", ()))

    # telemetry (ISSUE 6): the producer's two phases — host batch
    # assembly (next_batch / next_stack) and the sharded device
    # transfer — are spanned under cat "data", so an exported trace
    # shows feeder work on its own thread track against the loop's
    # feeder_wait stalls. Resolved per call: a late configure() (cli
    # --trace_dir) still catches a feeder built earlier; disabled
    # cost is one attribute check per batch.
    assemble = "next_stack" if bucketed_stack else "assemble"

    def host_batch():
        import numpy as np

        with get_telemetry().span(assemble, cat="data"):
            if bucketed_stack:
                # bucket-run scheduler: one geometry run's prefix,
                # already stacked [k, B, Tb+1, 5] with k <= stack (run
                # remainders are short — the consumer replays those
                # per micro-step)
                out = loader.next_stack(stack, int16_scale=quant_scale)
            elif stack == 1:
                out = next_fn(int16_scale=quant_scale)
                if cast is not None:
                    out = dict(out)  # don't mutate the loader's dict
            else:
                parts = [next_fn(int16_scale=quant_scale)
                         for _ in range(stack)]
                out = {k: np.stack([p[k] for p in parts])
                       for k in parts[0]}
            if cast is not None:
                out["strokes"] = out["strokes"].astype(cast)
            return out

    if mesh is not None:
        from sketch_rnn_tpu.parallel.mesh import shard_batch

        def producer():
            batch = host_batch()
            with get_telemetry().span("transfer", cat="data"):
                return shard_batch(batch, mesh, stacked=stack > 1)
    else:
        producer = host_batch
    if depth <= 0:
        return SyncFeeder(producer)
    return Prefetcher(producer, depth=depth)
