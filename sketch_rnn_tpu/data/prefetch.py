"""Overlapped input pipeline: background-thread batch prefetch.

The reference feeds its training loop synchronously from host numpy
(SURVEY.md §3.1: ``loader.random_batch()`` then ``sess.run`` each step).
On TPU that serializes host batch assembly + host->device transfer with
device compute; at flagship scale (global batch 2048 x 250 steps) the
host feed would starve the chips (SURVEY §7 "input pipeline that doesn't
starve 8 chips").

``Prefetcher`` runs a single producer thread that assembles the next
``depth`` batches — including the sharded device transfer, so the DMA
overlaps the current step's compute — ahead of the consumer. One producer
thread keeps the loader's RNG sequence identical to a synchronous feed
(tested in tests/test_prefetch.py), so turning prefetch on/off cannot
change training results, only throughput.

JAX note: ``jax.device_put`` / sharded transfers are thread-safe and
asynchronous; dispatching them from the producer thread simply enqueues
the copies earlier. The consumer receives committed device arrays.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional


class Prefetcher:
    """Bounded look-ahead around a ``producer() -> batch`` callable.

    - ``get()`` returns batches in exactly the order the producer yields
      them (single producer thread).
    - A producer exception is re-raised by the next ``get()`` call.
    - ``close()`` (or exiting the context manager) stops the thread; it is
      idempotent and never blocks on a full queue.
    """

    _SENTINEL = object()

    def __init__(self, producer: Callable[[], Any], depth: int = 2):
        if depth <= 0:
            raise ValueError(f"prefetch depth must be positive, got {depth}")
        self._producer = producer
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="batch-prefetch", daemon=True)
        self._thread.start()

    # -- producer side -----------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                self._put(self._producer())
        except BaseException as e:  # noqa: BLE001 — must cross the thread
            self._exc = e
            self._put(self._SENTINEL)

    def _put(self, item: Any) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return
            except queue.Full:
                continue

    # -- consumer side -----------------------------------------------------

    def get(self) -> Any:
        """Next batch; re-raises a producer failure; blocks while healthy."""
        if self._stop.is_set():
            raise RuntimeError("Prefetcher is closed")
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._exc is not None and self._q.empty():
                    raise self._exc
                if not self._thread.is_alive() and self._q.empty():
                    if self._exc is not None:
                        raise self._exc
                    raise RuntimeError("prefetch thread died unexpectedly")
                continue
            if item is self._SENTINEL:
                raise self._exc  # type: ignore[misc]
            return item

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


class SyncFeeder:
    """Synchronous drop-in for :class:`Prefetcher` (depth 0): assembles
    and transfers each batch on the calling thread. The strawman the
    overlapped pipeline is benchmarked against, and the fallback when
    prefetching is disabled."""

    def __init__(self, producer: Callable[[], Any]):
        self._producer = producer

    def get(self) -> Any:
        return self._producer()

    def close(self) -> None:
        pass

    def __enter__(self) -> "SyncFeeder":
        return self

    def __exit__(self, *exc_info) -> None:
        pass


def prefetch_batches(loader, mesh=None, depth: int = 2, stack: int = 1,
                     transfer_dtype: Optional[str] = None):
    """Feeder over ``loader.random_batch()`` with the device transfer
    (sharded onto ``mesh`` when given) done on the producer thread;
    ``depth <= 0`` returns a synchronous feeder with the same interface.

    ``stack=K`` (for ``steps_per_call=K`` multi-step training) assembles
    K consecutive batches per ``get()`` and stacks them on a new leading
    axis — one transfer and one dispatch feed K micro-steps. The loader's
    RNG sequence is identical to K single gets, so K-step training sees
    exactly the batches K single steps would have.

    ``transfer_dtype="bfloat16"`` casts the strokes array host-side so
    the transfer moves half the bytes (``hps.transfer_dtype``; the model
    upcasts on entry — see config.py for the rounding trade).
    """
    if stack < 1:
        raise ValueError(f"stack must be >= 1, got {stack}")
    if transfer_dtype not in (None, "float32", "bfloat16"):
        # mirror HParams' validation for direct callers: an arbitrary
        # dtype (e.g. int8) would silently truncate the stroke deltas
        raise ValueError(f"transfer_dtype must be 'float32' or "
                         f"'bfloat16', got {transfer_dtype!r}")
    cast = None
    if transfer_dtype == "bfloat16":
        import jax.numpy as jnp

        cast = jnp.dtype(transfer_dtype)

    def host_batch():
        import numpy as np

        if stack == 1:
            out = loader.random_batch()
            if cast is not None:
                out = dict(out)  # don't mutate the loader's dict
        else:
            parts = [loader.random_batch() for _ in range(stack)]
            out = {k: np.stack([p[k] for p in parts]) for k in parts[0]}
        if cast is not None:
            out["strokes"] = out["strokes"].astype(cast)
        return out

    if mesh is not None:
        from sketch_rnn_tpu.parallel.mesh import shard_batch

        def producer():
            return shard_batch(host_batch(), mesh, stacked=stack > 1)
    else:
        producer = host_batch
    if depth <= 0:
        return SyncFeeder(producer)
    return Prefetcher(producer, depth=depth)
