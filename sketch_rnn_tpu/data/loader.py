"""QuickDraw dataset loading and batching (host-side numpy).

TPU-native equivalent of the reference's ``DataLoader`` / ``load_dataset``
(SURVEY.md §2 component 1, §3.5; reference unreadable — semantics per the
canonical pipeline described there):

- read per-category ``.npz`` files with ``train``/``valid``/``test`` arrays
  of stroke-3 int16 sequences,
- drop sequences longer than ``max_seq_len``, clamp extreme offsets,
- normalize offsets by the *train* split's std (the scale factor is part of
  the model contract and is checkpointed),
- pad to ``max_seq_len`` in stroke-5 with a prepended start token,
- random-scale + point-dropout augmentation at train time.

QuickDraw data is not present in this environment (SURVEY §7 'Data
availability'), so ``make_synthetic_strokes`` provides a deterministic
synthetic sketch distribution behind the same interface; the real-data path
is exercised by tests that write tiny ``.npz`` files.

Batches stay host-side numpy; the trainer moves them onto the device mesh
with a single sharded transfer per step (SURVEY §3.1 boundary notes).
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data import native_batcher as NB
from sketch_rnn_tpu.data import strokes as S
from sketch_rnn_tpu.utils.faults import fault_point
from sketch_rnn_tpu.utils.profiling import PaddingLedger
from sketch_rnn_tpu.utils.telemetry import get_telemetry


def _purify(stroke3_list, max_seq_len: int, limit: float = 1000.0,
            source: Optional[str] = None, skip_bad: bool = False):
    """Drop too-long sequences; clamp absurd offsets to ±limit.

    Hardening (ISSUE 10 satellite): a corrupt record — wrong rank,
    wrong column count, non-numeric — used to surface as a raw numpy
    traceback from deep inside batching. Now it fails with ONE line
    naming ``source`` and the record index; with ``skip_bad`` it is
    skipped instead, counted in the ``records_skipped`` telemetry
    counter (cat ``data``) and summarized in a single warning.
    """
    out = []
    skipped = 0
    for i, s in enumerate(stroke3_list):
        try:
            # empty records are DROPPED, not corrupt — the pre-existing
            # filter contract (np.array([]) is 1-D, so the shape check
            # below must not see them)
            if len(s) == 0:
                continue
            s = np.array(s, dtype=np.float32)
            if s.ndim != 2 or s.shape[1] != 3:
                raise ValueError(f"expected an [N, 3] stroke-3 array, "
                                 f"got shape {s.shape}")
        except (ValueError, TypeError) as e:
            where = f"{source or '<in-memory corpus>'} record {i}"
            if not skip_bad:
                raise ValueError(
                    f"corrupt stroke record: {where}: {e}") from None
            skipped += 1
            continue
        if len(s) > max_seq_len:
            continue
        s[:, 0:2] = np.clip(s[:, 0:2], -limit, limit)
        out.append(s)
    if skipped:
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("records_skipped", float(skipped), cat="data")
        print(f"[data] WARNING: skipped {skipped} corrupt record(s) in "
              f"{source or '<in-memory corpus>'} (--skip_bad_records)",
              file=sys.stderr, flush=True)
    return out


class DataLoader:
    """Pads, normalizes, augments and batches stroke-3 sequences.

    The loader takes ownership of float32 input arrays (they are not
    copied, and ``normalize`` scales them in place); pass copies if the
    caller needs the originals intact.

    ``random_batch``/``get_batch`` return a dict:

    - ``"strokes"``: ``[B, max_seq_len + 1, 5]`` float32 stroke-5 with the
      start token ``(0, 0, 1, 0, 0)`` at t=0,
    - ``"seq_len"``: ``[B]`` int32 true lengths (excluding start token),
    - ``"labels"``: ``[B]`` int32 class ids (zeros when unlabeled).

    Length-bucketed execution (ISSUE 4, ``hps.bucket_edges``):
    :meth:`next_batch` feeds training from a seeded per-epoch bucket
    plan — batches padded only to their bucket edge ``Tb`` (strokes
    ``[B, Tb + 1, 5]``), every example covered exactly once per epoch —
    and :meth:`get_batch` pads eval batches to :meth:`eval_pad_len`.
    With ``bucket_edges`` empty (default) ``next_batch`` is exactly
    ``random_batch``. Every assembled batch is accounted in
    ``padding_ledger`` (padded-timestep fraction + per-bucket counts).

    Bucket-run scheduling (ISSUE 5, ``hps.bucket_run_len`` /
    ``steps_per_call > 1``): the plan orders batches into *geometry
    runs* — maximal consecutive sequences sharing one ``(B, Tb)`` —
    and :meth:`next_stack` pops up to K same-geometry batches at once,
    stacked on a new leading axis, so one transfer + one compiled
    K-step scan can consume them. The stacked stream is micro-batch-
    for-micro-batch identical to the :meth:`next_batch` stream (same
    plan, same assembly order, same RNG draws), so stacking can never
    change WHAT is trained on, only how it is dispatched.

    Coordinated multi-host mode (ISSUE 14, ``coordinated=True``): the
    loader holds the GLOBAL corpus (every host passes the identical
    list in the identical order, with the shared seed) and derives the
    identical *global* schedule — the random feed draws GLOBAL batches
    of ``hps.batch_size * num_hosts`` rows, the bucketed epoch plan
    bins and shuffles GLOBAL indices — then stripes each batch's row
    dimension: host ``h`` emits rows ``[h*B_local, (h+1)*B_local)``.
    Per-host geometry is therefore ``(B_local, Tb)`` with the SAME
    ``Tb`` sequence on every host (the SPMD collectives can never see
    mismatched programs — the guard this mode lifts), the
    concatenation of the per-host slices is bitwise the single-host
    global stream (each host assembles the full global batch, one
    shared augmentation draw per batch, and slices; assembly cost is
    ~69x cheaper than the step, so the H-fold host redundancy buys
    exact topology invariance), and because the whole schedule is a
    pure function of ``(seed, epoch, global corpus, B_global)`` —
    never of ``num_hosts`` — a resume at a DIFFERENT host count
    replays the same global example stream under the new striping
    (topology-change-equivalent resume; ``fast_forward`` needs no
    changes). ``emit_global=True`` returns the un-sliced global batch
    — the light-mode elastic runtime's replicated-program feed
    (train/elastic.py); the sliced mode is the real-mesh transfer
    contract (``parallel.mesh.shard_batch``).
    """

    def __init__(self,
                 stroke3_list: Sequence[np.ndarray],
                 hps: HParams,
                 labels: Optional[np.ndarray] = None,
                 augment: bool = False,
                 seed: int = 0,
                 global_size: Optional[int] = None,
                 num_hosts: int = 1,
                 host_id: int = 0,
                 coordinated: bool = False,
                 emit_global: bool = False):
        self.hps = hps
        self.scale_factor = 1.0  # set by normalize(); int16 transfer reads it
        self.strokes: List[np.ndarray] = [np.asarray(s, np.float32)
                                          for s in stroke3_list]
        if labels is None:
            labels = np.zeros((len(self.strokes),), dtype=np.int32)
        self.labels = np.asarray(labels, dtype=np.int32)
        assert len(self.labels) == len(self.strokes)
        self.augment = augment
        self.rng = np.random.default_rng(seed)
        # Multi-host SPMD safety: every host must run the SAME number of
        # jitted eval programs (each contains cross-host all-reduces, so a
        # host running one extra batch deadlocks the cluster). Host-striped
        # corpora differ in size by at most 1; both batch counts derive
        # from the GLOBAL size so they are identical on every host:
        # - num_batches (training-era full batches) from the guaranteed-
        #   common floor global//num_hosts,
        # - num_eval_batches from the ceil, so the sweep covers every
        #   host's full local corpus (hosts holding a striping remainder
        #   would otherwise never evaluate it when the common length is an
        #   exact batch multiple).
        self.num_hosts = num_hosts
        self.host_id = host_id
        self.coordinated = coordinated
        self.emit_global = emit_global
        if coordinated:
            if not 0 <= host_id < num_hosts:
                raise ValueError(f"host_id {host_id} out of range for "
                                 f"num_hosts={num_hosts}")
            # the corpus IS the global corpus; the schedule is planned
            # over GLOBAL batches of B_local * num_hosts rows, so batch
            # counts are trivially identical on every host (and on every
            # TOPOLOGY with the same global batch — the resume contract)
            self._gbatch = hps.batch_size * num_hosts
            self._common_len = self._max_local_len = len(self.strokes)
        else:
            if host_id or emit_global:
                raise ValueError("host_id / emit_global need "
                                 "coordinated=True (the legacy striped "
                                 "loader holds only its own stripe)")
            self._gbatch = hps.batch_size
            if global_size is not None and num_hosts > 1:
                self._common_len = global_size // num_hosts
                self._max_local_len = -(-global_size // num_hosts)
            else:
                self._common_len = self._max_local_len = len(self.strokes)
        self.num_batches = self._common_len // self._gbatch
        # -- length-bucketed execution (ISSUE 4) ---------------------------
        # Effective edges always end at max_seq_len (the terminal bucket),
        # so every admitted sequence has a bucket. Empty = bucketing off,
        # the exact-parity default: next_batch then IS random_batch.
        self.seed = seed
        if hps.bucket_edges:
            if num_hosts > 1 and not coordinated:
                # each host would plan its own bucket schedule, so the
                # per-step GLOBAL batch would mix (B, Tb) geometries
                # across hosts and the SPMD collectives would deadlock;
                # multi-host bucketing needs the coordinated global plan
                raise RuntimeError(
                    f"bucket_edges on a host-striped loader (num_hosts="
                    f"{num_hosts}) would launch mismatched per-host "
                    f"batch geometries; build the loader with "
                    f"coordinated=True (the ISSUE 14 coordinated global "
                    f"plan: every host derives the identical schedule "
                    f"from the global corpus and stripes each batch's "
                    f"rows)")
            edges = tuple(hps.bucket_edges)
            if edges[-1] < hps.max_seq_len:
                edges = edges + (hps.max_seq_len,)
            self.bucket_edges: Tuple[int, ...] = edges
        else:
            self.bucket_edges = ()
        self._lengths = np.array([len(s) for s in self.strokes], np.int32)
        self._bucket_epoch = 0
        self._bucket_queue: List[tuple] = []
        self.padding_ledger = PaddingLedger(
            self.bucket_edges or (hps.max_seq_len,))

    def __len__(self) -> int:
        return len(self.strokes)

    # -- normalization -----------------------------------------------------

    def calculate_normalizing_scale_factor(self) -> float:
        return S.calculate_normalizing_scale_factor(self.strokes)

    def normalize(self, scale_factor: float) -> None:
        # in place: the loader owns its arrays (see class docstring — float32
        # inputs are adopted without copying). The factor is kept for the
        # int16 transfer path (data/prefetch.py): quantizing a normalized
        # offset back by this factor recovers the EXACT integer delta for
        # integer-origin corpora like QuickDraw.
        self.scale_factor = float(scale_factor)
        for s in self.strokes:
            s[:, 0:2] /= scale_factor

    # -- batching ----------------------------------------------------------

    def _pad_batch(self, batch: Sequence[np.ndarray],
                   nmax: Optional[int] = None) -> np.ndarray:
        # the shared stroke-5 layout (NB.pad_batch_numpy): ONE
        # implementation behind this, the streaming batcher's fallback
        # and the serve endpoints' prefix padding — the bitwise
        # serve-vs-offline parity contract depends on them agreeing
        nmax = self.hps.max_seq_len if nmax is None else nmax
        return NB.pad_batch_numpy(list(batch), nmax)[0]

    def _assemble(self, idx: np.ndarray,
                  int16_scale: Optional[float] = None,
                  pad_to: Optional[int] = None
                  ) -> Dict[str, np.ndarray]:
        # hot path: the C++ batcher (SURVEY §2 component 1 native path)
        # runs the whole batch assembly as one native call — at train time
        # including the augmentations (scale jitter + point dropout), so
        # no per-sequence Python loop remains. Golden-tested equal to the
        # numpy path (bit-exact without augmentation, distributionally
        # with — the native RNG is a counter-based stream, not numpy's).
        # ``int16_scale``: quantize offsets back to integer data units in
        # the SAME native pass (the exact int16 transfer path,
        # data/prefetch.py) and add the "transfer_scale" [B] leaf.
        # ``pad_to``: pad only to this bucket edge instead of max_seq_len
        # (length-bucketed execution; every row must fit — callers bin by
        # raw length, and augmentation only ever SHORTENS a sequence).
        # fault site (ISSUE 10): a batch-assembly failure — fires on
        # the prefetch producer thread in a real run, so a chaos plan
        # exercises the Prefetcher's cross-thread error propagation
        fault_point("data.batch")
        pad = self.hps.max_seq_len if pad_to is None else int(pad_to)
        if int16_scale is not None and not (int16_scale > 0):
            # mirrors the prefetch guard for direct random_batch callers:
            # the native path refuses quant<=0 (returns None) and the
            # numpy fallback would quantize with scale 0 into all-zero
            # offsets + transfer_scale 0 (device-side divide-by-zero)
            raise ValueError(
                f"int16_scale must be positive, got {int16_scale}")
        raw = [self.strokes[i] for i in idx]
        # ONE augmentation seed per batch, shared by every native attempt:
        # drawing a fresh seed per attempt would make the augmentation
        # stream diverge across environments (native-i16 present vs
        # absent) for the same loader seed (ADVICE r4)
        aug_seed = int(self.rng.integers(0, 2 ** 63)) if self.augment else 0
        strokes = None
        if int16_scale is not None:
            native = NB.assemble_batch_aug_i16(
                raw, pad,
                self.hps.random_scale_factor if self.augment else 0.0,
                self.hps.augment_stroke_prob if self.augment else 0.0,
                seed=aug_seed,
                quant=float(int16_scale))
            if native is not None:
                strokes, seq_len = native
            # else: assemble float32 below, quantize in numpy at the end
        if strokes is None:
            if self.augment:
                native = NB.assemble_batch_aug(
                    raw, pad,
                    self.hps.random_scale_factor,
                    self.hps.augment_stroke_prob,
                    seed=aug_seed)
            else:
                native = NB.assemble_batch(raw, pad)
            if native is not None:
                strokes, seq_len = native
            else:
                if self.augment:
                    raw = [S.augment_strokes(
                        S.random_scale(s, self.hps.random_scale_factor,
                                       self.rng),
                        self.hps.augment_stroke_prob, self.rng) for s in raw]
                strokes = self._pad_batch(raw, pad)
                seq_len = np.array([len(s) for s in raw], dtype=np.int32)
            if int16_scale is not None:
                # numpy fallback quantization: same rounding (np.rint is
                # half-even, matching the native nearbyintf)
                q = np.empty(strokes.shape, np.int16)
                np.clip(np.rint(strokes[..., :2] * int16_scale),
                        -32767, 32767, out=q[..., :2], casting="unsafe")
                q[..., 2:] = strokes[..., 2:]
                strokes = q
        # padding-waste accounting (host-side, thread-safe, no RNG): the
        # metrics row's padded_frac / per-bucket dispatch columns
        self.padding_ledger.record(pad, len(raw), int(seq_len.sum()))
        batch = {
            "strokes": strokes,
            "seq_len": seq_len,
            "labels": self.labels[idx],
        }
        if int16_scale is not None:
            batch["transfer_scale"] = np.full((len(raw),), int16_scale,
                                              np.float32)
        return batch

    @property
    def num_eval_batches(self) -> int:
        """Batches for a full eval sweep, including a wrap-filled tail.

        ``ceil(max_local_len / batch_size)``: trailing batches wrap around
        to the start of the corpus, so every example on EVERY host is
        evaluated at least once while all batches keep the full (compiled)
        batch shape. Identical on every host (derived from the pre-stripe
        corpus size), so the SPMD sweep launches the same program count
        cluster-wide. Zero when any host's stripe is empty (common length
        0): eval is then impossible cluster-wide and every host must agree
        on that rather than deadlock.
        """
        if self._common_len == 0:
            return 0
        b = self._gbatch
        return (self._max_local_len + b - 1) // b

    def filter_by_label(self, label: int) -> "DataLoader":
        """New loader over this one's class-``label`` examples only.

        For single-host per-class inspection (multi-host per-class EVAL
        uses ``train.loop.evaluate_per_class``, which sweeps the standard
        batches with a class mask instead). Shares the (already
        normalized) stroke arrays — do not call ``normalize`` on the
        result. Augmentation is off: the filtered view exists for
        deterministic eval. Single-host only, enforced here (ADVICE r2):
        the per-class GLOBAL count is not derivable locally under host
        striping, so a striped filtered loader would launch mismatched
        SPMD batch counts across hosts and deadlock the sweep.
        """
        if self.num_hosts > 1:
            raise RuntimeError(
                f"filter_by_label on a host-striped loader "
                f"(num_hosts={self.num_hosts}) would deadlock the SPMD "
                f"eval sweep (the per-class GLOBAL count is not a batch "
                f"multiple on every host, coordinated or not); use "
                f"train.loop.evaluate_per_class instead")
        sel = np.flatnonzero(self.labels == label)
        return DataLoader([self.strokes[i] for i in sel], self.hps,
                          labels=self.labels[sel], augment=False)

    def host_slice(self, batch: Dict[str, np.ndarray],
                   host: Optional[int] = None) -> Dict[str, np.ndarray]:
        """Host ``host``'s row-slice of a GLOBAL coordinated batch:
        rows ``[host * B_local, (host + 1) * B_local)`` of every leaf
        (strokes, seq_len, labels, weights, transfer_scale alike). The
        striping contract: the per-host slices partition the global
        batch exactly, in host order — tier-1-pinned, and what
        ``parallel.mesh.shard_batch`` ships per process on a real
        mesh."""
        if not self.coordinated:
            raise ValueError("host_slice needs a coordinated loader")
        h = self.host_id if host is None else host
        if not 0 <= h < self.num_hosts:
            raise ValueError(f"host {h} out of range for "
                             f"num_hosts={self.num_hosts}")
        b = self.hps.batch_size
        return {k: v[h * b:(h + 1) * b] for k, v in batch.items()}

    def _emit(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Route an assembled GLOBAL batch to the configured view: this
        host's row-slice (the real-mesh transfer contract), the whole
        global batch (``emit_global`` — the light-mode replicated
        runtime), or unchanged for legacy (uncoordinated) loaders."""
        if not self.coordinated or self.emit_global:
            return batch
        return self.host_slice(batch)

    def plan_fingerprint(self, epoch: Optional[int] = None) -> str:
        """Digest of the coordinated schedule a peer host must agree
        on: global batch size, bucket edges, the CORPUS CONTENT
        (labels + every normalized stroke's bytes — a same-sized but
        diverged corpus, e.g. a stale file on one host's disk, must
        NOT pass), and — under bucketed execution — the exact ``(Tb,
        idx, weighted?)`` epoch plan. Pure in ``(seed, epoch)``; the
        elastic runtime exchanges it at fleet start so diverged plans
        fail LOUDLY instead of silently training hosts on different
        global streams (train/elastic.py). Cost: one pass over the
        corpus bytes per fleet (re)start — O(corpus), not O(steps)."""
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        h.update(f"{self.seed}:{self._gbatch}:{self.bucket_edges}:"
                 f"{len(self.strokes)}:{self.augment}".encode())
        h.update(np.ascontiguousarray(self.labels).tobytes())
        for s in self.strokes:
            h.update(np.ascontiguousarray(s).tobytes())
        if self.bucket_edges:
            ep = self._bucket_epoch if epoch is None else int(epoch)
            for tb, idx, w in self._plan_bucket_epoch(ep):
                h.update(np.int64(tb).tobytes())
                h.update(np.ascontiguousarray(idx, np.int64).tobytes())
                h.update(b"-" if w is None
                         else np.ascontiguousarray(w, np.float32).tobytes())
        return h.hexdigest()

    def random_batch(self, int16_scale: Optional[float] = None
                     ) -> Dict[str, np.ndarray]:
        idx = self.rng.choice(len(self.strokes), self._gbatch,
                              replace=len(self.strokes) < self._gbatch)
        return self._emit(self._assemble(idx, int16_scale=int16_scale))

    def fast_forward(self, n_batches: int) -> None:
        """Advance the training feed by ``n_batches`` batches, discarding
        them — crash-equivalent resume alignment (ISSUE 10).

        A resumed run builds a FRESH loader whose RNG stream starts at
        batch 0, but resumes training at step R — so without alignment
        its step-R batch would be the stream's batch 0, not the batch
        the uninterrupted run drew at step R, and the final states
        could never match. Consuming R batches through the real
        :meth:`next_batch` path (assembly included — the augmentation
        stream draws inside ``_assemble``) makes the resumed feed
        byte-identical to the uninterrupted run's from step R on;
        ``scripts/resilience_bench.py`` is the caller that proves the
        resulting final state leaf-bitwise equal. The padding ledger's
        window is reset afterwards so the discarded batches cannot leak
        into the resumed run's first ``padded_frac`` metrics row.
        """
        if n_batches < 0:
            raise ValueError(f"n_batches must be >= 0, got {n_batches}")
        for _ in range(n_batches):
            self.next_batch()
        if n_batches:
            self.padding_ledger.window()

    # -- length-bucketed batching (ISSUE 4) --------------------------------

    def bucket_edge_of(self, length: int) -> int:
        """Smallest bucket edge that fits a sequence of ``length`` steps
        (``max_seq_len`` when bucketing is off)."""
        if not self.bucket_edges:
            return self.hps.max_seq_len
        e = int(np.searchsorted(np.asarray(self.bucket_edges), length))
        if e >= len(self.bucket_edges):
            raise ValueError(
                f"sequence length {length} exceeds the terminal bucket "
                f"edge {self.bucket_edges[-1]} (= max_seq_len); the "
                f"corpus was not filtered to max_seq_len")
        return self.bucket_edges[e]

    def _plan_bucket_epoch(self, epoch: int) -> List[tuple]:
        """One epoch's bucketed batch plan: ``[(tb, idx[B], weights?)]``.

        Deterministic in ``(loader seed, epoch)`` and independent of the
        loader's augmentation RNG stream (a separate generator plans the
        epoch). Covering contract: every corpus index appears with
        weight 1 exactly ONCE across the epoch's batches — a seeded
        permutation is binned by RAW length (augmentation point-dropout
        only shortens, so a raw-length bin's edge always still fits),
        each bucket is cut into full batches of ``batch_size``, and the
        per-bucket tails are merged (padded to the largest member's
        edge) into the final batches; the last of those wrap-fills with
        already-emitted rows carrying weight 0, exactly like the eval
        sweep's wrap batches, so every full-shape batch stays
        compiled-geometry-clean while the weighted loss still treats
        each example once. The batch ORDER then passes through a seeded
        windowed shuffle (``bucket_shuffle_window``) so binning by
        length cannot introduce a length-curriculum bias; windows >= the
        epoch's batch count give a full shuffle.

        Coordinated multi-host mode plans GLOBAL batches (``B_local *
        num_hosts`` indices per batch) over the global corpus — the
        plan is identical on every host AND at every topology sharing
        the global batch size, which is what makes host-striped
        bucketing and topology-change-equivalent resume possible.
        """
        b = self._gbatch
        rng = np.random.default_rng([self.seed & 0x7FFFFFFF, 9176, epoch])
        perm = rng.permutation(len(self.strokes))
        bins: Dict[int, List[int]] = {e: [] for e in self.bucket_edges}
        for i in perm:
            bins[self.bucket_edge_of(int(self._lengths[i]))].append(int(i))
        batches: List[tuple] = []
        tails: List[Tuple[int, int]] = []
        for e in self.bucket_edges:
            arr = bins[e]
            for lo in range(0, len(arr) - len(arr) % b, b):
                batches.append((e, np.array(arr[lo:lo + b], np.int64),
                                None))
            tails.extend((e, i) for i in arr[len(arr) - len(arr) % b:])
        for lo in range(0, len(tails), b):
            chunk = tails[lo:lo + b]
            tb = max(e for e, _ in chunk)
            idx = np.array([i for _, i in chunk], np.int64)
            w = None
            if len(idx) < b:
                w = np.zeros((b,), np.float32)
                w[:len(idx)] = 1.0
                idx = idx[np.arange(b) % len(idx)]
            batches.append((tb, idx, w))
        if self.hps.bucket_run_len > 0:
            # run-aware shuffle (ISSUE 5): group consecutive same-
            # geometry batches into runs of <= bucket_run_len and let
            # the windowed shuffle permute RUNS as units instead of
            # splitting them — the stacked K-step scheduler amortizes
            # exactly these consecutive same-(B, Tb) sequences. Pure
            # ordering: the multiset of batches (hence coverage and
            # per-batch contents) is untouched, and nothing here reads
            # steps_per_call, so the plan stays K-independent.
            runs: List[List[tuple]] = []
            for bt in batches:
                g = (bt[0], bt[2] is None)
                if (runs and (runs[-1][0][0], runs[-1][0][2] is None) == g
                        and len(runs[-1]) < self.hps.bucket_run_len):
                    runs[-1].append(bt)
                else:
                    runs.append([bt])
            shuffled = _windowed_shuffle(runs,
                                         self.hps.bucket_shuffle_window,
                                         rng)
            return [bt for run in shuffled for bt in run]
        return _windowed_shuffle(batches,
                                 self.hps.bucket_shuffle_window, rng)

    @staticmethod
    def _count_geometry_runs(plan: List[tuple]) -> int:
        """Maximal consecutive same-geometry sequences in a plan (a run
        boundary falls wherever ``(Tb, weighted?)`` changes)."""
        runs, prev = 0, None
        for tb, _, w in plan:
            g = (tb, w is None)
            if g != prev:
                runs += 1
                prev = g
        return runs

    def _refill_bucket_queue(self) -> None:
        if not self.strokes:
            raise ValueError("bucketed next_batch on an empty corpus")
        plan = self._plan_bucket_epoch(self._bucket_epoch)
        self._bucket_epoch += 1
        self.padding_ledger.note_epoch_plan(
            self._count_geometry_runs(plan), len(plan))
        self._bucket_queue = plan

    def next_batch(self, int16_scale: Optional[float] = None
                   ) -> Dict[str, np.ndarray]:
        """Next training batch: the bucketed epoch stream when
        ``hps.bucket_edges`` is set, else exactly :meth:`random_batch`
        (the buckets-off path is bit-for-bit the pre-bucketing feed —
        same RNG stream, same shapes)."""
        if not self.bucket_edges:
            return self.random_batch(int16_scale=int16_scale)
        if not self._bucket_queue:
            self._refill_bucket_queue()
        tb, idx, w = self._bucket_queue.pop(0)
        batch = self._assemble(idx, int16_scale=int16_scale, pad_to=tb)
        if w is not None:
            # wrap-filled duplicate rows are zero-weighted: the loss
            # normalizes by sum(weights), so the epoch's weighted stream
            # treats every example exactly once (mdn.reconstruction_loss)
            batch["weights"] = w
        return self._emit(batch)

    def seek_epoch(self, epoch: int) -> None:
        """Rewind the bucketed stream to the START of ``epoch``'s plan.

        The plan is a pure function of ``(seed, epoch)``, so two
        loaders (or two passes over one loader) seeked to the same
        epoch emit identical batch streams — the hook benchmarks use
        to time arms over the same workload (scripts/bucket_bench.py).
        Bucketed loaders only; the queue refills lazily on the next
        ``next_batch``/``next_stack`` call."""
        if not self.bucket_edges:
            raise ValueError("seek_epoch requires bucketed execution "
                             "(bucket_edges)")
        self._bucket_queue = []
        self._bucket_epoch = int(epoch)

    def next_stack(self, k_max: int, int16_scale: Optional[float] = None
                   ) -> Dict[str, np.ndarray]:
        """Up to ``k_max`` consecutive same-geometry training batches,
        stacked on a new leading axis (ISSUE 5 bucket-run scheduler).

        Pops the maximal prefix of the current geometry run — batches
        sharing one ``(Tb, weighted?)`` — capped at ``k_max`` and at
        the epoch boundary (stacks never cross an epoch refill), so
        every returned array has leading axis ``k`` with ``1 <= k <=
        k_max``. A full ``k == k_max`` stack rides the compiled K-step
        scan; shorter stacks are run remainders the training loop
        replays as single micro-steps.

        Stream contract: concatenating the micro-batches of successive
        ``next_stack`` calls reproduces the :meth:`next_batch` stream
        of an identically-seeded loader EXACTLY (same plan, same
        assembly order, same augmentation RNG draws) — the scheduler
        changes dispatch, never data. Weighted wrap-tail batches form
        their own (length-1) runs, so a stack's micro-batches either
        all carry ``"weights"`` or none do.
        """
        if k_max < 1:
            raise ValueError(f"k_max must be >= 1, got {k_max}")
        if not self.bucket_edges:
            raise ValueError(
                "next_stack is the bucketed scheduler's entry point; "
                "with bucket_edges unset use next_batch/random_batch "
                "(fixed-T stacks are plain np.stack over K batches)")
        if not self._bucket_queue:
            self._refill_bucket_queue()
        tb0, _, w0 = self._bucket_queue[0]
        k = 1
        while (k < k_max and k < len(self._bucket_queue)
               and self._bucket_queue[k][0] == tb0
               and (self._bucket_queue[k][2] is None) == (w0 is None)):
            k += 1
        # delegate the pops to next_batch so the stream-identity
        # contract is structural, not a duplicated assembly body
        # (k <= len(queue), so no refill can happen mid-stack)
        parts = [self.next_batch(int16_scale=int16_scale)
                 for _ in range(k)]
        return {name: np.stack([p[name] for p in parts])
                for name in parts[0]}

    def eval_pad_len(self, batch_index: int) -> int:
        """Pad length :meth:`get_batch` will use for ``batch_index``:
        the bucket edge of the batch's longest row under bucketed
        execution, else ``max_seq_len``. Host-side metadata only — the
        eval sweep groups consecutive same-geometry batches into one
        scan program with it (train.loop._sweep_rows)."""
        if not self.bucket_edges:
            return self.hps.max_seq_len
        idx = self._eval_indices(batch_index)
        return self.bucket_edge_of(int(self._lengths[idx].max()))

    def _eval_indices(self, batch_index: int) -> np.ndarray:
        if not 0 <= batch_index < self.num_eval_batches:
            raise IndexError(f"batch {batch_index} of "
                             f"{self.num_eval_batches}")
        lo = batch_index * self._gbatch
        linear = np.arange(lo, lo + self._gbatch)
        # modulo is over the LOCAL length so hosts holding a striping
        # remainder example still use it (coordinated mode: the length
        # IS the global corpus and the batch is global)
        return linear % len(self.strokes)

    def get_batch(self, batch_index: int) -> Dict[str, np.ndarray]:
        """Deterministic eval batch; includes a ``"weights"`` [B] vector.

        Wrapped batches (linear index past the local corpus) repeat rows
        from the corpus start to keep the compiled batch shape; those
        duplicate rows get weight 0 so weighted eval metrics are exact
        sample means over the split (first occurrences get weight 1).

        Under bucketed execution (``hps.bucket_edges``) the batch is
        padded only to :meth:`eval_pad_len` — masked eval losses are
        bitwise independent of the pad length (tested), so the sweep
        result is unchanged while the eval scan runs at bucket depth.
        """
        lo = batch_index * self._gbatch
        linear = np.arange(lo, lo + self._gbatch)
        idx = self._eval_indices(batch_index)
        pad = (self.eval_pad_len(batch_index)
               if self.bucket_edges else None)
        batch = self._assemble(idx, pad_to=pad)
        batch["weights"] = (linear < len(self.strokes)).astype(np.float32)
        return self._emit(batch)


def _windowed_shuffle(items: List, window: int,
                      rng: np.random.Generator) -> List:
    """tf.data-style windowed shuffle: emit a uniform draw from a
    sliding buffer of ``window`` items. ``window`` >= ``len(items)`` is
    a full shuffle; a small window bounds how far an item can travel,
    which is enough to break bucket-ordered (length-curriculum) runs."""
    if len(items) <= 1:
        return list(items)
    out: List = []
    buf: List = []
    for it in items:
        buf.append(it)
        if len(buf) >= max(1, window):
            out.append(buf.pop(int(rng.integers(len(buf)))))
    while buf:
        out.append(buf.pop(int(rng.integers(len(buf)))))
    return out


# -- dataset assembly ------------------------------------------------------


def _stripe(seqs, labels, host_id: int, num_hosts: int):
    """Disjoint per-host slice of a corpus (shared by real + synthetic
    paths so the striping scheme cannot drift between them)."""
    if num_hosts <= 1:
        return seqs, labels
    return seqs[host_id::num_hosts], labels[host_id::num_hosts]


def _host_seed(seed: int, host_id: int) -> int:
    """Decorrelate per-host loader RNG streams."""
    return seed + 7919 * host_id


def load_dataset(hps: HParams,
                 data_dir: Optional[str] = None,
                 host_id: int = 0,
                 num_hosts: int = 1,
                 scale_factor: Optional[float] = None,
                 skip_bad_records: bool = False,
                 coordinated: Optional[bool] = None,
                 emit_global: bool = False,
                 ) -> Tuple[DataLoader, DataLoader, DataLoader, float]:
    """Read category ``.npz`` files and build train/valid/test loaders.

    Multi-category configs (BASELINE configs 4-5) pool the categories and
    attach the category index as the class label. ``host_id``/``num_hosts``
    stripe the training examples across hosts for multi-host data
    parallelism (each host feeds its own slice of the global batch).

    ``coordinated`` (ISSUE 14): every host keeps the GLOBAL corpus and
    the SHARED seed, derives the identical global schedule, and emits
    its row-slice of every batch (see the DataLoader docstring) —
    required for host-striped bucketed execution, and what makes a
    resume at a different host count replay the same global stream.
    Default ``None`` auto-selects it exactly when it is required
    (``hps.bucket_edges`` and ``num_hosts > 1``); the legacy striped
    corpus (decorrelated per-host feeds) remains the buckets-off
    multi-host default, byte-for-byte. ``emit_global`` (coordinated
    only) returns un-sliced global batches — the light-mode elastic
    runtime's replicated feed.

    Returns ``(train, valid, test, scale_factor)``; every split is
    normalized by the train split's scale factor (SURVEY §3.5) — or by a
    given ``scale_factor`` (eval/sample against a checkpoint must reuse the
    checkpointed value, which is part of the model contract).

    Hardening (ISSUE 10 satellite): an unreadable/truncated ``.npz`` or
    a corrupt record fails with ONE line naming the file (and record
    index) instead of a decompression traceback; ``skip_bad_records``
    skips corrupt records instead, counted in the ``records_skipped``
    telemetry counter (``cli --skip_bad_records``).
    """
    data_dir = data_dir or hps.data_dir
    splits = {"train": ([], []), "valid": ([], []), "test": ([], [])}
    for label, name in enumerate(hps.data_set):
        path = os.path.join(data_dir, name)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path} not found; QuickDraw .npz files are required "
                f"(or use make_synthetic_strokes for a synthetic corpus)")
        try:
            npz = np.load(path, allow_pickle=True, encoding="latin1")
        except Exception as e:  # noqa: BLE001 — np.load raises zipfile/
            # pickle/OSError zoo on damage; the user needs the file name
            raise RuntimeError(
                f"{path}: unreadable .npz ({type(e).__name__}: {e}) — "
                f"corrupt or truncated download?") from None
        with npz:
            for split in splits:
                try:
                    # materializing the array decompresses the zip
                    # member — truncation/bit-rot surfaces HERE
                    arr = list(npz[split])
                except KeyError:
                    raise RuntimeError(
                        f"{path}: no {split!r} array — not a sketch-rnn "
                        f".npz (needs train/valid/test)") from None
                except Exception as e:  # noqa: BLE001
                    raise RuntimeError(
                        f"{path}: corrupt {split!r} array "
                        f"({type(e).__name__}: {e}) — truncated or "
                        f"damaged .npz member") from None
                seqs = _purify(arr, hps.max_seq_len,
                               source=f"{path}[{split}]",
                               skip_bad=skip_bad_records)
                splits[split][0].extend(seqs)
                splits[split][1].extend([label] * len(seqs))

    _SEEDS = {"train": 1, "valid": 2, "test": 3}  # fixed: runs must be reproducible

    coord = (num_hosts > 1 and bool(hps.bucket_edges)
             if coordinated is None else coordinated)

    def build(split: str, augment: bool) -> DataLoader:
        seqs, labels = splits[split]
        if not seqs:
            raise ValueError(
                f"{split} split is empty after filtering to "
                f"max_seq_len={hps.max_seq_len}; raise max_seq_len or check "
                f"the data files {hps.data_set}")
        if coord:
            # coordinated global plan (ISSUE 14): every host keeps the
            # WHOLE split and the SHARED seed — the schedule is then a
            # pure function of (seed, epoch, global corpus) on every
            # host and at every topology; each host emits only its
            # row-slice of each globally-planned batch
            return DataLoader(seqs, hps,
                              labels=np.array(labels, np.int32),
                              augment=augment, seed=_SEEDS[split],
                              num_hosts=num_hosts, host_id=host_id,
                              coordinated=True, emit_global=emit_global)
        # every split is host-striped: train for data parallelism, valid/
        # test so the eval sweep's global batches hold DISTINCT rows (each
        # host feeds 1/num_hosts of each global batch)
        global_size = len(seqs)
        seqs, labels = _stripe(seqs, labels, host_id, num_hosts)
        return DataLoader(seqs, hps, labels=np.array(labels, np.int32),
                          augment=augment,
                          seed=_host_seed(_SEEDS[split], host_id),
                          global_size=global_size, num_hosts=num_hosts)

    train = build("train", augment=True)
    # Scale factor comes from the FULL train split (pre-shard): every host
    # must normalize identically (it is part of the model contract and is
    # checkpointed — SURVEY §5 'Checkpoint / resume').
    scale = (scale_factor if scale_factor is not None
             else S.calculate_normalizing_scale_factor(splits["train"][0]))
    valid = build("valid", augment=False)
    test = build("test", augment=False)
    for dl in (train, valid, test):
        dl.normalize(scale)
    return train, valid, test, scale


# -- synthetic corpus ------------------------------------------------------


def make_synthetic_strokes(num: int,
                           num_classes: int = 1,
                           min_len: int = 24,
                           max_len: int = 96,
                           seed: int = 0,
                           fixed_class: Optional[int] = None,
                           integer_grid: Optional[float] = None,
                           ) -> Tuple[List[np.ndarray], np.ndarray]:
    """Deterministic synthetic sketch corpus (SURVEY §7 'Data availability').

    Each class is a distinct parametric figure (ellipses / zigzags / spirals
    with class-dependent frequency), drawn as 1-3 pen strokes with noise, so
    models can measurably overfit and class-conditioning is learnable.

    ``integer_grid`` (VERDICT r4 #2): scale the figures by this factor and
    snap ABSOLUTE coordinates to the integer lattice before differencing —
    QuickDraw's own shape (integer pixel coords -> integer deltas, no
    cumulative rounding drift). The corpus then has a normalization scale
    factor proportional to the grid (a grid of 255 — a pixel-canvas
    snap — lands at ~17-65 depending on the class mix, QuickDraw's own
    range), inside the int16 transfer mode's accepted band, letting
    that mode train with meaningful loss instead of refusing. The default
    ``None`` keeps the legacy float-natured corpus (goldens, existing
    history rows).

    Returns ``(stroke3_list, labels)`` — float32 arrays; with
    ``integer_grid`` the offset values are exact integers.
    """
    rng = np.random.default_rng(seed)
    # callers may shrink max_len arbitrarily (e.g. tiny max_seq_len configs)
    max_len = max(2, max_len)
    min_len = max(2, min(min_len, max_len))
    out: List[np.ndarray] = []
    if fixed_class is not None:
        labels = np.full((num,), fixed_class, dtype=np.int32)
    else:
        labels = rng.integers(0, num_classes, size=num).astype(np.int32)
    for i in range(num):
        c = int(labels[i])
        n = int(rng.integers(min_len, max_len + 1))
        t = np.linspace(0.0, 2.0 * np.pi, n)
        freq = 1.0 + c % 3
        radius = 1.0 + 0.5 * ((c // 3) % 3)
        phase = rng.random() * 2 * np.pi
        if c % 2 == 0:  # loopy figure
            x = radius * np.cos(freq * t + phase)
            y = radius * np.sin(t + phase) * (0.5 + 0.5 * (c % 5) / 4)
        else:           # zigzag figure
            x = t / np.pi - 1.0
            y = radius * np.sign(np.sin(freq * t + phase)) * (t / (2 * np.pi))
        x = x + rng.normal(0, 0.02, n)
        y = y + rng.normal(0, 0.02, n)
        if integer_grid is not None:
            # snap the ABSOLUTE path to the integer lattice, then diff:
            # deltas are exact integers with no cumulative rounding drift
            # (the same construction as QuickDraw's int16 pixel deltas)
            x = np.rint(x * integer_grid)
            y = np.rint(y * integer_grid)
        dx = np.diff(x, prepend=x[0]).astype(np.float32)
        dy = np.diff(y, prepend=y[0]).astype(np.float32)
        pen = np.zeros(n, dtype=np.float32)
        lift_pool = np.arange(4, n - 2)
        n_strokes = int(rng.integers(1, 2 + min(2, len(lift_pool))))
        lifts = rng.choice(lift_pool, size=n_strokes - 1,
                           replace=False) if n_strokes > 1 else []
        for j in lifts:
            pen[j] = 1.0
        pen[-1] = 1.0
        out.append(np.stack([dx, dy, pen], axis=1))
    return out, labels


def synthetic_loader(hps: HParams, num: int, seed: int = 0,
                     augment: bool = False,
                     scale_factor: Optional[float] = None,
                     host_id: int = 0, num_hosts: int = 1,
                     integer_grid: Optional[float] = None,
                     coordinated: Optional[bool] = None,
                     emit_global: bool = False,
                     ) -> Tuple[DataLoader, float]:
    """One synthetic-corpus DataLoader sized to ``hps`` (shared helper for
    the CLI, bench and driver entry; sequence lengths are clamped to fit
    ``max_seq_len``). Returns ``(loader, scale_factor)`` — pass a stored
    ``scale_factor`` to normalize by a checkpoint's contract instead of
    recomputing from this corpus. ``host_id``/``num_hosts`` stripe the
    corpus for multi-host DP; like ``load_dataset``, the scale factor is
    computed from the FULL pre-stripe corpus so every host normalizes
    identically. ``coordinated``/``emit_global`` select the ISSUE 14
    coordinated global plan exactly like :func:`load_dataset` (default:
    auto — coordinated when bucketed and multi-host)."""
    seqs, labels = make_synthetic_strokes(
        num, num_classes=max(hps.num_classes, 1),
        max_len=min(96, hps.max_seq_len - 2), seed=seed,
        integer_grid=integer_grid)
    if scale_factor is None:
        scale_factor = S.calculate_normalizing_scale_factor(seqs)
    coord = (num_hosts > 1 and bool(hps.bucket_edges)
             if coordinated is None else coordinated)
    if coord:
        loader = DataLoader(seqs, hps, labels=labels, augment=augment,
                            seed=seed, num_hosts=num_hosts,
                            host_id=host_id, coordinated=True,
                            emit_global=emit_global)
        loader.normalize(scale_factor)
        return loader, scale_factor
    global_size = len(seqs)
    seqs, labels = _stripe(seqs, labels, host_id, num_hosts)
    loader = DataLoader(seqs, hps, labels=labels, augment=augment,
                        seed=_host_seed(seed, host_id),
                        global_size=global_size, num_hosts=num_hosts)
    loader.normalize(scale_factor)
    return loader, scale_factor


def write_synthetic_npz(path: str, num_train: int = 200, num_valid: int = 50,
                        num_test: int = 50, class_id: int = 0,
                        seed: int = 0, **kw) -> None:
    """Write a synthetic corpus as a QuickDraw-shaped ``.npz`` file.

    QuickDraw ``.npz`` files are single-category (one file per class; the
    class label of a pooled dataset is the file's index in
    ``hps.data_set``, matching ``load_dataset``). ``class_id`` selects which
    synthetic figure family this file draws, so multi-file corpora have
    visually distinct classes.
    """
    sets = {}
    for split, n, s in (("train", num_train, seed), ("valid", num_valid,
                        seed + 1), ("test", num_test, seed + 2)):
        seqs, _ = make_synthetic_strokes(n, fixed_class=class_id,
                                         seed=s, **kw)
        sets[split] = np.array(seqs, dtype=object)
    np.savez_compressed(path, **sets)
