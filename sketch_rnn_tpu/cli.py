"""Command-line interface: train / eval / sample subcommands.

TPU-native equivalent of the reference's ``python sketch_rnn_train.py
--hparams=...`` entry point (SURVEY.md §1 "CLI / entry point", §2
component 14; reference unreadable — flag surface per the canonical CLI):
the ``--hparams`` override string uses the same ``key=value,key=value``
contract, plus subcommands replacing the reference's mode flags.

Usage:
    python -m sketch_rnn_tpu.cli train  --data_dir=D --workdir=W [--hparams=...]
    python -m sketch_rnn_tpu.cli eval   --data_dir=D --workdir=W [--split=test]
    python -m sketch_rnn_tpu.cli sample --workdir=W --output=out.svg [-n 10]

``--synthetic`` substitutes the deterministic synthetic corpus when no
QuickDraw ``.npz`` files are available (SURVEY §7 "Data availability").
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Tuple

import jax
import numpy as np

from sketch_rnn_tpu.config import HParams, get_default_hparams


# BASELINE.md's five benchmark configs as one-flag presets (applied before
# --hparams, so explicit overrides still win). Preset 5's mesh covers all
# available chips by default (mesh_shape=(-1,)).
PRESETS = {
    # 1: unconditional decoder-only LSTM, M=20 GMM, single category
    "uncond_lstm": "conditional=false,dec_model=lstm",
    # 2: full seq2seq VAE (bi-LSTM enc 256, dec 512, Nz=128), plain LSTM
    "vae": "conditional=true,dec_model=lstm",
    # 3: the decoder cell variants (LayerNorm-LSTM / HyperLSTM)
    "layer_norm": "conditional=true,dec_model=layer_norm",
    "hyper": "conditional=true,dec_model=hyper",
    # 4: class-conditional, 75 categories (data_set must list 75 files)
    "classes75": "conditional=true,dec_model=layer_norm,num_classes=75",
    # 5: 345-category QuickDraw, data-parallel over the device mesh,
    #    production perf config
    "quickdraw345_dp": ("conditional=true,dec_model=layer_norm,"
                        "num_classes=345,compute_dtype=bfloat16,"
                        "fused_rnn=true,fused_residual_dtype=bfloat16,"
                        "remat=true"),
}


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--preset", default="", choices=[""] + list(PRESETS),
                   help="BASELINE.md benchmark config preset "
                        "(hparams base; --hparams overrides on top)")
    p.add_argument("--hparams", default="",
                   help="comma-separated key=value overrides")
    p.add_argument("--workdir", default="workdir",
                   help="checkpoints + metrics directory")
    p.add_argument("--data_dir", default="", help="QuickDraw .npz directory")
    p.add_argument("--synthetic", action="store_true",
                   help="use the synthetic corpus instead of .npz files")
    p.add_argument("--synthetic_grid", type=float, default=255.0,
                   help="integer-grid scale of the synthetic corpus "
                        "(QuickDraw-shaped integer deltas, scale factor "
                        "> 5 so transfer_dtype=int16 works; 0 = legacy "
                        "float-natured corpus)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--skip_bad_records", action="store_true",
                   help="skip corrupt .npz records instead of failing "
                        "on the first one (counted in the "
                        "records_skipped telemetry counter + one "
                        "warning per file)")


def _resolve_hps(args) -> HParams:
    # workdir config (from a previous run's checkpoint meta) seeds the
    # defaults so eval/sample agree with training automatically
    base = get_default_hparams()
    meta_hps = _workdir_hps(args.workdir)
    if meta_hps is not None:
        base = meta_hps
    if args.preset:
        base = base.parse(PRESETS[args.preset])
    if args.data_dir:
        base = base.replace(data_dir=args.data_dir)
    return base.parse(args.hparams)


def _workdir_hps(workdir: str) -> Optional[HParams]:
    from sketch_rnn_tpu.train.checkpoint import latest_checkpoint
    step = latest_checkpoint(workdir) if workdir else None
    if step is None:
        return None
    meta = json.load(open(os.path.join(workdir, f"ckpt_{step:08d}.json")))
    return HParams.from_json(json.dumps(meta["hps"]))


def _load_data(hps: HParams, args,
               scale_factor: Optional[float] = None,
               host_id: Optional[int] = None,
               num_hosts: Optional[int] = None,
               local_hps: Optional[HParams] = None,
               coordinated: Optional[bool] = None,
               emit_global: bool = False,
               ) -> Tuple[object, object, object, float]:
    """Build loaders; ``scale_factor`` (from a checkpoint) overrides the
    recomputed train-split normalization — eval/sample must use the scale
    the model was trained with.

    ``hps`` here carries the GLOBAL batch size; per-host striping and the
    local loader batch size are applied internally (each host assembles
    ``1/process_count`` of every global batch). The elastic runtime
    (ISSUE 14) passes its own fleet coordinate + local hparams —
    ``host_id``/``num_hosts``/``local_hps`` default to the jax cluster's
    — and ``coordinated``/``emit_global`` select the coordinated global
    plan (see data/loader.py)."""
    from sketch_rnn_tpu.data.loader import load_dataset, synthetic_loader
    from sketch_rnn_tpu.parallel import multihost as mh
    lhps = local_hps if local_hps is not None else mh.local_batch_hps(hps)
    host = mh.process_index() if host_id is None else host_id
    nhosts = mh.process_count() if num_hosts is None else num_hosts
    if args.synthetic:
        grid = (args.synthetic_grid if args.synthetic_grid > 0 else None)
        if scale_factor is None:
            train_l, scale = synthetic_loader(
                lhps, 20 * hps.batch_size, seed=1, augment=True,
                host_id=host, num_hosts=nhosts, integer_grid=grid,
                coordinated=coordinated, emit_global=emit_global)
        else:
            # eval/sample with a checkpointed scale never touch the train
            # corpus — skip generating it
            train_l, scale = None, scale_factor
        # valid/test are striped too: each global eval batch then holds
        # num_hosts * (B/P) DISTINCT rows and the sweep does no
        # duplicated work across hosts
        valid_l, _ = synthetic_loader(lhps, 2 * hps.batch_size, seed=2,
                                      scale_factor=scale,
                                      host_id=host, num_hosts=nhosts,
                                      integer_grid=grid,
                                      coordinated=coordinated,
                                      emit_global=emit_global)
        test_l, _ = synthetic_loader(lhps, 2 * hps.batch_size, seed=3,
                                     scale_factor=scale,
                                     host_id=host, num_hosts=nhosts,
                                     integer_grid=grid,
                                     coordinated=coordinated,
                                     emit_global=emit_global)
        return train_l, valid_l, test_l, scale
    return load_dataset(lhps, scale_factor=scale_factor,
                        host_id=host, num_hosts=nhosts,
                        skip_bad_records=getattr(args, "skip_bad_records",
                                                 False),
                        coordinated=coordinated, emit_global=emit_global)


def _restore(hps: HParams, workdir: str):
    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train import make_train_state, restore_checkpoint
    model = SketchRNN(hps)
    template = make_train_state(model, hps, jax.random.key(0))
    state, scale, meta = restore_checkpoint(workdir, template)
    return model, state, scale, meta


def _arm_faults(args) -> int:
    """Arm the process-wide fault injector from ``--fault_plan`` (a
    chaos run, ISSUE 10). Returns an exit code: 0 = armed or no plan,
    2 = bad spec (usage error, before any expensive work). The caller
    owns the disarm (``faults.disable()`` in its finally)."""
    plan = getattr(args, "fault_plan", "")
    if not plan:
        return 0
    from sketch_rnn_tpu.utils import faults
    try:
        inj = faults.configure(plan, seed=getattr(args, "fault_seed", 0))
    except ValueError as e:
        print(f"[cli] bad --fault_plan: {e}", file=sys.stderr)
        return 2
    print(f"[faults] armed: {inj!r}", file=sys.stderr)
    return 0


def cmd_train(args) -> int:
    from sketch_rnn_tpu.parallel import multihost as mh
    from sketch_rnn_tpu.train import train
    from sketch_rnn_tpu.utils import faults
    mh.initialize()  # no-op unless launched as a multi-host cluster
    hps = _resolve_hps(args)
    # elastic fleet usage validation (ISSUE 14): fail before any
    # expensive work, like the serve-bench flag checks
    elastic_n = getattr(args, "elastic_hosts", 0)
    if elastic_n:
        if not args.rendezvous:
            print("[cli] --elastic_hosts needs --rendezvous DIR (the "
                  "shared heartbeat/barrier directory every host "
                  "points at)", file=sys.stderr)
            return 2
        if not 0 <= args.elastic_host_id < elastic_n:
            print(f"[cli] --elastic_host_id {args.elastic_host_id} out "
                  f"of range for --elastic_hosts {elastic_n}",
                  file=sys.stderr)
            return 2
        if hps.batch_size % elastic_n != 0:
            print(f"[cli] global batch {hps.batch_size} not divisible "
                  f"by {elastic_n} elastic hosts", file=sys.stderr)
            return 2
    elif args.rendezvous or getattr(args, "elastic_host_id", 0):
        print("[cli] --rendezvous/--elastic_host_id configure the "
              "elastic fleet; add --elastic_hosts N", file=sys.stderr)
        return 2
    serve_n = getattr(args, "serve_fleet", 0)
    if serve_n:
        # co-resident usage validation (ISSUE 20): fail before any
        # expensive work
        if elastic_n:
            print("[cli] --serve_fleet is single-process co-residency; "
                  "it does not compose with --elastic_hosts",
                  file=sys.stderr)
            return 2
        if serve_n < 2:
            print(f"[cli] --serve_fleet {serve_n} cannot serve through "
                  f"a rollout walk (one replica drains at a time); "
                  f"use N >= 2", file=sys.stderr)
            return 2
        if not args.workdir:
            print("[cli] --serve_fleet needs --workdir: the fleet "
                  "follows the training checkpoint directory",
                  file=sys.stderr)
            return 2
    rc = _arm_faults(args)
    if rc:
        return rc
    # the injector is process-global; in-process callers (tests,
    # drivers) must not inherit an armed plan from this run — the
    # finally covers EVERYTHING after arming, so a setup failure (bad
    # data_dir, bad --bucket_edges) can't leak the plan either
    try:
        if getattr(args, "bucket_edges", ""):
            # convenience spelling of --hparams bucket_edges=...:
            # accept comma OR semicolon separators (the hparam tuple
            # syntax is ';')
            hps = hps.parse(
                f"bucket_edges={args.bucket_edges.replace(',', ';')}")
        if getattr(args, "steps_per_call", 0):
            # convenience spelling of --hparams steps_per_call=K; with
            # --bucket_edges this turns on the bucket-run scheduler
            # (stacked same-geometry dispatch, ISSUE 5)
            hps = hps.replace(steps_per_call=args.steps_per_call)
        if getattr(args, "sync_io", False):
            # bisection/debugging escape hatch: force the fully
            # synchronous loop (blocking saves, eager metric
            # conversion) in one flag instead of two hparam overrides
            hps = hps.replace(async_checkpoint=False,
                              metrics_defer=False)
        if elastic_n:
            # elastic multi-host training (ISSUE 14): this process is
            # ONE host of a fleet coordinated through --rendezvous.
            # Light mode — no jax.distributed; each host runs the
            # identical global program over emit_global coordinated
            # loaders (replicated state), heartbeats, barriers every
            # step, and on a detected peer death checkpoints + resumes
            # at the surviving topology. Kill a host mid-run and watch
            # the survivors recover (README "Chaos quickstart").
            from sketch_rnn_tpu.train import elastic_train

            hps_e, args_e = hps, args

            def make_loaders(lhps, rank, n):
                return _load_data(hps_e, args_e, host_id=rank,
                                  num_hosts=n, local_hps=lhps,
                                  coordinated=True, emit_global=True)

            elastic_train(
                hps, make_loaders, rendezvous_dir=args.rendezvous,
                host_id=args.elastic_host_id, num_hosts=elastic_n,
                workdir=args.workdir, seed=args.seed,
                resume=not getattr(args, "no_resume", False),
                trace_dir=getattr(args, "trace_dir", "") or None,
                profile=getattr(args, "profile", False),
                watchdog=getattr(args, "watchdog", False),
                halt_on_anomaly=getattr(args, "halt_on_anomaly",
                                        False),
                stale_s=args.stale_after,
                heartbeat_interval_s=args.heartbeat_interval)
            return 0
        train_l, valid_l, test_l, scale = _load_data(hps, args)
        print(f"[cli] host {mh.process_index()}/{mh.process_count()}: "
              f"{len(train_l)} train / {len(valid_l)} valid sketches, "
              f"scale={scale:.4f}, devices={jax.device_count()}",
              flush=True)
        if serve_n:
            from sketch_rnn_tpu.runtime.coresident import \
                coresident_train

            _, summary = coresident_train(
                hps, train_l, valid_l, test_l, scale_factor=scale,
                workdir=args.workdir, seed=args.seed,
                replicas=serve_n,
                poll_s=getattr(args, "serve_poll", 0.25),
                resume=not getattr(args, "no_resume", False),
                profile=getattr(args, "profile", False),
                trace_dir=getattr(args, "trace_dir", "") or None,
                watchdog=getattr(args, "watchdog", False),
                halt_on_anomaly=getattr(args, "halt_on_anomaly",
                                        False))
            print(f"[cli] co-resident fleet: "
                  f"{len(summary['rollouts'])} rollout(s), served "
                  f"through ckpt {summary['serving_ckpt_id']}, "
                  f"{summary['health_degraded']}/"
                  f"{summary['health_samples']} degraded health "
                  f"samples, lineage in "
                  f"{os.path.join(args.workdir, 'RUN.json')}",
                  flush=True)
        else:
            train(hps, train_l, valid_l, test_l, scale_factor=scale,
                  workdir=args.workdir, seed=args.seed,
                  resume=not getattr(args, "no_resume", False),
                  profile=getattr(args, "profile", False),
                  trace_dir=getattr(args, "trace_dir", "") or None,
                  watchdog=getattr(args, "watchdog", False),
                  halt_on_anomaly=getattr(args, "halt_on_anomaly",
                                          False))
    finally:
        faults.disable()
    return 0


def cmd_distill(args) -> int:
    """Distill a draft decoder (ISSUE 18) from the latest checkpoint.

    Restores the teacher from ``--workdir``, then drives the REAL train
    loop (bucketed loader, async checkpointing, resume, telemetry) over
    a ``DistillModel`` into ``<workdir>/draft`` — its own checkpoints,
    draft-shaped, paired to the teacher via the RUN.json lineage block.
    Serve the pair with ``serve-bench --draft_ckpt <workdir>/draft``.
    """
    from sketch_rnn_tpu.parallel import multihost as mh
    from sketch_rnn_tpu.train import distill
    from sketch_rnn_tpu.train.checkpoint import ckpt_id_of
    mh.initialize()  # no-op unless launched as a multi-host cluster
    hps = _resolve_hps(args)
    try:
        model, state, scale, meta = _restore(hps, args.workdir)
    except FileNotFoundError as e:
        print(f"[cli] distill needs a teacher checkpoint in "
              f"--workdir: {e}", file=sys.stderr)
        return 2
    # the draft must train on the TEACHER's normalization — the
    # checkpointed scale overrides the recomputed one, like eval/sample
    # (but unlike eval/sample, distillation DOES need the train corpus,
    # which _load_data skips for synthetic runs with a pinned scale)
    if args.synthetic:
        from sketch_rnn_tpu.data.loader import synthetic_loader
        grid = (args.synthetic_grid if args.synthetic_grid > 0 else None)
        train_l, _ = synthetic_loader(
            mh.local_batch_hps(hps), 20 * hps.batch_size, seed=1,
            augment=True, scale_factor=scale,
            host_id=mh.process_index(), num_hosts=mh.process_count(),
            integer_grid=grid)
    else:
        train_l, _, _, scale = _load_data(hps, args, scale_factor=scale)
    print(f"[cli] distilling draft (size {hps.draft_rnn_size}, "
          f"{hps.draft_num_mixture or hps.num_mixture} mixtures) from "
          f"teacher step {int(state.step)}, scale={scale:.4f}",
          flush=True)
    distill(hps, state.params, train_l, args.workdir, seed=args.seed,
            num_steps=(args.steps or None),
            teacher_ckpt_id=ckpt_id_of(int(state.step)),
            scale_factor=scale,
            resume=not getattr(args, "no_resume", False))
    return 0


def cmd_eval(args) -> int:
    from sketch_rnn_tpu.parallel import multihost as mh
    from sketch_rnn_tpu.parallel.mesh import make_mesh
    from sketch_rnn_tpu.train import make_eval_step
    from sketch_rnn_tpu.train.loop import evaluate, evaluate_per_class
    from sketch_rnn_tpu.train.step import (make_multi_eval_step,
                                           make_multi_per_class_eval_step,
                                           make_per_class_eval_step)
    mh.initialize()  # no-op unless launched as a multi-host cluster
    hps = _resolve_hps(args)
    if args.per_class and hps.num_classes <= 0:
        print("[cli] --per_class needs a multi-class model "
              "(num_classes > 0)", file=sys.stderr)
        return 2
    model, state, scale, meta = _restore(hps, args.workdir)
    _, valid_l, test_l, _ = _load_data(hps, args, scale_factor=scale)
    loader = {"valid": valid_l, "test": test_l}[args.split]
    mesh = make_mesh(hps)
    eval_step = make_eval_step(model, hps, mesh)
    eval_k = hps.eval_steps_per_call
    multi = (None if eval_k == 1
             else (make_multi_eval_step(model, hps, mesh), eval_k))
    ev = evaluate(state.params, loader, eval_step, mesh, multi=multi)
    out = {"split": args.split, "step": meta["step"],
           **{k: round(v, 6) for k, v in sorted(ev.items())}}
    if args.per_class:
        # reference-paper parity surface: per-category losses. One masked
        # sweep over the standard eval batches — multi-host safe (the
        # batch schedule is identical on every host), unlike the old
        # filter_by_label loop. Classes with no examples report null.
        pc_step = make_per_class_eval_step(model, hps, mesh)
        pc_multi = (None if eval_k == 1 else
                    (make_multi_per_class_eval_step(model, hps, mesh),
                     eval_k))
        per = evaluate_per_class(state.params, loader, pc_step,
                                 hps.num_classes, mesh, multi=pc_multi)
        out["per_class"] = {
            str(c): (None if r is None
                     else {k: round(v, 6) for k, v in sorted(r.items())})
            for c, r in per.items()}
    print(json.dumps(out))
    return 0


def cmd_sample(args) -> int:
    from sketch_rnn_tpu.data import strokes as S
    from sketch_rnn_tpu.parallel import multihost as mh
    from sketch_rnn_tpu.sample import sample, svg_grid
    mh.initialize()  # no-op unless launched as a multi-host cluster
    hps = _resolve_hps(args)
    # usage errors fail before the (expensive) checkpoint restore
    if (args.interpolate or args.reconstruct) and not hps.conditional:
        print("[cli] --interpolate/--reconstruct need a conditional "
              "(encoder) model (hps.conditional=false)", file=sys.stderr)
        return 2
    if args.strokes_out and not (args.interpolate or args.reconstruct):
        print("[cli] --strokes_out archives the endpoint demos' raw "
              "stroke-5 arrays; add --interpolate or --reconstruct",
              file=sys.stderr)
        return 2
    if args.interpolate and args.n < 2:
        # the endpoint contract (frames >= 2) as a usage error, before
        # the expensive restore — an interpolation needs both ends
        print(f"[cli] --interpolate needs -n >= 2 frames, got "
              f"{args.n}", file=sys.stderr)
        return 2
    temps = None
    if args.temperatures:
        if args.interpolate or args.reconstruct:
            print("[cli] --temperatures cannot combine with "
                  "--interpolate/--reconstruct", file=sys.stderr)
            return 2
        try:
            temps = [float(t) for t in args.temperatures.split(",") if t]
        except ValueError:
            print(f"[cli] bad --temperatures {args.temperatures!r}; "
                  f"expected comma-separated floats", file=sys.stderr)
            return 2
        if not temps:
            print("[cli] --temperatures is empty", file=sys.stderr)
            return 2
    model, state, scale, meta = _restore(hps, args.workdir)
    key = jax.random.key(args.seed)
    z = None
    labels = None
    originals = None
    n = args.n
    if args.interpolate or args.reconstruct:
        # multi-task serving parity (ISSUE 15): both demos now ride the
        # SAME endpoint path the serving fleet runs
        # (serve/endpoints.serve_requests: fixed-geometry encode +
        # engine decode with per-request fold_in RNG), so the strokes
        # here are bitwise the `interpolate`/`reconstruct` endpoint's
        # on the same checkpoint/key/serving geometry — the satellite
        # parity pin. --strokes_out archives the raw stroke-5 arrays
        # (normalized model units) for exactly that comparison.
        from sketch_rnn_tpu.serve import Request, serve_requests
        _, valid_l, _, _ = _load_data(hps, args, scale_factor=scale)
        if args.interpolate:
            # --label conditions every frame's decode, exactly like
            # the pre-endpoint path (reconstruction keeps each
            # sketch's own dataset label, also as before)
            reqs = [Request(key=key, endpoint="interpolate",
                            prefix=(valid_l.strokes[0],
                                    valid_l.strokes[1]),
                            frames=n, temperature=args.temperature,
                            label=args.label)]
        else:
            # the reference notebook's reconstruction demo: encode real
            # sketches, decode conditioned on their posterior means, and
            # show inputs (top row) against reconstructions (bottom row)
            if n > len(valid_l.strokes):
                print(f"[cli] requested {n} reconstructions but the "
                      f"valid split holds {len(valid_l.strokes)}; "
                      f"clamping", file=sys.stderr)
                n = len(valid_l.strokes)
            reqs = [Request(key=jax.random.fold_in(key, i),
                            endpoint="reconstruct",
                            prefix=valid_l.strokes[i],
                            temperature=args.temperature,
                            label=(int(valid_l.labels[i])
                                   if hps.num_classes > 0 else 0))
                    for i in range(n)]
            originals = []
            for i in range(n):
                s3 = np.array(valid_l.strokes[i], np.float32)
                s3[:, 0:2] *= scale
                originals.append(s3)
        out = serve_requests(model, hps, state.params, reqs,
                             greedy=args.greedy)
        by_uid = {r.uid: r for r in out["results"]}
        if args.interpolate:
            strokes5 = list(by_uid[0].frames)
            lengths = np.asarray([len(s) for s in strokes5])
        else:
            strokes5 = [by_uid[i].strokes5 for i in range(n)]
            lengths = np.asarray([by_uid[i].length for i in range(n)])
        if args.strokes_out and mh.is_primary():
            # primary-only, like the SVG write below: hosts hold
            # different loader stripes, and a torn parity artifact is
            # worse than none
            np.savez(args.strokes_out,
                     **{f"strokes5_{i:03d}": s
                        for i, s in enumerate(strokes5)})
            print(f"[cli] wrote raw stroke-5 arrays to "
                  f"{args.strokes_out}", file=sys.stderr)
        sketches = []
        for s5 in strokes5:
            s3 = S.to_normal_strokes(np.asarray(s5))
            s3[:, 0:2] *= scale
            sketches.append(s3)
        if mh.is_primary():
            if originals is not None:
                cols = max(1, min(args.cols, n))
                blank = np.zeros((0, 3), np.float32)
                cells = []
                for lo in range(0, n, cols):
                    for row in (originals[lo:lo + cols],
                                sketches[lo:lo + cols]):
                        cells += row + [blank] * (cols - len(row))
                svg_grid(cells, cols=cols, path=args.output)
                print(f"[cli] wrote {n} input|reconstruction pairs "
                      f"(lengths {[int(x) for x in lengths]}) to "
                      f"{args.output}")
            else:
                svg_grid(sketches, cols=args.cols, path=args.output)
                print(f"[cli] wrote {len(sketches)} interpolation "
                      f"frames to {args.output}")
        return 0
    if labels is None and hps.num_classes > 0:
        labels = np.full((n,), args.label, np.int32)
    if temps is not None:
        # the notebook's temperature-sweep figure: one grid row of n
        # samples per temperature, SAME latents in every row so the rows
        # differ only by tau (conditional models: one prior z batch drawn
        # up front; the per-row keys still vary the in-row MDN draws).
        # The compiled sampler is reused across rows — temperature is a
        # runtime scalar.
        kz, key = jax.random.split(key)
        if hps.conditional:
            z = jax.random.normal(kz, (n, hps.z_size))
        sketches = []
        for i, tau in enumerate(temps):
            sk, _ = sample(model, state.params, hps,
                           jax.random.fold_in(key, i), n=n,
                           temperature=tau, z=z, labels=labels,
                           scale_factor=scale, greedy=args.greedy)
            sketches += sk
        if mh.is_primary():
            svg_grid(sketches, cols=n, path=args.output)
            print(f"[cli] wrote {len(temps)} temperature rows "
                  f"({temps}) x {n} sketches to {args.output}")
        return 0
    sketches, lengths = sample(model, state.params, hps, key, n=n,
                               temperature=args.temperature, z=z,
                               labels=labels, scale_factor=scale,
                               greedy=args.greedy)
    # multi-host: only the primary writes (hosts hold different loader
    # stripes, so concurrent writes to a shared path would tear the file)
    if mh.is_primary():
        svg_grid(sketches, cols=args.cols, path=args.output)
        print(f"[cli] wrote {n} sketches (lengths "
              f"{[int(x) for x in lengths]}) to {args.output}")
    return 0


def cmd_serve_bench(args) -> int:
    """Serve a burst of generation requests through the continuous-
    batching engine and print aggregate serving metrics as JSON.

    With ``--random_init`` the model is freshly initialized (engine
    plumbing / throughput benchmarking without a checkpoint); otherwise
    the latest checkpoint in ``--workdir`` is restored like ``sample``.
    """
    hps = _resolve_hps(args)
    # decode-kernel / quantization flavor (ISSUE 17): flags override
    # the hps fields, and an unsupported cell for the pallas kernel
    # fails HERE with the refusal naming the scan fallback — before
    # the expensive restore/compile, like every usage check below
    if args.decode_kernel:
        hps = hps.replace(decode_kernel=args.decode_kernel)
    if args.quantize:
        hps = hps.replace(serve_quantize=args.quantize)
    if hps.decode_kernel == "pallas":
        from sketch_rnn_tpu.ops.pallas_decode import check_cell_kind
        try:
            check_cell_kind(hps.dec_model)
        except ValueError as e:
            print(f"[cli] {e}", file=sys.stderr)
            return 2
    # speculative decoding (ISSUE 18): usage input fails HERE, before
    # the restore/compile, like every flavor/SLO check around it
    if not args.draft_ckpt:
        if args.draft_depth or args.draft_tol >= 0 or args.draft_noise:
            print("[cli] --draft_depth/--draft_tol/--draft_noise "
                  "configure speculative decoding; add --draft_ckpt "
                  "DIR (a distilled draft run — `cli distill` writes "
                  "<workdir>/draft) or --draft_ckpt self",
                  file=sys.stderr)
            return 2
    else:
        if hps.decode_kernel == "pallas":
            print("[cli] speculative decoding is scan-only (the "
                  "draft+verify program is one combined lax.scan); "
                  "drop --draft_ckpt or use --decode_kernel scan",
                  file=sys.stderr)
            return 2
        if args.draft_depth < 0:
            print(f"[cli] --draft_depth must be >= 0, got "
                  f"{args.draft_depth}", file=sys.stderr)
            return 2
        if args.draft_ckpt == "self":
            # self-draft: the teacher's own decode weights in draft
            # geometry (optionally noised) — the zero-training demo.
            # Force the matching geometry; self_draft_params refuses
            # anything else.
            if hps.dec_model != "lstm":
                print(f"[cli] --draft_ckpt self needs dec_model=lstm "
                      f"(got {hps.dec_model!r}); distill a real draft "
                      f"instead", file=sys.stderr)
                return 2
            hps = hps.replace(draft_rnn_size=hps.dec_rnn_size,
                              draft_num_mixture=0)
        else:
            if args.draft_noise:
                print("[cli] --draft_noise perturbs a SELF-draft; a "
                      "distilled draft is served as trained",
                      file=sys.stderr)
                return 2
            from sketch_rnn_tpu.utils import runinfo
            man = runinfo.read_manifest(args.draft_ckpt)
            if man is None:
                print(f"[cli] --draft_ckpt {args.draft_ckpt}: no "
                      f"RUN.json manifest (want the distill run dir, "
                      f"e.g. <teacher_workdir>/draft)", file=sys.stderr)
                return 2
            lineage = man.get("distill") or {}
            if lineage:
                # the lineage block pins the draft geometry the engine
                # must rebuild to load this checkpoint
                hps = hps.replace(
                    draft_rnn_size=int(lineage.get(
                        "draft_rnn_size", hps.draft_rnn_size)),
                    draft_num_mixture=int(lineage.get(
                        "draft_num_mixture", hps.draft_num_mixture)))
    # SLO specs, admission classes and the metrics port are usage
    # input: fail before the (expensive) restore/compile, like sample's
    # flag validation — a taken port must not cost the whole warmup
    # first. The server is harmless this early (it serves meta-only
    # until the core is configured below).
    slo_tracker = None
    if args.slo:
        from sketch_rnn_tpu.serve.slo import SLOTracker, parse_slo
        try:
            slo_tracker = SLOTracker([parse_slo(s) for s in args.slo])
        except ValueError as e:
            print(f"[cli] {e}", file=sys.stderr)
            return 2
    if args.fleet is None and (args.rate or args.classes):
        print("[cli] --rate/--classes configure the fleet scheduler; "
              "add --fleet", file=sys.stderr)
        return 2
    if getattr(args, "watch_ckpt", ""):
        # rollout needs a fleet with a survivor while one replica is
        # off-placement in the canary/walk — validated here, before
        # the expensive restore/compile (the --slo precedent)
        if args.fleet is None or args.fleet < 2:
            print("[cli] --watch_ckpt needs --fleet >= 2 (the rollout "
                  "walk retires one replica at a time; survivors keep "
                  "serving)", file=sys.stderr)
            return 2
    if args.fleet is not None:
        if args.static:
            print("[cli] --static (freeze-until-batch-done) has no "
                  "fleet equivalent; drop one of --static/--fleet",
                  file=sys.stderr)
            return 2
        from sketch_rnn_tpu.serve.admission import parse_admission_classes
        try:
            parse_admission_classes(args.classes)
        except ValueError as e:
            print(f"[cli] {e}", file=sys.stderr)
            return 2
        if args.rate < 0:
            print(f"[cli] --rate must be >= 0, got {args.rate}",
                  file=sys.stderr)
            return 2
        if args.fleet > len(jax.devices()):
            # usage input fails BEFORE the expensive restore/compile,
            # like the SLO/class specs above
            print(f"[cli] --fleet {args.fleet} needs {args.fleet} "
                  f"devices but only {len(jax.devices())} are "
                  f"available", file=sys.stderr)
            return 2
    # multi-task endpoint specs (ISSUE 15): validated HERE, before the
    # checkpoint restore — the --slo/--classes precedent. An
    # unconditional checkpoint rejects encoder endpoints with one line
    # naming hps.conditional.
    endpoints_cfg = None
    if args.endpoints or args.endpoint_mix:
        if args.fleet is None:
            print("[cli] --endpoints/--endpoint_mix configure the "
                  "multi-task fleet; add --fleet", file=sys.stderr)
            return 2
        from sketch_rnn_tpu.serve.admission import \
            parse_admission_classes
        from sketch_rnn_tpu.serve.endpoints import (ENCODER_ENDPOINTS,
                                                    ENDPOINTS,
                                                    parse_endpoint_specs)
        from sketch_rnn_tpu.serve.loadgen import parse_endpoint_mix
        try:
            ep_map, ep_classes = parse_endpoint_specs(
                args.endpoints,
                classes=parse_admission_classes(args.classes))
            mix = (parse_endpoint_mix(args.endpoint_mix)
                   if args.endpoint_mix else
                   tuple((e, 1.0) for e in ENDPOINTS if e in ep_map)
                   or (("generate", 1.0),))
        except ValueError as e:
            print(f"[cli] {e}", file=sys.stderr)
            return 2
        bad = [name for name, _ in mix if name not in ENDPOINTS]
        if bad:
            print(f"[cli] unknown endpoint(s) {bad} in "
                  f"--endpoint_mix; want {ENDPOINTS}", file=sys.stderr)
            return 2
        unrouted = [name for name, _ in mix
                    if name not in ep_map and len(ep_classes) > 1]
        if unrouted:
            print(f"[cli] endpoint(s) {unrouted} in the mix have no "
                  f"class route; add --endpoints "
                  f"{unrouted[0]}=CLASS", file=sys.stderr)
            return 2
        enc_needed = sorted(set(name for name, _ in mix)
                            & set(ENCODER_ENDPOINTS))
        if enc_needed and not hps.conditional:
            print(f"[cli] endpoint(s) {enc_needed} need the "
                  f"bidirectional encoder but this checkpoint is "
                  f"unconditional (hps.conditional=false)",
                  file=sys.stderr)
            return 2
        if args.frames < 2:
            print(f"[cli] --frames must be >= 2, got {args.frames}",
                  file=sys.stderr)
            return 2
        from sketch_rnn_tpu.serve.fleet import default_pool_cap
        pool_cap = default_pool_cap(args.slots or hps.serve_slots)
        if any(name == "interpolate" for name, _ in mix) \
                and args.frames > pool_cap:
            # the grid must fit one micro-burst — fail HERE, not in
            # the loadgen replay thread after the restore
            print(f"[cli] --frames {args.frames} exceeds the fleet's "
                  f"pool_cap {pool_cap} (4x slots); shrink --frames "
                  f"or raise --slots", file=sys.stderr)
            return 2
        endpoints_cfg = {"map": ep_map, "classes": ep_classes,
                         "mix": mix, "frames": args.frames,
                         "encoder": bool(enc_needed)}
    # multi-tenant serving (ISSUE 19): usage input fails HERE, before
    # the restore/compile, like every spec check above
    tenants_cfg = None
    if args.tenants or args.tenant_mix or args.tenant_cap \
            or args.tenant_slo:
        if args.fleet is None:
            print("[cli] --tenants/--tenant_mix/--tenant_cap/"
                  "--tenant_slo configure the multi-tenant fleet; add "
                  "--fleet", file=sys.stderr)
            return 2
        if args.tenants < 2:
            print(f"[cli] --tenants needs >= 2 tenants (got "
                  f"{args.tenants}); a single-tenant fleet is just "
                  f"--fleet", file=sys.stderr)
            return 2
        if args.draft_ckpt:
            print("[cli] --tenants serves value-paged params, which "
                  "excludes speculative decoding (the draft+verify "
                  "program bakes both trees); drop --draft_ckpt",
                  file=sys.stderr)
            return 2
        if args.tenant_cap < 0:
            print(f"[cli] --tenant_cap must be >= 0, got "
                  f"{args.tenant_cap}", file=sys.stderr)
            return 2
        from sketch_rnn_tpu.serve.admission import parse_tenant_slos
        from sketch_rnn_tpu.serve.loadgen import parse_tenant_mix
        names = [f"tn{i}" for i in range(args.tenants)]
        try:
            tslos = parse_tenant_slos(args.tenant_slo)
            tmix = (parse_tenant_mix(args.tenant_mix)
                    if args.tenant_mix
                    else tuple((t, 1.0) for t in [""] + names))
        except ValueError as e:
            print(f"[cli] {e}", file=sys.stderr)
            return 2
        known = set(names) | {""}
        bad = sorted({t for t, _ in tmix} - known) \
            + sorted(set(tslos) - known)
        if bad:
            print(f"[cli] unknown tenant(s) {bad} in --tenant_mix/"
                  f"--tenant_slo; --tenants {args.tenants} registers "
                  f"tn0..tn{args.tenants - 1} ('' = base)",
                  file=sys.stderr)
            return 2
        tenants_cfg = {"names": names, "mix": tmix,
                       "cap": args.tenant_cap, "slos": tslos}
    rc = _arm_faults(args)  # chaos runs: bad specs fail before binding
    if rc:
        return rc
    from sketch_rnn_tpu.utils import faults
    server = None
    # never leak an armed plan to in-process callers: the finally
    # covers everything after arming, including a failed port bind
    try:
        if args.metrics_port is not None:
            from sketch_rnn_tpu.serve.metrics_http import MetricsServer
            try:
                server = MetricsServer(port=args.metrics_port,
                                       slo=slo_tracker).start()
            except OSError as e:
                print(f"[cli] cannot bind --metrics_port "
                      f"{args.metrics_port}: {e}", file=sys.stderr)
                return 2
            print(f"[metrics] serving /metrics and /healthz on "
                  f"http://127.0.0.1:{server.port} (scrape while the "
                  f"bench runs, e.g. curl :{server.port}/metrics)",
                  file=sys.stderr)
        return _serve_bench_run(args, hps, slo_tracker, server,
                                endpoints_cfg=endpoints_cfg,
                                tenants_cfg=tenants_cfg)
    finally:
        faults.disable()
        if server is not None:
            server.stop()


def _serve_telemetry_start(args):
    """Enable the telemetry core (+ device-memory sampler) for an
    observed serve run. Returns ``(trace_dir, tel, tele, mem_sampler)``
    (all None/''-ish when neither --trace_dir nor --metrics_port asked
    for observability).

    MUST be called AFTER every engine/fleet warmup (ISSUE 9 satellite:
    this ordering was inlined in the single-engine path only, and a
    second serving path could silently compile inside the measured
    window): the exported per-request lifecycle then covers exactly the
    measured run, and the JitCompileProbe — which remembers geometries
    seen while disabled — reports the warm programs as cache HITS
    instead of recompiling. --metrics_port alone (no --trace_dir) still
    enables the core — the /metrics endpoint renders its counters/
    histograms live and would otherwise serve only meta + SLO series —
    but exports no files at exit.
    """
    trace_dir = getattr(args, "trace_dir", "") or None
    tel = None
    tele = None
    mem_sampler = None
    if trace_dir or args.metrics_port is not None:
        from sketch_rnn_tpu.parallel.multihost import topology
        from sketch_rnn_tpu.utils import telemetry as tele
        topo = topology()
        tel = tele.configure(trace_dir=trace_dir,
                             process_index=topo["process_index"],
                             host_count=topo["host_count"])
        # sampled device-memory gauges: /metrics shows live/peak HBM
        # while the burst runs, so slot-count choices are
        # memory-visible (no-op on stat-less backends)
        mem_sampler = tele.MemorySampler().start()
        mem_sampler.phase = "serve"
    return trace_dir, tel, tele, mem_sampler


def _serve_telemetry_abort(trace_dir, tel, tele, mem_sampler) -> None:
    """Crash-path teardown: a mid-run failure still leaves the trace
    that explains it (the train loop's post-mortem discipline);
    best-effort so an export failure never masks the real error."""
    if mem_sampler is not None:
        mem_sampler.stop()
    if tel is not None:
        if trace_dir:
            try:
                tel.export()
            except Exception:  # noqa: BLE001
                pass
        tele.disable()


def _serve_bench_fleet(args, hps, model, state_params, requests,
                       slo_tracker, server=None, endpoints_cfg=None,
                       ckpt_id: str = "", template_state=None,
                       draft_kw=None, tenants_cfg=None,
                       tenant_store=None):
    """The fleet measured section: build + warm the fleet, THEN enable
    telemetry (via the shared helper — the can't-recompile-into-the-
    window ordering), then replay the open-loop schedule and drain.

    With ``endpoints_cfg`` (ISSUE 15) the fleet routes each request's
    endpoint to its admission class (``--endpoints``), the warm pass
    also compiles the per-replica encode programs and the init-capable
    chunk geometry, and the report grows the per-endpoint latency
    table.

    Returns ``(out_metrics, fleet_report, request_rows,
    telemetry_handles)``.
    """
    from sketch_rnn_tpu.serve.admission import parse_admission_classes
    from sketch_rnn_tpu.serve.fleet import ServeFleet
    from sketch_rnn_tpu.serve.loadgen import (OpenLoopLoadGen,
                                              poisson_arrivals)

    if endpoints_cfg is not None:
        classes = endpoints_cfg["classes"]
        endpoint_classes = endpoints_cfg["map"]
    else:
        classes = parse_admission_classes(args.classes)
        endpoint_classes = None
    cls_order = [c.name for c in sorted(classes.values(),
                                        key=lambda c: c.priority)]
    tenant_kw = {}
    if tenant_store is not None:
        # value-paged multi-tenant serving (ISSUE 19): the fleet holds
        # ONE base tree + delta pages; tenant swaps are device_puts
        tenant_kw = dict(tenants=tenant_store,
                         tenant_cap=tenants_cfg["cap"],
                         tenant_slos=tenants_cfg["slos"])
    fleet = ServeFleet(model, hps, state_params,
                       replicas=args.fleet, slots=args.slots,
                       chunk=args.chunk, greedy=args.greedy,
                       classes=classes, slo=slo_tracker,
                       endpoint_classes=endpoint_classes,
                       ckpt_id=ckpt_id, **tenant_kw,
                       **(draft_kw or {}))
    if server is not None:
        # /healthz now answers from the LIVE fleet: a replica death
        # mid-run flips the verdict to degraded (ISSUE 10)
        server.health_source = fleet.health
    fleet.warm(requests[0],
               endpoints=bool(endpoints_cfg
                              and endpoints_cfg.get("encoder")))
    rollout_ctl = None
    watch_dir = getattr(args, "watch_ckpt", "") or None
    if watch_dir:
        # zero-downtime rollout (ISSUE 16): follow the training run's
        # checkpoint dir live — each new complete checkpoint is
        # validated, canaried bitwise on a retired replica, then
        # walked across the fleet; /healthz reports `rolling`, a bad
        # candidate quarantines or rolls back. The watcher thread dies
        # with fleet.close() (the controller join is wired there).
        import dataclasses as _dc

        from sketch_rnn_tpu.serve.rollout import RolloutController
        from sketch_rnn_tpu.train.state import make_train_state
        template = (template_state if template_state is not None
                    else make_train_state(model, hps,
                                          jax.random.key(0)))
        canary = [_dc.replace(r, uid=None, max_len=8)
                  for r in requests[:min(4, len(requests))]]
        rollout_ctl = RolloutController(fleet, model, hps, template,
                                        canary, slo=slo_tracker)
        rollout_ctl.watch(watch_dir)
    handles = _serve_telemetry_start(args)
    try:
        for i, r in enumerate(requests):
            r.uid = i

        def _submit(i):
            if endpoints_cfg is not None:
                # the endpoint routes to its class (fleet.submit maps)
                fleet.submit(requests[i])
            else:
                fleet.submit(requests[i],
                             cls=cls_order[i % len(cls_order)])

        with fleet:
            gen = OpenLoopLoadGen(
                poisson_arrivals(len(requests), args.rate, args.seed),
                _submit).start()
            gen.join()
            fleet.drain()
            if rollout_ctl is not None:
                # settle any in-flight walk before summarizing, then
                # record the lineage contract for RUN.json
                rollout_ctl.join()
            fsum = fleet.summary()
            if rollout_ctl is not None:
                fsum["serving_ckpt_id"] = fleet.serving_ckpt_id
                fsum["ckpt_lineage"] = rollout_ctl.lineage()
                fsum["rollout_log"] = list(rollout_ctl.rollout_log)
            rows = [{"uid": uid, "replica": rec["replica"],
                     "class": rec.get("class"),
                     "endpoint": rec.get("endpoint", "generate"),
                     "tenant": rec.get("tenant", ""),
                     "queue_pos": rec.get("queue_pos"),
                     "steps": rec["result"].steps,
                     "length": rec["result"].length,
                     "queue_wait_s": rec["result"].queue_wait_s,
                     "decode_s": rec["result"].decode_s,
                     "latency_s": rec["result"].latency_s}
                    for uid, rec in sorted(fleet.results.items())]
    except BaseException:
        _serve_telemetry_abort(*handles)
        raise
    fsum["offered_rate"] = args.rate
    fsum["loadgen_max_lag_s"] = round(gen.max_lag_s, 6)
    out_metrics = {
        "completed": fsum["completed"],
        "wall_s": fsum["wall_s"],
        "sketches_per_sec": fsum["sketches_per_sec"],
        "requests_shed": fsum["shed"],
        "shed_frac": fsum["shed_frac"],
        "latency_p50_s": fsum["latency"]["p50_s"],
        "latency_p95_s": fsum["latency"]["p95_s"],
        "latency_p99_s": fsum["latency"]["p99_s"],
    }
    if endpoints_cfg is not None:
        # the per-endpoint latency table (ISSUE 15): the mixed-endpoint
        # fleet's headline surface, next to the per-class SLO verdicts
        out_metrics["latency_by_endpoint"] = \
            fsum["latency_by_endpoint"]
        fsum["endpoint_mix"] = [list(m) for m in endpoints_cfg["mix"]]
        fsum["endpoint_classes"] = dict(endpoints_cfg["map"])
    if tenant_store is not None:
        # the per-tenant surface (ISSUE 19): latency/SLO/shed split by
        # tenant + the paged-adapter memory table, straight from the
        # fleet summary's tenants block
        out_metrics["latency_by_tenant"] = \
            fsum["tenants"]["latency_by_tenant"]
        out_metrics["tenant_swaps"] = fsum["tenants"]["tenant_swaps"]
        fsum["tenant_mix"] = [list(m) for m in tenants_cfg["mix"]]
    if slo_tracker is not None:
        out_metrics["slo"] = slo_tracker.summary()
    return out_metrics, fsum, rows, handles


def _build_endpoint_requests(args, hps, scale, n, kz, kreq,
                             endpoints_cfg):
    """The seeded mixed-endpoint request list (ISSUE 15): the SHARED
    ``serve/endpoints.build_mix_requests`` recipe (the acceptance
    bench draws the identical stream) over prefixes from the valid
    split (``--synthetic``/``--data_dir``) or a synthetic corpus."""
    from sketch_rnn_tpu.serve.endpoints import build_mix_requests

    mix = endpoints_cfg["mix"]
    pool, pool_labels = [], None
    if any(name != "generate" for name, _ in mix):
        if args.synthetic or args.data_dir:
            _, valid_l, _, _ = _load_data(hps, args, scale_factor=scale)
            pool, pool_labels = valid_l.strokes, valid_l.labels
        else:
            # --random_init without a corpus: a normalized synthetic
            # prefix pool (the loader computes its own scale — the
            # random-init params have no data contract to honor)
            from sketch_rnn_tpu.data.loader import synthetic_loader
            loader, _ = synthetic_loader(hps, max(64, min(2 * n, 512)),
                                         seed=args.seed)
            pool, pool_labels = loader.strokes, loader.labels
    z = None
    if hps.conditional:
        z = np.asarray(jax.random.normal(kz, (n, hps.z_size)),
                       np.float32)
    return build_mix_requests(hps, mix, n, args.seed, kreq, z, pool,
                              pool_labels,
                              frames=endpoints_cfg["frames"],
                              temperature=args.temperature,
                              default_label=args.label)


def _tenant_store_of(state_params, names, seed, ckpt_id):
    """Build the multi-tenant adapter store for ``--tenants`` (ISSUE
    19): N seeded stand-in fine-tunes registered as sparse int8-delta
    pages against the served tree. tn0 is a bitwise copy (the
    zero-delta proof rides every run), tn1 nudges every float leaf
    (the full quantized-delta path), the rest nudge only the output
    head — the realistic per-customer fine-tune shape."""
    from sketch_rnn_tpu.serve.tenants import TenantStore

    base = jax.tree_util.tree_map(lambda a: np.asarray(a), state_params)

    def perturb(want, pseed):
        rng = np.random.default_rng(pseed)

        def walk(node, path=""):
            if isinstance(node, dict):
                return {k: walk(v, f"{path}/{k}" if path else k)
                        for k, v in node.items()}
            a = np.asarray(node)
            hit = want is True or any(w in path for w in want)
            if (hit and np.issubdtype(a.dtype, np.floating)
                    and a.ndim >= 1):
                d = 0.01 * rng.standard_normal(a.shape)
                return (a + d).astype(a.dtype)
            return a
        return walk(base)

    store = TenantStore(base, base_ckpt_id=ckpt_id or "base")
    for i, t in enumerate(names):
        want = [] if i == 0 else (True if i == 1
                                  else ["out_w", "out_b"])
        rep = store.register(t, perturb(want, seed + 1000 + i))
        print(f"[cli] tenant {t}: {rep['pages']} adapter page(s), "
              f"{rep['nbytes']} bytes", file=sys.stderr)
    mt = store.memory_table()
    print(f"[cli] adapter memory: resident {mt['resident_bytes']} / "
          f"{mt['tenants']} full trees {mt['full_bytes']} "
          f"(ratio {mt['ratio']:.3f})", file=sys.stderr)
    return store


def _serve_bench_run(args, hps, slo_tracker, server,
                     endpoints_cfg=None, tenants_cfg=None) -> int:
    """The body of ``serve-bench`` after usage validation; the caller
    owns the metrics server's lifetime (stopped on every exit path)."""
    import time

    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.serve import Request, ServeEngine
    from sketch_rnn_tpu.train.metrics import MetricsWriter

    if args.random_init:
        model = SketchRNN(hps)
        state_params = model.init_params(jax.random.key(args.seed))
        scale = 1.0
        state = None
        init_ckpt_id = ""
    else:
        model, state, scale, _ = _restore(hps, args.workdir)
        state_params = state.params
        from sketch_rnn_tpu.train.checkpoint import ckpt_id_of
        init_ckpt_id = ckpt_id_of(int(state.step))
    # quantized serving (ISSUE 17): round the initial params through
    # the serving precision and stamp the serving identity, exactly as
    # rollout admission does for every later hot-swap — the engine /
    # fleet / canary all see the same dequantized f32 tree
    qreport = []
    if hps.serve_quantize != "float32":
        from sketch_rnn_tpu.serve.quantize import (quantize_for_serving,
                                                   stamp_ckpt_id)
        state_params, qreport = quantize_for_serving(
            state_params, hps.serve_quantize)
        init_ckpt_id = stamp_ckpt_id(init_ckpt_id, hps.serve_quantize)
    # speculative decoding (ISSUE 18): pair the serving params with a
    # draft tree. The draft stays f32 even under --quantize — it is
    # tiny, and the acceptance rule's bitwise contract is against the
    # (already-quantize-rounded) verifier tree above, so draft
    # precision only moves the acceptance RATE, never the strokes.
    draft_params = None
    if getattr(args, "draft_ckpt", ""):
        from sketch_rnn_tpu.models.draft import (DraftDecoder,
                                                 self_draft_params)
        if args.draft_ckpt == "self":
            noise = getattr(args, "draft_noise", 0.0)
            draft_params = self_draft_params(
                state_params, hps,
                key=jax.random.key(args.seed + 1) if noise else None,
                noise=noise)
        else:
            from sketch_rnn_tpu.train import (make_train_state,
                                              restore_checkpoint)
            dtemplate = make_train_state(DraftDecoder(hps), hps,
                                         jax.random.key(0))
            dstate, _, dmeta = restore_checkpoint(args.draft_ckpt,
                                                  dtemplate)
            draft_params = dstate.params
            print(f"[cli] speculative: draft from {args.draft_ckpt} "
                  f"step {dmeta['step']}, D="
                  f"{args.draft_depth or hps.draft_depth}, tol="
                  f"{args.draft_tol if args.draft_tol >= 0 else hps.draft_tol}",
                  file=sys.stderr)
    draft_kw = dict(
        draft_params=draft_params,
        draft_depth=getattr(args, "draft_depth", 0),
        draft_tol=(args.draft_tol if getattr(args, "draft_tol", -1.0)
                   >= 0 else None))
    key = jax.random.key(args.seed)
    kz, kreq = jax.random.split(key)
    n = args.n
    if endpoints_cfg is not None:
        requests = _build_endpoint_requests(args, hps, scale, n, kz,
                                            kreq, endpoints_cfg)
    else:
        z = None
        if hps.conditional:
            z = np.asarray(jax.random.normal(kz, (n, hps.z_size)),
                           np.float32)
        requests = [
            Request(key=jax.random.fold_in(kreq, i),
                    z=None if z is None else z[i],
                    label=args.label, temperature=args.temperature)
            for i in range(n)
        ]
    tenant_store = None
    if tenants_cfg is not None:
        # register the tenant fleet's adapter pages against the SERVED
        # tree (post-quantize: pages delta the tree replicas hold) and
        # stamp each request's tenant from the seeded mix stream
        from sketch_rnn_tpu.serve.loadgen import tenant_mix_ids
        tenant_store = _tenant_store_of(state_params,
                                        tenants_cfg["names"],
                                        args.seed, init_ckpt_id)
        tmix = tenants_cfg["mix"]
        tids = tenant_mix_ids(n, tmix, args.seed)
        for i, r in enumerate(requests):
            r.tenant = tmix[int(tids[i])][0]
    writer = (MetricsWriter(args.workdir, name="serve")
              if args.log_metrics else None)
    import dataclasses
    fleet_report = None
    t0 = time.time()
    if args.fleet is not None:
        # mesh-replicated fleet (ISSUE 9): R device-pinned engines, one
        # SLA-aware scheduler, open-loop Poisson arrivals at --rate.
        # The fleet feeds the SLO tracker class-keyed endpoints (one
        # per admission class), so /healthz judges the classes the
        # operator declared.
        out_metrics, fleet_report, rows, handles = _serve_bench_fleet(
            args, hps, model, state_params, requests, slo_tracker,
            server=server, endpoints_cfg=endpoints_cfg,
            ckpt_id=init_ckpt_id, template_state=state,
            draft_kw=draft_kw, tenants_cfg=tenants_cfg,
            tenant_store=tenant_store)
        trace_dir, tel, tele, mem_sampler = handles
        slots_v, chunk_v = fleet_report["slots"], fleet_report["chunk"]
        if writer is not None:
            for i, row in enumerate(rows):
                writer.write(i + 1, row)
    else:
        engine = ServeEngine(model, hps, state_params, slots=args.slots,
                             chunk=args.chunk, greedy=args.greedy,
                             **draft_kw)
        slots_v, chunk_v = engine.slots, engine.chunk
        # warmup: compile outside the timed run. The chunk program is
        # shape-specialized on the request-pool size, so the warm burst
        # must have the SAME request count — clones capped at one step.
        engine.run([dataclasses.replace(r, uid=None, max_len=1)
                    for r in requests])
        # telemetry configured AFTER warmup (shared helper — ISSUE 9
        # satellite: the ordering is the helper's contract now)
        trace_dir, tel, tele, mem_sampler = _serve_telemetry_start(args)
        # health & SLO layer (ISSUE 7): the tracker is fed by the
        # engine per completed request; the (already-bound) metrics
        # server exposes the LIVE /metrics + /healthz view of this run,
        # and the final scrape is archived as metrics.prom beside the
        # trace (or in the workdir) — the checkable artifact that the
        # endpoint and the end-of-run summary reconcile.
        t0 = time.time()
        try:
            out = engine.run(requests, recycle=not args.static,
                             metrics_writer=writer, slo=slo_tracker)
        except BaseException:
            _serve_telemetry_abort(trace_dir, tel, tele, mem_sampler)
            raise
        out_metrics = out["metrics"]
    if mem_sampler is not None:
        mem_sampler.stop()
    prom_path = None
    if server is not None:
        # archive the run's final scrape through the real HTTP
        # surface (not a render_prometheus call): the artifact
        # proves endpoint wiring end to end. Best-effort — a
        # scrape/write hiccup must not discard the completed run's
        # report and trace
        try:
            import urllib.request
            prom_dir = trace_dir or args.workdir
            os.makedirs(prom_dir, exist_ok=True)
            prom_path = os.path.join(prom_dir, "metrics.prom")
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/metrics",
                    timeout=10) as resp:
                scrape = resp.read().decode()
            with open(prom_path, "w") as f:
                f.write(scrape)
            print(f"[metrics] archived final scrape to {prom_path}",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            prom_path = None
            print(f"[metrics] WARNING: could not archive the final "
                  f"scrape: {e!r}", file=sys.stderr)
    if slo_tracker is not None:
        # an SLO that matched nothing (endpoint typo, or a future
        # endpoint this engine does not serve) would otherwise report
        # vacuous compliance forever — say so where the operator looks
        for key, rec in sorted(slo_tracker.summary().items()):
            if rec["total"] == 0:
                print(f"[slo] WARNING: {key} matched no completed "
                      f"request (endpoint {rec['endpoint']!r} unseen) "
                      f"— its compliance is vacuous", file=sys.stderr)
    run_id = None
    if tel is not None:
        run_id = tel.run_id
        exported = {}
        if trace_dir:
            exported = paths = tel.export()
            print(f"[telemetry] wrote {paths['jsonl']} and "
                  f"{paths['chrome']} (read with scripts/trace_report.py "
                  f"or Perfetto; per-request span trees / critical-path "
                  f"attribution with scripts/trace_query.py "
                  f"[--request UID])", file=sys.stderr)
        tele.disable()  # restore the process default
        # run manifest (ISSUE 8): the artifact index that joins this
        # bench's trace, prom scrape and report on one run_id. Only
        # for observed runs (trace/metrics enabled) — the no-flags
        # invisibility contract writes no files.
        from sketch_rnn_tpu.utils import runinfo
        man_dir = trace_dir or args.workdir
        artifacts = {k: v for k, v in exported.items()}
        if prom_path:
            artifacts["metrics_prom"] = prom_path
        if args.log_metrics:
            artifacts["serve_metrics"] = [
                os.path.join(args.workdir, f"serve_metrics.{e}")
                for e in ("csv", "jsonl")]
        extra = {"n_requests": n, "slots": slots_v, "chunk": chunk_v}
        if fleet_report is not None:
            extra["replicas"] = fleet_report["replicas"]
            extra["offered_rate"] = fleet_report["offered_rate"]
            if fleet_report.get("scale_log"):
                # the ISSUE 12 contract: elastic scale decisions and
                # the realized fleet trajectory land in RUN.json
                extra["scale_log"] = fleet_report["scale_log"]
                extra["replicas_live"] = fleet_report["replicas_live"]
            if fleet_report.get("ckpt_lineage"):
                # the ISSUE 16 lineage contract: which checkpoint
                # served which admitted-uid window, plus the rollout
                # state machine's event log
                extra["serving_ckpt_id"] = \
                    fleet_report.get("serving_ckpt_id")
                extra["ckpt_lineage"] = fleet_report["ckpt_lineage"]
                extra["rollout_log"] = fleet_report["rollout_log"]
        runinfo.write_manifest(
            man_dir, kind="serve_bench", hps=hps, run_id=run_id,
            artifacts=artifacts, extra=extra)
    report = {
        "kind": "serve_bench_cli",
        "run_id": run_id,
        "n_requests": n,
        "slots": slots_v,
        "chunk": chunk_v,
        "static": bool(args.static),
        "decode_kernel": hps.decode_kernel,
        "param_dtype": hps.serve_quantize,
        "quantized_tensors": len(qreport),
        "quantize_max_err": max((r["max_err"] for r in qreport),
                                default=0.0),
        "scale_factor": scale,
        "started": t0,
        **out_metrics,
    }
    if fleet_report is not None:
        report["fleet"] = fleet_report
    if server is not None:
        report["metrics_port"] = server.port
        report["metrics_prom"] = prom_path
    # json_safe: a breached p100 SLO carries an infinite burn rate, and
    # the summary line must stay strict JSON for downstream parsers
    from sketch_rnn_tpu.utils.telemetry import json_safe
    print(json.dumps(json_safe(report), allow_nan=False))
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="sketch_rnn_tpu",
                                 description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("train", help="train a model")
    _add_common(p)
    p.add_argument("--bucket_edges", default="",
                   help="length-bucketed execution: comma/semicolon-"
                        "separated bucket pad lengths (e.g. 64,128,250); "
                        "batches pad only to their bucket edge and each "
                        "(B, Tb) geometry gets its own compiled step. "
                        "Empty (default) = exact-parity fixed-T padding. "
                        "Shorthand for --hparams bucket_edges=...")
    p.add_argument("--steps_per_call", type=int, default=0,
                   help="optimizer micro-steps per jitted call (K>1 = "
                        "one lax.scan'd dispatch per K steps; composes "
                        "with --bucket_edges via the bucket-run "
                        "scheduler: geometry runs ride stacked "
                        "[K, B, Tb] transfers). 0 = keep the hparams "
                        "value. Shorthand for --hparams "
                        "steps_per_call=K")
    p.add_argument("--profile", action="store_true",
                   help="capture a jax.profiler device trace of steps "
                        "~10-20 into <workdir>/trace (view with XProf); "
                        "with --trace_dir the device trace lands in "
                        "<trace_dir>/device, aligned to the host spans")
    p.add_argument("--trace_dir", default="",
                   help="enable the unified telemetry runtime and write "
                        "telemetry.jsonl + trace.json (Chrome trace / "
                        "Perfetto) here at exit; read with "
                        "scripts/trace_report.py. Off by default and "
                        "invisible when off")
    p.add_argument("--no_resume", action="store_true",
                   help="start fresh even when <workdir> holds "
                        "checkpoints (default: resume from latest — the "
                        "reference's resume-from-latest contract)")
    p.add_argument("--elastic_hosts", type=int, default=0,
                   help="run as ONE host of an elastic N-host fleet "
                        "(ISSUE 14): launch N processes with "
                        "--elastic_host_id 0..N-1 sharing --rendezvous "
                        "and --workdir. Coordinated global data plan "
                        "(bucketed execution included), per-step "
                        "heartbeat/barrier death detection, and on a "
                        "host death the survivors checkpoint + resume "
                        "at the new topology — final state leaf-bitwise "
                        "an uninterrupted run's. 0 (default) = plain "
                        "single-process training")
    p.add_argument("--elastic_host_id", type=int, default=0,
                   help="this process's host id in the elastic fleet "
                        "(0-based, < --elastic_hosts)")
    p.add_argument("--rendezvous", default="",
                   help="shared directory for the elastic fleet's "
                        "heartbeats, step barriers and topology "
                        "generations (every host must see the same "
                        "path)")
    p.add_argument("--heartbeat_interval", type=float, default=0.25,
                   help="elastic liveness beat period in seconds")
    p.add_argument("--stale_after", type=float, default=2.5,
                   help="a barrier-missing host whose heartbeat file "
                        "stops ADVANCING for this many seconds is "
                        "declared DEAD; hosts still beating (or not "
                        "yet launched — no file) are waited for")
    p.add_argument("--sync_io", action="store_true",
                   help="disable the overlapped goodput runtime "
                        "(async_checkpoint=false,metrics_defer=false): "
                        "blocking saves and eager metric conversion, for "
                        "debugging/bisection; results are identical "
                        "either way, only step time changes")
    p.add_argument("--watchdog", action="store_true",
                   help="arm the training health watchdog "
                        "(train/watchdog.py): each logged metrics row "
                        "is checked for NaN/inf, robust-z loss/grad "
                        "spikes, goodput-phase stalls and throughput "
                        "collapse; a trip warns, emits a telemetry "
                        "incident event and writes "
                        "<workdir>/incident.json (warn-only). Off by "
                        "default and invisible when off")
    p.add_argument("--halt_on_anomaly", action="store_true",
                   help="watchdog trips STOP training (implies "
                        "--watchdog) after forcing a post-mortem "
                        "checkpoint into <workdir>/incident/ — the "
                        "resume directory is never touched, so a "
                        "diverged state cannot wedge resume-from-latest")
    p.add_argument("--serve_fleet", type=int, default=0,
                   help="co-resident train-and-serve (ISSUE 20): run "
                        "an N-replica serving fleet (N >= 2) in THIS "
                        "process while training; every async "
                        "checkpoint the loop saves is rolled out to "
                        "the live fleet through the validated/canaried "
                        "rollout path (admission gate, per-replica "
                        "walk, automatic rollback), /healthz staying "
                        "ok/rolling throughout. The serving lineage "
                        "(which checkpoint served which request "
                        "window) is merged into <workdir>/RUN.json. "
                        "0 (default) = train only")
    p.add_argument("--serve_poll", type=float, default=0.25,
                   help="co-resident checkpoint watcher poll period "
                        "in seconds")
    p.add_argument("--fault_plan", default="",
                   help="chaos run (utils/faults.py): arm deterministic "
                        "fault injection, e.g. 'train.step@12:kind=exit' "
                        "(hard-crash at step 12), 'ckpt.commit@1' "
                        "(transient commit failure, retried), "
                        "'metrics.row@3:kind=nan' (NaN a logged loss). "
                        "Sites: train.step, ckpt.commit, ckpt.torn, "
                        "ckpt.writer, data.batch, metrics.write, "
                        "metrics.row; elastic fleets add host.kill.hNN "
                        "(step-barrier entry of host NN — kind=exit is "
                        "an honest host death) and dcn.collective (the "
                        "barrier exchange itself). Off by default: no "
                        "injection, bitwise-identical runs")
    p.add_argument("--fault_seed", type=int, default=0,
                   help="seed of the fault plan's deterministic "
                        "p=... firing decisions")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("distill",
                       help="distill a draft decoder for speculative "
                            "serving")
    _add_common(p)
    p.add_argument("--steps", type=int, default=0,
                   help="distillation steps (0 = hps.num_steps); the "
                        "run resumes from <workdir>/draft like train "
                        "resumes from <workdir>")
    p.add_argument("--no_resume", action="store_true",
                   help="start the draft fresh even when "
                        "<workdir>/draft holds checkpoints")
    p.set_defaults(fn=cmd_distill)

    p = sub.add_parser("eval", help="evaluate a checkpoint")
    _add_common(p)
    p.add_argument("--split", choices=("valid", "test"), default="valid")
    p.add_argument("--per_class", action="store_true",
                   help="also report metrics per class (the reference "
                        "paper's per-category loss tables); multi-class "
                        "models, single host only")
    p.set_defaults(fn=cmd_eval)

    p = sub.add_parser("sample", help="draw sketches from a checkpoint")
    _add_common(p)
    p.add_argument("-n", type=int, default=10, help="number of sketches")
    p.add_argument("--temperature", type=float, default=0.5)
    p.add_argument("--temperatures", default="",
                   help="comma-separated sweep (e.g. 0.2,0.5,0.8,1.0): "
                        "one grid row of n sketches per temperature")
    p.add_argument("--greedy", action="store_true")
    mode = p.add_mutually_exclusive_group()
    mode.add_argument("--interpolate", action="store_true",
                      help="interpolate between two encoded valid sketches")
    mode.add_argument("--reconstruct", action="store_true",
                      help="encode n valid sketches and decode from their "
                           "latents; output pairs inputs (top row) with "
                           "reconstructions (bottom row)")
    p.add_argument("--label", type=int, default=0,
                   help="class id for class-conditional models")
    p.add_argument("--output", default="samples.svg")
    p.add_argument("--strokes_out", default="",
                   help="with --interpolate/--reconstruct: also write "
                        "the raw stroke-5 arrays (normalized model "
                        "units) to this .npz — the serve-vs-offline "
                        "bitwise parity artifact (the serving "
                        "endpoints produce these exact bytes on the "
                        "same checkpoint/key/serving geometry)")
    p.add_argument("--cols", type=int, default=5)
    p.set_defaults(fn=cmd_sample)

    p = sub.add_parser("serve-bench",
                       help="continuous-batching serving benchmark")
    _add_common(p)
    p.add_argument("-n", type=int, default=64, help="number of requests")
    p.add_argument("--slots", type=int, default=0,
                   help="decoder slots B (0 = hps.serve_slots)")
    p.add_argument("--chunk", type=int, default=0,
                   help="decode steps per dispatch K (0 = hps.serve_chunk)")
    p.add_argument("--temperature", type=float, default=0.5)
    p.add_argument("--label", type=int, default=0,
                   help="class id for class-conditional models")
    p.add_argument("--greedy", action="store_true")
    p.add_argument("--decode_kernel", default="",
                   choices=["", "scan", "pallas"],
                   help="serve decode flavor (ISSUE 17): 'scan' = the "
                        "step-per-iteration lax.scan chunk program "
                        "(bitwise fallback pin), 'pallas' = the fused "
                        "cache-resident decode kernel (whole K-step "
                        "chunk per pallas_call, carry resident in "
                        "VMEM; interpret mode off-TPU; lstm/"
                        "layer_norm cells only). Default: "
                        "hps.decode_kernel")
    p.add_argument("--quantize", default="",
                   choices=["", "float32", "bfloat16", "int8"],
                   help="serving-parameter precision (ISSUE 17): int8 "
                        "= per-tensor symmetric, dequant-on-load "
                        "(error <= scale/2 per element); bfloat16 = "
                        "round-through-bf16. Compute stays f32; the "
                        "served ckpt_id is stamped ':int8'/':bf16'. "
                        "Default: hps.serve_quantize")
    p.add_argument("--draft_ckpt", default="",
                   help="speculative decoding (ISSUE 18): serve with a "
                        "draft decoder proposing D steps per full-model "
                        "verification chunk. DIR = a distilled draft "
                        "run (`cli distill` writes <workdir>/draft; "
                        "the RUN.json lineage pins the draft "
                        "geometry); 'self' = the teacher's own decode "
                        "weights as the draft (zero-training demo, "
                        "lstm only). Strokes are BITWISE the "
                        "non-speculative engine's either way — only "
                        "device steps change. Scan kernel only")
    p.add_argument("--draft_depth", type=int, default=0,
                   help="draft steps per verification chunk D "
                        "(0 = hps.draft_depth)")
    p.add_argument("--draft_tol", type=float, default=-1.0,
                   help="acceptance tolerance on the continuous "
                        "offsets, in model units (< 0 = "
                        "hps.draft_tol); pen state always matches "
                        "exactly or rejects")
    p.add_argument("--draft_noise", type=float, default=0.0,
                   help="with --draft_ckpt self: per-leaf seeded "
                        "Gaussian weight noise, making the self-draft "
                        "an imperfect predictor (deterministic partial "
                        "acceptance — exercise the reject path without "
                        "training a draft)")
    p.add_argument("--static", action="store_true",
                   help="disable slot recycling (freeze-until-batch-done "
                        "schedule, for comparison)")
    p.add_argument("--fleet", type=int, nargs="?", const=0, default=None,
                   help="serve through a mesh-replicated fleet of N "
                        "device-pinned engines (bare/0 = one per "
                        "device): one host scheduler, SLA-aware "
                        "admission (least-loaded placement, "
                        "shed-on-overload), per-replica queues")
    p.add_argument("--rate", type=float, default=0.0,
                   help="open-loop Poisson arrival rate in requests/sec "
                        "for --fleet (deterministic seeded schedule, "
                        "decoupled from completions; 0 = closed burst: "
                        "every request arrives at t=0)")
    p.add_argument("--classes", action="append", default=[],
                   help="admission class spec for --fleet, repeatable; "
                        "parse_slo grammar with the endpoint naming the "
                        "class (e.g. 'interactive:p95<=250ms'); first "
                        "spec = highest drain priority; requests are "
                        "assigned round-robin over the classes; with "
                        "--slo, SLO endpoints match class names. "
                        "Default: one no-deadline 'default' class")
    p.add_argument("--endpoints", action="append", default=[],
                   help="multi-task endpoint route for --fleet, "
                        "repeatable: ENDPOINT=CLASS where CLASS is a "
                        "--classes-grammar spec declaring the class "
                        "('complete=interactive:p95<=250ms') or a bare "
                        "class name ('interpolate=batch'; declared "
                        "no-deadline if new). Endpoints: generate, "
                        "complete (stroke-prefix continuation), "
                        "reconstruct (encode->decode round trip), "
                        "interpolate (slerp grid as one batch "
                        "request). Encoder endpoints need a "
                        "conditional checkpoint; validation fails "
                        "before the restore")
    p.add_argument("--endpoint_mix", default="",
                   help="seeded endpoint mix for --endpoints runs, "
                        "'name:weight,...' (e.g. 'generate:4,"
                        "complete:3,reconstruct:2,interpolate:1'); "
                        "default: uniform over the routed endpoints")
    p.add_argument("--frames", type=int, default=6,
                   help="latent-grid size of interpolate requests in "
                        "the endpoint mix (must fit one micro-burst: "
                        "frames <= pool_cap = 4x slots)")
    p.add_argument("--tenants", type=int, default=0,
                   help="multi-tenant serving for --fleet (ISSUE 19): "
                        "register N >= 2 seeded stand-in fine-tunes "
                        "('tn0'..) as sparse int8-delta adapter pages "
                        "against the served checkpoint and serve them "
                        "through ONE value-paged fleet — tenant swaps "
                        "are pure device_puts (zero compiles), results "
                        "and cache fingerprints carry per-tenant "
                        "ckpt_ids, and the summary grows the "
                        "per-tenant latency/SLO/shed + adapter-memory "
                        "block. Excludes --draft_ckpt (the "
                        "draft+verify program bakes its params)")
    p.add_argument("--tenant_mix", default="",
                   help="seeded tenant mix for --tenants runs, "
                        "'name:weight,...' over tn0..tnN-1 (':1' "
                        "weights the base checkpoint); default: "
                        "uniform over base + every tenant")
    p.add_argument("--tenant_cap", type=int, default=0,
                   help="fair-share cap on outstanding pool rows per "
                        "tenant (0 = uncapped); admission sheds a "
                        "tenant at its cap BEFORE queue checks, so one "
                        "hot tenant cannot starve the rest")
    p.add_argument("--tenant_slo", action="append", default=[],
                   help="per-tenant SLO spec, repeatable: "
                        "tenant:class:pNN<=SECONDS (e.g. "
                        "'tn0:interactive:p95<=250ms', class optional) "
                        "— attainment is tracked and reported per "
                        "tenant, never pooled")
    p.add_argument("--random_init", action="store_true",
                   help="fresh random params instead of a checkpoint")
    p.add_argument("--log_metrics", action="store_true",
                   help="write per-request serve_metrics JSONL+CSV into "
                        "--workdir")
    p.add_argument("--trace_dir", default="",
                   help="enable per-request serving telemetry and write "
                        "telemetry.jsonl + trace.json (Chrome trace) "
                        "here; read with scripts/trace_report.py, or "
                        "answer 'why was this request slow' with "
                        "scripts/trace_query.py [--request UID]")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve a live Prometheus /metrics + /healthz "
                        "endpoint on 127.0.0.1:PORT for the run's "
                        "duration (0 = ephemeral port, printed on "
                        "stderr); enables the telemetry core even "
                        "without --trace_dir (no files exported, the "
                        "endpoint just renders live); the final scrape "
                        "is archived as metrics.prom beside the trace "
                        "(or workdir). Off by default: no listening "
                        "socket")
    p.add_argument("--slo", action="append", default=[],
                   help="latency SLO spec, repeatable: "
                        "[endpoint:[metric:]]pNN<=SECONDS (e.g. "
                        "'p95<=0.25' or 'generate:decode_s:p99<=100ms')"
                        "; compliance + error-budget burn rates land in "
                        "/metrics, /healthz and the summary JSON")
    p.add_argument("--fault_plan", default="",
                   help="chaos run (utils/faults.py): e.g. "
                        "'fleet.worker.r0@0' kills replica 0's first "
                        "burst — with --fleet the scheduler fails its "
                        "requests over to the survivors, drain() "
                        "completes, /healthz reports degraded, and the "
                        "retried strokes are bitwise identical to the "
                        "no-fault run. Off by default")
    p.add_argument("--fault_seed", type=int, default=0,
                   help="seed of the fault plan's deterministic "
                        "p=... firing decisions")
    p.add_argument("--watch_ckpt", default="",
                   help="zero-downtime rollout (ISSUE 16, needs "
                        "--fleet >= 2): follow this checkpoint dir and "
                        "hot-swap the serving fleet to each new "
                        "complete checkpoint — validated, canaried "
                        "bitwise on a retired replica, walked replica "
                        "by replica, rolled back automatically on "
                        "failure. Train in one terminal, serve-bench "
                        "with --watch_ckpt <ckpt_dir> in another; "
                        "RUN.json gains the checkpoint lineage")
    p.set_defaults(fn=cmd_serve_bench)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
