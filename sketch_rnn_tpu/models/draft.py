"""Tiny draft decoder for speculative serving (ISSUE 18).

A 1-layer narrow LSTM with its own (optionally truncated) MDN head,
distilled from the full decoder (``cli distill`` / train.distill). In
the serving engine's combined draft+verify scan it rides teacher-forced
on the verifier's emitted stroke stream and proposes the NEXT row one
position ahead; how often its proposals match the verifier (exact pen
one-hot + ``draft_tol`` on the continuous draw) sets how many rows a
dispatch commits. Its draws are never emitted, so its quality affects
throughput only — correctness rests entirely on the verifier.

Conditioning mirrors the full model: the draft consumes
``[prev5 ; extra]`` where ``extra`` is the FULL model's time-invariant
decoder features (z, class embedding) — in distillation the teacher is
frozen, so these are fixed features, and at serve time they are already
resident for the verifier. The z -> initial-carry projection is the
draft's own (``draft_init_w/b``), as the carry geometry differs.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.ops import linear as L
from sketch_rnn_tpu.ops.cells import make_cell

Params = Dict[str, Any]


def draft_mixture_count(hps: HParams) -> int:
    """Draft MDN components: ``draft_num_mixture`` or inherit the full M."""
    return hps.draft_num_mixture if hps.draft_num_mixture > 0 \
        else hps.num_mixture


class DraftDecoder:
    """Static draft-decoder definition; parameters are explicit pytrees.

    Parameter keys are ``draft_``-prefixed so a draft tree can never be
    confused with (or partially shadow) the full model's tree in a
    checkpoint or a serve-engine binding.
    """

    def __init__(self, hps: HParams):
        self.hps = hps
        cd = {"float32": None,
              "bfloat16": jnp.bfloat16}[hps.compute_dtype]
        self.cell = make_cell("lstm", hps.draft_rnn_size, compute_dtype=cd)
        self.num_mixture = draft_mixture_count(hps)
        self.out_dim = 6 * self.num_mixture + 3

    @property
    def input_size(self) -> int:
        """Matches the full model's decoder input: [prev5 ; z ; class]."""
        hps = self.hps
        size = 5
        if hps.conditional:
            size += hps.z_size
        if hps.num_classes > 0:
            size += hps.class_embed_size
        return size

    def init_params(self, key: jax.Array) -> Params:
        hps = self.hps
        keys = jax.random.split(key, 4)
        params: Params = {
            "draft_dec": self.cell.init_params(keys[0], self.input_size),
            "draft_out_w": L.xavier_uniform(
                keys[1], (hps.draft_rnn_size, self.out_dim)),
            "draft_out_b": jnp.zeros((self.out_dim,), jnp.float32),
        }
        if hps.conditional:
            params.update({
                "draft_init_w": L.xavier_uniform(
                    keys[2], (hps.z_size, self.cell.carry_size)),
                "draft_init_b": jnp.zeros((self.cell.carry_size,),
                                          jnp.float32),
            })
        return params

    def initial_carry(self, params: Params, z: Optional[jax.Array],
                      batch_size: int):
        if z is None:
            return self.cell.initial_carry(batch_size)
        flat = jnp.tanh(
            L.matmul(z, params["draft_init_w"], self.cell.compute_dtype)
            + params["draft_init_b"])
        return self.cell.unflatten_carry(flat)

    def decode_step(self, params: Params, carry, x_prev: jax.Array,
                    extra: Optional[jax.Array] = None
                    ) -> Tuple[Any, jax.Array]:
        """One step: ``[B, 5]`` prev stroke (+ time-invariant ``extra``
        ``[B, E]``) -> (carry, raw draft MDN projection ``[B, 6M'+3]``)."""
        inputs = x_prev if extra is None \
            else jnp.concatenate([x_prev, extra], axis=-1)
        carry, h = self.cell(params["draft_dec"], carry, inputs)
        return carry, L.matmul(h, params["draft_out_w"],
                               self.cell.compute_dtype) \
            + params["draft_out_b"]


def self_draft_params(params: Params, hps: HParams,
                      key: Optional[jax.Array] = None,
                      noise: float = 0.0) -> Params:
    """Synthetic distillate: the TEACHER's decode weights copied into
    the draft geometry, optionally perturbed by seeded Gaussian noise.

    ``noise=0`` yields a draft whose proposals are bitwise the
    verifier's draws (acceptance == 1 — the machinery/accounting pin);
    small ``noise`` stands in for a distilled draft — deterministic
    partial acceptance with mixed accept lengths, no training run
    needed (serve_bench's smoke arm; real drafts come from ``cli
    distill``). Requires the degenerate geometry a copy implies:
    ``dec_model == "lstm"``, ``draft_rnn_size == dec_rnn_size`` and an
    inherited mixture count.
    """
    if hps.dec_model != "lstm":
        raise ValueError(
            f"self_draft_params copies an LSTM decoder; dec_model="
            f"{hps.dec_model!r} has a different carry/param geometry")
    if hps.draft_rnn_size != hps.dec_rnn_size:
        raise ValueError(
            f"self_draft_params needs draft_rnn_size == dec_rnn_size, "
            f"got {hps.draft_rnn_size} != {hps.dec_rnn_size}")
    if draft_mixture_count(hps) != hps.num_mixture:
        raise ValueError(
            f"self_draft_params needs an inherited mixture count, got "
            f"draft_num_mixture={hps.draft_num_mixture} vs "
            f"num_mixture={hps.num_mixture}")
    draft: Params = {
        "draft_dec": jax.tree_util.tree_map(jnp.asarray, params["dec"]),
        "draft_out_w": jnp.asarray(params["out_w"]),
        "draft_out_b": jnp.asarray(params["out_b"]),
    }
    if hps.conditional:
        draft["draft_init_w"] = jnp.asarray(params["dec_init_w"])
        draft["draft_init_b"] = jnp.asarray(params["dec_init_b"])
    if noise:
        if key is None:
            raise ValueError("noise > 0 needs a PRNG key")
        leaves, treedef = jax.tree_util.tree_flatten(draft)
        leaves = [
            leaf + noise * jax.random.normal(
                jax.random.fold_in(key, i), leaf.shape, jnp.float32)
            for i, leaf in enumerate(leaves)]
        draft = jax.tree_util.tree_unflatten(treedef, leaves)
    return draft
