"""The sketch-rnn seq2seq VAE as one pure, jittable loss function.

TPU-native equivalent of the reference's ``Model`` class (SURVEY.md §2
components 6-10 and §3.2 forward pass; reference unreadable — architecture
per the sketch-rnn paper, arXiv:1704.03477 §3):

- bidirectional encoder over the stroke sequence; final fwd/bwd hidden
  states -> dense mu and sigma-hat heads,
- z = mu + exp(sigma_hat / 2) * eps with explicit PRNG keys,
- decoder initial carry = tanh(W z) covering the *full* cell carry
  (including the HyperLSTM's auxiliary state, as in the reference),
- teacher-forced decoder over [S_{t-1}; z (; class embedding)],
- 6M+3 projection -> MDN head -> masked GMM NLL + pen CE + annealed KL.

Unlike the reference's graph/session design (separate train and eval
graphs with shared weights, SURVEY §3.4), the model here is a set of pure
functions: ``train=True/False`` is a static argument and XLA compiles the
two variants; there is nothing to share because parameters are explicit.

Class-conditional decoding (BASELINE configs 4-5; UNVERIFIED in the
reference per SURVEY §3.5) is an optional learned embedding of the class
id concatenated to every decoder input step.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.ops import linear as L
from sketch_rnn_tpu.ops import mdn
from sketch_rnn_tpu.ops.cells import make_cell
from sketch_rnn_tpu.ops.rnn import (bidirectional_rnn,
                                    length_reverse_indices,
                                    run_rnn)

Params = Dict[str, Any]


def _dtype(hps: HParams):
    return {"float32": None, "bfloat16": jnp.bfloat16}[hps.compute_dtype]


def _rdtype(hps: HParams):
    """Fused-kernel residual storage dtype (None = float32)."""
    return {"float32": None,
            "bfloat16": jnp.bfloat16}[hps.fused_residual_dtype]


class SketchRNN:
    """Static model definition; parameters are explicit pytrees."""

    def __init__(self, hps: HParams):
        self.hps = hps
        cd = _dtype(hps)
        if hps.conditional:
            self.enc_fwd = make_cell(hps.enc_model, hps.enc_rnn_size,
                                     hps.hyper_rnn_size, hps.hyper_embed_size,
                                     compute_dtype=cd)
            self.enc_bwd = make_cell(hps.enc_model, hps.enc_rnn_size,
                                     hps.hyper_rnn_size, hps.hyper_embed_size,
                                     compute_dtype=cd)
        self.dec = make_cell(hps.dec_model, hps.dec_rnn_size,
                             hps.hyper_rnn_size, hps.hyper_embed_size,
                             compute_dtype=cd)
        self.out_dim = 6 * hps.num_mixture + 3

    # -- parameters --------------------------------------------------------

    def init_params(self, key: jax.Array) -> Params:
        hps = self.hps
        keys = jax.random.split(key, 10)
        dec_in = self.decoder_input_size
        params: Params = {
            "dec": self.dec.init_params(keys[0], dec_in),
            "out_w": L.xavier_uniform(keys[1], (hps.dec_rnn_size,
                                                self.out_dim)),
            "out_b": jnp.zeros((self.out_dim,), jnp.float32),
        }
        if hps.conditional:
            params.update({
                "enc_fwd": self.enc_fwd.init_params(keys[2], 5),
                "enc_bwd": self.enc_bwd.init_params(keys[3], 5),
                "mu_w": L.xavier_uniform(keys[4], (2 * hps.enc_rnn_size,
                                                   hps.z_size)),
                "mu_b": jnp.zeros((hps.z_size,), jnp.float32),
                "presig_w": L.xavier_uniform(keys[5], (2 * hps.enc_rnn_size,
                                                       hps.z_size)),
                "presig_b": jnp.zeros((hps.z_size,), jnp.float32),
                "dec_init_w": L.xavier_uniform(keys[6], (hps.z_size,
                                                         self.dec.carry_size)),
                "dec_init_b": jnp.zeros((self.dec.carry_size,), jnp.float32),
            })
        if hps.num_classes > 0:
            params["class_embed"] = L.normal_init(
                keys[7], (hps.num_classes, hps.class_embed_size), 0.05)
        return params

    @property
    def decoder_input_size(self) -> int:
        hps = self.hps
        size = 5
        if hps.conditional:
            size += hps.z_size
        if hps.num_classes > 0:
            size += hps.class_embed_size
        return size

    # -- submodules --------------------------------------------------------

    def encode(self, params: Params, x_tm: jax.Array, seq_len: jax.Array,
               key: Optional[jax.Array] = None, train: bool = False,
               x_rev_tm: Optional[jax.Array] = None
               ) -> Tuple[jax.Array, jax.Array]:
        """Time-major strokes ``[T, B, 5]`` -> (mu, presig), each [B, Nz].

        ``x_rev_tm``: optional pre-computed length-aware-reversed inputs
        (``_forward`` gathers them on the compact batch-major raw
        strokes, where the gather is ~3x cheaper than on this
        lane-padded time-major stream — see ops.rnn.bidirectional_rnn).
        """
        hps = self.hps
        x_tm = x_tm.astype(jnp.float32)  # robust to bf16-transferred strokes
        if x_rev_tm is not None:
            x_rev_tm = x_rev_tm.astype(jnp.float32)
        gen_f = gen_b = None
        if train and hps.use_recurrent_dropout and key is not None:
            # masks are drawn inside the scan (rdrop_gen) so no [T, B, H]
            # mask buffer is ever resident in HBM
            kf, kb = jax.random.split(key)
            gen_f = (kf, hps.recurrent_dropout_keep)
            gen_b = (kb, hps.recurrent_dropout_keep)
        h_final, _ = bidirectional_rnn(
            self.enc_fwd, self.enc_bwd, params["enc_fwd"], params["enc_bwd"],
            x_tm, seq_len=seq_len,
            rdrop_gen_fwd=gen_f, rdrop_gen_bwd=gen_b, remat=hps.remat,
            fused=hps.fused_rnn, residual_dtype=_rdtype(hps),
            xs_rev=x_rev_tm)
        mu = L.matmul(h_final, params["mu_w"], _dtype(hps)) + params["mu_b"]
        presig = L.matmul(h_final, params["presig_w"], _dtype(hps)) \
            + params["presig_b"]
        return mu, presig

    def sample_z(self, mu: jax.Array, presig: jax.Array, key: jax.Array
                 ) -> jax.Array:
        eps = jax.random.normal(key, mu.shape, jnp.float32)
        return mu + jnp.exp(presig / 2.0) * eps

    def decoder_initial_carry(self, params: Params,
                              z: Optional[jax.Array], batch_size: int):
        if z is None:
            return self.dec.initial_carry(batch_size)
        flat = jnp.tanh(
            L.matmul(z, params["dec_init_w"], _dtype(self.hps))
            + params["dec_init_b"])
        return self.dec.unflatten_carry(flat)

    def _decoder_extra(self, params: Params, z: Optional[jax.Array],
                       labels: Optional[jax.Array]
                       ) -> Optional[jax.Array]:
        """Time-invariant decoder features ``[B, E]``: z, class embedding."""
        parts = []
        if z is not None:
            parts.append(z)
        if self.hps.num_classes > 0:
            if labels is None:
                raise ValueError("num_classes > 0 requires batch labels")
            parts.append(params["class_embed"][labels])   # [B, E]
        return jnp.concatenate(parts, axis=-1) if parts else None

    @staticmethod
    def _broadcast_concat(x_tm: jax.Array,
                          extra: Optional[jax.Array]) -> jax.Array:
        if extra is None:
            return x_tm
        t = x_tm.shape[0]
        return jnp.concatenate(
            [x_tm, jnp.broadcast_to(extra[None], (t, *extra.shape))],
            axis=-1)

    def _decoder_inputs(self, params: Params, x_in_tm: jax.Array,
                        z: Optional[jax.Array],
                        labels: Optional[jax.Array]) -> jax.Array:
        return self._broadcast_concat(
            x_in_tm, self._decoder_extra(params, z, labels))

    def decode(self, params: Params, x_in_tm: jax.Array,
               z: Optional[jax.Array], labels: Optional[jax.Array] = None,
               key: Optional[jax.Array] = None, train: bool = False
               ) -> jax.Array:
        """Teacher-forced decoder -> raw MDN projections ``[T, B, 6M+3]``."""
        hps = self.hps
        b = x_in_tm.shape[1]
        # time-invariant features ride as a per-example bias on the fused
        # path (run_rnn concatenates them for scan/hyper) — no [T, B, E]
        # z broadcast in HBM unless input dropout needs the full stream
        extra = self._decoder_extra(params, z, labels)
        inputs = x_in_tm
        rgen = None
        if train and key is not None:
            krec, kin, kout = jax.random.split(key, 3)
            if hps.use_recurrent_dropout:
                rgen = (krec, hps.recurrent_dropout_keep)
            if hps.use_input_dropout:
                inputs = self._broadcast_concat(x_in_tm, extra)
                extra = None
                keep = hps.input_dropout_keep
                mask = jax.random.bernoulli(kin, keep, inputs.shape)
                inputs = inputs * mask / keep
        carry0 = self.decoder_initial_carry(params, z, b)
        _, hs = run_rnn(self.dec, params["dec"], inputs, carry0,
                        rdrop_gen=rgen, remat=hps.remat,
                        fused=hps.fused_rnn, residual_dtype=_rdtype(hps),
                        x_extra=extra)
        if train and key is not None and hps.use_output_dropout:
            keep = hps.output_dropout_keep
            mask = jax.random.bernoulli(kout, keep, hs.shape)
            hs = hs * mask / keep
        return L.matmul(hs, params["out_w"], _dtype(hps)) + params["out_b"]

    def decode_step(self, params: Params, carry, x_prev: jax.Array,
                    z: Optional[jax.Array] = None,
                    labels: Optional[jax.Array] = None
                    ) -> Tuple[Any, jax.Array]:
        """One autoregressive decoder step for sampling.

        ``x_prev`` is the previous stroke-5 ``[B, 5]``; returns the new cell
        carry and the raw MDN projection ``[B, 6M+3]``. Used inside the
        on-device sampling loop (SURVEY §2 component 15, §3.3).
        """
        inputs = self._decoder_inputs(params, x_prev[None], z, labels)[0]
        carry, h = self.dec(params["dec"], carry, inputs)
        return carry, L.matmul(h, params["out_w"], _dtype(self.hps)) \
            + params["out_b"]

    # -- loss --------------------------------------------------------------

    def _forward(self, params: Params, batch: Dict[str, jax.Array],
                 key: jax.Array, train: bool):
        """Shared forward preamble of :meth:`loss` and
        :meth:`eval_metrics_per_class`: batch-major strokes -> mixture
        params (+ posterior). ONE home for the entry-path recipe
        (time-major transpose, float32 upcast of possibly-bf16
        transferred strokes, the kenc/kz/kdec key split) so the two
        sweeps draw identical z for the same ``(batch, key)`` — the
        per-class/overall consistency test depends on that invariant.

        Returns ``(mp, x_target, labels, mu, presig)``; the posterior
        terms are None for non-conditional models.
        """
        hps = self.hps
        raw_bm = batch["strokes"]
        seq_len = batch["seq_len"]
        raw_rev = None
        if hps.conditional:
            # length-aware reversal for the encoder's backward direction,
            # gathered HERE on the compact batch-major RAW strokes: the
            # gather commutes with the dequant/upcast/transpose prep
            # (pure row selection; the int16 transfer_scale is
            # per-example and the gather stays within each example), and
            # on the lane-padded [T, B, 5] time-major stream it costs
            # ~3x more (scripts/probe_enc_pocket.py)
            rev_bm = length_reverse_indices(raw_bm.shape[1] - 1,
                                            seq_len).T
            raw_rev = jnp.take_along_axis(raw_bm[:, 1:],
                                          rev_bm[:, :, None], axis=1)

        def prep(bm):
            """dequant (int16 transfer) + time-major + f32 upcast."""
            if bm.dtype == jnp.int16:
                # int16 transfer (hps.transfer_dtype="int16"): offsets
                # arrive as integer data units, pen bits as 0/1;
                # dividing by the per-example transfer_scale reproduces
                # the host normalization BIT-FOR-BIT for integer-origin
                # corpora (data/prefetch.py) — the exact-feed mode
                sc = batch["transfer_scale"].astype(jnp.float32)  # [B]
                f = bm.astype(jnp.float32)
                bm = jnp.concatenate(
                    [f[..., :2] / sc[:, None, None], f[..., 2:]], axis=-1)
            return jnp.transpose(bm, (1, 0, 2)).astype(jnp.float32)

        strokes = prep(raw_bm)                   # [T+1, B, 5]
        x_in, x_target = strokes[:-1], strokes[1:]
        labels = batch.get("labels") if hps.num_classes > 0 else None
        kenc, kz, kdec = jax.random.split(key, 3)
        mu = presig = z = None
        if hps.conditional:
            mu, presig = self.encode(params, x_target, seq_len,
                                     key=kenc, train=train,
                                     x_rev_tm=prep(raw_rev))
            z = self.sample_z(mu, presig, kz)
        raw = self.decode(params, x_in, z, labels, key=kdec, train=train)
        mp = mdn.get_mixture_params(raw, hps.num_mixture)
        return mp, x_target, labels, mu, presig

    def loss(self, params: Params, batch: Dict[str, jax.Array],
             key: jax.Array, kl_weight: jax.Array, train: bool = True,
             axis_name: Optional[str] = None
             ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Full VAE loss on a loader batch; one fused XLA computation.

        ``batch["strokes"]`` is ``[B, Nmax+1, 5]`` (start token at t=0);
        ``kl_weight`` is the *annealed* weight (schedule computed outside,
        so the jitted graph is step-agnostic). Returns (total, metrics).

        ``axis_name``: set when ``batch`` is a per-device shard inside
        ``shard_map`` — every scalar (including the nonlinear KL floor)
        is then computed on psum'd GLOBAL sums, so the result equals the
        single-device global-batch loss and its local gradient is the
        device's contribution to the global gradient (psum grads to
        finish the all-reduce). This is the path that keeps the Pallas
        fused kernels shardable: pallas_call cannot be partitioned by
        GSPMD, so data parallelism must be explicit SPMD.
        """
        hps = self.hps
        # optional [B] example weights (eval sweeps zero out wrap-filled
        # duplicate rows; absent in training batches -> uniform)
        weights = batch.get("weights")
        mp, x_target, labels, mu, presig = self._forward(
            params, batch, key, train)
        if hps.conditional:
            kl_raw = mdn.kl_loss(mu, presig, weights=weights,
                                 axis_name=axis_name)
        else:
            kl_raw = jnp.float32(0.0)
        # canonical asymmetry: pen CE unmasked in training, masked in eval
        offset_nll, pen_ce = mdn.reconstruction_loss(
            mp, x_target, hps.max_seq_len, mask_pen=not train,
            weights=weights, axis_name=axis_name)
        r_cost = offset_nll + pen_ce
        if hps.conditional:
            kl_floored = mdn.kl_cost_with_floor(kl_raw, hps.kl_tolerance)
            total = r_cost + kl_weight * kl_floored
        else:
            # no latent -> no KL term at all (reference parity: the floor
            # must not inject a kl_tolerance constant into the loss)
            kl_floored = jnp.float32(0.0)
            total = r_cost
        metrics = {
            "loss": total,
            "recon": r_cost,
            "offset_nll": offset_nll,
            "pen_ce": pen_ce,
            "kl": kl_floored,
            "kl_raw": kl_raw,
            "kl_weight": jnp.asarray(kl_weight, jnp.float32),
        }
        return total, metrics

    def eval_metrics_per_class(self, params: Params,
                               batch: Dict[str, jax.Array], key: jax.Array,
                               axis_name: Optional[str] = None
                               ) -> Dict[str, jax.Array]:
        """Eval metrics split by class label in ONE forward pass.

        Returns the same metric keys as the eval-mode :meth:`loss` but as
        ``[num_classes]`` vectors, plus ``weight_sum`` — the GLOBAL
        per-class count of real (weight>0) rows in this batch. Per-class
        reductions are masked matmuls against a ``[C, B]`` class mask over
        the per-example loss sums, so the cost over a whole-split sweep is
        one standard sweep regardless of C — and, unlike
        ``DataLoader.filter_by_label``, the batch schedule is the standard
        eval sweep (identical on every host), which makes per-class eval
        safe under multi-host striping (VERDICT r2 #4; the paper's
        per-category tables are the parity surface).

        Semantics mirror eval: no dropout, pen CE masked, KL weight 1 with
        the free-bits floor applied to each batch's per-class KL mean.
        Note the floor is nonlinear, so its input partition matters: here
        it sees each standard batch's class-c rows, whereas a
        ``filter_by_label`` sweep feeds it full batches of class c — when
        a class's KL straddles ``kl_tolerance`` the floored ``kl`` /
        ``loss`` can differ slightly between the two paths (``kl_raw``,
        ``offset_nll``, ``pen_ce``, ``recon`` are linear and exact either
        way). Classes absent from the batch report zeros at
        ``weight_sum`` 0 — hosts must drop them from weighted averages.
        """
        hps = self.hps
        if hps.num_classes <= 0:
            raise ValueError("per-class eval needs num_classes > 0")
        labels = batch["labels"]
        weights = batch.get("weights")
        w = (jnp.ones(labels.shape, jnp.float32) if weights is None
             else weights.astype(jnp.float32))
        mp, x_target, _, mu, presig = self._forward(
            params, batch, key, train=False)
        kl_ex = (mdn.kl_per_example(mu, presig) if hps.conditional
                 else jnp.zeros(labels.shape, jnp.float32))   # [B]
        nll_ex, pen_ex = mdn.reconstruction_sums(mp, x_target,
                                                 mask_pen=True)  # [B] each

        cls = jnp.arange(hps.num_classes)
        mask = (labels[None, :] == cls[:, None]) * w[None, :]   # [C, B]

        def gsum(v):
            return (jax.lax.psum(v, axis_name) if axis_name else v)

        cnt = gsum(mask.sum(axis=-1))                           # [C]
        safe = jnp.maximum(cnt, 1.0)
        offset_nll = gsum(mask @ nll_ex) / (hps.max_seq_len * safe)
        pen_ce = gsum(mask @ pen_ex) / (hps.max_seq_len * safe)
        kl_raw = gsum(mask @ kl_ex) / safe
        recon = offset_nll + pen_ce
        if hps.conditional:
            kl_floored = mdn.kl_cost_with_floor(kl_raw, hps.kl_tolerance)
            total = recon + kl_floored
        else:
            kl_floored = jnp.zeros_like(kl_raw)
            total = recon
        ones = jnp.ones_like(cnt)
        return {
            "loss": total,
            "recon": recon,
            "offset_nll": offset_nll,
            "pen_ce": pen_ce,
            "kl": kl_floored,
            "kl_raw": kl_raw,
            "kl_weight": ones,
            "weight_sum": cnt,
        }
