from sketch_rnn_tpu.models.draft import (DraftDecoder,
                                         draft_mixture_count,
                                         self_draft_params)
from sketch_rnn_tpu.models.vae import SketchRNN

__all__ = ["SketchRNN", "DraftDecoder", "draft_mixture_count",
           "self_draft_params"]
