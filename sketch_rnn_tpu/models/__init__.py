from sketch_rnn_tpu.models.vae import SketchRNN

__all__ = ["SketchRNN"]
