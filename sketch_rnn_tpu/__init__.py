"""sketch_rnn_tpu — a TPU-native sketch-rnn framework.

A ground-up JAX/XLA re-design of the capability surface of the reference
(ByzanTine/sketch-rnn; see SURVEY.md — the reference mount was empty when
surveyed, so citations are to SURVEY.md sections and BASELINE.json):

- stroke-5 QuickDraw data pipeline (SURVEY §2 component 1)
- LSTM / LayerNorm-LSTM / HyperLSTM cells as pure ``lax.scan`` step
  functions (components 2-5; the cuDNN fused path becomes XLA-fused scan)
- seq2seq VAE: bi-LSTM encoder, reparameterized latent, autoregressive
  decoder, 20-component bivariate-GMM + pen mixture-density head
  (components 6-10)
- single-jit training step with optax, KL annealing, gradient clipping,
  data-parallel over a ``jax.sharding.Mesh`` with ICI collectives in
  place of NCCL (components 11, 18)
- fully on-device autoregressive sampling via ``lax.while_loop``
  (component 15)
"""

from sketch_rnn_tpu.config import HParams, get_default_hparams

__version__ = "0.1.0"

__all__ = ["HParams", "get_default_hparams", "__version__"]
