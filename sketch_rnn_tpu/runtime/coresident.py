"""Co-resident train-and-serve: one process, one model lineage
(ISSUE 20).

``cli train --serve_fleet N`` trains while an N-replica
:class:`~sketch_rnn_tpu.serve.fleet.ServeFleet` serves the SAME model
in the same process: training's async checkpoints land in the workdir,
the PR 16 :class:`~sketch_rnn_tpu.serve.rollout.CheckpointWatcher`
picks each one up, and the rollout controller walks the fleet to it
live — admission-validated, canary-gated, rolled back on failure. The
fleet serves throughout: ``/healthz`` reports only ``ok`` / ``rolling``
(or ``scaling``), never ``degraded``, and a post-swap request is
bitwise what a cold engine started from the same checkpoint computes
(the rollout acceptance bar, re-proven here under a LIVE training
producer instead of a test writing checkpoints by hand).

The loop also closes: completed requests are a stroke corpus, and
:meth:`CoResident.corpus` converts their stroke-5 Results back to
stroke-3 so ``data.native_batcher.stream_batches`` can assemble train
batches straight from the serving fleet's output — the
continual-learning smoke (serve -> collect -> train on what was
served) with no materialized dataset.

Threads follow the repo's naming discipline (the conftest guard
whitelists prefixes): the watcher is ``rollout-watcher`` (PR 16), the
health sampler ``coresident-health``, the request feeder
``coresident-loadgen``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["CoResident", "coresident_train", "default_canaries",
           "stroke5_to_stroke3"]


def stroke5_to_stroke3(strokes5, length: Optional[int] = None
                       ) -> np.ndarray:
    """A served Result's stroke-5 rows back to the stroke-3 ingestion
    format ``(dx, dy, pen_lift)``: column 3 is the lift bit; the final
    row closes its stroke (the end-of-sketch row, when drawn, is
    excluded by ``length`` — ``Result.length``'s contract)."""
    s5 = np.asarray(strokes5, np.float32)
    if length is not None:
        s5 = s5[:max(int(length), 1)]
    s3 = s5[:, [0, 1, 3]].copy()
    s3[-1, 2] = 1.0
    return s3


def default_canaries(hps, n: int = 3, cap: int = 4) -> List[Any]:
    """A small seeded canary burst (the per-swap bitwise gate):
    conditional models exercise z, as the rollout contract asks."""
    import jax

    from sketch_rnn_tpu.serve.engine import Request

    reqs = []
    for i in range(n):
        rng = np.random.default_rng(9000 + i)
        reqs.append(Request(
            key=jax.random.key(9000 + i),
            z=(rng.standard_normal(hps.z_size).astype(np.float32)
               if hps.conditional else None),
            temperature=0.8, max_len=cap))
    return reqs


class CoResident:
    """A live serving fleet following a training run's checkpoints.

    Construction warms and starts the fleet, registers a
    :class:`RolloutController` and points its watcher at ``ckpt_dir``
    (the training workdir). A background sampler polls ``/healthz``
    continuously; its log is the never-degraded evidence. Use as a
    context manager, or call :meth:`close`.
    """

    def __init__(self, model, hps, params, ckpt_dir: str,
                 replicas: int = 2, ckpt_id: str = "",
                 canary_requests: Optional[Sequence[Any]] = None,
                 poll_s: float = 0.25,
                 health_period_s: float = 0.1) -> None:
        import jax

        from sketch_rnn_tpu.serve.fleet import ServeFleet
        from sketch_rnn_tpu.serve.rollout import RolloutController
        from sketch_rnn_tpu.train.state import make_train_state

        if replicas < 2:
            raise ValueError(
                f"co-resident serving needs >= 2 replicas (got "
                f"{replicas}): the rollout walk drains one replica at "
                f"a time, so a single replica cannot serve through a "
                f"swap")
        self.model = model
        self.hps = hps
        self.ckpt_dir = str(ckpt_dir)
        canaries = (list(canary_requests) if canary_requests
                    else default_canaries(hps))
        self.fleet = ServeFleet(model, hps, params, replicas=replicas,
                                ckpt_id=ckpt_id)
        self.fleet.warm(canaries[0])
        self.fleet.start()
        # template values are ignored — it is the shape manifest the
        # admission gate validates candidates against
        template = make_train_state(model, hps, jax.random.key(0))
        self.controller = RolloutController(
            self.fleet, model, hps, template, canaries,
            quarantine_dir=os.path.join(self.ckpt_dir, "quarantine"))
        self.watcher = self.controller.watch(self.ckpt_dir,
                                             poll_s=poll_s)
        self.health_log: List[str] = []
        self._health_lock = threading.Lock()
        self._stop = threading.Event()
        self._feeder: Optional[threading.Thread] = None
        self._fed = 0
        self._health_thread = threading.Thread(
            target=self._health_loop, args=(float(health_period_s),),
            name="coresident-health", daemon=True)
        self._health_thread.start()

    # -- health -------------------------------------------------------------

    def sample_health(self) -> str:
        """One ``/healthz`` verdict through the REAL endpoint payload
        (``serve.metrics_http.health_payload``), recorded in
        :attr:`health_log` — the co-resident acceptance reads the log:
        ok/rolling/scaling only, never degraded."""
        from sketch_rnn_tpu.serve.metrics_http import health_payload
        from sketch_rnn_tpu.utils.telemetry import get_telemetry

        status = str(health_payload(get_telemetry(),
                                    health=self.fleet.health)["status"])
        with self._health_lock:
            self.health_log.append(status)
        return status

    def _health_loop(self, period_s: float) -> None:
        while not self._stop.is_set():
            try:
                self.sample_health()
            except Exception:  # noqa: BLE001 — sampler must outlive
                pass           # transient fleet-lock contention
            self._stop.wait(period_s)

    def health_statuses(self) -> List[str]:
        with self._health_lock:
            return list(self.health_log)

    # -- load ----------------------------------------------------------------

    def start_loadgen(self, requests: Sequence[Any],
                      interval_s: float = 0.0) -> None:
        """Feed ``requests`` to the fleet from a ``coresident-loadgen``
        thread (``force=True``: the continual-learning corpus must not
        lose members to shed policy), ``interval_s`` apart — the live
        traffic the fleet serves while training runs."""
        if self._feeder is not None:
            raise RuntimeError("loadgen already running")

        reqs = list(requests)

        def run() -> None:
            for r in reqs:
                if self._stop.is_set():
                    return
                self.fleet.submit(r, force=True)
                self._fed += 1
                if interval_s:
                    time.sleep(interval_s)

        self._feeder = threading.Thread(target=run,
                                        name="coresident-loadgen",
                                        daemon=True)
        self._feeder.start()

    def drain(self, timeout: Optional[float] = None) -> bool:
        if self._feeder is not None:
            self._feeder.join(timeout=timeout)
        return self.fleet.drain(timeout=timeout)

    def corpus(self) -> List[np.ndarray]:
        """Completed requests as stroke-3 sequences, uid order — the
        serve->train return path: feed it to ``stream_batches(corpus,
        batch_size, max_len)`` and train on what was served."""
        recs = self.fleet.results
        return [stroke5_to_stroke3(recs[uid]["result"].strokes5,
                                   recs[uid]["result"].length)
                for uid in sorted(recs)]

    # -- lineage -------------------------------------------------------------

    def lineage(self) -> List[Dict[str, Any]]:
        return self.controller.lineage()

    def serving_summary(self) -> Dict[str, Any]:
        statuses = self.health_statuses()
        return {
            "replicas": self.fleet.n_replicas,
            "serving_ckpt_id": self.fleet.serving_ckpt_id,
            "lineage": self.lineage(),
            # one report per checkpoint the watcher rolled to:
            # {ok, phase, from, to, swapped, rolled_back, ...}
            "rollouts": [dict(r) for r in self.watcher.reports],
            "requests_completed": len(self.fleet.results),
            "health_samples": len(statuses),
            "health_degraded": sum(s == "degraded" for s in statuses),
        }

    def write_manifest(self, out_dir: Optional[str] = None) -> str:
        """Merge the serving lineage into the run's RUN.json (same
        run_id as training's manifest, so the two compose): which
        checkpoint served which admitted-uid window, every rollout
        event, and the health record."""
        from sketch_rnn_tpu.utils import runinfo

        return runinfo.write_manifest(
            out_dir or self.ckpt_dir, kind="train", hps=self.hps,
            extra={"serving": self.serving_summary()})

    # -- lifecycle -----------------------------------------------------------

    def close(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._feeder is not None:
            self._feeder.join(timeout=timeout)
            self._feeder = None
        self._health_thread.join(timeout=timeout)
        # fleet.close() joins the controller's in-flight walk and stops
        # the watcher (fleet._rollout wiring, PR 16)
        self.fleet.close(timeout=timeout)

    def __enter__(self) -> "CoResident":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def coresident_train(hps, train_loader, valid_loader=None,
                     test_loader=None, scale_factor: float = 1.0,
                     workdir: Optional[str] = None, seed: int = 0,
                     replicas: int = 2, num_steps: Optional[int] = None,
                     resume: bool = True, poll_s: float = 0.25,
                     loadgen: Optional[Sequence[Any]] = None,
                     **train_kw):
    """Run ``train.loop.train`` with a co-resident serving fleet
    following its checkpoints; returns ``(state, summary)``.

    The fleet starts on the latest checkpoint in ``workdir`` when one
    exists (the resume path serves what training resumes from),
    otherwise on the seed initialization — every subsequent checkpoint
    training saves is rolled out live by the watcher. ``loadgen``
    (optional) is a request list fed during training. The serving
    summary (lineage, rollouts, health record) is merged into
    ``<workdir>/RUN.json`` before the fleet closes.
    """
    import jax

    from sketch_rnn_tpu.models.vae import SketchRNN
    from sketch_rnn_tpu.train.checkpoint import (ckpt_id_of,
                                                 latest_checkpoint,
                                                 restore_checkpoint)
    from sketch_rnn_tpu.train.loop import train
    from sketch_rnn_tpu.train.state import make_train_state

    if not workdir:
        raise ValueError("co-resident serving needs a workdir: the "
                         "fleet follows its checkpoint directory")
    model = train_kw.pop("model", None) or SketchRNN(hps)
    params = make_train_state(model, hps, jax.random.key(seed)).params
    ckpt_id = ""
    step0 = latest_checkpoint(workdir) if resume else None
    if step0 is not None:
        target = make_train_state(model, hps, jax.random.key(seed))
        restored, _, _ = restore_checkpoint(workdir, target, step=step0)
        params, ckpt_id = restored.params, ckpt_id_of(step0)
    co = CoResident(model, hps, params, workdir, replicas=replicas,
                    ckpt_id=ckpt_id, poll_s=poll_s)
    try:
        if loadgen:
            co.start_loadgen(loadgen)
        state = train(hps, train_loader, valid_loader, test_loader,
                      scale_factor=scale_factor, workdir=workdir,
                      seed=seed, num_steps=num_steps, resume=resume,
                      model=model, **train_kw)
        co.drain(timeout=60.0)
        # let the watcher FINISH rolling to the final checkpoint
        # before summarizing (the watcher marks a step seen before its
        # walk completes, so _seen alone is not the done signal — the
        # fleet's authoritative serving id flipping is)
        final = latest_checkpoint(workdir)
        if final is not None:
            want = ckpt_id_of(final)
            deadline = time.monotonic() + 30.0
            while (co.fleet.serving_ckpt_id != want
                   and time.monotonic() < deadline):
                time.sleep(min(poll_s, 0.1))
        summary = co.serving_summary()
        co.write_manifest(workdir)
    finally:
        co.close()
    return state, summary
