"""One geometry-run scheduler: the unified dispatch runtime (ISSUE 20).

Every hot host loop in this codebase dispatches fixed-geometry compiled
programs and pays for three things: how work is GROUPED into runs (the
bucket-run / chunk / burst formation), how runs are ISSUED (stacked
scan vs per-item replay, pipelined vs serialized), and how results are
FETCHED (each ``device_get`` is a host sync that drains the dispatch
pipeline). Five sites hand-rolled the same answers independently; this
module owns THE copy of each mechanic and the sites delegate:

- :meth:`GeometryRunScheduler.dispatch_stack` — the bucket-run
  training scheduler's dispatch decision (``train.loop.dispatch_stack``
  is a thin delegate; ``scripts/bucket_bench.py`` rides the same one).
- :meth:`GeometryRunScheduler.geometry_runs` — geometry-boundary run
  formation for ordered sweeps (the eval sweep's chunker).
- :meth:`GeometryRunScheduler.bucket_runs` — bucket-grouped fixed-rows
  run formation for unordered items (the encode burst's grouper).
- :meth:`GeometryRunScheduler.form_burst` — priority-ordered,
  cost-capped, group-pure burst formation (the fleet's micro-bursts).
- :meth:`GeometryRunScheduler.pipeline` — the depth-1 software
  pipeline (dispatch chunk ``i+1`` before fetching chunk ``i``; zero
  host syncs between dispatches) the serve engine's chunk loop runs on.

Program identity stays geometry-keyed: :meth:`program` jits a callable
(optionally with **donated** argnums — the HBM-footprint lever) and
wraps it in a :class:`~sketch_rnn_tpu.utils.telemetry.JitCompileProbe`,
so one compile per geometry is an auditable property, never an
assumption. :meth:`register` adopts a probe a site already built (the
serve chunk/encode programs carry bespoke geometry keys) into the same
accounting.

The :class:`DispatchLedger` is the shared accounting surface: realized
K-amortization (``dispatches_saved = micro_items - dispatches``, the
number the training rows already log via the ``PaddingLedger`` view)
and host syncs (every :meth:`fetch`). The serve engine reports both in
its per-run metrics; the train loop's rows keep their pinned pre-PR
CSV schema — ``dispatches_saved`` is already a column there, and host
syncs surface through telemetry counters and GOODPUT/runtime-bench
records instead of new default columns (the ``PRE_PR_HEADER``
contract: telemetry may never leak columns into the metrics CSV).

Donation rules (the async-checkpoint snapshot discipline, ISSUE 3/16):

- Donate ONLY buffers the host provably never reads again: the train
  state (rebound every step; the async checkpointer snapshots BEFORE
  the donating dispatch consumes it) and the serve loop's carry/prev
  (opaque device round-trip, rebound every chunk).
- NEVER donate buffers a later dispatch re-reads: the serve request
  pool (every chunk of a burst gathers from it) and the ``t``/``done``
  vectors (outputs of chunk ``i`` are consumed as inputs of chunk
  ``i+1`` BEFORE the pipelined fetch of chunk ``i`` reads them).
- A donated buffer reused anyway fails LOUDLY (XLA: "buffer has been
  deleted or donated") — tests pin that error so a scheduling bug can
  never silently read stale memory.

Everything here is deterministic scheduling math — run formation,
dispatch counts, compile counts are pure functions of the work list —
which is what lets ``scripts/runtime_bench.py`` prove the unified
scheduler bitwise against the five legacy schedules (the box
constraint: acceptance never reads a wall clock).
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional
from typing import Sequence, Tuple

from sketch_rnn_tpu.utils.telemetry import JitCompileProbe


class DispatchLedger:
    """Shared dispatch/host-sync accounting (thread-safe counters).

    ``micro_items`` counts scheduled work units (micro-steps, chunk
    steps, encode rows), ``dispatches`` the jitted calls that carried
    them — ``dispatches_saved`` is the realized amortization, the same
    quantity the training ``PaddingLedger`` derives for its metrics
    rows. ``host_syncs`` counts device->host fetches (each one drains
    the dispatch pipeline; the steady-state loops target zero BETWEEN
    dispatches — the depth-1 pipeline fetches only behind the next
    issue)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.dispatches = 0
        self.micro_items = 0
        self.host_syncs = 0

    def record_run(self, use: int, n_disp: int) -> None:
        """Account one run: ``use`` work units over ``n_disp`` jitted
        calls (1 for a stacked dispatch, ``use`` for a replay)."""
        with self._lock:
            self.micro_items += int(use)
            self.dispatches += int(n_disp)

    def record_sync(self, n: int = 1) -> None:
        with self._lock:
            self.host_syncs += int(n)

    @property
    def dispatches_saved(self) -> int:
        return self.micro_items - self.dispatches

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"dispatches": self.dispatches,
                    "micro_items": self.micro_items,
                    "dispatches_saved": self.micro_items - self.dispatches,
                    "host_syncs": self.host_syncs}

    def window(self, since: Optional[Dict[str, int]] = None
               ) -> Dict[str, int]:
        """Counters since ``since`` (a prior :meth:`snapshot`)."""
        now = self.snapshot()
        if since is None:
            return now
        return {k: now[k] - since.get(k, 0) for k in now}


class _Depth1Pipeline:
    """At most one dispatch in flight: ``issue`` enqueues the next unit
    and returns the PREVIOUS unit's handle (None on the first call), so
    the host's fetch/collect work always overlaps the in-flight device
    compute — the serve engine's chunk discipline and prefetch.py's
    output-side mirror. ``drain`` hands back the final in-flight handle
    (its fetch is the run's one closing sync)."""

    def __init__(self, ledger: DispatchLedger) -> None:
        self._ledger = ledger
        self._inflight: Any = None

    def issue(self, dispatch_fn: Callable[[], Any]) -> Any:
        prev, self._inflight = self._inflight, dispatch_fn()
        return prev

    def drain(self) -> Any:
        handle, self._inflight = self._inflight, None
        return handle


class GeometryRunScheduler:
    """The unified dispatch runtime: program registry + run formation +
    pipelined issue + the shared :class:`DispatchLedger`.

    One instance per dispatch domain: the process-wide default
    (:func:`default_scheduler`) serves the training loop, eval sweep,
    fleet burst formation and encode bursts; each ``ServeEngine`` holds
    its own (its ledger feeds the per-run serve metrics). All methods
    are semantics-frozen ports of the five legacy sites —
    ``scripts/runtime_bench.py`` pins each against its pre-PR schedule.
    """

    def __init__(self, name: str = "runtime",
                 ledger: Optional[DispatchLedger] = None) -> None:
        self.name = str(name)
        self.ledger = ledger if ledger is not None else DispatchLedger()
        self._programs: List[weakref.ref] = []
        self._lock = threading.Lock()

    # -- program registry ---------------------------------------------------

    def program(self, fn: Callable, name: str, key_of=None, label_of=None,
                donate_argnums=None, **jit_kwargs) -> JitCompileProbe:
        """Jit ``fn`` (optionally donating ``donate_argnums``) and wrap
        it in a geometry-keyed :class:`JitCompileProbe` registered with
        this scheduler — compile counts become auditable through
        :meth:`compile_count` and the probe's telemetry spans."""
        import jax

        if donate_argnums is not None:
            jit_kwargs["donate_argnums"] = donate_argnums
        return self.register(JitCompileProbe(
            jax.jit(fn, **jit_kwargs), name,
            key_of=key_of, label_of=label_of))

    def register(self, probe: JitCompileProbe) -> JitCompileProbe:
        """Adopt an already-built probe (sites with bespoke geometry
        keys — the serve chunk/encode programs) into this scheduler's
        compile accounting. Held by WEAK reference: registration must
        never extend a program's lifetime (a hot-swap-retired encoder's
        probes — and the params its programs baked in — stay
        collectable)."""
        with self._lock:
            self._programs.append(weakref.ref(probe))
        return probe

    def compile_count(self) -> int:
        """Total compiled executables across live registered programs
        (one per geometry per program; the never-a-silent-recompile
        pin)."""
        with self._lock:
            self._programs = [r for r in self._programs
                              if r() is not None]
            programs = [r() for r in self._programs]
        return sum(p._cache_size() for p in programs if p is not None)

    # -- run formation ------------------------------------------------------

    def geometry_runs(self, n: int, k_max: int,
                      geom_of: Optional[Callable[[int], Any]] = None
                      ) -> Iterator[Tuple[int, int]]:
        """Chunk an ordered sweep of ``n`` items into runs of up to
        ``k_max`` that never cross a geometry boundary: yields ``(i,
        k)`` spans. The eval sweep's chunker (``train.loop._sweep_rows``
        semantics, frozen): a run extends while ``geom_of`` is constant;
        ``k_max=1`` (or no ``geom_of`` and ``k_max=1``) degenerates to
        the per-item schedule."""
        i = 0
        while i < n:
            k = min(k_max, n - i)
            if k > 1 and geom_of is not None:
                run, g0 = 1, geom_of(i)
                while run < k and geom_of(i + run) == g0:
                    run += 1
                k = run
            yield i, k
            i += k

    def bucket_runs(self, n: int, edge_of: Callable[[int], Any],
                    rows: int) -> Iterator[Tuple[Any, List[int]]]:
        """Group ``n`` unordered items by bucket edge and chop each
        group into fixed-``rows`` runs: yields ``(edge, indices)`` with
        ``len(indices) <= rows`` (the caller pads short runs to the
        compiled geometry). The encode burst's grouper
        (``serve.endpoints.EncodeProgram.encode`` semantics, frozen):
        edges ascend, each edge's items keep input order."""
        by_edge: Dict[Any, List[int]] = {}
        for i in range(n):
            by_edge.setdefault(edge_of(i), []).append(i)
        for edge in sorted(by_edge):
            idxs = by_edge[edge]
            for lo in range(0, len(idxs), rows):
                yield edge, idxs[lo:lo + rows]

    def form_burst(self, queues: Iterable, cap: int,
                   cost_of: Callable[[Any], int],
                   group_of: Optional[Callable[[Any], Any]] = None
                   ) -> List[Any]:
        """Pop a priority-ordered micro-burst: walk ``queues`` (deques,
        highest priority first), popping heads while the summed
        ``cost_of`` fits ``cap``; stop at the first head that does not
        fit, and — when ``group_of`` is given — at the first head whose
        group differs from the first popped item's (single-tenant
        bursts). Never skips ahead past a blocked head: priority order
        is never violated for capacity or purity. The fleet's
        ``pop_batch`` semantics, frozen."""
        batch: List[Any] = []
        used = 0
        group: Any = _UNSET
        for q in queues:
            while q and used < cap:
                if group is not _UNSET and group_of is not None \
                        and group_of(q[0]) != group:
                    return batch
                cost = cost_of(q[0])
                if used + cost > cap:
                    return batch
                item = q.popleft()
                if group is _UNSET and group_of is not None:
                    group = group_of(item)
                batch.append(item)
                used += cost
            if used >= cap:
                break
        return batch

    # -- stacked dispatch + remainder replay --------------------------------

    def dispatch_stack(self, single_step, multi_step, state, batch,
                       step: int, remaining: int, root_key, k: int):
        """One bucket-run dispatch decision (ISSUE 5 contract, frozen;
        ``train.loop.dispatch_stack`` and ``scripts/bucket_bench.py``
        both delegate here so the two cannot drift).

        ``batch`` is a stacked geometry-run prefix with leading axis
        ``kk <= k``; ``use = min(kk, remaining)`` micro-steps are
        consumed. A full ``use == k`` stack dispatches ONE compiled
        (K, B, Tb) scan (``multi_step`` built with
        ``key_by_global_step=True``: it folds the live ``state.step``
        into ``root_key``); anything shorter replays per micro-step
        through ``single_step`` with ``fold_in(root_key, step + i)`` —
        the identical key either way, so the whole run is step-for-step
        RNG-identical to K=1. Replay windows report metrics with the
        scan's semantics (:meth:`replay_window_metrics`).

        Returns ``(state, metrics, use, dispatches)`` and records the
        run in this scheduler's ledger — ``dispatches_saved`` in every
        surface derives from the same decision made here.
        """
        import jax

        kk = int(jax.tree_util.tree_leaves(batch)[0].shape[0])
        use = min(kk, remaining)
        if use == k:
            state, metrics = multi_step(state, batch, root_key)
            self.ledger.record_run(use, 1)
            return state, metrics, use, 1
        per_step = []
        for i in range(use):
            b_i = jax.tree_util.tree_map(lambda x: x[i], batch)
            state, m = single_step(
                state, b_i, jax.random.fold_in(root_key, step + i))
            per_step.append(m)
        self.ledger.record_run(use, use)
        return state, self.replay_window_metrics(per_step), use, use

    @staticmethod
    def replay_window_metrics(per_step: Sequence[Dict]) -> Dict:
        """Fold a replayed window's per-micro-step metric dicts into
        one row with the K-scan's semantics
        (``train.step.make_multi_train_step``): MEAN over the window,
        ``grad_norm_max`` the max, ``lr``/``kl_weight`` the last
        micro-step's schedule values. Pure device-side tree math on the
        (lazy) metric refs — no host sync. Shared by every replay path
        so logged rows cannot drift in meaning between the scan, the
        run-remainder replay and the fixed-T final remainder."""
        import jax.numpy as jnp

        sums = None
        gmax = None
        for m in per_step:
            g = m["grad_norm"]
            gmax = g if gmax is None else jnp.maximum(gmax, g)
            sums = (dict(m) if sums is None
                    else {name: sums[name] + m[name] for name in sums})
        metrics = {name: v / len(per_step) for name, v in sums.items()}
        metrics["grad_norm_max"] = gmax
        metrics["lr"] = per_step[-1]["lr"]
        metrics["kl_weight"] = per_step[-1]["kl_weight"]
        return metrics

    # -- pipelined issue / fetch --------------------------------------------

    def pipeline(self) -> _Depth1Pipeline:
        """A fresh depth-1 pipeline bound to this scheduler's ledger."""
        return _Depth1Pipeline(self.ledger)

    def fetch(self, refs):
        """Fetch device values to host numpy — THE accounted host sync.
        Every steady-state loop's sync count flows through here, so the
        ledger's ``host_syncs`` is exact by construction."""
        import jax

        self.ledger.record_sync()
        return jax.device_get(refs)


_UNSET = object()  # form_burst's "no group chosen yet" sentinel

_DEFAULT = GeometryRunScheduler("default")


def default_scheduler() -> GeometryRunScheduler:
    """The process-wide scheduler: training loop, eval sweep, fleet
    burst formation and encode bursts share it (and its ledger); each
    serve engine holds its own so per-run serve metrics stay
    per-engine."""
    return _DEFAULT
