"""Unified dispatch runtime (ISSUE 20): one geometry-run scheduler.

Five host-side schedulers grew up independently around the same
pattern — group work into fixed-shape runs, dispatch stacked, replay
remainders, never sync the host between dispatches:

- ``train.loop.dispatch_stack``      (bucket-run training scheduler)
- ``train.loop._sweep_rows``         (geometry-chunked eval sweep)
- ``serve.engine.ServeEngine.run``   (depth-1 pipelined chunk loop)
- ``serve.fleet._Replica.pop_batch`` (class-priority micro-bursts)
- ``serve.endpoints.EncodeProgram``  (prefix-bucketed encode bursts)

:mod:`sketch_rnn_tpu.runtime.scheduler` owns THE copies of those
mechanics — run formation, stacked dispatch + remainder replay, the
depth-1 pipeline, geometry-keyed program registration, buffer-donation
policy and the shared dispatch/host-sync ledger — and the five sites
delegate to it, so the dispatch contract can no longer drift between
training and serving. :mod:`sketch_rnn_tpu.runtime.coresident` cashes
in the unification: one process that trains AND serves, the training
loop's async checkpoints feeding the serving fleet's rollout path
live.
"""

from sketch_rnn_tpu.runtime.coresident import (  # noqa: F401
    CoResident,
    coresident_train,
)
from sketch_rnn_tpu.runtime.scheduler import (  # noqa: F401
    DispatchLedger,
    GeometryRunScheduler,
    default_scheduler,
)

__all__ = [
    "CoResident",
    "DispatchLedger",
    "GeometryRunScheduler",
    "coresident_train",
    "default_scheduler",
]
