"""Mixture-density-network head and VAE losses (pure jnp).

TPU-native equivalent of the reference's ``get_mixture_coef`` /
``get_lossfunc`` / ``tf_2d_normal`` + KL terms (SURVEY.md §2 components 9
and 10; reference unreadable — semantics per the sketch-rnn paper,
arXiv:1704.03477 §3.2-3.3, and the canonical loss subtleties recorded in
SURVEY §7 'Hard parts'):

- the bivariate-GMM NLL is computed with a fused ``logsumexp`` over
  components (numerically stabler than the reference's pdf-then-log with
  an epsilon; identical up to the epsilon),
- the GMM term is masked to each sequence's true length via
  ``fs = 1 - p3(target)``; the pen-state cross-entropy is *unmasked* to
  Nmax during training and masked during eval — that asymmetry is the
  canonical behavior and is controlled by ``mask_pen``,
- both terms are normalized by ``max_seq_len * batch`` regardless of mask,
- KL has the reference's ``kl_tolerance`` floor (free bits).

Everything here is elementwise/reduction math that XLA fuses straight into
the surrounding graph (SURVEY §2: "fuse into a single XLA graph").
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

LOG_2PI = 1.8378770664093453  # log(2*pi)


class MixtureParams(NamedTuple):
    """Per-step GMM + pen parameters; leading dims are arbitrary."""

    log_pi: jax.Array   # [..., M] log mixture weights (normalized)
    mu1: jax.Array      # [..., M]
    mu2: jax.Array      # [..., M]
    log_s1: jax.Array   # [..., M] log std of dx
    log_s2: jax.Array   # [..., M] log std of dy
    rho: jax.Array      # [..., M] correlation in (-1, 1)
    pen_logits: jax.Array  # [..., 3]


def get_mixture_params(raw: jax.Array, num_mixture: int) -> MixtureParams:
    """Split a ``[..., 6M+3]`` projection into normalized GMM parameters."""
    m = num_mixture
    if raw.shape[-1] != 6 * m + 3:
        raise ValueError(f"expected trailing dim {6 * m + 3}, got {raw.shape}")
    pen_logits = raw[..., :3]
    body = raw[..., 3:].reshape(*raw.shape[:-1], 6, m)
    logits, mu1, mu2, ls1, ls2, rho_raw = (body[..., j, :] for j in range(6))
    return MixtureParams(
        log_pi=jax.nn.log_softmax(logits, axis=-1),
        mu1=mu1,
        mu2=mu2,
        log_s1=ls1,
        log_s2=ls2,
        rho=jnp.tanh(rho_raw),
        pen_logits=pen_logits,
    )


def bivariate_normal_logpdf(dx: jax.Array, dy: jax.Array,
                            mp: MixtureParams) -> jax.Array:
    """Log pdf of (dx, dy) under each component; returns ``[..., M]``."""
    zx = (dx[..., None] - mp.mu1) * jnp.exp(-mp.log_s1)
    zy = (dy[..., None] - mp.mu2) * jnp.exp(-mp.log_s2)
    one_m_r2 = jnp.clip(1.0 - jnp.square(mp.rho), 1e-6, 1.0)
    z = zx * zx + zy * zy - 2.0 * mp.rho * zx * zy
    return (-z / (2.0 * one_m_r2)
            - 0.5 * jnp.log(one_m_r2) - mp.log_s1 - mp.log_s2 - LOG_2PI)


def gmm_nll(dx: jax.Array, dy: jax.Array, mp: MixtureParams) -> jax.Array:
    """Negative log-likelihood of offsets under the mixture, per step."""
    comp = mp.log_pi + bivariate_normal_logpdf(dx, dy, mp)
    return -jax.nn.logsumexp(comp, axis=-1)


def reconstruction_loss(mp: MixtureParams, target: jax.Array,
                        max_seq_len: int, mask_pen: bool = False,
                        weights: Optional[jax.Array] = None
                        ) -> Tuple[jax.Array, jax.Array]:
    """Offset-GMM NLL + pen-state CE, canonical masking and normalization.

    ``target`` is time-major stroke-5 ``[T, B, 5]`` (the sequence shifted
    one step ahead of the decoder input). Returns scalars
    ``(offset_nll, pen_ce)``, each already divided by ``max_seq_len * B``.

    ``weights`` (``[B]``, optional) weights each example's contribution
    and replaces ``B`` with ``sum(weights)`` in the normalization — used
    by the eval sweep to zero out wrap-filled duplicate rows so metrics
    are exact sample means while every batch keeps the compiled shape.
    """
    t, b = target.shape[0], target.shape[1]
    dx, dy, pen = target[..., 0], target[..., 1], target[..., 2:5]
    fs = 1.0 - pen[..., 2]  # 0 from the first end-of-sketch row onward
    nll = gmm_nll(dx, dy, mp) * fs
    pen_ce = -jnp.sum(pen * jax.nn.log_softmax(mp.pen_logits, -1), axis=-1)
    if mask_pen:
        pen_ce = pen_ce * fs
    if weights is None:
        denom = float(max_seq_len * b)
    else:
        w = weights.astype(jnp.float32)
        nll = nll * w[None, :]
        pen_ce = pen_ce * w[None, :]
        denom = max_seq_len * jnp.maximum(jnp.sum(w), 1.0)
    return jnp.sum(nll) / denom, jnp.sum(pen_ce) / denom


def kl_loss(mu: jax.Array, presig: jax.Array,
            weights: Optional[jax.Array] = None) -> jax.Array:
    """KL(q(z|x) || N(0, I)), mean over batch and latent dims.

    ``weights`` (``[B]``, optional): weighted mean over the batch axis
    (see :func:`reconstruction_loss`)."""
    per = -0.5 * jnp.mean(1.0 + presig - jnp.square(mu) - jnp.exp(presig),
                          axis=-1)                       # [B]
    if weights is None:
        return jnp.mean(per)
    w = weights.astype(jnp.float32)
    return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1.0)


def kl_cost_with_floor(kl: jax.Array, kl_tolerance: float) -> jax.Array:
    """The reference's free-bits floor: cost saturates at kl_tolerance."""
    return jnp.maximum(kl, kl_tolerance)
