"""Mixture-density-network head and VAE losses (pure jnp).

TPU-native equivalent of the reference's ``get_mixture_coef`` /
``get_lossfunc`` / ``tf_2d_normal`` + KL terms (SURVEY.md §2 components 9
and 10; reference unreadable — semantics per the sketch-rnn paper,
arXiv:1704.03477 §3.2-3.3, and the canonical loss subtleties recorded in
SURVEY §7 'Hard parts'):

- the bivariate-GMM NLL is computed with a fused ``logsumexp`` over
  components (numerically stabler than the reference's pdf-then-log with
  an epsilon; identical up to the epsilon),
- the GMM term is masked to each sequence's true length via
  ``fs = 1 - p3(target)``; the pen-state cross-entropy is *unmasked* to
  Nmax during training and masked during eval — that asymmetry is the
  canonical behavior and is controlled by ``mask_pen``,
- both terms are normalized by ``max_seq_len * batch`` regardless of mask,
- KL has the reference's ``kl_tolerance`` floor (free bits).

Length-bucketed execution (ISSUE 4): ``target`` may be a bucket-padded
``[Tb, B, 5]`` stream with ``Tb < max_seq_len`` — the normalizer stays
``max_seq_len * batch`` (passed explicitly), so the masked GMM term is
EXACTLY the fixed-T value: the truncated tail lies beyond every row's
true length, where ``fs`` is 0 and every summand exactly 0.0, making
the per-example time-sums of :func:`reconstruction_sums` bitwise
independent of the pad length (the masked-pen eval CE likewise; the
weighted eval scalars stay bitwise equal through the real eval step —
tested — while the no-weights whole-batch scalar may pick up ~1e-7
reduction-reassociation noise from the differently-tiled fused
program). The one term that changes is the canonical
UNMASKED train pen CE: it sums CE over all padded steps, so truncating
to ``Tb`` drops the all-padding tail ``[Tb, Nmax)`` — per row that tail
contributes ``(Nmax - Tb) * ce_pad / (Nmax * B)`` where ``ce_pad`` is
the CE of the (constant) end-of-sketch pen target, a well-trained
model's cheapest prediction (|delta| bounded by ``(1 - Tb/Nmax) *
max_step_ce``; scripts/bucket_bench.py reports the measured gap).
Buckets off (``bucket_edges=()``, the default) is the exact-parity
mode: every batch arrives at full ``max_seq_len`` and nothing changes.

Everything here is elementwise/reduction math that XLA fuses straight into
the surrounding graph (SURVEY §2: "fuse into a single XLA graph").
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

LOG_2PI = 1.8378770664093453  # log(2*pi)


def _global_sum(x: jax.Array, axis_name: Optional[str]) -> jax.Array:
    """Sum across the named device axis (inside shard_map), else identity.

    With ``axis_name`` set, the losses below compute GLOBAL-batch sums and
    normalizers via ``psum``, so a per-shard call inside ``shard_map``
    yields exactly the single-device global-batch value — including the
    KL free-bits floor, which is nonlinear and would be wrong if applied
    per shard and averaged (SURVEY §2 component 18: the gradient
    all-reduce then falls out of AD through the psum).
    """
    return jax.lax.psum(x, axis_name) if axis_name else x


class MixtureParams(NamedTuple):
    """Per-step GMM + pen parameters; leading dims are arbitrary."""

    log_pi: jax.Array   # [..., M] log mixture weights (normalized)
    mu1: jax.Array      # [..., M]
    mu2: jax.Array      # [..., M]
    log_s1: jax.Array   # [..., M] log std of dx
    log_s2: jax.Array   # [..., M] log std of dy
    rho: jax.Array      # [..., M] correlation in (-1, 1)
    pen_logits: jax.Array  # [..., 3]


def get_mixture_params(raw: jax.Array, num_mixture: int) -> MixtureParams:
    """Split a ``[..., 6M+3]`` projection into normalized GMM parameters."""
    m = num_mixture
    if raw.shape[-1] != 6 * m + 3:
        raise ValueError(f"expected trailing dim {6 * m + 3}, got {raw.shape}")
    pen_logits = raw[..., :3]
    body = raw[..., 3:].reshape(*raw.shape[:-1], 6, m)
    logits, mu1, mu2, ls1, ls2, rho_raw = (body[..., j, :] for j in range(6))
    return MixtureParams(
        log_pi=jax.nn.log_softmax(logits, axis=-1),
        mu1=mu1,
        mu2=mu2,
        log_s1=ls1,
        log_s2=ls2,
        rho=jnp.tanh(rho_raw),
        pen_logits=pen_logits,
    )


def bivariate_normal_logpdf(dx: jax.Array, dy: jax.Array,
                            mp: MixtureParams) -> jax.Array:
    """Log pdf of (dx, dy) under each component; returns ``[..., M]``."""
    zx = (dx[..., None] - mp.mu1) * jnp.exp(-mp.log_s1)
    zy = (dy[..., None] - mp.mu2) * jnp.exp(-mp.log_s2)
    one_m_r2 = jnp.clip(1.0 - jnp.square(mp.rho), 1e-6, 1.0)
    z = zx * zx + zy * zy - 2.0 * mp.rho * zx * zy
    return (-z / (2.0 * one_m_r2)
            - 0.5 * jnp.log(one_m_r2) - mp.log_s1 - mp.log_s2 - LOG_2PI)


def gmm_nll(dx: jax.Array, dy: jax.Array, mp: MixtureParams) -> jax.Array:
    """Negative log-likelihood of offsets under the mixture, per step."""
    comp = mp.log_pi + bivariate_normal_logpdf(dx, dy, mp)
    return -jax.nn.logsumexp(comp, axis=-1)


def reconstruction_sums(mp: MixtureParams, target: jax.Array,
                        mask_pen: bool = False
                        ) -> Tuple[jax.Array, jax.Array]:
    """Per-example time-summed ``(offset_nll, pen_ce)``, each ``[B]``.

    The pre-normalization numerators of :func:`reconstruction_loss`,
    kept per-example so callers can take arbitrary weighted reductions
    over the batch axis (the per-class eval sweep reduces them against a
    ``[C, B]`` class mask in one matmul instead of re-running the
    forward pass per class).
    """
    dx, dy, pen = target[..., 0], target[..., 1], target[..., 2:5]
    fs = 1.0 - pen[..., 2]  # 0 from the first end-of-sketch row onward
    nll = gmm_nll(dx, dy, mp) * fs
    pen_ce = -jnp.sum(pen * jax.nn.log_softmax(mp.pen_logits, -1), axis=-1)
    if mask_pen:
        pen_ce = pen_ce * fs
    return jnp.sum(nll, axis=0), jnp.sum(pen_ce, axis=0)


def reconstruction_loss(mp: MixtureParams, target: jax.Array,
                        max_seq_len: int, mask_pen: bool = False,
                        weights: Optional[jax.Array] = None,
                        axis_name: Optional[str] = None
                        ) -> Tuple[jax.Array, jax.Array]:
    """Offset-GMM NLL + pen-state CE, canonical masking and normalization.

    ``target`` is time-major stroke-5 ``[T, B, 5]`` (the sequence shifted
    one step ahead of the decoder input). Returns scalars
    ``(offset_nll, pen_ce)``, each already divided by ``max_seq_len * B``.

    ``weights`` (``[B]``, optional) weights each example's contribution
    and replaces ``B`` with ``sum(weights)`` in the normalization — used
    by the eval sweep to zero out wrap-filled duplicate rows so metrics
    are exact sample means while every batch keeps the compiled shape.

    ``axis_name``: when called on a per-device batch shard inside
    ``shard_map``, numerators AND normalizers are psum'd over that mesh
    axis, so the returned scalars are exactly the global-batch values.

    Bucketed batches (``T < max_seq_len``, module docstring): the GMM
    term and masked pen CE are exact; the unmasked train pen CE drops
    its truncated all-padding tail. ``T > max_seq_len`` is always a
    caller bug (the normalizer would silently shrink the loss) and
    raises.
    """
    if target.shape[0] > max_seq_len:
        raise ValueError(
            f"target has {target.shape[0]} steps but max_seq_len="
            f"{max_seq_len}: the fixed normalizer would under-weight "
            f"every step; pass the model's true max_seq_len")
    b = target.shape[1]
    nll, pen_ce = reconstruction_sums(mp, target, mask_pen)  # each [B]
    if weights is None:
        denom = max_seq_len * _global_sum(jnp.float32(b), axis_name)
    else:
        w = weights.astype(jnp.float32)
        nll = nll * w
        pen_ce = pen_ce * w
        denom = max_seq_len * jnp.maximum(
            _global_sum(jnp.sum(w), axis_name), 1.0)
    return (_global_sum(jnp.sum(nll), axis_name) / denom,
            _global_sum(jnp.sum(pen_ce), axis_name) / denom)


def kl_per_example(mu: jax.Array, presig: jax.Array) -> jax.Array:
    """KL(q(z|x) || N(0, I)) per example (mean over latent dims), ``[B]``."""
    return -0.5 * jnp.mean(1.0 + presig - jnp.square(mu) - jnp.exp(presig),
                           axis=-1)


def kl_loss(mu: jax.Array, presig: jax.Array,
            weights: Optional[jax.Array] = None,
            axis_name: Optional[str] = None) -> jax.Array:
    """KL(q(z|x) || N(0, I)), mean over batch and latent dims.

    ``weights`` (``[B]``, optional): weighted mean over the batch axis;
    ``axis_name``: global-batch mean across a mesh axis (see
    :func:`reconstruction_loss`)."""
    per = kl_per_example(mu, presig)                     # [B]
    if weights is None:
        num = _global_sum(jnp.sum(per), axis_name)
        den = _global_sum(jnp.float32(per.shape[0]), axis_name)
        return num / den
    w = weights.astype(jnp.float32)
    return (_global_sum(jnp.sum(per * w), axis_name)
            / jnp.maximum(_global_sum(jnp.sum(w), axis_name), 1.0))


def kl_cost_with_floor(kl: jax.Array, kl_tolerance: float) -> jax.Array:
    """The reference's free-bits floor: cost saturates at kl_tolerance."""
    return jnp.maximum(kl, kl_tolerance)
