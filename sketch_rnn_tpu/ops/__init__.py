from sketch_rnn_tpu.ops.cells import (
    HyperLSTMCell,
    LayerNormLSTMCell,
    LSTMCell,
    make_cell,
)
from sketch_rnn_tpu.ops.rnn import bidirectional_rnn, make_dropout_masks, run_rnn

__all__ = [
    "HyperLSTMCell",
    "LSTMCell",
    "LayerNormLSTMCell",
    "bidirectional_rnn",
    "make_cell",
    "make_dropout_masks",
    "run_rnn",
]
