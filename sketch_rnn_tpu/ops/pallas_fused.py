"""Recompute-backward fused RNN kernels (LSTM + LayerNorm-LSTM).

SURVEY.md §2 component 5 names the cuDNN fused LSTM as the reference's
performance core; round 1 shipped a reserve-space kernel
(:mod:`sketch_rnn_tpu.ops.pallas_lstm`) whose own profiling showed the
``[T, B, 4H]`` gate reserve (262 MB at the flagship shape) losing to XLA
scan's recompute AD. These kernels are the measured fix (VERDICT r1 next
#3), redesigned around recomputation:

- the input projection ``x @ wx`` happens INSIDE the kernel per step, so
  no ``[T, B, 4H]`` array ever exists in HBM (neither projections nor
  gates — the r1 kernel's whole bandwidth bill),
- the forward saves only what the model needs anyway (``hs``) plus the
  pre-step cell states ``cs`` — the same ``[T, B, 2H]`` residual
  footprint as ``lax.scan``'s AD,
- the backward re-runs the two gate matmuls per step (cheap: the MXU is
  idle waiting on the sequential dependency anyway) and fuses the whole
  gate/LN backward into the same grid step,
- both kernels tile the batch (outer grid axis) so VMEM holds one
  ``[bt, H]`` working set regardless of global batch size; weight
  gradients accumulate across all grid steps.

The LayerNorm variant covers the FLAGSHIP decoder cell (``layer_norm``),
which the r1 kernel never did. Semantics are bit-compatible with
:class:`sketch_rnn_tpu.ops.cells.LayerNormLSTMCell` (per-gate LN, cell
LN, forget bias after LN, recurrent dropout on the candidate).

Mixed precision: pass ``wx``/``wh`` already cast (e.g. bfloat16); the
kernel casts activations to the weight dtype per matmul and accumulates
in float32 — the same contract as ``ops.linear.matmul``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LN_EPS = 1e-6  # matches ops.linear.layer_norm


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _batch_tile(b: int) -> int:
    """Largest VMEM-friendly divisor of the batch for the outer grid."""
    for cand in (128, 64, 32, 16, 8):
        if b % cand == 0:
            return cand
    return b


def _cast(x, w_ref):
    return x.astype(w_ref.dtype)


def _hash32(x):
    """murmur3-style avalanche over uint32 — a counter-based RNG in plain
    vector integer ops, so it runs identically on the TPU VPU and in
    interpret mode (pltpu.prng_* has no CPU lowering), and the backward
    kernel trivially regenerates the forward's bits from the same
    counters."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7feb352d)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846ca68b)
    x = x ^ (x >> 16)
    return x


def _prng_mask(seed_ref, t_real, ib, nbt, shape, keep_prob):
    """In-kernel recurrent-dropout mask: no [T, B, H] buffer ever exists
    in HBM (at the flagship batch that buffer is ~1 GB per RNN). The
    counter is unique per (time step, batch tile, element), so the
    backward regenerates the exact forward mask by using the same
    t_real. Counter wraparound at 2^32 only risks (harmless) mask
    collisions between far-apart elements."""
    bt, h = shape
    base = (seed_ref[0, 0].astype(jnp.uint32) * jnp.uint32(2654435761)
            + (t_real * nbt + ib).astype(jnp.uint32) * jnp.uint32(bt * h))
    idx = (jax.lax.broadcasted_iota(jnp.uint32, shape, 0) * jnp.uint32(h)
           + jax.lax.broadcasted_iota(jnp.uint32, shape, 1))
    bits = _hash32(base + idx)
    # Mosaic has no uint32->f32 cast; the 24-bit value fits int32 exactly
    bits24 = jax.lax.bitcast_convert_type(bits >> 8, jnp.int32)
    u = bits24.astype(jnp.float32) * (1.0 / (1 << 24))
    return (u < keep_prob).astype(jnp.float32) * (1.0 / keep_prob)


def _step_mask(mask_ref, seed_ref, t_real, ib, nbt, shape, keep_prob,
               mask_mode):
    if mask_mode == "streamed":
        return mask_ref[0]
    if mask_mode == "prng":
        return _prng_mask(seed_ref, t_real, ib, nbt, shape, keep_prob)
    return None


def _ln_fwd(u, gamma, beta):
    """Row layer-norm; returns (y, xhat, r) for reuse in the backward."""
    mu = jnp.mean(u, axis=-1, keepdims=True)
    xc = u - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + _LN_EPS)
    xhat = xc * r
    return xhat * gamma + beta, xhat, r


def _ln_bwd_input(dy, gamma, xhat, r):
    """Gradient w.r.t. the LN input (gamma/beta grads handled by caller)."""
    dxhat = dy * gamma
    return r * (dxhat
                - jnp.mean(dxhat, axis=-1, keepdims=True)
                - xhat * jnp.mean(dxhat * xhat, axis=-1, keepdims=True))


# ===========================================================================
# vanilla LSTM
# ===========================================================================


def _lstm_gates(pre, c_prev, mask, *, forget_bias):
    h = c_prev.shape[-1]
    i = jax.nn.sigmoid(pre[:, :h])
    g_u = jnp.tanh(pre[:, h:2 * h])
    g = g_u * mask if mask is not None else g_u
    f = jax.nn.sigmoid(pre[:, 2 * h:3 * h] + forget_bias)
    o = jax.nn.sigmoid(pre[:, 3 * h:])
    new_c = c_prev * f + i * g
    return i, g_u, f, o, new_c


def _lstm_fwd_kernel(x_ref, wx_ref, b_ref, wh_ref, c0_ref, h0_ref, mask_ref,
                     seed_ref, hs_ref, cs_ref, cT_ref, hT_ref,
                     c_scr, h_scr, *, forget_bias, mask_mode, keep_prob):
    ib = pl.program_id(0)
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(it == 0)
    def _():
        c_scr[:] = c0_ref[:]
        h_scr[:] = h0_ref[:]

    c, h = c_scr[:], h_scr[:]
    x = x_ref[0]
    pre = (jnp.dot(_cast(x, wx_ref), wx_ref[:],
                   preferred_element_type=jnp.float32)
           + b_ref[0]
           + jnp.dot(_cast(h, wh_ref), wh_ref[:],
                     preferred_element_type=jnp.float32))
    m = _step_mask(mask_ref, seed_ref, it, ib, pl.num_programs(0),
                   c.shape, keep_prob, mask_mode)
    _, _, _, o, new_c = _lstm_gates(pre, c, m, forget_bias=forget_bias)
    new_h = jnp.tanh(new_c) * o

    cs_ref[0] = c          # PRE-step cell state: the backward's residual
    c_scr[:] = new_c
    h_scr[:] = new_h
    hs_ref[0] = new_h

    @pl.when(it == nt - 1)
    def _():
        cT_ref[:] = new_c
        hT_ref[:] = new_h


def _lstm_bwd_kernel(x_ref, wx_ref, b_ref, wh_ref, cs_ref, hp_ref, mask_ref,
                     seed_ref, dhs_ref, dcT_ref, dhT_ref,
                     dx_ref, dwx_ref, db_ref, dwh_ref, dc0_ref, dh0_ref,
                     dc_scr, dh_scr, *, forget_bias, mask_mode, keep_prob):
    """Reverse-time inner grid: program (ib, it) handles step T-1-it."""
    ib = pl.program_id(0)
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when((ib == 0) & (it == 0))
    def _():
        dwx_ref[:] = jnp.zeros_like(dwx_ref)
        db_ref[:] = jnp.zeros_like(db_ref)
        dwh_ref[:] = jnp.zeros_like(dwh_ref)

    @pl.when(it == 0)
    def _():
        dc_scr[:] = dcT_ref[:]
        dh_scr[:] = dhT_ref[:]

    # ---- recompute the forward step (the whole point of this kernel) ----
    x, h_prev, c_prev = x_ref[0], hp_ref[0], cs_ref[0]
    pre = (jnp.dot(_cast(x, wx_ref), wx_ref[:],
                   preferred_element_type=jnp.float32)
           + b_ref[0]
           + jnp.dot(_cast(h_prev, wh_ref), wh_ref[:],
                     preferred_element_type=jnp.float32))
    # t_real = nt-1-it: the prng mask must be the one the FORWARD drew
    m = _step_mask(mask_ref, seed_ref, nt - 1 - it, ib,
                   pl.num_programs(0), c_prev.shape, keep_prob, mask_mode)
    i, g_u, f, o, new_c = _lstm_gates(pre, c_prev, m,
                                      forget_bias=forget_bias)
    tanh_c = jnp.tanh(new_c)

    # ---- backward gate math ----
    dh = dh_scr[:] + dhs_ref[0]
    dc = dc_scr[:] + dh * o * (1.0 - tanh_c * tanh_c)
    do = dh * tanh_c
    df = dc * c_prev
    g = g_u * m if m is not None else g_u
    di = dc * g
    dg_u = dc * i * m if m is not None else dc * i
    d_pre = jnp.concatenate([
        di * i * (1.0 - i),
        dg_u * (1.0 - g_u * g_u),
        df * f * (1.0 - f),
        do * o * (1.0 - o),
    ], axis=-1)

    d_pre_c = _cast(d_pre, wx_ref)
    dx_ref[0] = jnp.dot(d_pre_c, wx_ref[:].T,
                        preferred_element_type=jnp.float32)
    dwx_ref[:] += jnp.dot(_cast(x, wx_ref).T, d_pre_c,
                          preferred_element_type=jnp.float32)
    db_ref[0] += jnp.sum(d_pre, axis=0)
    dh_scr[:] = jnp.dot(d_pre_c, wh_ref[:].T,
                        preferred_element_type=jnp.float32)
    dwh_ref[:] += jnp.dot(_cast(h_prev, wh_ref).T, d_pre_c,
                          preferred_element_type=jnp.float32)
    dc_scr[:] = dc * f

    @pl.when(it == nt - 1)
    def _():
        dc0_ref[:] = dc_scr[:]
        dh0_ref[:] = dh_scr[:]


def _specs(bt, h, d, mask_mode, mask_shape):
    """Shared BlockSpec builders for the (batch-tile, time) grid."""
    step = lambda blk: pl.BlockSpec((1, *blk), lambda ib, it: (it, ib, 0),
                                    memory_space=pltpu.VMEM)
    tile = lambda blk: pl.BlockSpec(blk, lambda ib, it: (ib, 0),
                                    memory_space=pltpu.VMEM)
    whole = lambda shape: pl.BlockSpec(
        shape, lambda ib, it: tuple(0 for _ in shape),
        memory_space=pltpu.VMEM)
    mask_spec = step((bt, h)) if mask_mode == "streamed" \
        else whole(mask_shape)
    seed_spec = pl.BlockSpec((1, 1), lambda ib, it: (0, 0),
                             memory_space=pltpu.SMEM)
    return step, tile, whole, mask_spec, seed_spec


def _mask_args(masks, seed, t):
    """Resolve the dropout mode and its two (possibly dummy) operands."""
    if masks is not None and seed is not None:
        raise ValueError("pass masks or dropout_seed, not both")
    mode = "streamed" if masks is not None else \
        ("prng" if seed is not None else "none")
    mask_arg = masks if masks is not None \
        else jnp.zeros((t, 1, 1), jnp.float32)
    seed_arg = (jnp.asarray(seed, jnp.int32).reshape(1, 1)
                if seed is not None else jnp.zeros((1, 1), jnp.int32))
    return mode, mask_arg, seed_arg


def _seed_cotangent(seed):
    if seed is None:
        return None
    import numpy as np

    return np.zeros(jnp.shape(seed), dtype=jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 9))
def fused_lstm(xs: jax.Array, wx: jax.Array, b: jax.Array, wh: jax.Array,
               c0: jax.Array, h0: jax.Array, forget_bias: float = 1.0,
               masks: Optional[jax.Array] = None,
               dropout_seed: Optional[jax.Array] = None,
               keep_prob: float = 1.0
               ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Fused LSTM over a whole sequence, recompute-backward.

    Args:
      xs: ``[T, B, D]`` raw inputs (projection happens in-kernel).
      wx: ``[D, 4H]`` input weights (pre-cast for mixed precision).
      b: ``[4H]`` bias. wh: ``[H, 4H]`` recurrent weights.
      c0, h0: ``[B, H]`` initial carry. forget_bias: static.
      masks: optional ``[T, B, H]`` recurrent-dropout masks on the
        candidate gate (cotangent defined as zero).
      dropout_seed: optional int32 scalar — draw the masks INSIDE the
        kernel from the TPU PRNG instead (mutually exclusive with
        ``masks``; no mask buffer in HBM). ``keep_prob`` (static) is the
        keep probability for this mode.

    Returns ``(hs [T, B, H], (cT, hT))``.
    """
    hs, cT, hT, _ = _lstm_fwd_call(xs, wx, b, wh, c0, h0, forget_bias,
                                   masks, dropout_seed, keep_prob)
    return hs, (cT, hT)


def _lstm_fwd_call(xs, wx, b, wh, c0, h0, forget_bias, masks, seed,
                   keep_prob):
    t, bsz, d = xs.shape
    h = wh.shape[0]
    bt = _batch_tile(bsz)
    mode, mask_arg, seed_arg = _mask_args(masks, seed, t)
    b2 = b.reshape(1, -1).astype(jnp.float32)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, d, mode, mask_arg.shape)

    kernel = functools.partial(_lstm_fwd_kernel, forget_bias=forget_bias,
                               mask_mode=mode, keep_prob=keep_prob)
    hs, cs, cT, hT = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[step((bt, d)), whole(wx.shape), whole(b2.shape),
                  whole(wh.shape), tile((bt, h)), tile((bt, h)), mask_spec,
                  seed_spec],
        out_specs=(step((bt, h)), step((bt, h)), tile((bt, h)),
                   tile((bt, h))),
        out_shape=(
            jax.ShapeDtypeStruct((t, bsz, h), jnp.float32),  # hs
            jax.ShapeDtypeStruct((t, bsz, h), jnp.float32),  # cs (c_{t-1})
            jax.ShapeDtypeStruct((bsz, h), jnp.float32),     # cT
            jax.ShapeDtypeStruct((bsz, h), jnp.float32),     # hT
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32)],
        interpret=_interpret_default(),
    )(xs, wx, b2, wh, c0, h0, mask_arg, seed_arg)
    return hs, cT, hT, cs


def _fused_lstm_fwd(xs, wx, b, wh, c0, h0, forget_bias, masks,
                    dropout_seed, keep_prob):
    hs, cT, hT, cs = _lstm_fwd_call(xs, wx, b, wh, c0, h0, forget_bias,
                                    masks, dropout_seed, keep_prob)
    return (hs, (cT, hT)), (xs, wx, b, wh, h0, hs, cs, masks, dropout_seed)


def _fused_lstm_bwd(forget_bias, keep_prob, res, grads):
    xs, wx, b, wh, h0, hs, cs, masks, seed = res
    dhs, (dcT, dhT) = grads
    t, bsz, d = xs.shape
    h = wh.shape[0]
    bt = _batch_tile(bsz)
    mode, mask_arg, seed_arg = _mask_args(masks, seed, t)
    b2 = b.reshape(1, -1).astype(jnp.float32)
    h_prev = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    rev = lambda a: jnp.flip(a, axis=0)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, d, mode, mask_arg.shape)

    kernel = functools.partial(_lstm_bwd_kernel, forget_bias=forget_bias,
                               mask_mode=mode, keep_prob=keep_prob)
    dxs_rev, dwx, db2, dwh, dc0, dh0 = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[step((bt, d)), whole(wx.shape), whole(b2.shape),
                  whole(wh.shape), step((bt, h)), step((bt, h)), mask_spec,
                  seed_spec, step((bt, h)), tile((bt, h)), tile((bt, h))],
        out_specs=(step((bt, d)), whole(wx.shape), whole(b2.shape),
                   whole(wh.shape), tile((bt, h)), tile((bt, h))),
        out_shape=(
            jax.ShapeDtypeStruct((t, bsz, d), jnp.float32),
            jax.ShapeDtypeStruct(wx.shape, jnp.float32),
            jax.ShapeDtypeStruct(b2.shape, jnp.float32),
            jax.ShapeDtypeStruct(wh.shape, jnp.float32),
            jax.ShapeDtypeStruct((bsz, h), jnp.float32),
            jax.ShapeDtypeStruct((bsz, h), jnp.float32),
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32)],
        interpret=_interpret_default(),
    )(rev(xs), wx, b2, wh, rev(cs), rev(h_prev),
      rev(mask_arg) if mode == "streamed" else mask_arg, seed_arg,
      rev(dhs), dcT, dhT)
    dmasks = jnp.zeros_like(masks) if masks is not None else None
    # cotangent dtypes must match the primals (wx/wh may be pre-cast bf16)
    return (rev(dxs_rev).astype(xs.dtype), dwx.astype(wx.dtype),
            db2.reshape(-1).astype(b.dtype), dwh.astype(wh.dtype),
            dc0, dh0, dmasks, _seed_cotangent(seed))


fused_lstm.defvjp(_fused_lstm_fwd, _fused_lstm_bwd)


# ===========================================================================
# LayerNorm LSTM
# ===========================================================================


def _ln_gates(pre, c_prev, mask, gam, bet, gc, bc, *, forget_bias,
              want_residuals: bool):
    """Shared fwd gate math; optionally returns LN residuals for backward."""
    h = c_prev.shape[-1]
    ys, xhats, rs = [], [], []
    for j in range(4):
        y, xhat, r = _ln_fwd(pre[:, j * h:(j + 1) * h],
                             gam[j][None, :], bet[j][None, :])
        ys.append(y)
        xhats.append(xhat)
        rs.append(r)
    i = jax.nn.sigmoid(ys[0])
    g_u = jnp.tanh(ys[1])
    g = g_u * mask if mask is not None else g_u
    f = jax.nn.sigmoid(ys[2] + forget_bias)
    o = jax.nn.sigmoid(ys[3])
    new_c = c_prev * f + i * g
    yc, xhat_c, r_c = _ln_fwd(new_c, gc[0][None, :], bc[0][None, :])
    new_h = jnp.tanh(yc) * o
    if not want_residuals:
        return new_c, new_h
    return (i, g_u, f, o, new_c, new_h, yc, xhat_c, r_c, xhats, rs)


def _lnlstm_fwd_kernel(x_ref, wx_ref, wh_ref, gam_ref, bet_ref, gc_ref,
                       bc_ref, c0_ref, h0_ref, mask_ref, seed_ref,
                       hs_ref, cs_ref, cT_ref, hT_ref,
                       c_scr, h_scr, *, forget_bias, mask_mode, keep_prob):
    ib = pl.program_id(0)
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(it == 0)
    def _():
        c_scr[:] = c0_ref[:]
        h_scr[:] = h0_ref[:]

    c, h = c_scr[:], h_scr[:]
    pre = (jnp.dot(_cast(x_ref[0], wx_ref), wx_ref[:],
                   preferred_element_type=jnp.float32)
           + jnp.dot(_cast(h, wh_ref), wh_ref[:],
                     preferred_element_type=jnp.float32))
    m = _step_mask(mask_ref, seed_ref, it, ib, pl.num_programs(0),
                   c.shape, keep_prob, mask_mode)
    new_c, new_h = _ln_gates(pre, c, m, gam_ref[...], bet_ref[...],
                             gc_ref[...], bc_ref[...],
                             forget_bias=forget_bias,
                             want_residuals=False)
    cs_ref[0] = c
    c_scr[:] = new_c
    h_scr[:] = new_h
    hs_ref[0] = new_h

    @pl.when(it == nt - 1)
    def _():
        cT_ref[:] = new_c
        hT_ref[:] = new_h


def _lnlstm_bwd_kernel(x_ref, wx_ref, wh_ref, gam_ref, bet_ref, gc_ref,
                       bc_ref, cs_ref, hp_ref, mask_ref, seed_ref,
                       dhs_ref, dcT_ref, dhT_ref,
                       dx_ref, dwx_ref, dwh_ref, dgam_ref, dbet_ref,
                       dgc_ref, dbc_ref, dc0_ref, dh0_ref,
                       dc_scr, dh_scr, *, forget_bias, mask_mode,
                       keep_prob):
    ib = pl.program_id(0)
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when((ib == 0) & (it == 0))
    def _():
        dwx_ref[:] = jnp.zeros_like(dwx_ref)
        dwh_ref[:] = jnp.zeros_like(dwh_ref)
        dgam_ref[:] = jnp.zeros_like(dgam_ref)
        dbet_ref[:] = jnp.zeros_like(dbet_ref)
        dgc_ref[:] = jnp.zeros_like(dgc_ref)
        dbc_ref[:] = jnp.zeros_like(dbc_ref)

    @pl.when(it == 0)
    def _():
        dc_scr[:] = dcT_ref[:]
        dh_scr[:] = dhT_ref[:]

    x, h_prev, c_prev = x_ref[0], hp_ref[0], cs_ref[0]
    gam, bet = gam_ref[...], bet_ref[...]
    gc, bc = gc_ref[...], bc_ref[...]
    pre = (jnp.dot(_cast(x, wx_ref), wx_ref[:],
                   preferred_element_type=jnp.float32)
           + jnp.dot(_cast(h_prev, wh_ref), wh_ref[:],
                     preferred_element_type=jnp.float32))
    # t_real = nt-1-it: the prng mask must be the one the FORWARD drew
    m = _step_mask(mask_ref, seed_ref, nt - 1 - it, ib,
                   pl.num_programs(0), c_prev.shape, keep_prob, mask_mode)
    (i, g_u, f, o, new_c, _, yc, xhat_c, r_c, xhats, rs) = _ln_gates(
        pre, c_prev, m, gam, bet, gc, bc, forget_bias=forget_bias,
        want_residuals=True)
    tanh_yc = jnp.tanh(yc)

    dh = dh_scr[:] + dhs_ref[0]
    do = dh * tanh_yc
    dyc = dh * o * (1.0 - tanh_yc * tanh_yc)
    dgc_ref[0] += jnp.sum(dyc * xhat_c, axis=0)
    dbc_ref[0] += jnp.sum(dyc, axis=0)
    dc = dc_scr[:] + _ln_bwd_input(dyc, gc[0][None, :], xhat_c, r_c)

    df = dc * c_prev
    g = g_u * m if m is not None else g_u
    di = dc * g
    dg_u = dc * i * m if m is not None else dc * i
    dys = [di * i * (1.0 - i),
           dg_u * (1.0 - g_u * g_u),
           df * f * (1.0 - f),
           do * o * (1.0 - o)]
    d_pre_parts = []
    for j in range(4):
        dgam_ref[j] += jnp.sum(dys[j] * xhats[j], axis=0)
        dbet_ref[j] += jnp.sum(dys[j], axis=0)
        d_pre_parts.append(
            _ln_bwd_input(dys[j], gam[j][None, :], xhats[j], rs[j]))
    d_pre = jnp.concatenate(d_pre_parts, axis=-1)

    d_pre_c = _cast(d_pre, wx_ref)
    dx_ref[0] = jnp.dot(d_pre_c, wx_ref[:].T,
                        preferred_element_type=jnp.float32)
    dwx_ref[:] += jnp.dot(_cast(x, wx_ref).T, d_pre_c,
                          preferred_element_type=jnp.float32)
    dh_scr[:] = jnp.dot(d_pre_c, wh_ref[:].T,
                        preferred_element_type=jnp.float32)
    dwh_ref[:] += jnp.dot(_cast(h_prev, wh_ref).T, d_pre_c,
                          preferred_element_type=jnp.float32)
    dc_scr[:] = dc * f

    @pl.when(it == nt - 1)
    def _():
        dc0_ref[:] = dc_scr[:]
        dh0_ref[:] = dh_scr[:]


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 12))
def fused_ln_lstm(xs: jax.Array, wx: jax.Array, wh: jax.Array,
                  ln_gamma: jax.Array, ln_beta: jax.Array,
                  lnc_gamma: jax.Array, lnc_beta: jax.Array,
                  c0: jax.Array, h0: jax.Array, forget_bias: float = 1.0,
                  masks: Optional[jax.Array] = None,
                  dropout_seed: Optional[jax.Array] = None,
                  keep_prob: float = 1.0
                  ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Fused LayerNorm-LSTM (the flagship decoder cell), recompute-backward.

    Matches :class:`ops.cells.LayerNormLSTMCell`: per-gate LN with
    ``ln_gamma/ln_beta [4, H]``, cell-state LN with ``lnc_gamma/lnc_beta
    [H]``, no linear bias (the LN betas take that role), forget bias added
    after the LN, dropout on the candidate. Dropout comes as streamed
    ``masks`` or as in-kernel PRNG draws (``dropout_seed`` + static
    ``keep_prob`` — no mask buffer in HBM). Returns ``(hs, (cT, hT))``.
    """
    hs, cT, hT, _ = _lnlstm_fwd_call(xs, wx, wh, ln_gamma, ln_beta,
                                     lnc_gamma, lnc_beta, c0, h0,
                                     forget_bias, masks, dropout_seed,
                                     keep_prob)
    return hs, (cT, hT)


def _lnlstm_fwd_call(xs, wx, wh, gam, bet, gc, bc, c0, h0, forget_bias,
                     masks, seed, keep_prob):
    t, bsz, d = xs.shape
    h = wh.shape[0]
    bt = _batch_tile(bsz)
    mode, mask_arg, seed_arg = _mask_args(masks, seed, t)
    gc2, bc2 = gc.reshape(1, -1), bc.reshape(1, -1)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, d, mode, mask_arg.shape)

    kernel = functools.partial(_lnlstm_fwd_kernel, forget_bias=forget_bias,
                               mask_mode=mode, keep_prob=keep_prob)
    hs, cs, cT, hT = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[step((bt, d)), whole(wx.shape), whole(wh.shape),
                  whole(gam.shape), whole(bet.shape), whole(gc2.shape),
                  whole(bc2.shape), tile((bt, h)), tile((bt, h)), mask_spec,
                  seed_spec],
        out_specs=(step((bt, h)), step((bt, h)), tile((bt, h)),
                   tile((bt, h))),
        out_shape=(
            jax.ShapeDtypeStruct((t, bsz, h), jnp.float32),
            jax.ShapeDtypeStruct((t, bsz, h), jnp.float32),
            jax.ShapeDtypeStruct((bsz, h), jnp.float32),
            jax.ShapeDtypeStruct((bsz, h), jnp.float32),
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32)],
        interpret=_interpret_default(),
    )(xs, wx, wh, gam, bet, gc2, bc2, c0, h0, mask_arg, seed_arg)
    return hs, cT, hT, cs


def _fused_ln_lstm_fwd(xs, wx, wh, gam, bet, gc, bc, c0, h0, forget_bias,
                       masks, dropout_seed, keep_prob):
    hs, cT, hT, cs = _lnlstm_fwd_call(xs, wx, wh, gam, bet, gc, bc, c0, h0,
                                      forget_bias, masks, dropout_seed,
                                      keep_prob)
    return (hs, (cT, hT)), (xs, wx, wh, gam, bet, gc, bc, h0, hs, cs,
                            masks, dropout_seed)


def _fused_ln_lstm_bwd(forget_bias, keep_prob, res, grads):
    xs, wx, wh, gam, bet, gc, bc, h0, hs, cs, masks, seed = res
    dhs, (dcT, dhT) = grads
    t, bsz, d = xs.shape
    h = wh.shape[0]
    bt = _batch_tile(bsz)
    mode, mask_arg, seed_arg = _mask_args(masks, seed, t)
    gc2, bc2 = gc.reshape(1, -1), bc.reshape(1, -1)
    h_prev = jnp.concatenate([h0[None], hs[:-1]], axis=0)
    rev = lambda a: jnp.flip(a, axis=0)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, d, mode, mask_arg.shape)

    kernel = functools.partial(_lnlstm_bwd_kernel, forget_bias=forget_bias,
                               mask_mode=mode, keep_prob=keep_prob)
    (dxs_rev, dwx, dwh, dgam, dbet, dgc2, dbc2,
     dc0, dh0) = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[step((bt, d)), whole(wx.shape), whole(wh.shape),
                  whole(gam.shape), whole(bet.shape), whole(gc2.shape),
                  whole(bc2.shape), step((bt, h)), step((bt, h)), mask_spec,
                  seed_spec, step((bt, h)), tile((bt, h)), tile((bt, h))],
        out_specs=(step((bt, d)), whole(wx.shape), whole(wh.shape),
                   whole(gam.shape), whole(bet.shape), whole(gc2.shape),
                   whole(bc2.shape), tile((bt, h)), tile((bt, h))),
        out_shape=(
            jax.ShapeDtypeStruct((t, bsz, d), jnp.float32),
            jax.ShapeDtypeStruct(wx.shape, jnp.float32),
            jax.ShapeDtypeStruct(wh.shape, jnp.float32),
            jax.ShapeDtypeStruct(gam.shape, jnp.float32),
            jax.ShapeDtypeStruct(bet.shape, jnp.float32),
            jax.ShapeDtypeStruct(gc2.shape, jnp.float32),
            jax.ShapeDtypeStruct(bc2.shape, jnp.float32),
            jax.ShapeDtypeStruct((bsz, h), jnp.float32),
            jax.ShapeDtypeStruct((bsz, h), jnp.float32),
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32)],
        interpret=_interpret_default(),
    )(rev(xs), wx, wh, gam, bet, gc2, bc2, rev(cs), rev(h_prev),
      rev(mask_arg) if mode == "streamed" else mask_arg, seed_arg,
      rev(dhs), dcT, dhT)
    dmasks = jnp.zeros_like(masks) if masks is not None else None
    # cotangent dtypes must match the primals (wx/wh may be pre-cast bf16)
    return (rev(dxs_rev).astype(xs.dtype), dwx.astype(wx.dtype),
            dwh.astype(wh.dtype), dgam, dbet, dgc2.reshape(-1),
            dbc2.reshape(-1), dc0, dh0, dmasks, _seed_cotangent(seed))


fused_ln_lstm.defvjp(_fused_ln_lstm_fwd, _fused_ln_lstm_bwd)
