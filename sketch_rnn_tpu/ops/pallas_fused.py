"""Recompute-backward fused RNN kernels (LSTM + LayerNorm-LSTM).

SURVEY.md §2 component 5 names the cuDNN fused LSTM as the reference's
performance core; round 1 shipped a reserve-space kernel
(:mod:`sketch_rnn_tpu.ops.pallas_lstm`) whose own profiling showed the
``[T, B, 4H]`` gate reserve (262 MB at the flagship shape) losing to XLA
scan's recompute AD. These kernels are the measured fix (VERDICT r1 next
#3), redesigned around recomputation:

- the input projection ``x @ wx`` happens INSIDE the kernel per step, so
  no ``[T, B, 4H]`` array ever exists in HBM (neither projections nor
  gates — the r1 kernel's whole bandwidth bill),
- the forward saves only what the model needs anyway (``hs``) plus the
  pre-step cell states ``cs`` — the same ``[T, B, 2H]`` residual
  footprint as ``lax.scan``'s AD,
- the backward re-runs the two gate matmuls per step (cheap: the MXU is
  idle waiting on the sequential dependency anyway) and fuses the whole
  gate/LN backward into the same grid step,
- both kernels tile the batch (outer grid axis) so VMEM holds one
  ``[bt, H]`` working set regardless of global batch size; weight
  gradients accumulate across all grid steps.

The LayerNorm variant covers the FLAGSHIP decoder cell (``layer_norm``),
which the r1 kernel never did. Semantics are bit-compatible with
:class:`sketch_rnn_tpu.ops.cells.LayerNormLSTMCell` (per-gate LN, cell
LN, forget bias after LN, recurrent dropout on the candidate).

Mixed precision: pass ``wx``/``wh`` already cast (e.g. bfloat16); the
kernel casts activations to the weight dtype per matmul and accumulates
in float32 — the same contract as ``ops.linear.matmul``.

Measured negative result (v5e): unrolling TWO time steps per grid
program (halving the grid's time axis) made the latency-bound H=256
encoder SLOWER — 51.1 vs 45.7 ms fwd+bwd at B=4096/tile 512. Pallas
already overlaps block DMAs across grid steps, and in-kernel unrolling
neither shortens the sequential matmul dependency chain nor removes
any real overhead; it just doubles the live block working set. The
(batch-tile, single-time-step) grid is the right shape.

``residual_dtype`` (static, default float32) sets the storage dtype of
the saved streams — ``hs`` (which is ALSO the kernel's output, so the
model downstream of the RNN sees bf16-rounded activations) and the
pre-step carries: bfloat16 halves the kernels' HBM residual footprint
and bandwidth — at the flagship shape that is the difference between
fitting batch 4096 and OOM for the hyper cell. Carry state, gate math
and weight-grad accumulation stay float32; the in-kernel recurrence is
unrounded (each step reads the f32 VMEM carry, not the rounded HBM
copy), while outputs/residuals are rounded on write, so downstream
losses shift by bf16 rounding (~1e-2 relative) and gradients pick up
~0.4-1% relative noise from the recompute — the standard
mixed-precision activation trade.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LN_EPS = 1e-6  # matches ops.linear.layer_norm


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def vma_of(x) -> frozenset:
    """``x``'s varying-manual-axes (empty outside shard_map).

    The single place that knows about jax 0.9's ``typeof(...).vma``
    attribute; shared with ops.rnn's operand widening. On older jax
    (0.4.x: no ``jax.typeof``, no varying-manual-axes tracking) every
    array reports an empty vma, which disables the widening exactly
    where the concept does not exist.
    """
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return frozenset(getattr(typeof(x), "vma", None) or ())


def _sds(shape, dtype, ref):
    """ShapeDtypeStruct matching ``ref``'s varying-manual-axes.

    Inside ``shard_map`` (the data-parallel train step) every operand is
    varying over the data axis, and JAX 0.9 requires pallas_call outputs
    to declare their vma explicitly; outside shard_map this is a plain
    ShapeDtypeStruct.
    """
    vma = vma_of(ref)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _batch_tile(b: int, h: int, xb_bwd: bool = False,
                budget: int = 131072) -> int:
    """Largest VMEM-friendly divisor of the batch for the outer grid.

    Scaled inversely with the hidden size: the per-step working set is
    O(tile * 4h) f32 buffers, so ``tile * h`` is held under an
    empirically VMEM-safe budget (v5e, lstm/ln backward — the tightest
    kernel). Bigger tiles cut the grid-step count, which dominates for
    small-H cells: the H=256 encoder at B=4096 measured 56.6 ms fwd+bwd
    at tile 128 vs 46.2 ms at tile 512 (tile 1024 exceeds VMEM).

    ``xb_bwd``: the x_bias path adds two ``[tile, 4H]`` f32 blocks to
    the BACKWARD kernel (the bias operand and the in-output dxb
    accumulator), which puts the H=512/tile-256 backward right AT the
    16M scoped-VMEM line — it compiled or OOM'd (by 3.5-4M) depending
    on surrounding graph context (measured both on the same v5e), so
    the backward halves its budget for a deterministic margin. The
    forward has no grad accumulators and keeps the full budget; fwd
    and bwd are separate pallas_calls, so asymmetric tiles are fine
    (residual layout in HBM is tile-independent).
    """
    cap = max(8, (budget // 2 if xb_bwd else budget) // max(h, 1))
    for cand in (1024, 512, 256, 128, 64, 32, 16, 8):
        if cand <= cap and b % cand == 0:
            return cand
    return b


def _cast(x, w_ref):
    return x.astype(w_ref.dtype)


def _hash32(x):
    """murmur3-style avalanche over uint32 — a counter-based RNG in plain
    vector integer ops, so it runs identically on the TPU VPU and in
    interpret mode (pltpu.prng_* has no CPU lowering), and the backward
    kernel trivially regenerates the forward's bits from the same
    counters."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7feb352d)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846ca68b)
    x = x ^ (x >> 16)
    return x


def _prng_mask(seed_ref, t_real, ib, nbt, shape, keep_prob):
    """In-kernel recurrent-dropout mask: no [T, B, H] buffer ever exists
    in HBM (at the flagship batch that buffer is ~1 GB per RNN). The
    counter is unique per (time step, batch tile, element), so the
    backward regenerates the exact forward mask by using the same
    t_real. Counter wraparound at 2^32 only risks (harmless) mask
    collisions between far-apart elements."""
    bt, h = shape
    base = (seed_ref[0, 0].astype(jnp.uint32) * jnp.uint32(2654435761)
            + (t_real * nbt + ib).astype(jnp.uint32) * jnp.uint32(bt * h))
    idx = (jax.lax.broadcasted_iota(jnp.uint32, shape, 0) * jnp.uint32(h)
           + jax.lax.broadcasted_iota(jnp.uint32, shape, 1))
    bits = _hash32(base + idx)
    # Mosaic has no uint32->f32 cast; the 24-bit value fits int32 exactly
    bits24 = jax.lax.bitcast_convert_type(bits >> 8, jnp.int32)
    u = bits24.astype(jnp.float32) * (1.0 / (1 << 24))
    return (u < keep_prob).astype(jnp.float32) * (1.0 / keep_prob)


def _step_mask(mask_ref, seed_ref, t_real, ib, nbt, shape, keep_prob,
               mask_mode):
    if mask_mode == "streamed":
        return mask_ref[0]
    if mask_mode == "prng":
        return _prng_mask(seed_ref, t_real, ib, nbt, shape, keep_prob)
    return None


def _ln_fwd(u, gamma, beta):
    """Row layer-norm; returns (y, xhat, r) for reuse in the backward."""
    mu = jnp.mean(u, axis=-1, keepdims=True)
    xc = u - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + _LN_EPS)
    xhat = xc * r
    return xhat * gamma + beta, xhat, r


def _ln_bwd_input(dy, gamma, xhat, r):
    """Gradient w.r.t. the LN input (gamma/beta grads handled by caller)."""
    dxhat = dy * gamma
    return r * (dxhat
                - jnp.mean(dxhat, axis=-1, keepdims=True)
                - xhat * jnp.mean(dxhat * xhat, axis=-1, keepdims=True))


# ===========================================================================
# vanilla LSTM
# ===========================================================================


def _lstm_step_bwd_math(x, h_prev, c_prev, dh, dc_in, m, wx_ref, b_ref,
                        wh_ref, xb, *, forget_bias):
    """Shared LSTM backward step: recompute the forward from (x, carries),
    then the gate backward. Returns ``(d_pre [bt, 4H], dc_next)`` — the
    pre-activation gradient and the cell-carry gradient for step t-1.
    Used by both the full and the sequence-only backward kernels so the
    gate math cannot drift between them."""
    pre = (jnp.dot(_cast(x, wx_ref), wx_ref[:],
                   preferred_element_type=jnp.float32)
           + b_ref[0]
           + jnp.dot(_cast(h_prev, wh_ref), wh_ref[:],
                     preferred_element_type=jnp.float32))
    if xb is not None:
        pre = pre + xb
    i, g_u, f, o, new_c = _lstm_gates(pre, c_prev, m,
                                      forget_bias=forget_bias)
    tanh_c = jnp.tanh(new_c)
    dc = dc_in + dh * o * (1.0 - tanh_c * tanh_c)
    do = dh * tanh_c
    df = dc * c_prev
    g = g_u * m if m is not None else g_u
    di = dc * g
    dg_u = dc * i * m if m is not None else dc * i
    d_pre = jnp.concatenate([
        di * i * (1.0 - i),
        dg_u * (1.0 - g_u * g_u),
        df * f * (1.0 - f),
        do * o * (1.0 - o),
    ], axis=-1)
    return d_pre, dc * f


def _lstm_gates(pre, c_prev, mask, *, forget_bias):
    h = c_prev.shape[-1]
    i = jax.nn.sigmoid(pre[:, :h])
    g_u = jnp.tanh(pre[:, h:2 * h])
    g = g_u * mask if mask is not None else g_u
    f = jax.nn.sigmoid(pre[:, 2 * h:3 * h] + forget_bias)
    o = jax.nn.sigmoid(pre[:, 3 * h:])
    new_c = c_prev * f + i * g
    return i, g_u, f, o, new_c


def _lstm_fwd_kernel(x_ref, xb_ref, wx_ref, b_ref, wh_ref, c0_ref, h0_ref,
                     mask_ref, seed_ref, hs_ref, cs_ref, cT_ref, hT_ref,
                     c_scr, h_scr, *, forget_bias, mask_mode, keep_prob,
                     xb_mode):
    ib = pl.program_id(0)
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(it == 0)
    def _():
        c_scr[:] = c0_ref[:]
        h_scr[:] = h0_ref[:]

    c, h = c_scr[:], h_scr[:]
    x = x_ref[0]
    pre = (jnp.dot(_cast(x, wx_ref), wx_ref[:],
                   preferred_element_type=jnp.float32)
           + b_ref[0]
           + jnp.dot(_cast(h, wh_ref), wh_ref[:],
                     preferred_element_type=jnp.float32))
    if xb_mode:
        pre = pre + xb_ref[...]
    m = _step_mask(mask_ref, seed_ref, it, ib, pl.num_programs(0),
                   c.shape, keep_prob, mask_mode)
    _, _, _, o, new_c = _lstm_gates(pre, c, m, forget_bias=forget_bias)
    new_h = jnp.tanh(new_c) * o

    # PRE-step cell state: the backward's residual (possibly bf16 storage)
    cs_ref[0] = c.astype(cs_ref.dtype)
    c_scr[:] = new_c
    h_scr[:] = new_h
    hs_ref[0] = new_h.astype(hs_ref.dtype)

    @pl.when(it == nt - 1)
    def _():
        cT_ref[:] = new_c
        hT_ref[:] = new_h


def _lstm_bwd_kernel(x_ref, xb_ref, wx_ref, b_ref, wh_ref, cs_ref, hp_ref,
                     h00_ref, mask_ref, seed_ref, dhs_ref, dcT_ref, dhT_ref,
                     dx_ref, dxb_ref, dwx_ref, db_ref, dwh_ref, dc0_ref,
                     dh0_ref, dc_scr, dh_scr,
                     *, forget_bias, mask_mode, keep_prob, xb_mode):
    """Reverse-time inner grid: program (ib, it) handles step T-1-it.

    Operand streams arrive in NATURAL time order and are read through
    the reversed index maps of :func:`_rev_specs`; ``hp_ref`` is the
    ``hs`` stream at the clamped previous-step index, overridden with
    ``h00`` (the initial carry, residual-dtype) at the first real step.
    """
    ib = pl.program_id(0)
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when((ib == 0) & (it == 0))
    def _():
        dwx_ref[:] = jnp.zeros_like(dwx_ref)
        db_ref[:] = jnp.zeros_like(db_ref)
        dwh_ref[:] = jnp.zeros_like(dwh_ref)

    @pl.when(it == 0)
    def _():
        dc_scr[:] = dcT_ref[:]
        dh_scr[:] = dhT_ref[:]
        # dxb accumulates IN the (VMEM-resident, revisited) output block,
        # like the weight grads — a separate scratch would cost another
        # [bt, 4H] of VMEM and push the tile size down
        dxb_ref[...] = jnp.zeros_like(dxb_ref)

    # ---- recompute the forward step + gate backward (shared math) ----
    x = x_ref[0]
    h_prev = _prev_block(hp_ref, h00_ref, it, nt).astype(jnp.float32)
    c_prev = cs_ref[0].astype(jnp.float32)   # residuals may be bf16
    # t_real = nt-1-it: the prng mask must be the one the FORWARD drew
    m = _step_mask(mask_ref, seed_ref, nt - 1 - it, ib,
                   pl.num_programs(0), c_prev.shape, keep_prob, mask_mode)
    dh = dh_scr[:] + dhs_ref[0].astype(jnp.float32)
    d_pre, dc_next = _lstm_step_bwd_math(
        x, h_prev, c_prev, dh, dc_scr[:], m, wx_ref, b_ref, wh_ref,
        xb_ref[...] if xb_mode else None, forget_bias=forget_bias)

    if xb_mode:
        dxb_ref[...] += d_pre
    d_pre_c = _cast(d_pre, wx_ref)
    dx_ref[0] = jnp.dot(d_pre_c, wx_ref[:].T,
                        preferred_element_type=jnp.float32)
    dwx_ref[:] += jnp.dot(_cast(x, wx_ref).T, d_pre_c,
                          preferred_element_type=jnp.float32)
    db_ref[0] += jnp.sum(d_pre, axis=0)
    dh_scr[:] = jnp.dot(d_pre_c, wh_ref[:].T,
                        preferred_element_type=jnp.float32)
    dwh_ref[:] += jnp.dot(_cast(h_prev, wh_ref).T, d_pre_c,
                          preferred_element_type=jnp.float32)
    dc_scr[:] = dc_next

    @pl.when(it == nt - 1)
    def _():
        dc0_ref[:] = dc_scr[:]
        dh0_ref[:] = dh_scr[:]


def _specs(bt, h, mask_mode, mask_shape):
    """Shared BlockSpec builders for the (batch-tile, time) grid."""
    step = lambda blk: pl.BlockSpec((1, *blk), lambda ib, it: (it, ib, 0),
                                    memory_space=pltpu.VMEM)
    tile = lambda blk: pl.BlockSpec(blk, lambda ib, it: (ib, 0),
                                    memory_space=pltpu.VMEM)
    whole = _whole_spec
    mask_spec = step((bt, h)) if mask_mode == "streamed" \
        else whole(mask_shape)
    seed_spec = pl.BlockSpec((1, 1), lambda ib, it: (0, 0),
                             memory_space=pltpu.SMEM)
    return step, tile, whole, mask_spec, seed_spec


def _whole_spec(shape):
    """Whole-array BlockSpec (weights, biases, small operands); the one
    definition shared by the forward (_specs) and backward (_rev_specs)
    builders."""
    return pl.BlockSpec(shape, lambda ib, it: tuple(0 for _ in shape),
                        memory_space=pltpu.VMEM)


def _rev_specs(t, bt, h, mask_mode, mask_shape):
    """Reversed-time BlockSpec builders for the BACKWARD kernels.

    The backward grid iterates ``it = 0..T-1`` over REAL time step
    ``s = T-1-it``. Early rounds fed the kernels ``jnp.flip``-ed streams
    (plus a ``concatenate`` building ``h_prev``); those XLA copies cost
    ~20 ms per decoder backward at the flagship shape (measured,
    scripts/probe_dec_bwd_split.py — ~11% of the whole training step
    across both RNNs). Reading the NATURAL-ORDER streams through
    reversed index maps moves zero bytes instead:

    - ``rstep``: block ``s = t-1-it`` of a ``[T, B, *]`` stream.
    - ``rprev``: block ``s-1`` clamped to 0 — the previous-step entry of
      the ``hs`` stream, replacing the ``concat(h0, hs[:-1])`` copy; the
      kernel overrides the clamped duplicate read at ``s == 0``
      (``it == nt-1``) with the ``h0`` operand.
    - ``rmask``: streamed dropout masks, reversed like any step stream.

    The backward's OUTPUT ``dxs`` also uses ``rstep``, writing natural
    time order directly (no post-flip).
    """
    rstep = lambda blk: pl.BlockSpec(
        (1, *blk), lambda ib, it: (t - 1 - it, ib, 0),
        memory_space=pltpu.VMEM)
    rprev = lambda blk: pl.BlockSpec(
        (1, *blk), lambda ib, it: (jnp.maximum(t - 2 - it, 0), ib, 0),
        memory_space=pltpu.VMEM)
    rmask = (rstep((bt, h)) if mask_mode == "streamed"
             else _whole_spec(mask_shape))
    return rstep, rprev, rmask


def _prev_block(hp_ref, h00_ref, it, nt):
    """The previous-step hidden state under reversed indexing: the
    ``rprev`` block, overridden with the initial carry at the first real
    step (``it == nt-1``). ``h00`` arrives pre-cast to the residual
    dtype so step 0 recomputes from the SAME rounded value the old
    ``concat(h0.astype(hs.dtype), hs[:-1])`` path fed — bitwise parity
    with the flip-based layout."""
    return jnp.where(it == nt - 1, h00_ref[:], hp_ref[0])


def _mask_args(masks, seed):
    """Resolve the dropout mode and its two (possibly dummy) operands.

    The non-streamed dummy is ``[1, 1]``, NOT ``[t, 1, 1]``: Mosaic pads
    a block's two minor dims to the (8, 128) tile, so a ``[250, 1, 1]``
    whole-block dummy would cost 250*8*128*4 = 1.3M of VMEM for an
    operand the kernel never reads — measured as the difference between
    the seq-LSTM backward fitting (15M) and OOMing (16.11M) at tile
    1024 inside the full training graph.
    """
    if masks is not None and seed is not None:
        raise ValueError("pass masks or dropout_seed, not both")
    mode = "streamed" if masks is not None else \
        ("prng" if seed is not None else "none")
    mask_arg = masks if masks is not None \
        else jnp.zeros((1, 1), jnp.float32)
    seed_arg = (jnp.asarray(seed, jnp.int32).reshape(1, 1)
                if seed is not None else jnp.zeros((1, 1), jnp.int32))
    return mode, mask_arg, seed_arg


def _xb_args(x_bias, bt, tile, whole):
    """Resolve the per-example input-bias operand and its BlockSpec.

    ``x_bias [B, 4H]`` carries the projection of TIME-INVARIANT decoder
    inputs (the latent z and the class embedding): instead of streaming
    them through every step's ``[T, B, D]`` xs (and paying the wider
    in-kernel matmul plus the broadcast HBM traffic), the caller
    projects them ONCE and the kernel adds the result to each step's
    gate pre-activations.
    """
    if x_bias is None:
        return False, jnp.zeros((1, 1), jnp.float32), whole((1, 1))
    return True, x_bias, tile((bt, x_bias.shape[-1]))


def _xb_pair_args(x_bias, x_bias_hyper, bt, tile, whole):
    """Resolve the hyper kernel's TWO bias operands (main + aux LSTM).

    Shared by the fwd and bwd wrappers so their pallas_call operand lists
    cannot desynchronize.
    """
    xb_mode, xb_arg, xb_spec = _xb_args(x_bias, bt, tile, whole)
    if x_bias_hyper is not None:
        xbh_arg, xbh_spec = x_bias_hyper, tile((bt,
                                                x_bias_hyper.shape[-1]))
    else:
        xbh_arg, xbh_spec = xb_arg, xb_spec
    return xb_mode, xb_arg, xb_spec, xbh_arg, xbh_spec


def _seed_cotangent(seed):
    if seed is None:
        return None
    import numpy as np

    return np.zeros(jnp.shape(seed), dtype=jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 9, 10))
def fused_lstm(xs: jax.Array, wx: jax.Array, b: jax.Array, wh: jax.Array,
               c0: jax.Array, h0: jax.Array, forget_bias: float = 1.0,
               masks: Optional[jax.Array] = None,
               dropout_seed: Optional[jax.Array] = None,
               keep_prob: float = 1.0,
               residual_dtype=jnp.float32,
               x_bias: Optional[jax.Array] = None
               ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Fused LSTM over a whole sequence, recompute-backward.

    Args:
      xs: ``[T, B, D]`` raw inputs (projection happens in-kernel).
      wx: ``[D, 4H]`` input weights (pre-cast for mixed precision).
      b: ``[4H]`` bias. wh: ``[H, 4H]`` recurrent weights.
      c0, h0: ``[B, H]`` initial carry. forget_bias: static.
      masks: optional ``[T, B, H]`` recurrent-dropout masks on the
        candidate gate (cotangent defined as zero).
      dropout_seed: optional int32 scalar — draw the masks INSIDE the
        kernel from the TPU PRNG instead (mutually exclusive with
        ``masks``; no mask buffer in HBM). ``keep_prob`` (static) is the
        keep probability for this mode.
      residual_dtype: storage dtype for ``hs`` and the saved pre-step
        cell states (bfloat16 halves residual HBM; math stays f32).
      x_bias: optional ``[B, 4H]`` per-example bias added to every
        step's gate pre-activations — the projection of time-invariant
        inputs (z, class embedding), hoisted out of the per-step matmul.

    Returns ``(hs [T, B, H], (cT, hT))`` with ``hs`` in
    ``residual_dtype``; the final carry is always float32.
    """
    hs, cT, hT, _ = _lstm_fwd_call(xs, wx, b, wh, c0, h0, forget_bias,
                                   masks, dropout_seed, keep_prob,
                                   residual_dtype, x_bias)
    return hs, (cT, hT)


def _lstm_fwd_call(xs, wx, b, wh, c0, h0, forget_bias, masks, seed,
                   keep_prob, residual_dtype, x_bias):
    t, bsz, d = xs.shape
    h = wh.shape[0]
    bt = _batch_tile(bsz, h)
    mode, mask_arg, seed_arg = _mask_args(masks, seed)
    b2 = b.reshape(1, -1).astype(jnp.float32)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, mode, mask_arg.shape)
    xb_mode, xb_arg, xb_spec = _xb_args(x_bias, bt, tile, whole)

    kernel = functools.partial(_lstm_fwd_kernel, forget_bias=forget_bias,
                               mask_mode=mode, keep_prob=keep_prob,
                               xb_mode=xb_mode)
    hs, cs, cT, hT = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[step((bt, d)), xb_spec, whole(wx.shape), whole(b2.shape),
                  whole(wh.shape), tile((bt, h)), tile((bt, h)), mask_spec,
                  seed_spec],
        out_specs=(step((bt, h)), step((bt, h)), tile((bt, h)),
                   tile((bt, h))),
        out_shape=(
            _sds((t, bsz, h), residual_dtype, xs),  # hs
            _sds((t, bsz, h), residual_dtype, xs),  # cs (c_{t-1})
            _sds((bsz, h), jnp.float32, xs),        # cT
            _sds((bsz, h), jnp.float32, xs),        # hT
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32)],
        interpret=_interpret_default(),
    )(xs, xb_arg, wx, b2, wh, c0, h0, mask_arg, seed_arg)
    return hs, cT, hT, cs


def _fused_lstm_fwd(xs, wx, b, wh, c0, h0, forget_bias, masks,
                    dropout_seed, keep_prob, residual_dtype, x_bias):
    hs, cT, hT, cs = _lstm_fwd_call(xs, wx, b, wh, c0, h0, forget_bias,
                                    masks, dropout_seed, keep_prob,
                                    residual_dtype, x_bias)
    return (hs, (cT, hT)), (xs, wx, b, wh, h0, hs, cs, masks, dropout_seed,
                            x_bias)


def _fused_lstm_bwd(forget_bias, keep_prob, residual_dtype, res, grads):
    xs, wx, b, wh, h0, hs, cs, masks, seed, x_bias = res
    dhs, (dcT, dhT) = grads
    t, bsz, d = xs.shape
    h = wh.shape[0]
    bt = _batch_tile(bsz, h, xb_bwd=x_bias is not None)
    mode, mask_arg, seed_arg = _mask_args(masks, seed)
    b2 = b.reshape(1, -1).astype(jnp.float32)
    h00 = h0.astype(hs.dtype)  # see _prev_block: bitwise-matches the
    #                            old concat(h0.astype(hs.dtype), ...)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, mode, mask_arg.shape)
    rstep, rprev, rmask = _rev_specs(t, bt, h, mode, mask_arg.shape)
    xb_mode, xb_arg, xb_spec = _xb_args(x_bias, bt, tile, whole)

    kernel = functools.partial(_lstm_bwd_kernel, forget_bias=forget_bias,
                               mask_mode=mode, keep_prob=keep_prob,
                               xb_mode=xb_mode)
    dxs, dxb, dwx, db2, dwh, dc0, dh0 = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[rstep((bt, d)), xb_spec, whole(wx.shape), whole(b2.shape),
                  whole(wh.shape), rstep((bt, h)), rprev((bt, h)),
                  tile((bt, h)), rmask, seed_spec, rstep((bt, h)),
                  tile((bt, h)), tile((bt, h))],
        out_specs=(rstep((bt, d)), xb_spec, whole(wx.shape),
                   whole(b2.shape), whole(wh.shape), tile((bt, h)),
                   tile((bt, h))),
        out_shape=(
            _sds((t, bsz, d), jnp.float32, xs),
            _sds(xb_arg.shape, jnp.float32, xs),
            _sds(wx.shape, jnp.float32, xs),
            _sds(b2.shape, jnp.float32, xs),
            _sds(wh.shape, jnp.float32, xs),
            _sds((bsz, h), jnp.float32, xs),
            _sds((bsz, h), jnp.float32, xs),
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32)],
        interpret=_interpret_default(),
    )(xs, xb_arg, wx, b2, wh, cs, hs, h00, mask_arg, seed_arg,
      dhs, dcT, dhT)
    dmasks = jnp.zeros_like(masks) if masks is not None else None
    dxb_out = dxb.astype(x_bias.dtype) if x_bias is not None else None
    # cotangent dtypes must match the primals (wx/wh may be pre-cast bf16)
    return (dxs.astype(xs.dtype), dwx.astype(wx.dtype),
            db2.reshape(-1).astype(b.dtype), dwh.astype(wh.dtype),
            dc0, dh0, dmasks, _seed_cotangent(seed), dxb_out)


fused_lstm.defvjp(_fused_lstm_fwd, _fused_lstm_bwd)


# ===========================================================================
# sequence-only vanilla LSTM (the encoder's kernel)
# ===========================================================================
#
# The bidirectional encoder never uses the kernel's final carry (it
# gathers each sequence's last VALID state from hs) and its initial
# carries are constant zeros, so this variant drops the cT/hT outputs,
# the dcT/dhT cotangent operands and the dc0/dh0 gradient outputs.
# That removes four [tile, H] f32 blocks from the backward's VMEM
# budget — which is what lets the tile grow to 1024 at H=256
# (_batch_tile_seq): the full kernel's backward at tile 1024 measured
# 2.38M OVER the 16M scoped-VMEM limit, and halving the grid's batch
# axis is a direct win for the latency-bound encoder recurrence.


def _batch_tile_seq(b: int, h: int) -> int:
    """Batch tile for the sequence-only kernels: double the full
    kernels' budget (no final-carry / carry-grad / input-grad blocks
    in VMEM)."""
    return _batch_tile(b, h, budget=262144)


def _lstm_seq_fwd_kernel(x_ref, wx_ref, b_ref, wh_ref, c0_ref, h0_ref,
                         mask_ref, seed_ref, hs_ref, cs_ref,
                         c_scr, h_scr, *, forget_bias, mask_mode, keep_prob):
    ib = pl.program_id(0)
    it = pl.program_id(1)

    @pl.when(it == 0)
    def _():
        c_scr[:] = c0_ref[:]
        h_scr[:] = h0_ref[:]

    c, h = c_scr[:], h_scr[:]
    pre = (jnp.dot(_cast(x_ref[0], wx_ref), wx_ref[:],
                   preferred_element_type=jnp.float32)
           + b_ref[0]
           + jnp.dot(_cast(h, wh_ref), wh_ref[:],
                     preferred_element_type=jnp.float32))
    m = _step_mask(mask_ref, seed_ref, it, ib, pl.num_programs(0),
                   c.shape, keep_prob, mask_mode)
    _, _, _, o, new_c = _lstm_gates(pre, c, m, forget_bias=forget_bias)
    new_h = jnp.tanh(new_c) * o
    cs_ref[0] = c.astype(cs_ref.dtype)
    c_scr[:] = new_c
    h_scr[:] = new_h
    hs_ref[0] = new_h.astype(hs_ref.dtype)


def _lstm_seq_bwd_kernel(x_ref, wx_ref, b_ref, wh_ref, cs_ref, hp_ref,
                         h00_ref, mask_ref, seed_ref, dhs_ref,
                         dwx_ref, db_ref, dwh_ref,
                         dc_scr, dh_scr, *, forget_bias, mask_mode,
                         keep_prob):
    """Reverse-time grid; carries start from ZERO cotangents (no final
    carry was produced); the initial-carry AND input gradients are
    dropped (encoder contract: xs is data, carries are constants — only
    the weights are differentiated). Streams arrive in natural time
    order, read through :func:`_rev_specs` (no flip copies)."""
    ib = pl.program_id(0)
    it = pl.program_id(1)

    @pl.when((ib == 0) & (it == 0))
    def _():
        dwx_ref[:] = jnp.zeros_like(dwx_ref)
        db_ref[:] = jnp.zeros_like(db_ref)
        dwh_ref[:] = jnp.zeros_like(dwh_ref)

    @pl.when(it == 0)
    def _():
        dc_scr[:] = jnp.zeros_like(dc_scr)
        dh_scr[:] = jnp.zeros_like(dh_scr)

    x = x_ref[0]
    nt = pl.num_programs(1)
    h_prev = _prev_block(hp_ref, h00_ref, it, nt).astype(jnp.float32)
    c_prev = cs_ref[0].astype(jnp.float32)
    m = _step_mask(mask_ref, seed_ref, nt - 1 - it, ib,
                   pl.num_programs(0), c_prev.shape, keep_prob, mask_mode)
    dh = dh_scr[:] + dhs_ref[0].astype(jnp.float32)
    d_pre, dc_next = _lstm_step_bwd_math(
        x, h_prev, c_prev, dh, dc_scr[:], m, wx_ref, b_ref, wh_ref, None,
        forget_bias=forget_bias)

    d_pre_c = _cast(d_pre, wx_ref)
    dwx_ref[:] += jnp.dot(_cast(x, wx_ref).T, d_pre_c,
                          preferred_element_type=jnp.float32)
    db_ref[0] += jnp.sum(d_pre, axis=0)
    dh_scr[:] = jnp.dot(d_pre_c, wh_ref[:].T,
                        preferred_element_type=jnp.float32)
    dwh_ref[:] += jnp.dot(_cast(h_prev, wh_ref).T, d_pre_c,
                          preferred_element_type=jnp.float32)
    dc_scr[:] = dc_next


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 9, 10))
def fused_lstm_seq(xs: jax.Array, wx: jax.Array, b: jax.Array,
                   wh: jax.Array, c0: jax.Array, h0: jax.Array,
                   forget_bias: float = 1.0,
                   masks: Optional[jax.Array] = None,
                   dropout_seed: Optional[jax.Array] = None,
                   keep_prob: float = 1.0,
                   residual_dtype=jnp.float32) -> jax.Array:
    """Sequence-only fused LSTM: returns ``hs`` alone (no final carry).

    For recurrences where only the WEIGHTS are differentiated — the
    bidirectional encoder: xs is the data batch, carries are constant
    zeros, the final state is gathered from ``hs``. The xs/c0/h0
    cotangents are defined as ZERO (dropping their gradient blocks is
    what buys the doubled backward batch tile) — passing differentiated
    inputs or carries here silently loses their gradients, so callers
    must guard (ops.rnn's ``need_final=False`` contract does).
    Same argument semantics as :func:`fused_lstm` otherwise.
    """
    hs, _ = _lstm_seq_fwd_call(xs, wx, b, wh, c0, h0, forget_bias, masks,
                               dropout_seed, keep_prob, residual_dtype)
    return hs


def _lstm_seq_fwd_call(xs, wx, b, wh, c0, h0, forget_bias, masks, seed,
                       keep_prob, residual_dtype):
    t, bsz, d = xs.shape
    h = wh.shape[0]
    bt = _batch_tile_seq(bsz, h)
    mode, mask_arg, seed_arg = _mask_args(masks, seed)
    b2 = b.reshape(1, -1).astype(jnp.float32)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, mode, mask_arg.shape)

    kernel = functools.partial(_lstm_seq_fwd_kernel,
                               forget_bias=forget_bias, mask_mode=mode,
                               keep_prob=keep_prob)
    hs, cs = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[step((bt, d)), whole(wx.shape), whole(b2.shape),
                  whole(wh.shape), tile((bt, h)), tile((bt, h)), mask_spec,
                  seed_spec],
        out_specs=(step((bt, h)), step((bt, h))),
        out_shape=(
            _sds((t, bsz, h), residual_dtype, xs),
            _sds((t, bsz, h), residual_dtype, xs),
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32)],
        interpret=_interpret_default(),
    )(xs, wx, b2, wh, c0, h0, mask_arg, seed_arg)
    return hs, cs


def _fused_lstm_seq_fwd(xs, wx, b, wh, c0, h0, forget_bias, masks,
                        dropout_seed, keep_prob, residual_dtype):
    hs, cs = _lstm_seq_fwd_call(xs, wx, b, wh, c0, h0, forget_bias, masks,
                                dropout_seed, keep_prob, residual_dtype)
    return hs, (xs, wx, b, wh, c0, h0, hs, cs, masks, dropout_seed)


def _fused_lstm_seq_bwd(forget_bias, keep_prob, residual_dtype, res, dhs):
    xs, wx, b, wh, c0, h0, hs, cs, masks, seed = res
    t, bsz, d = xs.shape
    h = wh.shape[0]
    bt = _batch_tile_seq(bsz, h)
    mode, mask_arg, seed_arg = _mask_args(masks, seed)
    b2 = b.reshape(1, -1).astype(jnp.float32)
    h00 = h0.astype(hs.dtype)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, mode, mask_arg.shape)
    rstep, rprev, rmask = _rev_specs(t, bt, h, mode, mask_arg.shape)

    kernel = functools.partial(_lstm_seq_bwd_kernel,
                               forget_bias=forget_bias, mask_mode=mode,
                               keep_prob=keep_prob)
    dwx, db2, dwh = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[rstep((bt, d)), whole(wx.shape), whole(b2.shape),
                  whole(wh.shape), rstep((bt, h)), rprev((bt, h)),
                  tile((bt, h)), rmask, seed_spec, rstep((bt, h))],
        out_specs=(whole(wx.shape), whole(b2.shape), whole(wh.shape)),
        out_shape=(
            _sds(wx.shape, jnp.float32, xs),
            _sds(b2.shape, jnp.float32, xs),
            _sds(wh.shape, jnp.float32, xs),
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32)],
        interpret=_interpret_default(),
    )(xs, wx, b2, wh, cs, hs, h00, mask_arg, seed_arg, dhs)
    dmasks = jnp.zeros_like(masks) if masks is not None else None
    return (jnp.zeros_like(xs), dwx.astype(wx.dtype),
            db2.reshape(-1).astype(b.dtype), dwh.astype(wh.dtype),
            jnp.zeros_like(c0), jnp.zeros_like(h0), dmasks,
            _seed_cotangent(seed))


fused_lstm_seq.defvjp(_fused_lstm_seq_fwd, _fused_lstm_seq_bwd)


# ===========================================================================
# LayerNorm LSTM
# ===========================================================================


def _ln_gates(pre, c_prev, mask, gam, bet, gc, bc, *, forget_bias,
              want_residuals: bool):
    """Shared fwd gate math; optionally returns LN residuals for backward."""
    h = c_prev.shape[-1]
    ys, xhats, rs = [], [], []
    for j in range(4):
        y, xhat, r = _ln_fwd(pre[:, j * h:(j + 1) * h],
                             gam[j][None, :], bet[j][None, :])
        ys.append(y)
        xhats.append(xhat)
        rs.append(r)
    i = jax.nn.sigmoid(ys[0])
    g_u = jnp.tanh(ys[1])
    g = g_u * mask if mask is not None else g_u
    f = jax.nn.sigmoid(ys[2] + forget_bias)
    o = jax.nn.sigmoid(ys[3])
    new_c = c_prev * f + i * g
    yc, xhat_c, r_c = _ln_fwd(new_c, gc[0][None, :], bc[0][None, :])
    new_h = jnp.tanh(yc) * o
    if not want_residuals:
        return new_c, new_h
    return (i, g_u, f, o, new_c, new_h, yc, xhat_c, r_c, xhats, rs)


def _lnlstm_fwd_kernel(x_ref, xb_ref, wx_ref, wh_ref, gam_ref, bet_ref,
                       gc_ref, bc_ref, c0_ref, h0_ref, mask_ref, seed_ref,
                       hs_ref, cs_ref, cT_ref, hT_ref,
                       c_scr, h_scr, *, forget_bias, mask_mode, keep_prob,
                       xb_mode):
    ib = pl.program_id(0)
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(it == 0)
    def _():
        c_scr[:] = c0_ref[:]
        h_scr[:] = h0_ref[:]

    c, h = c_scr[:], h_scr[:]
    pre = (jnp.dot(_cast(x_ref[0], wx_ref), wx_ref[:],
                   preferred_element_type=jnp.float32)
           + jnp.dot(_cast(h, wh_ref), wh_ref[:],
                     preferred_element_type=jnp.float32))
    if xb_mode:
        pre = pre + xb_ref[...]
    m = _step_mask(mask_ref, seed_ref, it, ib, pl.num_programs(0),
                   c.shape, keep_prob, mask_mode)
    new_c, new_h = _ln_gates(pre, c, m, gam_ref[...], bet_ref[...],
                             gc_ref[...], bc_ref[...],
                             forget_bias=forget_bias,
                             want_residuals=False)
    cs_ref[0] = c.astype(cs_ref.dtype)
    c_scr[:] = new_c
    h_scr[:] = new_h
    hs_ref[0] = new_h.astype(hs_ref.dtype)

    @pl.when(it == nt - 1)
    def _():
        cT_ref[:] = new_c
        hT_ref[:] = new_h


def _ln_lstm_bwd_gates(dh, dc_carry, c_prev, m, ln_res, gam, gc,
                       dgam_ref, dbet_ref, dgc_ref, dbc_ref):
    """Backward through the LayerNorm-LSTM gate block (shared by the
    layer_norm and hyper kernels).

    ``ln_res`` is ``_ln_gates(..., want_residuals=True)``'s output for the
    recomputed step. Accumulates the four LN parameter grads into the
    given refs in place and returns ``(d_pre [bt, 4H], dc_next)`` — the
    gradient w.r.t. the pre-LN gate activations and the cell-state carry
    gradient to propagate to step t-1.
    """
    (i, g_u, f, o, _new_c, _new_h, yc, xhat_c, r_c, xhats, rs) = ln_res
    tanh_yc = jnp.tanh(yc)
    do = dh * tanh_yc
    dyc = dh * o * (1.0 - tanh_yc * tanh_yc)
    dgc_ref[0] += jnp.sum(dyc * xhat_c, axis=0)
    dbc_ref[0] += jnp.sum(dyc, axis=0)
    dc = dc_carry + _ln_bwd_input(dyc, gc[0][None, :], xhat_c, r_c)

    df = dc * c_prev
    g = g_u * m if m is not None else g_u
    di = dc * g
    dg_u = dc * i * m if m is not None else dc * i
    dys = [di * i * (1.0 - i),
           dg_u * (1.0 - g_u * g_u),
           df * f * (1.0 - f),
           do * o * (1.0 - o)]
    d_pre_parts = []
    for j in range(4):
        dgam_ref[j] += jnp.sum(dys[j] * xhats[j], axis=0)
        dbet_ref[j] += jnp.sum(dys[j], axis=0)
        d_pre_parts.append(
            _ln_bwd_input(dys[j], gam[j][None, :], xhats[j], rs[j]))
    return jnp.concatenate(d_pre_parts, axis=-1), dc * f


def _lnlstm_bwd_kernel(x_ref, xb_ref, wx_ref, wh_ref, gam_ref, bet_ref,
                       gc_ref, bc_ref, cs_ref, hp_ref, h00_ref, mask_ref,
                       seed_ref, dhs_ref, dcT_ref, dhT_ref,
                       dx_ref, dxb_ref, dwx_ref, dwh_ref, dgam_ref,
                       dbet_ref, dgc_ref, dbc_ref, dc0_ref, dh0_ref,
                       dc_scr, dh_scr, *, forget_bias, mask_mode,
                       keep_prob, xb_mode):
    """Reverse-time grid over natural-order streams (see _rev_specs)."""
    ib = pl.program_id(0)
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when((ib == 0) & (it == 0))
    def _():
        dwx_ref[:] = jnp.zeros_like(dwx_ref)
        dwh_ref[:] = jnp.zeros_like(dwh_ref)
        dgam_ref[:] = jnp.zeros_like(dgam_ref)
        dbet_ref[:] = jnp.zeros_like(dbet_ref)
        dgc_ref[:] = jnp.zeros_like(dgc_ref)
        dbc_ref[:] = jnp.zeros_like(dbc_ref)

    @pl.when(it == 0)
    def _():
        dc_scr[:] = dcT_ref[:]
        dh_scr[:] = dhT_ref[:]
        # dxb accumulates IN the (VMEM-resident, revisited) output block,
        # like the weight grads — a separate scratch would cost another
        # [bt, 4H] of VMEM and push the tile size down
        dxb_ref[...] = jnp.zeros_like(dxb_ref)

    x = x_ref[0]
    h_prev = _prev_block(hp_ref, h00_ref, it, nt).astype(jnp.float32)
    c_prev = cs_ref[0].astype(jnp.float32)   # residuals may be bf16
    gam, bet = gam_ref[...], bet_ref[...]
    gc, bc = gc_ref[...], bc_ref[...]
    pre = (jnp.dot(_cast(x, wx_ref), wx_ref[:],
                   preferred_element_type=jnp.float32)
           + jnp.dot(_cast(h_prev, wh_ref), wh_ref[:],
                     preferred_element_type=jnp.float32))
    if xb_mode:
        pre = pre + xb_ref[...]
    # t_real = nt-1-it: the prng mask must be the one the FORWARD drew
    m = _step_mask(mask_ref, seed_ref, nt - 1 - it, ib,
                   pl.num_programs(0), c_prev.shape, keep_prob, mask_mode)
    ln_res = _ln_gates(pre, c_prev, m, gam, bet, gc, bc,
                       forget_bias=forget_bias, want_residuals=True)

    dh = dh_scr[:] + dhs_ref[0].astype(jnp.float32)
    d_pre, dc_next = _ln_lstm_bwd_gates(dh, dc_scr[:], c_prev, m, ln_res,
                                        gam, gc, dgam_ref, dbet_ref,
                                        dgc_ref, dbc_ref)
    if xb_mode:
        dxb_ref[...] += d_pre

    d_pre_c = _cast(d_pre, wx_ref)
    dx_ref[0] = jnp.dot(d_pre_c, wx_ref[:].T,
                        preferred_element_type=jnp.float32)
    dwx_ref[:] += jnp.dot(_cast(x, wx_ref).T, d_pre_c,
                          preferred_element_type=jnp.float32)
    dh_scr[:] = jnp.dot(d_pre_c, wh_ref[:].T,
                        preferred_element_type=jnp.float32)
    dwh_ref[:] += jnp.dot(_cast(h_prev, wh_ref).T, d_pre_c,
                          preferred_element_type=jnp.float32)
    dc_scr[:] = dc_next

    @pl.when(it == nt - 1)
    def _():
        dc0_ref[:] = dc_scr[:]
        dh0_ref[:] = dh_scr[:]


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 12, 13))
def fused_ln_lstm(xs: jax.Array, wx: jax.Array, wh: jax.Array,
                  ln_gamma: jax.Array, ln_beta: jax.Array,
                  lnc_gamma: jax.Array, lnc_beta: jax.Array,
                  c0: jax.Array, h0: jax.Array, forget_bias: float = 1.0,
                  masks: Optional[jax.Array] = None,
                  dropout_seed: Optional[jax.Array] = None,
                  keep_prob: float = 1.0,
                  residual_dtype=jnp.float32,
                  x_bias: Optional[jax.Array] = None
                  ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Fused LayerNorm-LSTM (the flagship decoder cell), recompute-backward.

    Matches :class:`ops.cells.LayerNormLSTMCell`: per-gate LN with
    ``ln_gamma/ln_beta [4, H]``, cell-state LN with ``lnc_gamma/lnc_beta
    [H]``, no linear bias (the LN betas take that role), forget bias added
    after the LN, dropout on the candidate. Dropout comes as streamed
    ``masks`` or as in-kernel PRNG draws (``dropout_seed`` + static
    ``keep_prob`` — no mask buffer in HBM). ``x_bias [B, 4H]``: optional
    per-example bias added to every step's gate pre-activations — the
    projection of time-invariant inputs (z, class embedding), hoisted
    out of the per-step matmul. Returns ``(hs, (cT, hT))`` with ``hs``
    stored in ``residual_dtype``.
    """
    hs, cT, hT, _ = _lnlstm_fwd_call(xs, wx, wh, ln_gamma, ln_beta,
                                     lnc_gamma, lnc_beta, c0, h0,
                                     forget_bias, masks, dropout_seed,
                                     keep_prob, residual_dtype, x_bias)
    return hs, (cT, hT)


def _lnlstm_fwd_call(xs, wx, wh, gam, bet, gc, bc, c0, h0, forget_bias,
                     masks, seed, keep_prob, residual_dtype, x_bias):
    t, bsz, d = xs.shape
    h = wh.shape[0]
    bt = _batch_tile(bsz, h)
    mode, mask_arg, seed_arg = _mask_args(masks, seed)
    gc2, bc2 = gc.reshape(1, -1), bc.reshape(1, -1)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, mode, mask_arg.shape)
    xb_mode, xb_arg, xb_spec = _xb_args(x_bias, bt, tile, whole)

    kernel = functools.partial(_lnlstm_fwd_kernel, forget_bias=forget_bias,
                               mask_mode=mode, keep_prob=keep_prob,
                               xb_mode=xb_mode)
    hs, cs, cT, hT = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[step((bt, d)), xb_spec, whole(wx.shape), whole(wh.shape),
                  whole(gam.shape), whole(bet.shape), whole(gc2.shape),
                  whole(bc2.shape), tile((bt, h)), tile((bt, h)), mask_spec,
                  seed_spec],
        out_specs=(step((bt, h)), step((bt, h)), tile((bt, h)),
                   tile((bt, h))),
        out_shape=(
            _sds((t, bsz, h), residual_dtype, xs),
            _sds((t, bsz, h), residual_dtype, xs),
            _sds((bsz, h), jnp.float32, xs),
            _sds((bsz, h), jnp.float32, xs),
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32)],
        interpret=_interpret_default(),
    )(xs, xb_arg, wx, wh, gam, bet, gc2, bc2, c0, h0, mask_arg, seed_arg)
    return hs, cT, hT, cs


def _fused_ln_lstm_fwd(xs, wx, wh, gam, bet, gc, bc, c0, h0, forget_bias,
                       masks, dropout_seed, keep_prob, residual_dtype,
                       x_bias):
    hs, cT, hT, cs = _lnlstm_fwd_call(xs, wx, wh, gam, bet, gc, bc, c0, h0,
                                      forget_bias, masks, dropout_seed,
                                      keep_prob, residual_dtype, x_bias)
    return (hs, (cT, hT)), (xs, wx, wh, gam, bet, gc, bc, h0, hs, cs,
                            masks, dropout_seed, x_bias)


def _fused_ln_lstm_bwd(forget_bias, keep_prob, residual_dtype, res, grads):
    xs, wx, wh, gam, bet, gc, bc, h0, hs, cs, masks, seed, x_bias = res
    dhs, (dcT, dhT) = grads
    t, bsz, d = xs.shape
    h = wh.shape[0]
    bt = _batch_tile(bsz, h, xb_bwd=x_bias is not None)
    mode, mask_arg, seed_arg = _mask_args(masks, seed)
    gc2, bc2 = gc.reshape(1, -1), bc.reshape(1, -1)
    h00 = h0.astype(hs.dtype)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, mode, mask_arg.shape)
    rstep, rprev, rmask = _rev_specs(t, bt, h, mode, mask_arg.shape)
    xb_mode, xb_arg, xb_spec = _xb_args(x_bias, bt, tile, whole)

    kernel = functools.partial(_lnlstm_bwd_kernel, forget_bias=forget_bias,
                               mask_mode=mode, keep_prob=keep_prob,
                               xb_mode=xb_mode)
    (dxs, dxb, dwx, dwh, dgam, dbet, dgc2, dbc2,
     dc0, dh0) = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[rstep((bt, d)), xb_spec, whole(wx.shape), whole(wh.shape),
                  whole(gam.shape), whole(bet.shape), whole(gc2.shape),
                  whole(bc2.shape), rstep((bt, h)), rprev((bt, h)),
                  tile((bt, h)), rmask, seed_spec, rstep((bt, h)),
                  tile((bt, h)), tile((bt, h))],
        out_specs=(rstep((bt, d)), xb_spec, whole(wx.shape),
                   whole(wh.shape), whole(gam.shape), whole(bet.shape),
                   whole(gc2.shape), whole(bc2.shape), tile((bt, h)),
                   tile((bt, h))),
        out_shape=(
            _sds((t, bsz, d), jnp.float32, xs),
            _sds(xb_arg.shape, jnp.float32, xs),
            _sds(wx.shape, jnp.float32, xs),
            _sds(wh.shape, jnp.float32, xs),
            _sds(gam.shape, jnp.float32, xs),
            _sds(bet.shape, jnp.float32, xs),
            _sds(gc2.shape, jnp.float32, xs),
            _sds(bc2.shape, jnp.float32, xs),
            _sds((bsz, h), jnp.float32, xs),
            _sds((bsz, h), jnp.float32, xs),
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32)],
        interpret=_interpret_default(),
    )(xs, xb_arg, wx, wh, gam, bet, gc2, bc2, cs, hs, h00,
      mask_arg, seed_arg, dhs, dcT, dhT)
    dmasks = jnp.zeros_like(masks) if masks is not None else None
    dxb_out = dxb.astype(x_bias.dtype) if x_bias is not None else None
    # cotangent dtypes must match the primals (wx/wh may be pre-cast bf16)
    return (dxs.astype(xs.dtype), dwx.astype(wx.dtype),
            dwh.astype(wh.dtype), dgam, dbet, dgc2.reshape(-1),
            dbc2.reshape(-1), dc0, dh0, dmasks, _seed_cotangent(seed),
            dxb_out)


fused_ln_lstm.defvjp(_fused_ln_lstm_fwd, _fused_ln_lstm_bwd)


# ===========================================================================
# HyperLSTM (layer-norm variant — the default and only one make_cell builds)
# ===========================================================================
#
# Per step (ops/cells.py HyperLSTMCell.step_pre):
#   hyper_pre = x @ wxh_x + h @ wxh_h + b_h + hyper_h @ whh     (aux LSTM)
#   (hyper_c, hyper_h) <- vanilla LSTM gates
#   z_p  = hyper_h @ w_hz_p (+ b_hz_p for p in {x, h})           [B, 4e]
#   s_p  = z_p @ zd_p                                            [B, 4H]
#   pre  = s_x * (x @ wx) + s_h * (h @ wh) + s_b + b
#   then per-gate LN -> gates -> cell LN -> h, exactly LayerNormLSTM.
#
# The cell's per-gate [e, h] scale projections run in BLOCK form (four
# small matmuls per path, see _block_scale): an earlier dense
# block-diagonal [4e, 4H] design made them one MXU matmul each, but its
# f32 gradient accumulators cost 4x the VMEM and pushed the x_bias
# backward over the 16M scoped-VMEM line; the kernel is latency-bound,
# so the smaller matmuls cost nothing measurable.
#
# Residuals are only the four carry streams (c, h, hyper_c, hyper_h) —
# [T, B, 2(H+HH)] total, the same footprint scan AD needs for its carries
# — and the backward recomputes everything else in-step, like the other
# kernels in this file. The working set is ~2x the LayerNorm kernel's
# (extra weights + their VMEM-resident gradient accumulators), so the
# batch tile is capped separately (SRT_HYPER_TILE, default 64 — 128
# exceeds v5e VMEM in the backward).

import os as _os

_HYPER_MAX_TILE = int(_os.environ.get("SRT_HYPER_TILE", "64"))


def _hyper_batch_tile(b: int, xb_bwd: bool = False) -> int:
    """Largest divisor of ``b`` that fits the hyper kernel's VMEM cap.

    Must DIVIDE the batch — the grid is ``b // bt`` programs, so a
    non-divisor would silently drop the trailing rows.

    ``xb_bwd``: with the x_bias path the BACKWARD adds four bias blocks
    (xb/dxb ``[tile, 4H]`` + xbh/dxbh ``[tile, 4HH]`` f32) and measured
    0.6-1.9M OVER the 16M scoped-VMEM line at tile 64 on v5e (it
    compiled in some whole-model graphs and OOM'd standalone — the same
    at-the-line flakiness as ``_batch_tile``), so the backward halves
    the cap; the forward keeps the full tile.
    """
    cap = max(1, _HYPER_MAX_TILE // 2) if xb_bwd else _HYPER_MAX_TILE
    for cand in range(min(b, cap), 0, -1):
        if b % cand == 0:
            return cand
    return b


def _block_scale(z, zd_ref):
    """``[bt, 4e] x [4, e, h] -> [bt, 4H]`` per-gate scale projection.

    The cell's scale projections are four independent ``[e, h]`` blocks;
    the kernel multiplies each gate's slice by its own block (4 small
    MXU matmuls). An earlier design expanded them to one dense
    block-diagonal ``[4e, 4H]`` matmul — fewer, bigger matmuls, but the
    dense gradient accumulators cost 4x the VMEM ([4e, 4H] f32 vs
    [4, e, h]) and pushed the x_bias backward 0.6-2M over the 16M
    scoped-VMEM line (v5e, measured); the kernels are latency- not
    MXU-bound, so the small matmuls cost nothing measurable.
    """
    e = zd_ref.shape[1]
    return jnp.concatenate(
        [jnp.dot(_cast(z[:, j * e:(j + 1) * e], zd_ref), zd_ref[j],
                 preferred_element_type=jnp.float32) for j in range(4)],
        axis=-1)


def _block_unscale(ds, zd_ref):
    """Backward of :func:`_block_scale` w.r.t. z: ``[bt, 4H] -> [bt, 4e]``."""
    h = zd_ref.shape[2]
    return jnp.concatenate(
        [jnp.dot(_cast(ds[:, j * h:(j + 1) * h], zd_ref), zd_ref[j].T,
                 preferred_element_type=jnp.float32) for j in range(4)],
        axis=-1)


def _block_scale_grad(z, ds, zd_ref, dzd_ref):
    """Accumulate ``dzd[j] += z_j^T @ ds_j`` into the [4, e, h] grad ref."""
    e = zd_ref.shape[1]
    h = zd_ref.shape[2]
    for j in range(4):
        dzd_ref[j] += jnp.dot(
            _cast(z[:, j * e:(j + 1) * e], zd_ref).T,
            _cast(ds[:, j * h:(j + 1) * h], zd_ref),
            preferred_element_type=jnp.float32)


def _hyper_recompute(x, h, c, hc, hh, wx_ref, b_ref, wh_ref, wxhx_ref,
                     wxhh_ref, bh_ref, whh_ref, whzx_ref, bhzx_ref,
                     whzh_ref, bhzh_ref, whzb_ref, zdx_ref, zdh_ref,
                     zdb_ref, gam_ref, bet_ref, gc_ref, bc_ref, m,
                     forget_bias, want_residuals, xb=None, xbh=None):
    """One forward step from (x, carries); shared by fwd and bwd kernels.

    ``xb``/``xbh``: optional per-example projections of time-invariant
    inputs — added to the main input projection BEFORE the hyper scaling
    (it is part of ``xh``) and to the aux LSTM's pre-activations.
    """
    hyper_pre = (jnp.dot(_cast(x, wxhx_ref), wxhx_ref[:],
                         preferred_element_type=jnp.float32)
                 + jnp.dot(_cast(h, wxhh_ref), wxhh_ref[:],
                           preferred_element_type=jnp.float32)
                 + bh_ref[0]
                 + jnp.dot(_cast(hh, whh_ref), whh_ref[:],
                           preferred_element_type=jnp.float32))
    if xbh is not None:
        hyper_pre = hyper_pre + xbh
    hi, hg, hf, ho, new_hc = _lstm_gates(hyper_pre, hc, None,
                                         forget_bias=forget_bias)
    new_hh = jnp.tanh(new_hc) * ho

    xp = jnp.dot(_cast(x, wx_ref), wx_ref[:],
                 preferred_element_type=jnp.float32)
    if xb is not None:
        xp = xp + xb
    hp = jnp.dot(_cast(h, wh_ref), wh_ref[:],
                 preferred_element_type=jnp.float32)
    zx = jnp.dot(_cast(new_hh, whzx_ref), whzx_ref[:],
                 preferred_element_type=jnp.float32) + bhzx_ref[0]
    zh = jnp.dot(_cast(new_hh, whzh_ref), whzh_ref[:],
                 preferred_element_type=jnp.float32) + bhzh_ref[0]
    zb = jnp.dot(_cast(new_hh, whzb_ref), whzb_ref[:],
                 preferred_element_type=jnp.float32)
    sx = _block_scale(zx, zdx_ref)
    sh = _block_scale(zh, zdh_ref)
    sb = _block_scale(zb, zdb_ref)
    pre = sx * xp + sh * hp + sb + b_ref[0]

    ln = _ln_gates(pre, c, m, gam_ref[...], bet_ref[...], gc_ref[...],
                   bc_ref[...], forget_bias=forget_bias,
                   want_residuals=want_residuals)
    aux = (hi, hg, hf, ho, new_hc, new_hh, xp, hp, zx, zh, zb, sx, sh)
    return ln, aux


def _hyper_fwd_kernel(x_ref, xb_ref, xbh_ref, wx_ref, b_ref, wh_ref,
                      wxhx_ref, wxhh_ref,
                      bh_ref, whh_ref, whzx_ref, bhzx_ref, whzh_ref,
                      bhzh_ref, whzb_ref, zdx_ref, zdh_ref, zdb_ref,
                      gam_ref, bet_ref, gc_ref, bc_ref,
                      c0_ref, h0_ref, hc0_ref, hh0_ref, mask_ref, seed_ref,
                      hs_ref, cs_ref, hycs_ref, hyhs_ref,
                      cT_ref, hT_ref, hcT_ref, hhT_ref,
                      c_scr, h_scr, hc_scr, hh_scr,
                      *, forget_bias, mask_mode, keep_prob, xb_mode):
    ib = pl.program_id(0)
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(it == 0)
    def _():
        c_scr[:] = c0_ref[:]
        h_scr[:] = h0_ref[:]
        hc_scr[:] = hc0_ref[:]
        hh_scr[:] = hh0_ref[:]

    c, h, hc, hh = c_scr[:], h_scr[:], hc_scr[:], hh_scr[:]
    m = _step_mask(mask_ref, seed_ref, it, ib, pl.num_programs(0),
                   c.shape, keep_prob, mask_mode)
    (new_c, new_h), aux = _hyper_recompute(
        x_ref[0], h, c, hc, hh, wx_ref, b_ref, wh_ref, wxhx_ref, wxhh_ref,
        bh_ref, whh_ref, whzx_ref, bhzx_ref, whzh_ref, bhzh_ref, whzb_ref,
        zdx_ref, zdh_ref, zdb_ref, gam_ref, bet_ref, gc_ref, bc_ref, m,
        forget_bias, want_residuals=False,
        xb=xb_ref[...] if xb_mode else None,
        xbh=xbh_ref[...] if xb_mode else None)
    new_hc, new_hh = aux[4], aux[5]

    # PRE-step states: the backward's residuals (possibly bf16 storage)
    cs_ref[0] = c.astype(cs_ref.dtype)
    hycs_ref[0] = hc.astype(hycs_ref.dtype)
    c_scr[:] = new_c
    h_scr[:] = new_h
    hc_scr[:] = new_hc
    hh_scr[:] = new_hh
    hs_ref[0] = new_h.astype(hs_ref.dtype)
    hyhs_ref[0] = new_hh.astype(hyhs_ref.dtype)

    @pl.when(it == nt - 1)
    def _():
        cT_ref[:] = new_c
        hT_ref[:] = new_h
        hcT_ref[:] = new_hc
        hhT_ref[:] = new_hh


def _hyper_bwd_kernel(x_ref, xb_ref, xbh_ref, wx_ref, b_ref, wh_ref,
                      wxhx_ref, wxhh_ref,
                      bh_ref, whh_ref, whzx_ref, bhzx_ref, whzh_ref,
                      bhzh_ref, whzb_ref, zdx_ref, zdh_ref, zdb_ref,
                      gam_ref, bet_ref, gc_ref, bc_ref,
                      cs_ref, hp_ref, h00_ref, hycs_ref, hyhp_ref,
                      hh00_ref, mask_ref, seed_ref,
                      dhs_ref, dcT_ref, dhT_ref, dhcT_ref, dhhT_ref,
                      dx_ref, dxb_ref, dxbh_ref, dwx_ref, db_ref, dwh_ref,
                      dwxhx_ref,
                      dwxhh_ref, dbh_ref, dwhh_ref, dwhzx_ref, dbhzx_ref,
                      dwhzh_ref, dbhzh_ref, dwhzb_ref, dzdx_ref, dzdh_ref,
                      dzdb_ref, dgam_ref, dbet_ref, dgc_ref, dbc_ref,
                      dc0_ref, dh0_ref, dhc0_ref, dhh0_ref,
                      dc_scr, dh_scr, dhc_scr, dhh_scr,
                      *, forget_bias, mask_mode, keep_prob, xb_mode):
    """Reverse-time inner grid: program (ib, it) handles step T-1-it."""
    ib = pl.program_id(0)
    it = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when((ib == 0) & (it == 0))
    def _():
        for r in (dwx_ref, db_ref, dwh_ref, dwxhx_ref, dwxhh_ref, dbh_ref,
                  dwhh_ref, dwhzx_ref, dbhzx_ref, dwhzh_ref, dbhzh_ref,
                  dwhzb_ref, dzdx_ref, dzdh_ref, dzdb_ref, dgam_ref,
                  dbet_ref, dgc_ref, dbc_ref):
            r[:] = jnp.zeros_like(r)

    @pl.when(it == 0)
    def _():
        dc_scr[:] = dcT_ref[:]
        dh_scr[:] = dhT_ref[:]
        dhc_scr[:] = dhcT_ref[:]
        dhh_scr[:] = dhhT_ref[:]
        # bias grads accumulate IN their (VMEM-resident, revisited)
        # output blocks, like the weight grads
        dxb_ref[...] = jnp.zeros_like(dxb_ref)
        dxbh_ref[...] = jnp.zeros_like(dxbh_ref)

    # ---- recompute the forward step (natural-order streams through
    # _rev_specs; prev-step blocks overridden with the initial carries
    # at the first real step) ----
    x = x_ref[0]
    h_prev = _prev_block(hp_ref, h00_ref, it, nt).astype(jnp.float32)
    c_prev = cs_ref[0].astype(jnp.float32)   # residuals may be bf16
    hc_prev = hycs_ref[0].astype(jnp.float32)
    hh_prev = _prev_block(hyhp_ref, hh00_ref, it, nt).astype(jnp.float32)
    # t_real = nt-1-it: the prng mask must be the one the FORWARD drew
    m = _step_mask(mask_ref, seed_ref, nt - 1 - it, ib,
                   pl.num_programs(0), c_prev.shape, keep_prob, mask_mode)
    ln, aux = _hyper_recompute(
        x, h_prev, c_prev, hc_prev, hh_prev, wx_ref, b_ref, wh_ref,
        wxhx_ref, wxhh_ref, bh_ref, whh_ref, whzx_ref, bhzx_ref, whzh_ref,
        bhzh_ref, whzb_ref, zdx_ref, zdh_ref, zdb_ref, gam_ref, bet_ref,
        gc_ref, bc_ref, m, forget_bias, want_residuals=True,
        xb=xb_ref[...] if xb_mode else None,
        xbh=xbh_ref[...] if xb_mode else None)
    (hi, hg, hf, ho, new_hc, new_hh, xp, hp_, zx, zh, zb, sx, sh) = aux
    gam, gc = gam_ref[...], gc_ref[...]

    # ---- main LayerNorm-LSTM backward (shared with _lnlstm_bwd_kernel) --
    dh = dh_scr[:] + dhs_ref[0].astype(jnp.float32)
    d_pre, dc_next = _ln_lstm_bwd_gates(dh, dc_scr[:], c_prev, m, ln,
                                        gam, gc, dgam_ref, dbet_ref,
                                        dgc_ref, dbc_ref)
    dc_scr[:] = dc_next

    # ---- pre = sx*xp + sh*hp + sb + b ----
    dsx = d_pre * xp
    dxp = d_pre * sx
    dsh = d_pre * hp_
    dhp = d_pre * sh
    db_ref[0] += jnp.sum(d_pre, axis=0)                       # dsb == d_pre
    if xb_mode:
        dxb_ref[...] += dxp       # xb is part of xh, pre-scaling

    # ---- per-gate scale projections (block form, see _block_scale) ----
    dzx = _block_unscale(dsx, zdx_ref)
    dzh = _block_unscale(dsh, zdh_ref)
    dzb = _block_unscale(d_pre, zdb_ref)
    _block_scale_grad(zx, dsx, zdx_ref, dzdx_ref)
    _block_scale_grad(zh, dsh, zdh_ref, dzdh_ref)
    _block_scale_grad(zb, d_pre, zdb_ref, dzdb_ref)

    # ---- hyper_h -> z projections ----
    dzx_c = _cast(dzx, whzx_ref)
    dzh_c = _cast(dzh, whzh_ref)
    dzb_c = _cast(dzb, whzb_ref)
    dhh = (dhh_scr[:]
           + jnp.dot(dzx_c, whzx_ref[:].T,
                     preferred_element_type=jnp.float32)
           + jnp.dot(dzh_c, whzh_ref[:].T,
                     preferred_element_type=jnp.float32)
           + jnp.dot(dzb_c, whzb_ref[:].T,
                     preferred_element_type=jnp.float32))
    hh_c = _cast(new_hh, whzx_ref)
    dwhzx_ref[:] += jnp.dot(hh_c.T, dzx_c,
                            preferred_element_type=jnp.float32)
    dwhzh_ref[:] += jnp.dot(hh_c.T, dzh_c,
                            preferred_element_type=jnp.float32)
    dwhzb_ref[:] += jnp.dot(hh_c.T, dzb_c,
                            preferred_element_type=jnp.float32)
    dbhzx_ref[0] += jnp.sum(dzx, axis=0)
    dbhzh_ref[0] += jnp.sum(dzh, axis=0)

    # ---- aux (vanilla) LSTM backward ----
    tanh_hc = jnp.tanh(new_hc)
    dhc = dhc_scr[:] + dhh * ho * (1.0 - tanh_hc * tanh_hc)
    dho = dhh * tanh_hc
    dhf = dhc * hc_prev
    dhi = dhc * hg
    dhg = dhc * hi
    dh_pre = jnp.concatenate([
        dhi * hi * (1.0 - hi),
        dhg * (1.0 - hg * hg),
        dhf * hf * (1.0 - hf),
        dho * ho * (1.0 - ho),
    ], axis=-1)
    dhc_scr[:] = dhc * hf

    if xb_mode:
        dxbh_ref[...] += dh_pre
    dh_pre_c = _cast(dh_pre, wxhx_ref)
    dbh_ref[0] += jnp.sum(dh_pre, axis=0)
    dwxhx_ref[:] += jnp.dot(_cast(x, wxhx_ref).T, dh_pre_c,
                            preferred_element_type=jnp.float32)
    dwxhh_ref[:] += jnp.dot(_cast(h_prev, wxhh_ref).T, dh_pre_c,
                            preferred_element_type=jnp.float32)
    dwhh_ref[:] += jnp.dot(_cast(hh_prev, whh_ref).T, dh_pre_c,
                           preferred_element_type=jnp.float32)
    dhh_scr[:] = jnp.dot(dh_pre_c, whh_ref[:].T,
                         preferred_element_type=jnp.float32)

    # ---- main input/recurrent projections + carry-out grads ----
    dxp_c = _cast(dxp, wx_ref)
    dhp_c = _cast(dhp, wh_ref)
    dx_ref[0] = (jnp.dot(dxp_c, wx_ref[:].T,
                         preferred_element_type=jnp.float32)
                 + jnp.dot(dh_pre_c, wxhx_ref[:].T,
                           preferred_element_type=jnp.float32))
    dwx_ref[:] += jnp.dot(_cast(x, wx_ref).T, dxp_c,
                          preferred_element_type=jnp.float32)
    dwh_ref[:] += jnp.dot(_cast(h_prev, wh_ref).T, dhp_c,
                          preferred_element_type=jnp.float32)
    dh_scr[:] = (jnp.dot(dhp_c, wh_ref[:].T,
                         preferred_element_type=jnp.float32)
                 + jnp.dot(dh_pre_c, wxhh_ref[:].T,
                           preferred_element_type=jnp.float32))

    @pl.when(it == nt - 1)
    def _():
        dc0_ref[:] = dc_scr[:]
        dh0_ref[:] = dh_scr[:]
        dhc0_ref[:] = dhc_scr[:]
        dhh0_ref[:] = dhh_scr[:]


@functools.partial(jax.custom_vjp, nondiff_argnums=(24, 27, 28))
def fused_hyper_lstm(xs: jax.Array, wx: jax.Array, b: jax.Array,
                     wh: jax.Array, wxh_x: jax.Array, wxh_h: jax.Array,
                     bh: jax.Array, whh: jax.Array,
                     w_hz_x: jax.Array, b_hz_x: jax.Array,
                     w_hz_h: jax.Array, b_hz_h: jax.Array,
                     w_hz_b: jax.Array,
                     zd_x: jax.Array, zd_h: jax.Array, zd_b: jax.Array,
                     ln_gamma: jax.Array, ln_beta: jax.Array,
                     lnc_gamma: jax.Array, lnc_beta: jax.Array,
                     c0: jax.Array, h0: jax.Array,
                     hc0: jax.Array, hh0: jax.Array,
                     forget_bias: float = 1.0,
                     masks: Optional[jax.Array] = None,
                     dropout_seed: Optional[jax.Array] = None,
                     keep_prob: float = 1.0,
                     residual_dtype=jnp.float32,
                     x_bias: Optional[jax.Array] = None,
                     x_bias_hyper: Optional[jax.Array] = None):
    """Fused HyperLSTM (layer-norm variant), recompute-backward.

    ``x_bias [B, 4H]`` / ``x_bias_hyper [B, 4HH]``: optional per-example
    projections of time-invariant inputs onto the main gates (added to
    the input projection BEFORE the hyper scaling) and the aux LSTM's
    pre-activations — pass both or neither.

    Matches :class:`ops.cells.HyperLSTMCell` with ``use_layer_norm=True``
    (the only variant ``make_cell`` builds). Weight layout:

    - ``wx [D, 4H]``, ``wh [H, 4H]``, ``b [4H]``: main-gate projections.
    - ``wxh_x [D, 4HH]``, ``wxh_h [H, 4HH]``, ``bh [4HH]``,
      ``whh [HH, 4HH]``: the aux LSTM over ``[x; h]`` (its fused input
      weight split row-wise) and its own recurrent weights.
    - ``w_hz_p [HH, 4e]`` (+ ``b_hz_p [4e]`` for p ∈ {x, h}): hyper_h →
      per-gate embeddings.
    - ``zd_p [4, e, h]``: the cell's per-gate scale projections, in the
      cell's own block layout (multiplied per gate inside the kernel).
    - per-gate LN ``ln_gamma/ln_beta [4, H]``, cell LN ``[H]``.

    Returns ``(hs [T, B, H], ((cT, hT), (hcT, hhT)))`` — the same carry
    structure as the scan cell.
    """
    hs, fin, _ = _hyper_fwd_call(
        xs, wx, b, wh, wxh_x, wxh_h, bh, whh, w_hz_x, b_hz_x, w_hz_h,
        b_hz_h, w_hz_b, zd_x, zd_h, zd_b, ln_gamma, ln_beta, lnc_gamma,
        lnc_beta, c0, h0, hc0, hh0, forget_bias, masks, dropout_seed,
        keep_prob, residual_dtype, x_bias, x_bias_hyper)
    return hs, fin


def _hyper_fwd_call(xs, wx, b, wh, wxh_x, wxh_h, bh, whh, w_hz_x, b_hz_x,
                    w_hz_h, b_hz_h, w_hz_b, zd_x, zd_h, zd_b, gam, bet,
                    gc, bc, c0, h0, hc0, hh0, forget_bias, masks, seed,
                    keep_prob, residual_dtype, x_bias, x_bias_hyper):
    if (x_bias is None) != (x_bias_hyper is None):
        raise ValueError("pass both x_bias and x_bias_hyper or neither")
    t, bsz, d = xs.shape
    h = wh.shape[0]
    hh_size = whh.shape[0]
    bt = _hyper_batch_tile(bsz)
    mode, mask_arg, seed_arg = _mask_args(masks, seed)
    b2 = b.reshape(1, -1).astype(jnp.float32)
    bh2 = bh.reshape(1, -1).astype(jnp.float32)
    bhzx2 = b_hz_x.reshape(1, -1).astype(jnp.float32)
    bhzh2 = b_hz_h.reshape(1, -1).astype(jnp.float32)
    gc2, bc2 = gc.reshape(1, -1), bc.reshape(1, -1)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, mode, mask_arg.shape)

    (xb_mode, xb_arg, xb_spec, xbh_arg,
     xbh_spec) = _xb_pair_args(x_bias, x_bias_hyper, bt, tile, whole)

    kernel = functools.partial(_hyper_fwd_kernel, forget_bias=forget_bias,
                               mask_mode=mode, keep_prob=keep_prob,
                               xb_mode=xb_mode)
    hs, cs, hycs, hyhs, cT, hT, hcT, hhT = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[step((bt, d)), xb_spec, xbh_spec,
                  whole(wx.shape), whole(b2.shape),
                  whole(wh.shape), whole(wxh_x.shape), whole(wxh_h.shape),
                  whole(bh2.shape), whole(whh.shape), whole(w_hz_x.shape),
                  whole(bhzx2.shape), whole(w_hz_h.shape),
                  whole(bhzh2.shape), whole(w_hz_b.shape),
                  whole(zd_x.shape), whole(zd_h.shape), whole(zd_b.shape),
                  whole(gam.shape), whole(bet.shape), whole(gc2.shape),
                  whole(bc2.shape), tile((bt, h)), tile((bt, h)),
                  tile((bt, hh_size)), tile((bt, hh_size)), mask_spec,
                  seed_spec],
        out_specs=(step((bt, h)), step((bt, h)), step((bt, hh_size)),
                   step((bt, hh_size)), tile((bt, h)), tile((bt, h)),
                   tile((bt, hh_size)), tile((bt, hh_size))),
        out_shape=(
            _sds((t, bsz, h), residual_dtype, xs),       # hs
            _sds((t, bsz, h), residual_dtype, xs),       # cs
            _sds((t, bsz, hh_size), residual_dtype, xs),  # hycs
            _sds((t, bsz, hh_size), residual_dtype, xs),  # hyhs
            _sds((bsz, h), jnp.float32, xs),
            _sds((bsz, h), jnp.float32, xs),
            _sds((bsz, hh_size), jnp.float32, xs),
            _sds((bsz, hh_size), jnp.float32, xs),
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, hh_size), jnp.float32),
                        pltpu.VMEM((bt, hh_size), jnp.float32)],
        interpret=_interpret_default(),
    )(xs, xb_arg, xbh_arg, wx, b2, wh, wxh_x, wxh_h, bh2, whh, w_hz_x,
      bhzx2, w_hz_h, bhzh2, w_hz_b, zd_x, zd_h, zd_b, gam, bet, gc2, bc2,
      c0, h0, hc0, hh0, mask_arg, seed_arg)
    return hs, ((cT, hT), (hcT, hhT)), (cs, hycs, hyhs)


def _fused_hyper_fwd(xs, wx, b, wh, wxh_x, wxh_h, bh, whh, w_hz_x, b_hz_x,
                     w_hz_h, b_hz_h, w_hz_b, zd_x, zd_h, zd_b, gam, bet,
                     gc, bc, c0, h0, hc0, hh0, forget_bias, masks,
                     dropout_seed, keep_prob, residual_dtype, x_bias,
                     x_bias_hyper):
    hs, fin, (cs, hycs, hyhs) = _hyper_fwd_call(
        xs, wx, b, wh, wxh_x, wxh_h, bh, whh, w_hz_x, b_hz_x, w_hz_h,
        b_hz_h, w_hz_b, zd_x, zd_h, zd_b, gam, bet, gc, bc, c0, h0, hc0,
        hh0, forget_bias, masks, dropout_seed, keep_prob, residual_dtype,
        x_bias, x_bias_hyper)
    res = (xs, wx, b, wh, wxh_x, wxh_h, bh, whh, w_hz_x, b_hz_x, w_hz_h,
           b_hz_h, w_hz_b, zd_x, zd_h, zd_b, gam, bet, gc, bc, h0, hh0,
           hs, cs, hycs, hyhs, masks, dropout_seed, x_bias, x_bias_hyper)
    return (hs, fin), res


def _fused_hyper_bwd(forget_bias, keep_prob, residual_dtype, res, grads):
    (xs, wx, b, wh, wxh_x, wxh_h, bh, whh, w_hz_x, b_hz_x, w_hz_h, b_hz_h,
     w_hz_b, zd_x, zd_h, zd_b, gam, bet, gc, bc, h0, hh0, hs, cs, hycs,
     hyhs, masks, seed, x_bias, x_bias_hyper) = res
    dhs, ((dcT, dhT), (dhcT, dhhT)) = grads
    t, bsz, d = xs.shape
    h = wh.shape[0]
    hh_size = whh.shape[0]
    bt = _hyper_batch_tile(bsz, xb_bwd=x_bias is not None)
    mode, mask_arg, seed_arg = _mask_args(masks, seed)
    b2 = b.reshape(1, -1).astype(jnp.float32)
    bh2 = bh.reshape(1, -1).astype(jnp.float32)
    bhzx2 = b_hz_x.reshape(1, -1).astype(jnp.float32)
    bhzh2 = b_hz_h.reshape(1, -1).astype(jnp.float32)
    gc2, bc2 = gc.reshape(1, -1), bc.reshape(1, -1)
    h00 = h0.astype(hs.dtype)
    hh00 = hh0.astype(hyhs.dtype)
    step, tile, whole, mask_spec, seed_spec = _specs(
        bt, h, mode, mask_arg.shape)
    rstep, rprev, rmask = _rev_specs(t, bt, h, mode, mask_arg.shape)

    (xb_mode, xb_arg, xb_spec, xbh_arg,
     xbh_spec) = _xb_pair_args(x_bias, x_bias_hyper, bt, tile, whole)

    kernel = functools.partial(_hyper_bwd_kernel, forget_bias=forget_bias,
                               mask_mode=mode, keep_prob=keep_prob,
                               xb_mode=xb_mode)
    (dxs, dxb, dxbh, dwx, db2, dwh, dwxhx, dwxhh, dbh2, dwhh, dwhzx,
     dbhzx2, dwhzh, dbhzh2, dwhzb, dzdx, dzdh, dzdb, dgam, dbet, dgc2,
     dbc2, dc0, dh0, dhc0, dhh0) = pl.pallas_call(
        kernel,
        grid=(bsz // bt, t),
        in_specs=[rstep((bt, d)), xb_spec, xbh_spec,
                  whole(wx.shape), whole(b2.shape),
                  whole(wh.shape), whole(wxh_x.shape), whole(wxh_h.shape),
                  whole(bh2.shape), whole(whh.shape), whole(w_hz_x.shape),
                  whole(bhzx2.shape), whole(w_hz_h.shape),
                  whole(bhzh2.shape), whole(w_hz_b.shape),
                  whole(zd_x.shape), whole(zd_h.shape), whole(zd_b.shape),
                  whole(gam.shape), whole(bet.shape), whole(gc2.shape),
                  whole(bc2.shape), rstep((bt, h)), rprev((bt, h)),
                  tile((bt, h)),
                  rstep((bt, hh_size)), rprev((bt, hh_size)),
                  tile((bt, hh_size)), rmask,
                  seed_spec, rstep((bt, h)), tile((bt, h)), tile((bt, h)),
                  tile((bt, hh_size)), tile((bt, hh_size))],
        out_specs=(rstep((bt, d)), xb_spec, xbh_spec,
                   whole(wx.shape), whole(b2.shape),
                   whole(wh.shape), whole(wxh_x.shape), whole(wxh_h.shape),
                   whole(bh2.shape), whole(whh.shape), whole(w_hz_x.shape),
                   whole(bhzx2.shape), whole(w_hz_h.shape),
                   whole(bhzh2.shape), whole(w_hz_b.shape),
                   whole(zd_x.shape), whole(zd_h.shape), whole(zd_b.shape),
                   whole(gam.shape), whole(bet.shape), whole(gc2.shape),
                   whole(bc2.shape), tile((bt, h)), tile((bt, h)),
                   tile((bt, hh_size)), tile((bt, hh_size))),
        out_shape=(
            _sds((t, bsz, d), jnp.float32, xs),
            _sds(xb_arg.shape, jnp.float32, xs),
            _sds(xbh_arg.shape, jnp.float32, xs),
            _sds(wx.shape, jnp.float32, xs),
            _sds(b2.shape, jnp.float32, xs),
            _sds(wh.shape, jnp.float32, xs),
            _sds(wxh_x.shape, jnp.float32, xs),
            _sds(wxh_h.shape, jnp.float32, xs),
            _sds(bh2.shape, jnp.float32, xs),
            _sds(whh.shape, jnp.float32, xs),
            _sds(w_hz_x.shape, jnp.float32, xs),
            _sds(bhzx2.shape, jnp.float32, xs),
            _sds(w_hz_h.shape, jnp.float32, xs),
            _sds(bhzh2.shape, jnp.float32, xs),
            _sds(w_hz_b.shape, jnp.float32, xs),
            _sds(zd_x.shape, jnp.float32, xs),
            _sds(zd_h.shape, jnp.float32, xs),
            _sds(zd_b.shape, jnp.float32, xs),
            _sds(gam.shape, jnp.float32, xs),
            _sds(bet.shape, jnp.float32, xs),
            _sds(gc2.shape, jnp.float32, xs),
            _sds(bc2.shape, jnp.float32, xs),
            _sds((bsz, h), jnp.float32, xs),
            _sds((bsz, h), jnp.float32, xs),
            _sds((bsz, hh_size), jnp.float32, xs),
            _sds((bsz, hh_size), jnp.float32, xs),
        ),
        scratch_shapes=[pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, h), jnp.float32),
                        pltpu.VMEM((bt, hh_size), jnp.float32),
                        pltpu.VMEM((bt, hh_size), jnp.float32)],
        interpret=_interpret_default(),
    )(xs, xb_arg, xbh_arg, wx, b2, wh, wxh_x, wxh_h, bh2, whh,
      w_hz_x, bhzx2, w_hz_h, bhzh2, w_hz_b, zd_x, zd_h, zd_b, gam, bet,
      gc2, bc2, cs, hs, h00, hycs, hyhs, hh00,
      mask_arg, seed_arg, dhs, dcT, dhT, dhcT, dhhT)
    dmasks = jnp.zeros_like(masks) if masks is not None else None
    # cotangent dtypes must match the primals (big weights may be bf16)
    return (dxs.astype(xs.dtype), dwx.astype(wx.dtype),
            db2.reshape(-1).astype(b.dtype), dwh.astype(wh.dtype),
            dwxhx.astype(wxh_x.dtype), dwxhh.astype(wxh_h.dtype),
            dbh2.reshape(-1).astype(bh.dtype), dwhh.astype(whh.dtype),
            dwhzx.astype(w_hz_x.dtype), dbhzx2.reshape(-1),
            dwhzh.astype(w_hz_h.dtype), dbhzh2.reshape(-1),
            dwhzb.astype(w_hz_b.dtype), dzdx.astype(zd_x.dtype),
            dzdh.astype(zd_h.dtype), dzdb.astype(zd_b.dtype),
            dgam, dbet, dgc2.reshape(-1), dbc2.reshape(-1),
            dc0, dh0, dhc0, dhh0, dmasks, _seed_cotangent(seed),
            dxb.astype(x_bias.dtype) if x_bias is not None else None,
            dxbh.astype(x_bias_hyper.dtype)
            if x_bias_hyper is not None else None)


fused_hyper_lstm.defvjp(_fused_hyper_fwd, _fused_hyper_bwd)
