"""Forward-only cache-resident Pallas decode kernels for serving.

ISSUE 17 tentpole. The serving hot path (`serve/engine.make_chunk_step`)
advances one cell step per `lax.scan` iteration: every step round-trips
the ``(c, h)`` carry through HBM and materializes the step's
intermediates (``h``, the raw MDN projection, the mixture params)
between XLA fusions. These kernels run a WHOLE K-step serve chunk as
one `pallas_call` with the carry resident in VMEM:

- ``decode_chunk`` — the generation path: per grid step it fuses the
  LSTM cell (lstm / layer_norm), the output projection, the MDN
  parameter head, the inverse-CDF / Box-Muller stroke sampler and the
  engine's per-slot done/live masking into one kernel program. The
  ``(c, h)`` carry, the previous stroke and the per-slot ``t``/``done``
  state live in VMEM scratch across all K steps — HBM sees the weights
  ONCE per chunk (constant ``index_map`` blocks are fetched when their
  index changes, i.e. never again across the grid) plus the tiny
  ``[K, B, 4]`` uniform stream in and the ``[K, B, 5]`` stroke stream
  out. The scan path pays the weight read, the carry round-trip and
  the inter-fusion intermediates K times per chunk
  (`scripts/bench_kernel.py --mode serve_decode` prints the modeled
  byte ledger; at the committed serve geometry the ratio is >5x).
- ``replay_chunk`` — the teacher-forced prefix replay of the endpoint
  encode phase (`serve/endpoints.make_encode_step`): the same carry
  residency for the ``E``-step replay, with the per-row ``t <
  seq_len`` liveness mask, returning only the final carry.

Semantics contract: both kernels mirror their scan twins OP FOR OP —
same `ops.linear.matmul` operand association (``(x @ wx + b) + h @
wh``), same `ops.linear.layer_norm`, same `ops.mdn.get_mixture_params`,
same sampling formulas on the same pre-drawn uniforms. In interpret
mode (the CPU tier-1 path, and the default off-TPU exactly like
`ops.pallas_fused`) UNCONDITIONAL models are bitwise-equal to the
jitted scan program. CONDITIONAL models (a ``z``/label ``extra``
operand) agree within a documented per-component tolerance of 1e-5
(measured <= ~5e-7 at f32): the kernel computes the loop-invariant
``extra @ wx[x_dim:]`` ONCE per chunk (that hoist is part of the perf
claim) while XLA compiles the scan body's per-step concat-dot with its
own FMA association — and compiles the same math differently again
outside `lax.scan`, so no single association is canonical
(scripts/parity_check.py --serve_decode measures both). The only other
divergence is invisible by construction: the scan path re-draws a DONE
slot's uniforms at its frozen step index while the caller pre-draws
uniforms at ``t0 + s``; a done slot's samples are discarded by the
live mask either way (see `make_uniforms`).

Randomness stays OUTSIDE the kernel: per-slot-step uniforms are
pre-drawn with the engine's own ``fold_in(request_key, t)`` discipline
(`make_uniforms`), because for a live slot ``t == t0 + s`` until the
step it finishes, and after that its draws are masked dead. This keeps
threefry out of the kernel body and makes the uniform block a plain
streamed operand.

The hyper cell's nested carry (a second LSTM + 12 projections) is not
worth a hand-rolled forward kernel at serve batch sizes; callers get a
clear refusal naming the scan fallback (``decode_kernel=scan``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sketch_rnn_tpu.ops import linear as L
from sketch_rnn_tpu.ops import mdn
from sketch_rnn_tpu.ops.pallas_fused import _interpret_default, _sds

SUPPORTED_CELLS = ("lstm", "layer_norm")


def check_cell_kind(kind: str) -> None:
    """Refuse cells the fused decode kernel does not cover, by name."""
    if kind not in SUPPORTED_CELLS:
        raise ValueError(
            f"decode_kernel=pallas supports cells {SUPPORTED_CELLS}, "
            f"not {kind!r} (the hyper cell's nested carry stays on the "
            f"scan path — use decode_kernel=scan)")


def make_uniforms(keys: jax.Array, t0: jax.Array, chunk: int) -> jax.Array:
    """Pre-draw the chunk's per-slot-step uniform blocks ``[K, B, 4]``.

    Step ``s`` of slot ``b`` gets ``uniform(fold_in(keys[b], t0[b] + s),
    (4,))`` — bitwise the engine's in-loop draw for every LIVE step
    (a live slot's ``t`` is exactly ``t0 + s`` until the step it
    finishes), and unused for done steps (the live mask discards the
    sampled stroke and freezes the carry, so those draws can never
    reach an output).
    """
    steps = t0[None, :] + jnp.arange(chunk, dtype=t0.dtype)[:, None]
    kstep = jax.vmap(lambda ts: jax.vmap(jax.random.fold_in)(keys, ts))(
        steps)
    return jax.vmap(jax.vmap(
        lambda k: jax.random.uniform(k, (4,))))(kstep)


def _take_rows(a: jax.Array, idx: jax.Array) -> jax.Array:
    """``take_along_axis(a, idx[:, None], -1)[:, 0]`` without a gather
    (TPU Pallas has no general gather): exactly one column matches, so
    the masked sum IS the selected element, bitwise."""
    cols = jax.lax.broadcasted_iota(jnp.int32, a.shape, 1)
    return jnp.sum(jnp.where(cols == idx[:, None], a, 0.0), axis=-1)


def _sample_rows(mp: mdn.MixtureParams, u: jax.Array, temps: jax.Array,
                 greedy: bool) -> jax.Array:
    """`serve.engine.sample_mixture_rows`, gather-free.

    Same formulas on the same operands in the same order — the
    categorical inverse-CDF, the Box-Muller pair and the temperature
    scalings are copied verbatim; only ``take_along_axis``/``one_hot``
    become iota-mask forms with bitwise-identical values.
    """
    tau = temps[:, None]
    if greedy:
        idx = jnp.argmax(mp.log_pi, axis=-1)
        pen_idx = jnp.argmax(mp.pen_logits, axis=-1)
    else:
        cdf = jnp.cumsum(
            jax.nn.softmax(mp.log_pi / tau, axis=-1), axis=-1)
        idx = jnp.minimum(
            jnp.sum(u[:, 0:1] > cdf, axis=-1), mp.log_pi.shape[-1] - 1)
        pen_cdf = jnp.cumsum(
            jax.nn.softmax(mp.pen_logits / tau, axis=-1), axis=-1)
        pen_idx = jnp.minimum(jnp.sum(u[:, 1:2] > pen_cdf, axis=-1), 2)
    mu1, mu2 = _take_rows(mp.mu1, idx), _take_rows(mp.mu2, idx)
    if greedy:
        dx, dy = mu1, mu2
    else:
        s1 = jnp.exp(_take_rows(mp.log_s1, idx))
        s2 = jnp.exp(_take_rows(mp.log_s2, idx))
        rho = _take_rows(mp.rho, idx)
        r = jnp.sqrt(-2.0 * jnp.log(jnp.maximum(u[:, 2], 1e-12)))
        theta = (2.0 * jnp.pi) * u[:, 3]
        e0, e1 = r * jnp.cos(theta), r * jnp.sin(theta)
        sq = jnp.sqrt(temps)
        dx = mu1 + s1 * sq * e0
        dy = mu2 + s2 * sq * (rho * e0
                              + jnp.sqrt(1.0 - jnp.square(rho)) * e1)
    pen_cols = jax.lax.broadcasted_iota(jnp.int32,
                                        (pen_idx.shape[0], 3), 1)
    pen = (pen_cols == pen_idx[:, None]).astype(jnp.float32)
    return jnp.concatenate([dx[:, None], dy[:, None], pen], axis=-1)


def _cell_step(cell_kind: str, cp, c, h, x, extra_xp, forget_bias,
               compute_dtype):
    """One fused cell step on VMEM values — `ops.cells` math as XLA
    compiles it for the scan twin: the time-invariant features' input
    projection is hoisted out of the loop (``extra_xp`` — see
    `decode_chunk`), so ``pre = ((x @ wx + extra_xp) [+ b]) + h @ wh``
    with the SAME accumulation association; gate order (i, g, f, o)."""
    xp = L.matmul(x, cp["wx"], compute_dtype)
    if extra_xp is not None:
        xp = xp + extra_xp
    if cell_kind == "lstm":
        xp = xp + cp["b"]
    pre = xp + L.matmul(h, cp["wh"], compute_dtype)
    gates = jnp.split(pre, 4, axis=-1)
    if cell_kind == "layer_norm":
        gates = [L.layer_norm(g, cp["ln_gamma"][j], cp["ln_beta"][j])
                 for j, g in enumerate(gates)]
    i, g, f, o = gates
    new_c = c * jax.nn.sigmoid(f + forget_bias) \
        + jax.nn.sigmoid(i) * jnp.tanh(g)
    out_c = new_c
    if cell_kind == "layer_norm":
        out_c = L.layer_norm(new_c, cp["lnc_gamma"], cp["lnc_beta"])
    new_h = jnp.tanh(out_c) * jax.nn.sigmoid(o)
    return new_c, new_h


def _ref_tree(cp_refs):
    """Deref a dict of cell-param Refs into a dict of VMEM values."""
    return {k: r[...] for k, r in cp_refs.items()}


def decode_chunk(cell_params, out_w, out_b, c0, h0, prev0,
                 extra: Optional[jax.Array], u, temps, t0, done0, caps,
                 end_token, *, cell_kind: str, num_mixture: int,
                 forget_bias: float = 1.0, compute_dtype=None,
                 greedy: bool = False,
                 interpret: Optional[bool] = None
                 ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array,
                            jax.Array]:
    """Run K fused decode steps with the carry resident in VMEM.

    Mirrors the body of `serve.engine.make_chunk_step`'s scan exactly
    (see module docstring); the caller does the pool gather / reset
    re-init (identical jnp either way) and pre-draws ``u`` with
    :func:`make_uniforms`.

    Args:
      cell_params: the decoder cell's param dict (``wx/wh/b`` or the
        layer-norm set), f32 (or pre-cast — same contract as the cell).
      out_w, out_b: MDN projection ``[H, 6M+3]`` / ``[6M+3]``.
      c0, h0: chunk-entry carry ``[B, H]``.
      prev0: previous stroke ``[B, 5]``.
      extra: time-invariant decoder features ``[B, E]`` (z and/or class
        embedding) or None for the unconditional/classless model. Its
        input projection ``extra @ wx[5:]`` is computed ONCE outside
        the kernel and added per step — exactly the loop-invariant
        hoist XLA applies to the scan twin's concat-dot, so the
        accumulation association (and hence the bits) match.
      u: pre-drawn uniforms ``[K, B, 4]``.
      temps: per-slot temperatures ``[B]``.
      t0, done0: per-slot step counts ``[B]`` i32 / done flags ``[B]``
        bool at chunk entry.
      caps: per-slot step caps ``[B]`` i32.
      end_token: the frozen-slot stroke row ``[5]``.

    Returns ``(strokes [K, B, 5], c, h, t, done)``.
    """
    check_cell_kind(cell_kind)
    if interpret is None:
        interpret = _interpret_default()
    k, b, _ = u.shape
    h_dim = h0.shape[-1]
    p_dim = out_w.shape[-1]
    x_dim = prev0.shape[-1]
    extra_xp = None
    if extra is not None:
        cell_params = dict(cell_params)
        wx = cell_params["wx"]
        extra_xp = L.matmul(extra, wx[x_dim:], compute_dtype)
        cell_params["wx"] = wx[:x_dim]
    cp_names = sorted(cell_params)

    col = lambda v, dt: v.astype(dt).reshape(b, 1)  # noqa: E731
    row = lambda v: v.reshape(1, -1)                # noqa: E731

    def kernel(*refs):
        n_cp = len(cp_names)
        cp_refs = dict(zip(cp_names, refs[:n_cp]))
        (out_w_ref, out_b_ref, c0_ref, h0_ref, prev0_ref) = \
            refs[n_cp:n_cp + 5]
        at = n_cp + 5
        xp_ref = None
        if extra_xp is not None:
            xp_ref = refs[at]
            at += 1
        (u_ref, temps_ref, t0_ref, done0_ref, caps_ref, end_ref,
         strokes_ref, c_out_ref, h_out_ref, t_out_ref, done_out_ref,
         c_scr, h_scr, prev_scr, t_scr, done_scr) = refs[at:]
        s = pl.program_id(0)

        @pl.when(s == 0)
        def _init():
            c_scr[...] = c0_ref[...]
            h_scr[...] = h0_ref[...]
            prev_scr[...] = prev0_ref[...]
            t_scr[...] = t0_ref[...]
            done_scr[...] = done0_ref[...]

        c, h = c_scr[...], h_scr[...]
        prev = prev_scr[...]
        t = t_scr[...][:, 0]
        done = done_scr[...][:, 0] != 0
        us = u_ref[0]
        new_c, new_h = _cell_step(
            cell_kind, _ref_tree(cp_refs), c, h, prev,
            None if xp_ref is None else xp_ref[...],
            forget_bias, compute_dtype)
        raw = L.matmul(new_h, out_w_ref[...], compute_dtype) \
            + out_b_ref[...][0]
        mp = mdn.get_mixture_params(raw, num_mixture)
        stroke = _sample_rows(mp, us, temps_ref[...][:, 0], greedy)
        live = ~done
        stroke = jnp.where(live[:, None], stroke, end_ref[...][0][None])
        c = jnp.where(live[:, None], new_c, c)
        h = jnp.where(live[:, None], new_h, h)
        t = t + live.astype(jnp.int32)
        done = done | (stroke[:, 4] > 0.5) \
            | (live & (t >= caps_ref[...][:, 0]))
        strokes_ref[0] = stroke
        c_scr[...], h_scr[...] = c, h
        prev_scr[...] = stroke
        t_scr[...] = t[:, None]
        done_scr[...] = done.astype(jnp.int32)[:, None]

        @pl.when(s == k - 1)
        def _finalize():
            c_out_ref[...] = c
            h_out_ref[...] = h
            t_out_ref[...] = t[:, None]
            done_out_ref[...] = done.astype(jnp.int32)[:, None]

    whole = lambda shape: pl.BlockSpec(  # noqa: E731 — resident block:
        shape, lambda s: (0,) * len(shape),  # fetched once, index fixed
        memory_space=pltpu.VMEM)
    step2 = lambda w: pl.BlockSpec(  # noqa: E731 — per-step stream
        (1, b, w), lambda s: (s, 0, 0), memory_space=pltpu.VMEM)

    operands = [cell_params[n] if cell_params[n].ndim > 1
                else row(cell_params[n]) for n in cp_names]
    in_specs = [whole(o.shape) for o in operands]
    operands += [out_w, row(out_b), c0, h0, prev0]
    in_specs += [whole(out_w.shape), whole((1, p_dim)), whole((b, h_dim)),
                 whole((b, h_dim)), whole((b, 5))]
    if extra_xp is not None:
        operands.append(extra_xp)
        in_specs.append(whole(extra_xp.shape))
    operands += [u, col(temps, jnp.float32), col(t0, jnp.int32),
                 col(done0, jnp.int32), col(caps, jnp.int32),
                 row(end_token.astype(jnp.float32))]
    in_specs += [step2(4), whole((b, 1)), whole((b, 1)), whole((b, 1)),
                 whole((b, 1)), whole((1, 5))]

    out_shape = [
        _sds((k, b, 5), jnp.float32, u),       # strokes
        _sds((b, h_dim), jnp.float32, c0),     # final c
        _sds((b, h_dim), jnp.float32, h0),     # final h
        _sds((b, 1), jnp.int32, t0),           # final t
        _sds((b, 1), jnp.int32, t0),           # final done
    ]
    out_specs = [step2(5), whole((b, h_dim)), whole((b, h_dim)),
                 whole((b, 1)), whole((b, 1))]
    scratch = [pltpu.VMEM((b, h_dim), jnp.float32),
               pltpu.VMEM((b, h_dim), jnp.float32),
               pltpu.VMEM((b, 5), jnp.float32),
               pltpu.VMEM((b, 1), jnp.int32),
               pltpu.VMEM((b, 1), jnp.int32)]
    strokes, c_f, h_f, t_f, done_f = pl.pallas_call(
        kernel,
        grid=(k,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)
    return strokes, c_f, h_f, t_f[:, 0], done_f[:, 0] != 0


def replay_chunk(cell_params, c0, h0, xs, extra: Optional[jax.Array],
                 seq_len, *, cell_kind: str, forget_bias: float = 1.0,
                 compute_dtype=None, interpret: Optional[bool] = None
                 ) -> Tuple[jax.Array, jax.Array]:
    """Teacher-forced prefix replay with the carry resident in VMEM.

    The fused twin of the scan in `serve.endpoints.make_encode_step`:
    advance the decoder carry through inputs ``xs [E, B, 5]`` with the
    per-row liveness mask ``t < seq_len`` (rows past their prefix
    length keep their carry — batch padding is inert), returning only
    the final ``(c, h)``. The MDN projection of the scan twin is
    dead code there (XLA DCE removes it); the kernel simply never
    computes it.
    """
    check_cell_kind(cell_kind)
    if interpret is None:
        interpret = _interpret_default()
    e, b, x_dim = xs.shape
    h_dim = h0.shape[-1]
    extra_xp = None
    if extra is not None:
        cell_params = dict(cell_params)
        wx = cell_params["wx"]
        extra_xp = L.matmul(extra, wx[x_dim:], compute_dtype)
        cell_params["wx"] = wx[:x_dim]
    cp_names = sorted(cell_params)

    def kernel(*refs):
        n_cp = len(cp_names)
        cp_refs = dict(zip(cp_names, refs[:n_cp]))
        (c0_ref, h0_ref) = refs[n_cp:n_cp + 2]
        at = n_cp + 2
        xp_ref = None
        if extra_xp is not None:
            xp_ref = refs[at]
            at += 1
        (xs_ref, len_ref, c_out_ref, h_out_ref, c_scr, h_scr) = refs[at:]
        s = pl.program_id(0)

        @pl.when(s == 0)
        def _init():
            c_scr[...] = c0_ref[...]
            h_scr[...] = h0_ref[...]

        c, h = c_scr[...], h_scr[...]
        new_c, new_h = _cell_step(
            cell_kind, _ref_tree(cp_refs), c, h, xs_ref[0],
            None if xp_ref is None else xp_ref[...],
            forget_bias, compute_dtype)
        live = s < len_ref[...][:, 0]
        c = jnp.where(live[:, None], new_c, c)
        h = jnp.where(live[:, None], new_h, h)
        c_scr[...], h_scr[...] = c, h

        @pl.when(s == e - 1)
        def _finalize():
            c_out_ref[...] = c
            h_out_ref[...] = h

    whole = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda s: (0,) * len(shape),
        memory_space=pltpu.VMEM)
    operands = [cell_params[n] if cell_params[n].ndim > 1
                else cell_params[n].reshape(1, -1) for n in cp_names]
    in_specs = [whole(o.shape) for o in operands]
    operands += [c0, h0]
    in_specs += [whole((b, h_dim)), whole((b, h_dim))]
    if extra_xp is not None:
        operands.append(extra_xp)
        in_specs.append(whole(extra_xp.shape))
    operands += [xs, seq_len.astype(jnp.int32).reshape(b, 1)]
    in_specs += [pl.BlockSpec((1, b, 5), lambda s: (s, 0, 0),
                              memory_space=pltpu.VMEM),
                 whole((b, 1))]
    c_f, h_f = pl.pallas_call(
        kernel,
        grid=(e,),
        in_specs=in_specs,
        out_specs=[whole((b, h_dim)), whole((b, h_dim))],
        out_shape=[_sds((b, h_dim), jnp.float32, c0),
                   _sds((b, h_dim), jnp.float32, h0)],
        scratch_shapes=[pltpu.VMEM((b, h_dim), jnp.float32),
                        pltpu.VMEM((b, h_dim), jnp.float32)],
        interpret=interpret,
    )(*operands)
    return c_f, h_f


def modeled_chunk_bytes(b: int, k: int, h: int, d_in: int, p: int,
                        extra_dim: int = 0) -> dict:
    """Deterministic per-chunk HBM byte ledger, scan vs fused kernel.

    The box-constraint proof arm (ROADMAP: no wall-clock claims off a
    real mesh): count the HBM traffic each program must move per
    K-step chunk at f32. The scan program touches, per STEP, the
    weight set (no VMEM residency across `lax.scan` iterations), the
    carry round-trip (read + write of ``2 [B, H]``), and the
    inter-fusion intermediates (``h`` and the ``[B, P]`` MDN raw /
    mixture params, each written then re-read); the fused kernel
    fetches the weights ONCE per chunk (constant-index blocks), keeps
    carry/intermediates in VMEM, and streams only the uniforms in and
    the strokes out. ``fused_ops_per_step`` counts the logical ops the
    kernel fuses into one program (cell matmuls + gates, projection,
    MDN head, sampler, masking) — each at LEAST one separate XLA
    fusion boundary (an HBM materialization) on the scan path.
    """
    f32 = 4
    weights = (d_in * 4 * h + h * 4 * h + 4 * h      # wx, wh, b/LN
               + h * p + p) * f32                     # out_w, out_b
    carry_rt = 2 * (2 * b * h) * f32                  # (c,h) read+write
    inter = (2 * b * h + 2 * 2 * b * p) * f32         # h, raw, mp
    stream = (b * 4 + b * 5) * f32                    # u in, stroke out
    scan_chunk = k * (weights + carry_rt + inter + stream)
    kernel_chunk = weights + carry_rt + k * stream
    return {
        "weight_bytes": weights,
        "scan_chunk_bytes": scan_chunk,
        "kernel_chunk_bytes": kernel_chunk,
        "modeled_speedup": scan_chunk / kernel_chunk,
        "fused_ops_per_step": 5,  # cell, projection, mdn head,
        #   sampler, masking — one pallas program vs >=5 XLA fusions
        "extra_dim": extra_dim,
    }
