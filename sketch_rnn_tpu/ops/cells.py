"""RNN cells as pure ``(params, carry, x) -> (carry, h)`` step functions.

TPU-native equivalents of the reference's cell library (SURVEY.md §2
components 2-4: ``LSTMCell``, ``LayerNormLSTMCell``, ``HyperLSTMCell``;
reference unreadable — semantics follow the canonical sketch-rnn cells and
the HyperNetworks paper, arXiv:1609.09106). The reference's cuDNN fused
path (component 5) is replaced by XLA fusion: each step is a single fused
``[x; h] @ W`` matmul (MXU-shaped) and the time loop is ``lax.scan`` in
:mod:`sketch_rnn_tpu.ops.rnn`.

Conventions:

- Cell objects hold only *static* configuration (sizes, flags); parameters
  are explicit pytrees from ``init_params`` so cells compose with ``jit``,
  ``grad``, ``scan`` and sharding transparently.
- Gate order in all fused weight matrices is ``(i, g, f, o)``.
- Recurrent dropout is *inverted* dropout on the candidate ``g``; masks are
  precomputed per step outside the scan (``ops.rnn.make_dropout_masks``) so
  the step stays a pure function of its inputs.
- ``compute_dtype`` (e.g. bfloat16) applies to matmul operands only;
  carries, gates and layer-norm statistics stay float32.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sketch_rnn_tpu.ops import linear as L

Carry = Any
Params = Dict[str, Any]


def _split_gates(pre: jax.Array) -> Tuple[jax.Array, ...]:
    return tuple(jnp.split(pre, 4, axis=-1))


class LSTMCell:
    """Vanilla LSTM with orthogonal recurrent init and forget-gate bias.

    New-framework equivalent of SURVEY §2 component 2.
    """

    def __init__(self, hidden_size: int, forget_bias: float = 1.0,
                 compute_dtype=None):
        self.hidden_size = hidden_size
        self.forget_bias = forget_bias
        self.compute_dtype = compute_dtype

    def init_params(self, key: jax.Array, input_size: int) -> Params:
        kx, kh = jax.random.split(key)
        h = self.hidden_size
        return {
            "wx": L.xavier_uniform(kx, (input_size, 4 * h)),
            "wh": L.orthogonal(kh, (h, 4 * h)),
            "b": jnp.zeros((4 * h,), jnp.float32),
        }

    def initial_carry(self, batch_size: int) -> Carry:
        z = jnp.zeros((batch_size, self.hidden_size), jnp.float32)
        return (z, z)

    @property
    def carry_size(self) -> int:
        """Flat width of the carry, for z -> initial-state projections."""
        return 2 * self.hidden_size

    def unflatten_carry(self, flat: jax.Array) -> Carry:
        c, h = jnp.split(flat, 2, axis=-1)
        return (c, h)

    def __call__(self, params: Params, carry: Carry, x: jax.Array,
                 rdrop_mask: Optional[jax.Array] = None
                 ) -> Tuple[Carry, jax.Array]:
        xp = L.matmul(x, params["wx"], self.compute_dtype) + params["b"]
        return self.step_pre(params, carry, xp, rdrop_mask)

    # -- hoisted-input path (cuDNN-style): the x @ wx projection for ALL
    # timesteps is one large MXU matmul outside the scan; the scan step
    # only does the recurrent h @ wh matmul (SURVEY §2 component 5).

    def precompute_inputs(self, params: Params, xs: jax.Array) -> jax.Array:
        """``[T, B, D] -> [T, B, 4H]`` input projections, one batched matmul."""
        return L.matmul(xs, params["wx"], self.compute_dtype) + params["b"]

    def step_pre(self, params: Params, carry: Carry, xp: jax.Array,
                 rdrop_mask: Optional[jax.Array] = None
                 ) -> Tuple[Carry, jax.Array]:
        c, h = carry
        pre = xp + L.matmul(h, params["wh"], self.compute_dtype)
        i, g, f, o = _split_gates(pre)
        g = jnp.tanh(g)
        if rdrop_mask is not None:
            g = g * rdrop_mask
        new_c = c * jax.nn.sigmoid(f + self.forget_bias) \
            + jax.nn.sigmoid(i) * g
        new_h = jnp.tanh(new_c) * jax.nn.sigmoid(o)
        return (new_c, new_h), new_h


class LayerNormLSTMCell:
    """LSTM with per-gate layer norm and a norm on the cell state.

    New-framework equivalent of SURVEY §2 component 3. Gate pre-activations
    are normalized per gate (four gamma/beta pairs); the new cell state is
    normalized before the output tanh. Linear layers carry no bias — the
    layer-norm betas take that role.
    """

    def __init__(self, hidden_size: int, forget_bias: float = 1.0,
                 compute_dtype=None):
        self.hidden_size = hidden_size
        self.forget_bias = forget_bias
        self.compute_dtype = compute_dtype

    def init_params(self, key: jax.Array, input_size: int) -> Params:
        kx, kh = jax.random.split(key)
        h = self.hidden_size
        return {
            "wx": L.xavier_uniform(kx, (input_size, 4 * h)),
            "wh": L.orthogonal(kh, (h, 4 * h)),
            "ln_gamma": jnp.ones((4, h), jnp.float32),
            "ln_beta": jnp.zeros((4, h), jnp.float32),
            "lnc_gamma": jnp.ones((h,), jnp.float32),
            "lnc_beta": jnp.zeros((h,), jnp.float32),
        }

    def initial_carry(self, batch_size: int) -> Carry:
        z = jnp.zeros((batch_size, self.hidden_size), jnp.float32)
        return (z, z)

    @property
    def carry_size(self) -> int:
        return 2 * self.hidden_size

    def unflatten_carry(self, flat: jax.Array) -> Carry:
        c, h = jnp.split(flat, 2, axis=-1)
        return (c, h)

    def __call__(self, params: Params, carry: Carry, x: jax.Array,
                 rdrop_mask: Optional[jax.Array] = None
                 ) -> Tuple[Carry, jax.Array]:
        xp = L.matmul(x, params["wx"], self.compute_dtype)
        return self.step_pre(params, carry, xp, rdrop_mask)

    def precompute_inputs(self, params: Params, xs: jax.Array) -> jax.Array:
        """``[T, B, D] -> [T, B, 4H]``; no bias — the LN betas take that role."""
        return L.matmul(xs, params["wx"], self.compute_dtype)

    def step_pre(self, params: Params, carry: Carry, xp: jax.Array,
                 rdrop_mask: Optional[jax.Array] = None
                 ) -> Tuple[Carry, jax.Array]:
        c, h = carry
        pre = xp + L.matmul(h, params["wh"], self.compute_dtype)
        gates = []
        for j, gate in enumerate(_split_gates(pre)):
            gates.append(L.layer_norm(gate, params["ln_gamma"][j],
                                      params["ln_beta"][j]))
        i, g, f, o = gates
        g = jnp.tanh(g)
        if rdrop_mask is not None:
            g = g * rdrop_mask
        new_c = c * jax.nn.sigmoid(f + self.forget_bias) \
            + jax.nn.sigmoid(i) * g
        normed_c = L.layer_norm(new_c, params["lnc_gamma"], params["lnc_beta"])
        new_h = jnp.tanh(normed_c) * jax.nn.sigmoid(o)
        return (new_c, new_h), new_h


class HyperLSTMCell:
    """HyperNetwork-modulated LSTM (SURVEY §2 component 4, the hard cell).

    A small auxiliary LSTM observes ``[x; h]`` and emits, per step and per
    gate, multiplicative scaling vectors for the input path and the
    recurrent path plus a dynamic bias (arXiv:1609.09106 §4). The main
    gates are layer-normalized.

    The 4x3 hyper projections are fused into three batched einsums so the
    per-step work is a few large MXU matmuls rather than 12 small ones.

    Init scheme (HyperNetworks paper): the ``hyper_h -> embedding``
    projections start at weight 0 / bias 1 and the ``embedding -> scale``
    projections at the constant ``0.1 / embed_size``, so every scale vector
    starts at exactly 0.1 and layer norm restores the magnitude; dynamic
    biases start at 0.
    """

    def __init__(self, hidden_size: int, hyper_size: int = 256,
                 embed_size: int = 32, forget_bias: float = 1.0,
                 use_layer_norm: bool = True, compute_dtype=None):
        self.hidden_size = hidden_size
        self.hyper_size = hyper_size
        self.embed_size = embed_size
        self.forget_bias = forget_bias
        self.use_layer_norm = use_layer_norm
        self.compute_dtype = compute_dtype
        self._hyper_cell = LSTMCell(hyper_size, forget_bias,
                                    compute_dtype=compute_dtype)

    def init_params(self, key: jax.Array, input_size: int) -> Params:
        h, hh, e = self.hidden_size, self.hyper_size, self.embed_size
        keys = jax.random.split(key, 5)
        params: Params = {
            "wx": L.xavier_uniform(keys[0], (input_size, 4 * h)),
            "wh": L.orthogonal(keys[1], (h, 4 * h)),
            "b": jnp.zeros((4 * h,), jnp.float32),
            # hyper_h -> per-gate embeddings, fused over {x-path, h-path}:
            # weight 0, bias 1 => embeddings start at exactly 1.
            "w_hz_x": jnp.zeros((hh, 4 * e), jnp.float32),
            "b_hz_x": jnp.ones((4 * e,), jnp.float32),
            "w_hz_h": jnp.zeros((hh, 4 * e), jnp.float32),
            "b_hz_h": jnp.ones((4 * e,), jnp.float32),
            # bias path: small random hyper_h -> embedding, zero -> bias.
            "w_hz_b": L.normal_init(keys[2], (hh, 4 * e), 0.01),
            # embedding -> scale vectors: constant 0.1/e => scales start 0.1.
            "w_zd_x": jnp.full((4, e, h), 0.1 / e, jnp.float32),
            "w_zd_h": jnp.full((4, e, h), 0.1 / e, jnp.float32),
            "w_zd_b": jnp.zeros((4, e, h), jnp.float32),
            "hyper": self._hyper_cell.init_params(
                keys[3], input_size + h),
        }
        if self.use_layer_norm:
            params.update({
                "ln_gamma": jnp.ones((4, h), jnp.float32),
                "ln_beta": jnp.zeros((4, h), jnp.float32),
                "lnc_gamma": jnp.ones((h,), jnp.float32),
                "lnc_beta": jnp.zeros((h,), jnp.float32),
            })
        return params

    def initial_carry(self, batch_size: int) -> Carry:
        z = jnp.zeros((batch_size, self.hidden_size), jnp.float32)
        return ((z, z), self._hyper_cell.initial_carry(batch_size))

    @property
    def carry_size(self) -> int:
        # main (c, h) plus the hyper LSTM's (c, h), as in the reference's
        # z -> full-state initial-state projection (SURVEY §3.2)
        return 2 * self.hidden_size + 2 * self.hyper_size

    def unflatten_carry(self, flat: jax.Array) -> Carry:
        h = self.hidden_size
        c, hh = flat[..., :h], flat[..., h:2 * h]
        hc, hhh = (flat[..., 2 * h:2 * h + self.hyper_size],
                   flat[..., 2 * h + self.hyper_size:])
        return ((c, hh), (hc, hhh))

    def _scales(self, params: Params, hyper_h: jax.Array, path: str
                ) -> jax.Array:
        """hyper_h -> [B, 4, H] scaling (or bias) vectors for one path."""
        e = self.embed_size
        z = L.matmul(hyper_h, params[f"w_hz_{path}"], self.compute_dtype)
        if path != "b":
            z = z + params[f"b_hz_{path}"]
        z = z.reshape(z.shape[0], 4, e)
        return jnp.einsum("bje,jeh->bjh", z, params[f"w_zd_{path}"],
                          preferred_element_type=jnp.float32)

    def __call__(self, params: Params, carry: Carry, x: jax.Array,
                 rdrop_mask: Optional[jax.Array] = None
                 ) -> Tuple[Carry, jax.Array]:
        xp = self.precompute_inputs(params, x)
        return self.step_pre(params, carry, xp, rdrop_mask)

    def precompute_inputs(self, params: Params, xs: jax.Array
                          ) -> Tuple[jax.Array, jax.Array]:
        """x-dependent projections for main and hyper cells.

        The hyper LSTM consumes ``[x; h]``; its fused input weight splits
        row-wise into an x-part (precomputable for all timesteps at once)
        and an h-part (recurrent, stays in the scan step). Returns
        ``(xs @ wx, xs @ hyper_wx[:D] + hyper_b)``.
        """
        wxh = params["hyper"]["wx"]
        d = wxh.shape[0] - self.hidden_size
        return (L.matmul(xs, params["wx"], self.compute_dtype),
                L.matmul(xs, wxh[:d], self.compute_dtype)
                + params["hyper"]["b"])

    def step_pre(self, params: Params, carry: Carry,
                 xp: Tuple[jax.Array, jax.Array],
                 rdrop_mask: Optional[jax.Array] = None
                 ) -> Tuple[Carry, jax.Array]:
        (c, h), hyper_carry = carry
        xh, hyper_xp = xp
        wxh = params["hyper"]["wx"]
        d = wxh.shape[0] - self.hidden_size
        hyper_pre = hyper_xp + L.matmul(h, wxh[d:], self.compute_dtype)
        hyper_carry, hyper_h = self._hyper_cell.step_pre(
            params["hyper"], hyper_carry, hyper_pre)
        hhp = L.matmul(h, params["wh"], self.compute_dtype)
        b4 = params["b"].reshape(4, self.hidden_size)
        sx = self._scales(params, hyper_h, "x")
        sh = self._scales(params, hyper_h, "h")
        sb = self._scales(params, hyper_h, "b")
        xh = xh.reshape(xh.shape[0], 4, self.hidden_size)
        hhp = hhp.reshape(hhp.shape[0], 4, self.hidden_size)
        pre = sx * xh + sh * hhp + sb + b4
        if self.use_layer_norm:
            gates = [L.layer_norm(pre[:, j], params["ln_gamma"][j],
                                  params["ln_beta"][j]) for j in range(4)]
        else:
            gates = [pre[:, j] for j in range(4)]
        i, g, f, o = gates
        g = jnp.tanh(g)
        if rdrop_mask is not None:
            g = g * rdrop_mask
        new_c = c * jax.nn.sigmoid(f + self.forget_bias) \
            + jax.nn.sigmoid(i) * g
        if self.use_layer_norm:
            out_c = L.layer_norm(new_c, params["lnc_gamma"],
                                 params["lnc_beta"])
        else:
            out_c = new_c
        new_h = jnp.tanh(out_c) * jax.nn.sigmoid(o)
        return ((new_c, new_h), hyper_carry), new_h


def make_cell(kind: str, hidden_size: int, hyper_size: int = 256,
              hyper_embed_size: int = 32, compute_dtype=None):
    """Factory mapping the reference's cell-choice hparam to a cell object.

    ``kind`` ∈ {"lstm", "layer_norm", "hyper"} (SURVEY §5 'Config').
    """
    if kind == "lstm":
        return LSTMCell(hidden_size, compute_dtype=compute_dtype)
    if kind == "layer_norm":
        return LayerNormLSTMCell(hidden_size, compute_dtype=compute_dtype)
    if kind == "hyper":
        return HyperLSTMCell(hidden_size, hyper_size=hyper_size,
                             embed_size=hyper_embed_size,
                             compute_dtype=compute_dtype)
    raise ValueError(f"unknown cell kind {kind!r}")
