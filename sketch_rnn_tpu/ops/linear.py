"""Parameter initializers and a tiny dense helper.

TPU-native equivalent of the reference's ``super_linear`` / orthogonal-init
helpers (SURVEY.md §2 components 2-4; reference unreadable — init schemes per
the canonical sketch-rnn cells and the HyperNetworks paper, arXiv:1609.09106).

All matmuls route through :func:`matmul`, which casts operands to a compute
dtype (bfloat16 on TPU for MXU throughput) while accumulating in float32
via ``preferred_element_type`` — the standard mixed-precision contract on
the MXU.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def orthogonal(key: jax.Array, shape, gain: float = 1.0,
               dtype=jnp.float32) -> jax.Array:
    """Orthogonal init (used for recurrent weights, as in the reference)."""
    if len(shape) < 2:
        raise ValueError("orthogonal init needs >=2 dims")
    rows, cols = int(np.prod(shape[:-1])), shape[-1]
    n = max(rows, cols)
    a = jax.random.normal(key, (n, n), jnp.float32)
    q, r = jnp.linalg.qr(a)
    q = q * jnp.sign(jnp.diag(r))  # make distribution uniform over O(n)
    return (gain * q[:rows, :cols]).reshape(shape).astype(dtype)


def xavier_uniform(key: jax.Array, shape, dtype=jnp.float32) -> jax.Array:
    fan_in, fan_out = shape[0], shape[-1]
    lim = float(np.sqrt(6.0 / (fan_in + fan_out)))
    return jax.random.uniform(key, shape, dtype, -lim, lim)


def normal_init(key: jax.Array, shape, stddev: float,
                dtype=jnp.float32) -> jax.Array:
    return stddev * jax.random.normal(key, shape, dtype)


def matmul(x: jax.Array, w: jax.Array, compute_dtype=None) -> jax.Array:
    """``x @ w`` with optional low-precision operands, f32 accumulation."""
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        w = w.astype(compute_dtype)
    return jnp.matmul(x, w, preferred_element_type=jnp.float32)


def layer_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array,
               eps: float = 1e-6) -> jax.Array:
    """Layer norm over the trailing axis (float32 statistics)."""
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
