"""Pallas fused LSTM sequence kernel — the cuDNN-LSTM equivalent on TPU.

SURVEY.md §2 component 5 ("Native-code census"): the reference's hot path
is cuDNN's fused LSTM; XLA's ``lax.scan`` is the idiomatic replacement and
this kernel is the hand-fused alternative for when profiling shows scan
overhead. Design mirrors cuDNN's layout:

- input projections ``x @ wx`` for ALL timesteps are computed OUTSIDE
  (one large MXU matmul — see ``ops.rnn.run_rnn(hoist=True)``),
- the kernel runs the sequential time loop as a Pallas grid over T
  (TPU grid steps execute in order on a core, so VMEM scratch carries
  (c, h) across steps with zero copies),
- the recurrent weights ``wh [H, 4H]`` are loaded into VMEM once and
  stay resident for all T steps,
- per step: one ``[B, H] @ [H, 4H]`` MXU matmul + fused VPU gate math,
- training reuses cuDNN's "reserve space" trick: the forward saves the
  post-activation gates and cell states, and the backward is a second
  Pallas kernel scanning t = T-1..0 (custom VJP below).

Gate order is ``(i, g, f, o)`` as in :mod:`sketch_rnn_tpu.ops.cells`;
forget-gate bias is applied by the caller's parameters (the kernel adds
``forget_bias`` itself, matching ``LSTMCell``).

Shape constraints (MXU/VPU tiling): ``B`` and ``H`` should be multiples
of 8 and 128 respectively for peak throughput; any shapes compile but
pad internally. Recurrent dropout on the candidate gate streams per-step
masks through the kernel like the inputs.

Profiling verdict (v5e, T=250 B=128 D=133 H=512, fwd+bwd): this kernel
59.6 ms vs XLA scan 53.0 ms — the reserve-space layout writes/reads
``[T, B, 4H]`` gates (262 MB HBM traffic) while XLA's scan AD saves only
the small inputs and recomputes gates in the backward, so at sketch-rnn
shapes the bandwidth bill exceeds the fusion win. Forward-only they tie
(13.1 vs 12.8 ms).

SUPERSEDED: :mod:`sketch_rnn_tpu.ops.pallas_fused` is the production
kernel family — it keeps the fusion but drops the reserve space
entirely (recompute backward, input projection in-kernel, batch tiling,
LayerNorm variant) and BEATS the scan 2.1-2.3x fwd+bwd at the same
shape (scripts/bench_kernel.py). This module stays as the measured
negative result that motivated the redesign and as the simplest
reference implementation of the Pallas sequence-grid pattern.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fwd_kernel(xp_ref, wh_ref, c0_ref, h0_ref, mask_ref,
                hs_ref, cT_ref, hT_ref, gates_ref, cs_ref,
                c_scr, h_scr, *, forget_bias: float, with_mask: bool):
    t = pl.program_id(0)
    nt = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        c_scr[:] = c0_ref[:]
        h_scr[:] = h0_ref[:]

    c, h = c_scr[:], h_scr[:]
    pre = xp_ref[0] + jnp.dot(h, wh_ref[:],
                              preferred_element_type=jnp.float32)
    hdim = c.shape[-1]
    i = jax.nn.sigmoid(pre[:, :hdim])
    g_u = jnp.tanh(pre[:, hdim:2 * hdim])  # unmasked candidate
    g = g_u * mask_ref[0] if with_mask else g_u
    f = jax.nn.sigmoid(pre[:, 2 * hdim:3 * hdim] + forget_bias)
    o = jax.nn.sigmoid(pre[:, 3 * hdim:])
    new_c = c * f + i * g
    new_h = jnp.tanh(new_c) * o

    c_scr[:] = new_c
    h_scr[:] = new_h
    hs_ref[0] = new_h
    # reserve space for the backward pass: post-activation gates + c_{t-1};
    # g is stored UNMASKED (the backward re-applies the mask; tanh' needs
    # the unmasked value)
    gates_ref[0] = jnp.concatenate([i, g_u, f, o], axis=-1)
    cs_ref[0] = c

    @pl.when(t == nt - 1)
    def _():
        cT_ref[:] = new_c
        hT_ref[:] = new_h


def _bwd_kernel(wh_ref, gates_ref, cs_ref, hs_ref, mask_ref,
                dhs_ref, dcT_ref, dhT_ref,
                dxp_ref, dwh_ref, dc0_ref, dh0_ref,
                dc_scr, dh_scr, *, with_mask: bool):
    """Reverse-time grid: program t processes step T-1-t."""
    t = pl.program_id(0)
    nt = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        dc_scr[:] = dcT_ref[:]
        dh_scr[:] = dhT_ref[:]
        dwh_ref[:] = jnp.zeros_like(dwh_ref)

    dh = dh_scr[:] + dhs_ref[0]
    dc = dc_scr[:]

    gates = gates_ref[0]
    hdim = dc.shape[-1]
    i, g_u = gates[:, :hdim], gates[:, hdim:2 * hdim]
    f, o = gates[:, 2 * hdim:3 * hdim], gates[:, 3 * hdim:]
    g = g_u * mask_ref[0] if with_mask else g_u  # masked candidate
    c_prev = cs_ref[0]
    new_c = c_prev * f + i * g
    tanh_c = jnp.tanh(new_c)

    do = dh * tanh_c
    dc = dc + dh * o * (1.0 - tanh_c * tanh_c)
    df = dc * c_prev
    di = dc * g                      # new_c = c*f + i*(g_u*m)
    dg_u = dc * i
    if with_mask:
        dg_u = dg_u * mask_ref[0]
    # pre-activation grads (tanh' uses the UNMASKED candidate)
    d_pre_i = di * i * (1.0 - i)
    d_pre_g = dg_u * (1.0 - g_u * g_u)
    d_pre_f = df * f * (1.0 - f)
    d_pre_o = do * o * (1.0 - o)
    d_pre = jnp.concatenate([d_pre_i, d_pre_g, d_pre_f, d_pre_o], axis=-1)

    dxp_ref[0] = d_pre
    # dh_{t-1} = d_pre @ wh^T ; dwh += h_{t-1}^T @ d_pre
    dh_scr[:] = jnp.dot(d_pre, wh_ref[:].T,
                        preferred_element_type=jnp.float32)
    h_prev = hs_ref[0]  # h_{t-1} (shifted stream, see caller)
    dwh_ref[:] += jnp.dot(h_prev.T, d_pre,
                          preferred_element_type=jnp.float32)
    dc_scr[:] = dc * f

    @pl.when(t == nt - 1)
    def _():
        dc0_ref[:] = dc_scr[:]
        dh0_ref[:] = dh_scr[:]


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def lstm_seq(xp: jax.Array, wh: jax.Array, c0: jax.Array, h0: jax.Array,
             forget_bias: float = 1.0,
             masks: Optional[jax.Array] = None):
    """Fused LSTM over a whole sequence.

    Args:
      xp: ``[T, B, 4H]`` precomputed input projections (x @ wx + b).
      wh: ``[H, 4H]`` recurrent weights.
      c0, h0: ``[B, H]`` initial carry.
      forget_bias: added to the forget gate pre-activation (static).
      masks: optional ``[T, B, H]`` recurrent-dropout masks on the
        candidate gate. A regular (traceable) operand — only its
        *presence* is static; its cotangent is defined as zero (dropout
        masks are never trained through).

    Returns ``(hs [T, B, H], (cT, hT))``.
    """
    hs, cT, hT, _, _ = _fwd(xp, wh, c0, h0, forget_bias, masks)
    return hs, (cT, hT)


def _fwd(xp, wh, c0, h0, forget_bias, masks):
    t, b, h4 = xp.shape
    h = h4 // 4
    with_mask = masks is not None
    mask_arg = masks if with_mask else jnp.zeros((t, 1, 1), xp.dtype)
    kernel = functools.partial(_fwd_kernel, forget_bias=forget_bias,
                               with_mask=with_mask)
    out_shapes = (
        jax.ShapeDtypeStruct((t, b, h), jnp.float32),    # hs
        jax.ShapeDtypeStruct((b, h), jnp.float32),       # cT
        jax.ShapeDtypeStruct((b, h), jnp.float32),       # hT
        jax.ShapeDtypeStruct((t, b, 4 * h), jnp.float32),  # gates reserve
        jax.ShapeDtypeStruct((t, b, h), jnp.float32),    # c_{t-1} reserve
    )
    step_spec = lambda blk: pl.BlockSpec(
        (1, *blk), lambda i: (i, 0, 0), memory_space=pltpu.VMEM)
    full = lambda shape: pl.BlockSpec(
        shape, lambda i: tuple(0 for _ in shape), memory_space=pltpu.VMEM)
    mask_spec = step_spec(mask_arg.shape[1:]) if with_mask \
        else full(mask_arg.shape)
    hs, cT, hT, gates, cs = pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=[step_spec((b, 4 * h)), full((h, 4 * h)),
                  full((b, h)), full((b, h)), mask_spec],
        out_specs=(step_spec((b, h)), full((b, h)), full((b, h)),
                   step_spec((b, 4 * h)), step_spec((b, h))),
        out_shape=out_shapes,
        scratch_shapes=[pltpu.VMEM((b, h), jnp.float32),
                        pltpu.VMEM((b, h), jnp.float32)],
        interpret=_interpret_default(),
    )(xp, wh, c0, h0, mask_arg)
    return hs, cT, hT, gates, cs


def _lstm_seq_fwd(xp, wh, c0, h0, forget_bias, masks):
    hs, cT, hT, gates, cs = _fwd(xp, wh, c0, h0, forget_bias, masks)
    return (hs, (cT, hT)), (wh, gates, cs, hs, h0, masks)


def _lstm_seq_bwd(forget_bias, residuals, grads):
    del forget_bias
    wh, gates, cs, hs, h0, masks = residuals
    dhs, (dcT, dhT) = grads
    t, b, h = dhs.shape
    with_mask = masks is not None
    mask_arg = masks if with_mask else jnp.zeros((t, 1, 1), dhs.dtype)

    # h_{t-1} stream: [h0, h_0..h_{T-2}]
    h_prev = jnp.concatenate([h0[None], hs[:-1]], axis=0)

    def rev(x):  # reverse-time streaming order for the backward grid
        return jnp.flip(x, axis=0)

    kernel = functools.partial(_bwd_kernel, with_mask=with_mask)
    step_spec = lambda blk: pl.BlockSpec(
        (1, *blk), lambda i: (i, 0, 0), memory_space=pltpu.VMEM)
    full = lambda shape: pl.BlockSpec(
        shape, lambda i: tuple(0 for _ in shape), memory_space=pltpu.VMEM)
    mask_spec = step_spec(mask_arg.shape[1:]) if with_mask \
        else full(mask_arg.shape)
    out_shapes = (
        jax.ShapeDtypeStruct((t, b, 4 * h), jnp.float32),  # dxp (reversed)
        jax.ShapeDtypeStruct(wh.shape, jnp.float32),       # dwh
        jax.ShapeDtypeStruct((b, h), jnp.float32),         # dc0
        jax.ShapeDtypeStruct((b, h), jnp.float32),         # dh0
    )
    dxp_rev, dwh, dc0, dh0 = pl.pallas_call(
        kernel,
        grid=(t,),
        in_specs=[full(wh.shape), step_spec((b, 4 * h)), step_spec((b, h)),
                  step_spec((b, h)), mask_spec, step_spec((b, h)),
                  full((b, h)), full((b, h))],
        out_specs=(step_spec((b, 4 * h)), full(wh.shape),
                   full((b, h)), full((b, h))),
        out_shape=out_shapes,
        scratch_shapes=[pltpu.VMEM((b, h), jnp.float32),
                        pltpu.VMEM((b, h), jnp.float32)],
        interpret=_interpret_default(),
    )(wh, rev(gates), rev(cs), rev(h_prev),
      rev(mask_arg) if with_mask else mask_arg, rev(dhs), dcT, dhT)
    dmasks = jnp.zeros_like(masks) if masks is not None else None
    return rev(dxp_rev), dwh, dc0, dh0, dmasks


lstm_seq.defvjp(_lstm_seq_fwd, _lstm_seq_bwd)
