"""Time-major scan runners: the TPU replacement for cuDNN fused RNNs.

SURVEY.md §2 component 5: the reference's hot path is cuDNN's fused LSTM;
on TPU the idiomatic equivalent is ``lax.scan`` over a single fused step —
XLA unrolls nothing, keeps weights resident, and fuses the elementwise gate
math into the matmuls. Components 6 and 8 (bi-directional encoder scan,
teacher-forced decoder scan) sit on these runners.

Everything is time-major ``[T, B, D]``: scan's leading axis is time, so no
transposes appear inside the compiled loop body.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def make_dropout_masks(key: jax.Array, keep_prob: float, steps: int,
                       batch_size: int, hidden_size: int) -> jax.Array:
    """Per-step inverted-dropout masks ``[T, B, H]`` for recurrent dropout.

    Generated outside the scan so the cell step stays pure; scanned in as
    xs. Matches the reference semantics of a fresh mask per timestep.
    """
    m = jax.random.bernoulli(key, keep_prob, (steps, batch_size, hidden_size))
    return m.astype(jnp.float32) / keep_prob


def _match_vma(carry, ref: jax.Array):
    """Mark ``carry`` as varying over ``ref``'s manual mesh axes.

    Inside ``shard_map`` (the data-parallel step), a zeros initial carry
    is unvarying while the scan/kernel outputs vary over the data axis —
    JAX 0.9's varying-manual-axes tracking rejects that carry mismatch.
    Broadcasting the carry to the inputs' vma fixes it without the cell
    or model code knowing the mesh axis; a no-op outside shard_map.
    """
    from sketch_rnn_tpu.ops.pallas_fused import vma_of

    vma = vma_of(ref)
    if not vma:
        return carry

    def widen(c):
        missing = tuple(vma - vma_of(c))
        return jax.lax.pcast(c, missing, to="varying") if missing else c

    return jax.tree_util.tree_map(widen, carry)


def _concat_extra(xs: jax.Array, extra: jax.Array) -> jax.Array:
    """Broadcast time-invariant features over T and concatenate to xs."""
    t = xs.shape[0]
    return jnp.concatenate(
        [xs, jnp.broadcast_to(extra[None], (t, *extra.shape))], axis=-1)


def _run_fused(cell, params, xs, carry0, rdrop_masks, reverse, rdrop_gen,
               residual_dtype=None, x_extra=None, seq_only=False):
    """Dispatch to the Pallas recompute-backward kernels (ops.pallas_fused).

    Covers all three cells (LSTM / LayerNormLSTM / HyperLSTM). ``reverse``
    flips inputs and outputs around the kernel. ``rdrop_gen`` maps to the
    kernels' IN-KERNEL PRNG dropout (a seed derived from the key; the TPU
    PRNG draws each step's mask inside the kernel, so no [T, B, H] mask
    buffer exists in HBM — the kernel equivalent of the scan path's
    in-loop draws; distributionally identical, different bits).
    """
    from sketch_rnn_tpu.ops.cells import (HyperLSTMCell, LayerNormLSTMCell,
                                          LSTMCell)
    from sketch_rnn_tpu.ops import pallas_fused as PF

    masks = rdrop_masks
    seed, keep = None, 1.0
    if rdrop_gen is not None:
        key, keep = rdrop_gen
        seed = jax.random.randint(key, (), 0, jnp.iinfo(jnp.int32).max,
                                  dtype=jnp.int32)
    if reverse:
        xs = jnp.flip(xs, axis=0)
        if masks is not None:
            masks = jnp.flip(masks, axis=0)
    # inside shard_map every kernel operand must share the inputs'
    # varying axes (each device owns its copy of the replicated params;
    # carry0 was already matched by run_rnn); no-ops outside shard_map
    params = _match_vma(params, xs)
    masks = _match_vma(masks, xs) if masks is not None else None
    seed = _match_vma(seed, xs) if seed is not None else None
    cd = cell.compute_dtype
    cast = (lambda w: w.astype(cd)) if cd else (lambda w: w)
    wx, wh = cast(params["wx"]), cast(params["wh"])
    xb = None
    if x_extra is not None:
        # time-invariant inputs (z, class embedding): project ONCE into a
        # per-example [B, 4H] bias instead of streaming them through every
        # step's xs — the kernel's per-step matmul shrinks from
        # [*, d+E] @ [d+E, 4H] to [*, d] @ [d, 4H] and no [T, B, E]
        # broadcast ever exists in HBM
        d_s = xs.shape[-1]
        wx_e = cast(params["wx"][d_s:])
        wx = cast(params["wx"][:d_s])
        xb = jnp.dot(x_extra.astype(wx_e.dtype), wx_e,
                     preferred_element_type=jnp.float32)
    rd = residual_dtype if residual_dtype is not None else jnp.float32
    if isinstance(cell, HyperLSTMCell):
        if not cell.use_layer_norm:
            raise NotImplementedError(
                "fused HyperLSTM kernel covers the layer-norm variant "
                "(the only one make_cell builds)")
        (c0, h0), (hc0, hh0) = carry0
        hyper = params["hyper"]
        d_in = hyper["wx"].shape[0] - cell.hidden_size
        xbh = None
        if x_extra is not None:
            # the aux LSTM also consumes [x; h]: its x-part splits into a
            # per-step stroke projection and a per-example extra bias
            d_s = xs.shape[-1]
            wxh_e = cast(hyper["wx"][d_s:d_in])
            xbh = jnp.dot(x_extra.astype(wxh_e.dtype), wxh_e,
                          preferred_element_type=jnp.float32)
            wxh_x = cast(hyper["wx"][:d_s])
        else:
            wxh_x = cast(hyper["wx"][:d_in])
        hs, fin = PF.fused_hyper_lstm(
            xs, wx, params["b"], wh,
            wxh_x, cast(hyper["wx"][d_in:]), hyper["b"],
            cast(hyper["wh"]),
            cast(params["w_hz_x"]), params["b_hz_x"],
            cast(params["w_hz_h"]), params["b_hz_h"],
            cast(params["w_hz_b"]),
            params["w_zd_x"], params["w_zd_h"], params["w_zd_b"],
            params["ln_gamma"], params["ln_beta"],
            params["lnc_gamma"], params["lnc_beta"],
            c0, h0, hc0, hh0, cell.forget_bias, masks, seed, keep, rd,
            xb, xbh)
    elif isinstance(cell, LayerNormLSTMCell):
        c0, h0 = carry0
        hs, fin = PF.fused_ln_lstm(
            xs, wx, wh, params["ln_gamma"], params["ln_beta"],
            params["lnc_gamma"], params["lnc_beta"], c0, h0,
            cell.forget_bias, masks, seed, keep, rd, xb)
    else:
        c0, h0 = carry0
        if seq_only and xb is None:
            # encoder fast path: no final carry, no input/initial-carry
            # grads (xs is data, carries are constant zeros) -> the seq
            # kernel's backward fits twice the batch tile
            # (ops.pallas_fused._batch_tile_seq)
            hs = PF.fused_lstm_seq(xs, wx, params["b"], wh, c0, h0,
                                   cell.forget_bias, masks, seed, keep, rd)
            fin = None
        else:
            hs, fin = PF.fused_lstm(xs, wx, params["b"], wh, c0, h0,
                                    cell.forget_bias, masks, seed, keep,
                                    rd, xb)
    if reverse:
        hs = jnp.flip(hs, axis=0)
    return fin, hs


def fused_supported(cell) -> bool:
    """True when ``cell`` has a Pallas fused kernel (ops.pallas_fused)."""
    from sketch_rnn_tpu.ops.cells import (HyperLSTMCell, LayerNormLSTMCell,
                                          LSTMCell)
    if isinstance(cell, HyperLSTMCell):
        return cell.use_layer_norm
    return type(cell) in (LSTMCell, LayerNormLSTMCell)


def run_rnn(cell, params, xs: jax.Array, carry0: Optional[Any] = None,
            rdrop_masks: Optional[jax.Array] = None, reverse: bool = False,
            hoist: bool = False,
            rdrop_gen: Optional[Tuple[jax.Array, float]] = None,
            remat: bool = False, fused: bool = False,
            residual_dtype=None,
            x_extra: Optional[jax.Array] = None,
            need_final: bool = True) -> Tuple[Any, jax.Array]:
    """Scan ``cell`` over time-major inputs ``xs`` of shape ``[T, B, D]``.

    Returns ``(final_carry, hs)`` with ``hs`` of shape ``[T, B, H]``.
    ``reverse=True`` runs the sequence back-to-front but returns outputs in
    the original time order (for the backward half of the encoder).

    ``hoist=True`` precomputes the input projections for ALL timesteps as
    one large MXU matmul before the scan — the cuDNN-style layout (SURVEY
    §2 component 5): the loop then carries only the recurrent ``h @ wh``
    matmul. Measured on a v5e chip at the flagship decoder shape
    (T=250, B=128, D=133, H=512, fwd+bwd): hoist=False 53ms vs
    hoist=True 62ms — scan AD saves the hoisted ``[T, B, 4H]`` projections
    as residuals (262 MB of HBM traffic) while the per-step path saves
    only ``xs`` (17 MB) and recomputes, so hoisting LOSES under autodiff
    and is off by default. Forward-only the two are equal (12.8 vs 13.1
    ms); hoist remains available for inference-style sweeps.

    Recurrent dropout comes in two forms: ``rdrop_masks`` streams
    precomputed ``[T, B, H]`` masks (exact-equivalence testing), while
    ``rdrop_gen=(key, keep_prob)`` draws each step's mask INSIDE the scan
    from ``fold_in(key, t)`` — no mask buffer ever exists in HBM, which
    at batch 1024 saves 500 MB of residuals per RNN. The two paths are
    distributionally identical but draw different bits.

    ``remat=True`` wraps the step in ``jax.checkpoint``: the backward
    recomputes gate math from the carries instead of saving per-step
    intermediates — the standard FLOPs-for-HBM trade that unlocks large
    global batches (the OOM at batch 1024 f32 was exactly these
    residuals).

    ``residual_dtype`` (fused path only): storage dtype for the kernels'
    saved streams — bfloat16 halves residual HBM footprint/bandwidth at
    ~0.4% relative gradient noise; None keeps float32.

    ``x_extra`` (``[B, E]``, optional): TIME-INVARIANT input features
    (the decoder's z and class embedding). The cell's input weights must
    cover ``xs.width + E`` rows. On the fused path these are projected
    once into per-example gate biases (no ``[T, B, E]`` broadcast in
    HBM, narrower per-step matmuls; the hyper cell gets a second bias
    for its aux LSTM); on the scan path they are broadcast and
    concatenated — identical semantics either way.

    ``need_final=False`` declares that the caller uses only ``hs`` (not
    the returned final carry) and that NEITHER ``xs`` NOR ``carry0`` is
    differentiated (encoder contract: inputs are the data batch); with
    default (zero) carries the fused LSTM path then runs the
    sequence-only kernel, which drops the input/carry gradient blocks
    from its backward and fits double the batch tile. The returned
    final carry may be ``None`` in that case.
    """
    use_fused = fused and fused_supported(cell)
    if x_extra is not None and not use_fused:
        xs = _concat_extra(xs, x_extra)
        x_extra = None
    zero_carry = carry0 is None
    if carry0 is None:
        carry0 = cell.initial_carry(xs.shape[1])
    carry0 = _match_vma(carry0, xs)
    if rdrop_masks is not None and rdrop_gen is not None:
        raise ValueError("pass rdrop_masks or rdrop_gen, not both")

    if use_fused:
        # Pallas recompute-backward kernel (ops.pallas_fused): measured
        # 1.6-2.3x faster fwd+bwd than this scan per cell at T=250 B=128
        # H=512 on v5e (scripts/bench_kernel.py); remat is moot there
        # (the kernels save only the carry streams and recompute gates)
        return _run_fused(cell, params, xs, carry0, rdrop_masks, reverse,
                          rdrop_gen, residual_dtype, x_extra,
                          seq_only=not need_final and zero_carry)

    inputs = cell.precompute_inputs(params, xs) if hoist else xs
    stepper = cell.step_pre if hoist else cell

    if rdrop_gen is not None:
        key, keep = rdrop_gen
        b, h = xs.shape[1], cell.hidden_size

        def step(carry, inp):
            x, t = inp
            m = jax.random.bernoulli(
                jax.random.fold_in(key, t), keep, (b, h)
            ).astype(jnp.float32) / keep
            return stepper(params, carry, x, rdrop_mask=m)

        scan_xs = (inputs, jnp.arange(xs.shape[0]))
    elif rdrop_masks is not None:
        def step(carry, inp):
            x, m = inp
            return stepper(params, carry, x, rdrop_mask=m)

        scan_xs = (inputs, rdrop_masks)
    else:
        def step(carry, x):
            return stepper(params, carry, x)

        scan_xs = inputs

    if remat:
        step = jax.checkpoint(step)
    final, hs = lax.scan(step, carry0, scan_xs, reverse=reverse)
    return final, hs


def final_hidden(cell, carry) -> jax.Array:
    """Extract the hidden state ``h`` from a cell's carry."""
    # LSTM carry is (c, h); HyperLSTM carry is ((c, h), hyper_carry).
    head = carry[0]
    if isinstance(head, tuple):
        return head[1]
    return carry[1]


def length_reverse_indices(t: int, seq_len: jax.Array) -> jax.Array:
    """``[T, B]`` time indices that flip each sequence's valid prefix
    ``[0, len)`` and keep the padding rows in place — the reference's
    length-aware reversal as a static-shape gather index."""
    idx = jnp.arange(t)[:, None]                      # [T, 1]
    return jnp.where(idx < seq_len[None, :],
                     seq_len[None, :] - 1 - idx, idx)  # [T, B]


def bidirectional_rnn(cell_fwd, cell_bwd, params_fwd, params_bwd,
                      xs: jax.Array,
                      seq_len: Optional[jax.Array] = None,
                      rdrop_masks_fwd: Optional[jax.Array] = None,
                      rdrop_masks_bwd: Optional[jax.Array] = None,
                      rdrop_gen_fwd: Optional[Tuple[jax.Array, float]] = None,
                      rdrop_gen_bwd: Optional[Tuple[jax.Array, float]] = None,
                      remat: bool = False, fused: bool = False,
                      residual_dtype=None,
                      xs_rev: Optional[jax.Array] = None,
                      ) -> Tuple[jax.Array, jax.Array]:
    """Forward + backward scans; returns ``(h_final_concat, hs_concat)``.

    ``h_final_concat`` is ``[B, 2H]`` — the forward scan's state at the
    last *valid* step per sequence and the backward scan's state at t=0.

    The reference feeds fixed-length padded sequences to a sequence-length-
    aware bidirectional RNN (SURVEY §3.2). On TPU we keep shapes static:
    both scans run the full padded length, and ``seq_len`` selects the
    forward hidden state at each sequence's true end from the stacked
    outputs (a gather, not a dynamic loop). For the backward direction the
    padded tail is *before* the true data in reversed order; the reference
    masks it out by length-aware reversal, which here becomes flipping only
    the valid prefix via gather indices.

    ``xs_rev``: optionally pass the length-aware-reversed inputs
    (``take_along_axis(xs, length_reverse_indices(T, seq_len))``)
    pre-computed. The gather commutes with any elementwise prep
    (dequant/upcast) and with the time-major transpose, and on the
    [T, B, 5] stream it runs over the LANE-PADDED (5 -> 128) physical
    layout — ~6.8 ms/step at the flagship shape (measured,
    scripts/probe_enc_pocket.py) vs ~2 ms when the caller gathers the
    compact batch-major raw strokes instead (models.vae._forward).
    """
    t = xs.shape[0]
    if seq_len is None and xs_rev is not None:
        raise ValueError(
            "xs_rev was supplied but seq_len is None: the no-seq_len "
            "path runs a plain reverse scan over xs and would silently "
            "ignore the caller's length-aware-reversed inputs")
    if seq_len is None:
        fwd_carry, hs_f = run_rnn(cell_fwd, params_fwd, xs,
                                  rdrop_masks=rdrop_masks_fwd,
                                  rdrop_gen=rdrop_gen_fwd, remat=remat,
                                  fused=fused, residual_dtype=residual_dtype)
        bwd_carry, hs_b = run_rnn(cell_bwd, params_bwd, xs,
                                  rdrop_masks=rdrop_masks_bwd,
                                  rdrop_gen=rdrop_gen_bwd, remat=remat,
                                  reverse=True, fused=fused,
                                  residual_dtype=residual_dtype)
        h_f = final_hidden(cell_fwd, fwd_carry)
        h_b = final_hidden(cell_bwd, bwd_carry)
    else:
        # length-aware reversal: for each batch element flip its valid
        # prefix [0, len) and keep the padding in place (unless the
        # caller already gathered it on the cheaper compact layout)
        rev_idx = length_reverse_indices(t, seq_len)
        if xs_rev is None:
            xs_rev = jnp.take_along_axis(xs, rev_idx[:, :, None], axis=0)
        # need_final=False: the final-valid state comes from hs (gather
        # below), carries are the default zeros -> the fused LSTM path
        # takes the sequence-only kernel with the doubled batch tile
        _, hs_f = run_rnn(cell_fwd, params_fwd, xs,
                          rdrop_masks=rdrop_masks_fwd,
                          rdrop_gen=rdrop_gen_fwd, remat=remat, fused=fused,
                          residual_dtype=residual_dtype, need_final=False)
        # dropout masks are i.i.d. per step, so they need no matching reversal
        _, hs_b_rev = run_rnn(cell_bwd, params_bwd, xs_rev,
                              rdrop_masks=rdrop_masks_bwd,
                              rdrop_gen=rdrop_gen_bwd, remat=remat,
                              fused=fused, residual_dtype=residual_dtype,
                              need_final=False)
        # forward state at the last valid step, as a one-hot contraction
        # rather than take_along_axis: the gather's BACKWARD lowers to an
        # XLA scatter into [T, B, H], which on v5e measured ~55 ms/step
        # inside the training program (~24% of the whole step!) — the
        # cost hid from standalone probes because with frozen params the
        # cotangent being scattered is loop-invariant and XLA hoists it
        # out of timing chains (r4 glue_ladder bisection). The one-hot
        # einsum is EXACT (each output element is one input element
        # times 1.0, f32-accumulated) and both its forward and backward
        # are dense matmuls on the MXU.
        last = jnp.clip(seq_len - 1, 0, t - 1)            # [B]
        onehot = jax.nn.one_hot(last, t, dtype=hs_f.dtype)  # [B, T]
        h_f = jnp.einsum("tbh,bt->bh", hs_f, onehot,
                         preferred_element_type=jnp.float32
                         ).astype(hs_f.dtype)
        h_b = jnp.einsum("tbh,bt->bh", hs_b_rev, onehot,
                         preferred_element_type=jnp.float32
                         ).astype(hs_b_rev.dtype)
        hs_b = jnp.take_along_axis(hs_b_rev, rev_idx[:, :, None], axis=0)
    h_final = jnp.concatenate([h_f, h_b], axis=-1)
    hs = jnp.concatenate([hs_f, hs_b], axis=-1)
    return h_final, hs
