"""Asynchronous checkpointing: background writer off the dispatch path.

The synchronous ``save_checkpoint`` blocks the training loop's hot thread
on a device->host fetch (which first waits for every dispatched step to
finish) plus a msgpack serialization and file write — at an aggressive
``save_every`` that stall is the dominant host-side goodput loss
(ISSUE 3; the overlap design production JAX stacks use, arXiv:2204.06514).

``AsyncCheckpointer`` removes the stall in three moves:

1. **Device snapshot on the loop thread** — ``save()`` makes a device-side
   copy of the state (``jnp.copy`` per leaf: an async-dispatched HBM
   copy, enqueued after the producing step, so the host does not wait).
   The copy is essential for correctness, not a nicety: the train step
   donates its input state buffers (``donate_argnums=0``), so the NEXT
   dispatched step invalidates the arrays the loop just held — the
   snapshot gives the writer arrays nobody will donate. A
   ``copy_to_host_async`` on each snapshot leaf then starts the D2H
   transfer early so it overlaps device compute.
2. **Fetch + serialize + commit on a writer thread** — the blocking
   ``jax.device_get`` (waits for the snapshot copy to land) and the
   msgpack write happen off the hot thread, through the SAME
   ``checkpoint.write_checkpoint`` commit path as the sync save
   (sidecar-first, temp file + rename), so files are byte-identical to
   the sync path's and a kill mid-write never corrupts
   ``latest_checkpoint``.
3. **Backpressure: at most ONE in-flight save** — ``save()`` joins the
   pending writer before starting the next, and ``wait()`` joins at loop
   exit; saves can never pile up or reorder, and the loop's only
   checkpoint stall is the (steady-state ~zero) join of a long-finished
   write. A writer failure is stored and re-raised on the next
   ``save()``/``wait()`` — the sync path's failure-stops-training
   semantics, at most one save late.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.train.checkpoint import write_checkpoint
from sketch_rnn_tpu.train.state import TrainState
from sketch_rnn_tpu.utils.faults import fault_point
from sketch_rnn_tpu.utils.telemetry import get_telemetry


def snapshot_device_state(state: TrainState) -> TrainState:
    """Donation-safe device snapshot with the D2H transfer started.

    Returns a tree of fresh device arrays (async HBM copies — the host
    does not block) on which ``copy_to_host_async`` has been called, so a
    later ``jax.device_get`` only waits for transfers that overlap the
    already-dispatched compute.
    """
    snap = jax.tree_util.tree_map(jnp.copy, state)
    for leaf in jax.tree_util.tree_leaves(snap):
        # start the device->host transfer without blocking; device_get on
        # the writer thread then awaits the cached copy
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()
    return snap


class AsyncCheckpointer:
    """One-deep background checkpoint writer for a single directory.

    Not thread-safe across callers: exactly one loop thread calls
    ``save``/``wait``/``close`` (the training loop's usage). The writer
    thread only ever touches its private snapshot and the checkpoint
    directory.
    """

    def __init__(self, ckpt_dir: str, keep: int = 3):
        self.ckpt_dir = ckpt_dir
        self.keep = keep
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self.last_path: Optional[str] = None
        self.saves_started = 0

    # -- loop-thread API ---------------------------------------------------

    def save(self, state: TrainState, scale_factor: float,
             hps: HParams) -> None:
        """Snapshot ``state`` and commit it in the background.

        Joins any pending save first (backpressure: at most one
        in-flight), re-raising its failure — so a dead disk stops
        training at the NEXT save, exactly one cadence window late.
        """
        self.wait()
        # telemetry (ISSUE 6): the loop-thread snapshot and the writer
        # thread's fetch/commit are spanned under cat "ckpt", so an
        # exported trace shows the background save's lifetime against
        # the loop's (steady-state ~zero) ckpt_wait joins
        with get_telemetry().span("snapshot", cat="ckpt"):
            snap = snapshot_device_state(state)
        self.saves_started += 1
        self._thread = threading.Thread(
            target=self._write, args=(snap, float(scale_factor), hps),
            name="ckpt-writer", daemon=True)
        self._thread.start()

    def wait(self) -> None:
        """Join the in-flight save (if any); re-raise its failure."""
        self.join()
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise RuntimeError(
                f"async checkpoint write to {self.ckpt_dir} failed"
            ) from exc

    def join(self) -> None:
        """Join the in-flight save WITHOUT raising (for ``finally``
        blocks, where a writer error must not mask the propagating
        one; the stored failure still surfaces on the next
        ``wait()``/``save()``)."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None

    @property
    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def failure(self) -> Optional[BaseException]:
        """Peek at a stored background-write failure without clearing
        it (``wait()`` re-raises and clears) — for finally-block
        reporting, where raising is forbidden but silence loses the
        operator's only signal that a checkpoint never landed."""
        return self._exc

    # -- writer thread -----------------------------------------------------

    def _write(self, snap: TrainState, scale_factor: float,
               hps: HParams) -> None:
        try:
            tel = get_telemetry()
            # fault site (ISSUE 10): a writer-thread death BEFORE the
            # commit path's own retry loop — exercises the stored-
            # failure -> raise-one-save-late contract end to end
            fault_point("ckpt.writer")
            with tel.span("fetch", cat="ckpt"):
                host_state = jax.device_get(snap)
            with tel.span("commit", cat="ckpt"):
                # transient commit I/O failures retry with bounded
                # deterministic backoff (ISSUE 10); only a PERMANENT
                # failure (budget exhausted) lands in _exc and stops
                # training one save late
                self.last_path = write_checkpoint(
                    self.ckpt_dir, host_state, scale_factor, hps,
                    keep=self.keep, retries=hps.ckpt_retries,
                    retry_backoff_s=hps.ckpt_retry_backoff_s)
        except BaseException as e:  # noqa: BLE001 — must cross the thread
            self._exc = e
