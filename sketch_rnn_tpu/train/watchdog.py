"""Training health watchdog: anomaly detection over the metrics stream.

ISSUE 7 tentpole piece 2. PR 3-6 made every training pathology
*recorded* — NaN losses stop the run (``check_finite``), stalls land in
the GoodputLedger's ``t_<phase>_s`` columns, throughput in
``steps_per_sec`` — but nothing *watches* the stream: a loss spike at
step 40k is found by a human reading the CSV after the run (the
TensorFlow system paper treats continuous health monitoring as part of
a production training system, not an afterthought).

Two layers, split so the detection logic stays testable in isolation:

- :class:`Watchdog` — a PURE detector. ``feed(step, row)`` takes one
  metrics row (the exact dict the ``MetricsWriter`` persists) and
  returns the anomalies it implies. No I/O, no telemetry, no wall
  clock: deterministic for a deterministic row stream, which is what
  lets a test inject a synthetic loss-spike corpus and pin the trip
  step. Detectors:

  * **non-finite** — any NaN/inf value in the row (named per metric);
  * **spike** — rolling robust z-score (median + MAD over the last
    ``window`` rows) on ``spike_metrics`` (loss, grad_norm by
    default); only UPWARD excursions flag (a falling loss is the
    point of training). MAD-based, so the baseline tolerates the
    occasional prior spike without drifting (mean/stddev would);
  * **stall** — the GoodputLedger phase columns: when the window's
    accounted host time is dominated by non-compute phases
    (feeder_wait / ckpt_wait / metrics_drain), the loop is starving,
    not training;
  * **throughput collapse** — ``steps_per_sec`` under
    ``collapse_frac`` x its rolling median.

- :class:`WatchdogMonitor` — the (thin) impure wrapper the training
  loop installs on the metrics drain. On a trip it emits a telemetry
  incident event (cat ``watchdog`` — visible live on the /metrics
  endpoint via the ``incidents`` counter and in the exported trace),
  writes a structured ``incident.json`` post-mortem (the anomalies,
  the last-K metrics rows, the telemetry snapshot), prints one warning
  — and, only with ``halt=True`` (``cli train --halt_on_anomaly``),
  raises :class:`AnomalyHalt`, which the loop turns into a forced
  post-mortem checkpoint under ``<workdir>/incident/`` (NOT the resume
  directory: a possibly-diverged state must never become
  ``latest_checkpoint``) before propagating.

OFF by default and bitwise-invisible when off (the PR 6 pin extended):
``train()`` builds no monitor unless asked, and a warn-only watchdog on
a healthy run writes nothing and changes no logged value — it only
reads rows the drain already produced. Rows arrive one window late
under ``metrics_defer`` (the PR 3 contract), so detection latency is
one log window — the same latency ``check_finite`` already has.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from sketch_rnn_tpu.utils import faults as _faults
from sketch_rnn_tpu.utils.telemetry import get_telemetry, json_safe

INCIDENT_FILE = "incident.json"
INCIDENT_CKPT_DIR = "incident"

# the module-level registry of armed monitors, for the tier-1 conftest
# guard: tests must never leak an armed watchdog (train() disarms in
# its finally)
_ARMED: set = set()


class AnomalyHalt(RuntimeError):
    """Raised by a halting monitor; carries the trip's anomalies."""

    def __init__(self, step: int, anomalies: List["Anomaly"]):
        self.step = step
        self.anomalies = anomalies
        names = ", ".join(f"{a.kind}:{a.metric}" for a in anomalies)
        super().__init__(
            f"watchdog halt at step {step}: {names} — see incident.json "
            f"in the workdir for the post-mortem")


@dataclasses.dataclass
class Anomaly:
    """One detected anomaly: what tripped, on which metric, and the
    evidence (value vs threshold) a post-mortem needs."""

    kind: str        # "nonfinite" | "spike" | "stall" | "throughput"
    metric: str      # the offending metric/column name
    step: int
    value: float
    threshold: float
    detail: str

    def to_json(self) -> Dict:
        d = dataclasses.asdict(self)
        # json.dump rejects inf/nan only under allow_nan=False, but a
        # post-mortem must stay loadable by strict parsers either way
        for k in ("value", "threshold"):
            if not math.isfinite(d[k]):
                d[k] = repr(d[k])
        return d


class Watchdog:
    """Pure anomaly detector over the training metrics-row stream.

    ``feed(step, row)`` returns the row's anomalies (usually empty) and
    then absorbs the row into its rolling state. Rolling baselines use
    median + MAD over the previous ``window`` rows and activate only
    after ``min_history`` rows, so startup transients (the first
    windows include compile time and an untrained loss cliff) cannot
    trip. ``last_rows(k)`` returns the most recent rows for the
    incident post-mortem.
    """

    def __init__(self,
                 spike_metrics: Sequence[str] = ("loss", "grad_norm"),
                 window: int = 32,
                 min_history: int = 8,
                 z_thresh: float = 8.0,
                 stall_phases: Sequence[str] = ("feeder_wait",
                                                "ckpt_wait",
                                                "metrics_drain"),
                 stall_frac: float = 0.75,
                 stall_min_s: float = 1.0,
                 collapse_metric: str = "steps_per_sec",
                 collapse_frac: float = 0.25,
                 keep_rows: int = 16):
        if window < 2 or min_history < 2:
            raise ValueError("window and min_history must be >= 2")
        if min_history > window:
            raise ValueError(f"min_history={min_history} exceeds "
                             f"window={window}")
        self.spike_metrics = tuple(spike_metrics)
        self.window = window
        self.min_history = min_history
        self.z_thresh = z_thresh
        self.stall_phases = tuple(stall_phases)
        self.stall_frac = stall_frac
        self.stall_min_s = stall_min_s
        self.collapse_metric = collapse_metric
        self.collapse_frac = collapse_frac
        self._hist: Dict[str, deque] = {
            m: deque(maxlen=window)
            for m in (*self.spike_metrics, collapse_metric)}
        self._rows: deque = deque(maxlen=keep_rows)
        self._rows_seen = 0

    # -- rolling-statistic helpers ----------------------------------------

    @staticmethod
    def _median(xs: List[float]) -> float:
        s = sorted(xs)
        n = len(s)
        mid = n // 2
        return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])

    def _robust_z(self, x: float, hist: deque) -> Optional[float]:
        """|x - median| / (1.4826 * MAD), sign-aware (positive above
        the median). The denominator is floored at 1% of |median| so a
        near-constant history (MAD ~ 0) answers float jitter with a
        finite z instead of tripping on nothing."""
        if len(hist) < self.min_history:
            return None
        xs = list(hist)
        med = self._median(xs)
        mad = self._median([abs(v - med) for v in xs])
        denom = 1.4826 * mad + 0.01 * abs(med) + 1e-12
        return (x - med) / denom

    # -- detection ---------------------------------------------------------

    def feed(self, step: int, row: Dict[str, float]) -> List[Anomaly]:
        """Detect anomalies in ``row``, then absorb it into the rolling
        state (detection always compares against PRIOR rows only, so a
        spike cannot soften its own threshold)."""
        out: List[Anomaly] = []
        for k, v in sorted(row.items()):
            if k != "wall_time" and not math.isfinite(float(v)):
                out.append(Anomaly(
                    kind="nonfinite", metric=k, step=step,
                    value=float(v), threshold=math.inf,
                    detail=f"{k} went non-finite"))
        for m in self.spike_metrics:
            if m not in row or not math.isfinite(float(row[m])):
                continue
            z = self._robust_z(float(row[m]), self._hist[m])
            if z is not None and z > self.z_thresh:
                out.append(Anomaly(
                    kind="spike", metric=m, step=step,
                    value=float(row[m]), threshold=self.z_thresh,
                    detail=f"{m} robust z-score {z:.1f} > "
                           f"{self.z_thresh:g} over the last "
                           f"{len(self._hist[m])} rows"))
        out.extend(self._check_stall(step, row))
        out.extend(self._check_collapse(step, row))
        # absorb AFTER detection; keep non-finite values out of the
        # rolling baselines (one NaN would poison every later MAD)
        for m in self._hist:
            if m in row and math.isfinite(float(row[m])):
                self._hist[m].append(float(row[m]))
        self._rows.append({"step": step, **row})
        self._rows_seen += 1
        return out

    def _check_stall(self, step: int,
                     row: Dict[str, float]) -> List[Anomaly]:
        # startup gate, like the z-score detectors: the first windows
        # legitimately look stalled (prefetch queue filling, writer
        # threads warming) — the docstring's no-startup-trips promise
        # applies to every detector, not just the statistical ones
        if self._rows_seen < self.min_history:
            return []
        phases = {k: float(v) for k, v in row.items()
                  if k.startswith("t_") and k.endswith("_s")
                  and math.isfinite(float(v))}
        accounted = sum(phases.values())
        if accounted < self.stall_min_s:
            return []
        stall_cols = [f"t_{p}_s" for p in self.stall_phases]
        stall_s = sum(phases.get(c, 0.0) for c in stall_cols)
        frac = stall_s / accounted
        if frac <= self.stall_frac:
            return []
        worst = max(stall_cols, key=lambda c: phases.get(c, 0.0))
        return [Anomaly(
            kind="stall", metric=worst, step=step,
            value=round(frac, 4), threshold=self.stall_frac,
            detail=f"non-compute phases took {frac:.0%} of the window's "
                   f"{accounted:.2f}s accounted host time (worst: "
                   f"{worst}={phases.get(worst, 0.0):.2f}s)")]

    def _check_collapse(self, step: int,
                        row: Dict[str, float]) -> List[Anomaly]:
        m = self.collapse_metric
        if m not in row or not math.isfinite(float(row[m])):
            return []
        hist = self._hist[m]
        if len(hist) < self.min_history:
            return []
        med = self._median(list(hist))
        x = float(row[m])
        if med > 0 and x < self.collapse_frac * med:
            return [Anomaly(
                kind="throughput", metric=m, step=step,
                value=x, threshold=round(self.collapse_frac * med, 6),
                detail=f"{m}={x:.3f} fell under {self.collapse_frac:g}x "
                       f"the rolling median {med:.3f}")]
        return []

    def last_rows(self, k: Optional[int] = None) -> List[Dict]:
        rows = list(self._rows)
        return rows if k is None else rows[-k:]


class WatchdogMonitor:
    """The impure shell: detector -> incident artifacts (+ optional
    halt). Installed as the metrics drain's check callback by
    ``train()``; call signature matches ``check_finite``.
    """

    # a warn-only monitor on a persistently sick run trips every log
    # window; the retained history (and what incident.json re-writes)
    # must stay bounded or the post-mortem machinery itself becomes the
    # hot-path cost. The file keeps the newest KEEP_ANOMALIES.
    KEEP_ANOMALIES = 64

    def __init__(self, workdir: Optional[str], halt: bool = False,
                 detector: Optional[Watchdog] = None):
        self.workdir = workdir
        self.halt = halt
        self.detector = detector if detector is not None else Watchdog()
        self.incidents: deque = deque(maxlen=self.KEEP_ANOMALIES)
        self.total_anomalies = 0
        self.incident_path: Optional[str] = None

    def arm(self) -> "WatchdogMonitor":
        _ARMED.add(self)
        return self

    def disarm(self) -> None:
        _ARMED.discard(self)

    def __call__(self, scalars: Dict[str, float], step: int) -> None:
        anomalies = self.detector.feed(step, scalars)
        if not anomalies:
            return
        self.incidents.extend(anomalies)
        self.total_anomalies += len(anomalies)
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("incidents", len(anomalies), cat="watchdog")
            for a in anomalies:
                tel.instant("incident", cat="watchdog", args=a.to_json())
        self.incident_path = self._write_incident(step, anomalies)
        names = ", ".join(f"{a.kind}:{a.metric}" for a in anomalies)
        where = (f"; post-mortem written to {self.incident_path}"
                 if self.incident_path else "")
        print(f"[watchdog] WARNING: anomaly at step {step}: {names}"
              f"{where}", flush=True)
        if self.halt:
            raise AnomalyHalt(step, anomalies)

    def _write_incident(self, step: int,
                        anomalies: List[Anomaly]) -> Optional[str]:
        """Write/refresh ``<workdir>/incident.json``: the offending
        anomalies (latest trip), every anomaly so far, the last-K
        metrics rows, and the telemetry snapshot when tracing is on.
        Atomic (tmp + rename): a reader never sees a torn post-mortem.
        """
        if not self.workdir:
            return None
        tel = get_telemetry()
        snap = None
        if tel.enabled:
            raw = tel.snapshot()
            snap = {
                "aggregates": {f"{c}/{n}": v for (c, n), v in
                               sorted(raw["aggregates"].items())},
                "counters": {f"{c}/{n}": v for (c, n), v in
                             sorted(raw["counters"].items())},
                "gauges": {f"{c}/{n}": v for (c, n), v in
                           sorted(raw["gauges"].items())},
                "hists": {f"{c}/{n}": h["summary"] for (c, n), h in
                          sorted(raw["hists"].items())},
            }
        doc = {
            "step": step,
            "wall_time": time.time(),
            "halt": self.halt,
            "anomalies": [a.to_json() for a in anomalies],
            # bounded tail (newest KEEP_ANOMALIES); the exact lifetime
            # count rides alongside so a reader knows what was dropped
            "total_anomalies": self.total_anomalies,
            "recent_anomalies": [a.to_json() for a in self.incidents],
            "last_rows": self.detector.last_rows(),
            "telemetry": snap,
            # fault-injection evidence (ISSUE 10 satellite): when a
            # chaos plan is armed, the post-mortem names the exact
            # fired sites/invocations — an injected NaN row's incident
            # is attributable to its trigger, closing the loop between
            # injection and detection. None on un-injected runs.
            "faults": (_faults.get_injector().summary()
                       if _faults.get_injector() is not None else None),
        }
        os.makedirs(self.workdir, exist_ok=True)
        path = os.path.join(self.workdir, INCIDENT_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            # json_safe: last_rows carry the raw NaN/inf values that
            # tripped the detector — strict consumers must still be
            # able to read the post-mortem (allow_nan=False enforces)
            json.dump(json_safe(doc), f, indent=2, allow_nan=False)
        os.replace(tmp, path)
        return path


def armed_monitors() -> tuple:
    """Live armed monitors (the conftest no-leak guard reads this)."""
    return tuple(_ARMED)
