"""Metrics logging: console + CSV + JSONL (TensorBoard-free).

TPU-native equivalent of the reference's TF summary scalars
(SURVEY.md §2 component 16, §5 "Metrics / logging": total loss, recon-NLL,
KL, lr, KL weight to TensorBoard plus console prints). Here a dependency-
free writer emits the same scalars as append-only CSV and JSONL under the
work dir, which any plotting tool can consume.

:class:`MetricsDrain` is the goodput layer on top (ISSUE 3): converting
device metrics with ``float(v)`` at the log window synchronizes the host
on the step chain — the drain instead holds the device references for
ONE window and converts them when the next window's compute is already
dispatched, so logging never stalls dispatch. Values are bitwise
identical to the synchronous conversion (the fetch is late, not lossy),
and ``check_finite`` runs on the drained floats with the same
divergence-stops-training semantics, at most one window late.
"""

from __future__ import annotations

import csv
import json
import os
import sys
import time
from typing import Any, Callable, Dict, Optional, Sequence

from sketch_rnn_tpu.utils.faults import corrupt_value, fault_point


class MetricsWriter:
    """Append-only scalar logger; one row per logged step."""

    def __init__(self, workdir: Optional[str], name: str = "train"):
        self.workdir = workdir
        self.name = name
        self._csv_path = None
        self._jsonl_path = None
        self._fields: Optional[Sequence[str]] = None
        self._warned_drops: set = set()
        if workdir:
            os.makedirs(workdir, exist_ok=True)
            self._csv_path = os.path.join(workdir, f"{name}_metrics.csv")
            self._jsonl_path = os.path.join(workdir, f"{name}_metrics.jsonl")

    def write(self, step: int, scalars: Dict[str, float]) -> None:
        # fault site (ISSUE 10): a metrics-file I/O failure — the
        # chaos plan's stand-in for a full disk / yanked volume
        fault_point("metrics.write")
        # strings pass through (serve rows carry admission-class names,
        # ISSUE 9); everything else must coerce to float — the train
        # path stays strictly numeric (what the watchdog consumes)
        row = {"step": int(step), "wall_time": time.time()}
        row.update({k: (v if isinstance(v, str) else float(v))
                    for k, v in sorted(scalars.items())})
        if self._jsonl_path:
            with open(self._jsonl_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        if self._csv_path:
            new = self._fields is None and not os.path.exists(self._csv_path)
            if self._fields is None:
                header = None
                if not new:
                    # resuming into an existing CSV: adopt ITS header so
                    # columns stay aligned even if this run's first row has
                    # a different key set (extras dropped, missing empty)
                    with open(self._csv_path, newline="") as f:
                        header = next(csv.reader(f), None)
                if header:
                    self._fields = header
                else:
                    # fresh file, or an existing-but-headerless file (a
                    # crash truncated it): (re)write the header
                    self._fields = list(row)
                    new = True
            # the resume-alignment rule silently drops scalar keys absent
            # from the adopted header; silence cost a debugging session
            # (ISSUE 6 satellite) — warn ONCE per dropped key. The JSONL
            # row above kept the full key set either way.
            dropped = set(row).difference(self._fields)
            dropped -= self._warned_drops
            if dropped:
                self._warned_drops |= dropped
                print(f"[metrics] WARNING: {os.path.basename(self._csv_path)} "
                      f"drops keys absent from its existing header "
                      f"(CSV resume alignment; the JSONL keeps them): "
                      f"{sorted(dropped)}", file=sys.stderr, flush=True)
            with open(self._csv_path, "a", newline="") as f:
                w = csv.DictWriter(f, fieldnames=self._fields,
                                   extrasaction="ignore", restval="")
                if new:
                    w.writeheader()
                w.writerow(row)

    def log_console(self, step: int, scalars: Dict[str, float],
                    prefix: str = "") -> None:
        parts = " ".join(f"{k}={float(v):.4f}"
                         for k, v in sorted(scalars.items()))
        print(f"[{self.name}] step {step} {prefix}{parts}", flush=True)


def scalars_from_device(metrics: Dict[str, Any]) -> Dict[str, float]:
    """Convert a device-metrics dict to host floats.

    This is the ONE device->host synchronization point of the logging
    path — the module-level seam lets tests shim it with a counter to
    prove the training loop never converts eagerly (the no-blocking-
    host-sync tier-1 guard).
    """
    return {k: float(v) for k, v in metrics.items()}


class MetricsDrain:
    """One-window deferral queue between the train loop and a writer.

    ``push(step, device_metrics, extras)`` enqueues the CURRENT window's
    device references and drains the PREVIOUS window's — whose compute
    finished long ago (a full log window of steps has been dispatched
    since), so the ``float()`` conversions return without waiting and the
    step-dispatch chain never blocks on logging. ``flush()`` drains the
    tail (call at loop exit, before the final checkpoint).

    Each drained row is persisted BEFORE ``check`` runs, preserving the
    loop's divergence-leaves-its-diagnostic-record discipline; a
    ``check`` raise (``check_finite`` on a diverged loss) propagates to
    the caller — training stops at most one window after the divergent
    step. ``defer=False`` restores the synchronous path exactly: convert,
    write, check inside ``push`` (the ``metrics_defer=false`` escape
    hatch and the A/B baseline for goodput_bench).
    """

    def __init__(self, writer: MetricsWriter, defer: bool = True,
                 check: Optional[Callable[[Dict[str, float], int],
                                          None]] = None):
        self.writer = writer
        self.defer = defer
        self._check = check
        self._pending: Optional[tuple] = None
        self.drained_rows = 0

    def push(self, step: int, device_metrics: Dict[str, Any],
             extras: Optional[Dict[str, float]] = None) -> None:
        if not self.defer:
            self._emit(step, device_metrics, extras)
            return
        prev, self._pending = self._pending, (step, device_metrics, extras)
        if prev is not None:
            self._emit(*prev)

    def flush(self) -> None:
        prev, self._pending = self._pending, None
        if prev is not None:
            self._emit(*prev)

    def _emit(self, step, device_metrics, extras) -> None:
        scalars = scalars_from_device(device_metrics)
        if extras:
            scalars.update(extras)
        if "loss" in scalars:
            # value-corruption fault site (ISSUE 10, kind=nan only): a
            # drained row's loss goes NaN — the injected divergence the
            # watchdog must catch AND attribute (its incident.json
            # embeds the injector's fired log as evidence)
            scalars["loss"] = corrupt_value("metrics.row",
                                            scalars["loss"])
        self.drained_rows += 1
        self.writer.write(step, scalars)
        self.writer.log_console(step, scalars)
        if self._check is not None:
            self._check(scalars, step)
