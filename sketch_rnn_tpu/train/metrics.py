"""Metrics logging: console + CSV + JSONL (TensorBoard-free).

TPU-native equivalent of the reference's TF summary scalars
(SURVEY.md §2 component 16, §5 "Metrics / logging": total loss, recon-NLL,
KL, lr, KL weight to TensorBoard plus console prints). Here a dependency-
free writer emits the same scalars as append-only CSV and JSONL under the
work dir, which any plotting tool can consume.
"""

from __future__ import annotations

import csv
import json
import os
import time
from typing import Dict, Optional, Sequence


class MetricsWriter:
    """Append-only scalar logger; one row per logged step."""

    def __init__(self, workdir: Optional[str], name: str = "train"):
        self.workdir = workdir
        self.name = name
        self._csv_path = None
        self._jsonl_path = None
        self._fields: Optional[Sequence[str]] = None
        if workdir:
            os.makedirs(workdir, exist_ok=True)
            self._csv_path = os.path.join(workdir, f"{name}_metrics.csv")
            self._jsonl_path = os.path.join(workdir, f"{name}_metrics.jsonl")

    def write(self, step: int, scalars: Dict[str, float]) -> None:
        row = {"step": int(step), "wall_time": time.time()}
        row.update({k: float(v) for k, v in sorted(scalars.items())})
        if self._jsonl_path:
            with open(self._jsonl_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        if self._csv_path:
            new = self._fields is None and not os.path.exists(self._csv_path)
            if self._fields is None:
                header = None
                if not new:
                    # resuming into an existing CSV: adopt ITS header so
                    # columns stay aligned even if this run's first row has
                    # a different key set (extras dropped, missing empty)
                    with open(self._csv_path, newline="") as f:
                        header = next(csv.reader(f), None)
                if header:
                    self._fields = header
                else:
                    # fresh file, or an existing-but-headerless file (a
                    # crash truncated it): (re)write the header
                    self._fields = list(row)
                    new = True
            with open(self._csv_path, "a", newline="") as f:
                w = csv.DictWriter(f, fieldnames=self._fields,
                                   extrasaction="ignore", restval="")
                if new:
                    w.writeheader()
                w.writerow(row)

    def log_console(self, step: int, scalars: Dict[str, float],
                    prefix: str = "") -> None:
        parts = " ".join(f"{k}={float(v):.4f}"
                         for k, v in sorted(scalars.items()))
        print(f"[{self.name}] step {step} {prefix}{parts}", flush=True)
