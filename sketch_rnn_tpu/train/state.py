"""Train state and optimizer assembly.

TPU-native equivalent of the reference's Adam train-op construction
(SURVEY.md §2 component 11: Adam, exponential lr decay, global-norm
gradient clipping): an optax chain ``clip_by_global_norm -> adam(schedule)``
acting on an explicit ``TrainState`` pytree. The state is a NamedTuple so
it flows through ``jit``/``grad``/sharding and serializes as a plain
pytree.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import optax

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.train.schedules import lr_schedule


class TrainState(NamedTuple):
    params: Dict[str, Any]
    opt_state: Any
    step: jax.Array  # int32 scalar


def make_optimizer(hps: HParams) -> optax.GradientTransformation:
    """``clip_by_global_norm(grad_clip) -> adam(exp-decay lr)``.

    optax's ``adam`` takes the schedule as a callable of its own update
    count, which equals ``TrainState.step`` (both start at 0 and advance
    once per ``train_step``).
    """
    return optax.chain(
        optax.clip_by_global_norm(hps.grad_clip),
        optax.adam(learning_rate=lambda count: lr_schedule(hps, count)),
    )


def make_train_state(model, hps: HParams, key: jax.Array) -> TrainState:
    params = model.init_params(key)
    tx = make_optimizer(hps)
    return TrainState(params=params, opt_state=tx.init(params),
                      step=jnp.zeros((), jnp.int32))
