"""Draft-decoder distillation through the real train stack (ISSUE 18).

``DistillModel`` wraps a FROZEN full model (the teacher) and exposes the
``init_params`` / ``loss`` contract ``train.loop.train`` drives — so a
distillation run exercises the exact production stack (bucketed loader,
steps_per_call dispatch, async checkpointing, resume, telemetry) with
zero forked loop code: ``train(..., model=DistillModel(hps, teacher))``.

The objective trains the draft to be a cheap PREDICTOR of the teacher's
sampling behavior, which is what the serving acceptance rule scores:

- **offset GMM NLL + pen CE on the data** (the canonical
  ``mdn.reconstruction_loss``), teacher-forced on the corpus strokes
  and conditioned on the teacher's posterior MEAN z (no sampling — the
  distillation loss is deterministic per batch, which keeps the resume
  bitwise-replay property of the train loop meaningful);
- **soft pen distillation**: cross-entropy of the draft's pen logits
  against the teacher's pen PROBABILITIES at every real step. The
  acceptance rule rejects on the pen one-hot EXACTLY (both samplers
  invert the same uniform), so matching the teacher's pen CDF is where
  draft quality buys accept length most directly.

Teacher parameters are closed over as constants: gradients flow only
into the draft tree, and the saved checkpoints hold ONLY draft params
(their own shapes, their own resume lineage under ``<workdir>/draft``).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.models.draft import DraftDecoder
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.ops import linear as L
from sketch_rnn_tpu.ops import mdn
from sketch_rnn_tpu.ops.rnn import length_reverse_indices, run_rnn

Params = Dict[str, Any]


class DistillModel:
    """Frozen teacher + trainable draft, as one train-loop model."""

    def __init__(self, hps: HParams, teacher_params: Params):
        self.hps = hps
        self.teacher = SketchRNN(hps)
        self.draft = DraftDecoder(hps)
        # frozen constants in the compiled step: grad flows only into
        # the draft tree the loop owns
        self.teacher_params = jax.tree_util.tree_map(
            jnp.asarray, teacher_params)

    def init_params(self, key: jax.Array) -> Params:
        return self.draft.init_params(key)

    def loss(self, params: Params, batch: Dict[str, jax.Array],
             key: jax.Array, kl_weight: jax.Array, train: bool = True,
             axis_name: Optional[str] = None
             ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Distillation loss on a loader batch; one fused computation.

        Returns the train loop's canonical metric keys (kl terms are
        zero constants — the draft has no latent) plus ``pen_distill``,
        the soft-pen knowledge-distillation term.
        """
        hps = self.hps
        tp = self.teacher_params
        raw_bm = batch["strokes"]
        seq_len = batch["seq_len"]
        weights = batch.get("weights")
        # entry-path prep, the vae._forward recipe: int16 dequant ->
        # time-major -> f32 upcast (and the batch-major reverse gather
        # for the encoder's backward direction)
        raw_rev = None
        if hps.conditional:
            rev_bm = length_reverse_indices(raw_bm.shape[1] - 1,
                                            seq_len).T
            raw_rev = jnp.take_along_axis(raw_bm[:, 1:],
                                          rev_bm[:, :, None], axis=1)

        def prep(bm):
            if bm.dtype == jnp.int16:
                sc = batch["transfer_scale"].astype(jnp.float32)
                f = bm.astype(jnp.float32)
                bm = jnp.concatenate(
                    [f[..., :2] / sc[:, None, None], f[..., 2:]], axis=-1)
            return jnp.transpose(bm, (1, 0, 2)).astype(jnp.float32)

        strokes = prep(raw_bm)                   # [T+1, B, 5]
        x_in, x_target = strokes[:-1], strokes[1:]
        labels = batch.get("labels") if hps.num_classes > 0 else None
        z = None
        if hps.conditional:
            # posterior MEAN, never a sample: the draft must predict
            # the teacher's serving-time behavior for a FIXED z, and a
            # deterministic loss keeps distillation bitwise-resumable
            mu, _ = self.teacher.encode(tp, x_target, seq_len,
                                        train=False,
                                        x_rev_tm=prep(raw_rev))
            z = mu
        extra = self.teacher._decoder_extra(tp, z, labels)
        # teacher soft pen targets (teacher-forced, eval mode)
        traw = self.teacher.decode(tp, x_in, z, labels, train=False)
        t_pen = jax.nn.softmax(
            mdn.get_mixture_params(traw, hps.num_mixture).pen_logits)
        # draft forward: its cell over the same teacher-forced stream,
        # same time-invariant conditioning, its own z -> carry init
        b = x_in.shape[1]
        carry0 = self.draft.initial_carry(params, z, b)
        _, hs = run_rnn(self.draft.cell, params["draft_dec"], x_in,
                        carry0, x_extra=extra)
        draw = L.matmul(hs, params["draft_out_w"],
                        self.draft.cell.compute_dtype) \
            + params["draft_out_b"]
        dmp = mdn.get_mixture_params(draw, self.draft.num_mixture)
        offset_nll, pen_ce = mdn.reconstruction_loss(
            dmp, x_target, hps.max_seq_len, mask_pen=not train,
            weights=weights, axis_name=axis_name)
        # soft pen distillation, masked to real steps and normalized
        # like reconstruction_loss (max_seq_len x global batch)
        t_steps = x_in.shape[0]
        mask = (jnp.arange(t_steps)[:, None]
                < seq_len[None, :]).astype(jnp.float32)     # [T, B]
        if weights is not None:
            mask = mask * weights[None, :].astype(jnp.float32)
        kd = -jnp.sum(t_pen * jax.nn.log_softmax(dmp.pen_logits, -1),
                      axis=-1)                              # [T, B]
        num = jnp.sum(kd * mask)
        den = jnp.float32(b) if weights is None \
            else jnp.maximum(jnp.sum(weights.astype(jnp.float32)), 1.0)
        if axis_name:
            num = jax.lax.psum(num, axis_name)
            den = jax.lax.psum(den, axis_name)
        pen_distill = num / (hps.max_seq_len * den)
        recon = offset_nll + pen_ce
        total = recon + pen_distill
        metrics = {
            "loss": total,
            "recon": recon,
            "offset_nll": offset_nll,
            "pen_ce": pen_ce,
            "pen_distill": pen_distill,
            "kl": jnp.float32(0.0),
            "kl_raw": jnp.float32(0.0),
            "kl_weight": jnp.asarray(kl_weight, jnp.float32),
        }
        return total, metrics


def draft_dir_of(workdir: str) -> str:
    """The draft run's home under a teacher workdir: its checkpoints
    have draft shapes and must never collide with the teacher's."""
    return os.path.join(workdir, "draft")


def distill(hps: HParams, teacher_params: Params, train_loader,
            workdir: str, seed: int = 0,
            num_steps: Optional[int] = None,
            teacher_ckpt_id: str = "", **train_kw):
    """Distill a draft decoder via the production train loop.

    Trains ``DistillModel(hps, teacher_params)`` into
    ``<workdir>/draft`` (own checkpoints, own resume) and records the
    pairing lineage in that directory's RUN.json: which teacher
    checkpoint this draft was distilled from, and the draft geometry a
    serving engine must rebuild to load it. Returns the final
    TrainState (``state.params`` is the draft tree).
    """
    from sketch_rnn_tpu.train.loop import train
    from sketch_rnn_tpu.utils import runinfo

    dmodel = DistillModel(hps, teacher_params)
    out = draft_dir_of(workdir)
    state = train(hps, train_loader, workdir=out, seed=seed,
                  num_steps=num_steps, model=dmodel, **train_kw)
    runinfo.write_manifest(
        out, kind="distill", hps=hps,
        extra={"distill": {
            "teacher_ckpt_id": teacher_ckpt_id,
            "teacher_workdir": os.path.abspath(workdir),
            "draft_rnn_size": hps.draft_rnn_size,
            "draft_num_mixture": dmodel.draft.num_mixture,
            "steps": int(state.step),
        }})
    return state
