"""Learning-rate decay and KL-annealing schedules (pure jnp functions).

TPU-native equivalent of the reference's per-step schedule updates
(SURVEY.md §2 component 11-12, §5 "Config": ``learning_rate=1e-3`` with
exponential decay to ``min_learning_rate``, and the KL weight annealed as
``eta = kl_weight - (kl_weight - kl_weight_start) * R^step``; reference
unreadable — formulas per the canonical implementation noted there).

Both are pure functions of the step so they trace into the jitted train
step; nothing is recompiled as the step advances.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sketch_rnn_tpu.config import HParams


def _exp_decay(step: jax.Array, rate: float) -> jax.Array:
    # rate**step via exp/log: step may be a traced int32 inside jit
    return jnp.exp(jnp.asarray(step, jnp.float32) * jnp.log(jnp.float32(rate)))


def lr_schedule(hps: HParams, step: jax.Array) -> jax.Array:
    """``(lr0 - lr_min) * decay^step + lr_min``."""
    return ((hps.learning_rate - hps.min_learning_rate)
            * _exp_decay(step, hps.decay_rate) + hps.min_learning_rate)


def kl_weight_schedule(hps: HParams, step: jax.Array) -> jax.Array:
    """Annealed KL weight: rises from ``kl_weight_start`` to ``kl_weight``."""
    return (hps.kl_weight - (hps.kl_weight - hps.kl_weight_start)
            * _exp_decay(step, hps.kl_decay_rate))
