"""Checkpoint save / restore (full training state + model contract).

TPU-native equivalent of the reference's TF-Saver checkpointing
(SURVEY.md §2 component 13, §5 "Checkpoint / resume"): the FULL pytree is
saved — parameters, optimizer state, step, AND the data-normalization
scale factor, which is part of the model contract (a model restored
without its scale factor decodes garbage).

Format: flax msgpack bytes for the state pytree plus a JSON sidecar with
format version / step / scale factor / hparams, named
``ckpt_<step>.msgpack`` + ``ckpt_<step>.json``. Restore-from-latest scans
the directory, matching the reference's resume-from-latest flag. Writes
go via a temp file + rename so a crash mid-save never corrupts the
latest checkpoint.

Versioning: ``format_version`` in the sidecar (VERDICT r4 #8). Sidecars
without the field are version 1 (every pre-versioning checkpoint,
e.g. the committed demo). Restore fails LOUDLY on a future version or
a corrupt/truncated msgpack instead of half-restoring.

Validation (ISSUE 16): :func:`validate_checkpoint` is the public
candidate ADMISSION GATE the rollout controller (serve/rollout.py) and
restore-time loading share — complete file pair, readable sidecar,
known format version, decodable msgpack, a leaf-by-leaf shape manifest
against the caller's template pytree, and finite parameter leaves.
Every rejection is ONE :class:`CheckpointValidationError` line naming
the file and the first offending field (``dec/h0/kernel: shape (4, 8)
!= template (8, 8)``), never a mid-restore traceback — the line a
quarantine entry, an operator and a test can all read. ``ckpt_id_of``
mints the checkpoint identity (``ckpt_00000042``) that stamps serving
Results, cache namespaces and RUN.json lineage.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.train.state import TrainState
from sketch_rnn_tpu.utils.faults import fault_point, retry_call

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")

# Bump when the saved layout changes incompatibly (pytree structure,
# sidecar schema). Version 1: flax-msgpack TrainState + json sidecar
# {step, scale_factor, hps} — unchanged since round 1.
FORMAT_VERSION = 1


def _paths(ckpt_dir: str, step: int) -> Tuple[str, str]:
    base = os.path.join(ckpt_dir, f"ckpt_{step:08d}")
    return base + ".msgpack", base + ".json"


def ckpt_id_of(step: int) -> str:
    """The checkpoint's serving identity: the file basename without
    extension (``ckpt_00000042``). ONE minting site — the rollout
    controller, the result cache's version namespace, Result stamping
    and RUN.json lineage must all agree on what a checkpoint is
    called, and the name that already keys resume is the honest one."""
    return f"ckpt_{int(step):08d}"


class CheckpointValidationError(RuntimeError):
    """A candidate checkpoint failed the admission gate. The message is
    ONE line naming the file and the first offending field; ``path``
    and ``reason`` carry the same split for quarantine records."""

    def __init__(self, path: str, reason: str):
        self.path = path
        self.reason = reason
        super().__init__(
            f"cannot restore checkpoint {path}: {reason}")


def _base_of(path: str) -> str:
    """Strip a ``.msgpack``/``.json``(+``.tmp``) extension so callers
    may name the candidate by either file of the pair."""
    for ext in (".msgpack.tmp", ".json.tmp", ".msgpack", ".json"):
        if path.endswith(ext):
            return path[:-len(ext)]
    return path


def _manifest_mismatch(tmpl, got, prefix: str = "") -> Optional[str]:
    """First structural difference between two flax state dicts, as a
    one-line description naming the field path, or None when the shape
    manifests agree. Walks template order so the failure is stable."""
    if isinstance(tmpl, dict) or isinstance(got, dict):
        if not (isinstance(tmpl, dict) and isinstance(got, dict)):
            return (f"field {prefix or '<root>'} is "
                    f"{type(got).__name__}, template expects "
                    f"{type(tmpl).__name__}")
        missing = [k for k in tmpl if k not in got]
        if missing:
            return f"field {prefix}{missing[0]} missing from checkpoint"
        extra = [k for k in got if k not in tmpl]
        if extra:
            return f"field {prefix}{extra[0]} not in template"
        for k in tmpl:
            r = _manifest_mismatch(tmpl[k], got[k], f"{prefix}{k}/")
            if r:
                return r
        return None
    ts, gs = np.shape(tmpl), np.shape(got)
    if ts != gs:
        return (f"field {prefix.rstrip('/') or '<root>'} has shape "
                f"{gs}, template expects {ts}")
    return None


def _first_nonfinite(sd, prefix: str = "") -> Optional[str]:
    """First float leaf holding a NaN/Inf, by field path, or None."""
    if isinstance(sd, dict):
        for k in sd:
            r = _first_nonfinite(sd[k], f"{prefix}{k}/")
            if r:
                return r
        return None
    a = np.asarray(sd)
    if np.issubdtype(a.dtype, np.floating) and not np.isfinite(a).all():
        bad = int(a.size - np.isfinite(a).sum())
        return (f"field {prefix.rstrip('/') or '<root>'} has {bad} "
                f"non-finite value(s)")
    return None


def validate_checkpoint(path: str, target: TrainState,
                        check_finite: bool = True
                        ) -> Tuple[TrainState, float, dict]:
    """THE candidate admission gate (ISSUE 16): fully validate the
    checkpoint at ``path`` (either file of the pair names it) against
    ``target``'s pytree and return ``(state, scale_factor, meta)``.

    Checks, in order — each failing as ONE
    :class:`CheckpointValidationError` line naming the file and field:
    both files of the pair exist (a torn save is incomplete, not
    corrupt), the sidecar parses and carries ``scale_factor``, the
    format version is known, the msgpack decodes
    (``ckpt.load.corrupt`` fault site — the injectable disk-damage
    arm), the shape manifest matches the template leaf-by-leaf, and
    (``check_finite``) every float leaf is finite — a NaN'd candidate
    must be quarantined at the gate, never hot-swapped into a serving
    replica. Shared by :func:`restore_checkpoint` and the rollout
    controller so training resume and serving admission can never
    disagree about what a loadable checkpoint is."""
    base = _base_of(path)
    data_path, meta_path = base + ".msgpack", base + ".json"
    if not os.path.exists(data_path):
        raise CheckpointValidationError(
            data_path, "msgpack missing (incomplete/torn save)")
    if not os.path.exists(meta_path):
        raise CheckpointValidationError(
            meta_path, "sidecar missing (incomplete/torn save)")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except ValueError as e:
        raise CheckpointValidationError(
            meta_path, f"sidecar is not valid JSON ({e})") from e
    if not isinstance(meta, dict) or "scale_factor" not in meta:
        raise CheckpointValidationError(
            meta_path, "sidecar field scale_factor missing")
    version = meta.get("format_version", 1)  # pre-versioning sidecars
    if version > FORMAT_VERSION:
        raise CheckpointValidationError(
            meta_path,
            f"format_version={version} is newer than this build's "
            f"{FORMAT_VERSION}; refusing to guess at the layout")
    with open(data_path, "rb") as f:
        raw = f.read()
    try:
        # the injectable disk-damage arm: an armed ckpt.load.corrupt
        # plan surfaces exactly like a real torn/garbled msgpack
        fault_point("ckpt.load.corrupt")
        restored_sd = serialization.msgpack_restore(raw)
    except Exception as e:  # noqa: BLE001 — classified into ONE line
        raise CheckpointValidationError(
            data_path,
            f"msgpack corrupt or truncated ({len(raw)} bytes: "
            f"{type(e).__name__}: {e})") from e
    bad = _manifest_mismatch(serialization.to_state_dict(target),
                             restored_sd)
    if bad:
        raise CheckpointValidationError(
            data_path,
            f"{bad} — the checkpoint was saved from different hparams "
            f"than the template (compare its .json sidecar)")
    if check_finite:
        bad = _first_nonfinite(restored_sd.get("params", restored_sd))
        if bad:
            raise CheckpointValidationError(data_path, bad)
    try:
        state = serialization.from_state_dict(target, restored_sd)
    except Exception as e:  # noqa: BLE001
        raise CheckpointValidationError(
            data_path, f"{type(e).__name__}: {e}") from e
    return state, float(meta["scale_factor"]), meta


def save_checkpoint(ckpt_dir: str, state: TrainState, scale_factor: float,
                    hps: HParams, keep: int = 3, retries: int = 0,
                    retry_backoff_s: float = 0.05) -> str:
    """Write the state; prune to the ``keep`` most recent. Returns path.

    Synchronous: the device->host fetch and the file write both happen on
    the calling thread. The training loop's overlapped path
    (``train.async_ckpt.AsyncCheckpointer``) fetches and commits on a
    background thread through the same :func:`write_checkpoint`, so both
    paths produce byte-identical files. ``retries``/``retry_backoff_s``
    pass through to the commit's transient-failure retry loop.
    """
    return write_checkpoint(ckpt_dir, jax.device_get(state), scale_factor,
                            hps, keep=keep, retries=retries,
                            retry_backoff_s=retry_backoff_s)


def write_checkpoint(ckpt_dir: str, host_state: TrainState,
                     scale_factor: float, hps: HParams,
                     keep: int = 3, retries: int = 0,
                     retry_backoff_s: float = 0.05) -> str:
    """Serialize an already-fetched HOST pytree and atomically commit it.

    The single commit discipline shared by the sync and async save paths:
    sidecar FIRST (latest_checkpoint() requires both files, so a crash
    after this write but before the msgpack lands leaves only a harmless
    orphan json and resume falls back to the previous complete
    checkpoint), then the msgpack — each via temp file + rename so a kill
    mid-write never corrupts ``latest_checkpoint``.

    Fault tolerance (ISSUE 10): the whole commit is idempotent (every
    write is tmp + rename keyed by step), so ``retries > 0`` retries a
    TRANSIENT I/O failure with bounded deterministic backoff — a retry
    after a torn first attempt simply rewrites both files. Permanent
    failures re-raise after the budget, preserving the
    failure-stops-training-loudly contract. Fault sites: ``ckpt.commit``
    (the whole commit fails, inside the retry loop) and ``ckpt.torn``
    (a crash in the torn instant between the sidecar and msgpack
    renames — what :func:`latest_checkpoint`'s completeness rule
    exists for).
    """

    def _commit() -> str:
        fault_point("ckpt.commit")
        os.makedirs(ckpt_dir, exist_ok=True)
        step = int(host_state.step)
        data_path, meta_path = _paths(ckpt_dir, step)
        meta = {"format_version": FORMAT_VERSION, "step": step,
                "scale_factor": float(scale_factor),
                "hps": json.loads(hps.to_json())}
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=2)
        os.replace(tmp, meta_path)
        fault_point("ckpt.torn")
        tmp = data_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(serialization.to_bytes(host_state))
        os.replace(tmp, data_path)
        _prune(ckpt_dir, keep)
        return data_path

    if retries <= 0:
        return _commit()
    return retry_call(_commit, retries, retry_backoff_s,
                      describe=f"checkpoint commit to {ckpt_dir}",
                      counter="ckpt_commit_retries")


def _complete_steps(ckpt_dir: str) -> list:
    """Steps whose checkpoint is COMPLETE: both the msgpack and its json
    sidecar exist. A crash mid-save leaves at most one of the pair, and
    both resume (latest_checkpoint) and cleanup (_prune) must agree on
    completeness — this helper is the single definition."""
    return sorted(s for name in os.listdir(ckpt_dir)
                  if (m := _CKPT_RE.match(name))
                  and os.path.exists(_paths(ckpt_dir,
                                            s := int(m.group(1)))[1]))


def latest_checkpoint(ckpt_dir: str) -> Optional[int]:
    """Highest COMPLETELY checkpointed step in ``ckpt_dir``, or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    steps = _complete_steps(ckpt_dir)
    return steps[-1] if steps else None


def restore_checkpoint(ckpt_dir: str, target: TrainState,
                       step: Optional[int] = None
                       ) -> Tuple[TrainState, float, dict]:
    """Restore ``(state, scale_factor, meta)``; ``target`` fixes the pytree
    structure (build it with ``make_train_state`` from the same hparams).

    Loads through :func:`validate_checkpoint` (ISSUE 16), so a corrupt
    msgpack, a future format version or a template built from different
    hparams all fail as ONE line naming the file and the first
    offending field instead of a mid-restore traceback."""
    if step is None:
        step = latest_checkpoint(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    data_path, _ = _paths(ckpt_dir, step)
    return validate_checkpoint(data_path, target)


_ANY_CKPT_RE = re.compile(r"^ckpt_(\d+)\.(?:msgpack|json)(?:\.tmp)?$")


def _prune(ckpt_dir: str, keep: int) -> None:
    """Keep the ``keep`` newest COMPLETE checkpoints; drop everything else,
    including orphan files from crashed saves (a lone json from a
    sidecar-first save that died mid-write, or a ``.tmp`` from a crash
    during the serialization write — both would otherwise accumulate).
    ``.tmp`` files of kept steps are also stale (the save replaces them
    before pruning) but are left alone: the next save of that step
    overwrites them."""
    complete = _complete_steps(ckpt_dir)
    keep_steps = set(complete[-keep:]) if keep > 0 else set(complete)
    for name in os.listdir(ckpt_dir):
        m = _ANY_CKPT_RE.match(name)
        if m and int(m.group(1)) not in keep_steps:
            try:
                os.remove(os.path.join(ckpt_dir, name))
            except OSError:
                pass
