"""Checkpoint save / restore (full training state + model contract).

TPU-native equivalent of the reference's TF-Saver checkpointing
(SURVEY.md §2 component 13, §5 "Checkpoint / resume"): the FULL pytree is
saved — parameters, optimizer state, step, AND the data-normalization
scale factor, which is part of the model contract (a model restored
without its scale factor decodes garbage).

Format: flax msgpack bytes for the state pytree plus a JSON sidecar with
format version / step / scale factor / hparams, named
``ckpt_<step>.msgpack`` + ``ckpt_<step>.json``. Restore-from-latest scans
the directory, matching the reference's resume-from-latest flag. Writes
go via a temp file + rename so a crash mid-save never corrupts the
latest checkpoint.

Versioning: ``format_version`` in the sidecar (VERDICT r4 #8). Sidecars
without the field are version 1 (every pre-versioning checkpoint,
e.g. the committed demo). Restore fails LOUDLY on a future version or
a corrupt/truncated msgpack instead of half-restoring.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional, Tuple

import jax
from flax import serialization

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.train.state import TrainState
from sketch_rnn_tpu.utils.faults import fault_point, retry_call

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")

# Bump when the saved layout changes incompatibly (pytree structure,
# sidecar schema). Version 1: flax-msgpack TrainState + json sidecar
# {step, scale_factor, hps} — unchanged since round 1.
FORMAT_VERSION = 1


def _paths(ckpt_dir: str, step: int) -> Tuple[str, str]:
    base = os.path.join(ckpt_dir, f"ckpt_{step:08d}")
    return base + ".msgpack", base + ".json"


def save_checkpoint(ckpt_dir: str, state: TrainState, scale_factor: float,
                    hps: HParams, keep: int = 3, retries: int = 0,
                    retry_backoff_s: float = 0.05) -> str:
    """Write the state; prune to the ``keep`` most recent. Returns path.

    Synchronous: the device->host fetch and the file write both happen on
    the calling thread. The training loop's overlapped path
    (``train.async_ckpt.AsyncCheckpointer``) fetches and commits on a
    background thread through the same :func:`write_checkpoint`, so both
    paths produce byte-identical files. ``retries``/``retry_backoff_s``
    pass through to the commit's transient-failure retry loop.
    """
    return write_checkpoint(ckpt_dir, jax.device_get(state), scale_factor,
                            hps, keep=keep, retries=retries,
                            retry_backoff_s=retry_backoff_s)


def write_checkpoint(ckpt_dir: str, host_state: TrainState,
                     scale_factor: float, hps: HParams,
                     keep: int = 3, retries: int = 0,
                     retry_backoff_s: float = 0.05) -> str:
    """Serialize an already-fetched HOST pytree and atomically commit it.

    The single commit discipline shared by the sync and async save paths:
    sidecar FIRST (latest_checkpoint() requires both files, so a crash
    after this write but before the msgpack lands leaves only a harmless
    orphan json and resume falls back to the previous complete
    checkpoint), then the msgpack — each via temp file + rename so a kill
    mid-write never corrupts ``latest_checkpoint``.

    Fault tolerance (ISSUE 10): the whole commit is idempotent (every
    write is tmp + rename keyed by step), so ``retries > 0`` retries a
    TRANSIENT I/O failure with bounded deterministic backoff — a retry
    after a torn first attempt simply rewrites both files. Permanent
    failures re-raise after the budget, preserving the
    failure-stops-training-loudly contract. Fault sites: ``ckpt.commit``
    (the whole commit fails, inside the retry loop) and ``ckpt.torn``
    (a crash in the torn instant between the sidecar and msgpack
    renames — what :func:`latest_checkpoint`'s completeness rule
    exists for).
    """

    def _commit() -> str:
        fault_point("ckpt.commit")
        os.makedirs(ckpt_dir, exist_ok=True)
        step = int(host_state.step)
        data_path, meta_path = _paths(ckpt_dir, step)
        meta = {"format_version": FORMAT_VERSION, "step": step,
                "scale_factor": float(scale_factor),
                "hps": json.loads(hps.to_json())}
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=2)
        os.replace(tmp, meta_path)
        fault_point("ckpt.torn")
        tmp = data_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(serialization.to_bytes(host_state))
        os.replace(tmp, data_path)
        _prune(ckpt_dir, keep)
        return data_path

    if retries <= 0:
        return _commit()
    return retry_call(_commit, retries, retry_backoff_s,
                      describe=f"checkpoint commit to {ckpt_dir}",
                      counter="ckpt_commit_retries")


def _complete_steps(ckpt_dir: str) -> list:
    """Steps whose checkpoint is COMPLETE: both the msgpack and its json
    sidecar exist. A crash mid-save leaves at most one of the pair, and
    both resume (latest_checkpoint) and cleanup (_prune) must agree on
    completeness — this helper is the single definition."""
    return sorted(s for name in os.listdir(ckpt_dir)
                  if (m := _CKPT_RE.match(name))
                  and os.path.exists(_paths(ckpt_dir,
                                            s := int(m.group(1)))[1]))


def latest_checkpoint(ckpt_dir: str) -> Optional[int]:
    """Highest COMPLETELY checkpointed step in ``ckpt_dir``, or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    steps = _complete_steps(ckpt_dir)
    return steps[-1] if steps else None


def restore_checkpoint(ckpt_dir: str, target: TrainState,
                       step: Optional[int] = None
                       ) -> Tuple[TrainState, float, dict]:
    """Restore ``(state, scale_factor, meta)``; ``target`` fixes the pytree
    structure (build it with ``make_train_state`` from the same hparams)."""
    if step is None:
        step = latest_checkpoint(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    data_path, meta_path = _paths(ckpt_dir, step)
    with open(meta_path) as f:
        meta = json.load(f)
    version = meta.get("format_version", 1)  # pre-versioning sidecars
    if version > FORMAT_VERSION:
        raise RuntimeError(
            f"{meta_path} has checkpoint format_version={version}, newer "
            f"than this build's {FORMAT_VERSION}; refusing to guess at "
            f"the layout — restore with a matching or newer build")
    with open(data_path, "rb") as f:
        raw = f.read()
    try:
        state = serialization.from_bytes(target, raw)
    except Exception as e:
        # Two distinct failures surface here and the message must not
        # send the user down the wrong path: a truncated/corrupt msgpack
        # (torn write outside the atomic rename, disk damage) vs a
        # pytree-structure mismatch (restoring with different hparams —
        # a config error, not corruption). flax reports the latter as a
        # ValueError naming the differing structure.
        raise RuntimeError(
            f"cannot restore checkpoint {data_path} ({len(raw)} bytes): "
            f"{type(e).__name__}: {e} — either the file is corrupt or "
            f"truncated, or `target` was built from different hparams "
            f"than the checkpoint's (compare with its .json sidecar)"
        ) from e
    return state, float(meta["scale_factor"]), meta


_ANY_CKPT_RE = re.compile(r"^ckpt_(\d+)\.(?:msgpack|json)(?:\.tmp)?$")


def _prune(ckpt_dir: str, keep: int) -> None:
    """Keep the ``keep`` newest COMPLETE checkpoints; drop everything else,
    including orphan files from crashed saves (a lone json from a
    sidecar-first save that died mid-write, or a ``.tmp`` from a crash
    during the serialization write — both would otherwise accumulate).
    ``.tmp`` files of kept steps are also stale (the save replaces them
    before pruning) but are left alone: the next save of that step
    overwrites them."""
    complete = _complete_steps(ckpt_dir)
    keep_steps = set(complete[-keep:]) if keep > 0 else set(complete)
    for name in os.listdir(ckpt_dir):
        m = _ANY_CKPT_RE.match(name)
        if m and int(m.group(1)) not in keep_steps:
            try:
                os.remove(os.path.join(ckpt_dir, name))
            except OSError:
                pass
