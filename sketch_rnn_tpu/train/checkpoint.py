"""Checkpoint save / restore (full training state + model contract).

TPU-native equivalent of the reference's TF-Saver checkpointing
(SURVEY.md §2 component 13, §5 "Checkpoint / resume"): the FULL pytree is
saved — parameters, optimizer state, step, AND the data-normalization
scale factor, which is part of the model contract (a model restored
without its scale factor decodes garbage).

Format: flax msgpack bytes for the state pytree plus a JSON sidecar with
step / scale factor / hparams, named ``ckpt_<step>.msgpack`` +
``ckpt_<step>.json``. Restore-from-latest scans the directory, matching
the reference's resume-from-latest flag. Writes go via a temp file +
rename so a crash mid-save never corrupts the latest checkpoint.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional, Tuple

import jax
from flax import serialization

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.train.state import TrainState

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")


def _paths(ckpt_dir: str, step: int) -> Tuple[str, str]:
    base = os.path.join(ckpt_dir, f"ckpt_{step:08d}")
    return base + ".msgpack", base + ".json"


def save_checkpoint(ckpt_dir: str, state: TrainState, scale_factor: float,
                    hps: HParams, keep: int = 3) -> str:
    """Write the state; prune to the ``keep`` most recent. Returns path."""
    os.makedirs(ckpt_dir, exist_ok=True)
    state = jax.device_get(state)
    step = int(state.step)
    data_path, meta_path = _paths(ckpt_dir, step)
    tmp = data_path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.to_bytes(state))
    os.replace(tmp, data_path)
    meta = {"step": step, "scale_factor": float(scale_factor),
            "hps": json.loads(hps.to_json())}
    tmp = meta_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=2)
    os.replace(tmp, meta_path)
    _prune(ckpt_dir, keep)
    return data_path


def latest_checkpoint(ckpt_dir: str) -> Optional[int]:
    """Highest checkpointed step in ``ckpt_dir``, or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [int(m.group(1)) for name in os.listdir(ckpt_dir)
             if (m := _CKPT_RE.match(name))]
    return max(steps) if steps else None


def restore_checkpoint(ckpt_dir: str, target: TrainState,
                       step: Optional[int] = None
                       ) -> Tuple[TrainState, float, dict]:
    """Restore ``(state, scale_factor, meta)``; ``target`` fixes the pytree
    structure (build it with ``make_train_state`` from the same hparams)."""
    if step is None:
        step = latest_checkpoint(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {ckpt_dir}")
    data_path, meta_path = _paths(ckpt_dir, step)
    with open(data_path, "rb") as f:
        state = serialization.from_bytes(target, f.read())
    with open(meta_path) as f:
        meta = json.load(f)
    return state, float(meta["scale_factor"]), meta


def _prune(ckpt_dir: str, keep: int) -> None:
    steps = sorted(int(m.group(1)) for name in os.listdir(ckpt_dir)
                   if (m := _CKPT_RE.match(name)))
    for s in steps[:-keep] if keep > 0 else []:
        for p in _paths(ckpt_dir, s):
            try:
                os.remove(p)
            except OSError:
                pass
