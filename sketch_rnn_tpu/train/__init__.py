"""Training subsystem: schedules, optimizer, jitted step, checkpoint, loop.

SURVEY.md §2 components 11-14 and §5 auxiliary subsystems.
"""

from sketch_rnn_tpu.train.schedules import kl_weight_schedule, lr_schedule
from sketch_rnn_tpu.train.state import TrainState, make_optimizer, make_train_state
from sketch_rnn_tpu.train.step import (
    make_eval_step,
    make_multi_train_step,
    make_per_class_eval_step,
    make_train_step,
)
from sketch_rnn_tpu.train.async_ckpt import AsyncCheckpointer
from sketch_rnn_tpu.train.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
    write_checkpoint,
)
from sketch_rnn_tpu.train.distill import (DistillModel, distill,
                                          draft_dir_of)
from sketch_rnn_tpu.train.elastic import ElasticCoordinator, elastic_train
from sketch_rnn_tpu.train.loop import evaluate, evaluate_per_class, train
from sketch_rnn_tpu.train.metrics import MetricsDrain, MetricsWriter
from sketch_rnn_tpu.train.watchdog import (
    AnomalyHalt,
    Watchdog,
    WatchdogMonitor,
)

__all__ = [
    "lr_schedule",
    "kl_weight_schedule",
    "TrainState",
    "make_optimizer",
    "make_train_state",
    "make_train_step",
    "make_multi_train_step",
    "make_eval_step",
    "make_per_class_eval_step",
    "save_checkpoint",
    "write_checkpoint",
    "restore_checkpoint",
    "latest_checkpoint",
    "AsyncCheckpointer",
    "MetricsDrain",
    "MetricsWriter",
    "train",
    "DistillModel",
    "distill",
    "draft_dir_of",
    "ElasticCoordinator",
    "elastic_train",
    "evaluate",
    "evaluate_per_class",
    "AnomalyHalt",
    "Watchdog",
    "WatchdogMonitor",
]
