"""Host training loop and eval sweep.

TPU-native equivalent of the reference's ``train()`` / ``evaluate_model()``
(SURVEY.md §2 component 12, §3.1/§3.4): a thin host loop around ONE jitted
step — per iteration the host only assembles a numpy batch, transfers it
sharded onto the mesh, and (every ``log_every`` steps) fetches scalar
metrics. Everything else (fwd, bwd, all-reduce, Adam, schedules) runs on
device. Eval sweeps the whole valid/test split with the dropout-off step
and averages, which is the recon-NLL/KL parity surface.

Goodput runtime (ISSUE 3): in the steady state the loop performs NO
blocking host synchronization between step dispatches — checkpoints
commit on a background writer (``async_checkpoint``, train/async_ckpt.py)
and log-window metrics convert one window late (``metrics_defer``,
train/metrics.py MetricsDrain), so the dispatch pipeline stays full.
Both paths are semantics-preserving: resumed state, metric values, and
the training stream are bitwise-identical to the synchronous ones
(``scripts/goodput_bench.py`` measures the stall removal and asserts the
parity). A ``GoodputLedger`` attributes the loop's wall time per phase
(dispatch / feeder_wait / metrics_drain / ckpt_wait / eval) into every
metrics row and an end-of-run summary.

Length-bucketed execution (ISSUE 4): with ``hps.bucket_edges`` set the
feeder pulls bucketed batches (``DataLoader.next_batch``) padded only to
their bucket edge; the jitted step's shape-keyed compile cache routes
each ``(B, Tb)`` to its own executable (train/step.py), the eval sweep
chunks at geometry boundaries (``_sweep_rows``), and every metrics row
carries the loader ``PaddingLedger``'s padded-timestep fraction and
per-bucket dispatch counts. Buckets off (the default) is bit-for-bit the
pre-bucketing loop; masked eval losses are bucket-independent either
way. Note ``strokes_per_sec`` still counts nominal ``B * max_seq_len``
points per step — under bucketing read it against ``padded_frac``
(``scripts/bucket_bench.py`` reports the honest steps/sec comparison).

Bucket-run scheduler (ISSUE 5): bucketing now composes with
``steps_per_call=K``. The feeder hands stacked geometry-run prefixes
``[k, B, Tb+1, 5]`` (``DataLoader.next_stack``; ``k <= K``): a full
``k == K`` stack dispatches ONE compiled K-step scan for its ``(K, B,
Tb)`` geometry (``make_multi_train_step(key_by_global_step=True)``),
while run remainders replay their micro-batches through the single-step
program. Both paths key micro-step RNG as ``fold_in(root, global_step)``
— a bucketed K run is step-for-step RNG-identical to the K=1 bucketed
loop, and the epoch plan itself never reads K, so the consumed batch
stream is identical too. The ``PaddingLedger`` additionally reports
``runs_per_epoch`` / ``mean_run_len`` (plan run structure) and
``dispatches_saved`` (realized K-amortization) in every metrics row.

Telemetry runtime (ISSUE 6): ``train(..., trace_dir=...)`` enables the
process-wide telemetry core (utils/telemetry.py) — the ledgers above
double as views into it, the prefetch producer / async checkpointer /
serve engine emit their own spans — and exports a JSONL event stream
plus a Chrome-trace JSON at exit (``scripts/trace_report.py`` prints
the stall breakdown and reconciles it against the ledger totals). Off
by default and bitwise-invisible when off.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.data.loader import DataLoader
from sketch_rnn_tpu.data.prefetch import prefetch_batches
from sketch_rnn_tpu.models.vae import SketchRNN
from sketch_rnn_tpu.parallel.mesh import make_mesh, shard_batch
from sketch_rnn_tpu.parallel.multihost import (
    HostDeathDetected,
    is_primary,
    topology,
)
from sketch_rnn_tpu.train.async_ckpt import AsyncCheckpointer
from sketch_rnn_tpu.train.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from sketch_rnn_tpu.train.metrics import MetricsDrain, MetricsWriter
from sketch_rnn_tpu.train.state import TrainState, make_train_state
from sketch_rnn_tpu.train.step import (
    make_eval_step,
    make_multi_eval_step,
    make_multi_train_step,
    make_train_step,
)
from sketch_rnn_tpu.train.watchdog import (
    INCIDENT_CKPT_DIR,
    AnomalyHalt,
    WatchdogMonitor,
)
from sketch_rnn_tpu.runtime.scheduler import default_scheduler
from sketch_rnn_tpu.utils.debug import check_finite, param_count
from sketch_rnn_tpu.utils.faults import fault_point
from sketch_rnn_tpu.utils.profiling import GoodputLedger, Throughput
from sketch_rnn_tpu.utils import telemetry as tele

# the loop's accounted phases, pre-declared so every metrics row carries
# all t_<phase>_s columns from the first window (CSV header stability)
GOODPUT_PHASES = ("dispatch", "feeder_wait", "metrics_drain", "ckpt_wait",
                  "eval")


def dispatch_stack(single_step, multi_step, state, batch, step: int,
                   remaining: int, root_key, k: int):
    """One bucket-run scheduler dispatch decision (ISSUE 5) — THE
    shared copy of the contract, used by the training loop and by
    ``scripts/bucket_bench.py``'s timing/parity arms so the two cannot
    drift.

    ``batch`` is a stacked geometry-run prefix with leading axis ``kk
    <= k``; ``use = min(kk, remaining)`` micro-steps are consumed. A
    full ``use == k`` stack dispatches ONE compiled (K, B, Tb) scan
    (``multi_step`` must be built with ``key_by_global_step=True``; it
    folds the live ``state.step`` into ``root_key``), anything shorter
    replays per micro-step through ``single_step`` with
    ``fold_in(root_key, step + i)`` — the identical key either way, so
    the whole run is step-for-step RNG-identical to K=1.

    Replay windows report metrics with the SAME semantics as the scan
    (``make_multi_train_step``): the MEAN over the window's
    micro-steps, ``grad_norm_max`` the window's max, ``lr`` /
    ``kl_weight`` the last micro-step's schedule values — accumulated
    device-side (no host sync), so a spike inside a replay window
    surfaces in the logged row exactly like a spike inside a scan.

    Returns ``(state, metrics, use, dispatches)`` — ``dispatches`` is
    the number of jitted calls issued (1 for a full stack, ``use`` for
    a replay), so ledger accounting cannot drift from the decision
    made here.

    The decision itself now lives on the unified dispatch runtime
    (ISSUE 20, :meth:`runtime.scheduler.GeometryRunScheduler.
    dispatch_stack`); this delegate keeps the historical import path
    for the loop and the bench, and the runtime's shared ledger books
    the run as a side effect.
    """
    return default_scheduler().dispatch_stack(
        single_step, multi_step, state, batch, step, remaining,
        root_key, k)


def _replay_window_metrics(per_step) -> Dict:
    """Fold a replayed window's per-micro-step metric dicts into one
    row with the scan's semantics (``make_multi_train_step``): MEAN
    over the window, ``grad_norm_max`` the max, ``lr``/``kl_weight``
    the last micro-step's schedule values. Pure device-side tree math
    on the (lazy) metric refs — no host sync. Shared by every replay
    path so logged rows cannot drift in meaning between the scan, the
    run-remainder replay and the fixed-T final remainder. THE copy
    lives on the unified runtime (ISSUE 20)."""
    from sketch_rnn_tpu.runtime.scheduler import GeometryRunScheduler
    return GeometryRunScheduler.replay_window_metrics(per_step)


def _sweep_rows(params, loader: DataLoader, eval_step, mesh, key, multi):
    """Yield one per-batch metrics dict (host numpy) over the eval sweep.

    ``multi=(multi_step, k)`` chunks the sweep through a K-batch scan
    program (``train.step.make_multi_eval_step``): one dispatch + one
    host fetch per K batches instead of per batch, which removes the
    tunneled runtime's 10-130 ms per-call launch stall from the sweep's
    critical path (VERDICT r3 #5) — the eval-side analogue of
    ``steps_per_call``. Batch ``i`` uses ``fold_in(key, i)`` on BOTH
    paths, so chunked and unchunked sweeps draw identical keys and
    weights; results agree to float reassociation noise (~1e-6 — the
    scan is a different XLA program, so not bit-parity). A remainder of
    exactly 1 falls back to the single-batch program; a larger
    remainder runs a smaller scan — at most two program sizes per sweep
    geometry, compiled once and cached across a training run's sweeps.

    Bucketed execution (ISSUE 4): eval batches are padded to their
    bucket edge (``loader.eval_pad_len``), so a chunk additionally
    breaks at geometry changes — each scan program holds one ``(B, Tb)``
    and lands in the same shape-keyed compiled cache the fixed-T sweep
    already uses. Masked eval losses are bitwise independent of the pad
    length, so chunking/bucketing cannot change sweep results beyond
    the pre-existing ~1e-6 scan-reassociation note. With bucketing off
    ``eval_pad_len`` is constant and the chunk schedule is exactly the
    pre-bucketing one.
    """
    n = loader.num_eval_batches
    if n == 0:
        raise ValueError(
            f"eval split has no common batches ({len(loader)} local "
            f"examples, batch_size={loader.hps.batch_size}): some host's "
            f"stripe is empty; enlarge the split or reduce host count")
    multi_step, k_max = multi if multi is not None else (None, 1)
    pad_len = getattr(loader, "eval_pad_len", None)
    # run formation is the unified runtime's (ISSUE 20): same spans as
    # the historical inline chunker — geometry-bounded runs of <= k_max
    # — with the dispatch/host-sync accounting riding the shared ledger
    sched = default_scheduler()
    for i, k in sched.geometry_runs(
            n, k_max if multi_step is not None else 1, geom_of=pad_len):
        if k > 1:
            batches = [loader.get_batch(j) for j in range(i, i + k)]
            stacked = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *batches)
            if mesh is not None:
                stacked = shard_batch(stacked, mesh, stacked=True)
            sched.ledger.record_run(k, 1)
            out = sched.fetch(multi_step(params, stacked, key,
                                         jnp.arange(i, i + k)))
            for j in range(k):
                yield {m: v[j] for m, v in out.items()}
        else:
            batch = loader.get_batch(i)
            if mesh is not None:
                batch = shard_batch(batch, mesh)
            # eval is deterministic (no dropout, z uses the key) — a fixed
            # fold-in per batch keeps the sweep reproducible
            sched.ledger.record_run(1, 1)
            out = sched.fetch(dict(
                eval_step(params, batch,
                          jax.random.fold_in(key, i))))
            yield {m: np.asarray(v) for m, v in out.items()}


def evaluate(params, loader: DataLoader, eval_step,
             mesh=None, key: Optional[jax.Array] = None,
             multi=None) -> Dict[str, float]:
    """Average eval metrics over a full sweep of ``loader``.

    Sweeps ``loader.num_eval_batches`` batches — every example is covered
    at least once; the final batch wrap-fills from the corpus start so all
    batches keep the compiled shape. The batch count is identical on every
    host (derived from the pre-stripe corpus size) so the SPMD sweep can
    never launch mismatched collective programs across hosts.

    ``multi=(multi_eval_step, k)`` dispatch-amortizes the sweep (see
    :func:`_sweep_rows`); same keys/weighting as the per-batch path,
    equal to ~1e-6 reassociation noise.
    """
    if key is None:
        key = jax.random.key(0)
    totals: Dict[str, float] = {}
    weight_total = 0.0
    for metrics in _sweep_rows(params, loader, eval_step, mesh, key, multi):
        # batch metrics are weighted means over the real (non-wrap-filled)
        # rows; combine them weighted by the global real-row count so the
        # sweep result is the exact mean over the split
        w = float(metrics.pop("weight_sum", loader.hps.batch_size))
        weight_total += w
        for k, v in metrics.items():
            totals[k] = totals.get(k, 0.0) + float(v) * w
    return {k: v / max(weight_total, 1.0) for k, v in totals.items()}


def evaluate_per_class(params, loader: DataLoader, per_class_step,
                       num_classes: int, mesh=None,
                       key: Optional[jax.Array] = None,
                       multi=None
                       ) -> Dict[int, Optional[Dict[str, float]]]:
    """Per-class eval metrics over a full sweep of ``loader``.

    One standard sweep (the reference paper's per-category tables,
    VERDICT r2 #4): every batch runs ONE forward pass whose per-class
    reductions come back as ``[num_classes]`` vectors; batch vectors are
    combined weighted by the global per-class real-row counts. The batch
    schedule is identical on every host — per-class eval therefore works
    under multi-host striping, where a ``filter_by_label`` sweep would
    deadlock (its per-class batch count differs across hosts).

    Returns ``{class_id: metrics dict}`` with ``None`` for classes with
    no examples in the split.
    """
    if key is None:
        key = jax.random.key(0)
    totals: Dict[str, np.ndarray] = {}
    counts = np.zeros((num_classes,), np.float64)
    for metrics in _sweep_rows(params, loader, per_class_step, mesh, key,
                               multi):
        cnt = np.asarray(metrics.pop("weight_sum"), np.float64)  # [C]
        counts += cnt
        for k, v in metrics.items():
            totals[k] = totals.get(k, 0.0) + np.asarray(v, np.float64) * cnt
    out: Dict[int, Optional[Dict[str, float]]] = {}
    for c in range(num_classes):
        if counts[c] == 0:
            out[c] = None
        else:
            out[c] = {k: float(v[c] / counts[c]) for k, v in totals.items()}
    return out


def train(hps: HParams,
          train_loader: DataLoader,
          valid_loader: Optional[DataLoader] = None,
          test_loader: Optional[DataLoader] = None,
          scale_factor: float = 1.0,
          workdir: Optional[str] = None,
          seed: int = 0,
          num_steps: Optional[int] = None,
          use_mesh: bool = True,
          resume: bool = True,
          profile: bool = False,
          trace_dir: Optional[str] = None,
          watchdog: bool = False,
          halt_on_anomaly: bool = False,
          coordinator=None,
          model=None) -> TrainState:
    """Train for ``num_steps`` (default ``hps.num_steps``); returns state.

    Resumes from the latest checkpoint in ``workdir`` when present
    (reference parity: resume-from-latest, SURVEY §5). ``profile=True``
    captures a ``jax.profiler`` trace of steps 10-20 (post-compile) into
    ``<workdir>/trace`` (SURVEY §5 "Tracing / profiling").

    ``trace_dir`` (ISSUE 6) turns on the unified telemetry runtime: the
    process core records the loop's goodput phases, the prefetch
    producer, the async checkpointer and the padding counters, and the
    run exports ``telemetry.jsonl`` + ``trace.json`` (Chrome trace)
    into ``trace_dir`` at exit — read with ``scripts/trace_report.py``
    or Perfetto. With ``profile=True`` the device trace lands in
    ``<trace_dir>/device`` with alignment markers in the host stream.
    Telemetry off (the default) is invisible: no files, identical
    metrics rows. Multi-host runs record on the primary only.

    ``watchdog`` (ISSUE 7) arms the training health watchdog
    (train/watchdog.py) on the metrics drain: each logged row is fed
    to a pure anomaly detector (NaN/inf, robust-z loss and grad-norm
    spikes, goodput-phase stalls, throughput collapse); a trip emits a
    telemetry incident event and writes ``<workdir>/incident.json``
    (warn-only). ``halt_on_anomaly`` additionally stops training on a
    trip, after forcing a post-mortem checkpoint into
    ``<workdir>/incident/`` — deliberately NOT the resume directory,
    so a diverged state can never become ``latest_checkpoint``. Both
    off by default and bitwise-invisible when off: the drain's check
    chain is exactly ``check_finite`` and no watchdog state exists.

    ``coordinator`` (ISSUE 14, train/elastic.py ElasticCoordinator)
    makes this one host of an elastic fleet: its step barrier runs
    once per dispatch-loop iteration (the host-death detection point
    and the ``host.kill.hNN`` fault site), its host identity replaces
    the jax-cluster primary gating (the light-mode fleet has no
    ``jax.distributed``), and on a detected peer death the surviving
    primary commits a CONSISTENT checkpoint of the live state — every
    survivor holds the identical replicated state at the barriered
    step — through the active save path before the
    ``HostDeathDetected`` propagates to the restart protocol. None
    (the default) is bitwise-invisible: no barrier, no behavior
    change.

    ``model`` (ISSUE 18): an alternative model object implementing the
    ``init_params(key)`` / ``loss(params, batch, key, kl_weight,
    train, axis_name)`` contract — the distillation loop
    (train/distill.py DistillModel) trains a draft decoder through
    THIS exact stack (bucketed loader, async checkpointing, telemetry,
    resume) instead of forking a second loop. None (the default)
    builds the standard ``SketchRNN(hps)``, bitwise-unchanged.
    """
    num_steps = hps.num_steps if num_steps is None else num_steps
    primary = (coordinator.is_primary if coordinator is not None
               else is_primary())
    mem_sampler = None
    if trace_dir:
        # EVERY process records and exports its own shard (ISSUE 8):
        # the core is stamped with this host's fleet coordinate, so N
        # hosts sharing one trace_dir write telemetry.pNNNN.jsonl
        # shards instead of colliding on one path (the pre-tentpole
        # bug: the old primary-only gate hid every other host's
        # timeline entirely). scripts/trace_merge.py joins them.
        # Elastic light-mode fleets (ISSUE 14) have no jax cluster —
        # every process would stamp (0, 1) and overwrite one shared
        # shard — so the coordinator's fleet coordinate wins: original
        # host id + the gen-0 fleet size (stable across generations,
        # so a dead host's missing tail is annotated by trace_merge
        # instead of silently shrinking the declared topology).
        if coordinator is not None:
            # a post-death RELAUNCH reuses the live core instead of
            # configuring a fresh one: configure() discards recorded
            # events, and both generations export to the same shard
            # path — reconfiguring would silently drop every
            # survivor's pre-death timeline from the merged trace
            cur = tele.get_telemetry()
            if not (cur.enabled and cur.trace_dir == trace_dir
                    and cur.process_index == coordinator.host_id):
                tele.configure(trace_dir=trace_dir,
                               process_index=coordinator.host_id,
                               host_count=coordinator.fleet_size)
        else:
            topo = topology()
            tele.configure(trace_dir=trace_dir,
                           process_index=topo["process_index"],
                           host_count=topo["host_count"])
    # fail fast: an un-evaluable valid split would otherwise only raise at
    # the FIRST eval sweep, hours into training (everything needed for the
    # check is known now)
    if valid_loader is not None and valid_loader.num_eval_batches == 0:
        raise ValueError(
            f"valid split is not evaluable ({len(valid_loader)} local "
            f"examples, batch_size={hps.batch_size}); enlarge the split, "
            f"reduce batch_size, or pass valid_loader=None")
    if model is None:
        model = SketchRNN(hps)
    mesh = make_mesh(hps) if use_mesh else None

    root_key = jax.random.key(seed)
    root_key, init_key = jax.random.split(root_key)
    state = make_train_state(model, hps, init_key)
    if primary:
        print(f"[train] model: enc={hps.enc_model} dec={hps.dec_model} "
              f"params={param_count(state.params):,} "
              f"devices={mesh.size if mesh is not None else 1}", flush=True)
    if workdir and resume and latest_checkpoint(workdir) is not None:
        state, scale_factor, meta = restore_checkpoint(workdir, state)
        print(f"[train] resumed from step {meta['step']}", flush=True)
        # crash-equivalent resume (ISSUE 10): align the feed so step S
        # of the resumed run consumes the batch the uninterrupted run
        # drew at step S — with the per-step fold_in(key, step) RNG the
        # resumed run then reproduces the uninterrupted final state
        # leaf-bitwise (scripts/resilience_bench.py is the proof
        # harness). Works for the random feed AND the bucketed plan
        # (fast_forward replays the real next_batch stream, epoch
        # refills included).
        r = int(state.step)
        if (r and hps.resume_align
                and hasattr(train_loader, "fast_forward")):
            train_loader.fast_forward(r)
            print(f"[train] resume_align: training feed fast-forwarded "
                  f"{r} batches (crash-equivalent replay; "
                  f"--hparams resume_align=false to skip)", flush=True)

    # steps_per_call > 1: K optimizer steps per jitted call (one dispatch,
    # one stacked transfer) — host-loop amortization for remote runtimes;
    # K == 1 builds the plain single-step fn.
    # With bucketing on too, the bucket-run scheduler (ISSUE 5) drives
    # the same K-scan: the feeder hands stacked geometry-run prefixes
    # [k, B, Tb+1, 5] (k <= K), full stacks dispatch one compiled
    # (K, B, Tb) scan, run remainders replay as single micro-steps, and
    # the scan folds the LIVE global step into the key so the whole run
    # is step-for-step RNG-identical to the K=1 bucketed loop.
    spc = hps.steps_per_call
    run_sched = spc > 1 and bool(getattr(train_loader, "bucket_edges", ()))
    train_step = make_multi_train_step(model, hps, mesh,
                                       key_by_global_step=run_sched)
    single_step = None  # built lazily for remainder micro-step replays
    eval_step = make_eval_step(model, hps, mesh)
    # dispatch-amortized eval sweeps (same keys/weighting as per-batch;
    # the K-batch program only compiles if a sweep actually uses it)
    eval_multi = (None if hps.eval_steps_per_call == 1 else
                  (make_multi_eval_step(model, hps, mesh),
                   hps.eval_steps_per_call))
    # multi-host: only the primary process writes metrics and checkpoints.
    # workdir MUST be shared storage in multi-host runs — every host
    # restores from it on resume, so a per-host dir would desynchronize
    # the SPMD step counts (host 0 resumes, others restart at 0)
    write_dir = workdir if primary else None
    writer = MetricsWriter(write_dir, "train")
    eval_writer = MetricsWriter(write_dir, "valid")
    # the goodput runtime: one-window-deferred metrics conversion (the
    # drain persists each row before check_finite, preserving the
    # divergence-leaves-its-record discipline) and a one-deep background
    # checkpoint writer — in the steady state the loop never blocks on a
    # device->host sync between dispatches
    # health watchdog (ISSUE 7): fed each drained row BEFORE
    # check_finite, so a divergence leaves its incident.json post-mortem
    # even when check_finite then stops the run. With the watchdog off
    # (default) the check chain is exactly check_finite — bitwise the
    # pre-watchdog loop.
    wd_monitor = None
    check = check_finite
    if (watchdog or halt_on_anomaly) and primary:
        wd_monitor = WatchdogMonitor(write_dir,
                                     halt=halt_on_anomaly).arm()

        def check(scalars, at_step, _wd=wd_monitor):
            _wd(scalars, at_step)
            check_finite(scalars, at_step)

    drain = MetricsDrain(writer, defer=hps.metrics_defer, check=check)
    ckpt = (AsyncCheckpointer(write_dir)
            if write_dir and hps.async_checkpoint else None)
    ledger = GoodputLedger(GOODPUT_PHASES)
    # padding-waste ledger (ISSUE 4): the loader records every assembled
    # batch's pad length + true timesteps host-side, so each metrics row
    # carries padded_frac and per-bucket dispatch counts with NO device
    # sync; with bucketing off it quantifies the fixed-T waste the
    # buckets would remove. Columns are pre-declared at loader build
    # (CSV header stability).
    pad_ledger = getattr(train_loader, "padding_ledger", None)
    if getattr(train_loader, "bucket_edges", ()) and primary:
        sched = (f" run_sched: steps_per_call={spc} "
                 f"run_len={hps.bucket_run_len}" if run_sched else "")
        print(f"[train] bucketed execution: edges="
              f"{train_loader.bucket_edges} "
              f"shuffle_window={hps.bucket_shuffle_window}{sched}",
              flush=True)

    step = int(state.step)
    throughput = Throughput(hps.batch_size * hps.max_seq_len,
                            num_chips=mesh.size if mesh is not None else 1)
    throughput.update(step)
    profile_span = None
    # device trace destination: beside the host telemetry when a shared
    # trace_dir exists (so XProf and the host spans align per ISSUE 6),
    # the legacy <workdir>/trace otherwise
    device_dir = (os.path.join(trace_dir, "device") if trace_dir
                  else (f"{workdir}/trace" if workdir else None))
    if profile and device_dir:
        span = (step + 10, min(step + 20, num_steps))
        if span[0] < span[1]:  # enough post-compile steps left to trace
            profile_span = span
    trace_active = False
    # overlapped input pipeline: batch assembly + sharded device transfer
    # happen on a producer thread, hidden behind the previous step's
    # device compute (SURVEY §7 "input pipeline that doesn't starve 8
    # chips"); prefetch_depth=0 gives the synchronous feed
    feeder = prefetch_batches(train_loader, mesh, hps.prefetch_depth,
                              stack=spc, transfer_dtype=hps.transfer_dtype)
    # with K-step calls the loop only observes every K-th step, so cadence
    # triggers on crossing a multiple rather than landing on one (for K=1
    # the two are identical)
    crossed = lambda prev, every: step // every > prev // every
    last_saved_step = None  # highest step THIS run checkpointed
    if trace_dir:
        # sampled device-memory gauges (live/peak bytes, per-phase
        # peaks) — the /metrics + trace view that makes bucket-edge and
        # batch-size choices memory-visible; no-op on backends without
        # memory stats (CPU). Started IMMEDIATELY before the try so the
        # finally's stop() covers the thread's whole lifetime — a
        # fail-fast raise during setup must not leak the sampler.
        mem_sampler = tele.MemorySampler().start()
        mem_sampler.phase = "train"
    try:
        while step < num_steps:
            # fault site (ISSUE 10): one invocation per loop iteration
            # (== per global step at K=1), so a chaos plan can kill or
            # crash train() at an exact step — the crash-equivalence
            # harness (scripts/resilience_bench.py) resumes from latest
            # and proves the final state bitwise equal to the
            # uninterrupted run. No-op (one global read) when no fault
            # plan is armed.
            fault_point("train.step")
            if coordinator is not None:
                # elastic fleet (ISSUE 14): one barrier per dispatch-
                # loop iteration — the host-death detection point (and
                # the host.kill.hNN fault site, inside step_barrier).
                # All hosts enter barrier `step` together holding the
                # identical replicated state, which is what makes the
                # death-time checkpoint below CONSISTENT.
                coordinator.step_barrier(step)
            if profile_span and not trace_active and step >= profile_span[0]:
                tele.get_telemetry().instant(
                    tele.DEVICE_TRACE_START, cat=tele.PROFILER_CAT,
                    args={"logdir": device_dir, "step": step})
                jax.profiler.start_trace(device_dir)
                trace_active = True
            with ledger.span("feeder_wait"):
                batch = feeder.get()
            # key is a pure function of (seed, step): a resumed run
            # continues the stream instead of replaying the pre-checkpoint
            # keys. (The run scheduler derives its keys from root_key
            # directly — fold_in(root, global_step) per micro-step.)
            prev = step
            remaining = num_steps - step
            if run_sched:
                # bucket-run scheduler: the feeder's stack is one
                # geometry run's prefix with leading axis k <= spc —
                # dispatch_stack (the shared contract) scans a full
                # stack or replays a run remainder per micro-step
                if single_step is None:
                    single_step = make_train_step(model, hps, mesh)
                with ledger.span("dispatch"):
                    state, metrics, use, n_disp = dispatch_stack(
                        single_step, train_step, state, batch, step,
                        remaining, root_key, spc)
                if pad_ledger is not None:
                    pad_ledger.record_dispatch(use, n_disp)
                step += use
            elif spc == 1 or remaining >= spc:
                step_key = jax.random.fold_in(root_key, step)
                with ledger.span("dispatch"):
                    state, metrics = train_step(state, batch, step_key)
                if pad_ledger is not None:
                    pad_ledger.record_dispatch(spc, 1)
                step += spc
            else:
                # final non-K-aligned remainder: replay the stacked micro-
                # batches through a single-step program with the same
                # per-micro-step keys the K-step call would have used
                step_key = jax.random.fold_in(root_key, step)
                if single_step is None:
                    single_step = make_train_step(model, hps, mesh)
                per_step = []
                with ledger.span("dispatch"):
                    for i in range(remaining):
                        b_i = jax.tree_util.tree_map(lambda x: x[i], batch)
                        state, metrics = single_step(
                            state, b_i, jax.random.fold_in(step_key, i))
                        per_step.append(metrics)
                # this branch's window always logs: give it the same
                # row semantics as every scan window (mean / max /
                # last-schedule — _replay_window_metrics), so a spike
                # inside the remainder surfaces like any other
                metrics = _replay_window_metrics(per_step)
                if pad_ledger is not None:
                    pad_ledger.record_dispatch(remaining, remaining)
                step += remaining
            if trace_active and step >= profile_span[1]:
                jax.block_until_ready(metrics["loss"])
                jax.profiler.stop_trace()
                tele.get_telemetry().instant(
                    tele.DEVICE_TRACE_STOP, cat=tele.PROFILER_CAT,
                    args={"step": step})
                trace_active = False
                profile_span = None

            if crossed(prev, hps.log_every) or step == num_steps:
                # host-side extras (throughput, per-phase stall ledger)
                # ride with this window's device refs; the drain converts
                # + persists + finiteness-checks the PREVIOUS window,
                # whose compute is long done — no step-chain sync
                extras = throughput.update(step) or {}
                extras.update(ledger.window())
                if pad_ledger is not None:
                    extras.update(pad_ledger.window())
                with ledger.span("metrics_drain"):
                    drain.push(step, metrics, extras)

            if valid_loader is not None and crossed(prev, hps.eval_every):
                # per-phase memory attribution: the sweep's live-bytes
                # peak lands under phase_peak_bytes_eval
                if mem_sampler is not None:
                    mem_sampler.phase = "eval"
                with ledger.span("eval"):
                    ev = evaluate(state.params, valid_loader, eval_step,
                                  mesh, multi=eval_multi)
                if mem_sampler is not None:
                    mem_sampler.phase = "train"
                eval_writer.write(step, ev)
                eval_writer.log_console(step, ev)

            if write_dir and crossed(prev, hps.save_every):
                # drain the deferral queue BEFORE committing: without
                # this, a divergence in the save step's own log window
                # (the common alignment — save_every is a multiple of
                # log_every) would checkpoint the NaN state and become
                # latest_checkpoint before the one-window-late raise,
                # wedging resume-from-latest. The flush syncs on at most
                # one window, only on save steps (save_every >>
                # log_every), preserving the pre-r6 guarantee that a
                # committed checkpoint's logged windows were all finite.
                with ledger.span("metrics_drain"):
                    drain.flush()
                # async: join any previous save (steady state ~zero),
                # snapshot, hand off — the fetch + serialize + commit
                # happen on the writer thread
                with ledger.span("ckpt_wait"):
                    if ckpt is not None:
                        ckpt.save(state, scale_factor, hps)
                    else:
                        # transient I/O failures retry with bounded
                        # backoff (ISSUE 10); permanent ones still stop
                        # training here, loudly
                        save_checkpoint(
                            write_dir, state, scale_factor, hps,
                            retries=hps.ckpt_retries,
                            retry_backoff_s=hps.ckpt_retry_backoff_s)
                last_saved_step = step
        # tail of the deferral queue: the final window's row (and its
        # finiteness guard — divergence still stops the run before the
        # final checkpoint commits) lands here
        drain.flush()
    except HostDeathDetected as death:
        # elastic recovery entry (ISSUE 14): every survivor raises HERE
        # at the same barrier step with the identical replicated state.
        # The new primary (lowest surviving id) commits that state as a
        # CONSISTENT checkpoint into the shared workdir — through the
        # active async writer when armed (the PR 3 commit path; files
        # byte-identical to sync), else the sync save — so the restart
        # protocol (train/elastic.py) resumes from the death step
        # instead of replaying back to the last cadenced save. Zero
        # device steps are lost: the recovery cost is the host-side
        # fast-forward replay only. Commit failures propagate — a fleet
        # that cannot checkpoint must halt loudly, not restart blind.
        if coordinator is not None and workdir and death.new_primary:
            if ckpt is not None:
                ckpt.save(state, scale_factor, hps)
                ckpt.wait()
            else:
                save_checkpoint(workdir, state, scale_factor, hps,
                                retries=hps.ckpt_retries,
                                retry_backoff_s=hps.ckpt_retry_backoff_s)
            print(f"[elastic] consistent checkpoint committed at step "
                  f"{int(state.step)} after death of {death.dead}",
                  flush=True)
        raise
    except AnomalyHalt as halt:
        # --halt_on_anomaly tripped: force a post-mortem checkpoint of
        # the live state into <workdir>/incident/ — NOT the resume
        # directory, so a possibly-diverged state can never become
        # latest_checkpoint and wedge resume-from-latest — then let the
        # halt propagate (the finally below still drains/joins/exports)
        if write_dir:
            inc_dir = os.path.join(write_dir, INCIDENT_CKPT_DIR)
            save_checkpoint(inc_dir, state, scale_factor, hps)
            print(f"[watchdog] post-mortem checkpoint (step "
                  f"{int(state.step)}) forced into {inc_dir}; resume "
                  f"directory left untouched: {halt}", flush=True)
        raise
    finally:
        if wd_monitor is not None:
            wd_monitor.disarm()
        feeder.close()
        # best-effort: persist the pending deferred window so a crash
        # post-mortem has its last metrics row (the synchronous loop
        # wrote every window at its own step; deferral must not lose
        # one to an unrelated raise). Swallow everything — nothing in
        # a finally may mask the propagating error. On the normal path
        # the in-try flush already emptied the queue; this is a no-op.
        try:
            drain.flush()
        except Exception:  # noqa: BLE001
            pass
        # join (never raise here — a writer error must not mask a
        # propagating one; it resurfaces via ckpt.wait() below on the
        # normal path) so no daemon thread outlives the loop; a stored
        # failure is at least REPORTED, because on an abnormal exit
        # wait() never runs and the operator must learn the checkpoint
        # they think exists was never written
        if ckpt is not None:
            ckpt.join()
            if ckpt.failure is not None:
                print(f"[ckpt] WARNING: background checkpoint write "
                      f"failed: {ckpt.failure!r} — latest_checkpoint "
                      f"in {write_dir} is older than the last save "
                      f"cadence", flush=True)
        # a check_finite/evaluate/save raise must not leave an open trace
        # session (the partial trace would be unusable and the session
        # poisons any later start_trace in this process)
        if trace_active:
            jax.profiler.stop_trace()
        # the memory sampler thread must not outlive the loop (the
        # tier-1 conftest guard names leakers)
        if mem_sampler is not None:
            mem_sampler.stop()
        # post-mortem telemetry export (best-effort — nothing in a
        # finally may mask the propagating error): a crashed traced run
        # still leaves its JSONL + Chrome trace on disk — EVERY host
        # its own shard; the normal path re-exports at return with the
        # post-loop spans included
        if trace_dir:
            try:
                tele.get_telemetry().export()
            except Exception:  # noqa: BLE001
                pass

    if write_dir:
        if ckpt is not None:
            ckpt.wait()  # surface a background save failure loudly
        # skip the final write when THIS run's last cadenced save
        # already committed this exact step (num_steps a multiple of
        # save_every): it would re-fetch and rewrite byte-identical
        # files — for a large model that doubles end-of-run latency.
        # Tracked per-run, NOT via latest_checkpoint(): a stale
        # same-step checkpoint left by a previous --no_resume run must
        # be overwritten, so directory contents cannot be trusted
        if last_saved_step != step:
            save_checkpoint(write_dir, state, scale_factor, hps,
                            retries=hps.ckpt_retries,
                            retry_backoff_s=hps.ckpt_retry_backoff_s)
    if primary:
        totals = ledger.summary()
        print("[goodput] " + " ".join(
            f"{name}={rec['total_s']:.2f}s" for name, rec in
            sorted(totals.items())), flush=True)
    if test_loader is not None and test_loader.num_eval_batches > 0:
        ev = evaluate(state.params, test_loader, eval_step, mesh,
                      multi=eval_multi)
        MetricsWriter(write_dir, "test").write(int(state.step), ev)
        print("[test] " + " ".join(f"{k}={v:.4f}"
                                   for k, v in sorted(ev.items())),
              flush=True)
    if trace_dir:
        tel = tele.get_telemetry()
        paths = tel.export()  # every host exports its own shard
        if primary:
            n_hosts = tel.host_count
            merge_hint = (" — merge the per-host shards with "
                          "scripts/trace_merge.py" if n_hosts > 1 else "")
            print(f"[telemetry] wrote {paths['jsonl']} and "
                  f"{paths['chrome']} (read with scripts/trace_report.py "
                  f"or Perfetto){merge_hint}", flush=True)
            # run manifest (ISSUE 8): the artifact index joining this
            # run's metrics, trace shards and incidents on one run_id.
            # Primary-only and traced-runs-only — the telemetry-off
            # invisibility pin (no files) extends to RUN.json.
            from sketch_rnn_tpu.utils import runinfo
            artifacts: Dict[str, object] = {
                "telemetry_shards": [
                    tele.shard_jsonl_name(i, n_hosts)
                    for i in range(n_hosts)],
                "chrome_traces": [
                    tele.shard_chrome_name(i, n_hosts)
                    for i in range(n_hosts)],
            }
            if workdir:
                artifacts["metrics"] = [
                    os.path.join(workdir, f"{n}_metrics.{ext}")
                    for n in ("train", "valid") for ext in ("csv",
                                                            "jsonl")]
                incident = os.path.join(workdir, "incident.json")
                if os.path.exists(incident):
                    artifacts["incident"] = incident
            if profile and device_dir:
                artifacts["device_trace"] = device_dir
            runinfo.write_manifest(
                trace_dir, kind="train", hps=hps, run_id=tel.run_id,
                artifacts=artifacts,
                extra={"seed": seed, "num_steps": num_steps,
                       "final_step": int(state.step)})
        # restore the disabled default so a later untraced run in the
        # same process does not keep recording into (and paying for) a
        # stale core whose files are never re-exported
        tele.disable()
    return state
