"""Elastic multi-host training: survive host death, resume at the new
topology (ISSUE 14 tentpole pieces 2-3).

The pjit/TPUv4 scaling paper (PAPERS.md) treats preemption as routine
and checkpoint-restart as THE recovery primitive; the TensorFlow
system paper makes fault tolerance a first-class runtime axis. This
module composes the repo's existing pieces into that shape:

- **Coordinated data plan** (data/loader.py ``coordinated=True``):
  every host derives the identical global schedule and feeds its row
  slice, so the global example stream is a pure function of ``(seed,
  epoch)`` — independent of the host count.
- **Failure detection** (parallel/multihost.py): a heartbeat thread
  per host plus a :class:`FleetRendezvous` barrier around every
  dispatch-loop iteration; a peer that stops arriving with a stale
  heartbeat raises :class:`HostDeathDetected` on every survivor at the
  SAME step (the barrier is the synchronization point, so all
  survivors hold the identical replicated state there).
- **Restart protocol** (:func:`elastic_train`): on detected death the
  surviving primary commits a CONSISTENT checkpoint of the live state
  (through the same ``write_checkpoint`` commit path the async writer
  uses — byte-identical files), the survivors agree on the new
  topology via an atomically-published generation file, RUN.json is
  rewritten with the new host set + a death event, and ``train()``
  relaunches on the survivors with a re-striped coordinated loader.
  ``resume_align`` then fast-forwards the fresh loader through the
  SAME global stream under the NEW striping — which is why the
  recovered run reproduces, leaf-bitwise, an uninterrupted run started
  at the surviving topology (scripts/resilience_bench.py's
  ``host.kill`` chaos cell is the end-to-end proof, via two real
  subprocesses).

Light mode vs real mesh: this box cannot form a ``jax.distributed``
cluster (the slow-marked tests/test_multihost.py DP tests need the
accelerator tunnel), so the elastic runtime runs each host as an
independent process executing the IDENTICAL global program over the
full global batch (``emit_global=True`` loaders) — the SPMD replicated
-state model with the batch all-gather as the emulated collective.
State is therefore bitwise topology-independent and every claim above
is exact. On a real mesh the same coordinator wraps the same loop with
sliced loaders and ``shard_batch``; the device all-reduce then
reassociates across topologies, so the cross-topology claim relaxes to
the documented scan tolerance while the in-topology recovery contract
is unchanged.

Fault sites (utils/faults.py): ``host.kill.hNN`` fires at host NN's
step-barrier entry — ``kind=exit`` is an honest host death (no finally
blocks, heartbeat stops beating); ``dcn.collective`` fires inside the
barrier publish. Armed-but-never-firing plans are bitwise invisible,
and the whole runtime with ``num_hosts=1`` is pinned bitwise-equal to
a plain ``train()`` (tests/test_elastic.py).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.parallel.multihost import (
    FleetRendezvous,
    HostDeathDetected,
    HostHeartbeat,
    HEARTBEAT_STALE_S,
    BARRIER_TIMEOUT_S,
)
from sketch_rnn_tpu.utils.faults import fault_point

TOPOLOGY_PREFIX = "topology_g"

_CO_LOCK = threading.Lock()
_COORDINATORS: List["ElasticCoordinator"] = []


def topology_path(rendezvous_dir: str, gen: int) -> str:
    return os.path.join(rendezvous_dir, f"{TOPOLOGY_PREFIX}{gen:03d}.json")


class ElasticCoordinator:
    """One host's handle on one topology generation: heartbeat +
    per-step barrier + the host-kill fault site. ``train()`` calls
    :meth:`step_barrier` once per dispatch-loop iteration; everything
    else is :func:`elastic_train`'s restart protocol."""

    def __init__(self, rendezvous_dir: str, host_id: int,
                 hosts: List[int], gen: int = 0,
                 stale_s: float = HEARTBEAT_STALE_S,
                 timeout_s: float = BARRIER_TIMEOUT_S,
                 heartbeat_interval_s: Optional[float] = None,
                 fleet_size: Optional[int] = None,
                 heartbeat: Optional[HostHeartbeat] = None):
        self.dir = rendezvous_dir
        self.host_id = int(host_id)
        self.hosts = sorted(int(h) for h in hosts)
        self.gen = int(gen)
        # the DECLARED gen-0 fleet size (stable across generations):
        # telemetry shards are stamped with it so a dead host reads as
        # a missing shard of an N-host run, never a shrunk topology
        self.fleet_size = (max(self.hosts) + 1 if fleet_size is None
                           else int(fleet_size))
        self.rendezvous = FleetRendezvous(
            rendezvous_dir, host_id, self.hosts, gen=gen,
            stale_s=stale_s, timeout_s=timeout_s)
        # an externally-owned heartbeat (elastic_train passes one that
        # beats across EVERY generation — stopping it between
        # generations would freeze this host's liveness file exactly
        # while it rebuilds loaders for the relaunch, and a faster
        # survivor would declare it dead); a coordinator built bare
        # owns its own.
        kw = ({} if heartbeat_interval_s is None
              else {"interval_s": heartbeat_interval_s})
        self._owns_heartbeat = heartbeat is None
        self._heartbeat = (heartbeat if heartbeat is not None
                           else HostHeartbeat(rendezvous_dir, host_id,
                                              **kw))
        self._started = False

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    @property
    def rank(self) -> int:
        """Striping rank within this generation (loader ``host_id``)."""
        return self.hosts.index(self.host_id)

    @property
    def is_primary(self) -> bool:
        """The generation's writer: lowest surviving original id."""
        return self.host_id == self.hosts[0]

    def start(self, plan_fingerprint: Optional[str] = None,
              config_hash: Optional[str] = None) -> "ElasticCoordinator":
        """Begin heartbeating and run the generation-start barrier,
        exchanging the coordinated-plan fingerprint + config hash: a
        host whose loader planned a DIFFERENT global schedule (wrong
        seed, wrong corpus, wrong config) fails loudly here instead of
        silently training on a diverged stream."""
        if self._owns_heartbeat:
            self._heartbeat.start()
        with _CO_LOCK:
            _COORDINATORS.append(self)
        self._started = True
        peers = self.rendezvous.barrier(
            "start", step=-1,
            payload={"plan": plan_fingerprint, "config": config_hash})
        for h, doc in peers.items():
            for field, mine in (("plan", plan_fingerprint),
                                ("config", config_hash)):
                theirs = doc.get(field)
                if mine is not None and theirs is not None \
                        and theirs != mine:
                    raise RuntimeError(
                        f"coordinated {field} divergence at gen "
                        f"{self.gen}: host {h} announced {theirs!r}, "
                        f"host {self.host_id} holds {mine!r} — the "
                        f"hosts would train on different global "
                        f"schedules; check seeds/corpus/config")
        return self

    def step_barrier(self, step: int) -> None:
        """Synchronize one dispatch-loop iteration across the fleet.

        Entry is the ``host.kill.hNN`` fault site — one invocation per
        loop iteration, so ``host.kill.h1@10:kind=exit`` kills host 1
        exactly at step 10 (the crash-equivalence discipline of the
        ``train.step`` site). Raises :class:`HostDeathDetected` when a
        peer is missing-and-stale; every survivor raises at the SAME
        step, holding the identical replicated state."""
        fault_point(f"host.kill.h{self.host_id}")
        self.rendezvous.barrier(f"step{int(step):08d}", step=step)

    def commit_topology(self, new_hosts: List[int], at_step: int,
                        dead: List[int],
                        resumed_from: Optional[int],
                        retired: Optional[List[int]] = None) -> dict:
        """Publish (primary) / await the next generation's topology
        file, then return its contents. Atomic publish + poll-for-file
        doubles as the survivors' regroup barrier: nobody proceeds to
        the relaunch before the consistent checkpoint AND the agreed
        host set are on disk. ``retired`` names survivors deliberately
        left out (an indivisible survivor count — see
        :func:`divisible_prefix`): a host finding itself there exits
        cleanly, while one excluded WITHOUT a retirement record was
        falsely declared dead and must refuse to proceed."""
        import time

        gen = self.gen + 1
        path = topology_path(self.dir, gen)
        doc = {"generation": gen, "hosts": sorted(new_hosts),
               "dead": sorted(dead), "at_step": int(at_step),
               "resumed_from": resumed_from,
               "retired": sorted(retired or [])}
        new_primary = min(new_hosts) == self.host_id
        if new_primary:
            from sketch_rnn_tpu.parallel.multihost import _atomic_json
            _atomic_json(path, doc)
            return doc
        deadline = time.monotonic() + self.rendezvous.timeout_s
        while time.monotonic() < deadline:
            got = _read_topology(path)
            if got is not None:
                if (self.host_id not in got["hosts"]
                        and self.host_id not in got.get("retired",
                                                        [])):
                    raise RuntimeError(
                        f"host {self.host_id} excluded from gen {gen} "
                        f"topology {got['hosts']} — the new primary "
                        f"declared this host dead; refusing to rejoin "
                        f"a fleet that re-striped without it")
                return got
            time.sleep(0.02)
        raise RuntimeError(
            f"gen {gen} topology file never appeared in {self.dir} — "
            f"the new primary (host {min(new_hosts)}) died during the "
            f"restart protocol")

    def stop(self, remove_heartbeat: bool = False) -> None:
        """Idempotent teardown; ``remove_heartbeat=True`` marks a CLEAN
        completion (the liveness file is deleted so a reused rendezvous
        dir can never mistake this host for a corpse) — crash paths
        leave the frozen file behind as the death evidence. An
        externally-owned heartbeat (elastic_train's cross-generation
        one) is left running; its owner stops it."""
        if self._owns_heartbeat or remove_heartbeat:
            self._heartbeat.stop(remove=remove_heartbeat)
        with _CO_LOCK:
            if self in _COORDINATORS:
                _COORDINATORS.remove(self)
        self._started = False

    def __repr__(self) -> str:
        return (f"ElasticCoordinator(h{self.host_id:02d}, gen={self.gen}, "
                f"hosts={self.hosts})")


def stop_all() -> tuple:
    """Stop every live coordinator (heartbeat threads included);
    returns their reprs — the conftest guard asserts this is empty."""
    with _CO_LOCK:
        leaked = tuple(_COORDINATORS)
    names = tuple(repr(c) for c in leaked)
    for c in leaked:
        c.stop()
    return names


def _read_topology(path: str) -> Optional[dict]:
    from sketch_rnn_tpu.parallel.multihost import _read_json

    return _read_json(path)


def divisible_prefix(survivors: List[int], global_batch: int
                     ) -> List[int]:
    """The largest leading subset of ``survivors`` (sorted) whose size
    divides the global batch — the host set the fleet can actually
    re-stripe onto. 4 hosts at batch 8 losing one leaves 3 survivors,
    which 8 does not divide: rather than crashing every healthy host
    on the ``local_batch_hps`` ValueError mid-recovery, the fleet
    keeps the largest workable prefix (here 2 hosts) and RETIRES the
    rest cleanly. Always non-empty (1 divides everything), and always
    contains the minimum survivor (the new primary)."""
    s = sorted(survivors)
    for k in range(len(s), 0, -1):
        if global_batch % k == 0:
            return s[:k]
    raise AssertionError("unreachable: k=1 divides any batch")


def latest_topology(rendezvous_dir: str) -> Optional[dict]:
    """Highest-generation topology file in the rendezvous dir (None on
    a fresh fleet)."""
    try:
        names = sorted(n for n in os.listdir(rendezvous_dir)
                       if n.startswith(TOPOLOGY_PREFIX))
    except OSError:
        return None
    for name in reversed(names):
        doc = _read_topology(os.path.join(rendezvous_dir, name))
        if doc is not None:
            return doc
    return None


def elastic_train(hps: HParams,
                  make_loaders: Callable,
                  *,
                  rendezvous_dir: str,
                  host_id: int,
                  num_hosts: int,
                  workdir: str,
                  seed: int = 0,
                  num_steps: Optional[int] = None,
                  use_mesh: bool = True,
                  resume: bool = True,
                  trace_dir: Optional[str] = None,
                  profile: bool = False,
                  watchdog: bool = False,
                  halt_on_anomaly: bool = False,
                  stale_s: float = HEARTBEAT_STALE_S,
                  timeout_s: float = BARRIER_TIMEOUT_S,
                  heartbeat_interval_s: Optional[float] = None,
                  max_generations: int = 16):
    """Run ``train()`` as host ``host_id`` of an elastic ``num_hosts``
    fleet; returns the final TrainState on every surviving host (or
    None on a host cleanly RETIRED because a post-death survivor count
    did not divide the global batch — see :func:`divisible_prefix`).

    ``make_loaders(local_hps, rank, n_hosts)`` must build fresh
    COORDINATED loaders for one generation and return ``(train_loader,
    valid_loader, test_loader, scale_factor)`` — it is called again
    after every topology change with the new striping (the fresh-
    loader-per-relaunch discipline resume_align depends on). ``hps``
    carries the GLOBAL batch size, like the cli/train contract.

    The restart protocol on a detected death (every survivor, same
    step): the surviving primary has already committed the consistent
    checkpoint inside ``train()``'s handler; survivors agree on the
    new topology (generation file), the primary rewrites RUN.json with
    the new host set + the death event, and the loop relaunches
    ``train()`` with ``resume=True`` — restore + ``resume_align``
    fast-forward through the same global stream at the new striping.
    A host absent from the agreed topology (or the dead host itself)
    never rejoins: generations only shrink.
    """
    if num_hosts < 1 or not 0 <= host_id < num_hosts:
        raise ValueError(f"host_id {host_id} out of range for "
                         f"num_hosts={num_hosts}")
    if hps.batch_size % num_hosts != 0:
        raise ValueError(f"global batch {hps.batch_size} not divisible "
                         f"by {num_hosts} hosts")
    topo = latest_topology(rendezvous_dir)
    if topo is None:
        gen, hosts = 0, list(range(num_hosts))
    else:
        # a relaunched/late host joins the CURRENT generation (the
        # fleet may already have shrunk); the dead never rejoin
        gen, hosts = int(topo["generation"]), list(topo["hosts"])
        if host_id not in hosts:
            raise RuntimeError(
                f"host {host_id} is not part of the current topology "
                f"{hosts} (gen {gen}); dead hosts do not rejoin an "
                f"elastic fleet")
    events: List[dict] = []
    # ONE heartbeat for the whole run, beating across generations: the
    # inter-generation regroup (loader rebuild, plan fingerprint) can
    # take longer than stale_s on real data, and a survivor whose
    # liveness file froze during it would be falsely declared dead by
    # a faster peer. Stopped only on final return (clean: file
    # removed) or in the outer finally (crash: frozen file = the
    # evidence peers detect).
    hb_kw = ({} if heartbeat_interval_s is None
             else {"interval_s": heartbeat_interval_s})
    heartbeat = HostHeartbeat(rendezvous_dir, host_id,
                              **hb_kw).start()
    try:
        return _elastic_generations(
            hps, make_loaders, rendezvous_dir=rendezvous_dir,
            host_id=host_id, num_hosts=num_hosts, workdir=workdir,
            seed=seed, num_steps=num_steps, use_mesh=use_mesh,
            resume=resume, trace_dir=trace_dir, profile=profile,
            watchdog=watchdog, halt_on_anomaly=halt_on_anomaly,
            stale_s=stale_s, timeout_s=timeout_s,
            max_generations=max_generations, gen=gen, hosts=hosts,
            events=events, heartbeat=heartbeat)
    finally:
        heartbeat.stop()


def _elastic_generations(hps, make_loaders, *, rendezvous_dir, host_id,
                         num_hosts, workdir, seed, num_steps, use_mesh,
                         resume, trace_dir, profile, watchdog,
                         halt_on_anomaly, stale_s, timeout_s,
                         max_generations, gen, hosts, events,
                         heartbeat):
    """The per-generation loop of :func:`elastic_train` (which owns the
    cross-generation heartbeat wrapped around this)."""
    from sketch_rnn_tpu.parallel.multihost import local_batch_hps
    from sketch_rnn_tpu.train.loop import train
    from sketch_rnn_tpu.utils import runinfo

    while True:
        n = len(hosts)
        coord = ElasticCoordinator(
            rendezvous_dir, host_id, hosts, gen=gen, stale_s=stale_s,
            timeout_s=timeout_s, fleet_size=num_hosts,
            heartbeat=heartbeat)
        lhps = local_batch_hps(hps, num_hosts=n)
        train_l, valid_l, test_l, scale = make_loaders(
            lhps, coord.rank, n)
        fp = (train_l.plan_fingerprint()
              if hasattr(train_l, "plan_fingerprint") else None)
        try:
            coord.start(plan_fingerprint=fp,
                        config_hash=runinfo.config_hash(hps))
            if coord.is_primary and workdir:
                # RUN.json is the fleet's topology ledger (ISSUE 8
                # manifests): rewritten every generation with the LIVE
                # host set and the accumulated death events, so an
                # operator (and the chaos harness) can read exactly
                # how the fleet shrank and where each resume landed
                runinfo.write_manifest(
                    workdir, kind="elastic_train", hps=hps,
                    extra={"elastic": {
                        "generation": gen, "num_hosts": n,
                        "hosts": hosts, "events": events,
                        "rendezvous_dir": os.path.abspath(
                            rendezvous_dir)}})
            state = train(hps, train_l, valid_loader=valid_l,
                          test_loader=test_l, scale_factor=scale,
                          workdir=workdir, seed=seed,
                          num_steps=num_steps, use_mesh=use_mesh,
                          resume=resume, trace_dir=trace_dir,
                          profile=profile, watchdog=watchdog,
                          halt_on_anomaly=halt_on_anomaly,
                          coordinator=coord)
            # clean completion: drop the liveness file so a reused
            # rendezvous dir reads this host as "done", never "dead"
            coord.stop(remove_heartbeat=True)
            return state
        except HostDeathDetected as death:
            from sketch_rnn_tpu.train.checkpoint import latest_checkpoint

            # only the NEW PRIMARY's view of latest_checkpoint is
            # authoritative (it reads after its own consistent commit
            # inside train()'s handler); other survivors would race
            # that commit and record a stale cadenced save — they take
            # the value from the published topology doc instead
            resumed_from = (latest_checkpoint(workdir)
                            if workdir and death.new_primary else None)
            # a survivor count that does not divide the global batch
            # cannot be striped onto: keep the largest workable prefix
            # and RETIRE the rest cleanly (crashing every healthy host
            # on the local_batch_hps ValueError mid-recovery would
            # turn one death into a fleet-wide halt)
            new_hosts = divisible_prefix(death.survivors,
                                         hps.batch_size)
            retired = [h for h in death.survivors
                       if h not in new_hosts]
            topo_doc = coord.commit_topology(
                new_hosts, death.step, death.dead, resumed_from,
                retired=retired)
            resumed_from = topo_doc.get("resumed_from")
            print(f"[elastic] host {host_id}: detected death of "
                  f"{death.dead} at step {death.step}; regrouping as "
                  f"{topo_doc['hosts']} (resume from {resumed_from}"
                  + (f"; retired {topo_doc.get('retired')}"
                     if topo_doc.get("retired") else "") + ")",
                  flush=True)
            events.append({"generation": gen, "dead": death.dead,
                           "at_step": death.step,
                           "resumed_from": resumed_from,
                           "retired": topo_doc.get("retired", [])})
            gen, hosts = topo_doc["generation"], list(topo_doc["hosts"])
            if host_id not in hosts:
                # deliberately retired: exit CLEANLY (liveness file
                # removed — the fleet must not read this host as a
                # corpse; it holds no state the survivors need)
                print(f"[elastic] host {host_id}: retired — "
                      f"{len(death.survivors)} survivors do not "
                      f"divide global batch {hps.batch_size}; the "
                      f"fleet continues as {hosts}", flush=True)
                heartbeat.stop(remove=True)
                return None
            resume = True
            if gen > max_generations:
                raise RuntimeError(
                    f"elastic fleet restarted {gen} times — beyond "
                    f"max_generations={max_generations}; refusing to "
                    f"thrash") from death
        finally:
            coord.stop()
