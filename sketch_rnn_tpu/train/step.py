"""Jitted train / eval steps with mesh-aware sharding.

TPU-native equivalent of the reference's per-step ``sess.run(train_op)``
(SURVEY.md §3.1: on the GPU reference the host↔device boundary is crossed
every step; here the whole step — forward, backward, gradient all-reduce,
Adam update, schedules — is ONE jitted XLA computation).

Data parallelism (component 18) is EXPLICIT SPMD: the per-device loss/
gradient computation runs under ``jax.shard_map`` over the mesh's
``data`` axis with the batch sharded and parameters replicated, and the
gradient all-reduce is a ``lax.psum`` over ICI — the NCCL-allreduce
equivalent. Explicit (rather than GSPMD-automatic) partitioning is
load-bearing: the Pallas fused RNN kernels lower to ``tpu_custom_call``,
which the automatic partitioner cannot shard — under plain
``jit(in_shardings=...)`` each chip would all-gather the global batch
and run the full kernel, silently serializing data parallelism. Inside
``shard_map`` every device runs the kernel on its own batch shard.

Loss semantics stay EXACTLY global-batch: ``model.loss(axis_name=...)``
computes psum'd global sums/normalizers, so nonlinear terms (the KL
free-bits floor) see the global batch mean, and each device's local
gradient is its contribution to the global gradient (one psum finishes
the all-reduce — this is AD through the psum'd loss).

``donate_argnums=0`` donates the previous state's buffers to the update so
parameters are updated in place in HBM.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.parallel.mesh import (
    DATA_AXIS,
    batch_sharding,
    check_batch_divisible,
    replicated_sharding,
    stacked_batch_sharding,
)
from sketch_rnn_tpu.train.schedules import kl_weight_schedule, lr_schedule
from sketch_rnn_tpu.train.state import TrainState, make_optimizer
from sketch_rnn_tpu.utils.compat import shard_map
from sketch_rnn_tpu.utils.telemetry import JitCompileProbe

Batch = Dict[str, jax.Array]
Metrics = Dict[str, jax.Array]
StepFn = Callable[[TrainState, Batch, jax.Array], Tuple[TrainState, Metrics]]
EvalFn = Callable[[Any, Batch, jax.Array], Metrics]


def batch_geometry(batch: Batch) -> Tuple[int, int]:
    """``(B, T)`` of a loader batch — T excludes the start token.

    Host-side metadata (array SHAPES never sync the device). Under
    length-bucketed execution (ISSUE 4) this is the compiled-executable
    cache key: every jitted step/eval function here is traced per input
    geometry, so a batch padded to bucket edge ``Tb`` dispatches the
    ``(B, Tb)`` executable — the same shape-keyed cache the eval sweep's
    K-batch scan programs already live in. The cache is ``jax.jit``'s
    own; :func:`geometry_cache_size` exposes its size so tests (and the
    bucket bench) can assert one executable per bucket, not one per
    step.
    """
    b, t1 = batch["strokes"].shape[-3], batch["strokes"].shape[-2]
    return int(b), int(t1) - 1


def geometry_cache_size(fn) -> Optional[int]:
    """Number of compiled executables held by a jitted step/eval fn
    (None when the runtime does not expose it). Counts THROUGH a
    :class:`~sketch_rnn_tpu.utils.telemetry.JitCompileProbe` wrapper —
    the probe sums its own AOT executables with the inner jit cache."""
    try:
        return int(fn._cache_size())
    except AttributeError:
        return None


def _probe_batch_key(args) -> Tuple:
    """Compile-probe geometry key for step/eval calls: the BATCH dict's
    leaf shapes (args[1]) — the only shapes that vary across dispatches
    of one run (state/params geometries are fixed at build), and the
    exact signature jit's own executable cache keys on for them,
    including leaf presence (a weighted wrap-fill batch is a different
    program than an unweighted one)."""
    return tuple(sorted((k, tuple(v.shape)) for k, v in args[1].items()))


def _probe_batch_label(args) -> str:
    """Human-readable geometry for the compile span: ``(B, Tb)`` plus
    the stack depth K for stacked [K, B, Tb+1, 5] dispatches."""
    s = args[1]["strokes"].shape
    b, t = int(s[-3]), int(s[-2]) - 1
    return (f"K{int(s[0])}x(B{b},T{t})" if len(s) == 4
            else f"(B{b},T{t})")


def _probe(fn, name: str) -> JitCompileProbe:
    """Wrap a jitted step/eval fn with the per-geometry compile probe
    (ISSUE 8): compile spans + jit-cache hit/miss counters + per-
    executable cost/memory stats when telemetry is on; a passthrough
    (inner jit cache, bitwise the pre-probe path) when off. Every
    probe also registers with the unified runtime's default scheduler
    (ISSUE 20) so train/eval compile counts share one audit surface
    with the serve programs."""
    from sketch_rnn_tpu.runtime.scheduler import default_scheduler

    return default_scheduler().register(
        JitCompileProbe(fn, name, key_of=_probe_batch_key,
                        label_of=_probe_batch_label))


def _vma_check(hps: HParams) -> bool:
    """Whether shard_map's varying-manual-axes replication check can run.

    The Pallas HLO interpreter (used whenever the kernels run in
    interpret mode, i.e. non-TPU backends / the CPU test mesh) generates
    unvarying slice indices that jax 0.9's vma checker rejects ("open an
    issue / pass check_vma=False"); on real TPU the Mosaic path declares
    output vma (ops.pallas_fused._sds) and the check stays live.
    """
    from sketch_rnn_tpu.ops.pallas_fused import _interpret_default

    return not (hps.fused_rnn and _interpret_default())


def _make_single_step_core(model, hps: HParams, mesh: Optional[Mesh],
                           tx) -> StepFn:
    """The un-jitted ``(state, batch, key) -> (state, metrics)`` step body;
    shared by the single-step and K-micro-step (scan) jitted wrappers."""

    def grads_and_metrics(params, batch, key, kl_w, axis_name):
        if axis_name is not None:
            # decorrelate per-device dropout streams: each shard's rows
            # draw iid masks (a fresh global draw, not a split of one)
            key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))

        def loss_fn(p):
            return model.loss(p, batch, key, kl_w, train=True,
                              axis_name=axis_name)

        (_, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        if axis_name is not None:
            # local grads are per-device contributions to the GLOBAL loss
            # gradient (the loss is psum'd-global); sum completes the
            # all-reduce over ICI
            grads = jax.lax.psum(grads, axis_name)
        return grads, metrics

    def finish(state, grads, metrics):
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        metrics["lr"] = lr_schedule(hps, state.step)
        return TrainState(params, opt_state, state.step + 1), metrics

    if mesh is None:
        def step_fn(state: TrainState, batch: Batch, key: jax.Array):
            kl_w = kl_weight_schedule(hps, state.step)
            grads, metrics = grads_and_metrics(state.params, batch, key,
                                               kl_w, None)
            return finish(state, grads, metrics)

        return step_fn

    check_batch_divisible(hps.batch_size, mesh)
    sharded = shard_map(
        lambda params, batch, key, kl_w: grads_and_metrics(
            params, batch, key, kl_w, DATA_AXIS),
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(), P()),
        out_specs=(P(), P()),
        check_vma=_vma_check(hps),
    )

    def step_fn(state: TrainState, batch: Batch, key: jax.Array):
        kl_w = kl_weight_schedule(hps, state.step)
        grads, metrics = sharded(state.params, batch, key, kl_w)
        return finish(state, grads, metrics)

    return step_fn


def make_train_step(model, hps: HParams,
                    mesh: Optional[Mesh] = None,
                    donate: bool = True) -> StepFn:
    """Build the jitted ``(state, batch, key) -> (state, metrics)`` step.

    The returned function is the per-bucket compiled-step cache of
    length-bucketed execution: jit keys its executable cache on input
    geometry, so dispatching bucket-padded batches routes each ``(B,
    Tb)`` to its own compiled program (compiled once, on first
    dispatch) while the ``TrainState`` — whose shapes never vary with
    the bucket — is donated and updated in place by every one of them.
    The loss normalizer is ``hps.max_seq_len`` (static, NOT the batch
    T), which is what keeps the masked GMM term exactly
    bucket-independent (ops/mdn.py).

    ``donate=False`` builds the step WITHOUT state donation — the
    control arm ``scripts/runtime_bench.py`` measures the donated
    program's peak-bytes reduction against (ISSUE 20). Production
    callers never pass it: donating the state is the live contract the
    async checkpointer's snapshot-before-dispatch discipline assumes.
    """
    step_fn = _make_single_step_core(model, hps, mesh, make_optimizer(hps))
    dn = dict(donate_argnums=0) if donate else {}
    if mesh is None:
        return _probe(jax.jit(step_fn, **dn), "train_step")
    repl = replicated_sharding(mesh)
    data = batch_sharding(mesh)
    return _probe(jax.jit(
        step_fn,
        # pytree-prefix shardings: whole state replicated, whole batch
        # data-sharded, key replicated
        in_shardings=(repl, data, repl),
        out_shardings=(repl, repl),
        **dn,
    ), "train_step")


def make_multi_train_step(model, hps: HParams,
                          mesh: Optional[Mesh] = None,
                          steps_per_call: Optional[int] = None,
                          key_by_global_step: bool = False) -> StepFn:
    """Build a jitted K-micro-step train call (host-loop amortization).

    ``(state, batches, key) -> (state, last_metrics)`` where ``batches``
    is a stacked pytree with leading axis ``K = steps_per_call`` (one
    fresh batch per micro-step, see ``data.prefetch.prefetch_batches``'s
    ``stack``). The K optimizer steps run as ONE ``lax.scan`` inside one
    XLA program: one dispatch + one host->device transfer per K steps,
    which removes per-launch latency from the step-time critical path —
    the TPU-native answer to remote-runtime dispatch overhead (the
    reference pays a ``sess.run`` boundary EVERY step, SURVEY §3.1).

    Micro-step ``i`` uses ``fold_in(key, i)``; schedules read the live
    ``state.step`` carried through the scan, so K calls of this are
    step-for-step equivalent (same schedules, same per-step key
    discipline) to K single-step calls with keys ``fold_in(key, i)``.

    ``key_by_global_step=True`` (the bucket-run scheduler's mode,
    ISSUE 5) folds the live ``state.step`` carried through the scan
    instead of the micro-step index: micro-step ``i`` starting at
    global step ``s0`` uses ``fold_in(key, s0 + i)``. Called with the
    loop's ROOT key, this makes a ``steps_per_call=K`` run step-for-
    step RNG-IDENTICAL to the K=1 loop (whose per-step key is
    ``fold_in(root, global_step)``) — which is what lets run
    remainders replay through the single-step program mid-run without
    forking the key stream. One compiled K-scan per input geometry:
    the returned function's jit cache keys on the stacked batch shape,
    so bucketed ``[K, B, Tb, ...]`` stacks each get their own
    executable (``geometry_cache_size`` counts scan programs the same
    way it counts single-step ones).

    Returned metrics are the MEAN over the K micro-steps (a divergence
    spike inside the window surfaces at the next log line instead of
    only when it happens to land on micro-step K), plus
    ``grad_norm_max`` — the window's worst-case gradient norm, the
    earliest instability signal. ``lr`` stays the last micro-step's
    value (the schedule's current point; a K-mean would be a value no
    step used). Aggregation happens inside the jitted program — the
    scan's stacked metrics never leave the device.
    """
    k = hps.steps_per_call if steps_per_call is None else steps_per_call
    if k == 1 and not key_by_global_step:
        return make_train_step(model, hps, mesh)
    tx = make_optimizer(hps)
    single = _make_single_step_core(model, hps, mesh, tx)

    def multi_fn(state: TrainState, batches: Batch, key: jax.Array):
        def body(st, xs):
            batch_i, i = xs
            micro_key = (jax.random.fold_in(key, st.step)
                         if key_by_global_step
                         else jax.random.fold_in(key, i))
            st, metrics = single(st, batch_i, micro_key)
            return st, metrics

        # scan length comes from the stacked batch's leading axis, so
        # the SAME jitted fn serves every full-stack size the scheduler
        # dispatches (one executable per (K, B, Tb) input geometry)
        state, stacked = jax.lax.scan(
            body, state,
            (batches, jnp.arange(jax.tree_util.tree_leaves(batches)[0]
                                 .shape[0])))
        metrics = jax.tree_util.tree_map(
            lambda v: jnp.mean(v, axis=0), stacked)
        metrics["grad_norm_max"] = jnp.max(stacked["grad_norm"])
        # schedule values stay the last micro-step's (the state.step the
        # log line is attributed to); a K-mean would be a value no step
        # actually used
        metrics["lr"] = stacked["lr"][-1]
        metrics["kl_weight"] = stacked["kl_weight"][-1]
        return state, metrics

    if mesh is None:
        return _probe(jax.jit(multi_fn, donate_argnums=0),
                      "train_step_k")
    repl = replicated_sharding(mesh)
    stacked_data = stacked_batch_sharding(mesh)
    return _probe(jax.jit(multi_fn,
                          in_shardings=(repl, stacked_data, repl),
                          out_shardings=(repl, repl),
                          donate_argnums=0), "train_step_k")


def _make_eval_core(model, hps: HParams, mesh: Optional[Mesh]):
    """Un-jitted ``(params, batch, key) -> metrics`` eval body (shard_map'd
    over the mesh when given); shared by the single-batch and the
    K-batch (scan) jitted wrappers so the two cannot drift."""

    def eval_fn(params, batch: Batch, key: jax.Array,
                axis_name: Optional[str] = None) -> Metrics:
        if axis_name is not None:
            # decorrelate per-shard z draws (as in training): without the
            # fold every device would sample identical posterior noise and
            # the NLL estimator's variance would not average down
            key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
        _, metrics = model.loss(params, batch, key, kl_weight=1.0,
                                train=False, axis_name=axis_name)
        # GLOBAL count of real (weight>0) rows, computed on device so each
        # host sees the cluster-wide value — the eval sweep weights batch
        # averages by it (wrap-filled duplicate rows carry weight 0)
        if "weights" in batch:
            ws = jnp.sum(batch["weights"])
        else:
            ws = jnp.float32(batch["strokes"].shape[0])
        if axis_name is not None:
            ws = jax.lax.psum(ws, axis_name)
        metrics["weight_sum"] = ws
        return metrics

    if mesh is None:
        return eval_fn
    return shard_map(
        lambda params, batch, key: eval_fn(params, batch, key, DATA_AXIS),
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P()),
        out_specs=P(),
        check_vma=_vma_check(hps),
    )


def _jit_single_eval(core, mesh: Optional[Mesh],
                     name: str = "eval_step") -> EvalFn:
    if mesh is None:
        return _probe(jax.jit(core), name)
    repl = replicated_sharding(mesh)
    return _probe(jax.jit(core,
                          in_shardings=(repl, batch_sharding(mesh), repl),
                          out_shardings=repl), name)


def _jit_multi_eval(core, mesh: Optional[Mesh],
                    name: str = "eval_step_k"):
    """K-batch eval call: ``(params, batches, key, idx) -> metrics`` with
    every metric stacked ``[K, ...]``.

    ``batches`` is a stacked pytree (leading axis K), ``idx`` the
    absolute batch indices ``[K]``; batch ``idx[j]`` uses
    ``fold_in(key, idx[j])`` — exactly the key the single-batch sweep
    would use, so the two paths agree up to XLA reassociation noise
    (~1e-6; the scan compiles as a different program). One
    dispatch + one host fetch per K batches amortizes the tunneled
    runtime's 10-130 ms per-call launch cost the same way
    ``make_multi_train_step`` does for training (VERDICT r3 #5).
    """

    def multi_fn(params, batches: Batch, key: jax.Array, idx: jax.Array):
        def body(_, xs):
            batch_i, i = xs
            return None, core(params, batch_i, jax.random.fold_in(key, i))

        _, stacked = jax.lax.scan(body, None, (batches, idx))
        return stacked

    if mesh is None:
        return _probe(jax.jit(multi_fn), name)
    repl = replicated_sharding(mesh)
    return _probe(jax.jit(multi_fn,
                          in_shardings=(repl, stacked_batch_sharding(mesh),
                                        repl, repl),
                          out_shardings=repl), name)


def make_eval_step(model, hps: HParams,
                   mesh: Optional[Mesh] = None) -> EvalFn:
    """Jitted eval: dropout off, pen CE masked, KL un-annealed (weight=1).

    Mirrors the reference's weight-tied eval graph (SURVEY §3.4) — here
    simply the same pure loss with ``train=False`` compiled as a second
    XLA program. Returned metrics use the eval normalization that is the
    parity surface: recon-NLL, KL (floored) and total with full KL weight.
    On a mesh the sweep runs under ``shard_map`` like training; psum'd
    global sums make every weighted metric exactly the global-batch value
    regardless of how the zero-weight wrap rows fall across shards.
    """
    return _jit_single_eval(_make_eval_core(model, hps, mesh), mesh,
                            "eval_step")


def make_multi_eval_step(model, hps: HParams,
                         mesh: Optional[Mesh] = None):
    """K-batch jitted eval (see :func:`_jit_multi_eval`); pair it with
    ``hps.eval_steps_per_call`` as ``evaluate``'s ``multi=`` argument."""
    return _jit_multi_eval(_make_eval_core(model, hps, mesh), mesh,
                           "eval_step_k")


def _make_per_class_core(model, hps: HParams, mesh: Optional[Mesh]):
    """Un-jitted per-class eval body (see :func:`_make_eval_core`)."""

    def eval_fn(params, batch: Batch, key: jax.Array,
                axis_name: Optional[str] = None) -> Metrics:
        if axis_name is not None:
            # decorrelate per-shard z draws, as in make_eval_step
            key = jax.random.fold_in(key, jax.lax.axis_index(axis_name))
        return model.eval_metrics_per_class(params, batch, key,
                                            axis_name=axis_name)

    if mesh is None:
        return eval_fn
    return shard_map(
        lambda params, batch, key: eval_fn(params, batch, key, DATA_AXIS),
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P()),
        out_specs=P(),
        check_vma=_vma_check(hps),
    )


def make_per_class_eval_step(model, hps: HParams,
                             mesh: Optional[Mesh] = None) -> EvalFn:
    """Jitted per-class eval: ``[num_classes]`` metric vectors per batch.

    Same sweep discipline as :func:`make_eval_step` — the batch schedule
    is the STANDARD eval sweep, identical on every host, so per-class
    eval is multi-host safe (``DataLoader.filter_by_label`` is not: the
    per-class global batch count is not derivable locally under host
    striping). Per-class reduction happens inside the forward program
    (``model.eval_metrics_per_class``), psum'd over the mesh axis.
    """
    return _jit_single_eval(_make_per_class_core(model, hps, mesh), mesh,
                            "per_class_eval")


def make_multi_per_class_eval_step(model, hps: HParams,
                                   mesh: Optional[Mesh] = None):
    """K-batch jitted per-class eval (metrics stacked ``[K, C]``)."""
    return _jit_multi_eval(_make_per_class_core(model, hps, mesh), mesh,
                           "per_class_eval_k")
