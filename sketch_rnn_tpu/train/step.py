"""Jitted train / eval steps with mesh-aware sharding.

TPU-native equivalent of the reference's per-step ``sess.run(train_op)``
(SURVEY.md §3.1: on the GPU reference the host↔device boundary is crossed
every step; here the whole step — forward, backward, gradient all-reduce,
Adam update, schedules — is ONE jitted XLA computation). Data parallelism
(component 18) is expressed with ``NamedSharding``: the batch is split
along the mesh's ``data`` axis, parameters/optimizer state are replicated,
and the SPMD partitioner inserts the gradient all-reduce over ICI (the
NCCL-allreduce equivalent).

``donate_argnums=0`` donates the previous state's buffers to the update so
parameters are updated in place in HBM.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.parallel.mesh import (
    batch_sharding,
    check_batch_divisible,
    replicated_sharding,
)
from sketch_rnn_tpu.train.schedules import kl_weight_schedule, lr_schedule
from sketch_rnn_tpu.train.state import TrainState, make_optimizer

Batch = Dict[str, jax.Array]
Metrics = Dict[str, jax.Array]
StepFn = Callable[[TrainState, Batch, jax.Array], Tuple[TrainState, Metrics]]
EvalFn = Callable[[Any, Batch, jax.Array], Metrics]


def make_train_step(model, hps: HParams,
                    mesh: Optional[Mesh] = None) -> StepFn:
    """Build the jitted ``(state, batch, key) -> (state, metrics)`` step."""
    tx = make_optimizer(hps)

    def step_fn(state: TrainState, batch: Batch, key: jax.Array
                ) -> Tuple[TrainState, Metrics]:
        kl_w = kl_weight_schedule(hps, state.step)

        def loss_fn(params):
            return model.loss(params, batch, key, kl_w, train=True)

        (_, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        metrics["lr"] = lr_schedule(hps, state.step)
        return TrainState(params, opt_state, state.step + 1), metrics

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=0)
    check_batch_divisible(hps.batch_size, mesh)
    repl = replicated_sharding(mesh)
    data = batch_sharding(mesh)
    return jax.jit(
        step_fn,
        # pytree-prefix shardings: whole state replicated, whole batch
        # data-sharded, key replicated
        in_shardings=(repl, data, repl),
        out_shardings=(repl, repl),
        donate_argnums=0,
    )


def make_eval_step(model, hps: HParams,
                   mesh: Optional[Mesh] = None) -> EvalFn:
    """Jitted eval: dropout off, pen CE masked, KL un-annealed (weight=1).

    Mirrors the reference's weight-tied eval graph (SURVEY §3.4) — here
    simply the same pure loss with ``train=False`` compiled as a second
    XLA program. Returned metrics use the eval normalization that is the
    parity surface: recon-NLL, KL (floored) and total with full KL weight.
    """

    def eval_fn(params, batch: Batch, key: jax.Array) -> Metrics:
        _, metrics = model.loss(params, batch, key,
                                kl_weight=1.0, train=False)
        # GLOBAL count of real (weight>0) rows, computed on device so each
        # host sees the cluster-wide value — the eval sweep weights batch
        # averages by it (wrap-filled duplicate rows carry weight 0)
        if "weights" in batch:
            metrics["weight_sum"] = jnp.sum(batch["weights"])
        else:
            metrics["weight_sum"] = jnp.float32(batch["strokes"].shape[0])
        return metrics

    if mesh is None:
        return jax.jit(eval_fn)
    repl = replicated_sharding(mesh)
    data = batch_sharding(mesh)
    return jax.jit(eval_fn, in_shardings=(repl, data, repl),
                   out_shardings=repl)
