"""Mesh construction and batch sharding.

TPU-native replacement for the reference's NCCL data-parallel layer
(SURVEY.md §2 component 18, §5 "Distributed communication backend";
reference unreadable — semantics per BASELINE.json's "pmap'd data-parallel
loop with gradient allreduce over ICI instead of NCCL").

Design: a named device mesh with the batch sharded along the ``data``
axis and parameters replicated. The training step runs the per-device
loss/gradient computation under ``jax.shard_map`` (see
``train/step.py``): explicit SPMD is load-bearing because the Pallas
fused RNN kernels lower to ``tpu_custom_call``, which the automatic
GSPMD partitioner cannot shard — the gradient all-reduce is an explicit
``lax.psum`` over ICI (the NCCL-allreduce equivalent), falling out of AD
through the psum'd global loss. The mesh keeps extra named axes
(``hps.mesh_shape``/``mesh_axes``) open for model-parallel sharding
later without changing the step API.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sketch_rnn_tpu.config import HParams

DATA_AXIS = "data"


def make_mesh(hps: Optional[HParams] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the device mesh from ``hps.mesh_shape`` / ``hps.mesh_axes``.

    A ``-1`` entry in ``mesh_shape`` absorbs all remaining devices (the
    default ``(-1,)`` over ``("data",)`` is pure data parallelism across
    every chip).
    """
    devices = list(devices if devices is not None else jax.devices())
    shape = list(hps.mesh_shape) if hps is not None else [-1]
    axes = tuple(hps.mesh_axes) if hps is not None else (DATA_AXIS,)
    if len(shape) != len(axes):
        raise ValueError(f"mesh_shape {shape} and mesh_axes {axes} "
                         f"must have equal length")
    n = len(devices)
    if shape.count(-1) > 1:
        raise ValueError("at most one -1 in mesh_shape")
    fixed = int(np.prod([s for s in shape if s != -1])) if shape else 1
    if -1 in shape:
        if n % fixed != 0:
            raise ValueError(f"{n} devices not divisible by fixed mesh "
                             f"dims {fixed}")
        shape[shape.index(-1)] = n // fixed
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh_shape {shape} != device count {n}")
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axes)


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding for host batches: leading (batch) dim split over ``axis``."""
    return NamedSharding(mesh, P(axis))


def stacked_batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding for ``[K, batch, ...]`` stacked multi-step batches: the
    micro-step axis replicated, the batch axis split over ``axis``.

    This is also the bucketed stacked layout (ISSUE 5): a geometry
    run's stack is ``[k, B, Tb+1, 5]`` where the per-bucket ``Tb`` is
    replicated shape metadata (every device compiles against it) and
    only ``B`` shards — so length-bucketed K-step execution composes
    with the mesh exactly like fixed-T K-step execution, one sharded
    transfer per dispatched run prefix."""
    return NamedSharding(mesh, P(None, axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding (parameters, PRNG keys, scalars)."""
    return NamedSharding(mesh, P())


def check_batch_divisible(batch_size: int, mesh: Mesh,
                          axis: str = DATA_AXIS) -> None:
    n = mesh.shape[axis]
    if batch_size % n != 0:
        raise ValueError(
            f"batch_size={batch_size} must be divisible by the {axis!r} "
            f"mesh axis size {n} (global batch is split across devices)")


def shard_batch(batch: Dict[str, Any], mesh: Mesh,
                axis: str = DATA_AXIS, stacked: bool = False
                ) -> Dict[str, jax.Array]:
    """Move a host numpy batch onto the mesh, split along ``axis``.

    One sharded transfer per step — the only host→device boundary in the
    training loop (SURVEY §3.1 boundary notes). Under multi-host
    execution each process passes its LOCAL shard of the global batch
    (``1/process_count`` of the rows, see ``parallel.multihost``) and the
    global array is assembled without any cross-host data movement.
    ``stacked=True`` handles ``[K, batch, ...]`` multi-step batches
    (micro-step axis replicated, batch axis split) — including bucketed
    geometry-run stacks ``[k, B, Tb+1, 5]``, whose per-run ``k`` and
    per-bucket ``Tb`` vary call to call (shape metadata only; each
    geometry routes to its own compiled program downstream).
    """
    if stacked:
        # a torn stack (a producer bug mixing run prefixes) would
        # otherwise surface as an opaque XLA shape error steps later
        ks = {np.shape(x)[0] for x in jax.tree_util.tree_leaves(batch)}
        if len(ks) > 1:
            raise ValueError(
                f"stacked batch leaves disagree on the micro-step "
                f"leading axis: {sorted(ks)}")
    sharding = (stacked_batch_sharding if stacked
                else batch_sharding)(mesh, axis)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sharding, x),
            batch)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)
