"""Device-mesh and sharding utilities (SURVEY.md §2 component 18).

The reference scales with NCCL gradient allreduce; the TPU-native
equivalent is a ``jax.sharding.Mesh`` with the batch sharded over a
``data`` axis — XLA inserts the gradient all-reduce over ICI when the
replicated parameters are updated from sharded-batch gradients.
"""

from sketch_rnn_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated_sharding,
    shard_batch,
)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "replicated_sharding",
    "shard_batch",
]
