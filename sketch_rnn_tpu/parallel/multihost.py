"""Multi-host (multi-process) data parallelism over ICI + DCN.

TPU-native equivalent of the reference's NCCL multi-node scaling path
(SURVEY.md §2 component 18, §5 "Distributed communication backend";
reference unreadable — [B] names NCCL allreduce as the mechanism).

The JAX model: one process per host, each owning its local devices;
``jax.distributed.initialize`` wires the cluster, ``jax.devices()`` then
returns the GLOBAL device list so the same ``Mesh`` + ``NamedSharding``
code paths scale from 1 chip to a pod — XLA routes the gradient
all-reduce over ICI within a slice and DCN across slices. Host-side, each
process feeds only its shard of the global batch
(``jax.make_array_from_process_local_data`` assembles the global array),
and the data loader stripes examples by ``host_id`` (see
``data.loader.load_dataset``).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from sketch_rnn_tpu.config import HParams


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host cluster; no-op for single-process runs.

    With no arguments, relies on the standard cluster auto-detection
    (TPU pod metadata / ``JAX_COORDINATOR_ADDRESS`` etc.). Call once,
    before any other JAX API touches devices.
    """
    if num_processes is None and coordinator_address is None \
            and "JAX_COORDINATOR_ADDRESS" not in os.environ \
            and os.environ.get("SKETCH_RNN_TPU_MULTIHOST") != "1":
        return  # single-process
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_primary() -> bool:
    """True on the process that owns logging/checkpoint writes."""
    return jax.process_index() == 0


def topology() -> dict:
    """This process's fleet coordinate (ISSUE 8): what the telemetry
    core is stamped with (per-host shard naming), what RUN.json and
    bench rows record, and what makes any multi-host artifact
    joinable back to the process that produced it."""
    return {
        "process_index": jax.process_index(),
        "host_count": jax.process_count(),
        "device_count": jax.device_count(),
        "local_device_count": jax.local_device_count(),
    }


def local_batch_hps(hps: HParams) -> HParams:
    """Per-host loader hparams: each host assembles ``1/num_hosts`` of the
    global batch (``hps.batch_size`` stays the GLOBAL batch everywhere
    else — schedules, throughput accounting, the jitted step)."""
    n = jax.process_count()
    if hps.batch_size % n != 0:
        raise ValueError(f"global batch {hps.batch_size} not divisible by "
                         f"{n} hosts")
    return hps.replace(batch_size=hps.batch_size // n)
