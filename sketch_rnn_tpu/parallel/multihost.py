"""Multi-host (multi-process) data parallelism over ICI + DCN.

TPU-native equivalent of the reference's NCCL multi-node scaling path
(SURVEY.md §2 component 18, §5 "Distributed communication backend";
reference unreadable — [B] names NCCL allreduce as the mechanism).

The JAX model: one process per host, each owning its local devices;
``jax.distributed.initialize`` wires the cluster, ``jax.devices()`` then
returns the GLOBAL device list so the same ``Mesh`` + ``NamedSharding``
code paths scale from 1 chip to a pod — XLA routes the gradient
all-reduce over ICI within a slice and DCN across slices. Host-side, each
process feeds only its shard of the global batch
(``jax.make_array_from_process_local_data`` assembles the global array),
and the data loader stripes examples by ``host_id`` (see
``data.loader.load_dataset``).

Host-failure detection (ISSUE 14): the elastic runtime's DCN-side
primitives live here — a :class:`HostHeartbeat` daemon thread
(``host-heartbeat-hNN``, registry-drained by the test guard) that
keeps a liveness file fresh in a shared rendezvous directory, and a
:class:`FleetRendezvous` step barrier around the dispatch loop. A host
that stops arriving at the barrier while its heartbeat goes stale is
declared DEAD: the barrier raises :class:`HostDeathDetected` carrying
the dead/surviving sets, and the fleet-restart coordinator
(train/elastic.py) turns that into a consistent checkpoint + relaunch
at the surviving topology. A missing-but-fresh host is merely SLOW and
is waited for (up to the hard barrier timeout), so transient stalls
never trigger a restart. The barrier body is also the
``dcn.collective`` fault site — a chaos plan can fail the collective
itself (utils/faults.py), and the whole layer is filesystem-based so
two real subprocesses exercise it with no accelerator tunnel (the
``_multihost_worker.py`` light-mode discipline).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

import jax

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.utils.faults import fault_point


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host cluster; no-op for single-process runs.

    With no arguments, relies on the standard cluster auto-detection
    (TPU pod metadata / ``JAX_COORDINATOR_ADDRESS`` etc.). Call once,
    before any other JAX API touches devices.
    """
    if num_processes is None and coordinator_address is None \
            and "JAX_COORDINATOR_ADDRESS" not in os.environ \
            and os.environ.get("SKETCH_RNN_TPU_MULTIHOST") != "1":
        return  # single-process
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_primary() -> bool:
    """True on the process that owns logging/checkpoint writes."""
    return jax.process_index() == 0


def topology() -> dict:
    """This process's fleet coordinate (ISSUE 8): what the telemetry
    core is stamped with (per-host shard naming), what RUN.json and
    bench rows record, and what makes any multi-host artifact
    joinable back to the process that produced it."""
    return {
        "process_index": jax.process_index(),
        "host_count": jax.process_count(),
        "device_count": jax.device_count(),
        "local_device_count": jax.local_device_count(),
    }


def local_batch_hps(hps: HParams, num_hosts: Optional[int] = None
                    ) -> HParams:
    """Per-host loader hparams: each host assembles ``1/num_hosts`` of the
    global batch (``hps.batch_size`` stays the GLOBAL batch everywhere
    else — schedules, throughput accounting, the jitted step).
    ``num_hosts`` defaults to the jax cluster size; the light-mode
    elastic runtime (no ``jax.distributed``) passes its own fleet
    size."""
    n = jax.process_count() if num_hosts is None else int(num_hosts)
    if hps.batch_size % n != 0:
        raise ValueError(f"global batch {hps.batch_size} not divisible by "
                         f"{n} hosts")
    return hps.replace(batch_size=hps.batch_size // n)


# -- host-failure detection (ISSUE 14) --------------------------------------

# liveness thresholds: a host is SUSPECT once its heartbeat file is
# stale_s old (several missed beats, not one scheduling hiccup), and the
# barrier gives up entirely at timeout_s (a collective failure, loud)
HEARTBEAT_INTERVAL_S = 0.25
HEARTBEAT_STALE_S = 2.5
BARRIER_TIMEOUT_S = 120.0

_HB_LOCK = threading.Lock()
_HEARTBEATS: List["HostHeartbeat"] = []


class HostDeathDetected(RuntimeError):
    """Raised by :meth:`FleetRendezvous.barrier` when one or more peers
    stopped arriving AND let their heartbeats go stale. Carries the
    evidence the restart coordinator needs: ``dead`` / ``survivors``
    (original host ids), the barrier ``step``, and whether THIS host is
    the surviving fleet's new primary (``new_primary`` — min survivor
    id; the one that commits the consistent checkpoint)."""

    def __init__(self, dead: List[int], survivors: List[int], step: int,
                 host_id: int):
        self.dead = sorted(dead)
        self.survivors = sorted(survivors)
        self.step = int(step)
        self.host_id = int(host_id)
        self.new_primary = bool(self.survivors
                                and self.survivors[0] == host_id)
        super().__init__(
            f"host death detected at step {step}: dead={self.dead}, "
            f"survivors={self.survivors}")


def _atomic_json(path: str, doc: dict) -> None:
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # absent, or torn mid-replace on exotic filesystems


def heartbeat_path(rendezvous_dir: str, host_id: int) -> str:
    return os.path.join(rendezvous_dir, f"heartbeat_h{host_id:02d}.json")


class HostHeartbeat:
    """Daemon thread keeping ``heartbeat_hNN.json`` fresh: ``{host,
    count, time}`` rewritten atomically every ``interval_s``. A hard
    kill (``os._exit``, preemption) stops the rewrites instantly — the
    staleness every peer's barrier then observes. Registered process-
    wide so the conftest guard can prove no ``host-heartbeat-*`` thread
    outlives a test (:func:`stop_all_heartbeats`)."""

    def __init__(self, rendezvous_dir: str, host_id: int,
                 interval_s: float = HEARTBEAT_INTERVAL_S):
        os.makedirs(rendezvous_dir, exist_ok=True)
        self.path = heartbeat_path(rendezvous_dir, host_id)
        self.host_id = int(host_id)
        self.interval_s = float(interval_s)
        self._count = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"host-heartbeat-h{host_id:02d}",
            daemon=True)

    def start(self) -> "HostHeartbeat":
        self._beat()  # liveness visible BEFORE the first barrier entry
        with _HB_LOCK:
            _HEARTBEATS.append(self)
        self._thread.start()
        return self

    def _beat(self) -> None:
        self._count += 1
        _atomic_json(self.path, {"host": self.host_id,
                                 "count": self._count,
                                 "time": time.time()})

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._beat()
            except OSError:
                # a torn rendezvous dir must not kill the thread: the
                # peers will see staleness and treat this host as dead,
                # which is the honest outcome
                pass

    def stop(self, remove: bool = False) -> None:
        """Stop beating; ``remove=True`` additionally deletes the
        liveness file — ONLY for a host that finished its work
        cleanly. A crashing host must leave its (frozen) file behind:
        that frozen heartbeat is exactly what peers' barriers detect
        as death, while an absent file reads as "not booted yet" and
        is waited for. So: completed -> removed; crashed (raise or
        kill) -> frozen file -> detected; a leftover frozen file in a
        reused rendezvous dir is itself evidence of an unclean
        death."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        with _HB_LOCK:
            if self in _HEARTBEATS:
                _HEARTBEATS.remove(self)
        if remove:
            try:
                os.remove(self.path)
            except OSError:
                pass

    def __repr__(self) -> str:
        return (f"HostHeartbeat(h{self.host_id:02d}, "
                f"alive={self._thread.is_alive()})")


def stop_all_heartbeats() -> tuple:
    """Stop every live heartbeat; returns their reprs (the conftest
    guard asserts this is empty — a non-empty return names the
    leaker)."""
    with _HB_LOCK:
        leaked = tuple(_HEARTBEATS)
    names = tuple(repr(h) for h in leaked)
    for h in leaked:
        h.stop()
    return names


class FleetRendezvous:
    """Filesystem step barrier over a fixed host set (one topology
    generation). ``barrier(name)`` publishes this host's arrival file
    (+ optional JSON payload), then polls for every peer's:

    - all present -> returns ``{host_id: payload}``;
    - a peer missing whose heartbeat file exists but has NOT ADVANCED
      for ``stale_s`` of observed waiting -> declared dead,
      :class:`HostDeathDetected` raises (the elastic recovery entry
      point). Advance-based, never age-based: a leftover file from a
      crashed previous incarnation is (correctly) frozen -> dead,
      while clock skew or a busy-but-beating peer can never false-kill;
    - a peer missing with NO heartbeat file -> not booted yet (clean
      stops delete the file): waited for toward ``timeout_s``;
    - a peer missing but heartbeat-advancing -> merely slow; waited;
    - ``timeout_s`` exceeded -> RuntimeError naming the stragglers (a
      collective failure / launch failure, not a detected death — loud
      by design).

    Arrival files are namespaced by generation so a relaunched fleet
    can never match a previous topology's barriers, and each host
    prunes its own previous arrival file once the next barrier
    completes (a 100k-step run must not leave 100k files per host).
    The publish body is the ``dcn.collective`` fault site."""

    def __init__(self, rendezvous_dir: str, host_id: int,
                 hosts: List[int], gen: int = 0,
                 stale_s: float = HEARTBEAT_STALE_S,
                 timeout_s: float = BARRIER_TIMEOUT_S,
                 poll_s: float = 0.02):
        self.dir = rendezvous_dir
        self.host_id = int(host_id)
        self.hosts = sorted(int(h) for h in hosts)
        if self.host_id not in self.hosts:
            raise ValueError(f"host {host_id} not in fleet {self.hosts}")
        self.gen = int(gen)
        self.stale_s = float(stale_s)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        self._prev_arrival: Optional[str] = None
        os.makedirs(rendezvous_dir, exist_ok=True)

    def _arrival(self, name: str, host: int) -> str:
        return os.path.join(self.dir,
                            f"bar_g{self.gen:03d}_{name}_h{host:02d}.json")

    def _hb_time(self, host: int) -> Optional[float]:
        hb = _read_json(heartbeat_path(self.dir, host))
        return None if hb is None else float(hb.get("time", 0.0))

    def barrier(self, name: str, step: int = 0,
                payload: Optional[dict] = None) -> Dict[int, dict]:
        # the collective's failure site: a chaos plan can fail the
        # exchange itself (kind=raise surfaces as a crashed host to the
        # peers; kind=exit IS a host death)
        fault_point("dcn.collective")
        _atomic_json(self._arrival(name, self.host_id),
                     {"host": self.host_id, "step": int(step),
                      **(payload or {})})
        t0 = time.monotonic()
        # liveness = the heartbeat ADVANCING while we wait, never its
        # absolute age: a stale file left by a crashed previous
        # incarnation in a reused rendezvous dir, or clock skew, must
        # not read as an instant death. A peer is dead only once its
        # heartbeat file exists but has not moved for stale_s of
        # OBSERVED waiting; a peer with NO heartbeat file has simply
        # not booted yet (clean stops delete the file) and is waited
        # for toward the hard timeout, which names it loudly.
        peers = [h for h in self.hosts if h != self.host_id]
        hb_seen = {h: self._hb_time(h) for h in peers}
        last_adv = {h: t0 for h in peers}
        while True:
            missing = [h for h in self.hosts
                       if not os.path.exists(self._arrival(name, h))]
            if not missing:
                out = {}
                for h in self.hosts:
                    doc = self._read_arrival(name, h)
                    out[h] = doc
                # prune MY OWN previous arrival file: every peer has
                # entered THIS barrier, so all of them exited (and read
                # the payloads of) the previous one — the file can
                # never be needed again, and a long run must not
                # accumulate one file per host per step
                if self._prev_arrival is not None:
                    try:
                        os.remove(self._prev_arrival)
                    except OSError:
                        pass
                self._prev_arrival = self._arrival(name, self.host_id)
                return out
            now = time.monotonic()
            dead = []
            for h in missing:
                t = self._hb_time(h)
                if t is not None and t != hb_seen[h]:
                    hb_seen[h], last_adv[h] = t, now
                elif (t is not None
                        and now - last_adv[h] > self.stale_s):
                    dead.append(h)
            if dead:
                survivors = [h for h in self.hosts if h not in dead]
                raise HostDeathDetected(dead, survivors, step,
                                        self.host_id)
            if now - t0 > self.timeout_s:
                unbooted = [h for h in missing
                            if self._hb_time(h) is None]
                raise RuntimeError(
                    f"fleet barrier {name!r} (gen {self.gen}) timed out "
                    f"after {self.timeout_s:.0f}s waiting for hosts "
                    f"{missing}"
                    + (f" (never heartbeated — never launched? "
                       f"{unbooted})" if unbooted else
                       " whose heartbeats are still fresh — a wedged "
                       "(not dead) peer; raise the timeout or "
                       "investigate the straggler"))
            time.sleep(self.poll_s)

    def _read_arrival(self, name: str, host: int) -> dict:
        # atomic writes make a present file complete; retry a beat to
        # ride out os.replace visibility on network filesystems
        for _ in range(50):
            doc = _read_json(self._arrival(name, host))
            if doc is not None:
                return doc
            time.sleep(self.poll_s)
        raise RuntimeError(f"barrier arrival file for host {host} "
                           f"({name!r}) exists but never became "
                           f"readable")
