"""Profiling helpers (SURVEY.md §5 "Tracing / profiling").

The reference had no in-repo profiler and leaned on TF timeline /
TensorBoard; the TPU-native equivalents are ``jax.profiler`` traces
(viewable in XProf/TensorBoard) plus simple steps/sec / strokes/sec/chip
counters — the BASELINE.json metric.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a ``jax.profiler`` device trace into ``logdir``.

    Wrap a few training steps; open the result with XProf/TensorBoard.
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class SpanTimer:
    """Named wall-clock span accumulator (host-side, nesting-agnostic).

    The serving engine wraps each phase of its loop (``chunk`` dispatch,
    ``admit`` slot writes, ``collect`` output gathering) so a bench run
    can attribute wall time without a device trace. ``summary()``
    returns ``{name: {count, total_s, mean_ms}}``.
    """

    def __init__(self):
        self._spans: dict = {}

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            rec = self._spans.setdefault(name, [0, 0.0])
            rec[0] += 1
            rec[1] += time.perf_counter() - t0

    def summary(self) -> dict:
        return {
            name: {"count": n, "total_s": round(t, 6),
                   "mean_ms": round(1e3 * t / n, 4) if n else 0.0}
            for name, (n, t) in sorted(self._spans.items())
        }


class GoodputLedger(SpanTimer):
    """Per-phase wall-clock ledger for the training loop (ISSUE 3).

    The host loop wraps each phase of its iteration — ``dispatch`` (step
    enqueue, including device backpressure), ``feeder_wait`` (input
    pipeline starvation), ``metrics_drain`` (deferred metric
    conversion), ``ckpt_wait`` (join + snapshot of the async
    checkpointer, or the full sync save), ``eval`` (sweep turnaround) —
    so a run can attribute every second of wall time between device
    goodput and host stalls without a device trace.

    :meth:`window` returns the per-phase seconds accrued SINCE the last
    ``window()`` call (keys ``t_<phase>_s``) for embedding in the
    ``MetricsWriter`` row of each log window; the inherited
    :meth:`~SpanTimer.summary` gives run totals for the end-of-train
    console line.
    """

    def __init__(self, phases: tuple = ()):
        super().__init__()
        # pre-declare phases that first fire late (ckpt_wait, eval): the
        # FIRST metrics row defines the CSV header, so a phase absent
        # from it would be dropped from the CSV forever (the writer's
        # resume-alignment rule); seeding pins every column from row one
        for name in phases:
            self._spans.setdefault(name, [0, 0.0])
        self._window_mark: dict = {}

    def window(self, prefix: str = "t_") -> dict:
        out = {}
        for name, (_, total) in sorted(self._spans.items()):
            prev = self._window_mark.get(name, 0.0)
            out[f"{prefix}{name}_s"] = round(total - prev, 6)
            self._window_mark[name] = total
        return out


class Throughput:
    """Streaming steps/sec and strokes/sec/chip counter.

    ``update(step)`` returns a dict of rates since the previous update (or
    None on the first call / zero elapsed time). ``strokes_per_step`` is
    ``global_batch * padded_seq_len`` — the stroke points processed by one
    training step.
    """

    def __init__(self, strokes_per_step: int,
                 num_chips: Optional[int] = None):
        self.strokes_per_step = strokes_per_step
        self.num_chips = num_chips or jax.device_count()
        self._t: Optional[float] = None
        self._step: int = 0

    def update(self, step: int) -> Optional[dict]:
        now = time.perf_counter()
        if self._t is None or step <= self._step:
            self._t, self._step = now, step
            return None
        dt = now - self._t
        if dt <= 0:
            return None
        steps_s = (step - self._step) / dt
        self._t, self._step = now, step
        return {
            "steps_per_sec": steps_s,
            "strokes_per_sec": steps_s * self.strokes_per_step,
            "strokes_per_sec_per_chip":
                steps_s * self.strokes_per_step / self.num_chips,
        }
