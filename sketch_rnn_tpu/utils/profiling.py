"""Profiling helpers (SURVEY.md §5 "Tracing / profiling").

The reference had no in-repo profiler and leaned on TF timeline /
TensorBoard; the TPU-native equivalents are ``jax.profiler`` traces
(viewable in XProf/TensorBoard) plus simple steps/sec / strokes/sec/chip
counters — the BASELINE.json metric.

Since ISSUE 6 the ledgers here are VIEWS over the unified telemetry
core (``utils/telemetry.py``): each keeps its own aggregation store —
the authoritative source for its ``window()``/``summary()`` metrics-row
contract, bitwise-unchanged whether telemetry is on or off — and
mirrors every measurement (spans for the timers, counters for the
padding ledger) into the process-wide core, where the JSONL /
Chrome-trace exporters and ``scripts/trace_report.py`` see one stream.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional, Sequence

import jax

from sketch_rnn_tpu.utils.telemetry import get_telemetry


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a ``jax.profiler`` device trace into ``logdir``.

    Wrap a few training steps; open the result with XProf/TensorBoard.
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class SpanTimer:
    """Named wall-clock span accumulator (host-side, nesting-agnostic).

    The serving engine wraps each phase of its loop (``chunk`` dispatch,
    ``admit`` slot writes, ``collect`` output gathering) so a bench run
    can attribute wall time without a device trace. ``summary()``
    returns ``{name: {count, total_s, mean_ms}}``.

    Thread-safe (ISSUE 6 satellite): the serve engine's depth-1
    pipelined dispatch lets span closes interleave across threads, and
    the unlocked ``rec[0] += 1`` read-modify-write lost increments.

    A view over the telemetry core (ISSUE 6): every closed span is also
    emitted into the process-wide :mod:`~sketch_rnn_tpu.utils.telemetry`
    core under ``category`` with the SAME ``t1 - t0`` this accumulator
    adds, so an exported trace's per-name totals reconcile with
    ``summary()`` exactly (the local store stays authoritative for the
    ``window()``/``summary()`` row contracts, and keeps working — with
    identical values — when telemetry is off, which is the default).
    """

    def __init__(self, category: str = "host"):
        self._lock = threading.Lock()
        self._spans: dict = {}
        self.category = category

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            with self._lock:
                rec = self._spans.setdefault(name, [0, 0.0])
                rec[0] += 1
                rec[1] += t1 - t0
            tel = get_telemetry()
            if tel.enabled:
                tel.emit_span(name, self.category, t0, t1)

    def summary(self) -> dict:
        with self._lock:
            items = [(name, n, t)
                     for name, (n, t) in sorted(self._spans.items())]
        return {
            name: {"count": n, "total_s": round(t, 6),
                   "mean_ms": round(1e3 * t / n, 4) if n else 0.0}
            for name, n, t in items
        }


class GoodputLedger(SpanTimer):
    """Per-phase wall-clock ledger for the training loop (ISSUE 3).

    The host loop wraps each phase of its iteration — ``dispatch`` (step
    enqueue, including device backpressure), ``feeder_wait`` (input
    pipeline starvation), ``metrics_drain`` (deferred metric
    conversion), ``ckpt_wait`` (join + snapshot of the async
    checkpointer, or the full sync save), ``eval`` (sweep turnaround) —
    so a run can attribute every second of wall time between device
    goodput and host stalls without a device trace.

    :meth:`window` returns the per-phase seconds accrued SINCE the last
    ``window()`` call (keys ``t_<phase>_s``) for embedding in the
    ``MetricsWriter`` row of each log window; the inherited
    :meth:`~SpanTimer.summary` gives run totals for the end-of-train
    console line.
    """

    def __init__(self, phases: tuple = ()):
        super().__init__(category="train")
        # pre-declare phases that first fire late (ckpt_wait, eval): the
        # FIRST metrics row defines the CSV header, so a phase absent
        # from it would be dropped from the CSV forever (the writer's
        # resume-alignment rule); seeding pins every column from row one
        for name in phases:
            self._spans.setdefault(name, [0, 0.0])
        self._window_mark: dict = {}

    def window(self, prefix: str = "t_") -> dict:
        out = {}
        with self._lock:
            for name, (_, total) in sorted(self._spans.items()):
                prev = self._window_mark.get(name, 0.0)
                out[f"{prefix}{name}_s"] = round(total - prev, 6)
                self._window_mark[name] = total
        return out


class PaddingLedger:
    """Padded-timestep accounting for (bucketed) batch assembly (ISSUE 4).

    ``DataLoader._assemble`` records every assembled batch — the pad
    length ``tb`` it was padded to, its row count and its total TRUE
    timesteps — so each training metrics row can carry the padding-waste
    fraction and the per-bucket dispatch counts, making the bucketed
    runtime's win (or the fixed-T baseline's waste) observable without a
    device sync: everything here is host-side numpy bookkeeping at
    assembly time. Batches are recorded when ASSEMBLED, which leads
    consumption by at most ``prefetch_depth`` batches — window
    attribution may be off by that lead, totals are exact.

    Thread-safe (the prefetch producer thread assembles concurrently
    with the loop reading windows). ``edges`` pre-declares the
    ``bucket_T<edge>_n`` columns so the FIRST metrics row already
    carries every column (the CSV-header stability rule, see
    :class:`GoodputLedger`).

    Run-length / dispatch-amortization accounting (ISSUE 5): the
    bucket-run scheduler additionally records plan-level run structure
    (:meth:`note_epoch_plan` — how many maximal same-geometry runs the
    epoch plan holds) and realized dispatch amortization
    (:meth:`record_dispatch` — how many micro-steps rode how many
    actual dispatches, called by the training loop / bench loop), so
    every metrics row can show how much host-loop launch cost the
    stacked K-step path removed.

    :meth:`window` returns, since the last ``window()`` call:

    - ``padded_frac`` — fraction of dispatched timesteps that were
      padding (``1 - true/dispatched``; 0.0 when nothing was assembled),
    - ``bucket_T<edge>_n`` — batches assembled per bucket edge,
    - ``runs_per_epoch`` / ``mean_run_len`` — the most recently planned
      epoch's geometry-run count and mean batches per run (0 when no
      bucket plan exists, e.g. fixed-T runs),
    - ``dispatches_saved`` — micro-steps minus dispatches recorded in
      the window (0 under per-batch dispatch).
    """

    def __init__(self, edges: Sequence[int] = ()):
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = {int(e): 0 for e in edges}
        self._dispatched = 0   # timesteps shipped (rows * tb)
        self._true = 0         # timesteps inside true sequence lengths
        self._micro = 0        # optimizer micro-steps dispatched
        self._calls = 0        # host->device dispatches carrying them
        self._epoch_runs = 0   # geometry runs in the last planned epoch
        self._epoch_batches = 0
        self._mark = (0, 0, {}, 0, 0)

    def record(self, tb: int, rows: int, true_steps: int) -> None:
        with self._lock:
            self._counts[int(tb)] = self._counts.get(int(tb), 0) + 1
            self._dispatched += int(rows) * int(tb)
            self._true += int(true_steps)
        # telemetry view (ISSUE 6): the same increments route through
        # the process core as counters (cat "data"), so an exported
        # trace carries the padding-waste accounting; the local ints
        # stay authoritative for window()/summary() and are untouched
        # when telemetry is off (the default)
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("dispatched_timesteps", int(rows) * int(tb),
                        cat="data")
            tel.counter("true_timesteps", int(true_steps), cat="data")
            tel.counter(f"bucket_T{int(tb)}_n", 1, cat="data")

    def record_dispatch(self, micro_steps: int, dispatches: int) -> None:
        """One scheduler decision: ``micro_steps`` optimizer steps rode
        ``dispatches`` jitted calls (a full K-stack is ``(K, 1)``, a
        run-remainder replay of r micro-batches is ``(r, r)``)."""
        with self._lock:
            self._micro += int(micro_steps)
            self._calls += int(dispatches)
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("micro_steps", int(micro_steps), cat="data")
            tel.counter("dispatches", int(dispatches), cat="data")

    def note_epoch_plan(self, n_runs: int, n_batches: int) -> None:
        """Record the run structure of a freshly planned bucket epoch
        (``n_runs`` maximal same-geometry runs over ``n_batches``)."""
        with self._lock:
            self._epoch_runs = int(n_runs)
            self._epoch_batches = int(n_batches)
        tel = get_telemetry()
        if tel.enabled:
            tel.gauge("runs_per_epoch", int(n_runs), cat="data")

    @staticmethod
    def _frac(dispatched: int, true: int) -> float:
        return 1.0 - true / dispatched if dispatched else 0.0

    @staticmethod
    def _run_cols(runs: int, batches: int) -> Dict[str, float]:
        return {"runs_per_epoch": runs,
                "mean_run_len": round(batches / runs, 3) if runs else 0.0}

    def window(self) -> Dict[str, float]:
        with self._lock:
            pd, pt, pc, pm, pk = self._mark
            out = {"padded_frac": round(
                self._frac(self._dispatched - pd, self._true - pt), 6)}
            for e in sorted(self._counts):
                out[f"bucket_T{e}_n"] = self._counts[e] - pc.get(e, 0)
            out.update(self._run_cols(self._epoch_runs,
                                      self._epoch_batches))
            out["dispatches_saved"] = ((self._micro - pm)
                                       - (self._calls - pk))
            self._mark = (self._dispatched, self._true, dict(self._counts),
                          self._micro, self._calls)
        return out

    def summary(self) -> Dict[str, float]:
        with self._lock:
            out = {"padded_frac": round(
                self._frac(self._dispatched, self._true), 6),
                "dispatched_timesteps": self._dispatched,
                "true_timesteps": self._true}
            for e in sorted(self._counts):
                out[f"bucket_T{e}_n"] = self._counts[e]
            out.update(self._run_cols(self._epoch_runs,
                                      self._epoch_batches))
            out["micro_steps"] = self._micro
            out["dispatches"] = self._calls
            out["dispatches_saved"] = self._micro - self._calls
        return out


class Throughput:
    """Streaming steps/sec and strokes/sec/chip counter.

    ``update(step)`` returns a dict of rates since the previous update (or
    None on the first call / zero elapsed time). ``strokes_per_step`` is
    ``global_batch * padded_seq_len`` — the stroke points processed by one
    training step.
    """

    def __init__(self, strokes_per_step: int,
                 num_chips: Optional[int] = None):
        self.strokes_per_step = strokes_per_step
        self.num_chips = num_chips or jax.device_count()
        self._t: Optional[float] = None
        self._step: int = 0

    def update(self, step: int) -> Optional[dict]:
        now = time.perf_counter()
        if self._t is None or step <= self._step:
            self._t, self._step = now, step
            return None
        dt = now - self._t
        if dt <= 0:
            return None
        steps_s = (step - self._step) / dt
        self._t, self._step = now, step
        return {
            "steps_per_sec": steps_s,
            "strokes_per_sec": steps_s * self.strokes_per_step,
            "strokes_per_sec_per_chip":
                steps_s * self.strokes_per_step / self.num_chips,
        }
