"""Auxiliary subsystems: profiling, NaN guards (SURVEY.md §5)."""

from sketch_rnn_tpu.utils.profiling import (
    GoodputLedger,
    SpanTimer,
    Throughput,
    trace,
)
from sketch_rnn_tpu.utils.debug import check_finite, find_nonfinite

__all__ = ["trace", "SpanTimer", "GoodputLedger", "Throughput",
           "check_finite", "find_nonfinite"]
