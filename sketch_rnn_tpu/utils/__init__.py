"""Auxiliary subsystems: profiling, telemetry, NaN guards (SURVEY.md §5)."""

from sketch_rnn_tpu.utils.profiling import (
    GoodputLedger,
    SpanTimer,
    Throughput,
    trace,
)
from sketch_rnn_tpu.utils.telemetry import (
    Histogram,
    Telemetry,
    configure,
    disable,
    get_telemetry,
)
from sketch_rnn_tpu.utils.debug import check_finite, find_nonfinite

__all__ = ["trace", "SpanTimer", "GoodputLedger", "Throughput",
           "Telemetry", "Histogram", "get_telemetry", "configure",
           "disable", "check_finite", "find_nonfinite"]
