"""Run identity & manifests: one join key across every artifact.

ISSUE 8 tentpole piece 3. The repo grew observability artifacts faster
than it grew ways to correlate them: a training run leaves metrics
CSV/JSONL, telemetry shards, maybe an incident.json; a serve-bench
leaves a trace, a metrics.prom scrape and a report line; bench runs
append history rows — and NOTHING ties them together, so "which trace
explains this bench regression" is archaeology. This module gives
every invocation:

- **run_id** — one process-wide id (``get_run_id``), minted lazily per
  process or inherited from ``SKETCH_RNN_RUN_ID`` (how a multi-host
  launcher gives every worker the SAME id, and how a driver script can
  stamp a whole experiment). It rides in telemetry meta lines, bench
  history rows and the manifest.
- **config_hash** — a short stable hash of the full HParams JSON, so
  two runs are provably the-same-config without diffing 40 fields.
- **host topology** — ``(process_index, host_count, device counts,
  device kind)``, the fleet coordinate that makes shards and history
  rows interpretable.
- **RUN.json** (``write_manifest``) — the artifact index: which
  metrics files, trace shards, prom scrape, incidents and bench rows
  belong to this run_id. Written atomically (tmp + rename) so a
  crashing run never leaves a torn manifest; re-writing merges the
  artifact index, so train can register its metrics early and its
  trace shards at exit.

No jax / numpy imports at module scope — the telemetry core resolves
run ids from here, and telemetry-shard subprocesses must stay light.
Manifests are strictly opt-in at the call sites (a traced or scraped
run): the bitwise-invisibility pin — telemetry off writes NO files —
extends to RUN.json.
"""

from __future__ import annotations

import binascii
import hashlib
import json
import os
import threading
import time
from typing import Dict, Optional

RUN_MANIFEST = "RUN.json"
RUN_ID_ENV = "SKETCH_RNN_RUN_ID"

_run_id: Optional[str] = None
_wall_time: Optional[float] = None
_mint_lock = threading.Lock()


def run_wall_time() -> float:
    """The process's ONE wall-clock stamp (minted at first use) — the
    run-manifest clock every history row and manifest of an invocation
    shares. ISSUE 14 satellite: bench/resilience cells used to stamp a
    fresh ``time.time()`` per row, so one run's committed rows carried
    N distinct timestamps and every re-run diffed on all of them;
    stamping the run's single clock keeps committed history rows
    diffing cleanly (one changed value per run) and makes ``wall_time``
    a JOIN key to the run's RUN.json ``created_unix``. Lock-guarded:
    concurrent first calls (in-process multi-host threads) must mint
    ONE stamp, or the join-key invariant breaks on its first use."""
    global _wall_time
    with _mint_lock:
        if _wall_time is None:
            _wall_time = time.time()
        return _wall_time


def set_run_wall_time(t: Optional[float]) -> None:
    """Pin (or with None, reset) the process wall-time stamp — tests."""
    global _wall_time
    _wall_time = t


def get_run_id() -> str:
    """This process's run id (minted once, stable for the process).

    ``SKETCH_RNN_RUN_ID`` in the environment wins — that is how every
    host of a multi-controller launch (and every subprocess a driver
    spawns) shares ONE id so their shards, rows and manifests join.
    Otherwise: ``YYYYmmdd-HHMMSS-<6 hex>`` — sortable, collision-safe
    across concurrent processes via the random suffix.
    """
    global _run_id
    if _run_id is None:
        env = os.environ.get(RUN_ID_ENV)
        if env:
            _run_id = env
        else:
            _run_id = (time.strftime("%Y%m%d-%H%M%S")
                       + "-"
                       + binascii.hexlify(os.urandom(3)).decode())
    return _run_id


def set_run_id(run_id: Optional[str]) -> None:
    """Pin (or with None, reset) the process run id — tests, and
    drivers that mint the id themselves before spawning workers."""
    global _run_id
    _run_id = run_id


def config_hash(hps) -> Optional[str]:
    """12-hex stable hash of the FULL HParams JSON (field order is
    dataclass-declaration order, so equal configs hash equal); None
    for callers without hparams (e.g. a bare trace-merge)."""
    if hps is None:
        return None
    text = hps.to_json() if hasattr(hps, "to_json") else json.dumps(
        hps, sort_keys=True)
    return hashlib.sha256(text.encode()).hexdigest()[:12]


def host_topology() -> Dict[str, object]:
    """The fleet coordinate of this process: ONE source of truth —
    :func:`parallel.multihost.topology` (what the telemetry core and
    shard names are stamped with) plus the device kind — degraded to a
    single-host/no-device stamp when jax is unusable, so manifest
    writing can never be the thing that breaks a run."""
    try:
        import jax

        from sketch_rnn_tpu.parallel.multihost import topology

        return {**topology(), "device_kind": jax.devices()[0].device_kind}
    except Exception:  # noqa: BLE001
        return {"process_index": 0, "host_count": 1,
                "device_count": 0, "local_device_count": 0,
                "device_kind": None}


def manifest_path(out_dir: str) -> str:
    return os.path.join(out_dir, RUN_MANIFEST)


def write_manifest(out_dir: str, kind: str,
                   artifacts: Optional[Dict[str, object]] = None,
                   hps=None, run_id: Optional[str] = None,
                   extra: Optional[Dict[str, object]] = None) -> str:
    """Write (or merge into) ``<out_dir>/RUN.json``; returns its path.

    ``artifacts`` maps artifact names to paths (or lists of paths) —
    the index that lets tooling walk from a run_id to every file the
    run produced. A manifest already present for the SAME run_id is
    merged (artifact keys update, extras update, first-created wins on
    identity fields), so multiple call sites of one run compose; a
    DIFFERENT run_id's manifest is replaced (the directory was reused
    — the stale index must not claim the new run's files). Atomic via
    tmp + ``os.replace`` so readers never see a torn manifest.
    """
    run_id = run_id or get_run_id()
    os.makedirs(out_dir, exist_ok=True)
    path = manifest_path(out_dir)
    doc: Dict[str, object] = {
        "run_id": run_id,
        "kind": kind,
        "created_unix": run_wall_time(),
        "config_hash": config_hash(hps),
        "host": host_topology(),
        "artifacts": {},
    }
    if os.path.exists(path):
        try:
            with open(path) as f:
                prev = json.load(f)
        except (OSError, ValueError):
            prev = None
        if isinstance(prev, dict) and prev.get("run_id") == run_id:
            doc.update({k: prev[k] for k in
                        ("kind", "created_unix", "config_hash", "host")
                        if prev.get(k) is not None})
            if isinstance(prev.get("artifacts"), dict):
                doc["artifacts"] = dict(prev["artifacts"])
            for k, v in prev.items():
                if k not in doc:
                    doc[k] = v
    if artifacts:
        doc["artifacts"].update(artifacts)
    if extra:
        doc.update(extra)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def read_manifest(out_dir: str) -> Optional[Dict]:
    """Load ``<out_dir>/RUN.json`` (None when absent/unreadable)."""
    try:
        with open(manifest_path(out_dir)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
