"""Unified telemetry runtime: spans, counters, streaming histograms.

ISSUE 6's observability substrate. The runtime grew four hand-rolled
timing stores (``SpanTimer``/``GoodputLedger``/``PaddingLedger`` in
utils/profiling.py, the serve engine's end-of-run latency aggregate) and
no exporter a human can open — the reference leaned on TF's timeline /
TensorBoard tracing for exactly this (TensorFlow system paper,
PAPERS.md). This module is the ONE telemetry contract everything emits
into:

- **Spans** — named, categorized wall-clock intervals (monotonic
  ``perf_counter`` start/end, optional attribute dict, recording thread)
  kept in a bounded ring buffer so a long run cannot grow memory without
  bound; per-(category, name) count/total aggregates are maintained
  independently of the ring, so breakdown totals stay exact even after
  the ring drops old events.
- **Counters** — monotonic totals (``counter``) and sampled gauges
  (``gauge``); each update also lands a ring event, which is what
  renders as a Chrome-trace counter track (e.g. live serve slots over
  time).
- **Streaming histograms** — log-bucket (growth ``2**(1/8)``, <=~4.5%
  relative quantile error) p50/p95/p99 WITHOUT retaining samples, so
  per-request latency distributions stream live at serving rates
  instead of appearing only in a final summary dict.

Two exporters, written into a shared ``trace_dir``:

- ``telemetry.jsonl`` — newline-JSONL event stream (one meta line, then
  span/instant/counter events, then aggregate/histogram summary lines);
  the input of ``scripts/trace_report.py``.
- ``trace.json`` — Chrome-trace ``traceEvents`` JSON; open in
  ``chrome://tracing`` or Perfetto (https://ui.perfetto.dev). Threads
  get named tracks (main loop, batch-prefetch, ckpt-writer), spans are
  ``ph: "X"`` complete events, gauges are ``ph: "C"`` counter tracks.

An optional ``jax.profiler`` device trace (:meth:`Telemetry.device_trace`)
captures into ``<trace_dir>/device`` with instant markers dropped into
the host stream at start/stop, so the XProf device timeline can be
aligned against the host spans of the same run.

Process-wide contract: the module holds one global instance, DISABLED by
default — every probe site (ledgers, prefetch producer, async
checkpointer, serve engine) checks ``enabled`` and costs one attribute
read when off, so telemetry off is invisible: no files, no extra
columns, bitwise-identical metrics (the tier-1 pin in
tests/test_telemetry.py). ``configure(trace_dir=...)`` swaps in a fresh
enabled instance (``cli train --trace_dir=...``,
``cli serve-bench --trace_dir=...``).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

TELEMETRY_JSONL = "telemetry.jsonl"
CHROME_TRACE = "trace.json"
# the device-trace alignment marker protocol — ONE copy of the schema,
# shared by Telemetry.device_trace and the training loop's split
# start/stop sites (and whatever trace_report learns to read later)
DEVICE_TRACE_START = "device_trace_start"
DEVICE_TRACE_STOP = "device_trace_stop"
PROFILER_CAT = "profiler"


def json_safe(obj):
    """Strict-JSON-safe copy: non-finite floats become repr strings.

    Python's ``json.dumps`` happily emits the non-standard ``NaN`` /
    ``Infinity`` tokens, which strict consumers (jq, ``JSON.parse``,
    Go) reject — fatal for exactly the artifacts that carry non-finite
    values by design (a NaN-loss incident post-mortem, an infinite
    SLO burn rate in a health payload or bench report). Shared by the
    watchdog, the /healthz payload and the serve-bench report.
    """
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return repr(obj)
    return obj


class Histogram:
    """Streaming log-bucket histogram: quantiles without sample retention.

    Observations land in geometric buckets ``[G**i, G**(i+1))`` with
    ``G = 2**(1/8)``; a quantile is answered at its bucket's geometric
    midpoint (clamped to the observed min/max), giving <=~4.5% relative
    error at any stream length with O(#occupied buckets) memory —
    the HdrHistogram idea, sized for second-scale latencies down to
    microseconds. ``count``/``total``/``min``/``max`` are exact.
    Non-positive observations (clock underflow on a zero-length wait)
    count into a dedicated zero bucket that quantile answers as 0.0.

    Not internally locked — :class:`Telemetry` serializes access.
    """

    GROWTH = 2.0 ** 0.125
    _LOG_G = math.log(GROWTH)

    __slots__ = ("count", "total", "vmin", "vmax", "_buckets", "_zero")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self._buckets: Dict[int, int] = {}
        self._zero = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        if v <= 0.0:
            self._zero += 1
            return
        i = int(math.floor(math.log(v) / self._LOG_G))
        self._buckets[i] = self._buckets.get(i, 0) + 1

    def quantile(self, q: float) -> float:
        """Approximate ``q``-quantile of the stream.

        Well-defined at every stream length (ISSUE 7 satellite): an
        EMPTY histogram answers 0.0 (there is no sample to clamp to —
        callers that need "no data" distinct from zero check ``count``),
        a single-sample histogram answers that sample for every ``q``
        (the bucket midpoint clamps to [vmin, vmax] == [v, v]), and
        ``q`` outside [0, 1] clamps to the range instead of producing a
        negative rank that would walk the buckets nonsensically.
        """
        if self.count == 0:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        rank = q * (self.count - 1)  # np.percentile's 'linear' rank
        cum = self._zero
        if rank < cum:
            return 0.0
        for i in sorted(self._buckets):
            cum += self._buckets[i]
            if rank < cum:
                mid = self.GROWTH ** (i + 0.5)
                return min(max(mid, self.vmin), self.vmax)
        return self.vmax

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative ``(upper_edge, count_at_or_below)`` pairs — the
        Prometheus histogram exposition shape (``le=`` buckets).

        The zero bucket exports with edge 0.0; geometric buckets export
        their exclusive upper edge ``G**(i+1)``. Empty histograms return
        ``[]`` (the renderer still emits ``+Inf``/sum/count lines, so an
        unseen series scrapes as a valid zero histogram rather than
        erroring)."""
        out: List[Tuple[float, int]] = []
        cum = 0
        if self._zero:
            cum = self._zero
            out.append((0.0, cum))
        for i in sorted(self._buckets):
            cum += self._buckets[i]
            out.append((self.GROWTH ** (i + 1), cum))
        return out

    def summary(self) -> Dict[str, float]:
        if self.count == 0:
            return {"count": 0, "mean": 0.0, "min": 0.0, "max": 0.0,
                    "p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {"count": self.count,
                "mean": self.total / self.count,
                "min": self.vmin, "max": self.vmax,
                "p50": self.quantile(0.50),
                "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}


class _SpanCtx:
    """Context manager returned by :meth:`Telemetry.span`; times the
    block with ``perf_counter`` and records on exit (exceptions
    included — the span still closes, Chrome traces stay well-formed)."""

    __slots__ = ("_tel", "_name", "_cat", "_args", "_t0")

    def __init__(self, tel: "Telemetry", name: str, cat: str,
                 args: Optional[dict]):
        self._tel = tel
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc_info) -> None:
        self._tel.emit_span(self._name, self._cat, self._t0,
                            time.perf_counter(), self._args)


class _NullCtx:
    """Reusable no-op context: what a disabled core hands out, so the
    off path allocates nothing and times nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc_info):
        return False


_NULL_CTX = _NullCtx()


class Telemetry:
    """Thread-safe process-wide telemetry core (see module docstring).

    All mutation goes through one lock; every probe first checks
    :attr:`enabled` so a disabled core costs one attribute read per
    probe site. Timestamps are ``time.perf_counter()`` seconds relative
    to the instance's construction (``origin_perf``); ``origin_unix``
    (wall clock at construction) rides in the export meta so events can
    be correlated with log lines.
    """

    def __init__(self, capacity: int = 1 << 16, enabled: bool = True,
                 trace_dir: Optional[str] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.enabled = enabled
        self.capacity = capacity
        self.trace_dir = trace_dir
        self.dropped = 0
        self.origin_perf = time.perf_counter()
        self.origin_unix = time.time()
        self._lock = threading.Lock()
        self._events: deque = deque()
        # exact per-(cat, name) span aggregates, independent of the ring
        self._agg: Dict[Tuple[str, str], List[float]] = {}
        self._counters: Dict[Tuple[str, str], float] = {}
        self._hists: Dict[Tuple[str, str], Histogram] = {}
        # keys in _counters that hold a gauge's latest SAMPLE rather
        # than a monotonic total — the /metrics renderer must type them
        # differently (Prometheus gauge vs counter)
        self._gauge_keys: set = set()

    # -- recording ---------------------------------------------------------

    def span(self, name: str, cat: str = "host",
             args: Optional[dict] = None):
        """Context manager timing a block as one span (no-op when
        disabled)."""
        if not self.enabled:
            return _NULL_CTX
        return _SpanCtx(self, name, cat, args)

    def emit_span(self, name: str, cat: str, t0: float, t1: float,
                  args: Optional[dict] = None) -> None:
        """Record an already-timed span (``t0``/``t1`` from
        ``perf_counter``) — the path the ledger views use, so THEIR
        accumulation and the core's see the identical ``t1 - t0``."""
        if not self.enabled:
            return
        dur = t1 - t0
        ev = {"type": "span", "name": name, "cat": cat,
              "ts": t0 - self.origin_perf, "dur": dur,
              "tid": threading.current_thread().name}
        if args:
            ev["args"] = args
        with self._lock:
            rec = self._agg.setdefault((cat, name), [0, 0.0])
            rec[0] += 1
            rec[1] += dur
            self._append(ev)

    def instant(self, name: str, cat: str = "host",
                args: Optional[dict] = None,
                ts: Optional[float] = None) -> None:
        """Record a zero-duration marker event (e.g. request enqueue)."""
        if not self.enabled:
            return
        t = (time.perf_counter() if ts is None else ts) - self.origin_perf
        ev = {"type": "instant", "name": name, "cat": cat, "ts": t,
              "tid": threading.current_thread().name}
        if args:
            ev["args"] = args
        with self._lock:
            self._append(ev)

    def counter(self, name: str, delta: float = 1.0,
                cat: str = "host") -> None:
        """Increment a monotonic counter; the ring records the new
        total (a Chrome counter track of the running value)."""
        if not self.enabled:
            return
        ts = time.perf_counter() - self.origin_perf
        with self._lock:
            total = self._counters.get((cat, name), 0.0) + delta
            self._counters[(cat, name)] = total
            self._append({"type": "counter", "name": name, "cat": cat,
                          "ts": ts, "value": total})

    def gauge(self, name: str, value: float, cat: str = "host",
              ts: Optional[float] = None) -> None:
        """Sample an instantaneous value (e.g. live serve slots); the
        latest sample is also kept under counters for snapshots."""
        if not self.enabled:
            return
        t = (time.perf_counter() if ts is None else ts) - self.origin_perf
        with self._lock:
            self._counters[(cat, name)] = float(value)
            self._gauge_keys.add((cat, name))
            self._append({"type": "counter", "name": name, "cat": cat,
                          "ts": t, "value": float(value)})

    def observe(self, name: str, value: float, cat: str = "host") -> None:
        """Feed one observation into the named streaming histogram."""
        if not self.enabled:
            return
        with self._lock:
            h = self._hists.get((cat, name))
            if h is None:
                h = self._hists[(cat, name)] = Histogram()
            h.observe(value)

    def _append(self, ev: dict) -> None:
        # caller holds the lock
        if len(self._events) >= self.capacity:
            self._events.popleft()
            self.dropped += 1
        self._events.append(ev)

    # -- reading -----------------------------------------------------------

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def aggregates(self) -> Dict[Tuple[str, str], Tuple[int, float]]:
        """Exact span (count, total_s) per (category, name)."""
        with self._lock:
            return {k: (int(v[0]), float(v[1]))
                    for k, v in self._agg.items()}

    def counters(self) -> Dict[Tuple[str, str], float]:
        with self._lock:
            return dict(self._counters)

    def histogram(self, name: str, cat: str = "host"
                  ) -> Optional[Dict[str, float]]:
        """Live summary of one streaming histogram (None if unseen)."""
        with self._lock:
            h = self._hists.get((cat, name))
            return None if h is None else h.summary()

    def snapshot(self) -> Dict[str, Dict]:
        """One consistent view of every aggregate store, taken under a
        single lock acquisition — what the ``/metrics`` endpoint renders
        and the watchdog embeds in ``incident.json``. Counters and
        gauges come back separated (gauges hold their latest sample,
        not a monotonic total), histograms as ``{summary, buckets}``.
        """
        with self._lock:
            return {
                "aggregates": {k: (int(v[0]), float(v[1]))
                               for k, v in self._agg.items()},
                "counters": {k: v for k, v in self._counters.items()
                             if k not in self._gauge_keys},
                "gauges": {k: v for k, v in self._counters.items()
                           if k in self._gauge_keys},
                "hists": {k: {"summary": h.summary(),
                              "total": h.total,
                              "buckets": h.buckets()}
                          for k, h in self._hists.items()},
                "dropped": self.dropped,
            }

    # -- exporters ---------------------------------------------------------

    def export_jsonl(self, path: str) -> None:
        """Write the newline-JSONL event stream: one meta line, the ring
        events in record order, then ``agg``/``counter_total``/``hist``
        summary lines (exact even when the ring dropped events)."""
        with self._lock:
            events = list(self._events)
            agg = {k: list(v) for k, v in self._agg.items()}
            counters = dict(self._counters)
            hists = {k: h.summary() for k, h in self._hists.items()}
            dropped = self.dropped
        with open(path, "w") as f:
            f.write(json.dumps({
                "type": "meta", "origin_unix": self.origin_unix,
                "pid": os.getpid(), "capacity": self.capacity,
                "dropped": dropped}) + "\n")
            for ev in events:
                f.write(json.dumps(ev) + "\n")
            for (cat, name), (n, total) in sorted(agg.items()):
                f.write(json.dumps({
                    "type": "agg", "cat": cat, "name": name,
                    "count": int(n), "total_s": total}) + "\n")
            for (cat, name), v in sorted(counters.items()):
                f.write(json.dumps({
                    "type": "counter_total", "cat": cat, "name": name,
                    "value": v}) + "\n")
            for (cat, name), s in sorted(hists.items()):
                f.write(json.dumps({
                    "type": "hist", "cat": cat, "name": name, **s}) + "\n")

    def export_chrome_trace(self, path: str) -> None:
        """Write a Chrome-trace ``traceEvents`` JSON (chrome://tracing /
        Perfetto). Spans -> ``X`` complete events, instants -> ``i``,
        counters/gauges -> ``C`` tracks; threads get name metadata."""
        events = self.events()
        pid = os.getpid()
        tids: Dict[str, int] = {}
        out: List[dict] = []

        def tid_of(name: str) -> int:
            if name not in tids:
                tids[name] = len(tids)
                out.append({"ph": "M", "name": "thread_name", "pid": pid,
                            "tid": tids[name],
                            "args": {"name": name}})
            return tids[name]

        for ev in events:
            ts_us = ev["ts"] * 1e6
            if ev["type"] == "span":
                rec = {"ph": "X", "name": ev["name"], "cat": ev["cat"],
                       "pid": pid, "tid": tid_of(ev["tid"]),
                       "ts": ts_us, "dur": ev["dur"] * 1e6}
                if "args" in ev:
                    rec["args"] = ev["args"]
                out.append(rec)
            elif ev["type"] == "instant":
                rec = {"ph": "i", "name": ev["name"], "cat": ev["cat"],
                       "pid": pid, "tid": tid_of(ev["tid"]),
                       "ts": ts_us, "s": "t"}
                if "args" in ev:
                    rec["args"] = ev["args"]
                out.append(rec)
            elif ev["type"] == "counter":
                out.append({"ph": "C", "name": ev["name"],
                            "cat": ev["cat"], "pid": pid, "tid": 0,
                            "ts": ts_us,
                            "args": {ev["name"]: ev["value"]}})
        with open(path, "w") as f:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)

    def export(self, trace_dir: Optional[str] = None) -> Dict[str, str]:
        """Write both exporters into ``trace_dir`` (default: the
        configured one); returns ``{"jsonl": path, "chrome": path}``."""
        d = trace_dir or self.trace_dir
        if not d:
            raise ValueError("no trace_dir configured or given")
        os.makedirs(d, exist_ok=True)
        paths = {"jsonl": os.path.join(d, TELEMETRY_JSONL),
                 "chrome": os.path.join(d, CHROME_TRACE)}
        self.export_jsonl(paths["jsonl"])
        self.export_chrome_trace(paths["chrome"])
        return paths

    # -- device-trace alignment -------------------------------------------

    @contextlib.contextmanager
    def device_trace(self, subdir: str = "device") -> Iterator[None]:
        """Capture a ``jax.profiler`` device trace into
        ``<trace_dir>/<subdir>`` with instant markers in the host stream
        at start/stop, so the XProf timeline aligns with the host spans
        of the same run. No-op when disabled or without a trace_dir."""
        if not (self.enabled and self.trace_dir):
            yield
            return
        import jax

        logdir = os.path.join(self.trace_dir, subdir)
        self.instant(DEVICE_TRACE_START, cat=PROFILER_CAT,
                     args={"logdir": logdir})
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            self.instant(DEVICE_TRACE_STOP, cat=PROFILER_CAT)


# -- the process-wide instance ----------------------------------------------

_global = Telemetry(enabled=False)


def get_telemetry() -> Telemetry:
    """The process-wide core. Disabled (and empty) unless
    :func:`configure` ran; probe sites resolve it at call time so a
    late ``configure`` still catches every subsystem."""
    return _global


def configure(trace_dir: Optional[str] = None,
              capacity: int = 1 << 16) -> Telemetry:
    """Swap in a FRESH enabled core (old events do not leak across
    runs) writing into ``trace_dir``; returns it."""
    global _global
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
    _global = Telemetry(capacity=capacity, enabled=True,
                        trace_dir=trace_dir)
    return _global


def disable() -> None:
    """Restore the disabled default (tests; end of a traced run)."""
    global _global
    _global = Telemetry(enabled=False)
