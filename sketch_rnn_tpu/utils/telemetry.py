"""Unified telemetry runtime: spans, counters, streaming histograms.

ISSUE 6's observability substrate. The runtime grew four hand-rolled
timing stores (``SpanTimer``/``GoodputLedger``/``PaddingLedger`` in
utils/profiling.py, the serve engine's end-of-run latency aggregate) and
no exporter a human can open — the reference leaned on TF's timeline /
TensorBoard tracing for exactly this (TensorFlow system paper,
PAPERS.md). This module is the ONE telemetry contract everything emits
into:

- **Spans** — named, categorized wall-clock intervals (monotonic
  ``perf_counter`` start/end, optional attribute dict, recording thread)
  kept in a bounded ring buffer so a long run cannot grow memory without
  bound; per-(category, name) count/total aggregates are maintained
  independently of the ring, so breakdown totals stay exact even after
  the ring drops old events.
- **Counters** — monotonic totals (``counter``) and sampled gauges
  (``gauge``); each update also lands a ring event, which is what
  renders as a Chrome-trace counter track (e.g. live serve slots over
  time).
- **Streaming histograms** — log-bucket (growth ``2**(1/8)``, <=~4.5%
  relative quantile error) p50/p95/p99 WITHOUT retaining samples, so
  per-request latency distributions stream live at serving rates
  instead of appearing only in a final summary dict.

Two exporters, written into a shared ``trace_dir``:

- ``telemetry.jsonl`` — newline-JSONL event stream (one meta line, then
  span/instant/counter events, then aggregate/histogram summary lines);
  the input of ``scripts/trace_report.py``.
- ``trace.json`` — Chrome-trace ``traceEvents`` JSON; open in
  ``chrome://tracing`` or Perfetto (https://ui.perfetto.dev). Threads
  get named tracks (main loop, batch-prefetch, ckpt-writer), spans are
  ``ph: "X"`` complete events, gauges are ``ph: "C"`` counter tracks.

An optional ``jax.profiler`` device trace (:meth:`Telemetry.device_trace`)
captures into ``<trace_dir>/device`` with instant markers dropped into
the host stream at start/stop, so the XProf device timeline can be
aligned against the host spans of the same run.

Process-wide contract: the module holds one global instance, DISABLED by
default — every probe site (ledgers, prefetch producer, async
checkpointer, serve engine) checks ``enabled`` and costs one attribute
read when off, so telemetry off is invisible: no files, no extra
columns, bitwise-identical metrics (the tier-1 pin in
tests/test_telemetry.py). ``configure(trace_dir=...)`` swaps in a fresh
enabled instance (``cli train --trace_dir=...``,
``cli serve-bench --trace_dir=...``).

Fleet awareness (ISSUE 8): every core is stamped with
``(process_index, host_count, run_id)`` and exports PER-HOST SHARD
files (``telemetry.p0001.jsonl`` under multi-controller — no path
collisions; the bare single-host names are unchanged).
``scripts/trace_merge.py`` merges N shards into one Chrome trace with
per-host track groups plus a global summary that reconciles exactly
with the per-shard summaries — which is why histograms serialize their
raw log buckets (:meth:`Histogram.to_dict`) and support an exact
:meth:`Histogram.merge`. ``run_id`` (utils/runinfo.py) is the join key
between traces, metrics, bench rows and the ``RUN.json`` manifest.

This module deliberately imports neither jax nor numpy at module
scope: telemetry-shard subprocesses (tests/_multihost_worker.py's
light mode) must start in milliseconds. The jax-touching helpers
(:class:`JitCompileProbe`, :class:`MemorySampler`) import lazily.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import re
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

TELEMETRY_JSONL = "telemetry.jsonl"
CHROME_TRACE = "trace.json"

# -- series naming (ISSUE 9) -------------------------------------------------
#
# The serving fleet multiplexes one telemetry core across R replica
# engines and C admission classes; per-replica gauges and per-class
# histograms are DISTINCT series, keyed by name suffix. The naming
# contract lives here (one copy) so the emitters (serve/engine.py,
# serve/fleet.py) and the readers (scripts/trace_report.py, /metrics
# consumers) can never drift: `slots_live_r03` is replica 3's
# occupancy gauge, `latency_s_interactive` is the `interactive`
# class's latency histogram.

_SERIES_SAFE = re.compile(r"[^A-Za-z0-9_]")


def replica_series(name: str, replica: Optional[int] = None) -> str:
    """Per-replica series name: ``slots_live`` -> ``slots_live_r03``.

    ``replica=None`` returns ``name`` unchanged (the single-engine
    series keep their legacy names — committed traces stay readable)."""
    if replica is None:
        return name
    return f"{name}_r{int(replica):02d}"


def replica_of_series(name: str, base: str) -> Optional[int]:
    """Inverse of :func:`replica_series`: the replica index encoded in
    ``name`` (None when ``name`` is not a per-replica series of
    ``base``)."""
    m = re.match(re.escape(base) + r"_r(\d+)$", name)
    return int(m.group(1)) if m else None


def class_series(name: str, cls: Optional[str] = None) -> str:
    """Per-admission-class series name: ``latency_s`` ->
    ``latency_s_interactive`` (class sanitized to Prometheus-legal
    chars; ``None``/empty keeps the aggregate series name)."""
    if not cls:
        return name
    return f"{name}_{_SERIES_SAFE.sub('_', str(cls))}"


def endpoint_series(name: str, endpoint: Optional[str] = None) -> str:
    """Per-serving-endpoint series name (ISSUE 15):
    ``latency_s`` -> ``latency_s_ep_complete``. Rides the
    :func:`class_series` naming contract with an ``ep_`` marker so an
    endpoint can never collide with an admission class of the same
    name; ``None``/empty keeps the aggregate series name. The emitter
    (serve/engine.py, serve/endpoints.py) and every /metrics consumer
    key the per-endpoint request/latency series identically."""
    if not endpoint:
        return name
    return class_series(name, f"ep_{endpoint}")


def tenant_series(name: str, tenant: Optional[str] = None) -> str:
    """Per-tenant series name (ISSUE 19): ``requests_completed`` ->
    ``requests_completed_tn_acme``. Rides the :func:`class_series`
    naming contract with a ``tn_`` marker so a tenant can never collide
    with an admission class or endpoint of the same name; ``None``/empty
    keeps the aggregate series name. The emitter (serve/fleet.py) and
    every /metrics consumer key the per-tenant request/latency/shed
    series identically."""
    if not tenant:
        return name
    return class_series(name, f"tn_{tenant}")


def site_series(name: str, site: Optional[str] = None) -> str:
    """Per-fault-site series name (ISSUE 10): ``faults_injected`` ->
    ``faults_injected_ckpt_commit`` (site dots and other non-Prometheus
    chars sanitized). Same contract as :func:`class_series` — the
    emitter (utils/faults.py) and every /metrics consumer key the
    per-site counters identically."""
    if not site:
        return name
    return f"{name}_{_SERIES_SAFE.sub('_', str(site))}"


def shard_suffix(process_index: int, host_count: int) -> str:
    """Filename suffix isolating one host's export shard.

    Single-host runs keep the bare legacy names (every existing
    consumer and committed trace stays valid); multi-controller runs
    get ``.pNNNN`` so N processes writing one shared ``--trace_dir``
    can never collide (the ISSUE 8 pre-tentpole bugfix)."""
    if host_count <= 1:
        return ""
    return f".p{process_index:04d}"


def shard_jsonl_name(process_index: int, host_count: int) -> str:
    root, ext = os.path.splitext(TELEMETRY_JSONL)
    return f"{root}{shard_suffix(process_index, host_count)}{ext}"


def shard_chrome_name(process_index: int, host_count: int) -> str:
    root, ext = os.path.splitext(CHROME_TRACE)
    return f"{root}{shard_suffix(process_index, host_count)}{ext}"
# the device-trace alignment marker protocol — ONE copy of the schema,
# shared by Telemetry.device_trace and the training loop's split
# start/stop sites (and whatever trace_report learns to read later)
DEVICE_TRACE_START = "device_trace_start"
DEVICE_TRACE_STOP = "device_trace_stop"
PROFILER_CAT = "profiler"


# -- causal trace context (ISSUE 11) -----------------------------------------
#
# Every hop of a request's life can stamp its event with a causal
# coordinate — (trace_id, span_id, parent_id) — so a query tool can
# reconstruct one span TREE per request across threads, replicas and
# hosts, and the Chrome exporter can draw flow arrows between the hops.
# The naming contract lives HERE (one copy), shared by the emitters
# (serve/engine.py, serve/fleet.py) and the reader
# (scripts/trace_query.py): span ids are PURE functions of
# (uid, hop, attempt), so a retried request's tree is reconstructible
# without any shared mutable id allocator — the same no-RNG-stream
# discipline as utils/faults.py.


def span_link(trace_id: str, span_id: str,
              parent_id: Optional[str] = None) -> Dict[str, str]:
    """The propagation helper: the ``trace`` dict an event carries.

    ``parent_id=None`` marks a tree ROOT. Pass the result as the
    ``trace=`` argument of :meth:`Telemetry.emit_span` /
    :meth:`Telemetry.instant`."""
    link = {"id": str(trace_id), "span": str(span_id)}
    if parent_id is not None:
        link["parent"] = str(parent_id)
    return link


REQUEST_TRACE_PREFIX = "req-"


def request_trace_id(uid) -> str:
    """One trace per request uid: the join key of its span tree."""
    return f"{REQUEST_TRACE_PREFIX}{int(uid)}"


def request_span_id(hop: str, uid, attempt: int = 0) -> str:
    """Span id of one hop of request ``uid``'s life. ``attempt``
    distinguishes failover retries (attempt 0 spans keep the bare name,
    so pre-failover traces and healthy runs read identically)."""
    base = f"{hop}-{int(uid)}"
    return base if not attempt else f"{base}-a{int(attempt)}"


def request_parent_id(uid, attempt: int = 0) -> str:
    """The parent a per-attempt hop hangs under: the request ROOT span
    for the first attempt, the attempt's ``retry`` span afterwards —
    which is itself rooted, so a retried request stays ONE tree."""
    if not attempt:
        return request_span_id("request", uid)
    return request_span_id("retry", uid, attempt)


# -- critical-path latency decomposition (ISSUE 11) --------------------------
#
# One segment schema for "why was this request slow", shared by the
# emitter (the serve engine stamps `segments` into every complete
# event), the fleet/engine summaries, scripts/trace_query.py and the
# bench rows — the single latency-decomposition source of truth
# (scripts/profile_breakdown.py's train-step ladder is marked legacy
# and points here for the serving side).

CRITICAL_PATH_SEGMENTS = ("queue_wait_s", "decode_s")

# display labels for the dominant-segment verdicts (p99_dom=queue|decode)
SEGMENT_LABELS = {"queue_wait_s": "queue", "decode_s": "decode"}


def critical_path_segments(queue_wait_s: float, latency_s: float
                           ) -> List[Tuple[str, float]]:
    """Per-request critical-path decomposition whose LEFT-TO-RIGHT
    float sum is BITWISE ``latency_s``.

    ``queue_wait_s`` is the Result's exact queue segment (original
    arrival -> slot admission — failover requeues keep the original
    ``enqueue_ts`` clock base); the decode segment is the REMAINDER of
    the request's latency clock, compensated so ``q + d == latency_s``
    exactly (plain ``latency - queue`` can be an ulp off under IEEE
    rounding, and the reconciliation contract is bitwise, not approx).
    It therefore reconciles with the Result's own ``decode_s`` within
    one ulp rather than matching it bitwise — the sum invariant is the
    one the tree query verifies. The unreachable non-convergent case
    degrades to attributing the whole clock to decode, which still
    sums exactly (``0.0 + x == x`` for ``x >= 0``).
    """
    q, lat = float(queue_wait_s), float(latency_s)
    d = lat - q
    for _ in range(8):
        s = q + d
        if s == lat:
            return [("queue_wait_s", q), ("decode_s", d)]
        d += lat - s
    return [("queue_wait_s", 0.0), ("decode_s", lat)]


def segments_sum(segments) -> float:
    """The decomposition's canonical (left-to-right) float sum — the
    exact-reconciliation side of :func:`critical_path_segments`."""
    total = 0.0
    for _, v in segments:
        total += float(v)
    return total


def tail_attribution(latency_segments, q: float = 0.99) -> Optional[Dict]:
    """Dominant critical-path segment of the latency tail.

    ``latency_segments``: ``[(latency_s, [(segment, seconds), ...])]``
    per completed request. The tail set is every request at or above
    the ``q``-quantile latency (``np.percentile`` linear — the same
    rank math as ``ServeEngine.run()``'s summary, so the threshold IS
    the reported p99); their segments are summed and the largest share
    names the verdict: a queue-dominated tail wants capacity, a
    decode-dominated tail wants a faster engine (the ROADMAP's
    autoscaling signal). Deterministic: ties break in segment order.
    Returns ``{p99_s, tail_n, dom, dom_frac, segments}`` or None when
    there is nothing to attribute.
    """
    rows = [(float(lat), segs) for lat, segs in latency_segments]
    if not rows:
        return None
    import numpy as np  # lazy: telemetry stays import-light

    lats = np.array([lat for lat, _ in rows])
    thresh = float(np.percentile(lats, 100.0 * q))
    totals: Dict[str, float] = {}
    order: List[str] = []
    tail_n = 0
    for lat, segs in rows:
        if lat < thresh:
            continue
        tail_n += 1
        for name, v in segs:
            if name not in totals:
                totals[name] = 0.0
                order.append(name)
            totals[name] += float(v)
    accounted = sum(totals.values())
    dom = max(order, key=lambda nm: totals[nm]) if order else None
    return {
        "p99_s": thresh,
        "tail_n": tail_n,
        "dom": SEGMENT_LABELS.get(dom, dom),
        "dom_frac": (round(totals[dom] / accounted, 4)
                     if dom is not None and accounted > 0 else None),
        "segments": {SEGMENT_LABELS.get(nm, nm): round(v, 6)
                     for nm, v in totals.items()},
    }


def attribute_chunk_steps(chunk_steps: int, n_live: int
                          ) -> List[int]:
    """Deterministic integer split of one chunk's device steps over its
    live slots: every live slot gets ``chunk // n``, the first
    ``chunk % n`` slots (ascending slot order — deterministic in the
    admission schedule) one extra, so the shares sum to ``chunk_steps``
    EXACTLY in integers. Per-class cost built on this is provable
    bitwise on any box — no float division, no wall clock (the
    ROADMAP's scheduling-math constraint)."""
    if n_live < 1:
        raise ValueError(f"n_live must be >= 1, got {n_live}")
    base, extra = divmod(int(chunk_steps), n_live)
    return [base + 1 if i < extra else base for i in range(n_live)]


def chrome_flow_events(items) -> List[dict]:
    """Chrome-trace flow events (``ph`` s/t/f) chaining each trace's
    events in time order, so Perfetto draws arrows across thread (and,
    in a merged fleet trace, host) tracks.

    ``items``: ``[(trace_id, ts_us, pid, tid), ...]`` — one entry per
    traced event, any order. Traces with fewer than two events draw no
    arrow. Shared by the single-host exporter and trace_merge's merged
    writer (one copy of the flow protocol)."""
    by_trace: Dict[str, List[Tuple[float, int, int]]] = {}
    for trace_id, ts_us, pid, tid in items:
        by_trace.setdefault(str(trace_id), []).append(
            (float(ts_us), pid, tid))
    out: List[dict] = []
    for fid, (trace_id, pts) in enumerate(sorted(by_trace.items())):
        if len(pts) < 2:
            continue
        pts.sort()
        for i, (ts_us, pid, tid) in enumerate(pts):
            ph = "s" if i == 0 else ("f" if i == len(pts) - 1 else "t")
            rec = {"ph": ph, "id": fid, "cat": "request",
                   "name": trace_id, "pid": pid, "tid": tid,
                   "ts": ts_us}
            if ph == "f":
                rec["bp"] = "e"  # bind to the enclosing slice
            out.append(rec)
    return out


def stamp_trace_flow(rec: dict, ev: dict, flows: List, pid: int) -> None:
    """Collection side of the flow protocol: surface a traced event's
    causal coordinate in its Chrome record's ``args.trace`` and
    register one flow point for :func:`chrome_flow_events`. Untraced
    events are left alone. Shared by both branches of both Chrome
    writers (the single-host exporter and trace_merge's merged one),
    so a change to how the coordinate is surfaced lands everywhere."""
    if "trace" not in ev:
        return
    rec["args"] = {**rec.get("args", {}), "trace": ev["trace"]}
    flows.append((ev["trace"]["id"], rec["ts"], pid, rec["tid"]))


def json_safe(obj):
    """Strict-JSON-safe copy: non-finite floats become repr strings.

    Python's ``json.dumps`` happily emits the non-standard ``NaN`` /
    ``Infinity`` tokens, which strict consumers (jq, ``JSON.parse``,
    Go) reject — fatal for exactly the artifacts that carry non-finite
    values by design (a NaN-loss incident post-mortem, an infinite
    SLO burn rate in a health payload or bench report). Shared by the
    watchdog, the /healthz payload and the serve-bench report.
    """
    if isinstance(obj, dict):
        return {k: json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_safe(v) for v in obj]
    if isinstance(obj, float) and not math.isfinite(obj):
        return repr(obj)
    return obj


class Histogram:
    """Streaming log-bucket histogram: quantiles without sample retention.

    Observations land in geometric buckets ``[G**i, G**(i+1))`` with
    ``G = 2**(1/8)``; a quantile is answered at its bucket's geometric
    midpoint (clamped to the observed min/max), giving <=~4.5% relative
    error at any stream length with O(#occupied buckets) memory —
    the HdrHistogram idea, sized for second-scale latencies down to
    microseconds. ``count``/``total``/``min``/``max`` are exact.
    Non-positive observations (clock underflow on a zero-length wait)
    count into a dedicated zero bucket that quantile answers as 0.0.

    Not internally locked — :class:`Telemetry` serializes access.
    """

    GROWTH = 2.0 ** 0.125
    _LOG_G = math.log(GROWTH)

    __slots__ = ("count", "total", "vmin", "vmax", "_buckets", "_zero",
                 "growth", "_log_g")

    def __init__(self, growth: Optional[float] = None):
        # growth is an INSTANCE property since ISSUE 8: shard merging
        # is only exact between histograms on the same bucket lattice,
        # so merge() must be able to see (and reject) a mismatch
        self.growth = float(growth) if growth else self.GROWTH
        if self.growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {self.growth}")
        self._log_g = math.log(self.growth)
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = -math.inf
        self._buckets: Dict[int, int] = {}
        self._zero = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        if v <= 0.0:
            self._zero += 1
            return
        i = int(math.floor(math.log(v) / self._log_g))
        self._buckets[i] = self._buckets.get(i, 0) + 1

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold ``other`` into ``self`` EXACTLY (in place; returns self).

        Two histograms on the same log-bucket lattice merge without any
        approximation: per-bucket counts add, ``count``/``total``/
        ``min``/``max`` combine exactly, so a fleet-merged histogram's
        quantiles are precisely what one process observing the union
        stream would report (the trace_merge reconciliation contract,
        ISSUE 8). A growth mismatch is REJECTED — resampling between
        lattices would silently break that exactness.
        """
        if other.growth != self.growth:
            raise ValueError(
                f"cannot merge histograms with different bucket growth "
                f"({self.growth!r} vs {other.growth!r}): log-bucket "
                f"merging is only exact on one lattice")
        self.count += other.count
        self.total += other.total
        if other.vmin < self.vmin:
            self.vmin = other.vmin
        if other.vmax > self.vmax:
            self.vmax = other.vmax
        self._zero += other._zero
        for i, n in other._buckets.items():
            self._buckets[i] = self._buckets.get(i, 0) + n
        return self

    def to_dict(self) -> Dict:
        """Loss-free serialized form (the shard export's ``raw`` field):
        everything :meth:`from_dict` needs to rebuild this histogram
        bit-for-bit, which is what makes cross-host merging exact."""
        return {
            "growth": self.growth,
            "count": self.count,
            "total": self.total,
            "min": None if self.count == 0 else self.vmin,
            "max": None if self.count == 0 else self.vmax,
            "zero": self._zero,
            "buckets": [[i, self._buckets[i]]
                        for i in sorted(self._buckets)],
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "Histogram":
        h = cls(growth=d.get("growth"))
        h.count = int(d["count"])
        h.total = float(d["total"])
        if d.get("min") is not None:
            h.vmin = float(d["min"])
        if d.get("max") is not None:
            h.vmax = float(d["max"])
        h._zero = int(d.get("zero", 0))
        h._buckets = {int(i): int(n) for i, n in d.get("buckets", [])}
        return h

    def quantile(self, q: float) -> float:
        """Approximate ``q``-quantile of the stream.

        Well-defined at every stream length (ISSUE 7 satellite): an
        EMPTY histogram answers 0.0 (there is no sample to clamp to —
        callers that need "no data" distinct from zero check ``count``),
        a single-sample histogram answers that sample for every ``q``
        (the bucket midpoint clamps to [vmin, vmax] == [v, v]), and
        ``q`` outside [0, 1] clamps to the range instead of producing a
        negative rank that would walk the buckets nonsensically.
        """
        if self.count == 0:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        rank = q * (self.count - 1)  # np.percentile's 'linear' rank
        cum = self._zero
        if rank < cum:
            return 0.0
        for i in sorted(self._buckets):
            cum += self._buckets[i]
            if rank < cum:
                mid = self.growth ** (i + 0.5)
                return min(max(mid, self.vmin), self.vmax)
        return self.vmax

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative ``(upper_edge, count_at_or_below)`` pairs — the
        Prometheus histogram exposition shape (``le=`` buckets).

        The zero bucket exports with edge 0.0; geometric buckets export
        their exclusive upper edge ``G**(i+1)``. Empty histograms return
        ``[]`` (the renderer still emits ``+Inf``/sum/count lines, so an
        unseen series scrapes as a valid zero histogram rather than
        erroring)."""
        out: List[Tuple[float, int]] = []
        cum = 0
        if self._zero:
            cum = self._zero
            out.append((0.0, cum))
        for i in sorted(self._buckets):
            cum += self._buckets[i]
            out.append((self.growth ** (i + 1), cum))
        return out

    def summary(self) -> Dict[str, float]:
        if self.count == 0:
            return {"count": 0, "mean": 0.0, "min": 0.0, "max": 0.0,
                    "p50": 0.0, "p95": 0.0, "p99": 0.0}
        return {"count": self.count,
                "mean": self.total / self.count,
                "min": self.vmin, "max": self.vmax,
                "p50": self.quantile(0.50),
                "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}


class _SpanCtx:
    """Context manager returned by :meth:`Telemetry.span`; times the
    block with ``perf_counter`` and records on exit (exceptions
    included — the span still closes, Chrome traces stay well-formed)."""

    __slots__ = ("_tel", "_name", "_cat", "_args", "_trace", "_t0")

    def __init__(self, tel: "Telemetry", name: str, cat: str,
                 args: Optional[dict], trace: Optional[dict] = None):
        self._tel = tel
        self._name = name
        self._cat = cat
        self._args = args
        self._trace = trace

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc_info) -> None:
        self._tel.emit_span(self._name, self._cat, self._t0,
                            time.perf_counter(), self._args,
                            trace=self._trace)


class _NullCtx:
    """Reusable no-op context: what a disabled core hands out, so the
    off path allocates nothing and times nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc_info):
        return False


_NULL_CTX = _NullCtx()


class Telemetry:
    """Thread-safe process-wide telemetry core (see module docstring).

    All mutation goes through one lock; every probe first checks
    :attr:`enabled` so a disabled core costs one attribute read per
    probe site. Timestamps are ``time.perf_counter()`` seconds relative
    to the instance's construction (``origin_perf``); ``origin_unix``
    (wall clock at construction) rides in the export meta so events can
    be correlated with log lines.
    """

    def __init__(self, capacity: int = 1 << 16, enabled: bool = True,
                 trace_dir: Optional[str] = None,
                 process_index: int = 0, host_count: int = 1,
                 run_id: Optional[str] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if not (0 <= process_index < max(host_count, 1)):
            raise ValueError(f"process_index {process_index} out of range "
                             f"for host_count {host_count}")
        self.enabled = enabled
        self.capacity = capacity
        self.trace_dir = trace_dir
        # fleet stamp (ISSUE 8): rides in the export meta line and
        # keys the per-host shard filenames, so N processes sharing one
        # trace_dir produce N joinable (never colliding) streams
        self.process_index = int(process_index)
        self.host_count = int(host_count)
        self.run_id = run_id
        self.dropped = 0
        self.origin_perf = time.perf_counter()
        self.origin_unix = time.time()
        self._lock = threading.Lock()
        self._events: deque = deque()
        # exact per-(cat, name) span aggregates, independent of the ring
        self._agg: Dict[Tuple[str, str], List[float]] = {}
        self._counters: Dict[Tuple[str, str], float] = {}
        self._hists: Dict[Tuple[str, str], Histogram] = {}
        # keys in _counters that hold a gauge's latest SAMPLE rather
        # than a monotonic total — the /metrics renderer must type them
        # differently (Prometheus gauge vs counter)
        self._gauge_keys: set = set()

    # -- recording ---------------------------------------------------------

    def span(self, name: str, cat: str = "host",
             args: Optional[dict] = None,
             trace: Optional[dict] = None):
        """Context manager timing a block as one span (no-op when
        disabled). ``trace`` (a :func:`span_link` dict) stamps the
        span's causal coordinate."""
        if not self.enabled:
            return _NULL_CTX
        return _SpanCtx(self, name, cat, args, trace)

    def emit_span(self, name: str, cat: str, t0: float, t1: float,
                  args: Optional[dict] = None,
                  trace: Optional[dict] = None) -> None:
        """Record an already-timed span (``t0``/``t1`` from
        ``perf_counter``) — the path the ledger views use, so THEIR
        accumulation and the core's see the identical ``t1 - t0``.
        ``trace`` (a :func:`span_link` dict) rides the event verbatim
        into both exporters (ISSUE 11)."""
        if not self.enabled:
            return
        dur = t1 - t0
        ev = {"type": "span", "name": name, "cat": cat,
              "ts": t0 - self.origin_perf, "dur": dur,
              "tid": threading.current_thread().name}
        if args:
            ev["args"] = args
        if trace:
            ev["trace"] = trace
        with self._lock:
            rec = self._agg.setdefault((cat, name), [0, 0.0])
            rec[0] += 1
            rec[1] += dur
            self._append(ev)

    def instant(self, name: str, cat: str = "host",
                args: Optional[dict] = None,
                ts: Optional[float] = None,
                trace: Optional[dict] = None) -> None:
        """Record a zero-duration marker event (e.g. request enqueue)."""
        if not self.enabled:
            return
        t = (time.perf_counter() if ts is None else ts) - self.origin_perf
        ev = {"type": "instant", "name": name, "cat": cat, "ts": t,
              "tid": threading.current_thread().name}
        if args:
            ev["args"] = args
        if trace:
            ev["trace"] = trace
        with self._lock:
            self._append(ev)

    def counter(self, name: str, delta: float = 1.0,
                cat: str = "host") -> None:
        """Increment a monotonic counter; the ring records the new
        total (a Chrome counter track of the running value)."""
        if not self.enabled:
            return
        ts = time.perf_counter() - self.origin_perf
        with self._lock:
            total = self._counters.get((cat, name), 0.0) + delta
            self._counters[(cat, name)] = total
            self._append({"type": "counter", "name": name, "cat": cat,
                          "ts": ts, "value": total})

    def gauge(self, name: str, value: float, cat: str = "host",
              ts: Optional[float] = None) -> None:
        """Sample an instantaneous value (e.g. live serve slots); the
        latest sample is also kept under counters for snapshots."""
        if not self.enabled:
            return
        t = (time.perf_counter() if ts is None else ts) - self.origin_perf
        with self._lock:
            self._counters[(cat, name)] = float(value)
            self._gauge_keys.add((cat, name))
            self._append({"type": "counter", "name": name, "cat": cat,
                          "ts": t, "value": float(value)})

    def observe(self, name: str, value: float, cat: str = "host") -> None:
        """Feed one observation into the named streaming histogram."""
        if not self.enabled:
            return
        with self._lock:
            h = self._hists.get((cat, name))
            if h is None:
                h = self._hists[(cat, name)] = Histogram()
            h.observe(value)

    def _append(self, ev: dict) -> None:
        # caller holds the lock
        if len(self._events) >= self.capacity:
            self._events.popleft()
            self.dropped += 1
        self._events.append(ev)

    # -- reading -----------------------------------------------------------

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def aggregates(self) -> Dict[Tuple[str, str], Tuple[int, float]]:
        """Exact span (count, total_s) per (category, name)."""
        with self._lock:
            return {k: (int(v[0]), float(v[1]))
                    for k, v in self._agg.items()}

    def counters(self) -> Dict[Tuple[str, str], float]:
        with self._lock:
            return dict(self._counters)

    def histogram(self, name: str, cat: str = "host"
                  ) -> Optional[Dict[str, float]]:
        """Live summary of one streaming histogram (None if unseen)."""
        with self._lock:
            h = self._hists.get((cat, name))
            return None if h is None else h.summary()

    def snapshot(self) -> Dict[str, Dict]:
        """One consistent view of every aggregate store, taken under a
        single lock acquisition — what the ``/metrics`` endpoint renders
        and the watchdog embeds in ``incident.json``. Counters and
        gauges come back separated (gauges hold their latest sample,
        not a monotonic total), histograms as ``{summary, buckets}``.
        """
        with self._lock:
            return {
                "aggregates": {k: (int(v[0]), float(v[1]))
                               for k, v in self._agg.items()},
                "counters": {k: v for k, v in self._counters.items()
                             if k not in self._gauge_keys},
                "gauges": {k: v for k, v in self._counters.items()
                           if k in self._gauge_keys},
                "hists": {k: {"summary": h.summary(),
                              "total": h.total,
                              "buckets": h.buckets()}
                          for k, h in self._hists.items()},
                "dropped": self.dropped,
            }

    # -- exporters ---------------------------------------------------------

    def export_jsonl(self, path: str) -> None:
        """Write the newline-JSONL event stream: one meta line, the ring
        events in record order, then ``agg``/``counter_total``/``hist``
        summary lines (exact even when the ring dropped events).

        Fleet-merge additions (ISSUE 8): the meta line carries the
        ``(process_index, host_count, run_id)`` stamp, gauge-valued
        ``counter_total`` lines are flagged ``"gauge": true`` (a merge
        must SUM counters but never sum latest-sample gauges), and
        ``hist`` lines carry their loss-free ``raw`` log buckets so
        ``scripts/trace_merge.py`` can rebuild and exactly merge them.
        """
        with self._lock:
            events = list(self._events)
            agg = {k: list(v) for k, v in self._agg.items()}
            counters = dict(self._counters)
            gauge_keys = set(self._gauge_keys)
            hists = {k: (h.summary(), h.total, h.to_dict())
                     for k, h in self._hists.items()}
            dropped = self.dropped
        with open(path, "w") as f:
            f.write(json.dumps({
                "type": "meta", "origin_unix": self.origin_unix,
                "pid": os.getpid(), "capacity": self.capacity,
                "dropped": dropped,
                "process_index": self.process_index,
                "host_count": self.host_count,
                # announces the end sentinel up front (ISSUE 14): a
                # stream whose meta carries this but whose tail lacks
                # the sentinel was torn mid-export — even if the tear
                # landed inside the summary block
                "end_sentinel": True,
                "run_id": self.run_id}) + "\n")
            for ev in events:
                f.write(json.dumps(ev) + "\n")
            for (cat, name), (n, total) in sorted(agg.items()):
                f.write(json.dumps({
                    "type": "agg", "cat": cat, "name": name,
                    "count": int(n), "total_s": total}) + "\n")
            for (cat, name), v in sorted(counters.items()):
                rec = {"type": "counter_total", "cat": cat, "name": name,
                       "value": v}
                if (cat, name) in gauge_keys:
                    rec["gauge"] = True
                f.write(json.dumps(rec) + "\n")
            for (cat, name), (s, total, raw) in sorted(hists.items()):
                f.write(json.dumps({
                    "type": "hist", "cat": cat, "name": name, **s,
                    "total": total, "raw": raw}) + "\n")
            # end sentinel (ISSUE 14 satellite): a shard whose stream
            # stops before this line was torn mid-export — a killed
            # host's tail. trace_merge uses it (or, for pre-sentinel
            # exports, the presence of summary lines) to annotate the
            # merged meta with host_died instead of only warning that
            # totals undercount. Readers skip unknown types, so old
            # tooling is unaffected.
            f.write(json.dumps({"type": "end",
                                "events": len(events)}) + "\n")

    def export_chrome_trace(self, path: str) -> None:
        """Write a Chrome-trace ``traceEvents`` JSON (chrome://tracing /
        Perfetto). Spans -> ``X`` complete events, instants -> ``i``,
        counters/gauges -> ``C`` tracks; threads get name metadata.
        Trace-stamped events (ISSUE 11) additionally carry their causal
        coordinate in ``args.trace`` and chain into flow arrows
        (:func:`chrome_flow_events`), so Perfetto draws a request's
        hops across thread tracks."""
        events = self.events()
        pid = os.getpid()
        tids: Dict[str, int] = {}
        out: List[dict] = []
        flows: List[Tuple[str, float, int, int]] = []

        def tid_of(name: str) -> int:
            if name not in tids:
                tids[name] = len(tids)
                out.append({"ph": "M", "name": "thread_name", "pid": pid,
                            "tid": tids[name],
                            "args": {"name": name}})
            return tids[name]

        for ev in events:
            ts_us = ev["ts"] * 1e6
            if ev["type"] == "span":
                rec = {"ph": "X", "name": ev["name"], "cat": ev["cat"],
                       "pid": pid, "tid": tid_of(ev["tid"]),
                       "ts": ts_us, "dur": ev["dur"] * 1e6}
                if "args" in ev:
                    rec["args"] = ev["args"]
                stamp_trace_flow(rec, ev, flows, pid)
                out.append(rec)
            elif ev["type"] == "instant":
                rec = {"ph": "i", "name": ev["name"], "cat": ev["cat"],
                       "pid": pid, "tid": tid_of(ev["tid"]),
                       "ts": ts_us, "s": "t"}
                if "args" in ev:
                    rec["args"] = ev["args"]
                stamp_trace_flow(rec, ev, flows, pid)
                out.append(rec)
            elif ev["type"] == "counter":
                out.append({"ph": "C", "name": ev["name"],
                            "cat": ev["cat"], "pid": pid, "tid": 0,
                            "ts": ts_us,
                            "args": {ev["name"]: ev["value"]}})
        out.extend(chrome_flow_events(flows))
        with open(path, "w") as f:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)

    def export(self, trace_dir: Optional[str] = None) -> Dict[str, str]:
        """Write both exporters into ``trace_dir`` (default: the
        configured one); returns ``{"jsonl": path, "chrome": path}``.

        Paths are this host's SHARD (``telemetry.p0001.jsonl`` under
        multi-controller, the bare legacy names single-host), so every
        process of a fleet can export into one shared trace_dir;
        ``scripts/trace_merge.py`` joins the shards afterwards."""
        d = trace_dir or self.trace_dir
        if not d:
            raise ValueError("no trace_dir configured or given")
        os.makedirs(d, exist_ok=True)
        paths = {"jsonl": os.path.join(
                     d, shard_jsonl_name(self.process_index,
                                         self.host_count)),
                 "chrome": os.path.join(
                     d, shard_chrome_name(self.process_index,
                                          self.host_count))}
        self.export_jsonl(paths["jsonl"])
        self.export_chrome_trace(paths["chrome"])
        return paths

    # -- device-trace alignment -------------------------------------------

    @contextlib.contextmanager
    def device_trace(self, subdir: str = "device") -> Iterator[None]:
        """Capture a ``jax.profiler`` device trace into
        ``<trace_dir>/<subdir>`` with instant markers in the host stream
        at start/stop, so the XProf timeline aligns with the host spans
        of the same run. No-op when disabled or without a trace_dir."""
        if not (self.enabled and self.trace_dir):
            yield
            return
        import jax

        logdir = os.path.join(self.trace_dir, subdir)
        self.instant(DEVICE_TRACE_START, cat=PROFILER_CAT,
                     args={"logdir": logdir})
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            self.instant(DEVICE_TRACE_STOP, cat=PROFILER_CAT)


# -- the process-wide instance ----------------------------------------------

_global = Telemetry(enabled=False)


def get_telemetry() -> Telemetry:
    """The process-wide core. Disabled (and empty) unless
    :func:`configure` ran; probe sites resolve it at call time so a
    late ``configure`` still catches every subsystem."""
    return _global


def configure(trace_dir: Optional[str] = None,
              capacity: int = 1 << 16,
              process_index: int = 0, host_count: int = 1,
              run_id: Optional[str] = None) -> Telemetry:
    """Swap in a FRESH enabled core (old events do not leak across
    runs) writing into ``trace_dir``; returns it.

    ``(process_index, host_count)`` is the caller's fleet coordinate
    (``parallel.multihost.topology()`` in the runtime) — it keys the
    per-host shard filenames. ``run_id`` defaults to the process-wide
    id from :mod:`~sketch_rnn_tpu.utils.runinfo`, the key that joins
    this trace with metrics, bench rows and the RUN.json manifest."""
    global _global
    if run_id is None:
        from sketch_rnn_tpu.utils import runinfo

        run_id = runinfo.get_run_id()
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
    _global = Telemetry(capacity=capacity, enabled=True,
                        trace_dir=trace_dir,
                        process_index=process_index,
                        host_count=host_count, run_id=run_id)
    return _global


def disable() -> None:
    """Restore the disabled default (tests; end of a traced run)."""
    global _global
    _global = Telemetry(enabled=False)


@contextlib.contextmanager
def suppressed():
    """Temporarily swap in a disabled core (ISSUE 11: fleet warmup —
    the warm clone's 1-step burst must not emit request spans, or its
    auto-assigned uid 0 would collide with the real request 0's trace
    tree; the CLI orders warmup before configure, but the library API
    allows either order). Probe sites resolve the global at call time,
    so everything inside the block records nothing and the prior core
    comes back intact. NOT thread-safe — setup phases only, before any
    worker threads run."""
    global _global
    prev = _global
    _global = Telemetry(enabled=False)
    try:
        yield
    finally:
        _global = prev


# -- compile accounting ------------------------------------------------------


class JitCompileProbe:
    """Wrap a jitted callable with per-geometry compile accounting.

    Length-bucketed execution made compiled-program count a first-order
    cost (one executable per (B, Tb), train/step.py), but nothing
    observed WHEN compiles happen, how long they take, or what the
    executables cost — the pjit/TPUv4 scaling paper's first ask
    (PAPERS.md). This wrapper is the probe:

    - Every call derives a cheap geometry key (``key_of(args)`` —
      callers pass a lambda extracting only the VARYING shapes, e.g.
      the batch leaves; default: shapes of every arg leaf).
    - While telemetry is enabled, a first-seen geometry is compiled
      through the AOT path (``fn.lower(...).compile()``) so its
      ``cost_analysis()`` / ``memory_analysis()`` stats — flops, bytes
      accessed, peak device bytes — can be read off the actual
      executable; the compile is timed as ONE span (cat ``compile``)
      carrying those stats in its args, a ``jit_cache_miss`` counter
      ticks, and the executable lands in the probe's own cache. Repeat
      geometries tick ``jit_cache_hit`` and dispatch the cached
      executable — exactly one compile per geometry, same as jit's own
      shape-keyed cache (the bucketed-smoke acceptance pin).
    - While telemetry is disabled the call forwards straight to the
      jitted ``fn`` (its internal cache; bitwise the pre-probe path)
      but the geometry is still remembered: a run that enables tracing
      AFTER warmup (serve-bench's documented order) reports warm
      geometries as cache HITS instead of recompiling them into the
      measured window.

    Exposes ``_cache_size()`` (own executables + the inner jit cache)
    so :func:`train.step.geometry_cache_size` counts through the probe
    transparently.
    """

    _FALLBACK = object()  # geometry compiled inside fn's own jit cache

    def __init__(self, fn, name: str, key_of=None, label_of=None):
        self._fn = fn
        self._name = name
        self._key_of = key_of
        self._label_of = label_of
        self._cache: Dict = {}
        self._lock = threading.Lock()

    def _geom(self, args):
        if self._key_of is not None:
            return self._key_of(args)
        import jax

        return tuple(tuple(getattr(leaf, "shape", ()))
                     for leaf in jax.tree_util.tree_leaves(args))

    def __call__(self, *args):
        key = self._geom(args)
        with self._lock:
            entry = self._cache.get(key)
        tel = get_telemetry()
        if entry is not None:
            if tel.enabled:
                tel.counter("jit_cache_hit", 1.0, cat="compile")
            fn = self._fn if entry is self._FALLBACK else entry
            return fn(*args)
        if not tel.enabled:
            # first dispatch with tracing off: the inner jit compiles
            # and caches; remember the geometry so later-enabled runs
            # count it warm instead of recompiling it
            with self._lock:
                self._cache.setdefault(key, self._FALLBACK)
            return self._fn(*args)
        tel.counter("jit_cache_miss", 1.0, cat="compile")
        span_args = {"geometry": (self._label_of(args) if self._label_of
                                  else repr(key))}
        t0 = time.perf_counter()
        try:
            compiled = self._fn.lower(*args).compile()
            span_args.update(executable_stats(compiled))
            entry = compiled
        except Exception as e:  # noqa: BLE001 — AOT is best-effort
            # a backend without the AOT path still gets the span and
            # the miss counter; the call itself must never fail here
            span_args["aot_error"] = repr(e)
            entry = self._FALLBACK
        t1 = time.perf_counter()
        tel.emit_span(self._name, "compile", t0, t1, args=span_args)
        if span_args.get("peak_bytes") is not None:
            # latest-compile peak device bytes as a gauge: the /metrics
            # view that makes bucket-edge / slot-count choices
            # memory-visible before a run OOMs
            tel.gauge(f"{self._name}_peak_bytes",
                      span_args["peak_bytes"], cat="compile")
        with self._lock:
            self._cache.setdefault(key, entry)
        fn = self._fn if entry is self._FALLBACK else entry
        return fn(*args)

    def _cache_size(self) -> int:
        try:
            inner = int(self._fn._cache_size())
        except AttributeError:
            inner = 0
        with self._lock:
            own = sum(1 for v in self._cache.values()
                      if v is not self._FALLBACK)
        return inner + own

    def __repr__(self) -> str:
        return f"JitCompileProbe({self._name}, {len(self._cache)} geoms)"


def executable_stats(compiled) -> Dict[str, float]:
    """Flops / bytes / peak-device-bytes of one compiled executable.

    Read from ``cost_analysis()`` (may be a per-device list) and
    ``memory_analysis()`` (absent on some backends — missing pieces are
    simply omitted). ``peak_bytes`` is the executable's device-memory
    high-water estimate: arguments + outputs + temporaries (XLA's
    ``CompiledMemoryStats``), the number that decides whether a bucket
    edge or slot count fits in HBM. ``alias_bytes`` is the donated /
    input-output-aliased portion of the arguments (ISSUE 20): a donated
    program's EFFECTIVE high water is ``peak_bytes - alias_bytes``,
    because aliased argument buffers are reused as outputs instead of
    coexisting with them — the quantity ``scripts/runtime_bench.py``
    measures the donation win on."""
    out: Dict[str, float] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            if ca.get("flops") is not None:
                out["flops"] = float(ca["flops"])
            if ca.get("bytes accessed") is not None:
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:  # noqa: BLE001
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            arg = float(getattr(ma, "argument_size_in_bytes", 0) or 0)
            outb = float(getattr(ma, "output_size_in_bytes", 0) or 0)
            tmp = float(getattr(ma, "temp_size_in_bytes", 0) or 0)
            out["argument_bytes"] = arg
            out["output_bytes"] = outb
            out["temp_bytes"] = tmp
            out["peak_bytes"] = arg + outb + tmp
            alias = getattr(ma, "alias_size_in_bytes", None)
            if alias is not None:
                out["alias_bytes"] = float(alias or 0)
    except Exception:  # noqa: BLE001
        pass
    return out


# -- device-memory sampling --------------------------------------------------

# every started sampler, for the conftest no-leaked-threads guard
_SAMPLERS: set = set()
_SAMPLERS_LOCK = threading.Lock()


def _default_device_stats() -> Optional[Dict[str, float]]:
    """Live/peak device bytes over this process's local devices via
    ``jax`` memory stats: ``bytes_in_use`` SUMS across local devices
    (total live footprint this host holds), ``peak_bytes_in_use`` is
    the per-device MAX (each device's HBM is its own ceiling — a sum
    would hide that one chip is about to OOM). None when the backend
    exposes no stats (CPU)."""
    import jax

    in_use = 0.0
    peak = 0.0
    seen = False
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001
            stats = None
        if not stats:
            continue
        seen = True
        in_use += float(stats.get("bytes_in_use", 0) or 0)
        peak = max(peak, float(stats.get("peak_bytes_in_use", 0) or 0))
    if not seen:
        return None
    return {"bytes_in_use": in_use, "peak_bytes_in_use": peak}


class MemorySampler:
    """Background device-memory gauge feeding the telemetry core.

    Samples ``jax`` device memory stats every ``interval_s`` on a
    daemon thread and records gauges (cat ``memory``):

    - ``device_bytes_in_use`` — live bytes summed over local devices,
    - ``device_peak_bytes`` — per-device peak high-water mark,
    - ``phase_peak_bytes_<phase>`` — the max LIVE bytes observed while
      :attr:`phase` held that label (the loop flips it train/eval), so
      an operator can read "eval sweeps spike HBM by X" off /metrics.

    Gauges land in the core's snapshot, so the ``/metrics`` endpoint
    renders them live and exported traces carry the timeline as Chrome
    counter tracks. Backends without memory stats (CPU) record nothing
    — ``stats_fn`` is injectable for tests. Started samplers register
    process-wide; :func:`stop_all_samplers` is the tier-1 conftest
    guard against leaked sampler threads.
    """

    def __init__(self, interval_s: float = 0.5,
                 telemetry: Optional[Telemetry] = None,
                 stats_fn=None):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = float(interval_s)
        self._telemetry = telemetry
        self._stats_fn = stats_fn or _default_device_stats
        self.phase = "run"
        self._phase_peak: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _tel(self) -> Telemetry:
        return self._telemetry if self._telemetry is not None \
            else get_telemetry()

    def sample(self) -> Optional[Dict[str, float]]:
        """Take one sample now (also the thread body's step); returns
        the stats recorded, or None (disabled core / no backend
        stats)."""
        tel = self._tel()
        if not tel.enabled:
            return None
        stats = self._stats_fn()
        if not stats:
            return None
        in_use = float(stats.get("bytes_in_use", 0.0))
        peak = float(stats.get("peak_bytes_in_use", 0.0))
        phase = self.phase
        prev = self._phase_peak.get(phase, 0.0)
        if in_use > prev:
            self._phase_peak[phase] = prev = in_use
        tel.gauge("device_bytes_in_use", in_use, cat="memory")
        tel.gauge("device_peak_bytes", peak, cat="memory")
        tel.gauge(f"phase_peak_bytes_{phase}", prev, cat="memory")
        return {"bytes_in_use": in_use, "peak_bytes_in_use": peak}

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — sampling must never kill
                pass           # the run it observes
            self._stop.wait(self.interval_s)

    def start(self) -> "MemorySampler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="memory-sampler",
                                        daemon=True)
        self._thread.start()
        with _SAMPLERS_LOCK:
            _SAMPLERS.add(self)
        return self

    def stop(self) -> None:
        thread = self._thread
        self._thread = None
        with _SAMPLERS_LOCK:
            _SAMPLERS.discard(self)
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5.0)

    def __enter__(self) -> "MemorySampler":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    def __repr__(self) -> str:
        state = "live" if self._thread is not None else "stopped"
        return f"MemorySampler({state}, phase={self.phase!r})"


def live_samplers() -> Tuple["MemorySampler", ...]:
    with _SAMPLERS_LOCK:
        return tuple(_SAMPLERS)


def stop_all_samplers() -> Tuple[str, ...]:
    """Stop every live sampler; returns their reprs (the conftest guard
    asserts this is empty — a non-empty return names the leaker)."""
    leaked = live_samplers()
    names = tuple(repr(s) for s in leaked)
    for s in leaked:
        s.stop()
    return names
