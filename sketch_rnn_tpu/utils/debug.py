"""NaN/Inf guards (SURVEY.md §5 "Race detection / sanitizers").

The reference has nothing to sanitize (single-process Python); the JAX
equivalents of its implicit safety net are explicit finiteness checks on
metrics/params. These are host-side helpers the train loop can call
cheaply on already-fetched scalars, plus a pytree scanner for post-mortem
debugging (which leaf went non-finite first).
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import numpy as np


def check_finite(scalars: Dict[str, float], step: int) -> None:
    """Raise FloatingPointError naming every non-finite metric."""
    bad = [k for k, v in scalars.items() if not np.isfinite(v)]
    if bad:
        raise FloatingPointError(
            f"non-finite metrics at step {step}: {bad} "
            f"(values {[scalars[k] for k in bad]}); "
            f"restore the previous checkpoint and lower the learning rate "
            f"or enable gradient clipping")


def find_nonfinite(tree: Any, prefix: str = "") -> List[str]:
    """Paths of all non-finite leaves in a pytree (post-mortem helper)."""
    out: List[str] = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(jax.device_get(leaf))
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            name = prefix + jax.tree_util.keystr(path)
            frac = float(np.mean(~np.isfinite(arr)))
            out.append(f"{name} ({frac:.1%} non-finite)")
    return out


def param_count(tree: Any) -> int:
    """Total number of scalar parameters in a pytree."""
    return sum(int(np.prod(np.shape(x))) for x in jax.tree_util.tree_leaves(tree))
