"""JAX version compatibility shims (0.4.x <-> 0.9 API drift).

The repo targets jax 0.9's public surface (``jax.shard_map``,
``jax.typeof(...).vma``); the deployment image may pin an older jax
(observed: 0.4.37, where shard_map still lives in ``jax.experimental``
and varying-manual-axes tracking does not exist). Every version probe
lives HERE so call sites stay on one spelling and the suite runs
unchanged on either release line.
"""

from __future__ import annotations

import jax

# jax 0.9+: varying-manual-axes tracking exists and its replication
# check understands while_loop; 0.4.x's check_rep predecessor has no
# rule for `while` and must stay off around loop-carrying shard_maps.
VMA_TRACKING = hasattr(jax, "typeof")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` on 0.9+; the experimental spelling on 0.4.x.

    ``check_vma`` maps onto 0.4.x's ``check_rep`` (the same replication
    check under its earlier name).
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as sm_old
    return sm_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
