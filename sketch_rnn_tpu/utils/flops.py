"""Analytic FLOP accounting and MFU (model FLOPs utilization).

The reference ships no perf instrumentation and BASELINE.json's
``published`` table is empty, so an in-repo roofline is the only honest
perf yardstick available (VERDICT r1 "what's weak" #4): count the
model's matmul FLOPs per stroke point analytically, multiply by measured
strokes/sec, and divide by the chip's peak to get MFU.

Counting convention: a matmul of shapes ``[.., D] @ [D, H]`` costs
``2*D*H`` FLOPs per row (multiply + add). Elementwise work (gate
nonlinearities, layer norm, the MDN head's pointwise math) is O(H) per
step against O(H^2) for the matmuls and is ignored — standard for MFU
accounting, and it keeps the number comparable across cell types.
"""

from __future__ import annotations

from typing import Optional

from sketch_rnn_tpu.config import HParams


def lstm_cell_flops(input_size: int, hidden: int) -> int:
    """Fwd FLOPs of one LSTM step per example: ``[x;h] @ W -> 4H`` gates."""
    return 2 * (input_size + hidden) * 4 * hidden


def layer_norm_lstm_cell_flops(input_size: int, hidden: int) -> int:
    # layer norm adds only O(H) elementwise work on top of the gate matmuls
    return lstm_cell_flops(input_size, hidden)


def hyper_lstm_cell_flops(input_size: int, hidden: int, hyper: int,
                          embed: int) -> int:
    """Main gates + aux LSTM over [x;h] + fused 4x3 hyper projections
    (ops/cells.py HyperLSTMCell: w_hz_* are [hyper, 4e], w_zd_* einsums
    are [4, e, h])."""
    main = lstm_cell_flops(input_size, hidden)
    aux = lstm_cell_flops(input_size + hidden, hyper)
    embeds = 3 * 2 * hyper * 4 * embed      # w_hz_{x,h,b}
    scales = 3 * 2 * 4 * embed * hidden     # w_zd_{x,h,b} einsums
    return main + aux + embeds + scales


def _cell_flops(kind: str, input_size: int, hidden: int, hps: HParams) -> int:
    if kind == "hyper":
        return hyper_lstm_cell_flops(input_size, hidden,
                                     hps.hyper_rnn_size,
                                     hps.hyper_embed_size)
    if kind == "layer_norm":
        return layer_norm_lstm_cell_flops(input_size, hidden)
    return lstm_cell_flops(input_size, hidden)


def flops_per_stroke(hps: HParams, train: bool = True) -> float:
    """Actual FLOPs executed per stroke point (one timestep of one
    sequence) — an implementation accounting, not a canonical-model one.

    Forward: encoder (2 directions over the full sequence, when
    conditional) + decoder cell + the 6M+3 output projection. Training
    multiplies by 3 (backward ~= 2x forward) plus one extra forward when
    ``hps.remat`` recomputes activations in the backward pass.

    On the fused decoder path (all three cells), the time-invariant
    inputs (z, class embedding) are projected ONCE per sequence as gate
    biases (ops/rnn.py x_extra; the hyper cell's aux LSTM gets its own),
    so the per-step decoder input width is just the stroke-5 — counting
    the full width there would overstate MFU by ~6% at the flagship
    config.
    """
    from sketch_rnn_tpu.models.vae import SketchRNN

    dec_in = SketchRNN(hps).decoder_input_size
    if hps.fused_rnn and not hps.use_input_dropout:
        dec_in = 5  # extras ride as a per-sequence bias, amortized ~0
    fwd = (_cell_flops(hps.dec_model, dec_in, hps.dec_rnn_size, hps)
           + 2 * hps.dec_rnn_size * (6 * hps.num_mixture + 3))
    if hps.conditional:
        fwd += 2 * _cell_flops(hps.enc_model, 5, hps.enc_rnn_size, hps)
    if not train:
        return float(fwd)
    mult = 4.0 if hps.remat else 3.0
    return float(fwd) * mult


# Peak dense bf16/f32 FLOP/s per chip by jax device_kind. Sources: public
# TPU spec sheets (v5e 197 bf16 TFLOP/s, v4 275, v3 123, v2 45, v6e 918).
_PEAK_BF16 = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,       # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops_per_chip(device_kind: str) -> Optional[float]:
    """Peak bf16 FLOP/s for a ``jax.Device.device_kind``; None if unknown
    (e.g. the virtual CPU platform), in which case MFU is not reported."""
    for name, peak in _PEAK_BF16.items():
        if device_kind.lower().startswith(name.lower()):
            return peak
    return None


def mfu(strokes_per_sec_per_chip: float, hps: HParams, device_kind: str,
        train: bool = True) -> Optional[float]:
    """Fraction of chip peak the measured throughput corresponds to."""
    peak = peak_flops_per_chip(device_kind)
    if peak is None or strokes_per_sec_per_chip <= 0:
        return None
    return strokes_per_sec_per_chip * flops_per_stroke(hps, train) / peak
