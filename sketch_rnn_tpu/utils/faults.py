"""Deterministic, plan-driven fault injection (the chaos layer).

ISSUE 10 tentpole piece 1. The ROADMAP's north star is a production
system, and production JAX stacks treat component failure as routine
(the pjit/TPUv4 scaling paper trains through preemptions via
checkpoint/resume; the TensorFlow system paper makes fault tolerance a
first-class runtime design axis) — but a recovery path that is never
exercised is a recovery path that does not work. This module makes
failure *injectable, reproducible and accounted*:

- **Named sites.** Every guarded operation calls
  :func:`fault_point("<site>")` (raising sites) or
  :func:`corrupt_value("<site>", v)` (value-corruption sites) at the
  exact place a real fault would land: the checkpoint commit
  (``ckpt.commit``), the torn instant between the sidecar and msgpack
  renames (``ckpt.torn``), the async writer thread (``ckpt.writer``),
  a fleet replica's burst dispatch (``fleet.worker.rNN``), a serving
  engine's chunk loop (``serve.chunk[.rNN]`` — fires mid-burst, after
  earlier chunks' completions already emitted telemetry, exercising
  the abort-ledger / duplicate-emission path), the data
  loader's batch assembly (``data.batch``), the metrics writer
  (``metrics.write``), a drained metrics row's loss value
  (``metrics.row``), the training loop's step dispatch
  (``train.step``), an elastic host's step-barrier entry
  (``host.kill.hNN``, train/elastic.py — ``kind=exit`` is an honest
  host DEATH: the heartbeat stops beating and every surviving peer's
  barrier detects it), the fleet barrier exchange itself
  (``dcn.collective``, parallel/multihost.py — the DCN-collective
  failure class), and the zero-downtime rollout path (ISSUE 16,
  serve/rollout.py + train/checkpoint.py): a candidate checkpoint's
  msgpack decode (``ckpt.load.corrupt`` — fires inside
  ``validate_checkpoint``, so serving admission AND training resume
  share the injected-corruption surface), the canary gate
  (``rollout.canary``) and each replica's swap step in the rolling
  walk (``rollout.swap.rNN``). Sites cost one module-global read when no plan is
  armed — the process default — so the chaos layer is invisible in
  production runs (the telemetry off-by-default discipline).

- **Pure firing decision.** Whether invocation ``n`` of a site fires
  is a pure function of ``(seed, site, n)`` and the plan — ``at=N``
  fires exactly at the Nth call, ``every=K`` on every Kth,
  ``p=0.25`` via a seeded hash — so every chaos run is exactly
  reproducible: re-running the same plan against the same workload
  kills the same burst / tears the same save. No RNG state is shared
  with anything (the decision hashes, it does not draw), so an armed
  plan that never fires is bitwise invisible to training and serving.

- **Accounted.** Every fire lands a telemetry counter
  (``faults_injected`` + the per-site series, cat ``faults``) and an
  entry in the injector's ``fired`` log; ``summary()`` is the evidence
  block incident post-mortems and RESILIENCE.json embed, closing the
  loop between injection and detection.

Plan grammar (one spec per site, comma-separated)::

    site[@N][:every=K][:p=F][:kind=raise|exit|nan][:times=M]

- ``site@N`` — fire at invocation N (0-based), once (``times=1``).
- ``site:every=K`` — fire every Kth invocation (0, K, 2K, ...).
- ``site:p=F`` — fire with probability F, decided by a seeded hash of
  ``(seed, site, n)`` (deterministic; independent across sites).
- ``kind=raise`` (default) raises :class:`InjectedFault`;
  ``kind=exit`` calls ``os._exit(EXIT_CODE)`` — a true crash, no
  ``finally`` blocks, the kill -9 of the crash-equivalence harness;
  ``kind=nan`` only fires at value sites (:func:`corrupt_value`),
  replacing the value with NaN.
- ``times=M`` caps total fires (default 1 for ``at``, unbounded for
  ``every``/``p``); ``times=0`` means unbounded explicitly.

``retry_call`` is the shared bounded-retry-with-backoff helper the
recovery paths use (checkpoint commits, fleet requeues): attempts and
backoff schedule are deterministic in the attempt index, and each
retry ticks a telemetry counter so recovery work is observable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Callable, Dict, List, Optional

EXIT_CODE = 70  # os.EX_SOFTWARE: the injected hard-crash exit status

KINDS = ("raise", "exit", "nan")


class InjectedFault(RuntimeError):
    """Raised at an armed fault site; carries the site + invocation so
    handlers (and tests) can tell injected failures from real ones."""

    def __init__(self, site: str, invocation: int):
        self.site = site
        self.invocation = invocation
        super().__init__(
            f"injected fault at site {site!r} (invocation {invocation})")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One site's firing rule (see the module docstring's grammar)."""

    site: str
    at: Optional[int] = None
    every: Optional[int] = None
    p: Optional[float] = None
    kind: str = "raise"
    times: Optional[int] = None   # None = grammar default

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"{self.site}: kind must be one of {KINDS}, "
                             f"got {self.kind!r}")
        rules = [r for r in (self.at, self.every, self.p) if r is not None]
        if len(rules) != 1:
            raise ValueError(
                f"{self.site}: exactly one of @N / every=K / p=F must be "
                f"given, got {len(rules)}")
        if self.every is not None and self.every < 1:
            raise ValueError(f"{self.site}: every must be >= 1")
        if self.p is not None and not 0.0 < self.p <= 1.0:
            raise ValueError(f"{self.site}: p must be in (0, 1]")

    @property
    def max_fires(self) -> Optional[int]:
        """Fire cap: explicit ``times`` wins (0 = unbounded); ``at``
        defaults to one fire, ``every``/``p`` to unbounded."""
        if self.times is not None:
            return None if self.times == 0 else self.times
        return 1 if self.at is not None else None

    def due(self, seed: int, n: int) -> bool:
        """Pure firing decision for invocation ``n`` (ignores the fire
        cap — the injector enforces that statefully)."""
        if self.at is not None:
            return n == self.at
        if self.every is not None:
            return n % self.every == 0
        return _unit_hash(seed, self.site, n) < self.p


def _unit_hash(seed: int, site: str, n: int) -> float:
    """Deterministic uniform in [0, 1) from ``(seed, site, n)`` — a
    hash, not an RNG draw, so probabilistic sites share no stream with
    the workload (or each other)."""
    h = hashlib.blake2b(f"{seed}:{site}:{n}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


def parse_plan(spec: str) -> Dict[str, FaultSpec]:
    """Parse a ``--fault_plan`` string into ``{site: FaultSpec}``.

    Example: ``"ckpt.commit@1,fleet.worker.r0@0,metrics.row@3:kind=nan"``.
    """
    out: Dict[str, FaultSpec] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        head = fields[0]
        kw: Dict[str, object] = {}
        if "@" in head:
            site, at = head.split("@", 1)
            try:
                kw["at"] = int(at)
            except ValueError:
                raise ValueError(f"bad fault spec {part!r}: @N needs an "
                                 f"integer invocation, got {at!r}")
        else:
            site = head
        if not site:
            raise ValueError(f"bad fault spec {part!r}: empty site name")
        for f in fields[1:]:
            if "=" not in f:
                raise ValueError(f"bad fault spec {part!r}: field {f!r} "
                                 f"is not key=value")
            k, v = f.split("=", 1)
            if k == "kind":
                kw["kind"] = v
            elif k == "every":
                kw["every"] = int(v)
            elif k == "p":
                kw["p"] = float(v)
            elif k == "times":
                kw["times"] = int(v)
            else:
                raise ValueError(f"bad fault spec {part!r}: unknown key "
                                 f"{k!r} (kind/every/p/times)")
        if site in out:
            raise ValueError(f"duplicate fault site {site!r} in plan")
        out[site] = FaultSpec(site=site, **kw)
    return out


class FaultInjector:
    """Stateful executor of a parsed plan: per-site invocation counters
    (thread-safe — fleet workers hit sites concurrently), the fire cap,
    the fired log, and the telemetry counters. Construct via
    :func:`configure`; the module global is what the sites consult."""

    def __init__(self, plan: Dict[str, FaultSpec], seed: int = 0):
        self.plan = dict(plan)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._fires: Dict[str, int] = {}
        self.fired: List[Dict] = []

    def _step(self, site: str) -> Optional[Dict]:
        """Count one invocation of ``site``; return the booked fire
        record (never re-read from ``fired`` — concurrent sites would
        race for [-1]) or None. The telemetry tick happens outside the
        lock."""
        spec = self.plan.get(site)
        with self._lock:
            n = self._counts.get(site, 0)
            self._counts[site] = n + 1
            if spec is None or not spec.due(self.seed, n):
                return None
            cap = spec.max_fires
            if cap is not None and self._fires.get(site, 0) >= cap:
                return None
            self._fires[site] = self._fires.get(site, 0) + 1
            rec = {"site": site, "invocation": n, "kind": spec.kind}
            self.fired.append(rec)
        from sketch_rnn_tpu.utils.telemetry import get_telemetry, \
            site_series
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("faults_injected", 1.0, cat="faults")
            tel.counter(site_series("faults_injected", site), 1.0,
                        cat="faults")
        return rec

    def hit(self, site: str) -> None:
        """One invocation of a raising site: no-op, raise, or hard-exit
        per the due spec. ``kind=nan`` specs never fire here — a value
        site and a raising site with the same name would double-count
        otherwise."""
        spec = self.plan.get(site)
        if spec is not None and spec.kind == "nan":
            return
        rec = self._step(site)
        if rec is None:
            return
        if rec["kind"] == "exit":
            # the genuine crash: no finally blocks, no exception
            # handlers, no atexit — what kill -9 / a preemption does
            os._exit(EXIT_CODE)
        raise InjectedFault(site, rec["invocation"])

    def corrupt(self, site: str, value: float) -> float:
        """One invocation of a value site: returns ``value`` or NaN."""
        spec = self.plan.get(site)
        if spec is None or spec.kind != "nan":
            return value
        return float("nan") if self._step(site) is not None else value

    def count(self, site: str) -> int:
        with self._lock:
            return self._counts.get(site, 0)

    def summary(self) -> Dict:
        """The evidence block: seed, plan, per-site invocation counts
        and the exact fired log (incident.json / RESILIENCE.json)."""
        with self._lock:
            return {
                "seed": self.seed,
                "plan": {s: {k: v for k, v in dataclasses.asdict(
                    spec).items() if v is not None and k != "site"}
                    for s, spec in sorted(self.plan.items())},
                "counts": dict(sorted(self._counts.items())),
                "fired": list(self.fired),
            }

    def __repr__(self) -> str:
        return (f"FaultInjector(seed={self.seed}, "
                f"sites={sorted(self.plan)}, fired={len(self.fired)})")


# the process-wide injector; None = chaos layer off (the default)
_INJECTOR: Optional[FaultInjector] = None


def configure(plan, seed: int = 0) -> FaultInjector:
    """Arm the process-wide injector with ``plan`` (a spec string or a
    parsed ``{site: FaultSpec}``); replaces any previous one."""
    global _INJECTOR
    if isinstance(plan, str):
        plan = parse_plan(plan)
    _INJECTOR = FaultInjector(plan, seed=seed)
    return _INJECTOR


def disable() -> None:
    """Disarm (the process default; the conftest guard restores it)."""
    global _INJECTOR
    _INJECTOR = None


def get_injector() -> Optional[FaultInjector]:
    return _INJECTOR


def fault_point(site: str) -> None:
    """THE raising fault site. One global read when disarmed."""
    inj = _INJECTOR
    if inj is not None:
        inj.hit(site)


def corrupt_value(site: str, value: float) -> float:
    """THE value-corruption site. One global read when disarmed."""
    inj = _INJECTOR
    if inj is None:
        return value
    return inj.corrupt(site, value)


def backoff_s(base_s: float, attempt: int, cap_s: float = 2.0) -> float:
    """Deterministic exponential backoff: ``min(cap, base * 2**attempt)``
    — a pure function of the attempt index, so recovery cost is a
    schedule, not a wall-clock accident."""
    if base_s <= 0:
        return 0.0
    return min(cap_s, base_s * (2.0 ** attempt))


def retry_call(fn: Callable, retries: int, backoff_base_s: float = 0.0,
               describe: str = "operation",
               counter: Optional[str] = None):
    """Call ``fn()`` with up to ``retries`` bounded retries.

    Transient = any ``Exception`` (and :class:`InjectedFault`, which
    subclasses RuntimeError — injected transients exercise exactly the
    real path); ``BaseException`` (KeyboardInterrupt, SystemExit)
    passes through. The final failure re-raises the LAST error, so a
    permanent fault still stops the caller loudly. Each retry sleeps
    the deterministic :func:`backoff_s` schedule and ticks the
    ``counter`` telemetry series (cat ``faults``) when given.
    """
    if retries < 0:
        raise ValueError(f"retries must be >= 0, got {retries}")
    last: Optional[Exception] = None
    for attempt in range(retries + 1):
        if attempt:
            time.sleep(backoff_s(backoff_base_s, attempt - 1))
            from sketch_rnn_tpu.utils.telemetry import get_telemetry
            tel = get_telemetry()
            if tel.enabled and counter:
                tel.counter(counter, 1.0, cat="faults")
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — transient by contract
            last = e
            if attempt >= retries:
                raise
            print(f"[faults] WARNING: {describe} failed "
                  f"(attempt {attempt + 1}/{retries + 1}): {e!r}; "
                  f"retrying in {backoff_s(backoff_base_s, attempt):.2f}s",
                  flush=True)
    raise last  # unreachable; keeps type checkers honest
