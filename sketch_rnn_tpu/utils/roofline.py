"""Per-phase analytic roofline for the fused RNN kernels.

VERDICT r3 #1: the claim "MFU 0.27-0.30 is the structural ceiling on
v5e" rested on three closed probe negatives, not arithmetic. This
module is the arithmetic half of the reconciliation: for the encoder
(``fused_lstm_seq`` x2 directions) and decoder (``fused_ln_lstm`` with
x_bias) phases it derives, from the SAME tile functions the kernels
use,

- the grid geometry (steps, batch tiles),
- the per-grid-step matmul set and its MXU time under a padded-pass
  model (operands are padded to the 128x128 systolic tile, so a
  ``[bt, 5] @ [5, 4H]`` input projection costs a full K=128 pass),
- the whole-phase HBM bytes (residual streams at ``residual_dtype``,
  cotangents at the primal dtype, weight grads).

``scripts/roofline.py`` supplies the measured half (scan replicas of
the per-step compute split into matmul-only / gates-only arms, the
standalone kernels, and an HBM stream anchor) and prints the
reconciliation table recorded in ARCHITECTURE.md. Keeping the
arithmetic importable and pure lets tests pin the geometry on CPU —
if a tile function or kernel shape changes, the model changes with it
or the tests fail.

SURVEY.md §2 component 5 (the performance core); no reference
file:line cites are possible (the /root/reference mount is empty —
see SURVEY.md provenance header).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

from sketch_rnn_tpu.config import HParams

MXU_LANE = 128  # systolic array edge: K and N pad to this
MXU_SUBLANE = 8  # M (the streaming dim) packs in sublanes of 8


def _ceil_to(x: int, q: int) -> int:
    return -(-x // q) * q


@dataclass(frozen=True)
class Matmul:
    """One ``[m, k] @ [k, n]`` inside a grid step."""
    m: int
    k: int
    n: int

    @property
    def flops(self) -> int:
        return 2 * self.m * self.k * self.n

    @property
    def padded_flops(self) -> int:
        """FLOP-equivalents the MXU actually spends: K and N rounded up
        to the 128 systolic edge (a K=5 input projection burns a full
        K=128 pass), M to the 8-sublane pack."""
        return (2 * _ceil_to(self.m, MXU_SUBLANE)
                * _ceil_to(self.k, MXU_LANE) * _ceil_to(self.n, MXU_LANE))


@dataclass(frozen=True)
class PhaseGeometry:
    """Grid + arithmetic model of one kernel phase (all directions)."""
    name: str
    directions: int
    seq_len: int
    batch: int
    hidden: int
    tile_fwd: int
    tile_bwd: int
    mm_fwd: Tuple[Matmul, ...]   # per fwd grid step
    mm_bwd: Tuple[Matmul, ...]   # per bwd grid step
    hbm_bytes_fwd: int           # whole phase, all directions
    hbm_bytes_bwd: int

    @property
    def grid_fwd(self) -> int:
        """Total fwd grid steps across directions."""
        return self.directions * self.seq_len * (self.batch // self.tile_fwd)

    @property
    def grid_bwd(self) -> int:
        return self.directions * self.seq_len * (self.batch // self.tile_bwd)

    def mxu_seconds(self, peak_flops: float) -> Tuple[float, float]:
        """(fwd, bwd) MXU-ideal seconds under the padded-pass model."""
        fwd = self.grid_fwd * sum(m.padded_flops for m in self.mm_fwd)
        bwd = self.grid_bwd * sum(m.padded_flops for m in self.mm_bwd)
        return fwd / peak_flops, bwd / peak_flops

    def hbm_seconds(self, gbytes_per_s: float) -> Tuple[float, float]:
        return (self.hbm_bytes_fwd / (gbytes_per_s * 1e9),
                self.hbm_bytes_bwd / (gbytes_per_s * 1e9))


def _dtype_bytes(name: str) -> int:
    return 2 if name == "bfloat16" else 4


def encoder_geometry(hps: HParams) -> PhaseGeometry:
    """``fused_lstm_seq`` x2 directions (the bidirectional encoder).

    Backward recomputes both forward matmuls, then runs the three grad
    matmuls (dwx, d_pre @ wh.T, dwh); there are no dxs / carry-grad
    outputs (the seq kernel's contract). Residuals hs+cs are stored at
    ``fused_residual_dtype``; the incoming cotangent dhs matches the
    (rounded) primal dtype; xs is the compute-dtype stroke tensor.
    """
    from sketch_rnn_tpu.ops.pallas_fused import _batch_tile_seq

    h, d, t, b = hps.enc_rnn_size, 5, hps.max_seq_len, hps.batch_size
    bt = _batch_tile_seq(b, h)
    rb = _dtype_bytes(hps.fused_residual_dtype)
    xb_ = _dtype_bytes(hps.compute_dtype)
    mm_fwd = (Matmul(bt, d, 4 * h), Matmul(bt, h, 4 * h))
    mm_bwd = mm_fwd + (
        Matmul(d, bt, 4 * h),     # dwx  = x.T @ d_pre
        Matmul(bt, 4 * h, h),     # dh   = d_pre @ wh.T
        Matmul(h, bt, 4 * h),     # dwh  = h_prev.T @ d_pre
    )
    dirs = 2
    fwd_bytes = dirs * t * b * (d * xb_ + 2 * h * rb)          # xs in, hs+cs out
    bwd_bytes = dirs * t * b * (d * xb_ + 3 * h * rb)          # xs, cs, h_prev, dhs
    return PhaseGeometry("encoder", dirs, t, b, h, bt, bt,
                         mm_fwd, mm_bwd, fwd_bytes, bwd_bytes)


def decoder_geometry(hps: HParams) -> PhaseGeometry:
    """``fused_ln_lstm`` with the x_bias path (flagship decoder).

    The backward tile halves (x_bias adds two [bt, 4H] f32 blocks to
    the backward's VMEM budget — see ``_batch_tile``), so the bwd grid
    has twice the steps at half the M. Backward additionally writes the
    dxs stream in f32 (the kernel's dx output) and the dxb block.
    """
    from sketch_rnn_tpu.ops.pallas_fused import _batch_tile

    h, d, t, b = hps.dec_rnn_size, 5, hps.max_seq_len, hps.batch_size
    bt_f = _batch_tile(b, h)
    bt_b = _batch_tile(b, h, xb_bwd=True)
    rb = _dtype_bytes(hps.fused_residual_dtype)
    xb_ = _dtype_bytes(hps.compute_dtype)
    mm_fwd = (Matmul(bt_f, d, 4 * h), Matmul(bt_f, h, 4 * h))
    mm_bwd = (
        Matmul(bt_b, d, 4 * h), Matmul(bt_b, h, 4 * h),  # recompute
        Matmul(bt_b, 4 * h, d),   # dx   = d_pre @ wx.T
        Matmul(d, bt_b, 4 * h),   # dwx  = x.T @ d_pre
        Matmul(bt_b, 4 * h, h),   # dh   = d_pre @ wh.T
        Matmul(h, bt_b, 4 * h),   # dwh  = h_prev.T @ d_pre
    )
    fwd_bytes = (t * b * (d * xb_ + 2 * h * rb)   # xs in, hs+cs out
                 + b * 4 * h * 4                  # x_bias read (once per tile pass)
                 + 2 * b * h * 4)                 # cT, hT out (f32)
    bwd_bytes = (t * b * (d * xb_ + 3 * h * rb)   # xs, cs, h_prev, dhs
                 + t * b * d * 4                  # dxs out (f32)
                 + 2 * b * 4 * h * 4)             # x_bias read + dxb out
    return PhaseGeometry("decoder", 1, t, b, h, bt_f, bt_b,
                         mm_fwd, mm_bwd, fwd_bytes, bwd_bytes)
