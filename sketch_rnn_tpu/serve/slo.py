"""Per-endpoint latency SLOs with rolling error-budget burn rates.

ISSUE 7's live-SLO layer: the ROADMAP's "mesh-sharded serving fleet
with SLA-aware admission" needs a signal an admission controller can
act on — not a post-hoc percentile table but a LIVE answer to "is this
endpoint inside its latency objective, and how fast is it spending its
error budget?" (the Gemma-on-TPU serving comparison in PAPERS.md is the
template for which numbers a serving stack must report).

The vocabulary is the standard SRE one:

- An :class:`SLO` is a quantile-style latency objective — "``target``
  fraction of requests must complete within ``objective_s``" (p95 <=
  250 ms is ``target=0.95, objective_s=0.25``). A request over the
  objective is a *breach*.
- The *error budget* is the allowed breach fraction, ``1 - target``.
- The *burn rate* is the observed breach fraction divided by the
  allowed one: 1.0 means breaching exactly at budget, > 1.0 means the
  budget is being spent faster than the objective allows (page-worthy),
  0.0 means no breaches.

:class:`SLOTracker` is fed one observation per completed request
(``ServeEngine.run(..., slo=...)`` wires this) and maintains, per SLO,
exact monotonic totals plus a bounded rolling window (count-based, so
results are deterministic for a deterministic request stream — no wall
clock in the math). It is surfaced in three places: the ``/metrics``
endpoint (serve/metrics_http.py), ``/healthz``'s degraded verdict, and
``serve_bench``'s end-of-run summary.
"""

from __future__ import annotations

import dataclasses
import re
import threading
from collections import deque
from typing import Dict, List, Sequence

DEFAULT_ENDPOINT = "generate"
DEFAULT_METRIC = "latency_s"
# the latency fields a completed Result carries — what the engine's
# observe() feed can ever populate. parse_slo closes over this set: a
# typo'd metric would otherwise track nothing and report vacuous
# compliance forever.
RESULT_METRICS = ("latency_s", "queue_wait_s", "decode_s")
# endpoint names land inside Prometheus label values: restrict to
# identifier-ish charsets so a spec cannot break the exposition text
_NAME_OK = re.compile(r"^[A-Za-z_][A-Za-z0-9_.-]*$")


@dataclasses.dataclass(frozen=True)
class SLO:
    """One latency objective: ``target`` fraction of ``endpoint``'s
    requests must have ``metric`` <= ``objective_s`` seconds."""

    objective_s: float
    target: float = 0.95
    endpoint: str = DEFAULT_ENDPOINT
    metric: str = DEFAULT_METRIC

    def __post_init__(self):
        if self.objective_s <= 0:
            raise ValueError(
                f"objective_s must be > 0, got {self.objective_s}")
        if not 0.0 < self.target <= 1.0:
            raise ValueError(f"target must be in (0, 1], got {self.target}")

    @property
    def key(self) -> str:
        """Stable identity for summaries/metric labels, e.g.
        ``generate:latency_s:p95``."""
        return (f"{self.endpoint}:{self.metric}:"
                f"p{self.target * 100:g}")

    @property
    def budget(self) -> float:
        """Allowed breach fraction (0 for a p100 objective)."""
        return 1.0 - self.target


def parse_slo(spec: str) -> SLO:
    """Parse an ``--slo`` spec string into an :class:`SLO`.

    Grammar: ``[endpoint:[metric:]]pNN<=VALUE`` where VALUE is seconds
    (or ``<number>ms``). Examples::

        p95<=0.25                      # generate latency_s p95 <= 250ms
        p99<=400ms
        generate:p95<=0.25
        generate:decode_s:p99<=0.1
    """
    if "<=" not in spec:
        raise ValueError(
            f"bad SLO spec {spec!r}: want [endpoint:[metric:]]pNN<=SECONDS"
            f" (e.g. 'p95<=0.25' or 'generate:decode_s:p99<=100ms')")
    left, _, right = spec.partition("<=")
    right = right.strip()
    try:
        if right.endswith("ms"):
            objective = float(right[:-2]) / 1e3
        else:
            objective = float(right)
    except ValueError:
        raise ValueError(f"bad SLO objective {right!r} in {spec!r}: want "
                         f"seconds (float) or '<number>ms'") from None
    parts = [p.strip() for p in left.strip().split(":")]
    quant = parts[-1]
    if not quant.startswith("p"):
        raise ValueError(f"bad SLO quantile {quant!r} in {spec!r}: want "
                         f"pNN (e.g. p95)")
    try:
        target = float(quant[1:]) / 100.0
    except ValueError:
        raise ValueError(
            f"bad SLO quantile {quant!r} in {spec!r}") from None
    endpoint = parts[0] if len(parts) >= 2 else DEFAULT_ENDPOINT
    metric = parts[1] if len(parts) == 3 else DEFAULT_METRIC
    if len(parts) > 3:
        raise ValueError(f"bad SLO spec {spec!r}: too many ':' segments")
    if not _NAME_OK.match(endpoint):
        raise ValueError(
            f"bad SLO endpoint {endpoint!r} in {spec!r}: want an "
            f"identifier ([A-Za-z_][A-Za-z0-9_.-]*) — it becomes a "
            f"Prometheus label value")
    if metric not in RESULT_METRICS:
        raise ValueError(
            f"bad SLO metric {metric!r} in {spec!r}: must be one of "
            f"{RESULT_METRICS} (the latency fields a completed request "
            f"reports) — anything else would track nothing and report "
            f"vacuous compliance")
    return SLO(objective_s=objective, target=target, endpoint=endpoint,
               metric=metric)


class SLOTracker:
    """Feed per-request latencies, read compliance + burn rates.

    Thread-safe: the engine's collect path observes while the metrics
    endpoint's scrape thread summarizes. ``window`` bounds the rolling
    burn-rate window in REQUESTS (deterministic, unlike a wall-clock
    window); totals are exact and unbounded. ``min_requests`` gates the
    health verdict — a handful of warmup requests must not flip
    ``/healthz`` to degraded.
    """

    def __init__(self, slos: Sequence[SLO], window: int = 256,
                 min_requests: int = 8):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._lock = threading.Lock()
        self._slos: List[SLO] = list(slos)
        seen = set()
        for s in self._slos:
            if s.key in seen:
                raise ValueError(f"duplicate SLO {s.key}")
            seen.add(s.key)
        self.min_requests = min_requests
        self._state: Dict[str, dict] = {
            s.key: {"slo": s, "total": 0, "breaches": 0,
                    "window": deque(maxlen=window)}
            for s in self._slos
        }

    @property
    def slos(self) -> List[SLO]:
        return list(self._slos)

    def observe(self, endpoint: str, values: Dict[str, float]) -> None:
        """Record one completed request on ``endpoint``; ``values`` maps
        metric name -> seconds (a Result's latency fields). SLOs whose
        metric is absent from ``values`` are skipped."""
        with self._lock:
            for st in self._state.values():
                slo = st["slo"]
                if slo.endpoint != endpoint:
                    continue
                v = values.get(slo.metric)
                if v is None:
                    continue
                breach = float(v) > slo.objective_s
                st["total"] += 1
                st["breaches"] += int(breach)
                st["window"].append(breach)

    @staticmethod
    def _burn(breaches: int, total: int, budget: float) -> float:
        """Breach fraction over the allowed fraction; a zero-budget
        (p100) objective burns infinitely on any breach, 0.0 otherwise."""
        if total == 0:
            return 0.0
        frac = breaches / total
        if budget <= 0.0:
            return float("inf") if frac > 0 else 0.0
        return frac / budget

    def summary(self) -> Dict[str, Dict]:
        """Per-SLO state: exact totals, compliance, window + total burn
        rates, and the ``met`` verdict (compliance >= target so far)."""
        out: Dict[str, Dict] = {}
        with self._lock:
            for key, st in self._state.items():
                slo, total = st["slo"], st["total"]
                breaches = st["breaches"]
                win = st["window"]
                wb = sum(win)
                compliance = 1.0 - breaches / total if total else 1.0
                out[key] = {
                    "endpoint": slo.endpoint,
                    "metric": slo.metric,
                    "objective_s": slo.objective_s,
                    "target": slo.target,
                    "total": total,
                    "breaches": breaches,
                    "compliance": round(compliance, 6),
                    "met": compliance >= slo.target,
                    "burn_rate": round(
                        self._burn(wb, len(win), slo.budget), 4),
                    "burn_rate_total": round(
                        self._burn(breaches, total, slo.budget), 4),
                    "window_n": len(win),
                }
        return out

    def healthy(self) -> bool:
        """False once any SLO with >= ``min_requests`` observations is
        out of compliance — the ``/healthz`` degraded signal."""
        return not any(
            not rec["met"] and rec["total"] >= self.min_requests
            for rec in self.summary().values())
