"""Deterministic content-addressed result cache (+ in-flight coalescing).

ISSUE 12 tentpole piece 1. Generation in this repo is a PURE function
of (config_hash, params checkpoint, key, z, label, temperature,
max_len) — the determinism contract every invariance suite pins — so
two requests with identical content MUST produce bitwise-identical
strokes, and the second one need not touch a device at all. This
module is that observation turned into a serving layer in front of
admission (serve/fleet.py consults it before placing a request):

- **Content addressing.** :func:`request_fingerprint` hashes the
  request's *content* fields — raw PRNG key data, z bytes, label,
  temperature, max_len — plus the cache's ``(config_hash, ckpt_id)``
  namespace with blake2b. Scheduling metadata (uid, class, queue
  position, enqueue time, retry attempt) is deliberately EXCLUDED:
  it changes WHEN a sketch is computed, never WHAT (the engine's
  documented contract), so it must not fragment the keyspace. Two
  different checkpoints (or configs) can never collide: their bytes
  are inside the hash.
- **Bounded LRU.** ``max_entries`` / ``max_bytes`` bound the store;
  eviction order is pure LRU over the get/put sequence, so for a
  deterministic request stream the hit/miss/evict sequence is itself
  deterministic (tier-1-tested). The cache keeps EXACT internal
  counters (hits / misses / evictions / bytes / coalesced) independent
  of telemetry — the telemetry core, when enabled, mirrors them as
  ``cache_hit`` / ``cache_miss`` / ``cache_evict`` counters and the
  ``cache_bytes`` gauge (cat ``serve``), which the ``/metrics``
  endpoint renders as ``sketch_rnn_serve_cache_*`` series for free.
- **Hits are the stored Result, bitwise.** A hit returns the stored
  strokes (marked ``cached=True`` on the Result the fleet builds) and
  remembers the ORIGINAL computation's uid, so the hit's fresh trace
  span links back to the origin request's trace_id — a cached
  request's tree explains where its bytes came from. The traffic
  bench proves hits bitwise equal to recomputation in-run.
- **In-flight coalescing.** A repeat arriving while its content is
  still being computed must not compute twice: the fleet registers it
  as a WAITER on the pending fingerprint and fans the result out at
  completion. This is what makes the cache's device-step savings a
  deterministic function of the trace (misses == distinct contents),
  not a race between completion and repetition.

The cache itself is pure host-side state with one lock (the fleet
calls it under its scheduler lock already, but a bare engine or a test
may not) and never imports jax.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from sketch_rnn_tpu.utils.telemetry import get_telemetry


def _hash_prefix(h, arr) -> None:
    """Hash one stroke prefix with its shape as a delimiter: two
    prefixes whose concatenated bytes agree but whose row splits differ
    can never collide."""
    a = np.asarray(arr, np.float32)
    h.update(f"<{a.shape}>".encode())
    h.update(a.tobytes())


def request_fingerprint(req, config_hash: str = "",
                        ckpt_id: str = "") -> bytes:
    """blake2b digest of the request CONTENT + the model namespace.

    Content = everything the strokes may depend on (the engine's
    determinism contract): raw PRNG key data, z, label, temperature,
    max_len — plus, for multi-task requests (ISSUE 15), the endpoint
    name, the prefix bytes (both sketches for interpolate, order-
    sensitive) and the frame count. A plain generate request hashes
    EXACTLY the pre-endpoint byte stream, so every fingerprint minted
    before this PR is unchanged (no cold-cache regression), while two
    endpoints can never collide on shared content: the endpoint tag is
    inside the hash. The endpoint-DERIVED decode state (z stamped by
    the planner, init_carry/init_prev) is deliberately NOT hashed for
    encoder endpoints — it is a pure function of (prefix, params), and
    hashing it would make the fingerprint depend on WHEN the planner
    ran. ``config_hash`` (the RUN.json HParams hash) and ``ckpt_id``
    (which params checkpoint is serving) namespace the keyspace so
    different models can never collide. uid/class/queue metadata never
    enter the hash — scheduling cannot fragment it.
    """
    import jax  # lazy: the serve-module discipline

    h = hashlib.blake2b(digest_size=16)
    h.update(config_hash.encode())
    h.update(b"\x00")
    h.update(ckpt_id.encode())
    h.update(b"\x00")
    key_data = np.asarray(jax.random.key_data(req.key))
    h.update(str(key_data.dtype).encode() + b"|")
    h.update(key_data.tobytes())
    endpoint = getattr(req, "endpoint", "generate") or "generate"
    prefix = getattr(req, "prefix", None)
    if endpoint == "generate" and prefix is None:
        if req.z is None:
            h.update(b"z:none")
        else:
            z = np.asarray(req.z, np.float32)
            h.update(z.tobytes())
    else:
        # the multi-task arm of the keyspace: the tag byte cannot
        # appear in the legacy stream's position (legacy continues
        # with z bytes or the literal b"z:none"), so old and new
        # fingerprints live in disjoint domains
        h.update(b"\x01ep:" + endpoint.encode() + b"\x00")
        if endpoint == "interpolate":
            a, b = prefix
            _hash_prefix(h, a)
            _hash_prefix(h, b)
            h.update(f"|frames:{int(getattr(req, 'frames', 0) or 0)}"
                     .encode())
        else:
            _hash_prefix(h, prefix)
    h.update(f"|{int(req.label)}|{float(req.temperature)!r}|"
             f"{req.max_len}".encode())
    return h.digest()


class CacheEntry:
    """One stored completion: the strokes plus origin metadata for the
    hit path's trace link. Multi-task results (ISSUE 15) also carry
    their endpoint and — for interpolations — the per-frame stroke
    arrays; the frames are COPIES of the concatenated buffer (the
    assembler builds ``strokes5`` with np.concatenate), so ``nbytes``
    counts both and the byte bound stays honest."""

    __slots__ = ("strokes5", "length", "steps", "origin_uid", "nbytes",
                 "endpoint", "frames", "ckpt_id")

    def __init__(self, strokes5: np.ndarray, length: int, steps: int,
                 origin_uid: int, endpoint: str = "generate",
                 frames=None, ckpt_id: str = ""):
        self.strokes5 = strokes5
        self.length = int(length)
        self.steps = int(steps)
        self.origin_uid = int(origin_uid)
        self.nbytes = int(strokes5.nbytes) + (
            0 if frames is None else sum(int(f.nbytes) for f in frames))
        self.endpoint = endpoint or "generate"
        self.frames = frames
        # which params checkpoint computed these strokes (ISSUE 16):
        # stamped from the producing Result so a hit re-serves its
        # origin's version label, never the fleet's current one
        self.ckpt_id = str(ckpt_id or "")


class ResultCache:
    """Bounded-LRU content-addressed store of completed Results.

    ``max_entries`` and ``max_bytes`` both bound the store (0 =
    unbounded on that axis); eviction pops the least-recently-used
    entry until both bounds hold. ``get`` refreshes recency; ``put``
    inserts most-recent. A ``put`` whose fingerprint is already stored
    keeps the FIRST entry (determinism makes them bitwise-equal
    anyway, and keep-first means a failover re-serve cannot churn the
    LRU order).
    """

    def __init__(self, config_hash: str = "", ckpt_id: str = "",
                 max_entries: int = 4096, max_bytes: int = 0):
        if max_entries < 0 or max_bytes < 0:
            raise ValueError(
                f"bounds must be >= 0, got max_entries={max_entries} "
                f"max_bytes={max_bytes}")
        self.config_hash = str(config_hash or "")
        self.ckpt_id = str(ckpt_id or "")
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._store: "OrderedDict[bytes, CacheEntry]" = OrderedDict()
        self._bytes = 0
        # exact counters, telemetry-independent (the ledger-as-view
        # discipline: telemetry mirrors these when enabled)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.coalesced = 0

    def fingerprint(self, req, ckpt_id: Optional[str] = None) -> bytes:
        """Fingerprint under this cache's namespace. ``ckpt_id``
        overrides the constructor-time version label — the rollout path
        (ISSUE 16) fingerprints against the fleet's CURRENT serving
        version, which changes over the cache's lifetime, so a v1 hit
        can never answer a v2 request."""
        return request_fingerprint(
            req, self.config_hash,
            self.ckpt_id if ckpt_id is None else ckpt_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def get(self, fp: bytes) -> Optional[CacheEntry]:
        """Lookup + LRU refresh; ticks hit/miss exactly (and mirrors
        into telemetry when enabled)."""
        tel = get_telemetry()
        with self._lock:
            entry = self._store.get(fp)
            if entry is None:
                self.misses += 1
            else:
                self._store.move_to_end(fp)
                self.hits += 1
        if tel.enabled:
            tel.counter("cache_hit" if entry is not None else
                        "cache_miss", 1.0, cat="serve")
        return entry

    def note_coalesced(self) -> None:
        """A repeat attached to an in-flight computation (the fleet's
        waiter path): no device work, but not a store lookup hit."""
        with self._lock:
            self.coalesced += 1
        tel = get_telemetry()
        if tel.enabled:
            tel.counter("cache_coalesced", 1.0, cat="serve")

    def put(self, fp: bytes, result) -> None:
        """Insert one completed Result's strokes (keep-first on
        duplicate fingerprints), then evict LRU until bounds hold."""
        entry = CacheEntry(result.strokes5, result.length, result.steps,
                           result.uid,
                           endpoint=getattr(result, "endpoint",
                                            "generate"),
                           frames=getattr(result, "frames", None),
                           ckpt_id=getattr(result, "ckpt_id", ""))
        evicted = 0
        tel = get_telemetry()
        with self._lock:
            if fp in self._store:
                return
            if self.max_entries == 0 and self.max_bytes == 0:
                pass  # unbounded
            self._store[fp] = entry
            self._bytes += entry.nbytes
            while ((self.max_entries and
                    len(self._store) > self.max_entries)
                   or (self.max_bytes and self._bytes > self.max_bytes
                       and len(self._store) > 1)):
                _, old = self._store.popitem(last=False)
                self._bytes -= old.nbytes
                self.evictions += 1
                evicted += 1
            total_bytes = self._bytes
        if tel.enabled:
            if evicted:
                tel.counter("cache_evict", float(evicted), cat="serve")
            tel.gauge("cache_bytes", float(total_bytes), cat="serve")

    def stats(self) -> Dict[str, Any]:
        """Exact counters for summaries / bench rows. Every arrival
        does exactly one :meth:`get` (lookups = hits + misses); a
        coalesced repeat ticked a miss there and then attached to the
        in-flight computation, so ``hit_rate`` — the fraction of
        arrivals served WITHOUT device work, the number the traffic
        bench reports — is (hits + coalesced) / lookups."""
        with self._lock:
            lookups = self.hits + self.misses
            served = self.hits + self.coalesced
            return {
                "entries": len(self._store),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "coalesced": self.coalesced,
                "evictions": self.evictions,
                "lookups": lookups,
                "hit_rate": round(served / max(lookups, 1), 4),
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "config_hash": self.config_hash,
                "ckpt_id": self.ckpt_id,
            }

    def keys(self) -> List[bytes]:
        """LRU order, least-recent first (tests pin eviction order)."""
        with self._lock:
            return list(self._store)

    def clear(self) -> None:
        """Drop entries AND counters (bench arms reset between runs)."""
        with self._lock:
            self._store.clear()
            self._bytes = 0
            self.hits = self.misses = 0
            self.evictions = self.coalesced = 0

    def __repr__(self) -> str:
        s = self.stats()
        return (f"ResultCache({s['entries']} entries, {s['bytes']}B, "
                f"hit_rate {s['hit_rate']}, ckpt={self.ckpt_id!r})")
