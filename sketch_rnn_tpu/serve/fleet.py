"""Mesh-replicated serving fleet: per-device engines behind one scheduler.

ISSUE 9 tentpole. The continuous-batching engine (serve/engine.py) is a
single-device program; the ROADMAP's north star is serving heavy
traffic, and the paper's decoder is tiny per-request — so fleet
throughput is a SCHEDULING problem (the Gemma-on-TPU comparison in
PAPERS.md), solved here with the same collective-free replication the
mesh-sharded sampler proved (pjit/TPUv4 scaling paper: below the
model-parallel threshold, independent per-device programs beat any
cross-device collective):

- **One replica per mesh device.** Each replica is a full
  :class:`~sketch_rnn_tpu.serve.engine.ServeEngine` pinned to its
  device (params, request pool and loop state all committed there), so
  R replicas run R independent chunk programs with ZERO cross-device
  communication — scaling is bounded by devices, not interconnect.
- **One host-side scheduler.** ``submit()`` stamps arrival time and
  admission class, asks the :class:`~sketch_rnn_tpu.serve.admission.
  AdmissionController` for a placement (least-loaded replica queue, or
  shed-on-overload), and wakes that replica's worker thread. Workers
  drain their queues in class-priority order into fixed-size
  **micro-bursts**: up to ``pool_cap`` requests served through one
  ``engine.run(..., pool_pad=pool_cap)`` call, so every burst of any
  size reuses the replica's single compiled program (the chunk program
  is shape-specialized on pool size). Burst size adapts to load —
  light traffic gets small low-latency bursts, heavy traffic amortizes
  full pools.
- **Placement is provably invisible to outputs.** The engine's
  per-request ``fold_in(request_key, t)`` RNG makes strokes a pure
  function of the request; the scheduler only ever chooses WHERE and
  WHEN. The invariance suite pins bitwise-identical strokes at 1, 2
  and 4 replicas and under shuffled arrival order.

Telemetry (wired through the PR 6-8 core, all off-by-default): each
replica's engine records its own ``slots_live_rNN`` occupancy gauge
(trace_report.py renders a per-replica timeline), completions feed
per-class latency histograms and the admission metadata on every
``complete`` event, and the scheduler counts
``requests_admitted_total`` / ``requests_shed_total`` (+ per-class) —
all scrapeable live via ``serve/metrics_http.py``'s ``/metrics`` +
``/healthz`` when a server is attached.

- **Failover (ISSUE 10).** A replica whose burst fails — injected via
  the ``fleet.worker.rNN`` fault site (utils/faults.py) or real — is
  marked dead instead of killing the fleet: its queued and in-flight
  requests are requeued to the survivors under a bounded per-request
  ``retry_budget`` with deterministic exponential backoff, the
  admission controller shrinks to the surviving capacity
  (``mark_dead``), ``drain()`` completes against the survivors, and
  ``health()`` feeds ``/healthz`` a ``degraded`` verdict. Because
  placement is invisible to outputs (above), a retried request's
  strokes are BITWISE identical to the no-fault run's — the chaos
  parity pin in tests/test_fleet.py. Only the death of the last
  replica (or an exhausted retry budget, recorded per request in
  ``failed``) surfaces as a failure.

- **Traffic shaping (ISSUE 12).** The fleet is now ELASTIC and cached:
  ``max_replicas`` pre-builds (and ``warm`` pre-compiles) spare
  replicas that start RETIRED — out of the placement set, no worker
  thread — and ``add_replica()`` / ``retire_replica()`` move the live
  set at runtime on the failover primitives (retire = drain + leave
  placement, exactly the graceful half of ``mark_dead``; spawn = the
  rejoin path). ``serve/autoscale.py`` decides when; every action
  lands in ``scale_log``, a ``replica_spawn``/``replica_retire`` span
  and the ``fleet_replicas`` gauge. A :class:`~sketch_rnn_tpu.serve.
  cache.ResultCache` attached as ``cache`` is consulted in ``submit``
  BEFORE admission: a content hit is served at the door (bitwise the
  original strokes, ``cached=True``, zero device steps) with a fresh
  trace span linking the ORIGIN computation's trace_id, and a repeat
  arriving while its content is still in flight coalesces onto the
  pending computation instead of computing twice — so cache savings
  are a deterministic function of the request stream, not a race.
  ``/healthz`` reports ``scaling`` while a retire is still draining
  (an intentional resize must not read as degradation).

- **Multi-tenant paging (ISSUE 19).** A :class:`~sketch_rnn_tpu.serve.
  tenants.TenantStore` attached as ``tenants`` turns the fleet
  multi-tenant: every engine is built in VALUE-PAGED mode (params are
  traced arguments, not baked constants — serve/engine.py), so a
  worker flips its replica to a burst's tenant with a pure value swap
  that never compiles. Bursts are single-tenant (``pop_batch`` stops
  at a tenant boundary, like the capacity stop), admission charges
  each request to its tenant's fair share (``tenant_cap`` rows
  fleet-wide; over-share requests shed with reason ``tenant_cap``
  even when the fleet has room), the result cache fingerprints under
  ``tenants.ckpt_id_of(tenant)`` so tenants can never collide on
  byte-identical content, per-tenant SLOs (``tenant_slos``) are judged
  by per-tenant trackers, and a fleet-shared
  :class:`~sketch_rnn_tpu.serve.tenants.PrefixReuseIndex` in front of
  the encode planner makes encode computes == distinct
  (tenant, prefix, edge) exactly. Placement stays invisible to
  outputs: a tenant's strokes are a pure function of (request content,
  that tenant's materialized params), pinned bitwise against
  single-tenant reference fleets.

Every started fleet registers process-wide so the tier-1 conftest
guard can prove no test leaks worker threads (:func:`stop_all`).
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.serve.admission import (
    AdmissionClass,
    AdmissionController,
    DEFAULT_CLASS,
    parse_admission_classes,
)
from sketch_rnn_tpu.serve.engine import Request, Result, ServeEngine
from sketch_rnn_tpu.serve import endpoints as endpoints_mod
from sketch_rnn_tpu.runtime.scheduler import default_scheduler
from sketch_rnn_tpu.serve.slo import SLOTracker
from sketch_rnn_tpu.serve.tenants import PrefixReuseIndex
from sketch_rnn_tpu.utils.faults import backoff_s, fault_point
from sketch_rnn_tpu.utils.telemetry import (
    class_series,
    critical_path_segments,
    endpoint_series,
    get_telemetry,
    request_span_id,
    request_trace_id,
    span_link,
    suppressed as telemetry_suppressed,
    tail_attribution,
    tenant_series,
)

# every live fleet, for the conftest no-stray-threads guard
_LIVE: set = set()
_LIVE_LOCK = threading.Lock()


def default_pool_cap(slots: int) -> int:
    """The fleet's micro-burst ceiling when none is configured: 4x the
    slot width (amortizes per-burst fixed costs at saturation while
    keeping light-traffic bursts small — see ServeFleet.__init__).
    ONE home for the factor: pre-restore CLI checks (does an
    interpolation's frame grid fit one burst?) and the fleet itself
    must never disagree about it."""
    return 4 * int(slots)


class _Replica:
    """One device's engine + its per-class queues (scheduler-owned)."""

    def __init__(self, idx: int, device, engine: ServeEngine,
                 class_order: Sequence[str]):
        self.idx = idx
        self.device = device
        self.engine = engine
        # drained in priority order (the scheduler's class_order is
        # already priority-sorted)
        self.queues: Dict[str, deque] = {c: deque() for c in class_order}
        self.cond: Optional[threading.Condition] = None  # set by fleet
        self.thread: Optional[threading.Thread] = None
        # failover state (ISSUE 10): a dead replica's worker has
        # exited; its requests were requeued or failed, and the
        # admission controller no longer places on it
        self.dead = False
        self.death: Optional[str] = None
        # elastic state (ISSUE 12): a RETIRED replica drains its queue
        # then its worker exits; rejoin (add_replica) brings it back —
        # the graceful sibling of `dead`
        self.retired = False
        self.retire_t0: Optional[float] = None
        # accumulated engine metrics across micro-bursts
        self.completed = 0
        self.bursts = 0
        self.chunks = 0
        self.device_steps = 0
        self.live_slot_steps = 0.0
        # cost attribution (ISSUE 11): attributed + idle ==
        # device_steps EXACTLY per booked burst (engine invariant)
        self.attributed_steps = 0
        self.idle_steps = 0
        self.burst_seq = 0  # keys the per-burst trace span ids
        self.tenant_swaps = 0  # value-paged tenant flips (ISSUE 19)

    def pending(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def pop_batch(self, cap: int) -> List[Request]:
        """Queued requests in class-priority order, chopped by DECODE-
        POOL cost: an interpolation occupies ``frames`` pool rows
        (ISSUE 15 — its latent grid decodes as child rows), everything
        else one, and the micro-burst must fit the fixed ``pool_cap``
        pad. Popping stops at the first head that no longer fits, so
        priority order is never violated for capacity.

        Bursts are SINGLE-TENANT (ISSUE 19): the whole burst runs on
        one materialized param tree, so popping also stops at the
        first head whose tenant differs from the first popped
        request's — the same keep-priority-order rule as the capacity
        stop (skipping ahead to lower-priority same-tenant work would
        violate class priority). Tenant-less fleets are unaffected:
        every request's tenant is ``""``.

        The formation rule itself lives on the unified dispatch
        runtime (ISSUE 20): :meth:`GeometryRunScheduler.form_burst` is
        the frozen port of this loop, shared with every other
        cost-capped grouper."""
        return default_scheduler().form_burst(
            self.queues.values(), cap,
            cost_of=endpoints_mod.pool_rows_of,
            group_of=lambda r: r.tenant or "")


class ServeFleet:
    """R device-pinned engines, one SLA-aware scheduler, thread workers.

    Lifecycle: construct -> (optionally) ``warm`` -> ``submit`` any
    number of requests (before or after ``start``) -> ``start`` ->
    ``drain`` -> ``close`` (or use as a context manager). Submissions
    before ``start`` are placed deterministically (backlog changes only
    through submits), which the closed-burst invariance/scaling arms
    rely on.
    """

    def __init__(self, model, hps: HParams, params, replicas: int = 0,
                 slots: int = 0, chunk: int = 0,
                 max_len: Optional[int] = None, greedy: bool = False,
                 classes: Optional[Dict[str, AdmissionClass]] = None,
                 devices: Optional[Sequence[Any]] = None,
                 pool_cap: int = 0, queue_cap: int = 0,
                 shed_margin: float = 1.0, slo=None,
                 retry_budget: int = 2,
                 retry_backoff_s: float = 0.05,
                 max_replicas: int = 0, cache=None,
                 endpoint_classes: Optional[Dict[str, str]] = None,
                 ckpt_id: str = "", draft_params=None,
                 draft_depth: int = 0,
                 draft_tol: Optional[float] = None,
                 tenants=None, tenant_cap: int = 0,
                 tenant_slos: Optional[Dict[str, List]] = None):
        import jax  # lazy, the serve-module discipline

        devices = list(devices if devices is not None else jax.devices())
        n = int(replicas) if replicas else len(devices)
        if n < 1:
            raise ValueError(f"replicas must be >= 1, got {n}")
        # elastic headroom (ISSUE 12): build (and warm) engines up to
        # max_replicas, but only `replicas` start in the placement set
        # — the rest sit retired until add_replica() rejoins them, so
        # an autoscale spawn never compiles inside the serving window
        n_build = max(n, int(max_replicas) or n)
        if n_build > len(devices):
            raise ValueError(
                f"{n_build} replicas need {n_build} devices but only "
                f"{len(devices)} are available")
        self.hps = hps
        self.slots = int(slots or hps.serve_slots)
        self.chunk = int(chunk or hps.serve_chunk)
        # micro-burst ceiling == the one pool size every burst pads to;
        # 4x slots (default_pool_cap) amortizes the per-burst fixed
        # costs (pool upload, pipeline fill, the all-but-empty drain
        # tail) at saturation while keeping light-traffic bursts small
        # (a burst only holds what was queued when the worker woke)
        self.pool_cap = int(pool_cap or default_pool_cap(self.slots))
        if self.pool_cap < 1:
            raise ValueError(f"pool_cap must be >= 1, got {self.pool_cap}")
        # endpoint -> admission-class routing (ISSUE 15): a submitted
        # request with no explicit class lands in its endpoint's class
        # (serve/endpoints.parse_endpoint_specs builds this map from
        # the --endpoints grammar); unmapped endpoints fall back to the
        # single-class default exactly as before
        self.endpoint_classes = dict(endpoint_classes) \
            if endpoint_classes else {}
        self.classes = dict(classes) if classes else \
            parse_admission_classes([])
        class_order = [c.name for c in sorted(self.classes.values(),
                                              key=lambda c: c.priority)]
        self._default_class = class_order[0] if len(class_order) == 1 \
            else None
        bad_routes = sorted(c for c in self.endpoint_classes.values()
                            if c not in self.classes)
        if bad_routes:
            raise ValueError(
                f"endpoint_classes route to undeclared admission "
                f"class(es) {bad_routes}; declared: "
                f"{sorted(self.classes)}")
        # multi-tenant paging (ISSUE 19): with a TenantStore attached,
        # `params` is the shared BASE tree and every engine is built
        # VALUE-PAGED (params as traced arguments), so workers flip a
        # replica between tenants without compiling. The shared
        # PrefixReuseIndex dedupes encode work across replicas.
        self.tenants = tenants
        self.tenant_cap = int(tenant_cap)
        self._tenant_slos_cfg = {t: list(s)
                                 for t, s in (tenant_slos or {}).items()}
        self._tenant_slo = {t: SLOTracker(s)
                            for t, s in self._tenant_slos_cfg.items()}
        self.encode_reuse = (PrefixReuseIndex()
                             if tenants is not None else None)
        if tenants is not None and not ckpt_id:
            ckpt_id = tenants.base_ckpt_id
        self._admission = AdmissionController(
            self.classes, n_replicas=n_build, slots=self.slots,
            queue_cap=queue_cap, shed_margin=shed_margin,
            tenant_cap=self.tenant_cap)
        self._slo = slo
        self._lock = threading.Lock()
        self._done_cv = threading.Condition(self._lock)
        self._replicas: List[_Replica] = []
        for r in range(n_build):
            with jax.default_device(devices[r]):
                # speculative decoding (ISSUE 18): every replica gets
                # the same draft — draft state is per-engine, and the
                # acceptance rule is replica-independent (pure in key /
                # draft params / verifier params), so fleet placement
                # still can never change a request's strokes
                eng = ServeEngine(model, hps, params, slots=self.slots,
                                  chunk=self.chunk, max_len=max_len,
                                  greedy=greedy, device=devices[r],
                                  replica_id=r, ckpt_id=ckpt_id,
                                  draft_params=draft_params,
                                  draft_depth=draft_depth,
                                  draft_tol=draft_tol,
                                  param_args=tenants is not None)
            eng.encode_reuse = self.encode_reuse
            rep = _Replica(r, devices[r], eng, class_order)
            rep.cond = threading.Condition(self._lock)
            if r >= n:
                rep.retired = True
                self._admission.retire(r)
            self._replicas.append(rep)
        self._initial_active = n
        # result cache (ISSUE 12): consulted in submit() before
        # admission; assignable between bench arms (the compiled
        # replicas are the expensive part, the cache is host state)
        self.cache = cache
        self._fp_of: Dict[int, bytes] = {}     # uid -> fingerprint
        self._pending: Dict[bytes, List] = {}  # fp -> coalesced waiters
        self._scale_log: List[Dict] = []
        # zero-downtime rollout (ISSUE 16): the fleet's AUTHORITATIVE
        # serving version. New submissions fingerprint under it, so a
        # cache entry computed by checkpoint v1 can never answer a
        # request admitted while v2 serves; the RolloutController flips
        # it old->new only after the LAST replica swap succeeded.
        self.serving_ckpt_id = str(ckpt_id or "")
        # the in-flight RolloutController, if any — close() joins its
        # walk before retiring workers so no half-swapped spare is
        # orphaned mid-rollout
        self._rollout = None
        if retry_budget < 0:
            raise ValueError(f"retry_budget must be >= 0, got "
                             f"{retry_budget}")
        self.retry_budget = int(retry_budget)
        self.retry_backoff_s = float(retry_backoff_s)
        self._next_uid = 0
        self._seen_uids: set = set()
        self._submitted = 0
        self._shed: List[Dict] = []
        self._results: Dict[int, Dict] = {}     # uid -> record
        self._failed: Dict[int, Dict] = {}      # uid -> failure record
        self._retries: Dict[int, int] = {}      # uid -> requeue count
        self._requeues = 0
        self._stop = False
        self._started = False
        self._error: Optional[BaseException] = None
        self._t_first_submit: Optional[float] = None
        self._t_last_done: Optional[float] = None

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    @property
    def n_live(self) -> int:
        """Replicas currently in the placement set (not dead, not
        retired) — the number the autoscaler moves and the
        ``fleet_replicas`` gauge reports."""
        return sum(1 for r in self._replicas
                   if not r.dead and not r.retired)

    # -- lifecycle ---------------------------------------------------------

    def warm(self, template: Request, endpoints: bool = False) -> None:
        """Compile every replica's chunk program OUTSIDE the measured
        window: one 1-step burst per replica at the fleet's fixed
        ``pool_cap`` — the exact (B, K, N) geometry every later
        micro-burst dispatches, so a measured run can never compile.
        ``template`` supplies valid request fields (z for conditional
        models); its strokes are discarded — endpoint fields are
        stripped, and a missing z is zero-filled, so an endpoint
        request works as the template too. Runs under a suppressed
        telemetry core (ISSUE 11): the clone's auto-assigned uid 0
        would otherwise emit a ``req-0`` span tree colliding with the
        real request 0's trace when the caller configured telemetry
        before warming.

        ``endpoints=True`` (ISSUE 15) additionally warms every
        replica's fixed-geometry encode program at every prefix edge
        AND the init-capable chunk program mixed bursts dispatch, so a
        measured mixed-endpoint window sees zero compiles.
        """
        import jax

        with telemetry_suppressed():
            for rep in self._replicas:
                z = template.z
                if self.hps.conditional and z is None:
                    z = np.zeros((self.hps.z_size,), np.float32)
                clone = dataclasses.replace(
                    template, uid=None, z=z, max_len=1, cls=None,
                    queue_pos=None, enqueue_ts=None, attempt=0,
                    endpoint="generate", prefix=None, frames=0,
                    parent_uid=None, init_carry=None, init_prev=None)
                with jax.default_device(rep.device):
                    rep.engine.run([clone], pool_pad=self.pool_cap)
                    if endpoints:
                        rep.engine.encoder.warm()
                        # the init-leaf pool geometry is its own
                        # compiled program — warm it with a planned
                        # 1-step completion so mixed bursts never
                        # compile in the measured window
                        cw = rep.engine.model.dec.carry_size
                        planned = dataclasses.replace(
                            clone, uid=None, endpoint="complete",
                            init_carry=np.zeros((cw,), np.float32),
                            init_prev=np.zeros((5,), np.float32))
                        rep.engine.run([planned],
                                       pool_pad=self.pool_cap)

    def start(self) -> "ServeFleet":
        if self._started:
            return self
        self._started = True
        with _LIVE_LOCK:
            _LIVE.add(self)
        for rep in self._replicas:
            if rep.retired or rep.dead:
                continue  # elastic spares spawn via add_replica()
            rep.thread = threading.Thread(
                target=self._worker, args=(rep,),
                name=f"fleet-replica-{rep.idx}", daemon=True)
            rep.thread.start()
        tel = get_telemetry()
        if tel.enabled:
            tel.gauge("fleet_replicas", self.n_live, cat="serve")
            if self.tenants is not None:
                # the paged-adapter residency gauge (ISSUE 19): how
                # many tenant fine-tunes this ONE fleet is serving
                tel.gauge("tenant_adapters_resident",
                          float(len(self.tenants.tenants)), cat="serve")
        return self

    def reset(self) -> None:
        """Clear results/shed/admission state between measurement arms
        (the compiled replica engines are the expensive part and are
        kept). Only legal while idle — no queued or in-flight work.

        A cleanly close()d fleet resets back to the pristine PRE-START
        state (ISSUE 11: serve_bench's capacity trials close ->
        reset -> re-queue the whole burst -> start(), so every trial
        replays the deterministic pre-start schedule — submitting into
        live workers would race the burst chop against the submit
        loop). close() alone stays terminal for submit()/drain(); only
        this explicit reset reopens, and only when every worker thread
        actually joined (a straggler forbids restart: two workers on
        one replica would corrupt the queues)."""
        with self._lock:
            if any(rep.pending() for rep in self._replicas):
                raise RuntimeError("reset with queued work")
            if self._done_locked() < self._submitted:
                raise RuntimeError("reset with requests in flight")
            if self._stop:
                lingering = [rep.thread.name for rep in self._replicas
                             if rep.thread is not None
                             and rep.thread.is_alive()]
                if lingering:
                    raise RuntimeError(
                        f"reset on a closed fleet with live worker "
                        f"thread(s) {lingering} — close() timed out; "
                        f"build a fresh fleet instead")
            if any(rep.dead for rep in self._replicas):
                # a dead replica's worker thread has exited and cannot
                # be restarted by reset — the measurement arms that use
                # reset() assume full capacity
                raise RuntimeError(
                    f"reset on a degraded fleet (dead replicas: "
                    f"{[r.idx for r in self._replicas if r.dead]}); "
                    f"build a fresh fleet instead")
            if self._stop:
                # every validation passed — only now reopen to the
                # pristine pre-start state (a raise above must leave a
                # closed fleet fully closed; a RUNNING fleet's flags
                # stay untouched so start() stays a no-op on it)
                self._stop = False
                self._started = False
            self._admission = AdmissionController(
                self.classes, n_replicas=self.n_replicas,
                slots=self.slots, queue_cap=self._admission.queue_cap,
                shed_margin=self._admission.shed_margin,
                tenant_cap=self._admission.tenant_cap)
            # fresh per-tenant SLO verdicts and a fresh encode-reuse
            # index per measurement arm (ISSUE 19): the reuse index's
            # compute/reuse ledger is a measured-window quantity, so
            # each arm starts cold (computes == distinct holds per arm)
            self._tenant_slo = {t: SLOTracker(s)
                                for t, s in self._tenant_slos_cfg.items()}
            if self.encode_reuse is not None:
                self.encode_reuse = PrefixReuseIndex()
                for rep in self._replicas:
                    rep.engine.encode_reuse = self.encode_reuse
            # restore the INITIAL topology (ISSUE 12): arms that
            # autoscaled re-measure from the same starting fleet.
            # Running fleets get workers spawned/retired to match;
            # closed ones re-spawn at the next start().
            for rep in self._replicas:
                want_retired = rep.idx >= self._initial_active
                if want_retired and not rep.retired:
                    rep.retired = True
                    rep.retire_t0 = time.perf_counter()
                    rep.cond.notify_all()  # wake to exit (queue empty)
                elif not want_retired and rep.retired:
                    rep.retired = False
                    rep.retire_t0 = None
                    if (self._started and not self._stop
                            and (rep.thread is None
                                 or not rep.thread.is_alive())):
                        rep.thread = threading.Thread(
                            target=self._worker, args=(rep,),
                            name=f"fleet-replica-{rep.idx}",
                            daemon=True)
                        rep.thread.start()
                if want_retired:
                    self._admission.retire(rep.idx)
            self._fp_of = {}
            self._pending = {}
            self._scale_log = []
            self._next_uid = 0
            self._seen_uids = set()
            self._submitted = 0
            self._shed = []
            self._results = {}
            self._failed = {}
            self._retries = {}
            self._requeues = 0
            self._t_first_submit = None
            self._t_last_done = None
            for rep in self._replicas:
                rep.completed = rep.bursts = rep.chunks = 0
                rep.device_steps = 0
                rep.live_slot_steps = 0.0
                rep.attributed_steps = rep.idle_steps = 0
                rep.tenant_swaps = 0

    # -- elastic scaling (ISSUE 12) ----------------------------------------

    def _rejoin_locked(self, rep: "_Replica", reason: str,
                       t0: float) -> int:
        """The elastic SPAWN body (caller holds the scheduler lock):
        clear the retired flags, rejoin the placement set, start a
        worker if the fleet is live, and record the action in
        ``scale_log`` + the ``replica_spawn`` span + the
        ``fleet_replicas`` gauge. Shared by :meth:`add_replica` and
        the failover self-heal so the rejoin invariants live once."""
        tel = get_telemetry()
        rep.retired = False
        rep.retire_t0 = None
        self._admission.rejoin(rep.idx)
        if (self._started and not self._stop
                and (rep.thread is None or not rep.thread.is_alive())):
            rep.thread = threading.Thread(
                target=self._worker, args=(rep,),
                name=f"fleet-replica-{rep.idx}", daemon=True)
            rep.thread.start()
        n_live = self.n_live
        self._scale_log.append({"action": "spawn", "replica": rep.idx,
                                "n_live": n_live, "reason": reason})
        if tel.enabled:
            tel.counter("replica_spawns", 1.0, cat="serve")
            tel.emit_span(
                "replica_spawn", "serve", t0, time.perf_counter(),
                args={"replica": rep.idx, "n_live": n_live,
                      "reason": reason},
                trace=span_link(f"replica-r{rep.idx}",
                                f"spawn-r{rep.idx}.{rep.burst_seq}"))
            tel.gauge("fleet_replicas", n_live, cat="serve")
        return rep.idx

    def add_replica(self, reason: str = "manual") -> int:
        """Rejoin the lowest retired replica into the placement set
        (the elastic SPAWN: PR 10's rejoin path — the engine is
        already built, pinned and warm, so a spawn never compiles).
        Returns the replica index. Recorded in ``scale_log``, the
        ``replica_spawn`` span, the ``fleet_replicas`` gauge."""
        t0 = time.perf_counter()
        with self._lock:
            if self._stop:
                raise RuntimeError("fleet is closed")
            cand = [r for r in self._replicas
                    if r.retired and not r.dead]
            if not cand:
                raise RuntimeError(
                    f"no retired replica to rejoin (live "
                    f"{self.n_live}/{self.n_replicas}) — build the "
                    f"fleet with max_replicas headroom")
            return self._rejoin_locked(cand[0], reason, t0)

    def retire_replica(self, replica: Optional[int] = None,
                       reason: str = "manual") -> int:
        """Gracefully remove one replica from the placement set (the
        elastic RETIRE: drain + leave placement — the graceful half of
        the failover path). Its queued work drains, then its worker
        exits; ``/healthz`` reports ``scaling`` while the drain is in
        flight. Defaults to the highest live index (deterministic);
        refuses to retire the last live replica."""
        tel = get_telemetry()
        with self._lock:
            if self._stop:
                raise RuntimeError("fleet is closed")
            live = [r for r in self._replicas
                    if not r.dead and not r.retired]
            if len(live) <= 1:
                raise RuntimeError(
                    "cannot retire the last live replica")
            if replica is None:
                rep = live[-1]
            else:
                rep = self._replicas[replica]
                if rep.dead or rep.retired:
                    raise RuntimeError(
                        f"replica {replica} is not live "
                        f"(dead={rep.dead}, retired={rep.retired})")
            rep.retired = True
            rep.retire_t0 = time.perf_counter()
            self._admission.retire(rep.idx)
            rep.cond.notify_all()   # wake: drain the queue, then exit
            n_live = self.n_live
            self._scale_log.append({"action": "retire",
                                    "replica": rep.idx,
                                    "n_live": n_live,
                                    "reason": reason})
            if tel.enabled:
                tel.counter("replica_retires", 1.0, cat="serve")
                tel.gauge("fleet_replicas", n_live, cat="serve")
            return rep.idx

    def set_target_replicas(self, target: int,
                            reason: str = "autoscale") -> List[Dict]:
        """Apply an autoscale decision: spawn/retire replicas until
        ``n_live == target`` (clamped to what was built AND is still
        alive — a dead replica can never rejoin, so scaling up after a
        crash tops out at the surviving count instead of raising out
        of the control loop). Returns the scale_log entries it
        appended — the bench records these as the realized decision
        timeline."""
        with self._lock:
            usable = sum(1 for r in self._replicas if not r.dead)
        target = max(1, min(int(target), usable))
        actions: List[Dict] = []
        while self.n_live < target:
            try:
                idx = self.add_replica(reason=reason)
            except RuntimeError as e:
                if "no retired replica" not in str(e):
                    raise  # a closed fleet must still propagate
                break  # a concurrent death consumed the headroom
            actions.append({"action": "spawn", "replica": idx})
        while self.n_live > target:
            try:
                idx = self.retire_replica(reason=reason)
            except RuntimeError as e:
                if "last live replica" not in str(e):
                    raise
                break  # a concurrent death got there first
            actions.append({"action": "retire", "replica": idx})
        return actions

    # -- zero-downtime rollout plumbing (ISSUE 16) -------------------------

    def rejoin_replica(self, replica: int,
                       reason: str = "rollout") -> int:
        """Rejoin ONE SPECIFIC retired replica into the placement set
        (the rollout walk rejoins exactly the replica it just swapped
        and canaried — :meth:`add_replica`'s lowest-retired pick could
        grab a different, unswapped spare). No-op if already live."""
        t0 = time.perf_counter()
        with self._lock:
            if self._stop:
                raise RuntimeError("fleet is closed")
            rep = self._replicas[replica]
            if rep.dead:
                raise RuntimeError(f"replica {replica} is dead")
            if not rep.retired:
                return replica
            return self._rejoin_locked(rep, reason, t0)

    def swap_params_retired(self, replica: int, params,
                            ckpt_id: str = "",
                            param_dtype: Optional[str] = None) -> None:
        """Hot-swap params on a RETIRED, DRAINED replica's engine.

        Refuses a live or still-draining replica: the swap rebuilds the
        chunk program (a compile), so it must never race a serving
        burst — the rollout walk retires, waits for the worker to
        drain-exit, swaps here, re-warms, then rejoins. The caller
        (serve/rollout.RolloutController) owns the retired replica for
        the duration; nothing else may rejoin it mid-swap."""
        import jax

        with self._lock:
            rep = self._replicas[replica]
            if rep.dead:
                raise RuntimeError(f"replica {replica} is dead")
            if not rep.retired or (rep.thread is not None
                                   and rep.thread.is_alive()):
                raise RuntimeError(
                    f"replica {replica} must be retired and drained "
                    f"before a param swap (retired={rep.retired}, "
                    f"draining={rep.thread is not None})")
        # outside the scheduler lock: the device_put + program rebuild
        # may compile, and survivors must keep draining meanwhile
        with jax.default_device(rep.device):
            rep.engine.swap_params(params, ckpt_id=ckpt_id,
                                   param_dtype=param_dtype)

    def wait_replica_drained(self, replica: int,
                             timeout: float = 60.0) -> bool:
        """Block until a retired replica's worker has drain-exited
        (``rep.thread is None``) — the precondition for
        :meth:`swap_params_retired`. False on timeout."""
        deadline = time.perf_counter() + timeout
        with self._lock:
            rep = self._replicas[replica]
            while rep.thread is not None and rep.thread.is_alive():
                left = deadline - time.perf_counter()
                if left <= 0:
                    return False
                self._done_cv.wait(left)
            return True

    def close(self, timeout: float = 30.0) -> List[str]:
        """Stop the workers (any queued-but-unstarted work is
        abandoned) and unregister.

        Joins each worker under one shared ``timeout`` budget and
        REPORTS stragglers instead of hanging (ISSUE 10 satellite): a
        worker wedged inside a device call cannot be force-killed from
        Python, so the caller gets the straggler names (also warned on
        stdout) and the process's daemon-thread teardown reaps them at
        exit. Returns the straggler thread names (empty = clean)."""
        # bugfix (ISSUE 16): a close() racing an in-flight rollout must
        # join the walk FIRST — stopping workers mid-walk would orphan
        # a half-swapped retired spare (new params, never canaried,
        # silently rejoinable by a later add_replica)
        ctl = self._rollout
        if ctl is not None:
            ctl.join(timeout=timeout)
        with self._lock:
            self._stop = True
            for rep in self._replicas:
                rep.cond.notify_all()
            self._done_cv.notify_all()
        deadline = time.perf_counter() + timeout
        stragglers: List[str] = []
        for rep in self._replicas:
            t = rep.thread
            if t is None:
                continue
            t.join(timeout=max(0.0, deadline - time.perf_counter()))
            if t.is_alive():
                stragglers.append(t.name)
        if stragglers:
            # stderr: serve-bench's stdout is a JSON report stream
            print(f"[fleet] WARNING: close() timed out after {timeout}s "
                  f"waiting for worker thread(s) {stragglers}; they are "
                  f"daemonic and die with the process", file=sys.stderr,
                  flush=True)
        with _LIVE_LOCK:
            _LIVE.discard(self)
        return stragglers

    def __enter__(self) -> "ServeFleet":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.close()

    def __repr__(self) -> str:
        return (f"ServeFleet({self.n_replicas} replicas x "
                f"B{self.slots}/K{self.chunk}, pool {self.pool_cap}, "
                f"{'running' if self._started and not self._stop else 'idle'})")

    # -- the scheduler -----------------------------------------------------

    def submit(self, req: Request, cls: Optional[str] = None,
               force: bool = False) -> bool:
        """Admit one request: route to the least-loaded replica queue or
        shed. Returns True iff admitted. Thread-safe (the load
        generator calls this from its replay thread). ``force`` skips
        the shed checks (same placement — the bench's parity/capacity
        arms must complete every request)."""
        # endpoint door checks (ISSUE 15): shape/encoder validation
        # fails HERE with one actionable line (an unconditional model
        # rejects encoder endpoints naming hps.conditional), and the
        # endpoint routes to its admission class when the caller gave
        # none — `complete=interactive`-style serving policy
        if (req.endpoint or "generate") != "generate" \
                or req.prefix is not None:
            endpoints_mod.validate_request(req, self.hps,
                                           pool_cap=self.pool_cap)
        cls_name = (cls or req.cls
                    or self.endpoint_classes.get(req.endpoint
                                                 or "generate")
                    or self._default_class)
        if cls_name is None:
            raise ValueError(
                f"request needs an admission class (configured: "
                f"{sorted(self.classes)})")
        # tenant door check (ISSUE 19): an unregistered tenant fails
        # HERE with one actionable line — inside a worker it would be
        # a burst death that fails over forever
        tenant = str(req.tenant or "")
        if self.tenants is not None:
            if tenant not in self.tenants:
                raise ValueError(
                    f"unknown tenant {tenant!r}: registered "
                    f"{sorted(self.tenants.tenants)} (empty string "
                    f"serves the base tree)")
        elif tenant:
            raise ValueError(
                f"request names tenant {tenant!r} but the fleet has "
                f"no TenantStore attached")
        tel = get_telemetry()
        # content fingerprint OUTSIDE the scheduler lock (blake2b over
        # the request fields; the cache is consulted under it) — under
        # the fleet's CURRENT serving version (ISSUE 16), so a rollout
        # namespaces the keyspace: v1 entries are invisible to requests
        # admitted under v2. Multi-tenant fleets (ISSUE 19) fingerprint
        # under the TENANT's serving identity instead: two tenants'
        # byte-identical requests land in disjoint keyspaces, so a hit
        # is always the requester's OWN adapter's bytes.
        fp_ckpt = (self.tenants.ckpt_id_of(tenant)
                   if self.tenants is not None
                   else (self.serving_ckpt_id or None))
        fp = (self.cache.fingerprint(req, ckpt_id=fp_ckpt)
              if self.cache is not None else None)
        with self._lock:
            if self._stop:
                raise RuntimeError("fleet is closed")
            if self._error is not None:
                raise RuntimeError("fleet worker failed") from self._error
            if req.uid is None:
                req.uid = self._next_uid
            if req.uid in self._seen_uids:
                # a duplicate would overwrite its twin's result record
                # and wedge drain() (done can never reach submitted) —
                # fail loudly at the door instead
                raise ValueError(f"duplicate request uid {req.uid}")
            self._seen_uids.add(req.uid)
            self._next_uid = max(self._next_uid, req.uid + 1)
            req.cls = cls_name
            if req.enqueue_ts is None:
                req.enqueue_ts = time.perf_counter()
            if self._t_first_submit is None:
                self._t_first_submit = req.enqueue_ts
            self._submitted += 1
            # result cache (ISSUE 12): consulted BEFORE admission — a
            # content hit is served at the door for zero device steps
            # (bitwise the origin computation's strokes), and a repeat
            # whose content is still IN FLIGHT coalesces onto the
            # pending computation (fan-out at completion) instead of
            # computing twice. Both paths bypass shed checks: they
            # cost no queue slot and no device work.
            if fp is not None:
                entry = self.cache.get(fp)
                if entry is not None:
                    self._book_cache_hit(req, cls_name, entry.strokes5,
                                         entry.length, entry.steps,
                                         entry.origin_uid, tel,
                                         endpoint=entry.endpoint,
                                         frames=entry.frames,
                                         ckpt_id=entry.ckpt_id,
                                         tenant=tenant)
                    return True
                if fp in self._pending:
                    self._pending[fp].append(req)
                    self.cache.note_coalesced()
                    if tel.enabled:
                        tel.instant(
                            "coalesced", cat="serve", ts=req.enqueue_ts,
                            args={"uid": req.uid, "class": cls_name},
                            trace=span_link(
                                request_trace_id(req.uid),
                                request_span_id("coalesced", req.uid)))
                    return True
            # admission evidence (ISSUE 11): the backlog the decision
            # saw, captured BEFORE place() mutates it — the arrival
            # instant carries the whole verdict (chosen replica,
            # per-replica backlog, est_wait, shed reason), so a trace
            # explains the placement without replaying the controller.
            # Only materialized when tracing is on: the copy is pure
            # trace evidence, and this is the hot admission path.
            backlog = self._admission.backlog if tel.enabled else None
            # cost-aware admission (ISSUE 15): a grid request charges
            # its decode-pool rows, so backlog/queue-cap/deadline-shed
            # see the real work it queues
            decision = self._admission.place(
                cls_name, force=force,
                cost=endpoints_mod.pool_rows_of(req),
                tenant=tenant)
            if decision.shed:
                self._shed.append({"uid": req.uid, "class": cls_name,
                                   "endpoint": req.endpoint
                                   or "generate",
                                   "tenant": tenant,
                                   "reason": decision.shed_reason,
                                   "est_wait_s": decision.est_wait_s})
                if tel.enabled:
                    # renders as ..._requests_shed_total on /metrics
                    # (the exposition layer appends _total to counters)
                    tel.counter("requests_shed", 1.0, cat="serve")
                    tel.counter(class_series("requests_shed", cls_name),
                                1.0, cat="serve")
                    if tenant:
                        tel.counter(tenant_series("requests_shed",
                                                  tenant), 1.0,
                                    cat="serve")
                    # a shed request never completes, so its submit
                    # instant IS its whole trace — a self-rooted
                    # single-span tree, never an orphan
                    tel.instant(
                        "submit", cat="serve", ts=req.enqueue_ts,
                        args={"uid": req.uid, "class": cls_name,
                              "shed": True,
                              "reason": decision.shed_reason,
                              "est_wait_s": decision.est_wait_s,
                              "backlog": backlog},
                        trace=span_link(request_trace_id(req.uid),
                                        request_span_id("shed",
                                                        req.uid)))
                self._done_cv.notify_all()
                return False
            req.queue_pos = decision.queue_pos
            rep = self._replicas[decision.replica]
            rep.queues[cls_name].append(req)
            if fp is not None:
                # this uid is now the PRIMARY computation for its
                # content: later repeats coalesce onto it (registered
                # only on admission — a shed request must never anchor
                # waiters that could then wait forever)
                self._pending[fp] = []
                self._fp_of[req.uid] = fp
            if tel.enabled:
                tel.counter("requests_admitted", 1.0, cat="serve")
                tel.instant(
                    "submit", cat="serve", ts=req.enqueue_ts,
                    args={"uid": req.uid, "class": cls_name,
                          "shed": False, "replica": decision.replica,
                          "queue_pos": decision.queue_pos,
                          "est_wait_s": decision.est_wait_s,
                          "backlog": backlog},
                    trace=span_link(request_trace_id(req.uid),
                                    request_span_id("submit", req.uid),
                                    request_span_id("request",
                                                    req.uid)))
            rep.cond.notify()
            return True

    def _book_cache_hit(self, req: Request, cls_name: Optional[str],
                        strokes5, length: int, steps: int,
                        origin_uid: int, tel,
                        coalesced: bool = False,
                        endpoint: str = "generate",
                        frames=None, ckpt_id: str = "",
                        tenant: str = "") -> None:
        """Serve one request from cached strokes (caller holds the
        lock): book a ``cached=True`` Result with ZERO attributed
        device steps, feed the SLO tracker the (tiny) real latency,
        and emit the causal trace — a root span over the request's
        clock plus a ``cache_hit`` instant carrying the ORIGIN
        computation's trace id, so a cached tree explains where its
        bytes came from (the ISSUE 12 trace-link contract)."""
        now = time.perf_counter()
        qw = now - req.enqueue_ts
        res = Result(uid=req.uid, strokes5=strokes5, length=length,
                     steps=steps, queue_wait_s=qw, decode_s=0.0,
                     latency_s=qw, attributed_steps=0, cached=True,
                     endpoint=endpoint or "generate", frames=frames,
                     # a hit re-serves its ORIGIN computation's version
                     # stamp (ISSUE 16) — the namespace guarantees it
                     # matches the namespace this request hashed under
                     ckpt_id=ckpt_id)
        self._results[req.uid] = {
            "result": res, "replica": None, "class": cls_name,
            "queue_pos": None, "cached": True,
            "endpoint": res.endpoint, "tenant": tenant,
            "origin_uid": origin_uid}
        if self._slo is not None:
            self._slo.observe(cls_name or DEFAULT_CLASS, {
                "queue_wait_s": res.queue_wait_s,
                "decode_s": res.decode_s,
                "latency_s": res.latency_s})
        tslo = self._tenant_slo.get(tenant)
        if tslo is not None:
            # per-tenant SLO verdicts key on the ADMISSION CLASS (the
            # tenant:class:pNN grammar) — a cached completion counts
            tslo.observe(cls_name or DEFAULT_CLASS, {
                "queue_wait_s": res.queue_wait_s,
                "decode_s": res.decode_s,
                "latency_s": res.latency_s})
        self._t_last_done = now
        if tel.enabled:
            trace_id = request_trace_id(req.uid)
            root_id = request_span_id("request", req.uid)
            tel.emit_span("request", "serve", req.enqueue_ts, now,
                          args={"uid": req.uid, "cached": True},
                          trace=span_link(trace_id, root_id))
            tel.instant(
                "cache_hit", cat="serve", ts=now,
                args={"uid": req.uid, "class": cls_name,
                      "coalesced": coalesced,
                      "origin_uid": origin_uid,
                      "origin_trace": request_trace_id(origin_uid)},
                trace=span_link(trace_id,
                                request_span_id("cache_hit", req.uid),
                                root_id))
            tel.instant(
                "complete", cat="serve", ts=now,
                args={"uid": req.uid, "steps": res.steps,
                      "length": res.length,
                      "queue_wait_s": res.queue_wait_s,
                      "decode_s": res.decode_s,
                      "latency_s": res.latency_s,
                      "segments": [
                          [k, v] for k, v in critical_path_segments(
                              res.queue_wait_s, res.latency_s)],
                      "attributed_steps": 0, "cached": True,
                      **({"class": cls_name} if cls_name else {})},
                trace=span_link(trace_id,
                                request_span_id("complete", req.uid),
                                root_id))
            tel.counter("requests_completed", 1.0, cat="serve")
            tel.observe("latency_s", res.latency_s, cat="serve")
            if cls_name is not None:
                tel.observe(class_series("latency_s", cls_name),
                            res.latency_s, cat="serve")
            # the per-endpoint series (ISSUE 15): a cached completion
            # is a completion — the ep_* counters must agree with the
            # aggregate and with summary()'s latency_by_endpoint,
            # which both count hits
            tel.counter(endpoint_series("requests_completed",
                                        res.endpoint), 1.0,
                        cat="serve")
            tel.observe(endpoint_series("latency_s", res.endpoint),
                        res.latency_s, cat="serve")
            if tenant:
                tel.counter(tenant_series("requests_completed", tenant),
                            1.0, cat="serve")
                tel.observe(tenant_series("latency_s", tenant),
                            res.latency_s, cat="serve")
        self._done_cv.notify_all()

    def _worker(self, rep: _Replica) -> None:
        """One replica's drain loop: wait for queued work, pop a
        micro-burst in class-priority order, serve it to completion on
        this replica's device, book the completions.

        Failover (ISSUE 10): a burst failure — injected
        (``fleet.worker.rNN`` fault site) or real — no longer kills the
        fleet. The replica is marked dead, its queued AND in-flight
        requests fail over to the survivors (:meth:`_on_replica_death`)
        and this worker exits; only the death of the LAST replica (or
        an exhausted per-request retry budget, recorded per request) is
        fleet-fatal."""
        import jax

        while True:
            with self._lock:
                while (not rep.pending() and not self._stop
                       and not rep.retired):
                    rep.cond.wait()
                if self._stop:
                    return
                if rep.retired and not rep.pending():
                    # elastic retire (ISSUE 12): the queue is drained —
                    # leave the fleet. The thread slot is cleared UNDER
                    # the lock so a concurrent add_replica either sees
                    # this worker gone (spawns a fresh one) or flipped
                    # `retired` before we woke (we keep serving above).
                    rep.thread = None
                    t0 = (rep.retire_t0 if rep.retire_t0 is not None
                          else time.perf_counter())
                    rep.retire_t0 = None
                    self._done_cv.notify_all()
                    tel = get_telemetry()
                    if tel.enabled:
                        now = time.perf_counter()
                        tel.emit_span(
                            "replica_retire", "serve", t0, now,
                            args={"replica": rep.idx,
                                  "n_live": self.n_live},
                            trace=span_link(
                                f"replica-r{rep.idx}",
                                f"retire-r{rep.idx}.{rep.burst_seq}"))
                        tel.gauge("fleet_replicas", self.n_live,
                                  cat="serve")
                    return
                batch = rep.pop_batch(self.pool_cap)
                bid = f"r{rep.idx}.b{rep.burst_seq}"
                rep.burst_seq += 1
            tel = get_telemetry()
            t_burst = time.perf_counter()
            try:
                # fault site: kill THIS replica's burst (plans target a
                # specific replica: "fleet.worker.r0@0")
                fault_point(f"fleet.worker.r{rep.idx}")
                with jax.default_device(rep.device):
                    # tenant paging (ISSUE 19): flip this replica to
                    # the burst's tenant with a pure VALUE swap —
                    # value-paged engines keep their compiled chunk +
                    # encode programs (the geometry key never sees a
                    # tenant dimension), so the flip is a device_put,
                    # never a compile. Bursts are single-tenant by
                    # pop_batch's tenant stop.
                    if self.tenants is not None and batch:
                        t = batch[0].tenant or ""
                        if t != rep.engine.serving_tenant:
                            rep.engine.swap_params(
                                self.tenants.materialize(t),
                                ckpt_id=self.tenants.ckpt_id_of(t))
                            rep.engine.serving_tenant = t
                            rep.tenant_swaps += 1
                            if tel.enabled:
                                tel.counter("tenant_swaps", 1.0,
                                            cat="serve")
                    # endpoint plan (ISSUE 15): the pre-decode encode
                    # phase runs on THIS replica's device, then the
                    # decode pool serves the planned rows; pure-
                    # generate bursts short-circuit to an identity
                    # plan. Inside the try: a mid-plan failure fails
                    # over the ORIGINAL requests like any burst death
                    # (planning is deterministic, so the survivor's
                    # re-plan stamps identical state).
                    plan = endpoints_mod.plan_batch(rep.engine, batch)
                    out = rep.engine.run(plan.engine_requests,
                                         pool_pad=self.pool_cap,
                                         burst=bid)
                    booked = endpoints_mod.assemble_results(
                        plan, out["results"])
            except BaseException as e:  # noqa: BLE001
                self._on_replica_death(rep, batch, e)
                return
            now = time.perf_counter()
            if tel.enabled:
                # the micro-burst span (ISSUE 11): its own rooted
                # trace naming every member uid; each member's
                # complete event carries `burst` back, so the linkage
                # is bidirectional without forcing a many-parent tree
                tel.emit_span(
                    "burst", "serve", t_burst, now,
                    args={"replica": rep.idx, "burst": bid,
                          "n_requests": len(batch),
                          "slots_live": min(len(batch),
                                            self.slots),
                          "pool_pad": self.pool_cap,
                          "uids": [r.uid for r in batch]},
                    trace=span_link(f"burst-{bid}", f"burst-{bid}"))
            m = out["metrics"]
            with self._lock:
                for res in booked:
                    rec = {"result": res, "replica": rep.idx,
                           "endpoint": res.endpoint}
                    req_of = None
                    for r in batch:
                        if r.uid == res.uid:
                            rec["class"] = r.cls
                            rec["queue_pos"] = r.queue_pos
                            req_of = r
                            break
                    tn = ((req_of.tenant or "")
                          if req_of is not None else "")
                    rec["tenant"] = tn
                    self._results[res.uid] = rec
                    self._admission.note_done(
                        rep.idx, res.decode_s,
                        cost=(len(res.frames) if res.frames else 1),
                        tenant=tn)
                    if self._slo is not None:
                        # class-keyed endpoints: a fleet SLO names the
                        # admission class it judges
                        self._slo.observe(rec.get("class") or
                                          DEFAULT_CLASS, {
                            "queue_wait_s": res.queue_wait_s,
                            "decode_s": res.decode_s,
                            "latency_s": res.latency_s})
                    tslo = self._tenant_slo.get(tn)
                    if tslo is not None:
                        # per-tenant SLO (ISSUE 19): each tenant is
                        # judged by its OWN tracker, never pooled
                        tslo.observe(rec.get("class") or DEFAULT_CLASS, {
                            "queue_wait_s": res.queue_wait_s,
                            "decode_s": res.decode_s,
                            "latency_s": res.latency_s})
                    if tel.enabled and tn:
                        tel.counter(tenant_series("requests_completed",
                                                  tn), 1.0, cat="serve")
                        tel.observe(tenant_series("latency_s", tn),
                                    res.latency_s, cat="serve")
                    # result cache fill + coalesced fan-out (ISSUE
                    # 12): the completed PRIMARY stores its strokes,
                    # then every repeat that arrived while it was in
                    # flight is served the identical bytes — so
                    # repeats never compute, deterministically
                    fp = self._fp_of.pop(res.uid, None)
                    if fp is not None and self.cache is not None:
                        # mixed-version honesty (ISSUE 16): store under
                        # the PRODUCING engine's version namespace. A
                        # request admitted under v1 but drained by an
                        # already-swapped v2 engine must fill the v2
                        # keyspace — filing v2 bytes under the v1 key
                        # would let a later v1 lookup serve them.
                        fp_put = fp
                        if (res.ckpt_id and req_of is not None
                                and res.ckpt_id != (self.serving_ckpt_id
                                                    or self.cache.ckpt_id)):
                            fp_put = self.cache.fingerprint(
                                req_of, ckpt_id=res.ckpt_id)
                        self.cache.put(fp_put, res)
                        for w in self._pending.pop(fp, []):
                            # a coalesced waiter shares its primary's
                            # fingerprint, hence its tenant namespace
                            self._book_cache_hit(
                                w, w.cls, res.strokes5, res.length,
                                res.steps, res.uid, tel,
                                coalesced=True, endpoint=res.endpoint,
                                frames=res.frames, ckpt_id=res.ckpt_id,
                                tenant=(w.tenant or ""))
                # booked REQUEST count (an interpolation's frames are
                # engine rows, not requests — m["completed"] counts
                # rows, the fleet counts requests)
                rep.completed += len(booked)
                rep.bursts += 1
                rep.chunks += m["chunks"]
                rep.device_steps += m["device_steps"]
                rep.attributed_steps += m["steps_attributed"]
                rep.idle_steps += m["steps_idle"]
                rep.live_slot_steps += (m["slot_utilization"]
                                        * m["chunks"] * self.chunk
                                        * self.slots)
                self._t_last_done = now
                self._done_cv.notify_all()

    def _on_replica_death(self, rep: _Replica, batch: List[Request],
                          exc: BaseException) -> None:
        """Fail one replica over to the survivors.

        Marks the replica dead (admission shrinks to the surviving
        capacity), then re-places its stranded requests — the in-flight
        burst (``engine.run`` is transactional: a raise books nothing)
        plus everything still queued — under the bounded per-request
        retry budget with deterministic exponential backoff. A request
        whose budget is exhausted is recorded in ``failed`` (it counts
        as done, so ``drain()`` still completes and reports honestly);
        the death of the LAST replica is fleet-fatal and surfaces as
        the pre-failover "fleet worker failed" raise."""
        tel = get_telemetry()
        t_death = time.perf_counter()
        with self._lock:
            rep.dead = True
            rep.death = repr(exc)
            stranded = list(batch)
            for q in rep.queues.values():
                stranded.extend(q)
                q.clear()
            self._admission.mark_dead(rep.idx)
            # survivors = the PLACEMENT set (a retired spare is not
            # dead, but admission will never place on it — counting it
            # here would requeue onto nobody and hang drain())
            live = [r for r in self._replicas
                    if not r.dead and not r.retired]
            if not live:
                # elastic self-heal (ISSUE 12 x PR 10): the last
                # placed replica died but a pre-warmed retired spare
                # exists — rejoin the lowest one (the spawn path,
                # never a compile) instead of going fleet-fatal
                spares = [r for r in self._replicas
                          if r.retired and not r.dead]
                if spares:
                    spare = spares[0]
                    self._rejoin_locked(
                        spare, f"failover: replica {rep.idx} died",
                        t_death)
                    live = [spare]
            if tel.enabled:
                tel.counter("replica_deaths", 1.0, cat="serve")
            # stderr: serve-bench's stdout is a JSON report stream
            print(f"[fleet] WARNING: replica {rep.idx} died mid-burst "
                  f"({exc!r}); failing {len(stranded)} request(s) over "
                  f"to {len(live)} surviving replica(s)",
                  file=sys.stderr, flush=True)
            if not live:
                self._error = exc
                self._stop = True
                for other in self._replicas:
                    other.cond.notify_all()
                self._done_cv.notify_all()
                return
            requeue: List[Request] = []
            max_attempt = 0
            for r in stranded:
                n = self._retries.get(r.uid, 0) + 1
                self._retries[r.uid] = n
                if n <= self.retry_budget:
                    requeue.append(r)
                    max_attempt = max(max_attempt, n)
                else:
                    self._failed[r.uid] = {
                        "uid": r.uid, "class": r.cls,
                        "replica": rep.idx,
                        "retries": n - 1,
                        "reason": f"retry budget ({self.retry_budget}) "
                                  f"exhausted",
                        "error": repr(exc)}
                    # terminal failure releases the tenant's
                    # fair-share rows (ISSUE 19) — note_done never
                    # fires for this request, and leaking them would
                    # throttle the tenant forever
                    self._admission.drop_tenant(
                        r.tenant or "",
                        cost=endpoints_mod.pool_rows_of(r))
                    if tel.enabled:
                        tel.counter("requests_failed", 1.0, cat="serve")
                        # a failed request never reaches the engine's
                        # completion emitter, so IT won't get a root
                        # span or a terminal instant there — emit both
                        # here, or its tree reads as a torn mid-flight
                        # export ("incomplete") instead of a request
                        # the fleet deliberately gave up on. The root
                        # still covers the full clock from the
                        # ORIGINAL arrival, and the terminal `failed`
                        # instant puts the tree under the orphan check.
                        trace_id = request_trace_id(r.uid)
                        root_id = request_span_id("request", r.uid)
                        tel.emit_span(
                            "request", "serve", r.enqueue_ts, t_death,
                            args={"uid": r.uid},
                            trace=span_link(trace_id, root_id))
                        tel.instant(
                            "failed", cat="serve", ts=t_death,
                            args={"uid": r.uid, "class": r.cls,
                                  "replica": rep.idx, "retries": n - 1,
                                  "reason": self._failed[r.uid]["reason"],
                                  "error": repr(exc)},
                            trace=span_link(
                                trace_id,
                                request_span_id("failed", r.uid),
                                root_id))
                    # coalesced repeats waiting on this computation can
                    # never be filled — fail them WITH their primary so
                    # drain() completes and reports honestly (ISSUE 12)
                    fpx = self._fp_of.pop(r.uid, None)
                    if fpx is not None:
                        for w in self._pending.pop(fpx, []):
                            self._failed[w.uid] = {
                                "uid": w.uid, "class": w.cls,
                                "replica": rep.idx, "retries": 0,
                                "reason": (f"coalesced onto failed "
                                           f"request {r.uid}"),
                                "error": repr(exc)}
                            if tel.enabled:
                                tel.counter("requests_failed", 1.0,
                                            cat="serve")
        # deterministic backoff OUTSIDE the lock (the dying worker is
        # the only thread that sleeps; submits/completions proceed):
        # the schedule is a pure function of the worst attempt index
        if requeue and self.retry_backoff_s > 0:
            time.sleep(backoff_s(self.retry_backoff_s, max_attempt - 1))
        with self._lock:
            now = time.perf_counter()
            for r in requeue:
                # already-admitted requests never re-shed OR re-count:
                # failover is the fleet's fault, not the client's
                # (requeue placement — same least-loaded rule over the
                # survivors, no shed checks, no second admitted tick)
                decision = self._admission.place(
                    r.cls, requeue=True,
                    cost=endpoints_mod.pool_rows_of(r))
                r.queue_pos = decision.queue_pos
                # stamp the attempt (ISSUE 11): the retried hops' span
                # ids hang under this retry span, so the request stays
                # ONE tree — and its enqueue_ts is untouched, so the
                # latency clock still starts at the ORIGINAL arrival
                # (the backdating-survives-requeue pin)
                r.attempt = self._retries[r.uid]
                target = self._replicas[decision.replica]
                target.queues[r.cls].append(r)
                self._requeues += 1
                if tel.enabled:
                    tel.counter("requests_requeued", 1.0, cat="serve")
                    # the retry span covers death -> requeue (backoff
                    # included), parented to the request ROOT
                    tel.emit_span(
                        "retry", "serve", t_death, now,
                        args={"uid": r.uid, "attempt": r.attempt,
                              "from_replica": rep.idx,
                              "to_replica": decision.replica,
                              "error": repr(exc)},
                        trace=span_link(
                            request_trace_id(r.uid),
                            request_span_id("retry", r.uid, r.attempt),
                            request_span_id("request", r.uid)))
                target.cond.notify()
            # failed requests count toward done — wake any drainer
            self._done_cv.notify_all()

    # -- completion & reporting --------------------------------------------

    def _done_locked(self) -> int:
        """Requests accounted for (caller holds the lock): completed,
        shed at the door, or failed after exhausting the retry budget."""
        return len(self._results) + len(self._shed) + len(self._failed)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted request completed, shed, or
        (failover, ISSUE 10) exhausted its retry budget; False on
        timeout. A replica death that failover absorbed does NOT raise
        — the drain completes against the surviving capacity and
        ``summary()``/``failed`` report the damage. Re-raises only a
        FLEET-fatal failure (the last replica died), and raises if the
        fleet is closed out from under the drain (close() abandons
        queued work, so the remainder can never complete)."""
        deadline = None if timeout is None else \
            time.perf_counter() + timeout
        with self._lock:
            while True:
                if self._error is not None:
                    raise RuntimeError(
                        "fleet worker failed") from self._error
                done = self._done_locked()
                if done >= self._submitted:
                    return True
                if self._stop:
                    raise RuntimeError(
                        f"fleet closed while draining "
                        f"({self._submitted - done} requests abandoned)")
                if deadline is not None:
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        return False
                    self._done_cv.wait(left)
                else:
                    self._done_cv.wait()

    @property
    def results(self) -> Dict[int, Dict]:
        """uid -> {result, replica, class, queue_pos} for every
        completed request."""
        with self._lock:
            return dict(self._results)

    @property
    def shed(self) -> List[Dict]:
        with self._lock:
            return list(self._shed)

    @property
    def failed(self) -> Dict[int, Dict]:
        """uid -> failure record for requests whose retry budget was
        exhausted by replica deaths (ISSUE 10; empty on healthy runs)."""
        with self._lock:
            return dict(self._failed)

    def health(self) -> Dict[str, Any]:
        """Live health verdict for ``/healthz`` (serve/metrics_http.py):
        ``healthy`` is False while any replica is dead, the fleet is
        fatally errored, or requests have been failed — the endpoint
        then reports ``degraded`` with this block as evidence."""
        with self._lock:
            dead = [{"replica": r.idx, "error": r.death}
                    for r in self._replicas if r.dead]
            retired = [r.idx for r in self._replicas if r.retired]
            # an in-flight resize (ISSUE 12): a retiring replica still
            # draining its queue — intentional, not degradation, so
            # /healthz reports `scaling` instead of flapping
            scaling = any(r.retired and r.thread is not None
                          and r.thread.is_alive()
                          for r in self._replicas)
            # an in-flight model rollout (ISSUE 16): intentional, not
            # degradation — /healthz reports `rolling` (which outranks
            # `scaling`: the rollout's own retire/rejoin churn would
            # otherwise read as an autoscale) with the controller's
            # evidence block (from/to ckpt_id, replicas swapped/total)
            roll_ev = (self._rollout.evidence()
                       if self._rollout is not None else None)
            rolling = bool(roll_ev and roll_ev.get("active"))
            out = {
                "healthy": not dead and self._error is None
                and not self._failed,
                "scaling": scaling,
                "rolling": rolling,
                "rollout": roll_ev,
                "serving_ckpt_id": self.serving_ckpt_id,
                "replicas": self.n_replicas,
                "replicas_live": self.n_live,
                "replicas_retired": retired,
                "replicas_dead": dead,
                "requests_failed": len(self._failed),
                "requests_requeued": self._requeues,
                "fatal": repr(self._error) if self._error else None,
            }
            if self.tenants is not None:
                # multi-tenant evidence (ISSUE 19): which fine-tunes
                # are resident and which tenant each replica's params
                # are currently paged to
                out["tenants"] = {
                    "adapters_resident": len(self.tenants.tenants),
                    "registered": sorted(self.tenants.tenants),
                    "serving": [r.engine.serving_tenant
                                for r in self._replicas],
                    "tenant_swaps": sum(r.tenant_swaps
                                        for r in self._replicas),
                }
            return out

    def summary(self) -> Dict[str, Any]:
        """Fleet-level aggregate: throughput, per-class latency
        percentiles, shed accounting, per-replica occupancy and the
        deterministic critical-path device-step count (the CPU-smoke
        scaling signal — see scripts/serve_bench.py)."""
        with self._lock:
            recs = list(self._results.values())
            shed = list(self._shed)
            failed = list(self._failed.values())
            requeues = self._requeues
            submitted = self._submitted
            reps = [(r.idx, r.completed, r.bursts, r.chunks,
                     r.device_steps, r.live_slot_steps, r.dead,
                     r.attributed_steps, r.idle_steps, r.retired,
                     r.tenant_swaps)
                    for r in self._replicas]
            tenant_slo = {t: trk.summary()
                          for t, trk in self._tenant_slo.items()}
            scale_log = list(self._scale_log)
            t0, t1 = self._t_first_submit, self._t_last_done
        wall = (t1 - t0) if (t0 is not None and t1 is not None) else 0.0
        by_class: Dict[str, List[float]] = {}
        by_endpoint: Dict[str, List[float]] = {}
        for rec in recs:
            by_class.setdefault(rec.get("class") or DEFAULT_CLASS,
                                []).append(rec["result"].latency_s)
            ep = (rec.get("endpoint")
                  or getattr(rec["result"], "endpoint", None)
                  or "generate")
            by_endpoint.setdefault(ep, []).append(
                rec["result"].latency_s)
        lat_all = [rec["result"].latency_s for rec in recs]

        def pct(xs: List[float]) -> Dict[str, Optional[float]]:
            if not xs:
                # zero completions (everything shed) must read as "no
                # data", never as a perfect 0ms p99
                return {"p50_s": None, "p95_s": None, "p99_s": None,
                        "mean_s": None}
            a = np.asarray(xs)
            return {"p50_s": round(float(np.percentile(a, 50)), 6),
                    "p95_s": round(float(np.percentile(a, 95)), 6),
                    "p99_s": round(float(np.percentile(a, 99)), 6),
                    "mean_s": round(float(a.mean()), 6)}

        shed_by_class: Dict[str, int] = {}
        for s in shed:
            shed_by_class[s["class"]] = shed_by_class.get(s["class"],
                                                          0) + 1
        per_replica = [{
            "replica": idx, "completed": comp, "bursts": bursts,
            "chunks": chunks, "device_steps": steps,
            "slot_utilization": round(
                live / max(chunks * self.chunk * self.slots, 1), 4),
            "dead": dead, "retired": retired,
            "steps_attributed": attr, "steps_idle": idle,
            "tenant_swaps": tswaps,
        } for idx, comp, bursts, chunks, steps, live, dead, attr, idle,
          retired, tswaps in reps]
        n_cached = sum(1 for rec in recs if rec.get("cached"))
        # per-class device-step cost (ISSUE 11): integer sums of the
        # engine's deterministic per-request attribution; `exact` pins
        # the identity attributed + idle == dispatched over every
        # BOOKED burst (a replica that died mid-burst booked nothing,
        # so the identity holds on degraded runs too)
        steps_by_class: Dict[str, int] = {}
        for rec in recs:
            c = rec.get("class") or DEFAULT_CLASS
            steps_by_class[c] = (steps_by_class.get(c, 0)
                                 + rec["result"].attributed_steps)
        # multi-tenant accounting (ISSUE 19): per-tenant completion/
        # latency split, per-tenant SLO verdicts, fair-share sheds,
        # the paged-adapter memory table and the encode-reuse ledger —
        # the block scripts/serve_bench.py --tenants commits verbatim
        tenants_block = None
        if self.tenants is not None:
            by_tenant: Dict[str, List[float]] = {}
            for rec in recs:
                by_tenant.setdefault(rec.get("tenant") or "",
                                     []).append(rec["result"].latency_s)
            shed_by_tenant: Dict[str, int] = {}
            for s in shed:
                tn = s.get("tenant") or ""
                shed_by_tenant[tn] = shed_by_tenant.get(tn, 0) + 1
            tenants_block = {
                "registered": sorted(self.tenants.tenants),
                "tenant_cap": self.tenant_cap,
                "tenant_swaps": sum(r["tenant_swaps"]
                                    for r in per_replica),
                "latency_by_tenant": {
                    t: {**pct(v), "completed": len(v)}
                    for t, v in sorted(by_tenant.items())},
                "shed_by_tenant": shed_by_tenant,
                "slo_by_tenant": tenant_slo,
                "memory": self.tenants.memory_table(),
                "encode_reuse": (self.encode_reuse.stats()
                                 if self.encode_reuse is not None
                                 else None),
            }
        total_attr = sum(r["steps_attributed"] for r in per_replica)
        total_idle = sum(r["steps_idle"] for r in per_replica)
        total_steps = sum(r["device_steps"] for r in per_replica)
        cost = {
            "steps_by_class": dict(sorted(steps_by_class.items())),
            "steps_attributed": total_attr,
            "steps_idle": total_idle,
            "steps_dispatched": total_steps,
            "exact": total_attr + total_idle == total_steps
            and sum(steps_by_class.values()) == total_attr,
        }
        return {
            "replicas": self.n_replicas,
            "replicas_dead": sum(1 for r in per_replica if r["dead"]),
            "replicas_live": self.n_live,
            "replicas_retired": sum(1 for r in per_replica
                                    if r["retired"]),
            "scale_log": scale_log,
            "slots": self.slots,
            "chunk": self.chunk,
            "pool_cap": self.pool_cap,
            "submitted": submitted,
            "completed": len(recs),
            "completed_cached": n_cached,
            "cache": (None if self.cache is None
                      else self.cache.stats()),
            "shed": len(shed),
            "shed_frac": round(len(shed) / submitted, 4) if submitted
            else 0.0,
            "shed_by_class": shed_by_class,
            # failover accounting (ISSUE 10): zero on healthy runs
            "failed": len(failed),
            "failed_requests": failed,
            "requeues": requeues,
            "retry_budget": self.retry_budget,
            "wall_s": round(wall, 6),
            "sketches_per_sec": round(len(recs) / wall, 3) if wall
            else 0.0,
            "latency": pct(lat_all),
            "latency_by_class": {c: {**pct(v), "completed": len(v)}
                                 for c, v in sorted(by_class.items())},
            # multi-task serving (ISSUE 15): the per-endpoint latency
            # surface — serve_bench's per-endpoint columns and the
            # README's mixed-endpoint table read exactly this block
            "latency_by_endpoint": {e: {**pct(v), "completed": len(v)}
                                    for e, v in
                                    sorted(by_endpoint.items())},
            # critical-path tail attribution (ISSUE 11): the shared
            # segment schema over every completed Result — is the p99
            # queue- or decode-dominated? (None with no completions)
            "tail": tail_attribution(
                [(rec["result"].latency_s,
                  critical_path_segments(rec["result"].queue_wait_s,
                                         rec["result"].latency_s))
                 for rec in recs]),
            "cost": cost,
            "tenants": tenants_block,
            "per_replica": per_replica,
            # the fleet's critical path in DEVICE STEPS: max over
            # replicas — deterministic for a closed burst, and the
            # scheduling-math scaling signal on boxes whose wall clock
            # cannot show parallelism (see serve_bench.py)
            "critical_path_device_steps": max(
                (r["device_steps"] for r in per_replica), default=0),
            "total_device_steps": sum(r["device_steps"]
                                      for r in per_replica),
            "admission": self._admission.summary(),
        }


def live_fleets() -> tuple:
    with _LIVE_LOCK:
        return tuple(_LIVE)


def stop_all() -> tuple:
    """Close every live fleet; returns their reprs (the conftest guard
    asserts this is empty — a non-empty return names the leaker)."""
    leaked = live_fleets()
    names = tuple(repr(f) for f in leaked)
    for f in leaked:
        f.close()
    return names
