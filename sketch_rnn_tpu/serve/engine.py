"""Continuous-batching generation engine: slot-recycling chunked decode.

The batch-synchronous sampler (``sample/sampler.py``) runs one
``lax.while_loop`` until the SLOWEST sketch in the batch finishes:
finished rows are frozen to end tokens and their slots burn compute for
the remainder of the batch, and B=1 generation is dispatch-bound
(``scripts/sampler_latency.py``). This engine fixes both waste sources
with the standard continuous-batching design from LLM serving (see
PAPERS.md: compiler-first O(1) autoregressive caching; Gemma serving),
which maps directly onto an RNN decoder because per-slot inference
state is JUST the cell carry:

- **Fixed-shape chunked decode step**: ONE compiled program advances
  all ``B`` slots by ``K`` decode steps per dispatch (amortizing
  per-launch latency exactly like training's ``steps_per_call``) and
  returns per-slot finished flags plus the ``[K, B, 5]`` stroke chunk.
- **Slot scheduler**: a host-side request queue admits pending requests
  into finished slots BETWEEN chunks — pointing the slot at the new
  request's row of the device-resident request pool (z / class label /
  temperature / PRNG key / step cap) and flagging it for on-device
  re-init — so steady-state slot utilization approaches 1 regardless
  of length skew.
- **Per-request determinism**: each request carries its own PRNG key
  and the per-step randomness is ``fold_in(request_key, t)`` where
  ``t`` is the request's OWN decode-step index. A request's strokes
  are therefore bitwise-independent of batch composition, slot
  position, admission time and chunk size — scheduling changes WHEN a
  sketch is computed, never WHAT is computed (the testable invariant,
  mirroring the per-shard fold_in discipline in ``parallel/``).

Note the engine's RNG stream intentionally differs from the legacy
sampler's (which draws one batch-wide key per step): determinism here
is per-REQUEST, the property a serving system must guarantee.

Host/device split: every request's fields are uploaded ONCE per burst
into a device-resident pool; loop state (carry, prev token, step
counts, done flags) round-trips through the chunk program as opaque
device arrays; a steady-state chunk ships only two tiny ``[B]``
scheduling vectors in and fetches (t, done, strokes) out, and chunk
i+1 is dispatched before chunk i's outputs are fetched (depth-1
pipelining, the ``data/prefetch.py`` discipline) so scheduler work
overlaps device compute. See ARCHITECTURE.md "Serving" for the design
and the measured alternatives.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from sketch_rnn_tpu.config import HParams
from sketch_rnn_tpu.ops import mdn
from sketch_rnn_tpu.runtime.scheduler import GeometryRunScheduler
from sketch_rnn_tpu.sample.sampler import END_TOKEN, START_TOKEN
from sketch_rnn_tpu.utils.faults import fault_point
from sketch_rnn_tpu.utils.profiling import SpanTimer
from sketch_rnn_tpu.utils.telemetry import (
    JitCompileProbe,
    attribute_chunk_steps,
    class_series,
    critical_path_segments,
    endpoint_series,
    get_telemetry,
    replica_series,
    request_parent_id,
    request_span_id,
    request_trace_id,
    span_link,
    tail_attribution,
)


@dataclasses.dataclass
class Request:
    """One generation request; everything its strokes may depend on.

    ``key`` is the request's OWN PRNG key (determinism contract above).
    ``max_len`` caps emitted strokes (default: the engine's max_len).

    The admission-metadata fields (cls / queue_pos / enqueue_ts /
    attempt) are stamped by the fleet scheduler (serve/fleet.py) — they
    explain *why* a request waited (class, position in the fleet queue,
    true arrival instant) and ride the telemetry ``complete`` events,
    but none of them can affect the request's strokes (the determinism
    contract covers them: scheduling metadata changes WHEN, never
    WHAT). ``enqueue_ts`` (a ``perf_counter`` instant) backdates the
    latency clock to the fleet-arrival time; unset, the clock starts at
    ``run()`` entry exactly as before.

    Multi-task serving (ISSUE 15): ``endpoint`` selects the workload —
    ``generate`` (this engine's native path), ``complete`` (encode a
    stroke-3 ``prefix``, replay it into the decoder carry, decode the
    continuation), ``reconstruct`` (encode ``prefix`` -> z -> full
    decode), ``interpolate`` (``prefix`` is a PAIR of sketches; the
    slerp grid of ``frames`` latents decodes as a batch of child rows).
    Encoder endpoints are planned by ``serve/endpoints.py`` BEFORE the
    engine sees them: the planner stamps the derived decode state —
    ``z`` (the posterior mean), and for ``complete`` the replayed
    ``init_carry`` (flat) + ``init_prev`` (the last prefix row) the
    chunk program re-initializes admitted slots from. ``parent_uid``
    marks an interpolation FRAME row (an internal child of the named
    parent request); children never book their own fleet results.
    Everything endpoint-derived is a pure function of (prefix, params),
    so the content fingerprint (serve/cache.py) hashes (endpoint,
    prefix, frames) and never the derived state.
    """

    key: jax.Array
    z: Optional[np.ndarray] = None
    label: int = 0
    temperature: float = 1.0
    max_len: Optional[int] = None
    uid: Optional[int] = None
    cls: Optional[str] = None
    queue_pos: Optional[int] = None
    enqueue_ts: Optional[float] = None
    # failover retry attempt (ISSUE 11): 0 on arrival, incremented by
    # the fleet each requeue. Keys the per-attempt span ids so a
    # retried request's trace stays ONE tree (the retry span parents
    # the re-served hops); like the other admission metadata it can
    # never affect the request's strokes.
    attempt: int = 0
    # multi-task serving (ISSUE 15) — see class docstring
    endpoint: str = "generate"
    prefix: Optional[Any] = None
    frames: int = 0
    parent_uid: Optional[int] = None
    init_carry: Optional[np.ndarray] = None   # [C] flat replayed carry
    init_prev: Optional[np.ndarray] = None    # [5] last prefix row
    # multi-tenant serving (ISSUE 19): which registered fine-tune's
    # params serve this request ("" = the fleet's base checkpoint).
    # Routing metadata like cls — the fleet pages a replica to the
    # tenant's adapter before decoding, and the result cache
    # fingerprints under the tenant's ckpt_id — but unlike cls it DOES
    # select the strokes (a different tenant is a different model).
    tenant: str = ""


@dataclasses.dataclass
class Result:
    """A completed request: its strokes plus serving telemetry."""

    uid: int
    strokes5: np.ndarray          # [n_rows, 5]; last row is p3 if drawn
    length: int                   # rows before the end-of-sketch state
    steps: int                    # decode steps executed (= n_rows)
    queue_wait_s: float           # enqueue -> slot admission
    decode_s: float               # admission -> completion
    latency_s: float              # enqueue -> completion
    # deterministic device-step COST of this request (ISSUE 11): each
    # chunk's K device steps split in integers over the slots live in
    # that chunk (utils/telemetry.attribute_chunk_steps), accumulated
    # over the request's chunks — pure scheduling math, so per-request
    # and per-class cost are provable bitwise; run() reports the idle
    # remainder so attributed + idle == dispatched EXACTLY.
    attributed_steps: int = 0
    # served from the result cache (ISSUE 12): the strokes are the
    # ORIGINAL computation's, bitwise (the determinism contract makes
    # hit == recomputation provable); attributed_steps is 0 — a hit
    # costs no device steps, which is the whole point
    cached: bool = False
    # multi-task serving (ISSUE 15): which workload produced this
    # result; interpolate results additionally carry the per-frame
    # stroke arrays (strokes5 is then their concatenation, so every
    # byte-counting consumer keeps working)
    endpoint: str = "generate"
    frames: Optional[List[np.ndarray]] = None
    # zero-downtime rollout (ISSUE 16): which params checkpoint
    # produced these strokes — stamped from the serving engine (or the
    # cache entry, for hits), so mixed-version serving during a
    # rolling swap is HONEST: every result names its version, and the
    # invariance tests can prove its bytes are that version's, bitwise
    ckpt_id: str = ""

    @property
    def ended(self) -> bool:
        """Whether the sketch drew its end-of-sketch pen state (vs cap)."""
        return self.steps > self.length


def sample_mixture_rows(mp: mdn.MixtureParams, u: jax.Array,
                        temps: jax.Array, greedy: bool = False
                        ) -> jax.Array:
    """Draw one stroke-5 row per slot from ``[B, ·]`` MDN params using
    FOUR uniforms per row (``u [B, 4]``) and per-row temperatures.

    The batch sampler's :func:`sample_from_mixture` draws through five
    per-key random primitives; with per-SLOT keys (the engine's
    determinism contract) that vmaps into ~6 threefry streams per row
    per step, measured ~70% per-step overhead on CPU. Here the same
    three draws — mixture component, pen state, bivariate normal — run
    from one pre-drawn uniform block: inverse-CDF for the categoricals,
    Box-Muller for the Gaussian. Same canonical temperature semantics
    (logits / tau, sigma * sqrt(tau)); a different (engine-local)
    random stream than the batch sampler, which is already the
    documented contract.
    """
    tau = temps[:, None]
    if greedy:
        idx = jnp.argmax(mp.log_pi, axis=-1)
        pen_idx = jnp.argmax(mp.pen_logits, axis=-1)
    else:
        cdf = jnp.cumsum(
            jax.nn.softmax(mp.log_pi / tau, axis=-1), axis=-1)
        idx = jnp.minimum(
            jnp.sum(u[:, 0:1] > cdf, axis=-1), mp.log_pi.shape[-1] - 1)
        pen_cdf = jnp.cumsum(
            jax.nn.softmax(mp.pen_logits / tau, axis=-1), axis=-1)
        pen_idx = jnp.minimum(jnp.sum(u[:, 1:2] > pen_cdf, axis=-1), 2)
    take = lambda a: jnp.take_along_axis(  # noqa: E731
        a, idx[:, None], axis=-1)[:, 0]
    mu1, mu2 = take(mp.mu1), take(mp.mu2)
    if greedy:
        dx, dy = mu1, mu2
    else:
        s1, s2 = jnp.exp(take(mp.log_s1)), jnp.exp(take(mp.log_s2))
        rho = take(mp.rho)
        # Box-Muller: two iid normals from two uniforms
        r = jnp.sqrt(-2.0 * jnp.log(jnp.maximum(u[:, 2], 1e-12)))
        theta = (2.0 * jnp.pi) * u[:, 3]
        e0, e1 = r * jnp.cos(theta), r * jnp.sin(theta)
        sq = jnp.sqrt(temps)
        dx = mu1 + s1 * sq * e0
        dy = mu2 + s2 * sq * (rho * e0
                              + jnp.sqrt(1.0 - jnp.square(rho)) * e1)
    pen = jax.nn.one_hot(pen_idx, 3, dtype=jnp.float32)
    return jnp.concatenate([dx[:, None], dy[:, None], pen], axis=-1)


def make_chunk_step(model, hps: HParams, chunk: int, params,
                    greedy: bool = False, kernel: str = "scan",
                    param_args: bool = False, donate: bool = False):
    """Build the jitted fixed-shape K-step decode program.

    ``fn(carry, prev, t, done, reset, slot_idx, pool) ->
    (carry, prev, t, done, strokes [K, B, 5])``.

    ``donate=True`` (ISSUE 20) donates the ``carry``/``prev`` input
    buffers to the program — both are opaque device round-trips the
    host never reads, rebound to the dispatch's outputs every chunk, so
    XLA may reuse their memory in place. ONLY those two: ``t``/``done``
    outputs of chunk ``i`` become chunk ``i+1``'s inputs before the
    pipelined fetch of chunk ``i`` reads them, and the pool is
    re-gathered by every chunk of the burst — donating either would
    hand a later reader deleted buffers. Default off: direct callers
    (kernel parity tests, ``scripts/bench_kernel.py``'s timing loop)
    legitimately re-dispatch the same state tuple; only the engine's
    single-consumer loop opts in.

    ``kernel`` selects the chunk program's decode core (ISSUE 17):
    ``"scan"`` is the `lax.scan` step loop below — the bitwise
    fallback pin — and ``"pallas"`` swaps the loop for the fused
    cache-resident kernel (`ops.pallas_decode.decode_chunk`): one
    pallas program advances all K steps with the carry resident in
    VMEM, the uniforms pre-drawn outside with the same
    ``fold_in(request_key, t)`` discipline (`make_uniforms` — bitwise
    the in-loop draw for every live step; done steps' draws are
    discarded by the live mask either way). The pool gather /
    on-device admission prologue is IDENTICAL jnp for both flavors,
    so determinism, admission and masking semantics cannot diverge.

    ``params`` (the decode-path weights) are closed over and baked into
    the compiled program as constants — the engine serves ONE model, and
    shipping ~10 weight leaves through jit argument processing on every
    chunk is measurable host time at serving chunk rates.

    ``param_args=True`` (ISSUE 19, multi-tenant value-paged mode)
    instead makes the params a TRACED TRAILING ARGUMENT:
    ``fn(carry, prev, t, done, reset, slot_idx, pool, params)``. The
    compiled program is then pure in the weights, so a tenant swap is a
    pure ``device_put`` of new values into the same executable — zero
    compiles, which is the multi-tenant acceptance bar — at the cost of
    the per-chunk pytree processing the constant mode avoids. The
    math is IDENTICAL jnp either way; the fleet's single-tenant parity
    references run value-paged too, so bitwise comparisons never cross
    the constant/argument boundary.

    ``pool`` is the device-resident REQUEST POOL — ``[N, ...]`` arrays
    of every pending request's fields (raw PRNG key data, z, label,
    temperature, step cap), uploaded once per burst. ``slot_idx [B]``
    maps each slot to its pool row and ``reset [B]`` marks slots the
    host admitted into since the last chunk; the program gathers the
    admitted requests' fields and re-initializes those slots' carry
    (the canonical z -> tanh projection, bitwise-identical to the
    batch sampler's init), prev token, step count and done flag before
    stepping. The host never touches the carry — it round-trips as an
    opaque device array — and a steady-state chunk ships only the two
    tiny ``[B]`` scheduling vectors in and fetches (strokes, t, done)
    out. The alternatives measured worse on CPU (and would be far
    worse over a tunnel): a host-side carry scatter ~2x per-chunk
    overhead, re-uploading per-slot request fields each admission
    ~0.3 ms/chunk.

    Done slots are frozen: they emit end tokens and keep their carry,
    so a slot's live steps within a chunk are always a prefix of the
    chunk. One compiled program exists per (B, K, pool size N) — pad
    or bucket N if burst sizes vary wildly.
    """
    num_mixture = hps.num_mixture
    if kernel not in ("scan", "pallas"):
        raise ValueError(
            f"kernel must be 'scan' or 'pallas', got {kernel!r}")
    if kernel == "pallas":
        from sketch_rnn_tpu.ops.pallas_decode import check_cell_kind
        check_cell_kind(hps.dec_model)

    def chunk_impl(params, carry, prev, t, done, reset, slot_idx, pool):
        b = t.shape[0]
        (pool_keys, pool_z, pool_labels, pool_temps, pool_caps,
         pool_init_carry, pool_init_prev, pool_init_mask) = pool
        key_data = pool_keys[slot_idx]
        z = None if pool_z is None else pool_z[slot_idx]
        labels = None if pool_labels is None else pool_labels[slot_idx]
        temps = pool_temps[slot_idx]
        max_steps = pool_caps[slot_idx]
        keys = jax.random.wrap_key_data(key_data)
        # on-device admission: freshly admitted slots start from the
        # request's initial state (init runs for all slots — one tiny
        # matmul — and the mask keeps live slots' carries)
        carry0 = model.decoder_initial_carry(params, z, b)
        start = jnp.broadcast_to(START_TOKEN, (b, 5))
        if pool_init_carry is not None:
            # endpoint-planned decode state (ISSUE 15): rows whose
            # init_mask is set start from the REPLAYED carry (sketch
            # completion) and their last prefix row instead of the
            # z-projected carry + START token. Pools with no planned
            # rows pass None leaves and compile the legacy program —
            # pure-generate bursts keep their exact pre-endpoint
            # geometry and bytes.
            use = pool_init_mask[slot_idx]
            planned = model.dec.unflatten_carry(
                pool_init_carry[slot_idx])
            carry0 = jax.tree_util.tree_map(
                lambda p, d: jnp.where(
                    use.reshape((-1,) + (1,) * (p.ndim - 1)), p, d),
                planned, carry0)
            start = jnp.where(use[:, None], pool_init_prev[slot_idx],
                              start)
        sel = lambda new, old: jnp.where(  # noqa: E731
            reset.reshape((-1,) + (1,) * (new.ndim - 1)), new, old)
        carry = jax.tree_util.tree_map(sel, carry0, carry)
        prev = jnp.where(reset[:, None], start, prev)
        t = jnp.where(reset, 0, t)
        done = jnp.where(reset, False, done)

        if kernel == "pallas":
            from sketch_rnn_tpu.ops.pallas_decode import (decode_chunk,
                                                          make_uniforms)
            c0, h0 = carry
            extra = model._decoder_extra(params, z, labels)
            u = make_uniforms(keys, t, chunk)
            strokes, c_f, h_f, t, done = decode_chunk(
                params["dec"], params["out_w"], params["out_b"],
                c0, h0, prev, extra, u, temps, t, done, max_steps,
                jnp.asarray(END_TOKEN, jnp.float32),
                cell_kind=hps.dec_model, num_mixture=num_mixture,
                forget_bias=model.dec.forget_bias,
                compute_dtype=model.dec.compute_dtype, greedy=greedy)
            return (c_f, h_f), strokes[-1], t, done, strokes

        def body(st, _):
            carry, prev, t, done = st
            # per-slot-step RNG folded from the REQUEST key at the
            # request's own step index: bitwise-independent of batch
            # composition, slot position and chunk boundaries. One
            # 4-uniform block per row carries the whole step's
            # randomness (see sample_mixture_rows).
            kstep = jax.vmap(jax.random.fold_in)(keys, t)
            u = jax.vmap(lambda k: jax.random.uniform(k, (4,)))(kstep)
            new_carry, raw = model.decode_step(params, carry, prev, z,
                                               labels)
            mp = mdn.get_mixture_params(raw, num_mixture)
            stroke = sample_mixture_rows(mp, u, temps, greedy=greedy)
            live = ~done
            stroke = jnp.where(live[:, None], stroke, END_TOKEN[None])
            carry = jax.tree_util.tree_map(
                lambda new, old: jnp.where(
                    live.reshape((-1,) + (1,) * (new.ndim - 1)), new, old),
                new_carry, carry)
            t = t + live.astype(jnp.int32)
            done = done | (stroke[:, 4] > 0.5) | (live & (t >= max_steps))
            return (carry, stroke, t, done), stroke

        (carry, prev, t, done), strokes = lax.scan(
            body, (carry, prev, t, done), None, length=chunk)
        return carry, prev, t, done, strokes

    if param_args:
        def chunk_fn(carry, prev, t, done, reset, slot_idx, pool, p):
            return chunk_impl(p, carry, prev, t, done, reset,
                              slot_idx, pool)
    else:
        baked = params

        def chunk_fn(carry, prev, t, done, reset, slot_idx, pool):
            return chunk_impl(baked, carry, prev, t, done, reset,
                              slot_idx, pool)
    if donate:
        return jax.jit(chunk_fn, donate_argnums=(0, 1))
    return jax.jit(chunk_fn)


def make_spec_chunk_step(model, draft_model, hps: HParams, depth: int,
                         params, draft_params, tol: float,
                         greedy: bool = False, donate: bool = False):
    """Build the jitted speculative (draft+verify) dispatch program
    (ISSUE 18).

    ``fn(carry, prev, t, done, reset, slot_idx, pool) ->
    (carry, prev, t, done, strokes [D+1, B, 5], acc [B], drafted [B])``
    where ``carry`` is the pair ``(full_carry, draft_carry)`` — the
    draft cell's state rides the same opaque device round-trip as the
    verifier's.

    One dispatch runs a COMBINED scan over ``D+1`` positions. At every
    position both models consume the same ``prev`` row and the same
    per-request ``fold_in(request_key, t)`` 4-uniform block:

    - the FULL model steps exactly the legacy chunk body (same
      decode_step, same ``sample_mixture_rows`` draw ``v``) — since
      ``prev`` is always a previously-EMITTED verifier row, the
      emitted stream is bitwise the legacy engine's, unconditionally;
    - the DRAFT cell rides along teacher-forced on that stream and
      proposes ``d`` for the same position from its own (truncated)
      MDN head.

    The acceptance rule — exact rejection over the pen-state CDF (both
    samplers invert the SAME uniform ``u[1]``, so pen one-hots must
    match exactly) plus ``|Δx|,|Δy| <= tol`` on the continuous GMM
    draw — decides how many rows the dispatch COMMITS: emission stops
    after the first rejected proposal, whose position emits the
    verifier's own draw (the correction row — so every dispatch
    advances a live slot by >= 1 row), and position ``D`` is the bonus
    row (no proposal to judge; the whole draft ran clean). Because
    emitted rows are ALWAYS the verifier's draws, the output
    distribution is trivially the full model's — bitwise, a strictly
    stronger guarantee than classic speculative sampling's
    distributional one — and the accept length is a pure function of
    (key, draft params, verifier params): deterministic, replayable
    from the trace seed, independent of scheduling.

    ``acc``/``drafted`` count this dispatch's accepted / judged
    proposals per slot (the bonus row is emitted but never judged),
    feeding the acceptance-rate ledger. The prologue is the SAME jnp
    admission code as ``make_chunk_step`` plus the draft carry's own
    z -> tanh init; endpoint rows with a planned replay carry start
    the DRAFT from its z-init (draft state only modulates throughput,
    never output — no replay machinery needed on the draft side).

    Scan-flavor only: the Pallas decode kernel has no draft lane, and
    the engine refuses the combination up front.
    """
    num_mixture = hps.num_mixture
    draft_m = draft_model.num_mixture
    if depth < 1:
        raise ValueError(f"draft depth must be >= 1, got {depth}")

    def chunk_fn(carry, prev, t, done, reset, slot_idx, pool):
        fcarry, dcarry = carry
        b = t.shape[0]
        (pool_keys, pool_z, pool_labels, pool_temps, pool_caps,
         pool_init_carry, pool_init_prev, pool_init_mask) = pool
        key_data = pool_keys[slot_idx]
        z = None if pool_z is None else pool_z[slot_idx]
        labels = None if pool_labels is None else pool_labels[slot_idx]
        temps = pool_temps[slot_idx]
        max_steps = pool_caps[slot_idx]
        keys = jax.random.wrap_key_data(key_data)
        carry0 = model.decoder_initial_carry(params, z, b)
        dcarry0 = draft_model.initial_carry(draft_params, z, b)
        start = jnp.broadcast_to(START_TOKEN, (b, 5))
        if pool_init_carry is not None:
            use = pool_init_mask[slot_idx]
            planned = model.dec.unflatten_carry(
                pool_init_carry[slot_idx])
            carry0 = jax.tree_util.tree_map(
                lambda p, d: jnp.where(
                    use.reshape((-1,) + (1,) * (p.ndim - 1)), p, d),
                planned, carry0)
            start = jnp.where(use[:, None], pool_init_prev[slot_idx],
                              start)
        sel = lambda new, old: jnp.where(  # noqa: E731
            reset.reshape((-1,) + (1,) * (new.ndim - 1)), new, old)
        fcarry = jax.tree_util.tree_map(sel, carry0, fcarry)
        dcarry = jax.tree_util.tree_map(sel, dcarry0, dcarry)
        prev = jnp.where(reset[:, None], start, prev)
        t = jnp.where(reset, 0, t)
        done = jnp.where(reset, False, done)
        # time-invariant draft conditioning: the FULL model's features
        # (z, class embedding) — frozen inputs from the draft's view
        extra = model._decoder_extra(params, z, labels)

        def body(st, i):
            fcarry, dcarry, prev, t, done, stop, acc, drf = st
            kstep = jax.vmap(jax.random.fold_in)(keys, t)
            u = jax.vmap(lambda k: jax.random.uniform(k, (4,)))(kstep)
            # verifier: the legacy chunk body's ops, verbatim
            new_fc, raw = model.decode_step(params, fcarry, prev, z,
                                            labels)
            mp = mdn.get_mixture_params(raw, num_mixture)
            v = sample_mixture_rows(mp, u, temps, greedy=greedy)
            # draft proposal for the SAME position from the SAME
            # uniforms — rejection sampling over a shared inverse-CDF
            new_dc, draw = draft_model.decode_step(draft_params, dcarry,
                                                   prev, extra)
            dmp = mdn.get_mixture_params(draw, draft_m)
            d = sample_mixture_rows(dmp, u, temps, greedy=greedy)
            pen_ok = jnp.all(d[:, 2:] == v[:, 2:], axis=-1)
            off_ok = (jnp.abs(d[:, 0] - v[:, 0]) <= tol) \
                & (jnp.abs(d[:, 1] - v[:, 1]) <= tol)
            emit = ~done & ~stop
            stroke = jnp.where(emit[:, None], v, END_TOKEN[None])
            gate = lambda new, old: jnp.where(  # noqa: E731
                emit.reshape((-1,) + (1,) * (new.ndim - 1)), new, old)
            fcarry = jax.tree_util.tree_map(gate, new_fc, fcarry)
            dcarry = jax.tree_util.tree_map(gate, new_dc, dcarry)
            prev = jnp.where(emit[:, None], stroke, prev)
            t = t + emit.astype(jnp.int32)
            done = done | (emit & (stroke[:, 4] > 0.5)) \
                | (emit & (t >= max_steps))
            # judged positions: emitted, non-bonus; the correction row
            # (first miss) is emitted THEN emission stops
            judged = emit & (i < depth)
            acc = acc + (judged & pen_ok & off_ok).astype(jnp.int32)
            drf = drf + judged.astype(jnp.int32)
            stop = stop | (emit & ~(pen_ok & off_ok) & (i < depth))
            return (fcarry, dcarry, prev, t, done, stop, acc, drf), \
                stroke

        zi = jnp.zeros((b,), jnp.int32)
        stop = jnp.zeros((b,), bool)
        (fcarry, dcarry, prev, t, done, _, acc, drf), strokes = lax.scan(
            body, (fcarry, dcarry, prev, t, done, stop, zi, zi),
            jnp.arange(depth + 1))
        return (fcarry, dcarry), prev, t, done, strokes, acc, drf

    # donate=True: same carry/prev-only donation contract as
    # make_chunk_step — the (full, draft) carry pair rides argnum 0
    if donate:
        return jax.jit(chunk_fn, donate_argnums=(0, 1))
    return jax.jit(chunk_fn)


class ServeEngine:
    """Continuous-batching generation over ``slots`` decoder slots.

    ``run(requests)`` drives the request list to completion and returns
    per-request :class:`Result` objects in completion order plus
    aggregate metrics. ``recycle=False`` degrades to static batching
    (admission only when ALL slots are done — the legacy sampler's
    freeze-until-batch-done schedule) with the SAME compiled program,
    which isolates the scheduling win in benchmarks.
    """

    def __init__(self, model, hps: HParams, params, slots: int = 0,
                 chunk: int = 0, max_len: Optional[int] = None,
                 greedy: bool = False, device=None,
                 replica_id: Optional[int] = None, ckpt_id: str = "",
                 decode_kernel: Optional[str] = None,
                 param_dtype: Optional[str] = None,
                 draft_params=None, draft_depth: int = 0,
                 draft_tol: Optional[float] = None,
                 param_args: bool = False):
        self.model = model
        self.hps = hps
        # value-paged params (ISSUE 19): multi-tenant fleets build their
        # engines with param_args=True so the chunk/encode programs take
        # the weights as traced arguments — swap_params between
        # congruent trees is then a pure device_put into the SAME
        # compiled executables (zero compiles; the probe instance and
        # its warm cache survive). Default off: single-tenant serving
        # keeps the baked-constant programs bitwise unchanged.
        self.param_args = bool(param_args)
        # which tenant's params this engine currently serves ("" =
        # base); stamped by the fleet's per-burst paging and read by
        # the planner's prefix-reuse index key
        self.serving_tenant = ""
        # optional fleet-shared PrefixReuseIndex (serve/tenants.py);
        # plan_batch consults it when set
        self.encode_reuse = None
        self.slots = int(slots or hps.serve_slots)
        self.chunk = int(chunk or hps.serve_chunk)
        self.max_len = int(max_len or hps.max_seq_len)
        # speculative decoding (ISSUE 18): ``draft_params`` arms the
        # draft+verify dispatch program (make_spec_chunk_step) — one
        # combined scan advances a slot up to draft_depth+1 rows per
        # dispatch while emitting ONLY the full model's draws, so
        # draft=on is bitwise draft=off (which is bitwise the legacy
        # engine: with no draft params this constructor builds the
        # pre-ISSUE-18 program, byte for byte). depth/tol default from
        # hps so fleet construction threads them for free.
        self.speculative = draft_params is not None
        self.draft_depth = int(draft_depth or hps.draft_depth) \
            if self.speculative else 0
        self.draft_tol = float(hps.draft_tol if draft_tol is None
                               else draft_tol)
        # chunk-program flavor + serving param precision (ISSUE 17):
        # both are part of the compiled program's identity — they ride
        # the JitCompileProbe geometry key so a scan->pallas or
        # fp32->int8 swap is accounted as a NEW compile, never a
        # silent cache hit — and default from hps so fleet/rollout
        # construction threads them for free. param_dtype is a LABEL
        # (quantized params arrive dequantized to f32 from
        # serve/quantize.py); the engine's compute is unchanged.
        self.decode_kernel = str(decode_kernel
                                 or getattr(hps, "decode_kernel", "scan"))
        if self.decode_kernel not in ("scan", "pallas"):
            raise ValueError(
                f"decode_kernel must be 'scan' or 'pallas', got "
                f"{self.decode_kernel!r}")
        if self.decode_kernel == "pallas":
            from sketch_rnn_tpu.ops.pallas_decode import check_cell_kind
            check_cell_kind(hps.dec_model)
        if self.speculative and self.decode_kernel == "pallas":
            raise ValueError(
                "speculative decoding is scan-only: the fused Pallas "
                "decode kernel has no draft lane — drop draft_params "
                "or use decode_kernel='scan'")
        if self.speculative and self.param_args:
            raise ValueError(
                "value-paged params (param_args) and speculative "
                "decoding are mutually exclusive: the draft+verify "
                "program bakes BOTH param trees as constants — serve "
                "multi-tenant fleets without draft_params")
        self.param_dtype = str(
            param_dtype or getattr(hps, "serve_quantize", "float32"))
        # greedy is part of the compiled program's identity; kept so a
        # hot-swap (ISSUE 16) rebuilds the chunk program with the same
        # sampling mode it was constructed with
        self.greedy = bool(greedy)
        # which params checkpoint this engine serves (ISSUE 16):
        # stamped onto every Result; "" = unversioned (pre-rollout
        # callers — random-init benches, tests)
        self.ckpt_id = str(ckpt_id or "")
        # fleet replication (ISSUE 9): ``device`` pins this engine's
        # params + request pool to one mesh device, so its chunk
        # program executes there and NOWHERE else — each replica is its
        # own collective-free program (the mesh-sharded-sampler
        # discipline). ``replica_id`` keys the per-replica telemetry
        # series (slots_live_rNN) and rides the complete events.
        self.device = device
        self.replica_id = replica_id
        self._slots_gauge = replica_series("slots_live", replica_id)
        if self.slots < 1 or self.chunk < 1:
            raise ValueError(
                f"slots and chunk must be >= 1, got {self.slots}/"
                f"{self.chunk}")
        if self.speculative:
            from sketch_rnn_tpu.models.draft import DraftDecoder
            self._draft_model = DraftDecoder(hps)
            self._draft_params = jax.device_put(draft_params, self.device)
        else:
            self._draft_model = None
            self._draft_params = None
        # unified dispatch runtime (ISSUE 20): each engine owns its own
        # GeometryRunScheduler — the chunk probe registers with it, the
        # run loop rides its depth-1 pipeline, and its DispatchLedger
        # feeds host_syncs / dispatches_saved into every per-run
        # metrics block (windowed per run, so concurrent runs on one
        # engine would still each report their own deltas)
        self.sched = GeometryRunScheduler(
            "serve_engine" if replica_id is None
            else f"serve_engine_r{replica_id}")
        self._bind_params(params)
        self.spans = SpanTimer(category="serve")

    # the decode-path weight leaves a chunk program consumes
    _DECODE_KEEP = ("dec", "out_w", "out_b", "dec_init_w", "dec_init_b",
                    "class_embed")

    def _bind_params(self, params) -> None:
        """Bind ``params`` as this engine's serving weights: device-put
        the decode subset and bake it into a fresh chunk program.

        Called at construction and by :meth:`swap_params` (ISSUE 16) —
        a rebuild COMPILES, so the rollout controller only ever swaps
        a retired replica outside the measured serving window."""
        # decode-path parameter subset, device-put once and baked into
        # the chunk program as constants: the encoder's weights never
        # enter a chunk, and per-call pytree processing of weight
        # leaves is measurable at serving chunk rates
        self.params = jax.device_put(
            {k: params[k] for k in self._DECODE_KEEP if k in params},
            self.device)
        # full parameter reference for the lazily-built endpoint encode
        # program (ISSUE 15): kept host-side only — a generate-only
        # engine never ships encoder weights to its device
        self._full_params = params
        self._encoder = None
        # compile probe (ISSUE 8): a traced cold start shows one
        # "serve_chunk" compile span with the executable's flops / peak
        # device bytes (the number that says how many slots fit in
        # HBM), then cache hits per chunk. serve-bench's warm-up-then-
        # configure order reports warm runs as hits instead of
        # recompiling into the measured window. B/K are fixed per
        # engine but the chunk program is ALSO shape-specialized on the
        # request-pool size N (make_chunk_step docstring), so the
        # geometry key is the pool leaf shapes — a second burst of a
        # different size must compile (and be accounted as) its own
        # executable, never dispatch the first burst's — PLUS the
        # kernel flavor and param dtype (ISSUE 17): a scan->pallas or
        # fp32->int8 swap rebuilds this probe, and the key must make
        # the rebuilt program its own geometry in the compile ledger,
        # not a cache hit on the old flavor's. The (draft_on, D)
        # fields (ISSUE 18) make arming speculation or changing draft
        # depth its own geometry too — they sit BEFORE the (kernel,
        # dtype) pair so key[:-2] stays the flavor-independent pool
        # geometry the probe pins compare.
        # the engine's loop is the single consumer of its own programs,
        # so carry/prev donation (ISSUE 20) is always safe here — each
        # dispatch rebinds both names to the outputs and nothing else
        # ever holds the old buffers
        if self.speculative:
            fn = make_spec_chunk_step(
                self.model, self._draft_model, self.hps,
                self.draft_depth, self.params, self._draft_params,
                self.draft_tol, self.greedy, donate=True)
        else:
            fn = make_chunk_step(self.model, self.hps, self.chunk,
                                 self.params, self.greedy,
                                 kernel=self.decode_kernel,
                                 param_args=self.param_args,
                                 donate=True)
        # value-paged mode appends params as a TRAILING traced argument
        # (a[7]); the geometry key stays the pool-shape tuple at a[6] —
        # the ISSUE 19 contract that the key must NOT grow a tenant
        # dimension (tenants are congruent, so their values share one
        # executable and tenant swaps are compile-free by construction)
        self._chunk_fn = JitCompileProbe(
            fn,
            "serve_chunk",
            key_of=lambda a: tuple(tuple(p.shape) for p in a[6]
                                   if p is not None)
            + (self.speculative, self.draft_depth)
            + (self.decode_kernel, self.param_dtype),
            label_of=lambda a: (f"(B{self.slots},K{self.chunk},"
                                f"N{a[6][0].shape[0]},"
                                + (f"D{self.draft_depth},"
                                   if self.speculative else "")
                                + f"{self.decode_kernel},"
                                f"{self.param_dtype})"))
        # ISSUE 20: the chunk program joins the engine scheduler's
        # compile accounting; a rebind (hot-swap) replaces the retired
        # probe so compile_count() reflects the LIVE program's
        # geometries, never a dead executable's (the registry's weak
        # refs drop the retired probe once nothing else holds it)
        with self.sched._lock:
            self.sched._programs = [
                r for r in self.sched._programs
                if r() is not None and r()._name != "serve_chunk"]
        self.sched.register(self._chunk_fn)

    def swap_params(self, params, ckpt_id: str = "",
                    param_dtype: Optional[str] = None) -> None:
        """Hot-swap this engine's serving weights in place (ISSUE 16).

        The decode subset is re-device-put, the chunk program is
        REBUILT (params are compile-time constants — the swap is a
        compile, which is why the rollout walk only swaps RETIRED
        replicas and re-warms them before they rejoin placement), and
        the lazy endpoint encoder is dropped so its next use rebuilds
        against the new weights. Shape-invariance is the caller's
        contract: the admission gate (train/checkpoint.py
        ``validate_checkpoint``) proved the candidate's manifest
        matches before any engine sees it. ``ckpt_id`` becomes the
        version every subsequent Result is stamped with.
        ``param_dtype`` (ISSUE 17) relabels the serving precision when
        the incoming params were quantized (serve/quantize.py) — the
        rebuilt program then registers under its own (kernel, dtype)
        probe geometry instead of silently cache-hitting the old.

        Value-paged mode (ISSUE 19, ``param_args=True``): when the
        incoming tree is CONGRUENT with the currently bound one (same
        structure, leaf shapes and dtypes) and the precision label is
        unchanged, the swap is a pure ``device_put`` of new values —
        the chunk program, its :class:`JitCompileProbe` instance and
        the lazily-built endpoint encoder all survive with their warm
        compile caches, so tenant paging costs ZERO compiles. A
        non-congruent tree (a genuinely different model) falls back to
        the legacy rebuild."""
        relabel = (param_dtype is not None
                   and str(param_dtype) != self.param_dtype)
        if (self.param_args and not relabel
                and self._congruent(params)):
            self.params = jax.device_put(
                {k: params[k] for k in self._DECODE_KEEP
                 if k in params}, self.device)
            self._full_params = params
            if self._encoder is not None:
                self._encoder.swap_params(params)
            self.ckpt_id = str(ckpt_id or "")
            return
        if param_dtype is not None:
            self.param_dtype = str(param_dtype)
        self._bind_params(params)
        self.ckpt_id = str(ckpt_id or "")

    def _congruent(self, params) -> bool:
        """Whether ``params``' decode subset matches the bound one in
        structure, shapes and dtypes — the value-swap precondition."""
        new = {k: params[k] for k in self._DECODE_KEEP if k in params}
        old_leaves, old_tree = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_tree = jax.tree_util.tree_flatten(new)
        if old_tree != new_tree:
            return False
        return all(
            getattr(o, "shape", None) == np.asarray(n).shape
            and getattr(o, "dtype", None) == np.asarray(n).dtype
            for o, n in zip(old_leaves, new_leaves))

    @property
    def encoder(self):
        """This engine's fixed-geometry endpoint encode program (ISSUE
        15), built lazily on first encoder-endpoint use so generate-only
        engines pay nothing. Raises for unconditional models — the
        encoder endpoints need ``hps.conditional``."""
        if self._encoder is None:
            if not self.hps.conditional:
                raise ValueError(
                    "encoder endpoints (complete/reconstruct/"
                    "interpolate) need a conditional model, but "
                    "hps.conditional is false on this checkpoint")
            from sketch_rnn_tpu.serve.endpoints import EncodeProgram
            self._encoder = EncodeProgram(
                self.model, self.hps, self._full_params,
                rows=self.slots, device=self.device,
                replica_id=self.replica_id,
                decode_kernel=self.decode_kernel,
                param_dtype=self.param_dtype,
                param_args=self.param_args)
        return self._encoder

    # -- the request pool --------------------------------------------------
    #
    # Residency tuned so a steady-state chunk moves (almost) nothing
    # host->device: the carry, prev token, step counts and done flags
    # round-trip through the chunk program as opaque device arrays
    # (on-device admission via the reset mask re-initializes admitted
    # slots); every request's fields live in a device-resident pool
    # uploaded ONCE per burst; admission ships only the [B] slot->pool
    # index vector and reset mask; and the per-chunk fetch is one
    # batched device_get of (t, done, strokes).

    def _prepare_pool(self, requests: List[Request], pad: int = 0):
        """Build + upload the request pool ``[N, ...]`` in one put.

        Key data is fetched per request host-side (not via one stacked
        jnp call, whose eager-op compile is per request-count — poison
        for a server seeing variable burst sizes); per-request
        ``max_len`` caps are validated here so admission is just two
        array writes.

        ``pad`` (fleet mode) pads the pool arrays to a FIXED row count
        so every micro-burst a replica serves reuses one compiled
        program regardless of its request count — the chunk program is
        shape-specialized on the pool size (see make_chunk_step), and a
        replica seeing Poisson-varying burst sizes would otherwise
        compile per distinct size. Pad rows are inert: ``slot_idx``
        only ever points at real rows, so padding cannot change any
        request's strokes (the invariance suite pins this).
        """
        hps = self.hps
        n = len(requests)
        if pad and pad < n:
            raise ValueError(f"pool pad {pad} < request count {n}")
        # endpoint guard (ISSUE 15): the engine decodes PLANNED state —
        # an encoder endpoint that skipped the serve/endpoints planning
        # phase would silently decode as plain generation
        for i, req in enumerate(requests):
            if req.endpoint == "interpolate" and req.parent_uid is None:
                raise ValueError(
                    f"request {i}: interpolate requests must be "
                    f"expanded into frame rows by serve/endpoints."
                    f"plan_batch before engine.run")
            if (req.endpoint == "complete" and req.init_carry is None) \
                    or (req.endpoint == "reconstruct"
                        and req.z is None):
                raise ValueError(
                    f"request {i}: endpoint {req.endpoint!r} carries "
                    f"no planned decode state — run it through "
                    f"serve/endpoints.plan_batch (the encode phase) "
                    f"before engine.run")
        key_data = np.stack([np.asarray(jax.random.key_data(req.key))
                             for req in requests])
        z = None
        if hps.conditional:
            missing = [i for i, r in enumerate(requests) if r.z is None]
            if missing:
                raise ValueError(
                    f"conditional model: requests {missing[:5]} need z")
            z = np.stack([np.asarray(r.z, np.float32)
                          for r in requests])
        labels = (np.asarray([r.label for r in requests], np.int32)
                  if hps.num_classes > 0 else None)
        temps = np.asarray([r.temperature for r in requests], np.float32)
        caps = np.asarray([r.max_len or self.max_len for r in requests],
                          np.int32)
        over = [i for i, c in enumerate(caps) if c > self.max_len]
        if over:
            raise ValueError(
                f"requests {over[:5]} exceed engine max_len "
                f"{self.max_len}")
        # planned decode state (ISSUE 15): present only when some
        # request in this pool carries a replayed carry — pure-generate
        # pools keep the legacy 5-leaf geometry (None leaves), so their
        # compiled program and bytes are untouched by the endpoint
        # machinery
        init_carry = init_prev = init_mask = None
        if any(r.init_carry is not None for r in requests):
            cw = self.model.dec.carry_size
            init_carry = np.zeros((n, cw), np.float32)
            init_prev = np.zeros((n, 5), np.float32)
            init_mask = np.zeros((n,), bool)
            for i, r in enumerate(requests):
                if r.init_carry is None:
                    continue
                ic = np.asarray(r.init_carry, np.float32)
                if ic.shape != (cw,):
                    raise ValueError(
                        f"request {i}: init_carry shape {ic.shape} != "
                        f"({cw},) (the decoder cell's flat carry)")
                init_carry[i] = ic
                init_prev[i] = np.asarray(r.init_prev, np.float32)
                init_mask[i] = True
        if pad and pad > n:
            extra = pad - n
            pad_rows = lambda a, fill: np.concatenate(  # noqa: E731
                [a, np.full((extra,) + a.shape[1:], fill, a.dtype)])
            key_data = pad_rows(key_data, 0)
            if z is not None:
                z = pad_rows(z, 0.0)
            if labels is not None:
                labels = pad_rows(labels, 0)
            temps = pad_rows(temps, 1.0)
            caps = pad_rows(caps, 1)
            if init_carry is not None:
                init_carry = pad_rows(init_carry, 0.0)
                init_prev = pad_rows(init_prev, 0.0)
                init_mask = pad_rows(init_mask, False)
        return jax.device_put((key_data, z, labels, temps, caps,
                               init_carry, init_prev, init_mask),
                              self.device)

    # -- the serving loop --------------------------------------------------

    def run(self, requests: List[Request], recycle: bool = True,
            metrics_writer=None, slo=None, pool_pad: int = 0,
            burst: Optional[str] = None) -> Dict[str, Any]:
        """Drive ``requests`` to completion; continuous batching when
        ``recycle`` (default), static freeze-until-batch-done otherwise.

        Returns ``{"results": [Result...], "metrics": {...aggregate}}``.
        ``metrics_writer``: optional ``train.metrics.MetricsWriter`` —
        one JSONL row per completed request.
        ``slo``: optional ``serve.slo.SLOTracker`` — fed each completed
        request's exact latency fields, so the live SLO/burn-rate view
        (the ``/metrics`` endpoint, ISSUE 7) sees the same floats as
        the returned Results; its summary rides in ``metrics["slo"]``.
        ``pool_pad``: pad the request pool to this fixed row count so
        variable-size bursts share one compiled program (fleet mode;
        see ``_prepare_pool``).
        ``burst``: the fleet's micro-burst id (ISSUE 11) — stamped on
        this run's traced events so every member request's tree links
        back to the burst span the fleet emits around this call.
        """
        t_start = time.perf_counter()
        self.spans = SpanTimer(category="serve")  # per-run (no warmup leak)
        # per-request lifecycle telemetry (ISSUE 6): enqueue/admit/
        # complete instants plus streaming latency histograms flow into
        # the process core LIVE — an operator (or trace_report.py) sees
        # queue-wait/decode/latency percentiles and slot occupancy
        # while the run is in flight, not only in the returned summary.
        # One attribute check when telemetry is off (the default).
        tel = get_telemetry()
        # auto-uids restart at 0 EVERY run (pre-fleet callers key
        # results on them): trace ids are pure in the uid, so a traced
        # session spanning several run() calls must pass explicit
        # unique uids (the fleet/loadgen allocators do) or its trace
        # analysis will collide the runs' request trees
        for i, req in enumerate(requests):
            if req.uid is None:
                req.uid = i
        queue = deque(enumerate(requests))
        pool = (self._prepare_pool(requests, pad=pool_pad)
                if requests else None)
        # the latency clock starts at the request's true arrival when
        # the fleet stamped one (enqueue_ts), else at run() entry —
        # bitwise-unchanged for every pre-fleet caller
        enq = {req.uid: (t_start if req.enqueue_ts is None
                         else req.enqueue_ts) for req in requests}
        if tel.enabled:
            # monotonic request counters feed the live /metrics endpoint
            # (ISSUE 7); the scrape's completed total reconciles exactly
            # with run()'s end-of-run `completed`
            tel.counter("requests_enqueued", len(requests), cat="serve")
            for req in requests:
                # causal coordinate (ISSUE 11): per-attempt span ids
                # keep a failover-retried request ONE tree (attempt > 0
                # hops hang under the fleet's retry span)
                tel.instant("enqueue", cat="serve", ts=enq[req.uid],
                            args={"uid": req.uid},
                            trace=span_link(
                                request_trace_id(req.uid),
                                request_span_id("enqueue", req.uid,
                                                req.attempt),
                                request_parent_id(req.uid, req.attempt)))
        admit_t: Dict[int, float] = {}
        slot_req: List[Optional[Request]] = [None] * self.slots
        results: List[Result] = []
        n_chunks = 0
        live_slot_steps = 0
        nslots = self.slots

        # device-resident loop state (opaque round-trip); the host owns
        # only the two [B] scheduling vectors. Speculative mode carries
        # the (full, draft) state PAIR through the same round-trip.
        carry = self.model.dec.initial_carry(nslots)
        if self.speculative:
            carry = (carry, self._draft_model.cell.initial_carry(nslots))
        prev = jnp.broadcast_to(START_TOKEN, (nslots, 5))
        t_dev = jnp.zeros((nslots,), jnp.int32)
        done_dev = jnp.ones((nslots,), bool)   # all slots start empty
        if self.device is not None:
            # pin the loop state alongside the pool: every array the
            # chunk program touches is committed to THIS replica's
            # device, so concurrent replicas can never contend for (or
            # silently migrate to) the process default device
            carry, prev, t_dev, done_dev = jax.device_put(
                (carry, prev, t_dev, done_dev), self.device)
        # donation hygiene (ISSUE 20): initial_carry aliases its (c, h)
        # leaves to ONE zeros buffer, and the chunk program donates the
        # carry — XLA rejects donating the same buffer twice, so split
        # the leaves into distinct buffers once per run (B x hidden
        # floats; every later chunk's carry is fresh program outputs)
        carry = jax.tree_util.tree_map(jnp.copy, carry)
        slot_idx = np.zeros((nslots,), np.int32)
        reset = np.zeros((nslots,), bool)
        # the dispatch index each slot's occupant FIRST runs in: under
        # pipelining one in-flight chunk still reports the PREVIOUS
        # occupant's (done) state for freshly admitted slots, and the
        # collector must not complete the new request from it
        first_chunk = np.zeros((nslots,), np.int64)
        n_disp = 0
        # t fetched from the most recent chunk (chunk c-1 while
        # processing chunk c): the row-delta base for continuing slots
        t_host = np.zeros((nslots,), np.int32)

        def admit_free_slots():
            now = time.perf_counter()
            with self.spans.span("admit"):
                for b in range(nslots):
                    if not queue:
                        break
                    if slot_req[b] is None:
                        idx, req = queue.popleft()
                        slot_idx[b] = idx
                        reset[b] = True
                        first_chunk[b] = n_disp  # the next dispatch
                        slot_req[b] = req
                        admit_t[req.uid] = now
                        if tel.enabled:
                            tel.instant("admit", cat="serve", ts=now,
                                        args={"uid": req.uid,
                                              "slot": int(b)},
                                        trace=span_link(
                                            request_trace_id(req.uid),
                                            request_span_id(
                                                "admit", req.uid,
                                                req.attempt),
                                            request_parent_id(
                                                req.uid, req.attempt)))

        def dispatch():
            """Enqueue one chunk; returns its output futures and its
            dispatch index."""
            nonlocal carry, prev, t_dev, done_dev, n_disp
            with self.spans.span("dispatch"):
                # .copy(): the CPU backend can alias numpy args
                # zero-copy, and the scheduler mutates these while the
                # async-dispatched chunk is still reading them
                if self.speculative:
                    (carry, prev, t_dev, done_dev, strokes_dev,
                     acc_dev, drf_dev) = \
                        self._chunk_fn(carry, prev, t_dev, done_dev,
                                       reset.copy(), slot_idx.copy(),
                                       pool)
                    out = (t_dev, done_dev, strokes_dev, acc_dev,
                           drf_dev)
                elif self.param_args:
                    # value-paged mode: the weights ride as a traced
                    # trailing argument, so the executable is shared
                    # across congruent tenant swaps
                    carry, prev, t_dev, done_dev, strokes_dev = \
                        self._chunk_fn(carry, prev, t_dev, done_dev,
                                       reset.copy(), slot_idx.copy(),
                                       pool, self.params)
                    out = (t_dev, done_dev, strokes_dev)
                else:
                    carry, prev, t_dev, done_dev, strokes_dev = \
                        self._chunk_fn(carry, prev, t_dev, done_dev,
                                       reset.copy(), slot_idx.copy(),
                                       pool)
                    out = (t_dev, done_dev, strokes_dev)
                reset[:] = False
                cidx = n_disp
                n_disp += 1
                # one dispatch carries K chunk steps: the ledger's
                # dispatches_saved is the realized K-amortization vs a
                # step-at-a-time schedule (ISSUE 20)
                self.sched.ledger.record_run(self.chunk, 1)
                return out, cidx

        # Depth-1 software pipelining (the prefetch.py discipline on
        # the output side): chunk i+1 is dispatched BEFORE chunk i's
        # outputs are fetched, so the host's fetch/collect/admit work
        # overlaps device compute instead of serializing a full
        # dispatch->execute->fetch round trip into every chunk
        # (measured ~1.3 ms/chunk on CPU, worth ~25% engine
        # throughput; over a tunnel it would dominate). The price is
        # that a freed slot idles ONE extra chunk before its next
        # request starts — scheduling delay only: per-request strokes
        # are admission-time-invariant by construction.
        # Stroke collection is DEFERRED to request completion: per
        # chunk the scheduler does a handful of vectorized numpy ops
        # (a 32-slot python loop per chunk measured ~0.3 ms — on par
        # with everything else host-side), retaining fetched chunk
        # outputs in a short ring; a request's strokes are gathered
        # from the ring only when it finishes. The ring needs
        # ceil(max_len / K) + 2 entries — the longest possible request
        # lifetime in chunks (caps force done) plus pipeline slack.
        ring: Dict[int, Any] = {}   # cidx -> (t, strokes)
        # speculative dispatches commit a VARIABLE row count (>= 1 per
        # live slot — the correction row), so the ring horizon is the
        # worst case of one row per dispatch, not max_len / K
        horizon = (self.max_len + 2 if self.speculative
                   else -(-self.max_len // self.chunk) + 2)
        # acceptance ledger (ISSUE 18): judged/accepted draft proposals
        # and engaged slot-steps (eligible slots x K per fetched chunk
        # — the denominator of accepted-steps/device-step; the legacy
        # engine's rows-emitted/engaged ratio is <= 1 by construction,
        # a speculative dispatch commits up to (D+1) rows per K-step
        # ledger unit)
        spec_acc = 0
        spec_drf = 0
        engaged_steps = 0
        occupied = np.zeros((nslots,), bool)
        n_live = 0
        # deterministic device-step cost attribution (ISSUE 11): each
        # fetched chunk's K steps split in integers over the slots live
        # in it (ascending slot order); chunks with no live slot — the
        # pipeline's admission bubble and the final drain chunk — land
        # in `idle`, so attributed + idle == dispatched EXACTLY. Pure
        # scheduling math: for a fixed request list the split is
        # bitwise-reproducible, wall clock never enters it.
        attr_steps: Dict[int, int] = {}
        idle_steps = 0
        # fault site (ISSUE 10 grammar): kill THIS burst mid-loop —
        # per-replica names so a fleet plan targets one engine
        # deterministically ("serve.chunk.r0@3" = replica 0's 4th
        # fetched chunk), after earlier chunks' completions already
        # emitted their telemetry (the abort-ledger path below)
        chunk_site = ("serve.chunk" if self.replica_id is None
                      else f"serve.chunk.r{self.replica_id}")

        def gather(b: int, cidx: int) -> np.ndarray:
            """Reassemble slot ``b``'s strokes from the ring at its
            completion in chunk ``cidx``."""
            parts = []
            for c in range(int(first_chunk[b]), cidx + 1):
                t_c, s_c = ring[c]
                base = (0 if c == first_chunk[b]
                        else int(ring[c - 1][0][b]))
                rows = int(t_c[b]) - base
                if rows:
                    parts.append(s_c[:rows, b])
            return np.concatenate(parts)

        admit_free_slots()
        occupied[:] = [r is not None for r in slot_req]
        n_live = int(occupied.sum())
        # the depth-1 pipeline now lives on the unified dispatch
        # runtime (ISSUE 20): issue() dispatches the next chunk and
        # hands back the previous in-flight one, so the dispatch order,
        # dispatch count and fetch schedule are EXACTLY the legacy
        # `nxt` juggling's — and every device_get flows through
        # sched.fetch, making host_syncs exact by construction (zero
        # between dispatches; one per fetched chunk).
        pipe = self.sched.pipeline()
        led0 = self.sched.ledger.snapshot()
        if requests:
            pipe.issue(dispatch)
        try:
            while n_live:
                # admissions decided from chunk i-1 ride dispatch i+1
                fut, cidx = pipe.issue(dispatch)
                t_prev = t_host    # chunk cidx-1's t: the row-delta base
                fault_point(chunk_site)
                with self.spans.span("fetch"):
                    if self.speculative:
                        t_host, done, strokes, acc, drf = \
                            self.sched.fetch(fut)
                        # done slots / stale occupants draft nothing
                        # (emit gating), so the full [B] sums are exact
                        spec_acc += int(acc.sum())
                        spec_drf += int(drf.sum())
                        if tel.enabled:
                            tel.counter("draft_steps_accepted",
                                        int(acc.sum()), cat="serve")
                            tel.counter("draft_steps_proposed",
                                        int(drf.sum()), cat="serve")
                    else:
                        t_host, done, strokes = self.sched.fetch(fut)
                n_chunks += 1
                t = t_host
                now = time.perf_counter()
                with self.spans.span("collect"):
                    ring[cidx] = (t, strokes)
                    ring.pop(cidx - horizon, None)
                    eligible = occupied & (first_chunk <= cidx)
                    base = np.where(first_chunk == cidx, 0, t_prev)
                    live_slot_steps += int(
                        (t - base)[eligible].sum())
                    engaged_steps += int(eligible.sum()) * self.chunk
                    live_idx = np.nonzero(eligible)[0]
                    if len(live_idx):
                        shares = attribute_chunk_steps(self.chunk,
                                                       len(live_idx))
                        for share, b in zip(shares, live_idx):
                            uid = slot_req[b].uid
                            attr_steps[uid] = attr_steps.get(uid, 0) + share
                    else:
                        idle_steps += self.chunk
                    if tel.enabled:
                        # per-chunk occupancy sample: how many slots held a
                        # request during this chunk — trace_report.py's
                        # slot-occupancy timeline, a Chrome counter track.
                        # Fleet replicas record their own series
                        # (slots_live_rNN) so the timeline is per-replica.
                        tel.gauge(self._slots_gauge, int(eligible.sum()),
                                  cat="serve", ts=now)
                    for b in np.nonzero(eligible & done)[0]:
                        req = slot_req[b]
                        s5 = gather(int(b), cidx)
                        steps = int(t[b])
                        length = steps - int(s5[-1, 4] > 0.5)
                        res = Result(
                            uid=req.uid, strokes5=s5, length=length,
                            steps=steps,
                            queue_wait_s=admit_t[req.uid] - enq[req.uid],
                            decode_s=now - admit_t[req.uid],
                            latency_s=now - enq[req.uid],
                            attributed_steps=attr_steps.get(req.uid, 0),
                            endpoint=req.endpoint or "generate",
                            ckpt_id=self.ckpt_id)
                        results.append(res)
                        if slo is not None and req.parent_uid is None:
                            # the SLO tracker sees the EXACT Result floats,
                            # so /metrics burn rates and run()'s summary can
                            # never tell different stories; keyed by the
                            # request's endpoint ("generate" for the whole
                            # pre-endpoint world — ISSUE 15 additive).
                            # Interpolation FRAME rows are skipped: their
                            # assembled PARENT observes once (the end-to-
                            # end request latency, endpoints.
                            # assemble_results), so attainment counts
                            # requests, never frames.
                            slo.observe(res.endpoint, {
                                "queue_wait_s": res.queue_wait_s,
                                "decode_s": res.decode_s,
                                "latency_s": res.latency_s})
                        if tel.enabled:
                            tel.counter("requests_completed", 1.0,
                                        cat="serve")
                            # the causal tree (ISSUE 11): a ROOT span over
                            # the whole request clock plus queue/decode
                            # child spans, all deterministic span ids —
                            # scripts/trace_query.py reconstructs one
                            # orphan-free tree per uid from these.
                            trace_id = request_trace_id(res.uid)
                            root_id = request_span_id("request", res.uid)
                            parent = request_parent_id(res.uid, req.attempt)
                            tel.emit_span(
                                "request", "serve", enq[res.uid], now,
                                args={"uid": res.uid},
                                trace=span_link(trace_id, root_id))
                            tel.emit_span(
                                "queue_wait", "serve", enq[res.uid],
                                admit_t[res.uid], args={"uid": res.uid},
                                trace=span_link(
                                    trace_id,
                                    request_span_id("queue", res.uid,
                                                    req.attempt), parent))
                            tel.emit_span(
                                "decode", "serve", admit_t[res.uid], now,
                                args={"uid": res.uid},
                                trace=span_link(
                                    trace_id,
                                    request_span_id("decode", res.uid,
                                                    req.attempt), parent))
                            # the complete event carries the EXACT Result
                            # latencies, so event-derived percentiles in
                            # trace_report.py match run()'s summary; the
                            # histograms stream the same values live.
                            # Admission metadata (class / fleet queue
                            # position / replica id) rides along when the
                            # fleet stamped it, so a trace explains WHY a
                            # request waited — never what it computed.
                            # `segments` is the critical-path decomposition
                            # whose in-order sum is BITWISE latency_s;
                            # `attributed_steps` the request's exact
                            # device-step cost (ISSUE 11).
                            ev_args = {"uid": res.uid,
                                       "steps": res.steps,
                                       "length": res.length,
                                       "queue_wait_s": res.queue_wait_s,
                                       "decode_s": res.decode_s,
                                       "latency_s": res.latency_s,
                                       "segments": [
                                           [k, v] for k, v in
                                           critical_path_segments(
                                               res.queue_wait_s,
                                               res.latency_s)],
                                       "attributed_steps":
                                           res.attributed_steps,
                                       "attempt": req.attempt}
                            if burst is not None:
                                ev_args["burst"] = burst
                            if req.cls is not None:
                                ev_args["class"] = req.cls
                            if req.queue_pos is not None:
                                ev_args["queue_pos"] = req.queue_pos
                            if self.replica_id is not None:
                                ev_args["replica"] = self.replica_id
                            if res.endpoint != "generate":
                                ev_args["endpoint"] = res.endpoint
                            tel.instant("complete", cat="serve", ts=now,
                                        args=ev_args,
                                        trace=span_link(
                                            trace_id,
                                            request_span_id("complete",
                                                            res.uid),
                                            root_id))
                            tel.counter("device_steps_attributed",
                                        res.attributed_steps, cat="serve")
                            if req.cls is not None:
                                tel.counter(
                                    class_series("device_steps_attributed",
                                                 req.cls),
                                    res.attributed_steps, cat="serve")
                            tel.observe("queue_wait_s", res.queue_wait_s,
                                        cat="serve")
                            tel.observe("decode_s", res.decode_s, cat="serve")
                            tel.observe("latency_s", res.latency_s,
                                        cat="serve")
                            if req.cls is not None:
                                # per-class latency histogram: the SLA
                                # surface an admission class is judged by
                                tel.observe(
                                    class_series("latency_s", req.cls),
                                    res.latency_s, cat="serve")
                            if req.parent_uid is None:
                                # per-endpoint request/latency series
                                # (ISSUE 15): the /metrics view of the
                                # mixed-endpoint workload. Interpolate
                                # FRAME rows are internal children —
                                # their parent books its own series at
                                # assembly, so endpoint counts stay
                                # request counts, never frame counts.
                                ep = res.endpoint
                                tel.counter(
                                    endpoint_series("requests_completed",
                                                    ep),
                                    1.0, cat="serve")
                                tel.observe(
                                    endpoint_series("latency_s", ep),
                                    res.latency_s, cat="serve")
                        slot_req[b] = None
                        occupied[b] = False
                        n_live -= 1
                        if metrics_writer is not None:
                            metrics_writer.write(len(results), {
                                "uid": res.uid, "steps": res.steps,
                                "length": res.length,
                                "queue_wait_s": res.queue_wait_s,
                                "decode_s": res.decode_s,
                                "latency_s": res.latency_s,
                                "attributed_steps": res.attributed_steps})
                if queue and (recycle or n_live == 0):
                    admit_free_slots()
                    occupied[:] = [r is not None for r in slot_req]
                    n_live = int(occupied.sum())
            tail = pipe.drain()
            if tail is not None:
                # drain the last in-flight (all-frozen) chunk — its steps
                # served no request, so they land in the idle bucket and
                # the attributed + idle == dispatched identity stays exact
                self.sched.fetch(tail[0][1])
                n_chunks += 1
                idle_steps += self.chunk
        except BaseException:
            # abort ledger: a mid-burst crash has already emitted
            # per-completion `attributed` counters and complete
            # events, but the run-level dispatched/idle counters
            # below never fire — and the fleet's
            # failover re-serves the WHOLE burst (a raise books
            # nothing), re-emitting those completions. Close the
            # dying run's counter identity on the way out: its
            # fetched chunks are `dispatched`, and every fetched
            # step not already emitted as a completion's
            # `attributed` lands in `idle` (partial shares of
            # never-completed requests included — the retry re-
            # attributes those from scratch on the survivor). The
            # exported stream then satisfies attributed + idle ==
            # dispatched EXACTLY even across a crash + failover.
            if tel.enabled and n_chunks:
                emitted = sum(r.attributed_steps for r in results)
                tel.counter("device_steps_dispatched",
                            n_chunks * self.chunk, cat="serve")
                tel.counter("device_steps_idle",
                            n_chunks * self.chunk - emitted,
                            cat="serve")
            raise

        wall = time.perf_counter() - t_start
        # this run's window of the engine scheduler's shared ledger
        # (ISSUE 20): dispatches, realized K-amortization and host
        # syncs — the pipelining pin is host_syncs == fetched chunks
        # (zero syncs BETWEEN dispatches)
        led = self.sched.ledger.window(led0)
        if tel.enabled and n_chunks:
            # run-level cost counters for /metrics: attributed ticks
            # per completion above; dispatched/idle close the exact
            # identity attributed + idle == dispatched on the scrape
            tel.counter("device_steps_dispatched",
                        n_chunks * self.chunk, cat="serve")
            tel.counter("device_steps_idle", idle_steps, cat="serve")
            # unified-runtime counters (ISSUE 20): the scrape-side view
            # of the same ledger window the metrics block reports
            tel.counter("dispatches", led["dispatches"], cat="runtime")
            tel.counter("dispatches_saved", led["dispatches_saved"],
                        cat="runtime")
            tel.counter("host_syncs", led["host_syncs"], cat="runtime")
            # speculative headline gauges (ISSUE 18): the /metrics view
            # of this run's acceptance rate and rows-per-ledger-step —
            # same floats as the returned metrics block below
            tel.gauge("accepted_steps_per_device_step",
                      round(int(sum(r.steps for r in results))
                            / max(engaged_steps, 1), 4), cat="serve")
            if self.speculative:
                tel.gauge("draft_acceptance_rate",
                          round(spec_acc / max(spec_drf, 1), 4),
                          cat="serve")
        lat = np.array([r.latency_s for r in results]) if results else \
            np.zeros((1,))
        metrics = {
            "completed": len(results),
            "wall_s": round(wall, 6),
            "sketches_per_sec": round(len(results) / wall, 3) if wall
            else 0.0,
            "decode_steps": int(sum(r.steps for r in results)),
            "device_steps": n_chunks * self.chunk,
            "chunks": n_chunks,
            # unified-runtime ledger window (ISSUE 20): jitted calls
            # this run issued, chunk-amortization realized vs a
            # step-at-a-time schedule, and host syncs (one per fetched
            # chunk under depth-1 pipelining — never between dispatches)
            "dispatches": led["dispatches"],
            "dispatches_saved": led["dispatches_saved"],
            "host_syncs": led["host_syncs"],
            # cost attribution (ISSUE 11): steps_attributed +
            # steps_idle == device_steps EXACTLY (integers) — the
            # invariant trace_query and the fleet summary reconcile
            "steps_attributed": int(sum(attr_steps.values())),
            "steps_idle": int(idle_steps),
            # speculative throughput surface (ISSUE 18): emitted rows
            # per engaged K-step ledger unit. The legacy chunk program
            # advances an engaged slot at most K rows per K steps, so
            # this is <= 1 by construction without a draft; a
            # speculative dispatch commits up to D+1 rows per unit.
            "accepted_steps_per_device_step": round(
                int(sum(r.steps for r in results))
                / max(engaged_steps, 1), 4),
            "slot_utilization": round(
                live_slot_steps / max(n_chunks * self.chunk * self.slots,
                                      1), 4),
            "queue_wait_mean_s": round(
                float(np.mean([r.queue_wait_s for r in results]))
                if results else 0.0, 6),
            "latency_p50_s": round(float(np.percentile(lat, 50)), 6),
            "latency_p95_s": round(float(np.percentile(lat, 95)), 6),
            "latency_p99_s": round(float(np.percentile(lat, 99)), 6),
            # tail attribution (ISSUE 11): is this run's p99 queue- or
            # decode-dominated? Same shared segment schema + percentile
            # rank as trace_query, so the two can never disagree.
            "tail": tail_attribution(
                [(r.latency_s,
                  critical_path_segments(r.queue_wait_s, r.latency_s))
                 for r in results]),
            "spans": self.spans.summary(),
        }
        if self.speculative:
            metrics["speculative"] = {
                "draft_depth": self.draft_depth,
                "draft_tol": self.draft_tol,
                "draft_steps_proposed": spec_drf,
                "draft_steps_accepted": spec_acc,
                "acceptance_rate": round(
                    spec_acc / max(spec_drf, 1), 4),
            }
        if slo is not None:
            metrics["slo"] = slo.summary()
        return {"results": results, "metrics": metrics}


def generate_many(model, params, hps: HParams, requests: List[Request],
                  slots: int = 0, chunk: int = 0,
                  max_len: Optional[int] = None, greedy: bool = False,
                  recycle: bool = True, metrics_writer=None, slo=None
                  ) -> Dict[str, Any]:
    """One-call request-level API: build an engine, serve ``requests``.

    Convenience wrapper over :class:`ServeEngine` for scripts/tests that
    serve one request list; long-lived callers should hold the engine
    (the compiled chunk program is cached on it).
    """
    eng = ServeEngine(model, hps, params, slots=slots, chunk=chunk,
                      max_len=max_len, greedy=greedy)
    return eng.run(requests, recycle=recycle,
                   metrics_writer=metrics_writer, slo=slo)
