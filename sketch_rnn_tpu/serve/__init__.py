"""Serving subsystem: continuous-batching generation (ROADMAP north
star — "serves heavy traffic"; engine design in ARCHITECTURE.md)."""

from sketch_rnn_tpu.serve.admission import (
    AdmissionClass,
    AdmissionController,
    parse_admission_classes,
    parse_tenant_slos,
)
from sketch_rnn_tpu.serve.autoscale import (
    AutoscalePolicy,
    AutoscaleSignals,
    Autoscaler,
    Decision,
    fleet_signals,
    plan_decisions,
    simulate_traffic,
)
from sketch_rnn_tpu.serve.cache import ResultCache, request_fingerprint
from sketch_rnn_tpu.serve.endpoints import (
    ENDPOINTS,
    ENCODER_ENDPOINTS,
    EncodeProgram,
    default_prefix_edges,
    parse_endpoint_specs,
    plan_batch,
    serve_requests,
    validate_request,
)
from sketch_rnn_tpu.serve.engine import (
    Request,
    Result,
    ServeEngine,
    generate_many,
    make_chunk_step,
    make_spec_chunk_step,
)
from sketch_rnn_tpu.serve.fleet import ServeFleet
from sketch_rnn_tpu.serve.loadgen import (
    OpenLoopLoadGen,
    Trace,
    TraceSpec,
    endpoint_mix_ids,
    make_trace,
    parse_endpoint_mix,
    parse_tenant_mix,
    poisson_arrivals,
    tenant_mix_ids,
)
from sketch_rnn_tpu.serve.metrics_http import MetricsServer
from sketch_rnn_tpu.serve.slo import SLO, SLOTracker, parse_slo
from sketch_rnn_tpu.serve.tenants import (
    PrefixReuseIndex,
    TenantStore,
    tree_nbytes,
)

__all__ = [
    "AdmissionClass",
    "AdmissionController",
    "ENDPOINTS",
    "ENCODER_ENDPOINTS",
    "EncodeProgram",
    "default_prefix_edges",
    "endpoint_mix_ids",
    "parse_endpoint_mix",
    "parse_endpoint_specs",
    "plan_batch",
    "serve_requests",
    "validate_request",
    "Autoscaler",
    "AutoscalePolicy",
    "AutoscaleSignals",
    "Decision",
    "OpenLoopLoadGen",
    "Request",
    "Result",
    "ResultCache",
    "ServeEngine",
    "ServeFleet",
    "Trace",
    "TraceSpec",
    "fleet_signals",
    "generate_many",
    "make_chunk_step",
    "make_spec_chunk_step",
    "make_trace",
    "parse_admission_classes",
    "plan_decisions",
    "poisson_arrivals",
    "simulate_traffic",
    "request_fingerprint",
    "MetricsServer",
    "PrefixReuseIndex",
    "SLO",
    "SLOTracker",
    "TenantStore",
    "parse_slo",
    "parse_tenant_mix",
    "parse_tenant_slos",
    "tenant_mix_ids",
    "tree_nbytes",
]
