"""Serving subsystem: continuous-batching generation (ROADMAP north
star — "serves heavy traffic"; engine design in ARCHITECTURE.md)."""

from sketch_rnn_tpu.serve.admission import (
    AdmissionClass,
    AdmissionController,
    parse_admission_classes,
)
from sketch_rnn_tpu.serve.engine import (
    Request,
    Result,
    ServeEngine,
    generate_many,
    make_chunk_step,
)
from sketch_rnn_tpu.serve.fleet import ServeFleet
from sketch_rnn_tpu.serve.loadgen import OpenLoopLoadGen, poisson_arrivals
from sketch_rnn_tpu.serve.metrics_http import MetricsServer
from sketch_rnn_tpu.serve.slo import SLO, SLOTracker, parse_slo

__all__ = [
    "AdmissionClass",
    "AdmissionController",
    "OpenLoopLoadGen",
    "Request",
    "Result",
    "ServeEngine",
    "ServeFleet",
    "generate_many",
    "make_chunk_step",
    "parse_admission_classes",
    "poisson_arrivals",
    "MetricsServer",
    "SLO",
    "SLOTracker",
    "parse_slo",
]
