"""Serving subsystem: continuous-batching generation (ROADMAP north
star — "serves heavy traffic"; engine design in ARCHITECTURE.md)."""

from sketch_rnn_tpu.serve.engine import (
    Request,
    Result,
    ServeEngine,
    generate_many,
    make_chunk_step,
)

__all__ = [
    "Request",
    "Result",
    "ServeEngine",
    "generate_many",
    "make_chunk_step",
]
