"""Serving subsystem: continuous-batching generation (ROADMAP north
star — "serves heavy traffic"; engine design in ARCHITECTURE.md)."""

from sketch_rnn_tpu.serve.engine import (
    Request,
    Result,
    ServeEngine,
    generate_many,
    make_chunk_step,
)
from sketch_rnn_tpu.serve.metrics_http import MetricsServer
from sketch_rnn_tpu.serve.slo import SLO, SLOTracker, parse_slo

__all__ = [
    "Request",
    "Result",
    "ServeEngine",
    "generate_many",
    "make_chunk_step",
    "MetricsServer",
    "SLO",
    "SLOTracker",
    "parse_slo",
]
