"""Deterministic open-loop load generation: Poisson + trace replay.

ISSUE 9 tentpole piece: a serving benchmark that feeds the next request
only after the previous one completes (closed-loop) lets a slow server
slow down its own load and report flattering latencies — the
coordinated-omission trap. This generator is **open-loop**: the arrival
schedule is drawn ONCE from a seeded Poisson process (exponential
inter-arrivals at ``rate_hz``) and replayed against the fleet's
``submit`` regardless of completions, so offered load is a property of
the benchmark, not of the server's health — the precondition for an
honest latency-vs-offered-load curve (the Gemma-on-TPU serving
comparison in PAPERS.md is the reporting template).

Determinism: :func:`poisson_arrivals` is a pure function of
``(n, rate_hz, seed)``, so two runs at the same offered load submit the
same requests at the same scheduled instants; what varies is only the
wall-clock jitter of the replay thread, which the generator measures
(``max_lag_s``) rather than hides. ``rate_hz <= 0`` degenerates to the
closed-burst schedule (every request at t=0) — the capacity-measurement
arm.

**Trace replay (ISSUE 12).** Real traffic is not stationary Poisson:
it has diurnal rate curves, flash crowds, heavy-tailed quiet gaps, and
— the property a result cache lives on — REPETITION. The trace layer
grows the generator into seeded traffic shapes, all pure functions of
a :class:`TraceSpec`:

- ``poisson``  — the stationary baseline (unchanged math).
- ``diurnal``  — sinusoidal rate modulation via thinning against the
  peak rate (one seeded uniform stream; deterministic).
- ``flash``    — piecewise-constant rate with a ``flash_mult`` x step
  inside ``[flash_at_s, flash_at_s + flash_dur_s)`` — the overload
  scenario the autoscaler is judged on.
- ``pareto``   — bounded-Pareto inter-arrivals (``alpha``, capped at
  ``pareto_cap_s``) rescaled to the requested mean rate: bursty
  heavy-tail arrivals without an unbounded quiet tail.

:func:`make_trace` additionally draws a **Zipf repetition model** over
a ``unique``-sized request space (``request_ids``): arrival ``i``
carries the content of request ``request_ids[i]``, so a few hot
requests dominate — the realistic hit structure the result cache
(serve/cache.py) is measured against. ``misses == distinct contents``
is then a pure function of the trace seed, which is what makes the
traffic bench's cache savings deterministic scheduling math.

Every started generator registers process-wide so the tier-1 conftest
guard can prove no test leaks a replay thread (:func:`stop_all`, the
serve/metrics_http.py discipline).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from sketch_rnn_tpu.utils.telemetry import (
    get_telemetry,
    request_span_id,
    request_trace_id,
    span_link,
)

# every live generator, for the conftest no-stray-threads guard
_LIVE: set = set()
_LIVE_LOCK = threading.Lock()


def poisson_arrivals(n: int, rate_hz: float, seed: int) -> np.ndarray:
    """Cumulative arrival offsets (seconds) for ``n`` requests.

    Exponential inter-arrivals at ``rate_hz`` (a Poisson process),
    deterministic in ``(n, rate_hz, seed)``. ``rate_hz <= 0`` means a
    closed burst: every request arrives at t=0.
    """
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    if rate_hz <= 0:
        return np.zeros((n,), np.float64)
    gaps = np.random.default_rng(seed).exponential(1.0 / rate_hz, size=n)
    return np.cumsum(gaps)


# -- traffic traces (ISSUE 12) ------------------------------------------------

TRACE_KINDS = ("poisson", "diurnal", "flash", "pareto")


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """One seeded traffic shape + repetition model (pure config).

    ``rate_hz`` is the BASE rate; the shape fields modulate it.
    ``unique`` sizes the distinct-request space the Zipf repetition
    model draws from (``unique >= n`` degenerates to all-distinct;
    ``zipf_s`` is the exponent — larger = hotter head). Everything
    downstream (:func:`make_trace`, the autoscale plan, the cache's
    expected miss count) is a pure function of this dataclass.
    """

    kind: str = "poisson"
    n: int = 256
    rate_hz: float = 100.0
    seed: int = 0
    # diurnal
    diurnal_period_s: float = 4.0
    diurnal_amp: float = 0.8
    # flash crowd
    flash_at_s: float = 1.0
    flash_dur_s: float = 0.5
    flash_mult: float = 6.0
    # heavy tail
    pareto_alpha: float = 1.5
    pareto_cap_s: float = 1.0
    # repetition
    unique: int = 0          # 0 = all requests distinct
    zipf_s: float = 1.1
    # multi-task endpoint mix (ISSUE 15): ((endpoint, weight), ...) —
    # each arrival draws its endpoint from this weighted table with a
    # seeded stream decorrelated from arrivals and repetition ids, so
    # the mix is a pure function of the spec like everything else.
    # Empty = single-endpoint legacy traces (no endpoint column).
    endpoint_mix: Tuple[Tuple[str, float], ...] = ()
    # multi-tenant mix (ISSUE 19): ((tenant, weight), ...) — each
    # arrival draws the tenant whose fine-tune serves it, from its own
    # seeded stream (seed + 3, decorrelated from arrivals / repetition
    # ids / endpoint mix). The Zipf knob above already models skewed
    # POPULARITY of contents; this table models skewed tenant traffic
    # shares. Empty = single-tenant legacy traces (no tenant column).
    tenant_mix: Tuple[Tuple[str, float], ...] = ()

    def __post_init__(self):
        if self.kind not in TRACE_KINDS:
            raise ValueError(f"unknown trace kind {self.kind!r}; want "
                             f"one of {TRACE_KINDS}")
        if self.n < 0 or self.rate_hz <= 0:
            raise ValueError(f"need n >= 0 and rate_hz > 0, got "
                             f"n={self.n} rate_hz={self.rate_hz}")
        if self.kind == "diurnal" and not 0 <= self.diurnal_amp < 1:
            raise ValueError(f"diurnal_amp must be in [0, 1), got "
                             f"{self.diurnal_amp}")
        if self.kind == "flash" and self.flash_mult < 1:
            raise ValueError(f"flash_mult must be >= 1, got "
                             f"{self.flash_mult}")
        if self.kind == "pareto" and self.pareto_alpha <= 0:
            raise ValueError(f"pareto_alpha must be > 0, got "
                             f"{self.pareto_alpha}")
        for field, mix in (("endpoint_mix", self.endpoint_mix),
                           ("tenant_mix", self.tenant_mix)):
            seen = set()
            for item in mix:
                if len(item) != 2:
                    raise ValueError(f"{field} entries are (name, "
                                     f"weight) pairs, got {item!r}")
                name, w = item
                if not name or not isinstance(name, str):
                    raise ValueError(f"bad name {name!r} in {field}")
                if name in seen:
                    raise ValueError(f"duplicate name {name!r} in "
                                     f"{field}")
                seen.add(name)
                if not w > 0:
                    raise ValueError(f"{field} weight for {name!r} "
                                     f"must be > 0, got {w}")


@dataclasses.dataclass(frozen=True)
class Trace:
    """A realized trace: arrival offsets + the repetition mapping.
    ``request_ids[i]`` names the CONTENT arrival ``i`` carries;
    ``endpoint_ids[i]`` (when the spec declares an ``endpoint_mix``)
    indexes the mix table for arrival ``i``'s endpoint."""

    spec: TraceSpec
    arrivals: np.ndarray      # [n] cumulative seconds, non-decreasing
    request_ids: np.ndarray   # [n] int64 into the unique request space
    endpoint_ids: Optional[np.ndarray] = None   # [n] into endpoint_mix
    tenant_ids: Optional[np.ndarray] = None     # [n] into tenant_mix

    @property
    def n(self) -> int:
        return len(self.arrivals)

    @property
    def duration_s(self) -> float:
        return float(self.arrivals[-1]) if len(self.arrivals) else 0.0

    def distinct(self) -> int:
        """Distinct contents actually drawn — the deterministic miss
        count a cold cache must see on this trace."""
        return int(len(np.unique(self.request_ids)))

    def endpoint_of(self, i: int) -> str:
        """Arrival ``i``'s endpoint name (``generate`` on mix-less
        legacy traces)."""
        if self.endpoint_ids is None:
            return "generate"
        return self.spec.endpoint_mix[int(self.endpoint_ids[i])][0]

    def endpoint_counts(self) -> dict:
        """Realized per-endpoint arrival counts — what the bench
        reports as the actual mix."""
        if self.endpoint_ids is None:
            return {"generate": self.n}
        names = [m[0] for m in self.spec.endpoint_mix]
        ids, counts = np.unique(self.endpoint_ids, return_counts=True)
        return {names[int(i)]: int(c) for i, c in zip(ids, counts)}

    def tenant_of(self, i: int) -> str:
        """Arrival ``i``'s tenant name ("" — the base checkpoint — on
        mix-less legacy traces)."""
        if self.tenant_ids is None:
            return ""
        return self.spec.tenant_mix[int(self.tenant_ids[i])][0]

    def tenant_counts(self) -> dict:
        """Realized per-tenant arrival counts — what the bench reports
        as the actual tenant mix."""
        if self.tenant_ids is None:
            return {"": self.n}
        names = [m[0] for m in self.spec.tenant_mix]
        ids, counts = np.unique(self.tenant_ids, return_counts=True)
        return {names[int(i)]: int(c) for i, c in zip(ids, counts)}


def diurnal_arrivals(n: int, rate_hz: float, period_s: float,
                     amp: float, seed: int) -> np.ndarray:
    """Sinusoidally-modulated Poisson arrivals via thinning.

    Instantaneous rate ``rate_hz * (1 + amp * sin(2 pi t / period))``;
    candidates are drawn at the peak rate and accepted with probability
    ``rate(t) / peak`` from the SAME seeded stream, so the result is a
    pure function of ``(n, rate_hz, period_s, amp, seed)``.
    """
    if n == 0:
        return np.zeros((0,), np.float64)
    rng = np.random.default_rng(seed)
    peak = rate_hz * (1.0 + amp)
    out = np.empty((n,), np.float64)
    t, k = 0.0, 0
    while k < n:
        t += rng.exponential(1.0 / peak)
        rate = rate_hz * (1.0 + amp * np.sin(2.0 * np.pi * t / period_s))
        if rng.random() * peak <= rate:
            out[k] = t
            k += 1
    return out


def flash_crowd_arrivals(n: int, rate_hz: float, at_s: float,
                         dur_s: float, mult: float,
                         seed: int) -> np.ndarray:
    """Piecewise-constant-rate arrivals: base rate everywhere except a
    ``mult`` x step inside ``[at_s, at_s + dur_s)`` — the flash crowd.
    Sequential seeded draws (gap at the CURRENT instant's rate), so the
    schedule is deterministic in the spec."""
    if n == 0:
        return np.zeros((0,), np.float64)
    rng = np.random.default_rng(seed)
    out = np.empty((n,), np.float64)
    t = 0.0
    for k in range(n):
        rate = rate_hz * (mult if at_s <= t < at_s + dur_s else 1.0)
        t += rng.exponential(1.0 / rate)
        out[k] = t
    return out


def pareto_arrivals(n: int, rate_hz: float, alpha: float, cap_s: float,
                    seed: int) -> np.ndarray:
    """Bounded-Pareto inter-arrivals with mean ``~1/rate_hz``.

    Heavy-tailed gaps (inverse-CDF of a Pareto with shape ``alpha``)
    are first scaled so the sample mean rate is ``rate_hz`` — offered
    load stays comparable across shapes — THEN truncated at ``cap_s``
    in realized seconds, so one draw can never stall the trace by more
    than the documented bound. Truncation only shortens gaps, so the
    realized mean rate is >= ``rate_hz`` by the clipped tail mass.
    Pure in the spec (the scale factor uses the sample mean, itself
    seeded).
    """
    if n == 0:
        return np.zeros((0,), np.float64)
    rng = np.random.default_rng(seed)
    u = rng.random(n)
    gaps = 1.0 / np.power(1.0 - u, 1.0 / alpha)  # Pareto, xm = 1
    gaps = gaps * ((1.0 / rate_hz) / gaps.mean())
    gaps = np.minimum(gaps, max(cap_s, 1e-9))
    return np.cumsum(gaps)


def zipf_request_ids(n: int, unique: int, s: float,
                     seed: int) -> np.ndarray:
    """Zipf-distributed content ids over ``[0, unique)``: repetition
    with a hot head, deterministic in the seed. ``unique <= 0`` means
    all-distinct (identity — no repetition, a cache sees 0 hits)."""
    if unique <= 0 or unique >= n:
        return np.arange(n, dtype=np.int64)
    ranks = np.arange(1, unique + 1, dtype=np.float64)
    p = ranks ** (-float(s))
    p /= p.sum()
    return np.random.default_rng(seed + 1).choice(
        unique, size=n, p=p).astype(np.int64)


def parse_endpoint_mix(spec: str) -> Tuple[Tuple[str, float], ...]:
    """Parse an ``--endpoint_mix`` string into the TraceSpec table:
    ``"generate:4,complete:3,reconstruct:2,interpolate:1"`` (bare names
    default to weight 1). Validation happens in TraceSpec."""
    out = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, _, w = item.partition(":")
        try:
            out.append((name.strip(), float(w) if w.strip() else 1.0))
        except ValueError:
            raise ValueError(
                f"bad endpoint_mix weight {w!r} for {name!r} (want "
                f"'name:weight,...')") from None
    if not out:
        raise ValueError(f"empty endpoint mix spec {spec!r}")
    return tuple(out)


def endpoint_mix_ids(n: int, mix: Tuple[Tuple[str, float], ...],
                     seed: int) -> Optional[np.ndarray]:
    """Seeded per-arrival endpoint assignment over the weighted mix
    (ISSUE 15): deterministic in ``(n, mix, seed)``, stream-decorrelated
    from arrivals (seed) and repetition ids (seed + 1) via seed + 2.
    ``mix`` empty -> None (legacy single-endpoint traces)."""
    if not mix:
        return None
    w = np.asarray([m[1] for m in mix], np.float64)
    return np.random.default_rng(seed + 2).choice(
        len(mix), size=n, p=w / w.sum()).astype(np.int64)


def parse_tenant_mix(spec: str) -> Tuple[Tuple[str, float], ...]:
    """Parse a ``--tenant_mix`` string into the TraceSpec table:
    ``"acme:4,globex:2,initech:1"`` (bare names default to weight 1) —
    the :func:`parse_endpoint_mix` grammar with tenant names.
    Validation happens in TraceSpec."""
    out = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, _, w = item.partition(":")
        try:
            out.append((name.strip(), float(w) if w.strip() else 1.0))
        except ValueError:
            raise ValueError(
                f"bad tenant_mix weight {w!r} for {name!r} (want "
                f"'name:weight,...')") from None
    if not out:
        raise ValueError(f"empty tenant mix spec {spec!r}")
    return tuple(out)


def tenant_mix_ids(n: int, mix: Tuple[Tuple[str, float], ...],
                   seed: int) -> Optional[np.ndarray]:
    """Seeded per-arrival tenant assignment over the weighted mix
    (ISSUE 19): deterministic in ``(n, mix, seed)``, decorrelated from
    every other trace stream via seed + 3. ``mix`` empty -> None
    (legacy single-tenant traces)."""
    if not mix:
        return None
    w = np.asarray([m[1] for m in mix], np.float64)
    return np.random.default_rng(seed + 3).choice(
        len(mix), size=n, p=w / w.sum()).astype(np.int64)


def trace_arrivals(spec: TraceSpec) -> np.ndarray:
    """The spec's arrival schedule (dispatch on ``kind``)."""
    if spec.kind == "poisson":
        return poisson_arrivals(spec.n, spec.rate_hz, spec.seed)
    if spec.kind == "diurnal":
        return diurnal_arrivals(spec.n, spec.rate_hz,
                                spec.diurnal_period_s,
                                spec.diurnal_amp, spec.seed)
    if spec.kind == "flash":
        return flash_crowd_arrivals(spec.n, spec.rate_hz, spec.flash_at_s,
                                    spec.flash_dur_s, spec.flash_mult,
                                    spec.seed)
    return pareto_arrivals(spec.n, spec.rate_hz, spec.pareto_alpha,
                           spec.pareto_cap_s, spec.seed)


def make_trace(spec: TraceSpec) -> Trace:
    """Realize a spec: arrivals + Zipf repetition ids (+ the seeded
    endpoint mix, ISSUE 15), pure in the spec (two calls with equal
    specs return bitwise-equal arrays)."""
    return Trace(spec=spec, arrivals=trace_arrivals(spec),
                 request_ids=zipf_request_ids(spec.n, spec.unique,
                                              spec.zipf_s, spec.seed),
                 endpoint_ids=endpoint_mix_ids(spec.n,
                                               spec.endpoint_mix,
                                               spec.seed),
                 tenant_ids=tenant_mix_ids(spec.n, spec.tenant_mix,
                                           spec.seed))


class OpenLoopLoadGen:
    """Replay an arrival schedule against ``submit(i)`` on its own thread.

    ``arrivals`` are cumulative offsets from :func:`poisson_arrivals`
    (or any schedule); ``submit`` is called with the request INDEX —
    the caller closes over its request list, so the generator never
    touches request objects. Open-loop: the thread sleeps to each
    scheduled instant and never waits on completions; if the host
    stalls past an arrival the request fires immediately and the
    shortfall is recorded in ``max_lag_s`` (honesty over smoothing).

    ``uid_of`` maps the arrival index to the request uid the submit
    callback will assign, keying the arrival's causal trace stamp
    (ISSUE 11). Defaults to identity — every in-repo caller (cli,
    serve_bench) numbers requests by arrival index; pass your own
    mapping if yours does not.
    """

    def __init__(self, arrivals: Sequence[float],
                 submit: Callable[[int], object],
                 name: str = "loadgen",
                 uid_of: Callable[[int], int] = lambda i: i):
        self.arrivals = np.asarray(arrivals, np.float64)
        if len(self.arrivals) and np.any(np.diff(self.arrivals) < 0):
            raise ValueError("arrivals must be non-decreasing")
        self._submit = submit
        self._uid_of = uid_of
        self.name = name
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.submitted = 0
        self.max_lag_s = 0.0
        self.started_ts: Optional[float] = None

    def _run(self) -> None:
        t0 = self.started_ts
        try:
            for i, at in enumerate(self.arrivals):
                while True:
                    lag = (time.perf_counter() - t0) - at
                    if lag >= 0:
                        break
                    if self._stop.wait(min(-lag, 0.05)):
                        return
                if self._stop.is_set():
                    return
                self.max_lag_s = max(self.max_lag_s, lag)
                tel = get_telemetry()
                if tel.enabled:
                    # the loadgen hop of the causal chain (ISSUE 11):
                    # scheduled vs realized arrival, BEFORE the submit
                    # — so a trace can tell replay lag (this thread
                    # fell behind the schedule) apart from queueing
                    # (the fleet made the request wait). SELF-ROOTED
                    # in the request's trace: the eventual terminal
                    # span may be `request` OR `shed`, so parenting
                    # under either would orphan the other outcome.
                    uid = self._uid_of(i)
                    tel.instant("loadgen_dispatch", cat="serve",
                                args={"index": int(i),
                                      "sched_s": float(at),
                                      "lag_s": round(float(lag), 6)},
                                trace=span_link(
                                    request_trace_id(uid),
                                    request_span_id("arrival", uid)))
                self._submit(i)
                self.submitted += 1
        finally:
            with _LIVE_LOCK:
                _LIVE.discard(self)

    def start(self) -> "OpenLoopLoadGen":
        if self._thread is not None:
            raise RuntimeError("load generator already started")
        self.started_ts = time.perf_counter()
        self._thread = threading.Thread(target=self._run, name=self.name,
                                        daemon=True)
        with _LIVE_LOCK:
            _LIVE.add(self)
        self._thread.start()
        return self

    @property
    def done(self) -> bool:
        return self._thread is not None and not self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the schedule to finish replaying; True when done."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def stop(self) -> None:
        """Abandon any un-submitted arrivals and join the thread."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        with _LIVE_LOCK:
            _LIVE.discard(self)

    def __enter__(self) -> "OpenLoopLoadGen":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    def __repr__(self) -> str:
        state = ("idle" if self._thread is None
                 else "done" if self.done else "replaying")
        return (f"OpenLoopLoadGen({self.name}: {self.submitted}/"
                f"{len(self.arrivals)} {state})")


def live_generators() -> Tuple["OpenLoopLoadGen", ...]:
    with _LIVE_LOCK:
        return tuple(_LIVE)


def stop_all() -> Tuple[str, ...]:
    """Stop every live generator; returns their reprs (the conftest
    guard asserts this is empty — a non-empty return names the leaker)."""
    leaked = live_generators()
    names = tuple(repr(g) for g in leaked)
    for g in leaked:
        g.stop()
    return names
