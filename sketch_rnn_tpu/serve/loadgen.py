"""Deterministic open-loop Poisson load generator.

ISSUE 9 tentpole piece: a serving benchmark that feeds the next request
only after the previous one completes (closed-loop) lets a slow server
slow down its own load and report flattering latencies — the
coordinated-omission trap. This generator is **open-loop**: the arrival
schedule is drawn ONCE from a seeded Poisson process (exponential
inter-arrivals at ``rate_hz``) and replayed against the fleet's
``submit`` regardless of completions, so offered load is a property of
the benchmark, not of the server's health — the precondition for an
honest latency-vs-offered-load curve (the Gemma-on-TPU serving
comparison in PAPERS.md is the reporting template).

Determinism: :func:`poisson_arrivals` is a pure function of
``(n, rate_hz, seed)``, so two runs at the same offered load submit the
same requests at the same scheduled instants; what varies is only the
wall-clock jitter of the replay thread, which the generator measures
(``max_lag_s``) rather than hides. ``rate_hz <= 0`` degenerates to the
closed-burst schedule (every request at t=0) — the capacity-measurement
arm.

Every started generator registers process-wide so the tier-1 conftest
guard can prove no test leaks a replay thread (:func:`stop_all`, the
serve/metrics_http.py discipline).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from sketch_rnn_tpu.utils.telemetry import (
    get_telemetry,
    request_span_id,
    request_trace_id,
    span_link,
)

# every live generator, for the conftest no-stray-threads guard
_LIVE: set = set()
_LIVE_LOCK = threading.Lock()


def poisson_arrivals(n: int, rate_hz: float, seed: int) -> np.ndarray:
    """Cumulative arrival offsets (seconds) for ``n`` requests.

    Exponential inter-arrivals at ``rate_hz`` (a Poisson process),
    deterministic in ``(n, rate_hz, seed)``. ``rate_hz <= 0`` means a
    closed burst: every request arrives at t=0.
    """
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    if rate_hz <= 0:
        return np.zeros((n,), np.float64)
    gaps = np.random.default_rng(seed).exponential(1.0 / rate_hz, size=n)
    return np.cumsum(gaps)


class OpenLoopLoadGen:
    """Replay an arrival schedule against ``submit(i)`` on its own thread.

    ``arrivals`` are cumulative offsets from :func:`poisson_arrivals`
    (or any schedule); ``submit`` is called with the request INDEX —
    the caller closes over its request list, so the generator never
    touches request objects. Open-loop: the thread sleeps to each
    scheduled instant and never waits on completions; if the host
    stalls past an arrival the request fires immediately and the
    shortfall is recorded in ``max_lag_s`` (honesty over smoothing).

    ``uid_of`` maps the arrival index to the request uid the submit
    callback will assign, keying the arrival's causal trace stamp
    (ISSUE 11). Defaults to identity — every in-repo caller (cli,
    serve_bench) numbers requests by arrival index; pass your own
    mapping if yours does not.
    """

    def __init__(self, arrivals: Sequence[float],
                 submit: Callable[[int], object],
                 name: str = "loadgen",
                 uid_of: Callable[[int], int] = lambda i: i):
        self.arrivals = np.asarray(arrivals, np.float64)
        if len(self.arrivals) and np.any(np.diff(self.arrivals) < 0):
            raise ValueError("arrivals must be non-decreasing")
        self._submit = submit
        self._uid_of = uid_of
        self.name = name
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.submitted = 0
        self.max_lag_s = 0.0
        self.started_ts: Optional[float] = None

    def _run(self) -> None:
        t0 = self.started_ts
        try:
            for i, at in enumerate(self.arrivals):
                while True:
                    lag = (time.perf_counter() - t0) - at
                    if lag >= 0:
                        break
                    if self._stop.wait(min(-lag, 0.05)):
                        return
                if self._stop.is_set():
                    return
                self.max_lag_s = max(self.max_lag_s, lag)
                tel = get_telemetry()
                if tel.enabled:
                    # the loadgen hop of the causal chain (ISSUE 11):
                    # scheduled vs realized arrival, BEFORE the submit
                    # — so a trace can tell replay lag (this thread
                    # fell behind the schedule) apart from queueing
                    # (the fleet made the request wait). SELF-ROOTED
                    # in the request's trace: the eventual terminal
                    # span may be `request` OR `shed`, so parenting
                    # under either would orphan the other outcome.
                    uid = self._uid_of(i)
                    tel.instant("loadgen_dispatch", cat="serve",
                                args={"index": int(i),
                                      "sched_s": float(at),
                                      "lag_s": round(float(lag), 6)},
                                trace=span_link(
                                    request_trace_id(uid),
                                    request_span_id("arrival", uid)))
                self._submit(i)
                self.submitted += 1
        finally:
            with _LIVE_LOCK:
                _LIVE.discard(self)

    def start(self) -> "OpenLoopLoadGen":
        if self._thread is not None:
            raise RuntimeError("load generator already started")
        self.started_ts = time.perf_counter()
        self._thread = threading.Thread(target=self._run, name=self.name,
                                        daemon=True)
        with _LIVE_LOCK:
            _LIVE.add(self)
        self._thread.start()
        return self

    @property
    def done(self) -> bool:
        return self._thread is not None and not self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the schedule to finish replaying; True when done."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def stop(self) -> None:
        """Abandon any un-submitted arrivals and join the thread."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        with _LIVE_LOCK:
            _LIVE.discard(self)

    def __enter__(self) -> "OpenLoopLoadGen":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    def __repr__(self) -> str:
        state = ("idle" if self._thread is None
                 else "done" if self.done else "replaying")
        return (f"OpenLoopLoadGen({self.name}: {self.submitted}/"
                f"{len(self.arrivals)} {state})")


def live_generators() -> Tuple["OpenLoopLoadGen", ...]:
    with _LIVE_LOCK:
        return tuple(_LIVE)


def stop_all() -> Tuple[str, ...]:
    """Stop every live generator; returns their reprs (the conftest
    guard asserts this is empty — a non-empty return names the leaker)."""
    leaked = live_generators()
    names = tuple(repr(g) for g in leaked)
    for g in leaked:
        g.stop()
    return names
